"""RPC front-end for the inference engine.

Reuses the :mod:`glt_tpu.distributed.rpc` fabric (the same
length-prefixed socket protocol the server-client training mode runs
on) so multi-process clients can query a TPU host without a new wire
format. Each client connection is served on its own thread by
RpcServer, so concurrent clients naturally interleave in the
MicroBatcher and share micro-batches.

Registered callees:
  * ``infer(ids, timeout_ms=None)`` -> [len(ids), D] numpy
  * ``stats()``                     -> metrics + cache + compile stats
  * ``invalidate(ids=None, version=None)`` -> entries dropped
  * ``ping()``                      -> server identity / readiness
  * ``apply_delta(...)``            -> stage + fold live updates into
    the server's stream ingestor (only when built with ``stream=``)
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..distributed.rpc import RpcClient, RpcServer
from ..utils.profile import Timer
from .batcher import EngineStalledError, MicroBatcher
from .engine import InferenceEngine
from .metrics import ServingMetrics


class ServingServer:
  """Hosts an InferenceEngine behind a micro-batched RPC endpoint.

  Args:
    engine: the InferenceEngine (warmup is triggered here unless
      ``warmup=False``).
    host/port: bind address; port 0 picks an ephemeral port (read it
      back from ``.address``).
    max_batch_size: micro-batch id capacity; defaults to the engine's
      largest bucket (a full micro-batch exactly fills one forward).
    max_wait_ms / max_queue / request_timeout_ms: MicroBatcher knobs.
    stall_timeout_ms: engine watchdog budget (MicroBatcher) — a
      dispatch running past it opens the engine circuit and fails all
      pending futures immediately. None disables the watchdog.
    stale_serve: while the engine circuit is OPEN, answer infer
      requests from the versioned EmbeddingCache (zero-fill for
      misses, stale_serves counted) instead of failing fast — the
      opt-in availability-over-freshness tier.
    slos: latency SLO policies (:class:`glt_tpu.obs.SloPolicy` list)
      evaluated on every ``stats()`` pull — each publishes a
      ``slo_burn{slo=...}`` gauge (windowed error-budget burn; the
      per-shard autoscaling/paging signal) and lands in the stats
      payload. None reads the ``GLT_OBS_SLO`` knob; policies without
      an explicit metric label default onto THIS server's
      ``serving_latency_seconds`` series.
    stream: optional :class:`glt_tpu.stream.StreamIngestor` (built by
      the caller with this server's engine + sampler); registers the
      ``apply_delta`` callee so a fleet router can propagate live
      graph/feature updates to remote replicas. Callers retrying
      apply_delta MUST mark it idempotent on their RpcClient (the
      ServingClient here does) — the req-id dedup replay is what makes
      a retried mutation exactly-once-observable.
  """

  def __init__(self, engine: InferenceEngine, host: str = '127.0.0.1',
               port: int = 0, max_batch_size: Optional[int] = None,
               max_wait_ms: float = 2.0, max_queue: int = 1024,
               request_timeout_ms: Optional[float] = 1000.0,
               warmup: bool = True,
               stall_timeout_ms: Optional[float] = None,
               stale_serve: bool = False,
               registry=None, metrics_name: str = '',
               slos=None, stream=None):
    self.engine = engine
    self.stream = stream
    self.stale_serve = bool(stale_serve)
    if warmup:
      engine.warmup()
    # metrics clock starts AFTER warmup: bucket compilation (tens of
    # seconds on real models) must not dilute the reported QPS.
    # ``registry``: publish the serving counters into a shared
    # MetricsRegistry (e.g. glt_tpu.obs.get_registry()) so one
    # exposition surface carries serving + pipeline-stage metrics;
    # ``metrics_name`` labels this server's series there — REQUIRED to
    # keep two servers on one registry from merging their counters.
    self.metrics = ServingMetrics(registry=registry, name=metrics_name)
    self.batcher = MicroBatcher(
        engine.infer,
        max_batch_size=max_batch_size or engine.buckets[-1],
        max_wait_ms=max_wait_ms, max_queue=max_queue,
        request_timeout_ms=request_timeout_ms, metrics=self.metrics,
        stall_timeout_ms=stall_timeout_ms)
    self._request_timeout_ms = request_timeout_ms
    # SLO burn: evaluated lazily on each stats() pull (the scrape/
    # health cadence IS the evaluation window) over this server's own
    # metrics registry, so per-shard burn gauges come for free when a
    # shared registry + metrics_name labels the fleet
    import dataclasses as _dc
    from ..obs.recorder import SloBurnEvaluator, parse_slo_env
    if slos is None:
      # a malformed GLT_OBS_SLO typo must degrade to no-SLO, not take
      # down serving (the env-knob bug class: GLT_OBS_BUFFER et al.)
      try:
        slos = parse_slo_env()
      except ValueError as e:
        import logging
        logging.getLogger(__name__).warning(
            'ignoring malformed GLT_OBS_SLO: %s', e)
        slos = []
    # policies are COPIED before defaulting labels: a slos list shared
    # across servers must not have server A's view label stamped onto
    # the objects server B then evaluates
    policies = [
        _dc.replace(p, labels=(dict(p.labels) if p.labels
                               else dict(self.metrics._labels)))
        for p in slos]
    self.slo = SloBurnEvaluator(policies,
                                registry=self.metrics.registry) \
        if policies else None
    # register BEFORE start(): a pre-registered server fails unknown
    # names fast instead of stalling the connection (rpc.RpcServer)
    self.rpc = RpcServer(host=host, port=port, auto_start=False)
    self.rpc.register('infer', self.infer)
    self.rpc.register('stats', self.stats)
    self.rpc.register('invalidate', self.invalidate)
    self.rpc.register('ping', self._ping)
    self.rpc.register('apply_delta', self.apply_delta)
    self.rpc.start()

  @property
  def address(self):
    return (self.rpc.host, self.rpc.port)

  # -- callees (also the in-process API) ---------------------------------

  def infer(self, ids, timeout_ms: Optional[float] = None) -> np.ndarray:
    from ..obs import get_tracer
    tracer = get_tracer()
    if not tracer.enabled:  # span kwargs would pay an asarray per call
      return self._infer(ids, timeout_ms)
    with tracer.span('serve.infer', ids=int(np.asarray(ids).size)):
      return self._infer(ids, timeout_ms)

  def _infer(self, ids, timeout_ms: Optional[float] = None) -> np.ndarray:
    t = Timer().start()
    # validate BEFORE batching: a bad id raised inside the dispatcher
    # would fail every co-batched request, not just this caller's
    self.engine.validate_ids(np.asarray(ids, dtype=np.int64).reshape(-1))
    try:
      fut = self.batcher.submit(ids, timeout_ms=timeout_ms)
      # the batcher enforces the queue deadline (and the engine
      # watchdog the dispatch); the extra slack here only guards
      # against a wedged dispatcher with the watchdog disabled
      wait = timeout_ms if timeout_ms is not None \
          else self._request_timeout_ms
      out = fut.result(timeout=None if wait is None else wait / 1e3 + 60)
    except EngineStalledError:
      # engine circuit OPEN: degrade to the cache tier if opted in —
      # availability over freshness, every such answer counted
      if not self.stale_serve:
        raise
      out = self._stale_infer(ids)
    self.metrics.record_request(t.stop(), np.asarray(ids).size)
    return out

  def _stale_infer(self, ids) -> np.ndarray:
    rows, cached = self.engine.stale_serve(ids)
    self.metrics.record_stale_serve(int(cached.sum()))
    self.metrics.add_gauge('stale_zero_fills', float((~cached).sum()))
    return rows

  def stats(self) -> dict:
    out = self.metrics.snapshot(cache=self.engine.cache)
    out['engine'] = self.engine.compile_stats()
    out['stalled'] = self.batcher.stalled
    out['stale_serve_enabled'] = self.stale_serve
    if self.slo is not None:
      out['slo_burn'] = {k: round(v, 4)
                         for k, v in self.slo.evaluate().items()}
    return out

  def invalidate(self, ids=None, version=None) -> int:
    # through the engine: serialized against in-flight infer
    return self.engine.invalidate(ids=ids, version=version)

  def apply_delta(self, ins=None, dels=None, feat_ids=None,
                  feat_rows=None, compact: bool = True) -> dict:
    """Stage live updates into this replica's stream ingestor and (by
    default) fold them immediately: compaction -> RCU snapshot swap ->
    engine ``update_snapshot`` cache invalidation, returning the
    snapshot version now being served — the consistency token the
    fleet router compares across shards. ``ins``/``dels`` are [2, n]
    edge blocks in this server's id space."""
    if self.stream is None:
      raise RuntimeError(
          'this server has no stream ingestor: build the ServingServer '
          'with stream= (a StreamIngestor over its engine) to accept '
          'apply_delta')
    staged = 0
    if ins is not None:
      ins = np.asarray(ins, np.int64).reshape(2, -1)
      if ins.shape[1]:
        staged += self.stream.insert_edges(ins[0], ins[1])
    if dels is not None:
      dels = np.asarray(dels, np.int64).reshape(2, -1)
      if dels.shape[1]:
        staged += self.stream.delete_edges(dels[0], dels[1])
    if feat_ids is not None:
      feat_ids = np.asarray(feat_ids, np.int64).reshape(-1)
      if feat_ids.size:
        staged += self.stream.update_features(
            feat_ids, np.asarray(feat_rows))
    info = self.stream.flush() if compact \
        else self.stream.maybe_compact()
    return {'staged': int(staged),
            'compacted': info is not None,
            'invalidated': int(info.get('invalidated', 0)) if info
            else 0,
            'version': int(self.engine.snapshot_version)}

  def _ping(self) -> dict:
    return {'ok': True, 'buckets': list(self.engine.buckets),
            'output_dim': self.engine.output_dim,
            'model_version': self.engine.model_version,
            'snapshot_version': self.engine.snapshot_version}

  def close(self) -> None:
    self.batcher.stop()
    self.rpc.stop()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()


class ServingClient:
  """Thin client over the rpc fabric's RpcClient."""

  def __init__(self, host: str, port: int, timeout: float = 180.0):
    # apply_delta is mutating-but-dedupable: with the request id
    # attached, a lost-reply retry replays the server's recorded reply
    # instead of staging the delta twice (rpc.IDEMPOTENT_CALLEES)
    self._rpc = RpcClient(host, port, timeout=timeout,
                          idempotent=frozenset({'apply_delta'}))

  def infer(self, ids, timeout_ms: Optional[float] = None) -> np.ndarray:
    # the client-supplied deadline ALSO bounds the rpc wait (plus small
    # slack for the wire): a wedged server cannot hold this caller past
    # its own deadline — the client times out, reconnects, and the
    # request-id dedup makes the retry safe
    rpc_timeout = (timeout_ms / 1e3 + 5.0
                   if timeout_ms is not None else None)
    return np.asarray(self._rpc.request(
        'infer', np.asarray(ids, dtype=np.int64),
        timeout_ms=timeout_ms, _rpc_timeout=rpc_timeout))

  def infer_async(self, ids, timeout_ms: Optional[float] = None):
    # same deadline contract as the sync path: the future must resolve
    # within the caller's budget even against a wedged server
    rpc_timeout = (timeout_ms / 1e3 + 5.0
                   if timeout_ms is not None else None)
    return self._rpc.async_request(
        'infer', np.asarray(ids, dtype=np.int64),
        timeout_ms=timeout_ms, _rpc_timeout=rpc_timeout)

  def stats(self) -> dict:
    return self._rpc.request('stats')

  def invalidate(self, ids=None, version=None) -> int:
    return self._rpc.request('invalidate', ids=ids, version=version)

  def apply_delta(self, ins=None, dels=None, feat_ids=None,
                  feat_rows=None, compact: bool = True) -> dict:
    return self._rpc.request(
        'apply_delta', ins=ins, dels=dels, feat_ids=feat_ids,
        feat_rows=feat_rows, compact=compact)

  def ping(self) -> dict:
    return self._rpc.request('ping')

  def close(self) -> None:
    self._rpc.close()

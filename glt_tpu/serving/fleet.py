"""Sharded serving fleet: one endpoint over N shards x M replicas.

ROADMAP item 4's missing piece: a single :class:`InferenceEngine` (or
one :class:`ServingServer`) is a single point of failure, and the
resilience ladder (retry -> breaker -> failover -> stale-serve,
docs/fault_tolerance.md) protects individual RPC peers — not a fleet.
:class:`FleetRouter` is the front door that composes those primitives
*per shard*::

        client ----> FleetRouter.infer(ids, klass)
                        |-- AdmissionController   (bounded per-class
                        |                          queues; deadline
                        |                          shed BEFORE dispatch)
                        |-- PartitionBook         (seed id -> shard)
                        |-- per shard: replica chain
                        |     r0 --breaker/health--> local engine or
                        |     r1 --breaker/health--> remote ServingServer
                        |     (walked with request_with_failover
                        |      semantics; every hop counted)
                        `-- stale tier: EmbeddingCache.lookup_stale
                              (whole replica set down; rows counted,
                               zero-fill counted, never silent)

**Resilience per shard.** Each replica gets its own
:class:`CircuitBreaker` labeled ``{shard=, replica=}`` and each shard
its own passive-first :class:`HealthMonitor` (labels ride the
``breaker_state`` / ``health_status`` series so two shards on one
shared registry never merge). The chain walk mirrors
``dist_client.request_with_failover``: known-DOWN replicas are skipped
(fail fast past them) unless they are the last resort — except a
rate-limited ``allow_probe`` pass-through so a restarted replica
rejoins. When every replica is skipped or failed, the router answers
from the fleet stale cache (``lookup_stale`` over every version it has
seen) or fails fast with :class:`FleetUnavailable`.

**Mutation propagation.** One :meth:`FleetRouter.apply_delta` fans out
to every shard (local shards stage into their
:class:`~glt_tpu.stream.StreamIngestor`; remote replicas get the
``apply_delta`` rpc, idempotent via the req-id dedup LRU). Propagation
runs under the snapshot gate's WRITE side while requests run under its
READ side, so no request ever spans mixed snapshot versions — the
versioned consistency token (``fleet_version`` gauge,
:meth:`FleetRouter.consistency_token`) advances only after every shard
has swapped + invalidated.

**Burn-driven scaling.** The router evaluates a per-shard
:class:`~glt_tpu.obs.SloBurnEvaluator` policy over the shared registry
(each shard's ``serving_latency_seconds{view=<shard>}`` series) and
publishes ``fleet_scale_signal{shard=}`` (+1 scale-up on fast burn, -1
scale-down on sustained idle, 0 otherwise); a fast-burn +1 also trips
the FlightRecorder (``fleet_scale_signal`` event) — the autoscaling
hook an operator or controller watches.

**Tracing.** A request opens one ``fleet.infer`` span; per-shard
dispatches run under ``contextvars.copy_context()`` so the rpc fabric
propagates ONE trace id from the router span through every shard's
server-side handler spans (the PR 6 rpc header contract).

See docs/serving_fleet.md for topology, admission classes, the
consistency token, and the knob table.
"""
from __future__ import annotations

import contextvars
import dataclasses
import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs.recorder import SloBurnEvaluator, SloPolicy, get_recorder
from ..obs.registry import MetricsRegistry
from ..obs.trace import get_tracer
from ..partition.partition_book import PartitionBook, infer_partition_book
from ..resilience.health import HealthMonitor
from ..resilience.retry import CircuitBreaker, CircuitOpenError, RetryPolicy
from ..utils import as_numpy
from .batcher import EngineStalledError, ServingOverloaded
from .embedding_cache import EmbeddingCache
from .engine import InferenceEngine
from .metrics import ServingMetrics

logger = logging.getLogger(__name__)

#: failures that justify walking to the next replica in the chain —
#: connection-class errors (a breaker rejection IS a ConnectionError)
#: plus the engine stall watchdog. Anything else (a ValueError from id
#: validation, a handler bug) re-raises: failing over a caller bug
#: would just fail it M times.
FAILOVER_ERRORS = (ConnectionError, OSError, TimeoutError,
                   EngineStalledError)


class FleetOverloaded(ServingOverloaded):
  """Admission rejected the request BEFORE dispatch: its class queue is
  full, or its deadline lapsed while waiting for an inflight slot."""


class FleetUnavailable(ConnectionError):
  """A shard's whole replica set is down and the stale tier could not
  answer. Subclasses ConnectionError so callers' existing
  connection-failure handling applies."""


# -- admission ------------------------------------------------------------


@dataclasses.dataclass
class AdmissionClass:
  """One admission class (``interactive`` / ``batch`` / ...).

  Args:
    name: class label on the ``fleet_*`` series.
    max_inflight: concurrent dispatches for this class.
    max_queue: admitted-but-waiting bound; arrivals past
      ``max_inflight + max_queue`` are rejected immediately.
    deadline_ms: default per-request deadline (a request still waiting
      for a slot when it lapses is SHED before dispatch; the remainder
      bounds every downstream rpc/engine wait).
  """
  name: str = 'default'
  max_inflight: int = 64
  max_queue: int = 256
  deadline_ms: float = 1000.0


class AdmissionController:
  """Bounded per-class queues with deadline shedding BEFORE dispatch.

  Overload control at the door (the "overload control for scaled
  services" lever): a request that cannot possibly meet its deadline is
  cheapest to fail while it has consumed nothing but a queue slot —
  shedding it AFTER the engine forward would burn a bucket on an answer
  nobody is waiting for. Rejections (queue full) and sheds (deadline
  lapsed waiting) are separate counters: the first says "add capacity
  or shrink the class", the second "the fleet is too slow for this
  deadline".
  """

  def __init__(self, classes: Optional[Sequence[AdmissionClass]] = None,
               registry: Optional[MetricsRegistry] = None):
    classes = list(classes) if classes else [AdmissionClass()]
    self.classes: Dict[str, AdmissionClass] = {
        c.name: c for c in classes}
    self._registry = registry
    self._lock = threading.Lock()
    self._cond = threading.Condition(self._lock)
    self._inflight = {c.name: 0 for c in classes}
    self._waiting = {c.name: 0 for c in classes}

  def _count(self, metric: str, klass: str) -> None:
    if self._registry is not None:
      self._registry.inc(metric, **{'class': klass})

  def admit(self, klass: str, deadline_ts: float) -> AdmissionClass:
    """Block until an inflight slot is free; the caller MUST pair with
    :meth:`release`. Raises :class:`FleetOverloaded` on a full class
    queue or a deadline lapsing before dispatch."""
    cls = self.classes.get(klass)
    if cls is None:
      raise KeyError(f'unknown admission class {klass!r} '
                     f'(have {sorted(self.classes)})')
    with self._cond:
      if (self._waiting[cls.name] + self._inflight[cls.name]
          >= cls.max_inflight + cls.max_queue):
        self._count('fleet_rejected_total', cls.name)
        raise FleetOverloaded(
            f'admission queue full for class {cls.name!r} '
            f'({self._waiting[cls.name]} waiting + '
            f'{self._inflight[cls.name]} inflight)')
      self._waiting[cls.name] += 1
      try:
        while self._inflight[cls.name] >= cls.max_inflight:
          remaining = deadline_ts - time.monotonic()
          if remaining <= 0:
            self._count('fleet_shed_total', cls.name)
            raise FleetOverloaded(
                f'deadline lapsed before dispatch (class {cls.name!r})')
          self._cond.wait(timeout=remaining)
      finally:
        self._waiting[cls.name] -= 1
      self._inflight[cls.name] += 1
    return cls

  def release(self, klass: str) -> None:
    with self._cond:
      self._inflight[klass] -= 1
      self._cond.notify()

  def snapshot(self) -> dict:
    with self._lock:
      return {name: {'inflight': self._inflight[name],
                     'waiting': self._waiting[name],
                     'max_inflight': c.max_inflight,
                     'max_queue': c.max_queue,
                     'deadline_ms': c.deadline_ms}
              for name, c in self.classes.items()}


# -- snapshot gate --------------------------------------------------------


class _SnapshotGate:
  """Reader-writer gate for the consistency token: infers are readers,
  ``apply_delta`` the (writer-preferring) writer. Holding WRITE across
  the whole fan-out is what makes the token a real barrier: no request
  admitted during propagation can observe shard A on version v and
  shard B still on v-1. The price is a serving pause bounded by one
  compaction (documented in docs/serving_fleet.md)."""

  def __init__(self):
    self._lock = threading.Lock()
    self._cond = threading.Condition(self._lock)
    self._readers = 0
    self._writer = False
    self._writers_waiting = 0

  def read_acquire(self, timeout: Optional[float] = None) -> bool:
    deadline = None if timeout is None else time.monotonic() + timeout
    with self._cond:
      # writer preference: readers queue behind a waiting writer so a
      # steady request stream cannot starve delta propagation forever
      while self._writer or self._writers_waiting:
        remaining = None if deadline is None \
            else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
          return False
        self._cond.wait(timeout=remaining)
      self._readers += 1
      return True

  def read_release(self) -> None:
    with self._cond:
      self._readers -= 1
      if self._readers == 0:
        self._cond.notify_all()

  def write_acquire(self) -> None:
    with self._cond:
      self._writers_waiting += 1
      try:
        while self._writer or self._readers:
          self._cond.wait()
      finally:
        self._writers_waiting -= 1
      self._writer = True

  def write_release(self) -> None:
    with self._cond:
      self._writer = False
      self._cond.notify_all()


# -- scaling policy -------------------------------------------------------


@dataclasses.dataclass
class ScalePolicy:
  """Burn-signal contract for :meth:`FleetRouter.evaluate_scaling`.

  Per shard, over the window since the previous evaluation:
  ``burn >= scale_up_burn`` publishes ``fleet_scale_signal{shard=}=+1``
  and trips the FlightRecorder (fast burn: latency SLO budget burning
  ``scale_up_burn``x too fast — add a replica / split the shard);
  ``burn <= scale_down_burn`` publishes -1 (sustained headroom);
  anything else 0. Windows thinner than ``min_window`` requests always
  publish 0 — a 3-request blip must not page anyone.
  """
  threshold_s: float = 0.25
  objective: float = 0.99
  scale_up_burn: float = 6.0
  scale_down_burn: float = 0.1
  min_window: int = 20


# -- replicas -------------------------------------------------------------


class _LocalReplica:
  """In-process engine behind the same breaker contract as a remote
  peer: ``infer`` takes the breaker token, failures in
  :data:`FAILOVER_ERRORS` count toward opening it, anything else
  returns the token without counting (a caller bug is not peer
  death)."""

  kind = 'local'

  def __init__(self, name: str, engine: InferenceEngine,
               breaker: CircuitBreaker):
    self.name = name
    self.engine = engine
    self.breaker = breaker

  def infer(self, ids: np.ndarray,
            timeout_ms: Optional[float] = None) -> np.ndarray:
    if not self.breaker.allow():
      raise CircuitOpenError(
          f'replica {self.name}: circuit OPEN (fail fast)')
    try:
      out = self.engine.infer(ids)
    except FAILOVER_ERRORS:
      self.breaker.record_failure()
      raise
    except Exception:
      self.breaker.release_probe()
      raise
    self.breaker.record_success()
    return out

  def apply_delta(self, **kw) -> dict:
    raise RuntimeError(
        'local replicas receive deltas through their shard ingestor, '
        'not apply_delta')

  def close(self) -> None:
    pass


class _RemoteReplica:
  """A ServingServer endpoint over the hardened rpc fabric. The
  breaker/retry live INSIDE the RpcClient (the PR 5 ladder);
  ``connect_retries`` is kept small so a dead peer costs one fast
  connect failure, not a 30 s redial loop, before the chain walks on."""

  kind = 'remote'

  def __init__(self, name: str, host: str, port: int,
               breaker: CircuitBreaker,
               retry: Optional[RetryPolicy] = None,
               timeout: float = 30.0, connect_retries: int = 1,
               metrics: Optional[ServingMetrics] = None):
    from ..distributed.rpc import RpcClient
    self.name = name
    self.address = (str(host), int(port))
    self.breaker = breaker
    self._rpc = RpcClient(
        host, port, timeout=timeout,
        connect_retries=connect_retries, retry_interval=0.1,
        retry=retry or RetryPolicy(max_attempts=2, base_delay_s=0.02,
                                   max_delay_s=0.2),
        breaker=breaker,
        # apply_delta rides the req-id dedup LRU: a lost-reply retry
        # replays the recorded reply, never double-stages the cut
        idempotent=frozenset({'apply_delta'}),
        metrics=metrics)

  def infer(self, ids: np.ndarray,
            timeout_ms: Optional[float] = None) -> np.ndarray:
    rpc_timeout = (timeout_ms / 1e3 + 5.0
                   if timeout_ms is not None else None)
    return np.asarray(self._rpc.request(
        'infer', np.asarray(ids, np.int64), timeout_ms=timeout_ms,
        _rpc_timeout=rpc_timeout))

  def apply_delta(self, **kw) -> dict:
    return self._rpc.request('apply_delta', **kw)

  def close(self) -> None:
    self._rpc.close()


# -- shards ---------------------------------------------------------------


class FleetShard:
  """One shard: an ordered replica chain plus its resilience state.

  Build with :meth:`local` (in-process engines) or :meth:`remote`
  (ServingServer addresses); the :class:`FleetRouter` binds metrics,
  breakers, and the health monitor when it takes ownership — all
  labeled series are created in one place, keyed ``shard``/``replica``,
  so two shards on one registry can never merge.
  """

  def __init__(self, name: str, *, engines: Sequence = (),
               addresses: Sequence = (), manager=None,
               samplers: Optional[Sequence] = None,
               retry: Optional[RetryPolicy] = None,
               breaker_threshold: int = 3, breaker_reset_s: float = 2.0,
               rpc_timeout: float = 30.0, connect_retries: int = 1,
               probe_interval_s: float = 0.5):
    assert bool(engines) != bool(addresses), \
        'a shard is local (engines=) XOR remote (addresses=)'
    self.name = str(name)
    self._engines = list(engines)
    self._addresses = [(str(h), int(p)) for h, p in addresses]
    self._manager = manager
    self._samplers = list(samplers) if samplers is not None else [
        e.sampler for e in self._engines
        if hasattr(e.sampler, 'refresh_overlay')]
    self._retry = retry
    self._breaker_threshold = int(breaker_threshold)
    self._breaker_reset_s = float(breaker_reset_s)
    self._rpc_timeout = float(rpc_timeout)
    self._connect_retries = int(connect_retries)
    self._probe_interval_s = float(probe_interval_s)
    self._ingestor = None
    # bound by the router:
    self.replicas: List = []
    self.metrics: Optional[ServingMetrics] = None
    self.health: Optional[HealthMonitor] = None
    self.slo: Optional[SloBurnEvaluator] = None

  # -- construction -------------------------------------------------------

  @classmethod
  def local(cls, name: str, engines: Sequence[InferenceEngine],
            manager=None, samplers: Optional[Sequence] = None,
            **kw) -> 'FleetShard':
    """In-process replicas. ``manager`` (a SnapshotManager shared by
    the engines) enables ``apply_delta`` propagation; ``samplers``
    (StreamSamplers to overlay-refresh, default: each engine's own
    when it is a StreamSampler) must cover every engine or folded
    deltas stay visible in stale overlays."""
    return cls(name, engines=engines, manager=manager,
               samplers=samplers, **kw)

  @classmethod
  def remote(cls, name: str, addresses: Sequence, **kw) -> 'FleetShard':
    """Remote ServingServer replicas as ``[(host, port), ...]`` walked
    in order (first = primary)."""
    return cls(name, addresses=addresses, **kw)

  def _bind(self, registry: MetricsRegistry,
            scale_policy: ScalePolicy) -> None:
    """Router-side composition: per-replica breakers, the shard health
    monitor, the shard metrics view, and the shard burn policy — every
    series labeled with this shard's name."""
    self.metrics = ServingMetrics(registry=registry, name=self.name)
    probes = {}
    for i, eng in enumerate(self._engines):
      rname = f'r{i}'
      breaker = CircuitBreaker(
          failure_threshold=self._breaker_threshold,
          reset_timeout_s=self._breaker_reset_s,
          name=f'{self.name}/{rname}',
          labels={'shard': self.name, 'replica': rname},
          registry=registry)
      self.replicas.append(_LocalReplica(rname, eng, breaker))
      # a local replica's liveness probe is its (lock-free) stats
      # surface — it cannot hang on a wedged engine lock
      probes[rname] = (lambda e=eng: e.compile_stats())
    for i, (host, port) in enumerate(self._addresses):
      rname = f'r{i}'
      breaker = CircuitBreaker(
          failure_threshold=self._breaker_threshold,
          reset_timeout_s=self._breaker_reset_s,
          name=f'{self.name}/{rname}',
          labels={'shard': self.name, 'replica': rname},
          registry=registry)
      self.replicas.append(_RemoteReplica(
          rname, host, port, breaker, retry=self._retry,
          timeout=self._rpc_timeout,
          connect_retries=self._connect_retries,
          metrics=self.metrics))
      from ..distributed.rpc import ping_endpoint
      probes[rname] = (lambda h=host, p=port:
                       ping_endpoint(h, p, timeout=2.0))
    # passive-first: the request path feeds record_failure/success; no
    # background prober thread unless the caller starts one. DOWN after
    # 2 consecutive failures — a fleet wants to stop queueing on a
    # corpse quickly; allow_probe re-admits it for recovery.
    self.health = HealthMonitor(
        probes, interval_s=self._probe_interval_s, degraded_after=1,
        down_after=2, labels={'shard': self.name}, registry=registry)
    self.slo = SloBurnEvaluator(
        [SloPolicy(name=self.name,
                   metric='serving_latency_seconds',
                   threshold_s=scale_policy.threshold_s,
                   objective=scale_policy.objective,
                   labels={'view': self.name})],
        registry=registry)

  # -- serving ------------------------------------------------------------

  def infer_failover(self, ids: np.ndarray,
                     timeout_ms: Optional[float] = None) -> np.ndarray:
    """Walk the replica chain (request_with_failover semantics): skip
    known-DOWN replicas unless last resort or a rate-limited
    probe-through; count every k>0 success as a failover. Raises the
    last :data:`FAILOVER_ERRORS` member when the whole chain fails."""
    chain = self.replicas
    last: Optional[BaseException] = None
    t0 = time.perf_counter()
    for k, rep in enumerate(chain):
      if (self.health.is_down(rep.name) and k < len(chain) - 1
          and not self.health.allow_probe(rep.name)):
        last = last or FleetUnavailable(
            f'{self.name}/{rep.name} is DOWN')
        continue
      if (self.health.is_down(rep.name) and k == len(chain) - 1
          and last is not None
          and not self.health.allow_probe(
              rep.name, min_interval_s=self._probe_interval_s)):
        # fail FAST while the whole set is down: the last resort is
        # only exercised on the rate-limited probe cadence, so a
        # dead-shard request costs a dict lookup, not a dial
        continue
      try:
        out = rep.infer(ids, timeout_ms=timeout_ms)
      except FAILOVER_ERRORS as e:
        self.health.record_failure(rep.name)
        last = e
        continue
      self.health.record_success(rep.name)
      if k > 0:
        self.metrics.record_failover()
      self.metrics.record_request(time.perf_counter() - t0,
                                  int(np.asarray(ids).size))
      return out
    raise last if last is not None else FleetUnavailable(
        f'shard {self.name} has no replicas')

  # -- mutation -----------------------------------------------------------

  @property
  def can_apply(self) -> bool:
    return self._manager is not None or bool(self._addresses)

  def apply(self, ins=None, dels=None, feat_ids=None,
            feat_rows=None) -> dict:
    """Propagate one delta to every replica of this shard; returns
    ``{'version': ..., 'invalidated': ...}``. Local: stage into the
    shared SnapshotManager once, then swap every engine onto the fresh
    snapshot. Remote: ``apply_delta`` rpc per replica (each owns its
    snapshot chain); all replicas must land on one version."""
    if self._manager is not None:
      return self._apply_local(ins, dels, feat_ids, feat_rows)
    if self._addresses:
      return self._apply_remote(ins=ins, dels=dels, feat_ids=feat_ids,
                                feat_rows=feat_rows)
    raise RuntimeError(
        f'shard {self.name} cannot apply deltas: local shard built '
        'without manager= (no stream lineage)')

  def _ingest(self):
    if self._ingestor is None:
      from ..stream.ingest import StreamIngestor
      # engine/sampler deliberately None: apply() fans the swap out to
      # EVERY engine/sampler, not just one
      self._ingestor = StreamIngestor(self._manager, auto_refresh=False)
    return self._ingestor

  def _apply_local(self, ins, dels, feat_ids, feat_rows) -> dict:
    ing = self._ingest()
    if ins is not None:
      ins = np.asarray(ins, np.int64).reshape(2, -1)
      if ins.shape[1]:
        ing.insert_edges(ins[0], ins[1])
    if dels is not None:
      dels = np.asarray(dels, np.int64).reshape(2, -1)
      if dels.shape[1]:
        ing.delete_edges(dels[0], dels[1])
    if feat_ids is not None:
      feat_ids = np.asarray(feat_ids, np.int64).reshape(-1)
      if feat_ids.size:
        ing.update_features(feat_ids, np.asarray(feat_rows))
    info = ing.flush()
    snap = self._manager.current()
    invalidated = 0
    if info is not None:
      # order per engine matches the ingestor contract: overlay drops
      # the folded ops first, cache invalidation runs strictly after
      # the feature swap
      for sampler in self._samplers:
        sampler.refresh_overlay(ing.edges)
      for eng in self._engines:
        invalidated += eng.update_snapshot(
            snap, touched_ids=info.get('touched'),
            version=info.get('version'))
    return {'version': int(snap.version), 'invalidated': invalidated,
            'compacted': info is not None}

  def _apply_remote(self, **kw) -> dict:
    versions, invalidated, last = [], 0, None
    for rep in self.replicas:
      try:
        out = rep.apply_delta(compact=True, **kw)
      except FAILOVER_ERRORS as e:
        # a dead replica misses the delta; its restart/recovery path
        # must resync before rejoining — record loudly
        self.health.record_failure(rep.name)
        logger.warning('apply_delta to %s/%s failed: %s', self.name,
                       rep.name, e)
        last = e
        continue
      self.health.record_success(rep.name)
      versions.append(int(out.get('version', -1)))
      invalidated += int(out.get('invalidated', 0))
    if not versions:
      raise last if last is not None else FleetUnavailable(
          f'shard {self.name}: no replica accepted the delta')
    if len(set(versions)) > 1:
      logger.warning('shard %s replicas diverged on snapshot version '
                     '%s', self.name, versions)
    return {'version': max(versions), 'invalidated': invalidated,
            'compacted': True, 'missed_replicas': last is not None}

  def close(self) -> None:
    if self.health is not None:
      self.health.stop()
    for rep in self.replicas:
      try:
        rep.close()
      except Exception:
        pass


# -- the router -----------------------------------------------------------


class FleetRouter:
  """One serving endpoint over partitioned/replicated shards.

  Args:
    shards: :class:`FleetShard` list; index == partition index.
    partition_book: seed id -> shard index (a
      :class:`~glt_tpu.partition.partition_book.PartitionBook` or an
      array accepted by ``infer_partition_book``). Replicated fleets
      (every shard serves the full graph) still route by the book —
      it is the load-spreading function.
    admission: :class:`AdmissionController`; None builds one with a
      single permissive ``default`` class.
    registry: shared MetricsRegistry for every per-shard series +
      the fleet series; None builds a private one (tests).
    scale_policy: burn-signal thresholds (:class:`ScalePolicy`).
    stale_serve: answer from the fleet stale cache when a shard's
      whole replica chain fails (rows + zero-fills counted); off =
      fail fast with :class:`FleetUnavailable`.
    stale_capacity: fleet stale-cache entries (successful rows are
      written back on every request while ``stale_serve`` is on).
    dispatch_workers: thread pool width for multi-shard fan-out.
  """

  def __init__(self, shards: Sequence[FleetShard], partition_book,
               admission: Optional[AdmissionController] = None,
               registry: Optional[MetricsRegistry] = None,
               scale_policy: Optional[ScalePolicy] = None,
               stale_serve: bool = True,
               stale_capacity: int = 100_000,
               dispatch_workers: Optional[int] = None,
               start_health_probes: bool = False):
    assert shards, 'a fleet needs at least one shard'
    self.registry = registry if registry is not None \
        else MetricsRegistry()
    self.shards = list(shards)
    self.book: PartitionBook = infer_partition_book(partition_book)
    if self.book.num_partitions != len(self.shards):
      raise ValueError(
          f'partition book maps {self.book.num_partitions} partitions '
          f'but the fleet has {len(self.shards)} shards')
    self.scale_policy = scale_policy or ScalePolicy()
    self.admission = admission if admission is not None \
        else AdmissionController(registry=self.registry)
    if self.admission._registry is None:
      self.admission._registry = self.registry
    self.stale_serve = bool(stale_serve)
    self._stale = EmbeddingCache(stale_capacity if stale_serve else 0)
    self.metrics = ServingMetrics(registry=self.registry, name='fleet')
    self._gate = _SnapshotGate()
    self._version = 0
    self._out_dim: Optional[int] = None
    names = set()
    for shard in self.shards:
      assert shard.name not in names, f'duplicate shard {shard.name!r}'
      names.add(shard.name)
      shard._bind(self.registry, self.scale_policy)
      if start_health_probes:
        shard.health.start()
    self._pool = ThreadPoolExecutor(
        max_workers=dispatch_workers or min(16, 2 * len(self.shards)),
        thread_name_prefix='glt-fleet')
    self.registry.set('fleet_version', 0.0)

  # -- request path -------------------------------------------------------

  def infer(self, ids, klass: str = 'default',
            timeout_ms: Optional[float] = None) -> np.ndarray:
    """Embeddings for ``ids`` (any shard mix, duplicates allowed),
    aligned with the input order. One trace id covers the router span
    and every shard dispatch under it."""
    t0 = time.perf_counter()
    ids_np = as_numpy(ids).astype(np.int64).reshape(-1)
    tracer = get_tracer()
    with tracer.span('fleet.infer', ids=int(ids_np.size),
                     klass=str(klass)):
      cls = self.admission.classes.get(klass)
      deadline_ms = timeout_ms if timeout_ms is not None \
          else (cls.deadline_ms if cls else 1000.0)
      deadline_ts = time.monotonic() + deadline_ms / 1e3
      self.admission.admit(klass, deadline_ts)
      try:
        out = self._routed_infer(ids_np, deadline_ts)
      finally:
        self.admission.release(klass)
      self.metrics.record_request(time.perf_counter() - t0,
                                  int(ids_np.size))
      self.registry.inc('fleet_requests_total', **{'class': klass})
      return out

  def _routed_infer(self, ids_np: np.ndarray,
                    deadline_ts: float) -> np.ndarray:
    if ids_np.size == 0:
      return np.zeros((0, self._out_dim or 0), np.float32)
    if ids_np.min() < 0:
      raise ValueError(
          f'negative node ids: {ids_np[ids_np < 0][:8].tolist()}')
    part = self.book[ids_np]
    if part.max() >= len(self.shards):
      bad = ids_np[part >= len(self.shards)][:8]
      raise ValueError(
          f'node ids past the partition book: {bad.tolist()}')
    remaining = deadline_ts - time.monotonic()
    # the gate read waits out any in-flight delta barrier — but never
    # past this request's deadline (counted as a shed: the request
    # died BEFORE dispatch)
    if not self._gate.read_acquire(timeout=max(remaining, 0.0)):
      self.registry.inc('fleet_shed_total', **{'class': '_barrier'})
      raise FleetOverloaded(
          'deadline lapsed waiting on the snapshot barrier')
    try:
      token = self._version
      targets = np.unique(part)
      budget_ms = max((deadline_ts - time.monotonic()) * 1e3, 1.0)
      if targets.size == 1:
        rows = self._serve_shard(self.shards[int(targets[0])], ids_np,
                                 budget_ms, token)
        return np.asarray(rows)
      out: List[Optional[np.ndarray]] = [None] * targets.size
      futs = []
      for j, s in enumerate(targets.tolist()):
        sub = ids_np[part == s]
        # copy_context: the shard dispatch (and its rpc spans) must
        # inherit THIS request's trace id, not open orphan roots
        ctx = contextvars.copy_context()
        futs.append((j, s, self._pool.submit(
            ctx.run, self._serve_shard, self.shards[s], sub,
            budget_ms, token)))
      errs = []
      for j, s, fut in futs:
        try:
          out[j] = np.asarray(fut.result())
        except Exception as e:  # collected: one bad shard fails the
          errs.append(e)       # request once, not via a pool deadlock
      if errs:
        raise errs[0]
      result = np.zeros(
          (ids_np.size, out[0].shape[1]), out[0].dtype)
      for j, s in enumerate(targets.tolist()):
        result[part == s] = out[j]
      return result
    finally:
      self._gate.read_release()

  def _serve_shard(self, shard: FleetShard, sub_ids: np.ndarray,
                   budget_ms: float, token: int) -> np.ndarray:
    tracer = get_tracer()
    with tracer.span('fleet.shard', shard=shard.name,
                     ids=int(sub_ids.size)):
      try:
        rows = shard.infer_failover(sub_ids, timeout_ms=budget_ms)
      except FAILOVER_ERRORS as e:
        return self._degrade(shard, sub_ids, e)
      if self._out_dim is None:
        self._out_dim = int(rows.shape[1])
      if self.stale_serve:
        # write-back under the consistency token: lookup_stale probes
        # newest-version-first, so post-delta rows shadow pre-delta
        self._stale.insert(sub_ids, rows, token)
      return rows

  def _degrade(self, shard: FleetShard, sub_ids: np.ndarray,
               cause: BaseException) -> np.ndarray:
    """Last tier: the whole replica chain failed. Serve stale rows
    (zero-fill true misses, both counted) or fail fast."""
    self.registry.inc('fleet_unavailable_total', shard=shard.name)
    if not self.stale_serve:
      raise FleetUnavailable(
          f'shard {shard.name}: all replicas failed '
          f'({cause})') from cause
    found = self._stale.lookup_stale(sub_ids)
    dim = self._out_dim
    if dim is None and found:
      dim = int(next(iter(found.values())).shape[0])
    if dim is None:
      raise FleetUnavailable(
          f'shard {shard.name}: all replicas failed and the stale '
          f'tier is empty ({cause})') from cause
    out = np.zeros((sub_ids.size, dim), np.float32)
    mask = np.zeros(sub_ids.size, bool)
    for k, i in enumerate(sub_ids.tolist()):
      row = found.get(int(i))
      if row is not None:
        out[k] = row
        mask[k] = True
    shard.metrics.record_stale_serve(int(mask.sum()))
    shard.metrics.add_gauge('stale_zero_fills', float((~mask).sum()))
    logger.warning(
        'shard %s degraded (%s): %d/%d rows stale, %d zero-filled',
        shard.name, cause, int(mask.sum()), sub_ids.size,
        int((~mask).sum()))
    return out

  # -- mutation path ------------------------------------------------------

  def apply_delta(self, ins=None, dels=None, feat_ids=None,
                  feat_rows=None) -> dict:
    """Fan one delta out to every shard under the write side of the
    snapshot gate, then advance the fleet consistency token. Edge
    blocks are [2, n] global-id pairs; every shard receives the full
    delta (replicated shards fold it all; a partitioned deployment
    routes sub-deltas before calling this — the gate semantics are
    identical). Requests admitted during propagation wait (bounded by
    their own deadlines); requests already past the gate finish on the
    OLD version fleet-wide before the barrier engages."""
    tracer = get_tracer()
    t = time.perf_counter()
    self._gate.write_acquire()
    try:
      with tracer.span('fleet.apply_delta'):
        results = {}
        for shard in self.shards:
          if not shard.can_apply:
            continue
          results[shard.name] = shard.apply(
              ins=ins, dels=dels, feat_ids=feat_ids,
              feat_rows=feat_rows)
        if not results:
          raise RuntimeError(
              'no shard in this fleet can apply deltas (local shards '
              'need manager=, remote replicas need stream=)')
        self._version += 1
        token = self._version
        if self.stale_serve:
          # stale rows computed against the previous snapshot must not
          # shadow fresh post-delta rows; deltas carry no per-shard
          # touched sets here, so the conservative sweep drops all
          self._stale.invalidate()
    finally:
      self._gate.write_release()
    self.registry.set('fleet_version', float(token))
    get_recorder().record('fleet_delta_applied', version=token,
                          shards=sorted(results),
                          wall_ms=round((time.perf_counter() - t) * 1e3,
                                        2))
    return {'fleet_version': token, 'shards': results}

  def consistency_token(self) -> int:
    """The fleet snapshot version: requests observe one consistent
    value across every shard they touch (the gate's guarantee)."""
    self._gate.read_acquire()
    try:
      return self._version
    finally:
      self._gate.read_release()

  # -- scaling + stats ----------------------------------------------------

  def evaluate_scaling(self) -> dict:
    """Per-shard burn -> ``fleet_scale_signal{shard=}`` (+1/0/-1); a
    fast-burn +1 also trips the FlightRecorder. Call on the scrape
    cadence (the window between calls IS the burn window)."""
    pol = self.scale_policy
    out = {}
    for shard in self.shards:
      det = shard.slo.evaluate_detailed()[shard.name]
      burn, window = det['burn'], det['window']
      signal = 0
      if window >= pol.min_window:
        if burn >= pol.scale_up_burn:
          signal = 1
          get_recorder().trip(
              'fleet_scale_signal', shard=shard.name,
              burn=round(burn, 3), window=window, signal=1,
              threshold_s=pol.threshold_s)
        elif burn <= pol.scale_down_burn:
          signal = -1
      self.registry.set('fleet_scale_signal', float(signal),
                        shard=shard.name)
      out[shard.name] = {'burn': burn, 'window': window,
                         'signal': signal}
    return out

  def stats(self) -> dict:
    shard_stats = {}
    for shard in self.shards:
      shard_stats[shard.name] = {
          'metrics': shard.metrics.snapshot(),
          'health': shard.health.snapshot(),
          'breakers': {r.name: r.breaker.state for r in shard.replicas},
      }
    return {
        'fleet_version': self.consistency_token(),
        'admission': self.admission.snapshot(),
        'scaling': self.evaluate_scaling(),
        'stale_serve_enabled': self.stale_serve,
        'shards': shard_stats,
        'metrics': self.metrics.snapshot(cache=self._stale),
    }

  def close(self) -> None:
    self._pool.shutdown(wait=False)
    for shard in self.shards:
      shard.close()

  def __enter__(self):
    return self

  def __exit__(self, *exc):
    self.close()

"""Micro-batching request queue for online inference.

XLA serves fixed shapes, so per-request execution wastes the device on
tiny launches and — worse — recompiles on every new request size. The
batcher merges concurrent requests into one micro-batch under two
bounds (the classic serving trade-off):

  * ``max_batch_size`` — flush as soon as the queued ids fill a batch
    (throughput bound);
  * ``max_wait_ms``    — flush when the OLDEST queued request has
    waited this long, full or not (latency bound).

Overload is handled at both ends: ``submit`` rejects immediately once
the queue holds ``max_queue`` requests (backpressure — callers see
:class:`ServingOverloaded` instead of unbounded queueing), and each
request carries a deadline after which it is failed with TimeoutError
rather than occupying a batch slot it can no longer use.

The dispatcher is a single thread, which also serializes access to the
engine (whose sampler threads donated buffers through its jitted
programs and is therefore not reentrant).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Callable, List, Optional

import numpy as np


def _fail_future(fut: Future, err: BaseException) -> None:
  """set_exception that tolerates losing the watchdog/dispatcher race:
  done() + set_exception is not atomic, and an InvalidStateError
  escaping the WATCHDOG thread would kill it silently — permanently
  disabling stall protection, the very bug it exists to fix."""
  try:
    if not fut.done():
      fut.set_exception(err)
  except InvalidStateError:
    pass  # the other thread resolved it first: that outcome stands


class ServingOverloaded(RuntimeError):
  """Raised by submit() when the request queue is at capacity."""


class EngineStalledError(RuntimeError):
  """The engine circuit is OPEN: a dispatched forward exceeded the
  stall watchdog's budget (wedged device, dead worker). Pending
  requests are failed with this immediately instead of queueing behind
  a corpse; submit() fails fast with it until the engine proves alive
  (the wedged call returning closes the circuit)."""


class _Request:
  __slots__ = ('ids', 'future', 'deadline', 't_submit')

  def __init__(self, ids, future, deadline, t_submit):
    self.ids = ids
    self.future = future
    self.deadline = deadline
    self.t_submit = t_submit


class MicroBatcher:
  """Deadline-driven micro-batch queue in front of a batch handler.

  Args:
    handler: ``fn(ids: np.ndarray[int64]) -> np.ndarray [len(ids), D]``
      — rows aligned with the input ids (the engine's ``infer``).
    max_batch_size: flush threshold in total queued ids; also the
      capacity used for the batch-fill metric.
    max_wait_ms: max time the oldest request waits before a partial
      flush.
    max_queue: request-count backpressure bound.
    request_timeout_ms: default per-request deadline (None = no
      deadline); ``submit`` can override per call.
    metrics: optional ServingMetrics (batch fill + timeout/reject
      counters).
    stall_timeout_ms: engine watchdog budget — if one dispatched
      handler call runs longer than this, the batch's AND the queue's
      futures are failed with :class:`EngineStalledError` immediately
      (bounded p99 even with a wedged engine) and submit() fails fast
      until the wedged call returns. None disables the watchdog.
  """

  def __init__(self, handler: Callable[[np.ndarray], np.ndarray],
               max_batch_size: int = 64, max_wait_ms: float = 2.0,
               max_queue: int = 1024,
               request_timeout_ms: Optional[float] = 1000.0,
               metrics=None, stall_timeout_ms: Optional[float] = None):
    assert max_batch_size > 0 and max_queue > 0
    self.handler = handler
    self.max_batch_size = int(max_batch_size)
    self.max_wait = float(max_wait_ms) / 1e3
    self.max_queue = int(max_queue)
    self.request_timeout = (float(request_timeout_ms) / 1e3
                            if request_timeout_ms is not None else None)
    self.metrics = metrics
    self.stall_timeout = (float(stall_timeout_ms) / 1e3
                          if stall_timeout_ms is not None else None)
    self._queue: 'deque[_Request]' = deque()
    self._cond = threading.Condition()
    self._running = True
    self._force_flush = False
    # engine-circuit state (watchdog): _inflight tracks the dispatch
    # the handler is currently chewing on; _stalled_gen marks a
    # dispatch the watchdog gave up on (its eventual result is
    # discarded — the futures are long failed)
    self._inflight: Optional[tuple] = None  # (batch, t_start, gen)
    self._gen = 0
    self._stalled = False
    self._stalled_gen = -1
    self._thread = threading.Thread(target=self._dispatch_loop,
                                    daemon=True, name='glt-batcher')
    self._thread.start()
    self._watchdog: Optional[threading.Thread] = None
    if self.stall_timeout is not None:
      self._watchdog = threading.Thread(target=self._watchdog_loop,
                                        daemon=True,
                                        name='glt-batcher-watchdog')
      self._watchdog.start()

  # -- client side -------------------------------------------------------

  def submit(self, ids, timeout_ms: Optional[float] = None) -> Future:
    """Enqueue a request for embeddings of ``ids``; returns a Future
    resolving to an aligned ``[len(ids), D]`` array. Raises
    ServingOverloaded if the queue is full (backpressure), RuntimeError
    after stop()."""
    ids = np.asarray(ids, dtype=np.int64).reshape(-1)
    timeout = (float(timeout_ms) / 1e3 if timeout_ms is not None
               else self.request_timeout)
    fut: Future = Future()
    with self._cond:
      if not self._running:
        raise RuntimeError('batcher is stopped')
      if self._stalled:
        # engine circuit OPEN: fail fast instead of queueing behind a
        # wedged forward (the server may answer from the embedding
        # cache instead — its stale-serve tier)
        if self.metrics is not None:
          self.metrics.record_shed()
        raise EngineStalledError(
            'engine stalled (dispatch exceeded '
            f'{self.stall_timeout}s); failing fast while the circuit '
            'is open')
      if len(self._queue) >= self.max_queue:
        if self.metrics is not None:
          self.metrics.record_rejected()
        raise ServingOverloaded(
            f'queue at capacity ({self.max_queue} requests)')
      now = time.monotonic()
      self._queue.append(_Request(
          ids, fut, now + timeout if timeout is not None else None, now))
      # notify_all: the watchdog waits on this condition too — a single
      # notify could wake IT instead of the dispatcher and strand the
      # queue until the next timeout tick
      self._cond.notify_all()
    return fut

  def flush(self) -> None:
    """Force an immediate flush of whatever is queued."""
    with self._cond:
      self._force_flush = True
      self._cond.notify_all()

  @property
  def depth(self) -> int:
    with self._cond:
      return len(self._queue)

  @property
  def stalled(self) -> bool:
    """True while the engine circuit is OPEN (a dispatch blew past
    ``stall_timeout_ms`` and has not returned yet)."""
    with self._cond:
      return self._stalled

  def stop(self) -> None:
    """Stop the dispatcher; pending requests fail with RuntimeError."""
    with self._cond:
      self._running = False
      pending = list(self._queue)
      self._queue.clear()
      self._cond.notify_all()
    for r in pending:
      _fail_future(r.future, RuntimeError('batcher stopped'))
    self._thread.join(timeout=5)
    if self._watchdog is not None:
      self._watchdog.join(timeout=5)

  # -- dispatcher --------------------------------------------------------

  def _expire_locked(self, now: float) -> None:
    """Fail queued requests whose deadline has passed. A deadline
    firing on an all-expired queue is the 'empty flush' case: the
    handler is simply not called. Counted as BOTH a timeout (the
    client-visible outcome) and a shed (the request never occupied a
    dispatch slot — load-shedding accounting)."""
    live = deque()
    for r in self._queue:
      if r.deadline is not None and now >= r.deadline:
        if self.metrics is not None:
          self.metrics.record_timeout()
          self.metrics.record_shed()
        _fail_future(r.future, TimeoutError(
            f'request timed out after {now - r.t_submit:.3f}s in queue'))
      else:
        live.append(r)
    self._queue = live

  def _pop_batch_locked(self) -> List[_Request]:
    """Take requests FIFO while they fit in max_batch_size total ids.
    The head request always ships even if oversized by itself (the
    engine chunks across buckets); later oversized requests wait for
    the next flush rather than starving the current one."""
    batch: List[_Request] = []
    total = 0
    while self._queue:
      r = self._queue[0]
      if batch and total + r.ids.size > self.max_batch_size:
        break
      batch.append(self._queue.popleft())
      total += r.ids.size
      if total >= self.max_batch_size:
        break
    return batch

  def _next_wakeup_locked(self, now: float) -> float:
    """Seconds until the next actionable instant: the oldest request's
    flush deadline or the nearest per-request timeout."""
    t = self._queue[0].t_submit + self.max_wait
    for r in self._queue:
      if r.deadline is not None:
        t = min(t, r.deadline)
    return max(t - now, 0.0)

  def _dispatch_loop(self) -> None:
    while True:
      batch: List[_Request] = []
      with self._cond:
        while self._running:
          now = time.monotonic()
          self._expire_locked(now)
          if not self._queue:
            self._force_flush = False
            self._cond.wait()
            continue
          total = sum(r.ids.size for r in self._queue)
          oldest_wait = now - self._queue[0].t_submit
          if (total >= self.max_batch_size
              or oldest_wait >= self.max_wait or self._force_flush):
            batch = self._pop_batch_locked()
            if not self._queue:
              self._force_flush = False
            break
          self._cond.wait(timeout=self._next_wakeup_locked(now))
        if not self._running:
          return
        if batch:
          self._gen += 1
          gen = self._gen
          self._inflight = (batch, time.monotonic(), gen)
      if batch:
        try:
          self._dispatch(batch)
        except BaseException as e:  # noqa: BLE001 — the thread SURVIVES
          # _dispatch fails its batch internally for handler errors;
          # this wrapper is the backstop for failures in the dispatch
          # MACHINERY itself (which used to kill this thread silently,
          # stranding every queued request until its timeout). Fail the
          # batch with the original error; queued requests stay queued
          # — the surviving dispatcher serves them next
          for r in batch:
            _fail_future(r.future, e)
        finally:
          with self._cond:
            if self._stalled_gen == gen:
              # the wedged call came back: the engine is alive again —
              # close the circuit (its futures were already failed by
              # the watchdog; any result was discarded by done() guards)
              self._stalled = False
              self._stalled_gen = -1
              if self.metrics is not None:
                self.metrics.set_gauge('engine_stalled', 0.0)
            self._inflight = None

  def _watchdog_loop(self) -> None:
    poll = max(self.stall_timeout / 4, 0.005)
    while True:
      with self._cond:
        if not self._running:
          return
        victims: List[_Request] = []
        if self._inflight is not None and not self._stalled:
          batch, t0, gen = self._inflight
          if time.monotonic() - t0 >= self.stall_timeout:
            self._stalled = True
            self._stalled_gen = gen
            victims = list(batch) + list(self._queue)
            self._queue.clear()
            if self.metrics is not None:
              self.metrics.record_breaker_open()
              self.metrics.set_gauge('engine_stalled', 1.0)
              # queued requests never dispatched: that is load shedding
              self.metrics.record_shed(len(victims) - len(batch))
        if not victims:
          # nothing notifies during a stall (the dispatcher is wedged
          # in the handler), so waiting BEFORE failing freshly
          # collected victims would delay them a whole poll interval
          # past the documented stall budget
          self._cond.wait(timeout=poll)
      if victims:
        err = EngineStalledError(
            f'engine stalled: dispatch exceeded {self.stall_timeout}s '
            '(wedged forward / dead device); failing pending requests')
        for r in victims:
          _fail_future(r.future, err)
        try:  # postmortem flight-recorder dump (obs layer) — AFTER the
          # victims are failed: clients already past their stall budget
          # must not also wait out a registry snapshot + disk write
          from ..obs.recorder import get_recorder
          get_recorder().trip(
              'engine_stall', stall_timeout_s=self.stall_timeout,
              victims=len(victims), error=str(err))
        except Exception:  # gltlint: disable=GLT006
          pass  # the recorder itself failed; nothing left to record to

  def _dispatch(self, batch: List[_Request]) -> None:
    try:
      # shed-at-dispatch: a request whose deadline lapsed between
      # queue-expiry and here must not ride the batch — it is failed
      # NOW (before the handler runs), not after wasting a slot
      now = time.monotonic()
      live: List[_Request] = []
      for r in batch:
        if r.deadline is not None and now >= r.deadline:
          if self.metrics is not None:
            self.metrics.record_timeout()
            self.metrics.record_shed()
          _fail_future(r.future, TimeoutError(
              f'request deadline lapsed after '
              f'{now - r.t_submit:.3f}s, shed before dispatch'))
        else:
          live.append(r)
      batch = live
      if not batch:
        return
      ids = np.concatenate([r.ids for r in batch])
      if self.metrics is not None:
        # an oversized head request ships whole: count its true size as
        # the capacity so the fill ratio stays a [0, 1] utilization
        self.metrics.record_batch(ids.size,
                                  max(ids.size, self.max_batch_size))
      from ..obs import get_tracer
      with get_tracer().span('serve.flush', requests=len(batch),
                             ids=int(ids.size)):
        out = self.handler(ids)
      out = np.asarray(out)
      if out.shape[0] != ids.size:
        # a real error, not an assert: under python -O a misaligned
        # handler would silently slice wrong rows to wrong callers
        raise ValueError(
            f'handler returned {out.shape[0]} rows for {ids.size} ids')
    except BaseException as e:  # noqa: BLE001 — failures go to callers
      for r in batch:
        _fail_future(r.future, e)
      return
    lo = 0
    for r in batch:
      hi = lo + r.ids.size
      try:
        if not r.future.done():
          r.future.set_result(out[lo:hi])
      except InvalidStateError:
        pass  # lost the race to the watchdog: its failure stands
      lo = hi

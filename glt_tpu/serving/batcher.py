"""Micro-batching request queue for online inference.

XLA serves fixed shapes, so per-request execution wastes the device on
tiny launches and — worse — recompiles on every new request size. The
batcher merges concurrent requests into one micro-batch under two
bounds (the classic serving trade-off):

  * ``max_batch_size`` — flush as soon as the queued ids fill a batch
    (throughput bound);
  * ``max_wait_ms``    — flush when the OLDEST queued request has
    waited this long, full or not (latency bound).

Overload is handled at both ends: ``submit`` rejects immediately once
the queue holds ``max_queue`` requests (backpressure — callers see
:class:`ServingOverloaded` instead of unbounded queueing), and each
request carries a deadline after which it is failed with TimeoutError
rather than occupying a batch slot it can no longer use.

The dispatcher is a single thread, which also serializes access to the
engine (whose sampler threads donated buffers through its jitted
programs and is therefore not reentrant).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional

import numpy as np


class ServingOverloaded(RuntimeError):
  """Raised by submit() when the request queue is at capacity."""


class _Request:
  __slots__ = ('ids', 'future', 'deadline', 't_submit')

  def __init__(self, ids, future, deadline, t_submit):
    self.ids = ids
    self.future = future
    self.deadline = deadline
    self.t_submit = t_submit


class MicroBatcher:
  """Deadline-driven micro-batch queue in front of a batch handler.

  Args:
    handler: ``fn(ids: np.ndarray[int64]) -> np.ndarray [len(ids), D]``
      — rows aligned with the input ids (the engine's ``infer``).
    max_batch_size: flush threshold in total queued ids; also the
      capacity used for the batch-fill metric.
    max_wait_ms: max time the oldest request waits before a partial
      flush.
    max_queue: request-count backpressure bound.
    request_timeout_ms: default per-request deadline (None = no
      deadline); ``submit`` can override per call.
    metrics: optional ServingMetrics (batch fill + timeout/reject
      counters).
  """

  def __init__(self, handler: Callable[[np.ndarray], np.ndarray],
               max_batch_size: int = 64, max_wait_ms: float = 2.0,
               max_queue: int = 1024,
               request_timeout_ms: Optional[float] = 1000.0,
               metrics=None):
    assert max_batch_size > 0 and max_queue > 0
    self.handler = handler
    self.max_batch_size = int(max_batch_size)
    self.max_wait = float(max_wait_ms) / 1e3
    self.max_queue = int(max_queue)
    self.request_timeout = (float(request_timeout_ms) / 1e3
                            if request_timeout_ms is not None else None)
    self.metrics = metrics
    self._queue: 'deque[_Request]' = deque()
    self._cond = threading.Condition()
    self._running = True
    self._force_flush = False
    self._thread = threading.Thread(target=self._dispatch_loop,
                                    daemon=True, name='glt-batcher')
    self._thread.start()

  # -- client side -------------------------------------------------------

  def submit(self, ids, timeout_ms: Optional[float] = None) -> Future:
    """Enqueue a request for embeddings of ``ids``; returns a Future
    resolving to an aligned ``[len(ids), D]`` array. Raises
    ServingOverloaded if the queue is full (backpressure), RuntimeError
    after stop()."""
    ids = np.asarray(ids, dtype=np.int64).reshape(-1)
    timeout = (float(timeout_ms) / 1e3 if timeout_ms is not None
               else self.request_timeout)
    fut: Future = Future()
    with self._cond:
      if not self._running:
        raise RuntimeError('batcher is stopped')
      if len(self._queue) >= self.max_queue:
        if self.metrics is not None:
          self.metrics.record_rejected()
        raise ServingOverloaded(
            f'queue at capacity ({self.max_queue} requests)')
      now = time.monotonic()
      self._queue.append(_Request(
          ids, fut, now + timeout if timeout is not None else None, now))
      self._cond.notify()
    return fut

  def flush(self) -> None:
    """Force an immediate flush of whatever is queued."""
    with self._cond:
      self._force_flush = True
      self._cond.notify()

  @property
  def depth(self) -> int:
    with self._cond:
      return len(self._queue)

  def stop(self) -> None:
    """Stop the dispatcher; pending requests fail with RuntimeError."""
    with self._cond:
      self._running = False
      pending = list(self._queue)
      self._queue.clear()
      self._cond.notify_all()
    for r in pending:
      r.future.set_exception(RuntimeError('batcher stopped'))
    self._thread.join(timeout=5)

  # -- dispatcher --------------------------------------------------------

  def _expire_locked(self, now: float) -> None:
    """Fail queued requests whose deadline has passed. A deadline
    firing on an all-expired queue is the 'empty flush' case: the
    handler is simply not called."""
    live = deque()
    for r in self._queue:
      if r.deadline is not None and now >= r.deadline:
        if self.metrics is not None:
          self.metrics.record_timeout()
        r.future.set_exception(TimeoutError(
            f'request timed out after {now - r.t_submit:.3f}s in queue'))
      else:
        live.append(r)
    self._queue = live

  def _pop_batch_locked(self) -> List[_Request]:
    """Take requests FIFO while they fit in max_batch_size total ids.
    The head request always ships even if oversized by itself (the
    engine chunks across buckets); later oversized requests wait for
    the next flush rather than starving the current one."""
    batch: List[_Request] = []
    total = 0
    while self._queue:
      r = self._queue[0]
      if batch and total + r.ids.size > self.max_batch_size:
        break
      batch.append(self._queue.popleft())
      total += r.ids.size
      if total >= self.max_batch_size:
        break
    return batch

  def _next_wakeup_locked(self, now: float) -> float:
    """Seconds until the next actionable instant: the oldest request's
    flush deadline or the nearest per-request timeout."""
    t = self._queue[0].t_submit + self.max_wait
    for r in self._queue:
      if r.deadline is not None:
        t = min(t, r.deadline)
    return max(t - now, 0.0)

  def _dispatch_loop(self) -> None:
    while True:
      batch: List[_Request] = []
      with self._cond:
        while self._running:
          now = time.monotonic()
          self._expire_locked(now)
          if not self._queue:
            self._force_flush = False
            self._cond.wait()
            continue
          total = sum(r.ids.size for r in self._queue)
          oldest_wait = now - self._queue[0].t_submit
          if (total >= self.max_batch_size
              or oldest_wait >= self.max_wait or self._force_flush):
            batch = self._pop_batch_locked()
            if not self._queue:
              self._force_flush = False
            break
          self._cond.wait(timeout=self._next_wakeup_locked(now))
        if not self._running:
          return
      if batch:
        self._dispatch(batch)

  def _dispatch(self, batch: List[_Request]) -> None:
    ids = np.concatenate([r.ids for r in batch])
    if self.metrics is not None:
      # an oversized head request ships whole: count its true size as
      # the capacity so the fill ratio stays a [0, 1] utilization
      self.metrics.record_batch(ids.size,
                                max(ids.size, self.max_batch_size))
    try:
      out = self.handler(ids)
      out = np.asarray(out)
      if out.shape[0] != ids.size:
        # a real error, not an assert: under python -O a misaligned
        # handler would silently slice wrong rows to wrong callers
        raise ValueError(
            f'handler returned {out.shape[0]} rows for {ids.size} ids')
    except BaseException as e:  # noqa: BLE001 — failures go to callers
      for r in batch:
        if not r.future.done():
          r.future.set_exception(e)
      return
    lo = 0
    for r in batch:
      hi = lo + r.ids.size
      if not r.future.done():
        r.future.set_result(out[lo:hi])
      lo = hi

"""Online GNN inference: micro-batching, bucketed compilation, and an
embedding cache over the training-side sampler/feature/model stack.

The request path is::

  ServingClient --rpc--> ServingServer --> MicroBatcher --> InferenceEngine
                                                              |-- EmbeddingCache (LRU, versioned)
                                                              |-- NeighborSampler (bucketed jit)
                                                              |-- Feature.gather (hot/cold)
                                                              `-- model forward (jit per bucket)

See docs/serving.md for architecture, bucket tuning, and cache
invalidation semantics.
"""
from .batcher import (  # noqa: F401
    EngineStalledError, MicroBatcher, ServingOverloaded,
)
from .embedding_cache import EmbeddingCache  # noqa: F401
from .engine import InferenceEngine  # noqa: F401
from .fleet import (  # noqa: F401
    AdmissionClass, AdmissionController, FleetOverloaded, FleetRouter,
    FleetShard, FleetUnavailable, ScalePolicy,
)
from .metrics import LatencyHistogram, ServingMetrics  # noqa: F401
from .server import ServingClient, ServingServer  # noqa: F401

__all__ = [
    'MicroBatcher', 'ServingOverloaded', 'EngineStalledError',
    'EmbeddingCache',
    'InferenceEngine', 'LatencyHistogram', 'ServingMetrics',
    'ServingClient', 'ServingServer',
    'AdmissionClass', 'AdmissionController', 'FleetOverloaded',
    'FleetRouter', 'FleetShard', 'FleetUnavailable', 'ScalePolicy',
]

"""Serving observability: QPS, latency percentiles, batch fill, cache
hit-rate.

Built on the :mod:`glt_tpu.utils.profile` primitives — the QPS line is a
ThroughputMeter (whose auto-scaled report keeps sub-million request
rates readable) and wall-clock anchoring uses the same
``time.perf_counter`` convention as profile.Timer. Latency percentiles
come from a fixed-memory log-spaced histogram rather than a sample
reservoir: p99 under heavy traffic must not depend on which requests
survived sampling.
"""
from __future__ import annotations

import math
import threading
import time

from ..utils.profile import ThroughputMeter


class LatencyHistogram:
  """Log-spaced latency histogram: fixed memory, ~5% relative bucket
  error across 10 µs .. ~100 s."""

  #: geometric bucket layout
  _MIN = 1e-5
  _GROWTH = 1.1

  def __init__(self, num_bins: int = 170):
    self._counts = [0] * (num_bins + 2)  # [under | bins | over]
    self._num_bins = num_bins
    self.count = 0
    self.sum = 0.0
    self.max = 0.0

  def _bin(self, seconds: float) -> int:
    if seconds < self._MIN:
      return 0
    b = int(math.log(seconds / self._MIN) / math.log(self._GROWTH)) + 1
    return min(b, self._num_bins + 1)

  def observe(self, seconds: float) -> None:
    self._counts[self._bin(seconds)] += 1
    self.count += 1
    self.sum += seconds
    self.max = max(self.max, seconds)

  def percentile(self, q: float) -> float:
    """q in [0, 100]; returns the upper edge of the bucket holding the
    q-th request (0.0 when empty)."""
    if self.count == 0:
      return 0.0
    target = math.ceil(self.count * q / 100.0)
    seen = 0
    for b, c in enumerate(self._counts):
      seen += c
      if seen >= target:
        if b == 0:
          return self._MIN
        return min(self._MIN * self._GROWTH ** b, self.max)
    return self.max

  @property
  def mean(self) -> float:
    return self.sum / self.count if self.count else 0.0


class ServingMetrics:
  """Aggregated counters shared by the batcher, engine, and server.

  All record_* methods are thread-safe (the batcher dispatcher, RPC
  handler threads, and direct callers all write concurrently).
  """

  def __init__(self):
    self._lock = threading.Lock()
    self.latency = LatencyHistogram()
    self.requests = 0
    self.ids_served = 0
    self.timeouts = 0
    self.rejected = 0
    self.batches = 0
    self.batched_ids = 0
    self.batch_capacity = 0
    # failure/degradation counters (resilience fabric): every degraded
    # answer and every recovery action is accounted here so a chaos run
    # can assert that shed + served == submitted, nothing silently lost
    self.retries = 0          # rpc attempts beyond the first
    self.reconnects = 0       # transparent socket re-establishments
    self.breaker_opens = 0    # CLOSED/HALF_OPEN -> OPEN transitions
    self.shed = 0             # requests dropped BEFORE dispatch (deadline)
    self.stale_serves = 0     # answers served from cache in degraded mode
    self.failovers = 0        # lookups redirected to a replica partition
    # gauges: last-value-wins instruments for state (vs the monotonic
    # counters above) — snapshot version, delta occupancy, compaction
    # latency... The stream ingestor publishes here so serving and
    # streaming share ONE observability surface.
    self._gauges: dict = {}
    self._t0 = time.perf_counter()

  def record_request(self, latency_s: float, num_ids: int = 1) -> None:
    with self._lock:
      self.latency.observe(latency_s)
      self.requests += 1
      self.ids_served += int(num_ids)

  def record_batch(self, num_ids: int, capacity: int) -> None:
    with self._lock:
      self.batches += 1
      self.batched_ids += int(num_ids)
      self.batch_capacity += int(capacity)

  def record_timeout(self) -> None:
    with self._lock:
      self.timeouts += 1

  def record_rejected(self) -> None:
    with self._lock:
      self.rejected += 1

  def record_retry(self, n: int = 1) -> None:
    with self._lock:
      self.retries += int(n)

  def record_reconnect(self) -> None:
    with self._lock:
      self.reconnects += 1

  def record_breaker_open(self) -> None:
    with self._lock:
      self.breaker_opens += 1

  def record_shed(self, n: int = 1) -> None:
    with self._lock:
      self.shed += int(n)

  def record_stale_serve(self, n: int = 1) -> None:
    with self._lock:
      self.stale_serves += int(n)

  def record_failover(self, n: int = 1) -> None:
    with self._lock:
      self.failovers += int(n)

  def set_gauge(self, name: str, value: float) -> None:
    with self._lock:
      self._gauges[str(name)] = float(value)

  def add_gauge(self, name: str, delta: float) -> float:
    """Atomic accumulate into a gauge (one lock hold — a
    get_gauge/set_gauge pair would tear under concurrent writers)."""
    with self._lock:
      v = self._gauges.get(str(name), 0.0) + float(delta)
      self._gauges[str(name)] = v
      return v

  def get_gauge(self, name: str, default: float = 0.0) -> float:
    with self._lock:
      return self._gauges.get(name, default)

  @property
  def elapsed(self) -> float:
    return time.perf_counter() - self._t0

  @property
  def qps(self) -> float:
    return self.requests / max(self.elapsed, 1e-9)

  @property
  def batch_fill_ratio(self) -> float:
    """Mean fraction of the micro-batch capacity actually carrying
    requested ids (1.0 = every flush full)."""
    return self.batched_ids / self.batch_capacity \
        if self.batch_capacity else 0.0

  def snapshot(self, cache=None) -> dict:
    with self._lock:
      out = {
          'requests': self.requests,
          'ids_served': self.ids_served,
          'qps': self.qps,
          'latency_p50_ms': self.latency.percentile(50) * 1e3,
          'latency_p99_ms': self.latency.percentile(99) * 1e3,
          'latency_mean_ms': self.latency.mean * 1e3,
          'latency_max_ms': self.latency.max * 1e3,
          'batches': self.batches,
          'batch_fill_ratio': self.batch_fill_ratio,
          'timeouts': self.timeouts,
          'rejected': self.rejected,
          # resilience counters: snapshotted under the SAME lock hold
          # as everything above — a reader can never see a torn pair
          # (e.g. a shed counted but its retry not yet) across fields
          'retries': self.retries,
          'reconnects': self.reconnects,
          'breaker_opens': self.breaker_opens,
          'shed': self.shed,
          'stale_serves': self.stale_serves,
          'failovers': self.failovers,
          'gauges': dict(self._gauges),
      }
    if cache is not None:
      out['cache'] = cache.stats()
      out['cache_hit_rate'] = out['cache']['hit_rate']
    return out

  def report(self, cache=None) -> str:
    """One-line human summary (ThroughputMeter formats the rate)."""
    snap = self.snapshot(cache)
    meter = ThroughputMeter('req')
    meter.update(self.requests, max(self.elapsed, 1e-9))
    line = (f'{meter.report()} p50={snap["latency_p50_ms"]:.2f}ms '
            f'p99={snap["latency_p99_ms"]:.2f}ms '
            f'fill={snap["batch_fill_ratio"]:.2f}')
    if cache is not None:
      line += f' cache_hit={snap["cache_hit_rate"]:.2f}'
    return line

"""Serving observability: QPS, latency percentiles, batch fill, cache
hit-rate — a back-compat **view over the unified obs registry**.

Historically this module owned its own lock + raw counter fields. It is
now a thin facade over :class:`glt_tpu.obs.MetricsRegistry`: every
counter, gauge and the latency histogram live in a registry (a private
one by default, or a shared one passed in), so serving, stream ingest,
resilience, the distributed fabric and the training loaders all publish
to ONE exposition surface (JSON / Prometheus text) while every existing
call site — ``record_*``, attribute reads, ``snapshot()`` keys — keeps
working unchanged.

The registry's single lock also closes the torn-read bug class for the
derived readings: ``qps`` / ``batch_fill_ratio`` / ``report()`` used to
read ``requests`` / ``elapsed`` / the counters WITHOUT the lock (the
same class of bug fixed for ``EmbeddingCache.hit_rate`` in PR 3); they
now all derive from one locked :meth:`snapshot` cut.
"""
from __future__ import annotations

import time
from typing import Optional

# LatencyHistogram moved to the obs layer (glt_tpu.obs.registry);
# re-exported here for back-compat with existing imports
from ..obs.registry import (  # noqa: F401
    LatencyHistogram, MetricsRegistry,
)
from ..utils.profile import ThroughputMeter

#: attribute name -> registry metric name. The attribute names (and the
#: snapshot() keys derived from them) are frozen public API.
_COUNTERS = {
    'requests': 'serving_requests_total',
    'ids_served': 'serving_ids_served_total',
    'timeouts': 'serving_timeouts_total',
    'rejected': 'serving_rejected_total',
    'batches': 'serving_batches_total',
    'batched_ids': 'serving_batched_ids_total',
    'batch_capacity': 'serving_batch_capacity_total',
    # failure/degradation counters (resilience fabric): every degraded
    # answer and every recovery action is accounted here so a chaos run
    # can assert that shed + served == submitted, nothing silently lost
    'retries': 'rpc_retries_total',
    'reconnects': 'rpc_reconnects_total',
    'breaker_opens': 'rpc_breaker_opens_total',
    'shed': 'serving_shed_total',
    'stale_serves': 'serving_stale_serves_total',
    'failovers': 'rpc_failovers_total',
}

_LATENCY = 'serving_latency_seconds'


class ServingMetrics:
  """Aggregated counters shared by the batcher, engine, and server.

  All record_* methods are thread-safe (the batcher dispatcher, RPC
  handler threads, and direct callers all write concurrently).

  Args:
    registry: publish into this :class:`MetricsRegistry` instead of a
      fresh private one — pass :func:`glt_tpu.obs.get_registry` to land
      these counters on the process-global exposition surface next to
      the pipeline stage timings.
    name: instance label attached to every instrument when sharing a
      registry (two ServingMetrics on one registry must not collide);
      empty = unlabeled.
  """

  def __init__(self, registry: Optional[MetricsRegistry] = None,
               name: str = ''):
    self.registry = registry if registry is not None \
        else MetricsRegistry()
    self._labels = {'view': str(name)} if name else {}
    self._c = {attr: self.registry.counter(metric, **self._labels)
               for attr, metric in _COUNTERS.items()}
    self.latency = self.registry.histogram(_LATENCY, **self._labels)
    # gauges: last-value-wins instruments for state (vs the monotonic
    # counters above) — snapshot version, delta occupancy, compaction
    # latency... The stream ingestor publishes here so serving and
    # streaming share ONE observability surface.
    self._gauge_names: set = set()
    self._t0 = time.perf_counter()

  # -- writers -----------------------------------------------------------

  def record_request(self, latency_s: float, num_ids: int = 1) -> None:
    with self.registry._lock:  # one atomic group, RLock-reentrant
      self.latency.observe(latency_s)
      self._c['requests'].inc()
      self._c['ids_served'].inc(int(num_ids))

  def record_batch(self, num_ids: int, capacity: int) -> None:
    with self.registry._lock:
      self._c['batches'].inc()
      self._c['batched_ids'].inc(int(num_ids))
      self._c['batch_capacity'].inc(int(capacity))

  def record_timeout(self) -> None:
    self._c['timeouts'].inc()

  def record_rejected(self) -> None:
    self._c['rejected'].inc()

  def record_retry(self, n: int = 1) -> None:
    self._c['retries'].inc(int(n))

  def record_reconnect(self) -> None:
    self._c['reconnects'].inc()

  def record_breaker_open(self) -> None:
    self._c['breaker_opens'].inc()

  def record_shed(self, n: int = 1) -> None:
    self._c['shed'].inc(int(n))

  def record_stale_serve(self, n: int = 1) -> None:
    self._c['stale_serves'].inc(int(n))

  def record_failover(self, n: int = 1) -> None:
    self._c['failovers'].inc(int(n))

  def set_gauge(self, name: str, value: float) -> None:
    with self.registry._lock:  # guards the name-set against snapshot()
      self._gauge_names.add(str(name))
      self.registry.set(str(name), float(value), **self._labels)

  def add_gauge(self, name: str, delta: float) -> float:
    """Atomic accumulate into a gauge (one lock hold — a
    get_gauge/set_gauge pair would tear under concurrent writers)."""
    with self.registry._lock:
      self._gauge_names.add(str(name))
      return self.registry.add(str(name), float(delta), **self._labels)

  def get_gauge(self, name: str, default: float = 0.0) -> float:
    if name not in self._gauge_names:
      return default
    return self.registry.gauge(str(name), **self._labels).value

  # -- readers -----------------------------------------------------------

  @property
  def elapsed(self) -> float:
    return time.perf_counter() - self._t0

  @property
  def qps(self) -> float:
    # ONE locked cut of exactly the fields involved (the historical
    # implementation read `requests` without the lock — the hit_rate
    # torn-read bug class); cheaper than a full snapshot() for pollers
    with self.registry._lock:
      requests = self._c['requests']._value
      elapsed = self.elapsed
    return requests / max(elapsed, 1e-9)

  @property
  def batch_fill_ratio(self) -> float:
    """Mean fraction of the micro-batch capacity actually carrying
    requested ids (1.0 = every flush full)."""
    with self.registry._lock:
      ids = self._c['batched_ids']._value
      cap = self._c['batch_capacity']._value
    return ids / cap if cap else 0.0

  def snapshot(self, cache=None) -> dict:
    out, _ = self._snapshot(cache)
    return out

  def _snapshot(self, cache=None):
    """(snapshot dict, elapsed) from ONE locked cut — ``elapsed`` rides
    alongside (not as a key: the snapshot key set is frozen API) so
    ``report()`` never pairs counters with a later clock read."""
    with self.registry._lock:
      elapsed = self.elapsed
      c = {attr: int(ctr._value) for attr, ctr in self._c.items()}
      # the registry RLock is held: histogram reads re-enter it
      lat = self.latency
      out = {
          'requests': c['requests'],
          'ids_served': c['ids_served'],
          'qps': c['requests'] / max(elapsed, 1e-9),
          'latency_p50_ms': lat.percentile(50) * 1e3,
          'latency_p99_ms': lat.percentile(99) * 1e3,
          'latency_mean_ms': lat.mean * 1e3,
          'latency_max_ms': lat.max * 1e3,
          'batches': c['batches'],
          'batch_fill_ratio': (c['batched_ids'] / c['batch_capacity']
                               if c['batch_capacity'] else 0.0),
          'timeouts': c['timeouts'],
          'rejected': c['rejected'],
          # resilience counters: snapshotted under the SAME lock hold
          # as everything above — a reader can never see a torn pair
          # (e.g. a shed counted but its retry not yet) across fields
          'retries': c['retries'],
          'reconnects': c['reconnects'],
          'breaker_opens': c['breaker_opens'],
          'shed': c['shed'],
          'stale_serves': c['stale_serves'],
          'failovers': c['failovers'],
          'gauges': {
              g: self.registry.gauge(g, **self._labels)._value
              for g in sorted(self._gauge_names)
          },
      }
    if cache is not None:
      out['cache'] = cache.stats()
      out['cache_hit_rate'] = out['cache']['hit_rate']
    return out, elapsed

  def report(self, cache=None) -> str:
    """One-line human summary (ThroughputMeter formats the rate) —
    every field derives from one locked snapshot cut."""
    snap, elapsed = self._snapshot(cache)
    meter = ThroughputMeter('req')
    meter.update(snap['requests'], max(elapsed, 1e-9))
    line = (f'{meter.report()} p50={snap["latency_p50_ms"]:.2f}ms '
            f'p99={snap["latency_p99_ms"]:.2f}ms '
            f'fill={snap["batch_fill_ratio"]:.2f}')
    if cache is not None:
      line += f' cache_hit={snap["cache_hit_rate"]:.2f}'
    return line


def _make_counter_property(attr: str):
  def fget(self) -> int:
    return int(self._c[attr].value)
  fget.__name__ = attr
  fget.__doc__ = f'Back-compat read of the {_COUNTERS[attr]} counter.'
  return property(fget)


for _attr in _COUNTERS:
  setattr(ServingMetrics, _attr, _make_counter_property(_attr))
del _attr

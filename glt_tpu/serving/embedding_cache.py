"""LRU embedding cache for the online inference engine.

Entries are keyed by ``(node_id, model_version)`` so a parameter reload
(version bump) instantly stops serving stale vectors without an O(N)
sweep: old-version entries simply stop hitting and age out of the LRU.
Explicit invalidation hooks cover the other staleness source — feature
or graph updates for specific nodes (``invalidate(ids=...)``) and bulk
flushes (``invalidate()``); registered listeners let callers fan the
event out (e.g. to replicas or metrics).

The reference has no inference cache; the design follows its feature
hot-cache philosophy (data/feature.py split_ratio): skewed access means
a small resident set absorbs most traffic.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Iterable, List, Optional

import numpy as np


class EmbeddingCache:
  """Thread-safe LRU of ``(node_id, model_version) -> np.ndarray`` rows.

  Args:
    capacity: max resident entries; 0 disables caching entirely (every
      lookup misses, inserts are dropped) — useful for benchmarking the
      uncached path.
  """

  def __init__(self, capacity: int = 100_000):
    self.capacity = int(capacity)
    self._data: 'OrderedDict[tuple, np.ndarray]' = OrderedDict()
    # live-entry count per version: keeps the id-probe set (invalidate
    # by ids probes (id, v) per live version) from growing with every
    # version ever served on a long-running server
    self._version_counts: dict = {}
    self._lock = threading.Lock()
    self._listeners: List[Callable] = []
    self.hits = 0
    self.misses = 0
    self.evictions = 0
    self.invalidations = 0

  def __len__(self) -> int:
    with self._lock:
      return len(self._data)

  @property
  def hit_rate(self) -> float:
    # snapshot both counters under the lock: reading them unlocked
    # against a concurrent lookup() can pair a new `hits` with a stale
    # `misses` (or vice versa) — a torn, even >1.0, ratio
    with self._lock:
      hits, misses = self.hits, self.misses
    total = hits + misses
    return hits / total if total else 0.0

  # -- lookup / insert ---------------------------------------------------

  def lookup(self, ids: Iterable[int], version: int) -> dict:
    """Returns {node_id: row} for the cached subset; counts a hit or
    miss per requested id (duplicates count once per occurrence, the
    traffic-weighted definition a serving hit-rate wants)."""
    out = {}
    with self._lock:
      for i in ids:
        key = (int(i), int(version))
        row = self._data.get(key)
        if row is None:
          self.misses += 1
        else:
          self._data.move_to_end(key)
          self.hits += 1
          out[int(i)] = row
    return out

  def lookup_stale(self, ids: Iterable[int]) -> dict:
    """Degraded-mode read: {node_id: row} probing EVERY live version,
    newest first — the stale-serve tier answers from whatever the cache
    still holds while the engine circuit is open. Counts neither hits
    nor misses (a disaster-mode read must not skew the steady-state
    hit-rate the capacity tuning watches) and does not touch LRU order
    (stale reads must not keep stale entries artificially hot)."""
    out = {}
    with self._lock:
      versions = sorted(self._version_counts, reverse=True)
      for i in ids:
        for v in versions:
          row = self._data.get((int(i), v))
          if row is not None:
            out[int(i)] = row
            break
    return out

  def insert(self, ids: Iterable[int], values: np.ndarray,
             version: int) -> None:
    if self.capacity <= 0:
      return
    with self._lock:
      for i, row in zip(ids, values):
        key = (int(i), int(version))
        if key not in self._data:
          self._version_counts[int(version)] = \
              self._version_counts.get(int(version), 0) + 1
        # copy: a row view into the engine's padded [bucket, D] output
        # would pin the WHOLE bucket array for as long as the entry
        # lives (bucket× memory amplification under LRU churn)
        self._data[key] = np.array(row, copy=True)
        self._data.move_to_end(key)
      while len(self._data) > self.capacity:
        (_, v), _ = self._data.popitem(last=False)
        self._drop_version_entry(v)
        self.evictions += 1

  def _drop_version_entry(self, version: int) -> None:
    n = self._version_counts.get(version, 0) - 1
    if n <= 0:
      self._version_counts.pop(version, None)
    else:
      self._version_counts[version] = n

  # -- invalidation hooks ------------------------------------------------

  def add_invalidation_listener(self, fn: Callable) -> None:
    """``fn(ids, version)`` is called after every invalidate (ids may
    be None for a bulk flush). Listeners run synchronously inside the
    caller's invalidation path — when that caller is the engine (whose
    ``invalidate`` holds the non-reentrant engine lock), a listener
    must NOT call back into the same engine; hand off to another
    thread for cascading invalidations."""
    self._listeners.append(fn)

  def invalidate(self, ids: Optional[Iterable[int]] = None,
                 version: Optional[int] = None) -> int:
    """Drop entries. ``ids`` None = all nodes; ``version`` None = all
    versions. Returns the number of entries dropped. The per-node form
    probes (id, version) keys directly — O(len(ids) x live versions),
    never a scan of the whole cache (feature-update hooks fire this on
    the serving path)."""
    with self._lock:
      if ids is None and version is None:
        dropped = len(self._data)
        self._data.clear()
        self._version_counts.clear()
      elif ids is None:
        keys = [k for k in self._data if k[1] == int(version)]
        for k in keys:
          del self._data[k]
        self._version_counts.pop(int(version), None)
        dropped = len(keys)
      else:
        versions = ([int(version)] if version is not None
                    else list(self._version_counts))
        dropped = 0
        for i in ids:
          for v in versions:
            if self._data.pop((int(i), v), None) is not None:
              self._drop_version_entry(v)
              dropped += 1
      self.invalidations += dropped
    for fn in self._listeners:
      fn(ids, version)
    return dropped

  def reset_stats(self) -> None:
    with self._lock:
      self.hits = self.misses = self.evictions = self.invalidations = 0

  def stats(self) -> dict:
    with self._lock:
      total = self.hits + self.misses
      return {
          'size': len(self._data), 'capacity': self.capacity,
          'hits': self.hits, 'misses': self.misses,
          # computed from the counters already under THIS lock hold —
          # self.hit_rate would deadlock (non-reentrant lock) and a
          # re-read could tear against a concurrent lookup()
          'hit_rate': self.hits / total if total else 0.0,
          'evictions': self.evictions,
          'invalidations': self.invalidations,
      }

"""Bucketed online inference engine: k-hop sample -> feature gather ->
model forward under pre-compiled padded shapes.

XLA compiles one program per input shape, so naive request-sized
execution recompiles the whole sample+forward pipeline on every new
request size — seconds of latency per distinct size. The engine instead
serves every request through a small set of **shape buckets**: a request
for ``n`` embeddings runs in the smallest bucket ``B >= n``, padded, and
``warmup()`` compiles every bucket up front so steady-state serving
never traces again. The multi-hop sampler already compiles one program
per seed shape (sampler/neighbor_sampler.py); buckets are exactly its
cache keys, and the forward is jitted per bucket here with a trace
counter that tests (and ``compile_stats``) can assert against.

Results flow through the LRU :class:`~glt_tpu.serving.embedding_cache.
EmbeddingCache` keyed ``(node_id, model_version)``: a request whose ids
are all cached skips sampling and the forward entirely, and partial
hits shrink the computed batch to the missing unique ids.

The engine is intentionally NOT thread-safe per call (``infer`` takes an
internal lock): the donated dedup tables inside the sampler's jitted
programs make it non-reentrant. Put the :class:`MicroBatcher` in front
of it — that is also where cross-request batching happens.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

import jax
import numpy as np

from ..data import Dataset
from ..data.feature import gather_features
from ..loader.transform import to_batch, to_hetero_batch
from ..obs import get_tracer
from ..sampler import NeighborSampler
from ..sampler.base import NodeSamplerInput
from ..utils import as_numpy
from .embedding_cache import EmbeddingCache


class InferenceEngine:
  """Online embedding/logit server over a trained GNN.

  Args:
    data: Dataset (graph + node features; labels unused).
    model: flax module whose ``apply(params, batch)`` returns a
      ``[batch_size, D]`` array for the seed rows (GraphSAGE/RGNN
      style). ``apply_fn`` overrides this contract if needed.
    params: trained parameters (e.g. restored via
      utils.checkpoint.restore_checkpoint).
    num_neighbors: serving fanout per hop, e.g. ``[15, 10, 5]``.
    buckets: padded seed-batch sizes to pre-compile, ascending. A
      request larger than the biggest bucket is served in chunks of it.
    cache: an EmbeddingCache, or None to build one of
      ``cache_capacity`` entries (0 disables caching).
    model_version: version tag for cache keys; ``set_params`` bumps it.
    seed: sampler RNG seed (serving samples fresh neighborhoods per
      request, matching the reference's inference-time sampling).
    sampler: inject a pre-built sampler instead of the default
      NeighborSampler over ``data.graph`` — how live-update serving
      plugs in a :class:`~glt_tpu.stream.StreamSampler` (whose jitted
      programs survive snapshot swaps; see ``update_snapshot``).
    row_gather: optional (table [N, D], rows [B]) -> [B, D] override
      for the serving feature gather (resolve_row_gather seam — tests
      inject the interpret-mode Pallas kernel). Applied at the gather
      CALL SITE, so it keeps serving after ``update_snapshot`` swaps
      in a new stream Feature.
    input_type: REQUIRED for a hetero ``data.graph`` (dict): the seed
      node type requests address. Buckets pad the seed-type batch; the
      pipeline samples every edge type (one fused multi-edge-type
      kernel invocation per hop on the ``pallas_fused`` engine) and
      the forward consumes a ``HeteroBatch`` — RGAT-style serving with
      the same zero-steady-state-recompile contract as homo.
  """

  def __init__(self, data: Dataset, model, params,
               num_neighbors: Sequence[int],
               buckets: Sequence[int] = (8, 64, 256),
               cache: Optional[EmbeddingCache] = None,
               cache_capacity: int = 100_000,
               model_version: int = 0,
               seed: Optional[int] = 0,
               apply_fn: Optional[Callable] = None,
               with_edge: bool = False,
               sampler=None,
               row_gather=None,
               input_type=None):
    self._hetero = isinstance(data.graph, dict)
    if self._hetero:
      # hetero serving: requests are seed-type node ids; the bucketed
      # pipeline samples the multi-edge-type neighborhood (one fused
      # program per bucket — on the pallas_fused engine each hop is one
      # multi-edge-type kernel invocation) and the forward consumes a
      # HeteroBatch. Bucket grid stays 1-D: requests seed ONE type.
      assert input_type is not None, (
          'hetero serving needs input_type (the seed node type '
          'requests address)')
    self.input_type = input_type
    self.data = data
    self.model = model
    self.params = params
    self.buckets = tuple(sorted({int(b) for b in buckets}))
    assert self.buckets and self.buckets[0] > 0
    self.model_version = int(model_version)
    self.cache = cache if cache is not None \
        else EmbeddingCache(cache_capacity)
    self.sampler = sampler if sampler is not None else NeighborSampler(
        data.graph,
        dict(num_neighbors) if isinstance(num_neighbors, dict)
        else list(num_neighbors),
        edge_dir=data.edge_dir, with_edge=with_edge, seed=seed)
    self.row_gather = row_gather
    self._apply_fn = apply_fn or (
        lambda params, batch: self.model.apply(params, batch))
    self._fwd = {}            # bucket -> jitted forward
    self._trace_counts = {}   # bucket -> times the forward was traced
    self.forward_calls = 0    # executed bucket runs (not traces)
    self._out_dim: Optional[int] = None
    self._warmed = False
    self._snapshot_version = 0
    self._lock = threading.Lock()

  # -- compilation -------------------------------------------------------

  def _make_forward(self, bucket: int):
    def fwd(params, batch):
      # trace-time side effect: executions never touch this counter, so
      # steady-state assertions can demand it stays flat
      self._trace_counts[bucket] = self._trace_counts.get(bucket, 0) + 1
      from ..obs.perf import count_compile
      count_compile('serve.forward')  # process-wide compiles_total{fn}
      return self._apply_fn(params, batch)
    return jax.jit(fwd)

  def _forward(self, bucket: int):
    if bucket not in self._fwd:
      self._fwd[bucket] = self._make_forward(bucket)
    return self._fwd[bucket]

  def warmup(self, publish_costs: Optional[bool] = None) -> dict:
    """Compile every bucket's sample+gather+forward pipeline once with
    dummy seeds. Serving before warmup works but pays compilation on
    first use of each bucket.

    ``publish_costs`` (default: the ``GLT_OBS_XLA_COST`` knob, off)
    additionally AOT-lowers each bucket's forward and publishes its
    XLA cost analysis as ``xla_flops{fn="serve.forward[b<bucket>]"}``
    etc. — NOTE this is one extra trace per bucket (the
    ``forward_traces`` counters each read 2 after warmup instead of
    1), which is why it is opt-in rather than ambient."""
    if publish_costs is None:
      from ..obs.perf import xla_cost_enabled
      publish_costs = xla_cost_enabled()
    with self._lock:
      for b in self.buckets:
        self._run_bucket(np.zeros(b, np.int64), b, b)
      if publish_costs:
        from ..obs.perf import instrument_compiled
        for b in self.buckets:
          batch = self.make_batch(np.zeros(b, np.int64), b, b)
          instrument_compiled(f'serve.forward[b{b}]', self._forward(b),
                              self.params, batch)
      self._warmed = True
      # warmup never inserts into the cache (only infer does), so only
      # the stats need resetting — a caller-supplied pre-populated
      # cache must survive warmup intact
      self.cache.reset_stats()
      self.forward_calls = 0
    return self.compile_stats()

  def compile_stats(self) -> dict:
    """Compilation/exec counters for the zero-recompile guarantee.

    Deliberately LOCK-FREE: infer() holds the engine lock across the
    device forward, so a wedged device would turn every stats scrape
    into a hang at exactly the moment operators need it (the stall
    path the watchdog exists for). The counters are GIL-atomic Python
    ints; a read racing an increment is off by at most one."""
    return {
        'forward_traces': dict(self._trace_counts),
        'sampler_compiled_fns': self.sampler.num_compiled_fns,
        'forward_calls': self.forward_calls,  # gltlint: disable=GLT002
    }

  @property
  def output_dim(self) -> Optional[int]:
    return self._out_dim

  @property
  def num_nodes(self) -> int:
    """Id-space bound for request validation: the seed TYPE's node
    count on a hetero graph (requests address one type)."""
    if self._hetero:
      return self.sampler._node_counts[self.input_type]
    return self.data.graph.num_nodes

  def validate_ids(self, ids: np.ndarray) -> None:
    """Reject out-of-range node ids: past the request boundary they
    would be silently clamped by the gather paths — a wrong-but-valid-
    looking embedding, cached under the bogus id forever."""
    if ids.size and (ids.min() < 0 or ids.max() >= self.num_nodes):
      bad = ids[(ids < 0) | (ids >= self.num_nodes)][:8]
      raise ValueError(
          f'node ids out of range [0, {self.num_nodes}): {bad.tolist()}')

  # -- serving -----------------------------------------------------------

  def bucket_for(self, n: int) -> int:
    for b in self.buckets:
      if n <= b:
        return b
    return self.buckets[-1]

  def make_batch(self, seeds: np.ndarray, n_valid: int, bucket: int):
    """Sample + gather a bucket-shaped Batch exactly as serving runs
    it (public so param init / benchmarks build batches through the
    same pipeline instead of re-rolling it). Hetero graphs produce a
    :class:`~glt_tpu.loader.transform.HeteroBatch` (per-type feature
    gather over the sampled node dict)."""
    if self._hetero:
      out = self.sampler.sample_from_nodes(
          NodeSamplerInput(seeds, self.input_type), n_valid=n_valid)
      # featureless node types are legal (node_loader tolerates partial
      # feature dicts the same way): gather only the types with a store
      feats = (self.data.node_features
               if isinstance(self.data.node_features, dict) else {})
      x_dict = {
          t: gather_features(feats[t], n, row_gather=self.row_gather)
          for t, n in out.node.items() if feats.get(t) is not None}
      return to_hetero_batch(out, x_dict=x_dict,
                             batch_size=bucket).replace(metadata=None)
    out = self.sampler.sample_from_nodes(seeds, n_valid=n_valid)
    # a pallas_fused sampler built with fused_feature= hands the rows
    # back pre-gathered (in-walk); gather_features passes them through
    x = gather_features(self.data.get_node_feature(), out.node,
                        row_gather=self.row_gather,
                        fused=(out.metadata or {}).get('node_feats'))
    # metadata carries per-call arrays (seed labels) — stripping it
    # keeps the forward's pytree signature identical across calls
    return to_batch(out, x=x, batch_size=bucket).replace(metadata=None)

  def init_params(self, rng_key):
    """Initialize (and install) model params against a bucket-shaped
    batch — for serving fresh/benchmark weights without a training
    loop."""
    b = self.buckets[0]
    batch = self.make_batch(np.zeros(b, np.int64), b, b)
    params = self.model.init(rng_key, batch)
    with self._lock:
      self.params = params
    return params

  def _run_bucket(self, seeds: np.ndarray, n_valid: int,
                  bucket: int) -> np.ndarray:
    """One padded pipeline pass; returns rows [:n_valid]."""
    padded = seeds
    if padded.shape[0] < bucket:
      padded = np.concatenate(
          [padded, np.full(bucket - padded.shape[0], padded[0] if
                           padded.size else 0, padded.dtype)])
    tracer = get_tracer()
    # sample.multihop / gather.features spans open inside make_batch;
    # the bucket span parents them and (np.asarray below is a full
    # device sync) carries the true end-to-end stage time
    with tracer.span('serve.bucket', bucket=bucket,
                     n_valid=int(n_valid)):
      batch = self.make_batch(padded, n_valid, bucket)
      with tracer.span('serve.forward', bucket=bucket):
        emb = self._forward(bucket)(self.params, batch)
        self.forward_calls += 1
        rows = np.asarray(emb)[:n_valid]
    if self._out_dim is None:
      self._out_dim = int(rows.shape[1])
    return rows

  def infer(self, ids) -> np.ndarray:
    """Embeddings/logits for ``ids`` (duplicates allowed), aligned with
    the input order: cache hits served directly, the missing unique ids
    computed through the smallest fitting bucket (chunked by the
    largest bucket when needed) and inserted back into the cache."""
    ids_np = as_numpy(ids).astype(np.int64).reshape(-1)
    if ids_np.size == 0:
      return np.zeros((0, self._out_dim or 0), np.float32)
    with self._lock:
      version = self.model_version
      local = self.cache.lookup(ids_np, version)
      missing = np.unique(ids_np[~np.isin(
          ids_np, np.fromiter(local, np.int64, len(local)))]) \
          if local else np.unique(ids_np)
      lo = 0
      while lo < missing.size:
        chunk = missing[lo:lo + self.buckets[-1]]
        lo += chunk.size
        bucket = self.bucket_for(chunk.size)
        rows = self._run_bucket(chunk, chunk.size, bucket)
        self.cache.insert(chunk, rows, version)
        for i, row in zip(chunk, rows):
          local[int(i)] = row
      return np.stack([local[int(i)] for i in ids_np])

  def stale_serve(self, ids):
    """Degradation tier: answer from the versioned EmbeddingCache ONLY
    (any live version, newest first), zero-filling true misses —
    never touches the sampler or the forward, and deliberately does
    NOT take the engine lock (the lock is exactly what a wedged infer
    is sitting on). Returns ``(rows [n, D], cached_mask [n])`` so the
    caller can count stale serves vs zero-fills.

    Raises RuntimeError when the output width is unknown (the engine
    never completed a forward) — there is nothing to degrade TO."""
    ids_np = as_numpy(ids).astype(np.int64).reshape(-1)
    found = self.cache.lookup_stale(ids_np)
    dim = self._out_dim
    if dim is None and found:
      dim = int(next(iter(found.values())).shape[0])
    if dim is None:
      raise RuntimeError(
          'stale_serve before any completed forward: output dim '
          'unknown and the cache is empty')
    out = np.zeros((ids_np.size, dim), np.float32)
    mask = np.zeros(ids_np.size, bool)
    for k, i in enumerate(ids_np.tolist()):
      row = found.get(int(i))
      if row is not None:
        out[k] = row
        mask[k] = True
    return out, mask

  # -- invalidation hooks ------------------------------------------------

  def set_params(self, params, bump_version: bool = True) -> int:
    """Hot-swap model parameters. With ``bump_version`` (default) the
    cache version advances so stale embeddings stop hitting instantly;
    the jitted programs are shape-stable and need no recompile."""
    with self._lock:
      self.params = params
      if bump_version:
        self.model_version += 1
      # return the version from THIS swap's lock hold: reading it
      # after release could observe a concurrent swap's bump (GLT002)
      return self.model_version

  def invalidate(self, ids=None, version=None) -> int:
    """Cache invalidation serialized against in-flight infer (the
    engine lock): without it, invalidating ids an infer is currently
    computing would drop nothing and the stale rows would be inserted
    right after."""
    with self._lock:
      if ids is not None:
        ids = as_numpy(ids).reshape(-1).tolist()
      return self.cache.invalidate(ids, version)

  def invalidate_nodes(self, ids) -> int:
    """Feature/graph update hook: drop cached embeddings of ``ids``
    across all versions."""
    return self.invalidate(ids=ids)

  @property
  def snapshot_version(self) -> int:
    """The stream-snapshot version this engine last swapped onto (0 =
    the construction-time graph). Read under the engine lock so a
    caller never observes the version of a swap whose invalidation has
    not landed yet — the consistency token the fleet router threads
    through `apply_delta` propagation."""
    with self._lock:
      return self._snapshot_version

  def update_snapshot(self, snapshot, touched_ids=None,
                      expand_in_neighbors: bool = False,
                      version: Optional[int] = None) -> int:
    """Swap serving onto a new stream snapshot (glt_tpu.stream).

    Under the engine lock (serialized against in-flight infer): install
    the snapshot's Feature as the gather source, then fan the touched
    node ids into :meth:`EmbeddingCache.invalidate` so no embedding
    computed against the old graph/features is ever served again. An
    in-flight request that sampled the old snapshot finishes on it
    (RCU) and any stale rows it caches are swept here, because the
    invalidation runs strictly after the swap.

    Args:
      snapshot: a :class:`glt_tpu.stream.Snapshot`; its ``feature``
        (when not None) replaces ``data.node_features``.
      touched_ids: node ids whose neighborhoods/features changed; None
        invalidates the whole cache (conservative fallback).
      expand_in_neighbors: additionally invalidate the reverse-layout
        1-hop neighborhood of the touched ids (``Snapshot.
        expand_affected`` via the CSC view for a CSR base) — the nodes
        whose cached embeddings *aggregate over* a touched node.
      version: the snapshot's version token (``Snapshot.version`` /
        the ingestor flush info ``'version'``); None auto-increments.
        Stamped in the SAME lock hold as the swap+invalidation, so
        :attr:`snapshot_version` == v implies version-v features are
        installed AND every pre-v cached row of a touched id is gone.

    Returns the number of cache entries dropped.
    """
    if self._hetero:
      # the stream/snapshot machinery is homogeneous (StreamSampler,
      # Snapshot.feature are single-type); silently installing a homo
      # Feature over the per-type dict would serve featureless hetero
      # batches from then on — refuse loudly instead
      raise NotImplementedError(
          'update_snapshot is homogeneous-only: hetero serving has no '
          'stream snapshot lineage yet (invalidate_nodes/invalidate '
          'remain available)')
    with self._lock:
      if snapshot.feature is not None:
        self.data.node_features = snapshot.feature
      self._snapshot_version = int(version) if version is not None \
          else self._snapshot_version + 1
      if touched_ids is None:
        return self.cache.invalidate()
      ids = as_numpy(touched_ids).astype(np.int64).reshape(-1)
      if expand_in_neighbors and ids.size:
        ids = snapshot.expand_affected(ids)
      ids = ids[(ids >= 0) & (ids < self.num_nodes)]
      if ids.size == 0:
        return 0
      return self.cache.invalidate(ids=ids.tolist())

"""ShmChannel — cross-process channel over the native shm ring buffer.

Reference: graphlearn_torch/python/channel/shm_channel.py:24-53 (pywrap
SampleQueue over csrc/shm_queue.cc). ``pin_memory`` has no TPU meaning
(device transfer happens via device_put at the consumer); accepted for
API parity and ignored.
"""
from __future__ import annotations

from .base import ChannelBase, SampleMessage, pack_message, unpack_message
from .shm import ShmQueue


class ShmChannel(ChannelBase):
  def __init__(self, capacity_bytes: int = 128 * 1024 * 1024,
               pin_memory: bool = False, shm_queue: ShmQueue = None):
    self._queue = shm_queue or ShmQueue(capacity_bytes)
    del pin_memory  # API parity only

  def send(self, msg: SampleMessage, timeout_ms: int = 60_000) -> None:
    self._queue.enqueue(pack_message(msg), timeout_ms)

  def recv(self, timeout_ms: int = 60_000) -> SampleMessage:
    return unpack_message(self._queue.dequeue(timeout_ms))

  def empty(self) -> bool:
    return self._queue.empty()

  def close(self) -> None:
    self._queue.close()

  def __reduce__(self):
    return (ShmChannel, (0, False, self._queue))

"""RemoteReceivingChannel — pull-prefetching consumer over remote fetchers.

Reference: graphlearn_torch/python/channel/remote_channel.py:24-131: pulls
``prefetch_size`` messages per server concurrently and tracks per-server
end-of-epoch markers. The fetcher abstraction here is any callable
returning a SampleMessage or raising StopIteration at epoch end (the
server-client mode wires it to DistServer.fetch_one_sampled_message).
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, List

from .base import ChannelBase, SampleMessage
from .shm import QueueTimeoutError


class RemoteReceivingChannel(ChannelBase):
  def __init__(self, fetch_fns: List[Callable[[], SampleMessage]],
               prefetch_size: int = 4):
    self.fetch_fns = fetch_fns
    self.prefetch_size = max(int(prefetch_size), 1)
    # prefetch_size bounds the per-server readahead: one puller thread
    # per server, and the shared buffer holds at most prefetch_size
    # messages per server before pullers block (the reference's
    # pull-prefetch window, remote_channel.py:76-131)
    self._out: 'queue.Queue' = queue.Queue(
        maxsize=self.prefetch_size * max(len(fetch_fns), 1))
    self._threads: List[threading.Thread] = []
    self._live = 0
    self._lock = threading.Lock()
    self._started = False

  def reset(self) -> None:
    """Start a new epoch of pulling (reference per-epoch re-arm)."""
    self._started = True
    with self._lock:
      self._live = len(self.fetch_fns)
    self._threads = []
    for fn in self.fetch_fns:
      t = threading.Thread(target=self._pull_loop, args=(fn,),
                           daemon=True)
      t.start()
      self._threads.append(t)

  def _pull_loop(self, fn) -> None:
    while True:
      try:
        msg = fn()
      except StopIteration:
        break
      except Exception as e:  # surface errors to the consumer
        self._out.put(e)
        break
      self._out.put(msg)
    with self._lock:
      self._live -= 1
      if self._live == 0:
        self._out.put(StopIteration())

  def send(self, msg: SampleMessage) -> None:
    raise RuntimeError('RemoteReceivingChannel is receive-only')

  def recv(self, timeout_ms: int = 60_000) -> SampleMessage:
    if not self._started:
      self.reset()
    try:
      item = self._out.get(timeout=timeout_ms / 1000)
    except queue.Empty as e:
      raise QueueTimeoutError('remote recv timed out') from e
    if isinstance(item, StopIteration):
      self._started = False
      raise StopIteration
    if isinstance(item, Exception):
      raise item
    return item

  def empty(self) -> bool:
    return self._out.empty()

"""RemoteReceivingChannel — pull-prefetching consumer over remote fetchers.

Reference: graphlearn_torch/python/channel/remote_channel.py:24-131: pulls
``prefetch_size`` messages per server concurrently and tracks per-server
end-of-epoch markers. The fetcher abstraction here is any callable
returning a SampleMessage or raising StopIteration at epoch end (the
server-client mode wires it to DistServer.fetch_one_sampled_message).

Design: one puller thread and one bounded queue *per server*, so
``prefetch_size`` bounds each server's readahead individually (a fast
server cannot fill a shared window and starve the others), and the
consumer round-robins across server queues. Each ``reset()`` starts a
new epoch: prior pullers are signalled to stop and their queues dropped,
so a partially-consumed epoch can never leak messages into the next one.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

from .base import ChannelBase, SampleMessage
from .shm import QueueTimeoutError


class _EndOfServer:
  """Sentinel a puller enqueues when its server's epoch is exhausted."""


class _Puller:
  """One server's puller thread + its bounded readahead queue. ``avail``
  is the channel-wide condition notified on every put, so the consumer
  wakes immediately on any server's arrival instead of polling."""

  def __init__(self, fn: Callable[[], SampleMessage], bound: int,
               avail: threading.Condition):
    self.q: 'queue.Queue' = queue.Queue(maxsize=bound)
    self.avail = avail
    self.stop = threading.Event()
    self.done = False  # consumer-side: sentinel seen
    self.thread = threading.Thread(target=self._loop, args=(fn,),
                                   daemon=True)
    self.thread.start()

  def _loop(self, fn) -> None:
    while not self.stop.is_set():
      try:
        item = fn()
      except StopIteration:
        item = _EndOfServer()
      except Exception as e:  # surface errors to the consumer
        item = e
      # Bounded put that stays responsive to the stop signal; on stop
      # the item is dropped (the epoch is being abandoned anyway).
      while not self.stop.is_set():
        try:
          self.q.put(item, timeout=0.1)
          with self.avail:
            self.avail.notify_all()
          break
        except queue.Full:
          continue
      if isinstance(item, (_EndOfServer, Exception)):
        return


class RemoteReceivingChannel(ChannelBase):
  def __init__(self, fetch_fns: List[Callable[[], SampleMessage]],
               prefetch_size: int = 4):
    self.fetch_fns = fetch_fns
    self.prefetch_size = max(int(prefetch_size), 1)
    self._pullers: List[_Puller] = []
    self._avail = threading.Condition()
    self._rr = 0  # round-robin cursor over server queues
    self._started = False

  def reset(self) -> None:
    """Start a new epoch of pulling (reference per-epoch re-arm).

    Any pullers from a partially-consumed previous epoch are stopped and
    their buffered messages discarded before the new epoch begins.
    """
    self._stop_pullers()
    self._started = True
    self._rr = 0
    self._pullers = [_Puller(fn, self.prefetch_size, self._avail)
                     for fn in self.fetch_fns]

  def _stop_pullers(self) -> None:
    for p in self._pullers:
      p.stop.set()
    for p in self._pullers:
      # Drain so a putter blocked on a full queue observes the stop.
      while True:
        try:
          p.q.get_nowait()
        except queue.Empty:
          break
      p.thread.join(timeout=2.0)
    self._pullers = []

  def send(self, msg: SampleMessage) -> None:
    raise RuntimeError('RemoteReceivingChannel is receive-only')

  def recv(self, timeout_ms: int = 60_000) -> SampleMessage:
    if not self._started:
      self.reset()
    deadline = time.monotonic() + timeout_ms / 1000
    while True:
      live = [p for p in self._pullers if not p.done]
      if not live:
        self._started = False
        raise StopIteration
      # Round-robin one non-blocking pass over the live servers; if all
      # are empty, sleep on the shared condition until ANY puller puts
      # (no per-queue pinning, no idle polling).
      item: Optional[object] = None
      src: Optional[_Puller] = None
      for off in range(len(live)):
        p = live[(self._rr + off) % len(live)]
        try:
          item = p.q.get_nowait()
          src = p
          self._rr = (self._rr + off + 1) % len(live)
          break
        except queue.Empty:
          continue
      if item is None:
        wait = deadline - time.monotonic()
        if wait <= 0.0:
          raise QueueTimeoutError('remote recv timed out')
        with self._avail:
          # re-check under the lock: a put may have landed between the
          # sweep above and acquiring the condition
          if all(p.q.empty() for p in live):
            self._avail.wait(timeout=wait)
        continue
      if isinstance(item, _EndOfServer):
        src.done = True
        continue
      if isinstance(item, Exception):
        # The puller thread exits after surfacing an error; mark its
        # server done so the epoch can still terminate if the consumer
        # swallows the error and keeps receiving.
        src.done = True
        raise item
      return item

  def stop(self) -> None:
    """Abandon the current epoch: stop pullers, drop buffered messages."""
    self._stop_pullers()
    self._started = False

  def empty(self) -> bool:
    return all(p.q.empty() for p in self._pullers)

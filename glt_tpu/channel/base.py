"""Channel abstractions + the flat SampleMessage wire format.

Reference: graphlearn_torch/python/channel/base.py (ChannelBase:25,
SampleMessage:28 = Dict[str, Tensor]) and the native TensorMapSerializer
(csrc/tensor_map.cc, include/tensor_map.h:24-52). SampleMessage here is
Dict[str, np.ndarray]; pack/unpack use the same flat binary layout
(|n| key_len|key|dtype|ndim|shape…|nbytes|data|) with zero-copy
``np.frombuffer`` views on the receive side.
"""
from __future__ import annotations

import struct
from typing import Dict

import numpy as np

SampleMessage = Dict[str, np.ndarray]

_DTYPES = [np.dtype(d) for d in (
    'bool', 'int8', 'uint8', 'int16', 'int32', 'int64',
    'float16', 'float32', 'float64',
)]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}
# bfloat16 rides as uint16 payload with its own code
_BF16_CODE = len(_DTYPES)


def _dtype_code(dt: np.dtype) -> int:
  if dt.name == 'bfloat16':
    return _BF16_CODE
  return _DTYPE_CODE[np.dtype(dt)]


def _code_dtype(code: int):
  if code == _BF16_CODE:
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)
  return _DTYPES[code]


def pack_message(msg: SampleMessage) -> bytes:
  """Serialize (TensorMapSerializer::Serialize equivalent)."""
  parts = [struct.pack('<I', len(msg))]
  for key, arr in msg.items():
    arr = np.ascontiguousarray(arr)
    kb = key.encode()
    parts.append(struct.pack('<I', len(kb)))
    parts.append(kb)
    parts.append(struct.pack('<II', _dtype_code(arr.dtype), arr.ndim))
    parts.append(struct.pack(f'<{max(arr.ndim,1)}Q',
                             *(arr.shape or (0,))))
    raw = arr.tobytes()
    parts.append(struct.pack('<Q', len(raw)))
    parts.append(raw)
  return b''.join(parts)


def unpack_message(buf: bytes) -> SampleMessage:
  """Deserialize with zero-copy views (TensorMapSerializer::Load)."""
  out: SampleMessage = {}
  (n,) = struct.unpack_from('<I', buf, 0)
  off = 4
  for _ in range(n):
    (klen,) = struct.unpack_from('<I', buf, off)
    off += 4
    key = buf[off:off + klen].decode()
    off += klen
    code, ndim = struct.unpack_from('<II', buf, off)
    off += 8
    shape = struct.unpack_from(f'<{max(ndim,1)}Q', buf, off)
    off += 8 * max(ndim, 1)
    if ndim == 0:
      shape = ()
    else:
      shape = shape[:ndim]
    (nbytes,) = struct.unpack_from('<Q', buf, off)
    off += 8
    total = int(np.prod(shape)) if ndim else 1
    arr = np.frombuffer(buf, dtype=_code_dtype(code), count=total,
                        offset=off).reshape(shape)
    out[key] = arr
    off += nbytes
  return out


class ChannelBase:
  """Producer->consumer byte channel of SampleMessages."""

  def send(self, msg: SampleMessage) -> None:
    raise NotImplementedError

  def recv(self, timeout_ms: int = 60_000) -> SampleMessage:
    raise NotImplementedError

  def empty(self) -> bool:
    raise NotImplementedError

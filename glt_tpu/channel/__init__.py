from .base import ChannelBase, SampleMessage, pack_message, unpack_message
from .shm import ShmQueue, QueueTimeoutError
from .shm_channel import ShmChannel
from .mp_channel import MpChannel
from .remote_channel import RemoteReceivingChannel

__all__ = [
    'ChannelBase', 'SampleMessage', 'pack_message', 'unpack_message',
    'ShmQueue', 'QueueTimeoutError',
    'ShmChannel', 'MpChannel', 'RemoteReceivingChannel',
]

"""MpChannel — multiprocessing.Queue-backed channel (reference
channel/mp_channel.py:21): the portable fallback when SysV shm is
unavailable; payloads pickle through the mp pipe."""
from __future__ import annotations

import multiprocessing as mp
import queue as _queue

from .base import ChannelBase, SampleMessage
from .shm import QueueTimeoutError


class MpChannel(ChannelBase):
  def __init__(self, capacity: int = 64):
    ctx = mp.get_context('spawn')
    self._queue = ctx.Queue(maxsize=capacity)

  def send(self, msg: SampleMessage, timeout_ms: int = 60_000) -> None:
    self._queue.put(msg, timeout=timeout_ms / 1000)

  def recv(self, timeout_ms: int = 60_000) -> SampleMessage:
    try:
      return self._queue.get(timeout=timeout_ms / 1000)
    except _queue.Empty as e:
      raise QueueTimeoutError('recv timed out') from e

  def empty(self) -> bool:
    return self._queue.empty()

"""ctypes bindings for the native shared-memory queue (csrc/shm_queue.cc)
— the reference's pywrap.SampleQueue surface (py_export_glt.cc:127-146):
picklable by shmid, blocking enqueue/dequeue with timeout.

The library is built on demand with the checked-in Makefile (g++ only; no
pybind11 in this image).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LIB_LOCK = threading.Lock()
_CSRC = os.path.join(os.path.dirname(__file__), '..', 'csrc')


class QueueTimeoutError(Exception):
  """Raised when a dequeue exceeds its timeout (reference
  py_export_glt.cc:133-137 maps the same condition to this name)."""


def _src_hash() -> str:
  import hashlib
  h = hashlib.sha256()
  for name in ('shm_queue.cc', 'Makefile'):
    with open(os.path.join(_CSRC, name), 'rb') as f:
      h.update(f.read())
  return h.hexdigest()


def _build_lib(force: bool = False) -> str:
  """Build libglt_shm.so when missing or when the source changed.

  Staleness is keyed on a content hash of the sources (recorded in a
  stamp file next to the .so), not on mtimes — after a fresh clone all
  files share checkout time, and a foreign-arch binary must not be
  dlopen'd just because it looks newer.
  """
  import fcntl
  so = os.path.join(_CSRC, 'libglt_shm.so')
  stamp = so + '.srchash'
  want = _src_hash()
  # Cross-process build lock: N worker processes importing simultaneously
  # on a fresh checkout must not run concurrent builds or dlopen a
  # half-linked .so. The winner builds to a temp name and renames
  # atomically; the others re-check the stamp under the lock and skip.
  with open(os.path.join(_CSRC, '.build.lock'), 'w') as lockf:
    fcntl.flock(lockf, fcntl.LOCK_EX)
    have = None
    if os.path.exists(stamp):
      with open(stamp) as f:
        have = f.read().strip()
    if force or not os.path.exists(so) or have != want:
      tmp = f'{so}.tmp.{os.getpid()}'
      try:
        subprocess.run(
            ['make', '-B', '-C', _CSRC, f'SO={os.path.basename(tmp)}'],
            check=True, capture_output=True)
        os.replace(tmp, so)
      finally:
        if os.path.exists(tmp):
          os.unlink(tmp)
      with open(stamp, 'w') as f:
        f.write(want)
  return so


def get_lib():
  global _LIB
  with _LIB_LOCK:
    if _LIB is None:
      try:
        lib = ctypes.CDLL(_build_lib())
      except OSError:
        # A stale/foreign binary slipped through (e.g. hand-copied):
        # rebuild from source once and retry.
        lib = ctypes.CDLL(_build_lib(force=True))
      lib.shmq_create.restype = ctypes.c_int
      lib.shmq_create.argtypes = [ctypes.c_uint64]
      lib.shmq_attach.restype = ctypes.c_void_p
      lib.shmq_attach.argtypes = [ctypes.c_int]
      lib.shmq_detach.argtypes = [ctypes.c_void_p]
      lib.shmq_destroy.argtypes = [ctypes.c_int]
      lib.shmq_enqueue.restype = ctypes.c_int
      lib.shmq_enqueue.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64, ctypes.c_int]
      lib.shmq_peek_size.restype = ctypes.c_int64
      lib.shmq_peek_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
      lib.shmq_dequeue.restype = ctypes.c_int64
      lib.shmq_dequeue.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                   ctypes.c_uint64, ctypes.c_int]
      lib.shmq_size.restype = ctypes.c_uint64
      lib.shmq_size.argtypes = [ctypes.c_void_p]
      _LIB = lib
    return _LIB


class ShmQueue:
  """Variable-block cross-process ring buffer.

  Picklable: only the shmid travels; the receiving process re-attaches
  (the ForkingPickler pattern of the reference, data/graph.py:257-306).
  """

  def __init__(self, capacity_bytes: int = 64 * 1024 * 1024,
               shmid: int = None, owner: bool = True):
    lib = get_lib()
    if shmid is None:
      shmid = lib.shmq_create(capacity_bytes)
      if shmid < 0:
        raise OSError(-shmid, 'shmq_create failed')
      owner = True
    self.shmid = shmid
    self.owner = owner
    self._handle = lib.shmq_attach(shmid)
    if not self._handle:
      raise OSError('shmq_attach failed')
    # peek+dequeue is a two-step protocol; serialize same-process
    # consumers (cross-process atomicity comes from the retry loop in
    # dequeue(): the C side refuses with -EMSGSIZE without consuming
    # when the block changed size under us)
    self._recv_lock = threading.Lock()

  def enqueue(self, data: bytes, timeout_ms: int = 60_000) -> None:
    rc = get_lib().shmq_enqueue(self._handle, data, len(data),
                                timeout_ms)
    if rc == -110:  # -ETIMEDOUT
      raise QueueTimeoutError('enqueue timed out')
    if rc != 0:
      raise OSError(-rc, 'shmq_enqueue failed')

  def dequeue(self, timeout_ms: int = 60_000) -> bytes:
    import time as _time
    lib = get_lib()
    deadline = _time.monotonic() + timeout_ms / 1000
    with self._recv_lock:
      while True:
        remaining = max(int((deadline - _time.monotonic()) * 1000), 1)
        size = lib.shmq_peek_size(self._handle, remaining)
        if size == -110:
          raise QueueTimeoutError('dequeue timed out')
        if size < 0:
          raise OSError(int(-size), 'shmq_peek_size failed')
        buf = ctypes.create_string_buffer(int(size))
        remaining = max(int((deadline - _time.monotonic()) * 1000), 1)
        got = lib.shmq_dequeue(self._handle, buf, int(size), remaining)
        if got == -110:
          raise QueueTimeoutError('dequeue timed out')
        if got == -90:  # -EMSGSIZE: another consumer won the race and
          continue      # the head block changed; re-peek
        if got < 0:
          raise OSError(int(-got), 'shmq_dequeue failed')
        return buf.raw[:got]

  def size(self) -> int:
    return int(get_lib().shmq_size(self._handle))

  def empty(self) -> bool:
    return self.size() == 0

  def close(self) -> None:
    if self._handle:
      get_lib().shmq_detach(self._handle)
      self._handle = None
    if self.owner:
      get_lib().shmq_destroy(self.shmid)
      self.owner = False

  # -- pickling by shmid -------------------------------------------------

  def __reduce__(self):
    return (ShmQueue, (0, self.shmid, False))

  def __del__(self):
    try:
      if getattr(self, '_handle', None):
        get_lib().shmq_detach(self._handle)
    except Exception:
      pass

"""Bucket-exchange-unbucket: the SPMD request/response pattern.

This is the TPU-native replacement for the reference's cross-partition
RPC fan-out (dist_neighbor_sampler.py:616-687: split ids by partition
book -> rpc to owners -> stitch): requests are packed into fixed-capacity
per-owner buckets, exchanged with one all_to_all over ICI, served
locally, and sent back with a second all_to_all; the un-bucketing scatter
is the positional stitch (stitch_sample_results.cu analog). All shapes
static; worst-case capacity = the full request vector per peer.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class BucketMeta(NamedTuple):
  order: jax.Array         # argsort of owner (stable)
  owner_sorted: jax.Array  # [B]
  pos_in_bucket: jax.Array  # [B]


def bucket_by_owner(ids: jax.Array, owner: jax.Array, n_shards: int,
                    fill_value=-1, capacity: int = 0):
  """Pack ids into per-owner buckets [n_shards, C].

  ``owner`` must be in [0, n_shards) for valid entries and == n_shards
  for invalid/padded ones (they are dropped). Bucket slots beyond each
  owner's request count hold ``fill_value``.

  ``capacity`` (default 0 = B, the worst case) caps each per-owner
  bucket: a device then ships n_shards*C elements instead of
  n_shards*B. Requests ranked past the cap are NOT packed — they come
  back as ``invalid_value`` from :func:`unbucket`, and the caller
  re-issues them (the bucketing is deterministic, so the host can
  replay it and drain overflow through the same compiled program; see
  ShardedFeature.lookup).
  """
  b = ids.shape[0]
  cap = capacity if capacity and capacity < b else b
  order = jnp.argsort(owner, stable=True)
  owner_sorted = jnp.take(owner, order)
  counts = jnp.bincount(jnp.minimum(owner_sorted, n_shards),
                        length=n_shards + 1)[:n_shards]
  offsets = jnp.cumsum(counts) - counts
  pos = jnp.arange(b) - jnp.take(
      offsets, jnp.minimum(owner_sorted, n_shards - 1))
  meta = BucketMeta(order, owner_sorted, pos)
  return bucket_payload(ids, meta, n_shards, fill_value,
                        capacity=cap), meta


def unbucket(resp: jax.Array, meta: BucketMeta, n_shards: int,
             invalid_value=0, round_offset=0) -> jax.Array:
  """Invert bucket_by_owner over a response [n_shards, C, ...]: returns
  [B, ...] in the original request order; dropped and over-capacity
  slots get ``invalid_value``. ``round_offset`` (may be a traced
  scalar) selects the drain round: only requests whose in-bucket rank
  lies in [round_offset, round_offset + C) are decoded — the inverse of
  the same offset passed to :func:`bucket_payload`."""
  cap = resp.shape[1]
  pos = meta.pos_in_bucket - round_offset
  ok = (meta.owner_sorted < n_shards) & (pos >= 0) & (pos < cap)
  gathered = resp[jnp.minimum(meta.owner_sorted, n_shards - 1),
                  jnp.clip(pos, 0, cap - 1)]
  shape = (ok.shape[0],) + (1,) * (gathered.ndim - 1)
  gathered = jnp.where(ok.reshape(shape), gathered, invalid_value)
  out = jnp.zeros_like(gathered)
  return out.at[meta.order].set(gathered)


def drain_rounds(meta: BucketMeta, n_shards: int, cap: int,
                 axis_name: str) -> jax.Array:
  """How many capped-exchange rounds serve every request: the max
  per-owner bucket occupancy over the WHOLE mesh, ceil-divided by the
  capacity. pmax makes the value identical on every device, so a
  lax.while_loop conditioned on it keeps the collectives inside the
  loop aligned — the drain runs entirely in-program (no host replay of
  the bucketing, no cross-process agreement round)."""
  counts = jnp.bincount(jnp.minimum(meta.owner_sorted, n_shards),
                        length=n_shards + 1)[:n_shards]
  local = (counts.max() + cap - 1) // cap
  return jax.lax.pmax(local.astype(jnp.int32), axis_name)


def capped_drain(round_out, meta: 'BucketMeta', n_shards: int, cap: int,
                 b: int, axis_name: str, zeros):
  """Accumulate ``round_out(base)`` over however many capped-exchange
  rounds serve every request (see :func:`drain_rounds`).

  ``round_out`` returns a pytree of per-request accumulators for the
  requests ranked [base, base+cap) per bucket; rounds past the true
  occupancy pack only fill lanes and therefore contribute exact
  zeros/False. ``zeros`` is the matching all-zero pytree. Bool leaves
  merge with ``|``, everything else with ``+``.

  On modern jax the round count is a pmax'd traced scalar driving a
  ``lax.while_loop`` (typical skew: one round). Legacy 0.4.x jax
  MISCOMPILES collectives under a traced while_loop inside shard_map
  (wrong values, not an error), so there the drain unrolls statically
  to its worst case ceil(b/cap) — value-identical, always paying the
  full exchange count. One implementation for every capped lookup path
  (parallel + distributed feature stores).
  """
  from jax import tree_util  # jax.tree.map is younger than the 0.4.x
  #                            targets the legacy branch exists for

  def merge(a, o):
    return a | o if a.dtype == jnp.bool_ else a + o

  from ..utils import compat
  if compat.LEGACY_JAX:
    acc = zeros
    for k in range(-(-b // cap)):
      acc = tree_util.tree_map(merge, acc, round_out(k * cap))
    return acc
  rounds = drain_rounds(meta, n_shards, cap, axis_name)

  def body(state):
    k, acc = state
    return k + 1, tree_util.tree_map(merge, acc, round_out(k * cap))

  _, acc = jax.lax.while_loop(lambda s: s[0] < rounds, body,
                              (jnp.zeros((), jnp.int32), zeros))
  return acc


def all_to_all(x: jax.Array, axis_name: str) -> jax.Array:
  """Exchange row p of x with peer p along ``axis_name``; x: [P, ...]."""
  n = x.shape[0]
  y = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                         tiled=False)
  return y.reshape((n,) + x.shape[1:])


def bucket_payload(values: jax.Array, meta: BucketMeta, n_shards: int,
                   fill_value=0, capacity: int = 0,
                   round_offset=0) -> jax.Array:
  """Pack a companion payload with the SAME ordering as an existing
  bucket_by_owner call (e.g. the col of a (row, col) pair routed by the
  row's owner). ``round_offset`` (may be a traced scalar, e.g. the
  drain-loop counter times the capacity) packs the requests ranked
  [round_offset, round_offset + cap) within each bucket — drain round k
  of a capped exchange packs offset k*cap."""
  b = values.shape[0]
  cap = capacity if capacity and capacity < b else b
  vals_sorted = jnp.take(values, meta.order)
  pos = meta.pos_in_bucket - round_offset
  ok = (meta.owner_sorted < n_shards) & (pos >= 0) & (pos < cap)
  buckets = jnp.full((n_shards + 1, cap), fill_value, values.dtype)
  buckets = buckets.at[
      jnp.where(ok, meta.owner_sorted, n_shards),
      jnp.where(ok, jnp.clip(pos, 0, cap - 1), 0)].set(
          jnp.where(ok, vals_sorted, fill_value))
  return buckets[:n_shards]


def sharded_segment_mean(msgs: jax.Array, targets: jax.Array,
                         mask: jax.Array, num_segments: int,
                         axis_name: str) -> jax.Array:
  """Context-parallel neighborhood aggregation (call inside shard_map).

  The graph-domain analogue of sequence/context parallelism (SURVEY.md
  §5.7: the 'sequence length' axis of this domain is neighborhood size):
  when a node's neighbor list is too large for one chip, its message
  rows are sharded across the mesh; every device reduces its local
  shard with a masked segment-sum and the partial sums/counts are
  psum'd over ICI — a ring-attention-style reduction where the softmax
  is replaced by the GNN's mean.

  Args:
    msgs: [M_local, D] this device's message shard.
    targets: [M_local] destination segment per message.
    mask: [M_local] validity.
    num_segments: global segment count (static).
    axis_name: mesh axis to reduce over.

  Returns [num_segments, D] — identical on every device.
  """
  total, cnt = _local_segment_sums(msgs, targets, mask, num_segments)
  total = jax.lax.psum(total, axis_name)
  cnt = jax.lax.psum(cnt, axis_name)
  return total / jnp.maximum(cnt[:, None], 1.0)


def _local_segment_sums(msgs, targets, mask, num_segments):
  """This device's masked (sum, count) per segment."""
  seg = jnp.where(mask, targets, num_segments)
  total = jax.ops.segment_sum(
      jnp.where(mask[:, None], msgs, 0.0), seg, num_segments + 1
  )[:num_segments]
  cnt = jax.ops.segment_sum(mask.astype(msgs.dtype), seg,
                            num_segments + 1)[:num_segments]
  return total, cnt


def sharded_segment_mean_scattered(msgs: jax.Array, targets: jax.Array,
                                   mask: jax.Array, num_segments: int,
                                   axis_name: str) -> jax.Array:
  """Ring (reduce-scatter) variant of :func:`sharded_segment_mean`:
  the aggregated output stays SHARDED — device i returns only its
  segment block [i*S/P, (i+1)*S/P) — so per-device memory and ICI
  bandwidth drop by the mesh size. ``psum_scatter`` lowers to the ring
  reduce-scatter on ICI (the reduce half of ring attention; the GNN
  mean replaces the softmax).

  ``num_segments`` must be divisible by the axis size. Returns
  [num_segments / P, D].
  """
  n_dev = jax.lax.axis_size(axis_name)
  assert num_segments % n_dev == 0, (
      f'num_segments ({num_segments}) must divide by the axis size '
      f'({n_dev}) for the scattered layout')
  total, cnt = _local_segment_sums(msgs, targets, mask, num_segments)
  total = jax.lax.psum_scatter(total, axis_name, scatter_dimension=0,
                               tiled=True)
  cnt = jax.lax.psum_scatter(cnt, axis_name, scatter_dimension=0,
                             tiled=True)
  return total / jnp.maximum(cnt[:, None], 1.0)

"""SPMD data-parallel training step: the DDP + distributed-feature loop
as one shard_map program.

Reference architecture being replaced (SURVEY.md §2.3): DDP/NCCL gradient
allreduce + per-rank sampling workers + RPC feature lookup. TPU-native
formulation: a single shard_map over the 'data' mesh axis where each
device (1) samples its own seed shard against the replicated topology,
(2) resolves features from the row-sharded feature table via the
all_to_all exchange in ShardedFeature, (3) computes grads, (4) psums —
the NCCL allreduce riding ICI. Params/optimizer state stay replicated.

Two execution modes share one batch body:

  * per-batch (``__call__``): one dispatch per batch — one Python loop
    iteration, one host->device seed transfer, one jit dispatch each.
  * superstep (``superstep`` / ``run_epoch``): K batches per donated
    dispatch via lax.scan (ops/superstep.py), consuming seed stacks the
    DeviceEpochLoader staged on device once per epoch. Bit-identical to
    K sequential per-batch calls (same RNG stream, same op sequence) —
    the scan only amortizes the per-batch host round-trips. The hetero
    sibling — per-edge-type collective sampling + RGNN update, same
    scan lift with the per-type table dict as the dedup state — is
    ``glt_tpu.distributed.DistHeteroTrainStep.superstep``
    (ops/superstep.py::superstep_hetero, which this trainer's homo
    ``(table, scratch)`` superstep is now a special case of).

For host-spilled features WITHOUT the pinned-host cold block
(``cold_array is None``) the fused body cannot resolve cold rows
in-program; ``cold_streaming=True`` instead splits each superstep into a
sampling scan and a consume scan: the host gathers the sampled cold rows
(``ShardedFeature.stage_cold_rows``) and ``device_put``s them between the
two, and ``run_epoch`` runs that stage phase for superstep N+1 on a
prefetch thread while the chip executes superstep N (double buffering —
``split_ratio < 1`` no longer serializes host gathers against compute).
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data import Graph
from ..ops.pipeline import edge_hop_offsets, multihop_sample, sample_budget
from ..ops.sample import sample_neighbors
from ..ops.pipeline import make_dedup_tables
from ..ops.superstep import scan_consume, superstep as build_superstep
from ..loader.transform import Batch


def _sage_update(model, tx, axis, bs, params, opt_state, batch, n_valid):
  """Forward/backward + DDP pmean + optimizer update for one batch —
  the training tail shared by the per-batch, fused-superstep and
  streaming-consume bodies (identical op sequence = loss parity)."""
  def loss_fn(p):
    logits = model.apply(p, batch)
    mask = jnp.arange(bs) < n_valid
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits, batch.y)
    return (jnp.where(mask, losses, 0).sum()
            / jnp.maximum(mask.sum(), 1))

  loss, grads = jax.value_and_grad(loss_fn)(params)
  # DDP allreduce (mean over devices), riding ICI
  grads = jax.lax.pmean(grads, axis)
  loss = jax.lax.pmean(loss, axis)
  updates, opt_state = tx.update(grads, opt_state, params)
  params = optax.apply_updates(params, updates)
  return params, opt_state, loss


class SPMDSageTrainStep:
  """Builds and runs the sharded sample+train step.

  Args:
    mesh: the device mesh (axis 'data').
    model: a flax module consuming a Batch (e.g. models.GraphSAGE).
    tx: optax optimizer.
    graph: replicated Graph (HBM-resident topology on every chip; the
      sharded-topology variant lives in glt_tpu.distributed).
    feature: a ShardedFeature row-sharded over the mesh.
    labels: [N] label array (replicated).
    fanouts: per-hop fanouts.
    batch_size_per_device: seed count per device per step.
    with_edge: also thread sampled edge ids through the pipeline into
      ``Batch.edge`` (edge-feature consumers).
    cold_streaming: opt-in — accept a host-spilled store WITHOUT the
      pinned-host cold block by staging cold rows per superstep (see
      module docstring). Only the superstep path serves such stores;
      per-batch ``__call__`` raises. Without it, such stores are
      rejected at construction exactly as before.
  """

  def __init__(self, mesh: Mesh, model, tx, graph: Graph, feature,
               labels, fanouts: Sequence[int],
               batch_size_per_device: int, axis: str = 'data',
               with_edge: bool = False, cold_streaming: bool = False):
    from .dist_feature import require_device_resident
    self._streaming = bool(cold_streaming)
    if not self._streaming:
      require_device_resident(feature, 'SPMDSageTrainStep')
    elif not getattr(feature, '_spill', False) \
        or getattr(feature, 'cold_array', None) is not None:
      raise ValueError(
          'cold_streaming=True needs a host-spilled store without a '
          'pinned-host cold block (split_ratio < 1, host_offload=False)')
    self.mesh = mesh
    self.model = model
    self.tx = tx
    self.graph = graph
    self.feature = feature
    self.fanouts = list(fanouts)
    self.bs = batch_size_per_device
    self.axis = axis
    self.with_edge = bool(with_edge)
    graph.lazy_init()
    self.labels = jax.device_put(labels, NamedSharding(mesh, P()))
    # one-time replication of the topology over the mesh: these ride
    # the step as jit ARGUMENTS (constants would ship in the axon
    # remote-compile payload — observed HTTP 413 at products scale),
    # and pre-committing the replicated sharding here keeps the
    # per-step call from re-broadcasting them each execution
    self._indptr = jax.device_put(graph.indptr, NamedSharding(mesh, P()))
    self._indices = jax.device_put(graph.indices,
                                   NamedSharding(mesh, P()))
    n_dev = mesh.shape[axis]
    # per-device inducer tables, stacked on the mesh axis
    table, scratch = make_dedup_tables(graph.num_nodes)
    self.tables = jax.device_put(
        jnp.broadcast_to(table, (n_dev,) + table.shape),
        NamedSharding(mesh, P(axis)))
    self.scratches = jax.device_put(
        jnp.broadcast_to(scratch, (n_dev,) + scratch.shape),
        NamedSharding(mesh, P(axis)))
    #: times each program was TRACED (trace-time side effect; executions
    #: never bump these) — zero-steady-state-recompile assertions read
    #: them. A fresh T (e.g. an epoch's ragged tail superstep) traces
    #: once more by design.
    self.step_traces = 0
    self.superstep_traces = 0
    self._step_fn = self._build()
    self._superstep_fn = self._build_superstep()
    if self._streaming:
      self._sample_fn = self._build_sample_superstep()
      self._consume_fn = self._build_consume_superstep()

  def init_params(self, key) -> dict:
    batch = self._dummy_batch()
    params = self.model.init(key, batch)
    return jax.device_put(params, NamedSharding(self.mesh, P()))

  def _dummy_batch(self) -> Batch:
    budget = sample_budget(self.bs, self.fanouts)
    ecap = edge_hop_offsets(self.bs, self.fanouts)[-1]
    return Batch(
        x=jnp.zeros((budget, self.feature.feature_dim)),
        row=jnp.zeros((ecap,), jnp.int32),
        col=jnp.zeros((ecap,), jnp.int32),
        edge_mask=jnp.zeros((ecap,), bool),
        node=jnp.zeros((budget,), jnp.int32),
        node_count=jnp.zeros((), jnp.int32),
        y=jnp.zeros((self.bs,), jnp.int32),
        batch_size=self.bs,
        edge_hop_offsets=tuple(edge_hop_offsets(self.bs, self.fanouts)),
    )

  # -- shared per-batch body ----------------------------------------------

  def _make_batch_body(self, feat_shard, labels, indptr, indices,
                       cold_shard):
    """The body of ONE training step as seen from inside shard_map:
    sample -> gather -> forward/backward -> pmean -> update. Shared
    verbatim by the per-batch step and the superstep scan so the two
    engines stay bit-identical."""
    feature, model, tx, axis = self.feature, self.model, self.tx, self.axis
    fanouts, bs = self.fanouts, self.bs
    offs = tuple(edge_hop_offsets(bs, fanouts))
    with_edge = self.with_edge
    one_hop = lambda ids, fanout, k, mask: sample_neighbors(
        indptr, indices, ids, fanout, k, seed_mask=mask)

    def body(params, opt_state, table, scratch, seeds, n_valid, key):
      key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
      out, table, scratch = multihop_sample(
          one_hop, seeds, n_valid[0], fanouts, key, table, scratch,
          with_edge=with_edge)
      node_valid = jnp.arange(out['node'].shape[0]) < out['node_count']
      x = feature.lookup_local(
          feat_shard, jnp.maximum(out['node'], 0), node_valid,
          axis_name=axis, cold_shard=cold_shard)
      y = jnp.take(labels, jnp.maximum(out['batch'], 0)[:bs])
      batch = Batch(
          x=x, row=out['row'], col=out['col'], edge_mask=out['edge_mask'],
          node=out['node'], node_count=out['node_count'], y=y,
          edge=out.get('edge'),
          batch_size=bs, edge_hop_offsets=offs)
      params, opt_state, loss = _sage_update(
          model, tx, axis, bs, params, opt_state, batch, n_valid[0])
      return params, opt_state, table, scratch, loss

    return body

  def _build(self):
    def device_step(params, opt_state, table, scratch, seeds, n_valid,
                    key, feat_shard, labels, indptr, indices,
                    *cold_shard):
      body = self._make_batch_body(
          feat_shard, labels, indptr, indices,
          cold_shard[0] if cold_shard else None)
      params, opt_state, table, scratch, loss = body(
          params, opt_state, table[0], scratch[0], seeds, n_valid, key)
      return (params, opt_state, table[None], scratch[None],
              loss[None])

    offloaded = self.feature.cold_array is not None
    fn = jax.shard_map(
        device_step, mesh=self.mesh,
        in_specs=(P(), P(), P(self.axis), P(self.axis), P(self.axis),
                  P(self.axis), P(self.axis), P(self.axis), P(), P(),
                  P())
        + ((P(self.axis),) if offloaded else ()),
        out_specs=(P(), P(), P(self.axis), P(self.axis), P(self.axis)),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def step(params, opt_state, tables, scratches, seeds, n_valid, keys,
             feat_array, labels, indptr, indices, *cold):
      # feat/cold/labels/topology ride as explicit args: (a) committed
      # shardings — incl. the cold block's pinned_host memory kind —
      # are preserved (a closed-over array would be re-laid-out as a
      # default-memory constant), and (b) a closed-over array becomes a
      # jit CONSTANT, which the axon remote-compile path ships in the
      # compile request body — hundreds of MB of topology in the
      # payload (observed HTTP 413 at products scale)
      self.step_traces += 1  # trace-time side effect only
      from ..obs.perf import count_compile
      count_compile('train.step')
      return fn(params, opt_state, tables, scratches, seeds, n_valid,
                keys, feat_array, labels, indptr, indices, *cold)

    return step

  # -- superstep: K batches per donated dispatch --------------------------

  def _build_superstep(self):
    """The fused superstep program: lax.scan of the per-batch body with
    params/opt-state/dedup-tables in the carry. Unsupported for
    streaming stores (cold rows are not in-program resolvable there);
    ``superstep()`` routes those through sample+stage+consume."""
    if self._streaming:
      return None
    axis = self.axis

    def device_superstep(params, opt_state, tables, scratches,
                         seeds_stack, n_valid_stack, keys, feat_shard,
                         labels, indptr, indices, *cold_shard):
      # per-device views: seeds_stack [T, bs], n_valid_stack [T, 1],
      # keys [T, 1], tables [1, ...]
      body = self._make_batch_body(
          feat_shard, labels, indptr, indices,
          cold_shard[0] if cold_shard else None)
      run = build_superstep(body)
      params, opt_state, table, scratch, losses = run(
          params, opt_state, tables[0], scratches[0], seeds_stack,
          n_valid_stack, keys)
      return (params, opt_state, table[None], scratch[None],
              losses[:, None])

    offloaded = self.feature.cold_array is not None
    stacked = P(None, self.axis)
    fn = jax.shard_map(
        device_superstep, mesh=self.mesh,
        in_specs=(P(), P(), P(self.axis), P(self.axis), stacked,
                  stacked, stacked, P(self.axis), P(), P(), P())
        + ((P(self.axis),) if offloaded else ()),
        out_specs=(P(), P(), P(self.axis), P(self.axis), stacked),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
    def step(params, opt_state, tables, scratches, seeds_stack,
             n_valid_stack, keys, feat_array, labels, indptr, indices,
             *cold):
      self.superstep_traces += 1  # trace-time side effect only
      from ..obs.perf import count_compile
      count_compile('train.superstep')
      return fn(params, opt_state, tables, scratches, seeds_stack,
                n_valid_stack, keys, feat_array, labels, indptr,
                indices, *cold)

    return step

  def _stacked_put(self, seeds_stack, n_valid_stack, keys):
    """Commit superstep inputs to the [T, shard] layout. Inputs the
    DeviceEpochLoader already staged (committed, correct sharding) pass
    through without a copy."""
    sh = NamedSharding(self.mesh, P(None, self.axis))
    seeds = jax.device_put(jnp.asarray(seeds_stack, jnp.int32), sh)
    n_valid = jax.device_put(jnp.asarray(n_valid_stack, jnp.int32), sh)
    keys = jax.device_put(keys, sh)
    return seeds, n_valid, keys

  def superstep(self, params, opt_state, seeds_stack, n_valid_stack,
                keys):
    """Run T training steps in ONE donated dispatch.

    seeds_stack: [T, n_dev * bs] shard-major per batch;
    n_valid_stack: [T, n_dev]; keys: [T, n_dev] PRNG keys (batch t on
    device d consumes keys[t, d], exactly as T sequential ``__call__``\\ s
    consuming ``keys[t]`` would). Params/opt-state are DONATED — reuse
    the returned ones. Returns (params, opt_state, loss [T, n_dev]).
    """
    seeds, n_valid, keys = self._stacked_put(seeds_stack, n_valid_stack,
                                             keys)
    from ..obs import get_registry, get_tracer
    tracer = get_tracer()
    if self._streaming:
      with tracer.span('train.superstep', streaming=True,
                       k=int(seeds.shape[0])):
        staged = self._sample_and_stage(seeds, n_valid, keys)
        out = self._consume(params, opt_state, staged, n_valid)
      if tracer.enabled:
        get_registry().set('train_superstep_traces',
                           float(self.superstep_traces))
      return out
    extra = ((self.feature.cold_array,)
             if self.feature.cold_array is not None else ())
    _synced = {}
    with tracer.span('train.superstep', k=int(seeds.shape[0]),
                     sync=lambda: _synced.get('loss')):
      (params, opt_state, self.tables, self.scratches,
       loss) = self._superstep_fn(
           params, opt_state, self.tables, self.scratches, seeds,
           n_valid, keys, self.feature.array, self.labels, self._indptr,
           self._indices, *extra)
      _synced['loss'] = loss
    if tracer.enabled:
      # re-trace visibility on the shared surface: the zero-steady-
      # state-recompile asserts read the attributes; dashboards read
      # these gauges
      get_registry().set('train_superstep_traces',
                         float(self.superstep_traces))
    return params, opt_state, loss

  # -- cold-row streaming: sample scan + host stage + consume scan --------

  def _build_sample_superstep(self):
    """Sampling-only scan (the multihop_sample_many shape, but under
    shard_map with the per-device key fold): produces the stacked
    sampler outputs the consume scan and the host cold-stager read."""
    axis, fanouts, bs = self.axis, self.fanouts, self.bs
    with_edge = self.with_edge

    def device_sample(tables, scratches, seeds_stack, n_valid_stack,
                      keys, indptr, indices):
      one_hop = lambda ids, fanout, k, mask: sample_neighbors(
          indptr, indices, ids, fanout, k, seed_mask=mask)

      def body(carry, x):
        table, scratch = carry
        seeds, n_valid, key = x
        key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
        out, table, scratch = multihop_sample(
            one_hop, seeds, n_valid[0], fanouts, key, table, scratch,
            with_edge=with_edge)
        keep = dict(node=out['node'], node_count=out['node_count'][None],
                    row=out['row'], col=out['col'],
                    edge_mask=out['edge_mask'])
        if with_edge:
          keep['edge'] = out['edge']
        return (table, scratch), keep

      (table, scratch), outs = jax.lax.scan(
          body, (tables[0], scratches[0]),
          (seeds_stack, n_valid_stack, keys))
      return table[None], scratch[None], outs

    stacked = P(None, self.axis)
    fn = jax.shard_map(
        device_sample, mesh=self.mesh,
        in_specs=(P(self.axis), P(self.axis), stacked, stacked, stacked,
                  P(), P()),
        out_specs=(P(self.axis), P(self.axis), stacked),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def sample(tables, scratches, seeds_stack, n_valid_stack, keys,
               indptr, indices):
      self.superstep_traces += 1  # trace-time side effect only
      from ..obs.perf import count_compile
      count_compile('train.sample_superstep')
      return fn(tables, scratches, seeds_stack, n_valid_stack, keys,
                indptr, indices)

    return sample

  def _build_consume_superstep(self):
    """Scan of gather+forward/backward+update over pre-sampled batches:
    hot rows resolve through the all_to_all lookup (cold lanes zero),
    the staged cold rows add in elementwise — the in-scan equivalent of
    ShardedFeature._resolve_cold_sharded's host merge."""
    feature, model, tx = self.feature, self.model, self.tx
    axis, bs = self.axis, self.bs
    offs = tuple(edge_hop_offsets(bs, self.fanouts))
    budget = sample_budget(bs, self.fanouts)
    with_edge = self.with_edge

    def device_consume(params, opt_state, outs, cold_x, n_valid_stack,
                       feat_shard, labels):
      def body(carry, x):
        params, opt_state = carry
        out, cold_t, n_valid = x
        node_count = out['node_count'][0]
        node_valid = jnp.arange(budget) < node_count
        xh = feature.lookup_local(
            feat_shard, jnp.maximum(out['node'], 0), node_valid,
            axis_name=axis)
        x_feat = xh + cold_t.astype(xh.dtype)
        y = jnp.take(labels, jnp.maximum(out['node'], 0)[:bs])
        batch = Batch(
            x=x_feat, row=out['row'], col=out['col'],
            edge_mask=out['edge_mask'], node=out['node'],
            node_count=node_count, y=y, edge=out.get('edge'),
            batch_size=bs, edge_hop_offsets=offs)
        params, opt_state, loss = _sage_update(
            model, tx, axis, bs, params, opt_state, batch, n_valid[0])
        return (params, opt_state), loss

      run = scan_consume(body)
      (params, opt_state), losses = run(
          (params, opt_state), (outs, cold_x, n_valid_stack))
      return params, opt_state, losses[:, None]

    stacked = P(None, self.axis)
    fn = jax.shard_map(
        device_consume, mesh=self.mesh,
        in_specs=(P(), P(), stacked, stacked, stacked, P(self.axis),
                  P()),
        out_specs=(P(), P(), stacked),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def consume(params, opt_state, outs, cold_x, n_valid_stack,
                feat_array, labels):
      self.superstep_traces += 1  # trace-time side effect only
      from ..obs.perf import count_compile
      count_compile('train.consume_superstep')
      return fn(params, opt_state, outs, cold_x, n_valid_stack,
                feat_array, labels)

    return consume

  def _sample_and_stage(self, seeds, n_valid, keys):
    """Dispatch the sampling scan, then host-gather + upload the cold
    rows for every sampled node stack. run_epoch calls this from the
    prefetch thread so the host gather for superstep N+1 overlaps the
    chip executing superstep N."""
    self.tables, self.scratches, outs = self._sample_fn(
        self.tables, self.scratches, seeds, n_valid, keys,
        self._indptr, self._indices)
    cold = self.feature.stage_cold_rows(
        np.asarray(outs['node']), np.asarray(outs['node_count']))
    cold_x = jax.device_put(
        cold, NamedSharding(self.mesh, P(None, self.axis)))
    return outs, cold_x

  def _consume(self, params, opt_state, staged, n_valid):
    outs, cold_x = staged
    params, opt_state, loss = self._consume_fn(
        params, opt_state, outs, cold_x, n_valid, self.feature.array,
        self.labels)
    return params, opt_state, loss

  # -- epoch drivers ------------------------------------------------------

  def make_epoch_loader(self, seeds, superstep_len: int = 8,
                        shuffle: bool = True, drop_last: bool = False,
                        drop_last_superstep: bool = False,
                        rng=None):
    """A DeviceEpochLoader pre-committed to this trainer's mesh layout
    (seed stacks [T, n_dev*bs] sharded on the batch axis)."""
    from ..loader.device_epoch import DeviceEpochLoader
    n_dev = self.mesh.shape[self.axis]
    sh = NamedSharding(self.mesh, P(None, self.axis))
    return DeviceEpochLoader(
        seeds, batch_size=n_dev * self.bs, superstep_len=superstep_len,
        num_shards=n_dev, shuffle=shuffle, drop_last=drop_last,
        drop_last_superstep=drop_last_superstep, rng=rng, sharding=sh,
        n_valid_sharding=sh)

  def run_epoch(self, params, opt_state, loader, key,
                stream_depth: int = 1):
    """Drive one epoch of supersteps from a DeviceEpochLoader.

    Non-streaming stores run the fused superstep per window. Streaming
    stores double-buffer: the sample+stage phase (device sampling scan,
    host cold-row gather, device_put) for window N+1 runs on a prefetch
    thread while the consume scan for window N executes — the host
    gather no longer serializes against compute. Returns
    (params, opt_state, losses [T_total, n_dev]).
    """
    n_dev = self.mesh.shape[self.axis]

    def keyed():
      k = key
      for ss in loader:
        k, sub = jax.random.split(k)
        yield ss, jax.random.split(sub, (ss.length, n_dev))

    losses = []
    if self._streaming:
      from ..utils.prefetch import prefetch

      def staged():
        for ss, keys in keyed():
          seeds, n_valid, keys = self._stacked_put(ss.seeds, ss.n_valid,
                                                   keys)
          yield self._sample_and_stage(seeds, n_valid, keys), n_valid

      for stage, n_valid in prefetch(staged(), depth=max(1,
                                                         stream_depth)):
        params, opt_state, loss = self._consume(params, opt_state,
                                                stage, n_valid)
        losses.append(loss)
    else:
      for ss, keys in keyed():
        params, opt_state, loss = self.superstep(
            params, opt_state, ss.seeds, ss.n_valid, keys)
        losses.append(loss)
    if not losses:  # empty epoch (e.g. drop_last_superstep ate it all)
      return params, opt_state, jnp.zeros((0, n_dev))
    return params, opt_state, jnp.concatenate(losses, axis=0)

  # -- per-batch path -----------------------------------------------------

  def __call__(self, params, opt_state, seeds, n_valid_per_device, keys):
    """seeds: [n_dev * bs] shard-major; n_valid_per_device: [n_dev];
    keys: [n_dev] PRNG keys. Returns (params, opt_state, loss[n_dev])."""
    if self._streaming:
      raise NotImplementedError(
          'cold_streaming stores run through superstep()/run_epoch(); '
          'the per-batch step cannot resolve host-spilled rows '
          'in-program')
    n_dev = self.mesh.shape[self.axis]
    seeds = jax.device_put(
        jnp.asarray(seeds, jnp.int32),
        NamedSharding(self.mesh, P(self.axis)))
    n_valid = jax.device_put(
        jnp.asarray(n_valid_per_device, jnp.int32),
        NamedSharding(self.mesh, P(self.axis)))
    extra = ((self.feature.cold_array,)
             if self.feature.cold_array is not None else ())
    from ..obs import get_registry, get_tracer
    tracer = get_tracer()
    _synced = {}
    with tracer.span('train.step', sync=lambda: _synced.get('loss')):
      (params, opt_state, self.tables, self.scratches,
       loss) = self._step_fn(
           params, opt_state, self.tables, self.scratches, seeds,
           n_valid, keys, self.feature.array, self.labels, self._indptr,
           self._indices, *extra)
      _synced['loss'] = loss
    if tracer.enabled:
      get_registry().set('train_step_traces', float(self.step_traces))
    return params, opt_state, loss

"""SPMD data-parallel training step: the DDP + distributed-feature loop
as one shard_map program.

Reference architecture being replaced (SURVEY.md §2.3): DDP/NCCL gradient
allreduce + per-rank sampling workers + RPC feature lookup. TPU-native
formulation: a single shard_map over the 'data' mesh axis where each
device (1) samples its own seed shard against the replicated topology,
(2) resolves features from the row-sharded feature table via the
all_to_all exchange in ShardedFeature, (3) computes grads, (4) psums —
the NCCL allreduce riding ICI. Params/optimizer state stay replicated.
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data import Graph
from ..ops.pipeline import edge_hop_offsets, multihop_sample, sample_budget
from ..ops.sample import sample_neighbors
from ..ops.pipeline import make_dedup_tables
from ..loader.transform import Batch


class SPMDSageTrainStep:
  """Builds and runs the sharded sample+train step.

  Args:
    mesh: the device mesh (axis 'data').
    model: a flax module consuming a Batch (e.g. models.GraphSAGE).
    tx: optax optimizer.
    graph: replicated Graph (HBM-resident topology on every chip; the
      sharded-topology variant lives in glt_tpu.distributed).
    feature: a ShardedFeature row-sharded over the mesh.
    labels: [N] label array (replicated).
    fanouts: per-hop fanouts.
    batch_size_per_device: seed count per device per step.
  """

  def __init__(self, mesh: Mesh, model, tx, graph: Graph, feature,
               labels, fanouts: Sequence[int],
               batch_size_per_device: int, axis: str = 'data'):
    from .dist_feature import require_device_resident
    require_device_resident(feature, 'SPMDSageTrainStep')
    self.mesh = mesh
    self.model = model
    self.tx = tx
    self.graph = graph
    self.feature = feature
    self.fanouts = list(fanouts)
    self.bs = batch_size_per_device
    self.axis = axis
    graph.lazy_init()
    self.labels = jax.device_put(labels, NamedSharding(mesh, P()))
    # one-time replication of the topology over the mesh: these ride
    # the step as jit ARGUMENTS (constants would ship in the axon
    # remote-compile payload — observed HTTP 413 at products scale),
    # and pre-committing the replicated sharding here keeps the
    # per-step call from re-broadcasting them each execution
    self._indptr = jax.device_put(graph.indptr, NamedSharding(mesh, P()))
    self._indices = jax.device_put(graph.indices,
                                   NamedSharding(mesh, P()))
    n_dev = mesh.shape[axis]
    # per-device inducer tables, stacked on the mesh axis
    table, scratch = make_dedup_tables(graph.num_nodes)
    self.tables = jax.device_put(
        jnp.broadcast_to(table, (n_dev,) + table.shape),
        NamedSharding(mesh, P(axis)))
    self.scratches = jax.device_put(
        jnp.broadcast_to(scratch, (n_dev,) + scratch.shape),
        NamedSharding(mesh, P(axis)))
    self._step_fn = self._build()

  def init_params(self, key) -> dict:
    batch = self._dummy_batch()
    params = self.model.init(key, batch)
    return jax.device_put(params, NamedSharding(self.mesh, P()))

  def _dummy_batch(self) -> Batch:
    budget = sample_budget(self.bs, self.fanouts)
    ecap = edge_hop_offsets(self.bs, self.fanouts)[-1]
    return Batch(
        x=jnp.zeros((budget, self.feature.feature_dim)),
        row=jnp.zeros((ecap,), jnp.int32),
        col=jnp.zeros((ecap,), jnp.int32),
        edge_mask=jnp.zeros((ecap,), bool),
        node=jnp.zeros((budget,), jnp.int32),
        node_count=jnp.zeros((), jnp.int32),
        y=jnp.zeros((self.bs,), jnp.int32),
        batch_size=self.bs,
        edge_hop_offsets=tuple(edge_hop_offsets(self.bs, self.fanouts)),
    )

  def _build(self):
    feature = self.feature
    model, tx, axis = self.model, self.tx, self.axis
    fanouts, bs = self.fanouts, self.bs
    offs = tuple(edge_hop_offsets(bs, fanouts))

    offloaded = feature.cold_array is not None

    def device_step(params, opt_state, table, scratch, seeds, n_valid,
                    key, feat_shard, labels, indptr, indices,
                    *cold_shard):
      table = table[0]
      scratch = scratch[0]
      key = jax.random.fold_in(key[0], jax.lax.axis_index(axis))
      one_hop = lambda ids, fanout, k, mask: sample_neighbors(
          indptr, indices, ids, fanout, k, seed_mask=mask)
      out, table, scratch = multihop_sample(
          one_hop, seeds, n_valid[0], fanouts, key, table, scratch)
      node_valid = jnp.arange(out['node'].shape[0]) < out['node_count']
      x = feature.lookup_local(
          feat_shard, jnp.maximum(out['node'], 0), node_valid,
          axis_name=axis,
          cold_shard=cold_shard[0] if cold_shard else None)
      y = jnp.take(labels, jnp.maximum(out['batch'], 0)[:bs])
      batch = Batch(
          x=x, row=out['row'], col=out['col'], edge_mask=out['edge_mask'],
          node=out['node'], node_count=out['node_count'], y=y,
          batch_size=bs, edge_hop_offsets=offs)

      def loss_fn(p):
        logits = model.apply(p, batch)
        mask = jnp.arange(bs) < n_valid[0]
        losses = optax.softmax_cross_entropy_with_integer_labels(
            logits, y)
        return (jnp.where(mask, losses, 0).sum()
                / jnp.maximum(mask.sum(), 1))

      loss, grads = jax.value_and_grad(loss_fn)(params)
      # DDP allreduce (mean over devices), riding ICI
      grads = jax.lax.pmean(grads, axis)
      loss = jax.lax.pmean(loss, axis)
      updates, opt_state = tx.update(grads, opt_state, params)
      params = optax.apply_updates(params, updates)
      return (params, opt_state, table[None], scratch[None],
              loss[None])

    fn = jax.shard_map(
        device_step, mesh=self.mesh,
        in_specs=(P(), P(), P(self.axis), P(self.axis), P(self.axis),
                  P(self.axis), P(self.axis), P(self.axis), P(), P(),
                  P())
        + ((P(self.axis),) if offloaded else ()),
        out_specs=(P(), P(), P(self.axis), P(self.axis), P(self.axis)),
        check_vma=False)

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def step(params, opt_state, tables, scratches, seeds, n_valid, keys,
             feat_array, labels, indptr, indices, *cold):
      # feat/cold/labels/topology ride as explicit args: (a) committed
      # shardings — incl. the cold block's pinned_host memory kind —
      # are preserved (a closed-over array would be re-laid-out as a
      # default-memory constant), and (b) a closed-over array becomes a
      # jit CONSTANT, which the axon remote-compile path ships in the
      # compile request body — hundreds of MB of topology in the
      # payload (observed HTTP 413 at products scale)
      return fn(params, opt_state, tables, scratches, seeds, n_valid,
                keys, feat_array, labels, indptr, indices, *cold)

    return step

  def __call__(self, params, opt_state, seeds, n_valid_per_device, keys):
    """seeds: [n_dev * bs] shard-major; n_valid_per_device: [n_dev];
    keys: [n_dev] PRNG keys. Returns (params, opt_state, loss[n_dev])."""
    n_dev = self.mesh.shape[self.axis]
    seeds = jax.device_put(
        jnp.asarray(seeds, jnp.int32),
        NamedSharding(self.mesh, P(self.axis)))
    n_valid = jax.device_put(
        jnp.asarray(n_valid_per_device, jnp.int32),
        NamedSharding(self.mesh, P(self.axis)))
    extra = ((self.feature.cold_array,)
             if self.feature.cold_array is not None else ())
    params, opt_state, self.tables, self.scratches, loss = self._step_fn(
        params, opt_state, self.tables, self.scratches, seeds, n_valid,
        keys, self.feature.array, self.labels, self._indptr,
        self._indices, *extra)
    return params, opt_state, loss

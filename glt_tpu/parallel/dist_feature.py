"""Sharded feature store with collective lookup — DistFeature, the SPMD way.

Reference: graphlearn_torch/python/distributed/dist_feature.py:69-452. The
reference looks up remote node features either by async RPC to the owner
(dist_feature.py:380-430) or — the design SURVEY.md §7 says to keep — by a
gloo all2all exchange (ids out, features back, dist_feature.py:270-366).
Here that exchange is the native formulation: the feature table is one
jax array row-sharded over the mesh ('range partition book': owner =
id // rows_per_shard), and lookup inside shard_map is

    bucket ids by owner -> all_to_all -> local gather -> all_to_all back
    -> positional un-bucket (the stitch, stitch_sample_results.cu analog)

with fixed-capacity buckets so shapes stay static. Collectives ride ICI.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import as_numpy


def _more_rounds_global(more: bool) -> bool:
  """Agree a drain-loop continuation across processes. The serving path
  no longer needs this (lookup_local drains in-program with a pmax'd
  round count); kept for host-side analysis/benchmarks."""
  if jax.process_count() == 1:
    return more
  from jax.experimental import multihost_utils
  return bool(np.asarray(multihost_utils.process_allgather(
      jnp.asarray([1 if more else 0]))).max())


def overflow_lanes(owner_key: np.ndarray, n_shards: int, b: int,
                   cap: int) -> np.ndarray:
  """Host replay of the device bucketing: True where a valid request
  (owner_key < n_shards) ranks past its per-owner bucket capacity for
  its B-lane device block. The SERVING path no longer uses this (the
  drain runs in-program, see lookup_local); it remains for round-count
  analysis (benchmarks/bench_bucket_drain.py predicts the grid with
  it)."""
  over = np.zeros(owner_key.shape[0], bool)
  for lo in range(0, owner_key.shape[0], b):
    ok = owner_key[lo:lo + b]
    order = np.argsort(ok, kind='stable')
    osort = ok[order]
    counts = np.bincount(np.minimum(osort, n_shards),
                         minlength=n_shards + 1)[:n_shards]
    offsets = np.cumsum(counts) - counts
    pos = np.arange(ok.shape[0]) - offsets[
        np.minimum(osort, n_shards - 1)]
    blk = np.zeros(ok.shape[0], bool)
    blk[order] = (osort < n_shards) & (pos >= cap)
    over[lo:lo + b] = blk
  return over


def require_device_resident(store, ctx: str) -> None:
  """Fused SPMD train steps gather features with ``lookup_local`` inside
  one jitted program, where the host-spill phase can never run — a
  spilled store there would silently train on zero vectors for every
  cold row. Trainers call this up front to fail loudly instead."""
  if store is None:
    return
  if getattr(store, '_spill', False) and \
      getattr(store, 'cold_array', None) is None:
    raise NotImplementedError(
        f'{ctx}: this train step runs sampling+gather+update as one '
        'jitted SPMD program and cannot resolve host-spilled (cold) '
        'feature rows; use host_offload=True (pinned-host cold block '
        'served inside the program via compute_on), a device-resident '
        'store (split_ratio=1.0), or the loader-driven path '
        '(DistLoader / NodeLoader collate, which resolves cold rows '
        'on host between device calls)')
  # bucket_cap needs NO rejection here: lookup_local drains capped
  # buckets in-program (round loop + pmax round count), so fused steps
  # serve overflow lanes exactly — including combined with host-offload


class ShardedFeature:
  """[N, D] feature table row-sharded over one mesh axis.

  The partition book is the range rule: owner(id) = id // rows_per_shard
  (a RangePartitionBook with uniform bounds, reference
  partition/partition_book.py:6-47).
  """

  def __init__(self, feats, mesh: Mesh, axis: str = 'data', dtype=None,
               row_gather=None, split_ratio: float = 1.0,
               bucket_cap: int = 0, host_offload: Optional[bool] = None):
    # row_gather: optional (shard [R, D], rows [M]) -> [M, D] override
    # for the serving gather — tests inject the interpret-mode Pallas
    # kernel; on TPU GLT_USE_PALLAS=1 selects it automatically
    self._row_gather = row_gather
    feats = as_numpy(feats)
    self.mesh = mesh
    self.axis = axis
    n_shards = mesh.shape[axis]
    n = feats.shape[0]
    self.num_rows = n
    self.rows_per_shard = math.ceil(n / n_shards)
    pad = self.rows_per_shard * n_shards - n
    if pad:
      feats = np.concatenate(
          [feats, np.zeros((pad,) + feats.shape[1:], feats.dtype)])
    if dtype is not None:
      feats = feats.astype(dtype)
    self.feature_dim = feats.shape[1]
    # bucket_cap < B caps each per-peer request bucket: the two
    # all_to_alls then move n_shards*C elements per device instead of
    # the [P, B] worst case (VERDICT r2: P-times the necessary ICI
    # bytes). Overflowed requests are drained by lookup() through the
    # SAME compiled program — the bucketing is deterministic, so the
    # host replays it to decide how many rounds are needed (usually 1).
    self.bucket_cap = int(bucket_cap)
    # cap is baked into the shard_map trace on first lookup; mutating it
    # later would desync the host drain from the compiled routing —
    # lookup() records the traced value and rejects mismatches
    self._traced_cap = None
    # host spill (reference unified_tensor.cu:202-231 pinned-CPU shard):
    # rows [hot_count, rows_per_shard) of EVERY shard stay host-side;
    # the uniform per-shard split keeps hot-ness arithmetic, so the
    # requester resolves cold lanes without any device flag. Cold
    # blocks are numpy views of ``feats`` — no extra host copy.
    self.split_ratio = float(split_ratio)
    self.hot_count = (self.rows_per_shard if self.split_ratio >= 1.0
                      else max(1, int(round(self.rows_per_shard
                                            * self.split_ratio))))
    self._spill = self.hot_count < self.rows_per_shard
    if self._spill:
      self._host_cold = [
          feats[p * self.rows_per_shard + self.hot_count:
                (p + 1) * self.rows_per_shard]
          for p in range(n_shards)]
      hot = np.concatenate([
          feats[p * self.rows_per_shard:
                p * self.rows_per_shard + self.hot_count]
          for p in range(n_shards)])
    else:
      self._host_cold = None
      hot = feats
    self.array = jax.device_put(
        hot, NamedSharding(mesh, P(axis)))
    # Host-offload: the cold block lives in PINNED HOST memory as a jax
    # array and is gathered INSIDE the compiled program via
    # compute_on('device_host') — the TPU-native analog of the
    # reference's UVA zero-copy CPU shard (unified_tensor.cu:202-231:
    # cudaHostRegisterMapped + device-side GatherTensorKernel reads
    # across PCIe). This is what lets fused SPMD train steps consume
    # spilled stores; without it cold rows resolve in lookup()'s host
    # phase between device calls. Default: on when spilling (opt out
    # with GLT_HOST_OFFLOAD=0 or host_offload=False).
    from ..utils.offload import maybe_pin_host, offload_requested
    self.cold_array = None
    if offload_requested(host_offload, self._spill) and self._spill:
      self.cold_array = maybe_pin_host(
          lambda: jax.device_put(
              np.concatenate(self._host_cold),
              NamedSharding(mesh, P(axis), memory_kind='pinned_host')),
          host_offload)
      if self.cold_array is not None:
        # the numpy blocks are the host-phase path's state; keeping
        # them would double the cold footprint in host RAM
        self._host_cold = None
    # compiled once; rebuilding shard_map per call would re-trace
    if self.cold_array is not None:
      self._lookup_fn = jax.jit(jax.shard_map(
          lambda shard, cold_shard, i, v: self.lookup_local(
              shard, i, v, cold_shard=cold_shard),
          mesh=self.mesh,
          in_specs=(P(self.axis),) * 4,
          out_specs=P(self.axis), check_vma=False))
    else:
      self._lookup_fn = jax.jit(jax.shard_map(
          lambda shard, i, v: self.lookup_local(shard, i, v),
          mesh=self.mesh,
          in_specs=(P(self.axis), P(self.axis), P(self.axis)),
          out_specs=P(self.axis), check_vma=False))

  # -- in-shard lookup ---------------------------------------------------

  def lookup_local(self, local_shard: jax.Array, ids: jax.Array,
                   valid: jax.Array, axis_name: Optional[str] = None,
                   cold_shard: Optional[jax.Array] = None) -> jax.Array:
    """Gather rows for global ``ids`` from inside shard_map.

    Args:
      local_shard: this device's [rows_per_shard, D] block (the shard_map
        view of ``self.array``).
      ids: [B] global row ids requested by this device.
      valid: [B] mask.
      axis_name: mesh axis to exchange over (defaults to ``self.axis``).
      cold_shard: this device's pinned-host [cold_count, D] block when
        host-offloading; cold lanes are then served in-program by a
        compute_on('device_host') gather instead of lookup()'s host
        phase. Fused train steps pass ``self.cold_array``'s shard here.

    Returns [B, D]; invalid slots are zero.

    With ``bucket_cap`` set the overflow drain runs IN-PROGRAM: the
    round count is the mesh-wide max bucket occupancy over the cap
    (pmax — identical everywhere, so the collectives inside the
    lax.while_loop stay aligned) and round k ships the requests ranked
    [k*cap, (k+1)*cap) within each bucket. No host replay, no
    cross-process agreement round — fused SPMD train steps can use
    capped stores directly.
    """
    from .collectives import (BucketMeta, all_to_all, bucket_payload,
                              capped_drain, unbucket)
    ax = axis_name or self.axis
    n_shards = self.mesh.shape[self.axis]
    b = ids.shape[0]
    owner = jnp.clip(ids // self.rows_per_shard, 0, n_shards - 1)
    owner = jnp.where(valid, owner, n_shards)  # pads sort last
    order = jnp.argsort(owner, stable=True)    # group requests by owner
    owner_sorted = jnp.take(owner, order)
    counts = jnp.bincount(jnp.minimum(owner_sorted, n_shards),
                          length=n_shards + 1)[:n_shards]
    offsets = jnp.cumsum(counts) - counts
    pos_in_bucket = jnp.arange(b) - jnp.take(
        offsets, jnp.minimum(owner_sorted, n_shards - 1))
    meta = BucketMeta(order, owner_sorted, pos_in_bucket)
    # fixed-capacity request buckets [n_shards, C] (C = B by default)
    cap = (self.bucket_cap if 0 < self.bucket_cap < b else b)

    def round_out(base):
      """One bucket-exchange-serve-unbucket pass over the requests
      ranked [base, base+cap) per bucket; other lanes come back 0."""
      req = bucket_payload(ids, meta, n_shards, fill_value=-1,
                           capacity=cap, round_offset=base)
      # exchange requests: row p of the result = what peer p asked us
      req_in = all_to_all(req, ax)
      # serve from the local block (hot rows only when spilling; cold
      # lanes return zero and the host phase in lookup() fills them)
      my_index = jax.lax.axis_index(ax)
      local_rows = req_in - my_index * self.rows_per_shard
      ok = (local_rows >= 0) & (local_rows < self.hot_count) & \
          (req_in >= 0)
      safe_rows = jnp.clip(local_rows, 0, self.hot_count - 1)
      # one DMA descriptor per served row instead of XLA's
      # per-output-element gather (the UnifiedTensor GatherTensorKernel
      # analogue, done the TPU way), when enabled
      from ..ops.pallas_kernels import resolve_row_gather
      gather = resolve_row_gather(self._row_gather)
      if gather is not None:
        rows_out = gather(local_shard, safe_rows.reshape(-1)).reshape(
            safe_rows.shape + (self.feature_dim,))
      else:
        rows_out = jnp.take(local_shard, safe_rows, axis=0)
      served = jnp.where(ok[..., None], rows_out, 0)
      if cold_shard is not None and self._spill:
        # serve the owner's SPILLED rows from pinned host memory
        # without leaving the program: index arithmetic stays on
        # device, the gather itself runs host-side (raw indexing —
        # bounds logic would materialize device-space constants inside
        # the host region)
        from jax.experimental import compute_on
        cold_count = self.rows_per_shard - self.hot_count
        cold_ok = (local_rows >= self.hot_count) & \
            (local_rows < self.rows_per_shard) & (req_in >= 0)
        cold_rows_idx = jnp.clip(local_rows - self.hot_count, 0,
                                 cold_count - 1)
        idx_h = jax.device_put(cold_rows_idx.reshape(-1),
                               jax.memory.Space.Host)
        with compute_on.compute_on('device_host'):
          cold_out = cold_shard[idx_h]
        cold_out = jax.device_put(
            cold_out, jax.memory.Space.Device).reshape(
                cold_rows_idx.shape + (self.feature_dim,))
        served = jnp.where(cold_ok[..., None],
                           cold_out.astype(served.dtype), served)
      # responses back; row p now holds our requests served by peer p
      resp = all_to_all(served, ax)
      resp = resp.reshape(n_shards, cap, self.feature_dim)
      # positional stitch back to request order
      return unbucket(resp, meta, n_shards, round_offset=base)

    if cap >= b:
      return round_out(0)  # a single uncapped round serves everything
    return capped_drain(
        round_out, meta, n_shards, cap, b, ax,
        jnp.zeros((b, self.feature_dim), local_shard.dtype))

  def _cold_values_host(self, nodes: np.ndarray, valid: np.ndarray):
    """The host cold-row gather core shared by the lookup() host phase
    and the streaming stager: range-rule arithmetic finds the cold
    lanes (owner = id // rows_per_shard, cold = local >= hot_count),
    values come from the per-partition ``_host_cold`` blocks. Returns
    ([..., D] values with zeros on non-cold lanes, any_cold)."""
    n_shards = self.mesh.shape[self.axis]
    owner = np.clip(nodes // self.rows_per_shard, 0, n_shards - 1)
    local = nodes - owner * self.rows_per_shard
    cold = valid & (local >= self.hot_count) & (nodes >= 0) \
        & (nodes < self.num_rows)
    np_dtype = np.dtype(self.array.dtype)
    out = np.zeros(nodes.shape + (self.feature_dim,), np_dtype)
    lanes = np.nonzero(cold)
    own = owner[lanes]
    for p in np.unique(own):
      m = tuple(ax[own == p] for ax in lanes)
      out[m] = self._host_cold[int(p)][
          local[m] - self.hot_count].astype(np_dtype)
    return out, bool(lanes[0].size)

  def stage_cold_rows(self, nodes: np.ndarray,
                      counts: np.ndarray) -> np.ndarray:
    """Host-gather the SPILLED rows for pre-sampled node stacks — the
    staging half of the superstep cold-row streaming pipeline
    (parallel/train.py). Cold-ness is arithmetic under the range rule,
    so no device round-trip is needed to find the lanes.

    Args:
      nodes: [..., n_shards * B] global node ids, shard-major blocks
        (device d's B sampled slots at [..., d*B:(d+1)*B]).
      counts: [..., n_shards] valid node counts per device block.

    Returns [..., n_shards * B, D] numpy: cold-row values on cold valid
    lanes, zeros elsewhere — exactly the lanes the in-program hot lookup
    (``lookup_local`` without a cold shard) returns as zero, so the
    consumer merges with one elementwise add.
    """
    if self._host_cold is None:
      raise ValueError(
          'stage_cold_rows serves host-spilled stores without a '
          'pinned-host cold block; this store resolves cold rows '
          'in-program (cold_array) or is fully device-resident')
    nodes = as_numpy(nodes).astype(np.int64)
    counts = as_numpy(counts)
    n_shards = self.mesh.shape[self.axis]
    nb = nodes.shape[-1]
    b = nb // n_shards
    lane = np.arange(nb) % b
    dev = np.arange(nb) // b
    valid = lane < counts[..., dev]
    return self._cold_values_host(nodes, valid)[0]

  def lookup(self, ids, valid=None) -> jax.Array:
    """Whole-mesh lookup from the host side: ids [n_shards * B] laid out
    shard-major; returns globally-sharded [n_shards * B, D]. Capped
    stores drain their overflow inside the compiled program (see
    lookup_local) — one call regardless of skew."""
    if self._traced_cap is None:
      self._traced_cap = self.bucket_cap
    elif self.bucket_cap != self._traced_cap:
      raise RuntimeError(
          f'bucket_cap changed from {self._traced_cap} to '
          f'{self.bucket_cap} after the first lookup compiled it in; '
          'the cached program would keep routing with the old cap. '
          'Set bucket_cap before the first lookup, or build a new '
          'ShardedFeature.')
    ids_np = as_numpy(ids).astype(np.int64)
    ids = jnp.asarray(ids_np)
    if valid is None:
      valid = jnp.ones(ids.shape, bool)
    n_shards = self.mesh.shape[self.axis]
    assert ids.shape[0] % n_shards == 0
    out = self._call_lookup_fn(ids, valid)
    if not self._spill or self.cold_array is not None:
      # host-offloaded stores serve cold lanes inside the program
      return out
    return self._resolve_cold_sharded(out, ids_np,
                                      as_numpy(valid).astype(bool),
                                      n_shards)

  def _call_lookup_fn(self, ids, valid):
    if self.cold_array is not None:
      return self._lookup_fn(self.array, self.cold_array, ids, valid)
    return self._lookup_fn(self.array, ids, valid)

  def _resolve_cold_sharded(self, out, ids_np, valid_np, n_shards):
    """Host phase: cold-ness is arithmetic under the range rule, so the
    requester finds its cold lanes without any device round-trip and
    merges them as one sharded add (cold lanes are zero in ``out``)."""
    delta, any_cold = self._cold_values_host(ids_np, valid_np)
    if not any_cold:
      return out
    delta_arr = jax.device_put(delta.astype(np.dtype(out.dtype)),
                               out.sharding)
    return out + delta_arr

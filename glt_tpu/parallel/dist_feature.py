"""Sharded feature store with collective lookup — DistFeature, the SPMD way.

Reference: graphlearn_torch/python/distributed/dist_feature.py:69-452. The
reference looks up remote node features either by async RPC to the owner
(dist_feature.py:380-430) or — the design SURVEY.md §7 says to keep — by a
gloo all2all exchange (ids out, features back, dist_feature.py:270-366).
Here that exchange is the native formulation: the feature table is one
jax array row-sharded over the mesh ('range partition book': owner =
id // rows_per_shard), and lookup inside shard_map is

    bucket ids by owner -> all_to_all -> local gather -> all_to_all back
    -> positional un-bucket (the stitch, stitch_sample_results.cu analog)

with fixed-capacity buckets so shapes stay static. Collectives ride ICI.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import as_numpy


def require_device_resident(store, ctx: str) -> None:
  """Fused SPMD train steps gather features with ``lookup_local`` inside
  one jitted program, where the host-spill phase can never run — a
  spilled store there would silently train on zero vectors for every
  cold row. Trainers call this up front to fail loudly instead."""
  if store is not None and getattr(store, '_spill', False):
    raise NotImplementedError(
        f'{ctx}: this train step runs sampling+gather+update as one '
        'jitted SPMD program and cannot resolve host-spilled (cold) '
        'feature rows; use a device-resident store (split_ratio=1.0) '
        'or the loader-driven path (DistLoader / NodeLoader collate, '
        'which resolves cold rows on host between device calls)')


class ShardedFeature:
  """[N, D] feature table row-sharded over one mesh axis.

  The partition book is the range rule: owner(id) = id // rows_per_shard
  (a RangePartitionBook with uniform bounds, reference
  partition/partition_book.py:6-47).
  """

  def __init__(self, feats, mesh: Mesh, axis: str = 'data', dtype=None,
               row_gather=None, split_ratio: float = 1.0):
    # row_gather: optional (shard [R, D], rows [M]) -> [M, D] override
    # for the serving gather — tests inject the interpret-mode Pallas
    # kernel; on TPU GLT_USE_PALLAS=1 selects it automatically
    self._row_gather = row_gather
    feats = as_numpy(feats)
    self.mesh = mesh
    self.axis = axis
    n_shards = mesh.shape[axis]
    n = feats.shape[0]
    self.num_rows = n
    self.rows_per_shard = math.ceil(n / n_shards)
    pad = self.rows_per_shard * n_shards - n
    if pad:
      feats = np.concatenate(
          [feats, np.zeros((pad,) + feats.shape[1:], feats.dtype)])
    if dtype is not None:
      feats = feats.astype(dtype)
    self.feature_dim = feats.shape[1]
    # host spill (reference unified_tensor.cu:202-231 pinned-CPU shard):
    # rows [hot_count, rows_per_shard) of EVERY shard stay host-side;
    # the uniform per-shard split keeps hot-ness arithmetic, so the
    # requester resolves cold lanes without any device flag. Cold
    # blocks are numpy views of ``feats`` — no extra host copy.
    self.split_ratio = float(split_ratio)
    self.hot_count = (self.rows_per_shard if self.split_ratio >= 1.0
                      else max(1, int(round(self.rows_per_shard
                                            * self.split_ratio))))
    self._spill = self.hot_count < self.rows_per_shard
    if self._spill:
      self._host_cold = [
          feats[p * self.rows_per_shard + self.hot_count:
                (p + 1) * self.rows_per_shard]
          for p in range(n_shards)]
      hot = np.concatenate([
          feats[p * self.rows_per_shard:
                p * self.rows_per_shard + self.hot_count]
          for p in range(n_shards)])
    else:
      self._host_cold = None
      hot = feats
    self.array = jax.device_put(
        hot, NamedSharding(mesh, P(axis)))
    # compiled once; rebuilding shard_map per call would re-trace
    self._lookup_fn = jax.jit(jax.shard_map(
        lambda shard, i, v: self.lookup_local(shard, i, v),
        mesh=self.mesh,
        in_specs=(P(self.axis), P(self.axis), P(self.axis)),
        out_specs=P(self.axis), check_vma=False))

  # -- in-shard lookup ---------------------------------------------------

  def lookup_local(self, local_shard: jax.Array, ids: jax.Array,
                   valid: jax.Array, axis_name: Optional[str] = None
                   ) -> jax.Array:
    """Gather rows for global ``ids`` from inside shard_map.

    Args:
      local_shard: this device's [rows_per_shard, D] block (the shard_map
        view of ``self.array``).
      ids: [B] global row ids requested by this device.
      valid: [B] mask.
      axis_name: mesh axis to exchange over (defaults to ``self.axis``).

    Returns [B, D]; invalid slots are zero.
    """
    ax = axis_name or self.axis
    n_shards = self.mesh.shape[self.axis]
    b = ids.shape[0]
    owner = jnp.clip(ids // self.rows_per_shard, 0, n_shards - 1)
    owner = jnp.where(valid, owner, n_shards)  # pads sort last
    order = jnp.argsort(owner, stable=True)    # group requests by owner
    ids_sorted = jnp.take(ids, order)
    owner_sorted = jnp.take(owner, order)
    counts = jnp.bincount(jnp.minimum(owner_sorted, n_shards),
                          length=n_shards + 1)[:n_shards]
    offsets = jnp.cumsum(counts) - counts
    pos_in_bucket = jnp.arange(b) - jnp.take(
        offsets, jnp.minimum(owner_sorted, n_shards - 1))
    # fixed-capacity request buckets [n_shards, B]
    sink_row, sink_col = n_shards, 0
    brow = jnp.where(owner_sorted < n_shards, owner_sorted, sink_row)
    req = jnp.full((n_shards + 1, b), -1, ids.dtype)
    req = req.at[brow, jnp.where(owner_sorted < n_shards,
                                 pos_in_bucket, sink_col)].set(ids_sorted)
    req = req[:n_shards]
    # exchange requests: row p of the result = what peer p asked us for
    req_in = jax.lax.all_to_all(req, ax, split_axis=0, concat_axis=0,
                                tiled=False)
    req_in = req_in.reshape(n_shards, b)
    # serve from the local block (hot rows only when spilling; cold
    # lanes return zero and the host phase in lookup() fills them)
    my_index = jax.lax.axis_index(ax)
    local_rows = req_in - my_index * self.rows_per_shard
    ok = (local_rows >= 0) & (local_rows < self.hot_count) & \
        (req_in >= 0)
    safe_rows = jnp.clip(local_rows, 0, self.hot_count - 1)
    # one DMA descriptor per served row instead of XLA's
    # per-output-element gather (the UnifiedTensor GatherTensorKernel
    # analogue, done the TPU way), when enabled
    from ..ops.pallas_kernels import resolve_row_gather
    gather = resolve_row_gather(self._row_gather)
    if gather is not None:
      rows_out = gather(local_shard, safe_rows.reshape(-1)).reshape(
          safe_rows.shape + (self.feature_dim,))
    else:
      rows_out = jnp.take(local_shard, safe_rows, axis=0)
    served = jnp.where(ok[..., None], rows_out, 0)
    # send responses back; row p now holds our requests served by peer p
    resp = jax.lax.all_to_all(served, ax, split_axis=0, concat_axis=0,
                              tiled=False)
    resp = resp.reshape(n_shards, b, self.feature_dim)
    # positional stitch back to request order
    gathered = resp[jnp.minimum(owner_sorted, n_shards - 1), pos_in_bucket]
    gathered = jnp.where((owner_sorted < n_shards)[:, None], gathered, 0)
    out = jnp.zeros_like(gathered)
    out = out.at[order].set(gathered)
    return out

  def lookup(self, ids, valid=None) -> jax.Array:
    """Whole-mesh lookup from the host side: ids [n_shards * B] laid out
    shard-major; returns globally-sharded [n_shards * B, D]."""
    ids_np = as_numpy(ids).astype(np.int64)
    ids = jnp.asarray(ids_np)
    if valid is None:
      valid = jnp.ones(ids.shape, bool)
    n_shards = self.mesh.shape[self.axis]
    assert ids.shape[0] % n_shards == 0
    out = self._lookup_fn(self.array, ids, valid)
    if not self._spill:
      return out
    # host phase: cold-ness is arithmetic under the range rule, so the
    # requester finds its cold lanes without any device round-trip and
    # merges them as one sharded add (cold lanes are zero in ``out``)
    valid_np = as_numpy(valid).astype(bool)
    owner = np.clip(ids_np // self.rows_per_shard, 0, n_shards - 1)
    local_row = ids_np - owner * self.rows_per_shard
    cold = valid_np & (local_row >= self.hot_count) & \
        (ids_np >= 0) & (ids_np < self.num_rows)
    if not cold.any():
      return out
    lanes = np.nonzero(cold)[0]
    np_dtype = np.dtype(out.dtype)
    delta = np.zeros((ids_np.shape[0], self.feature_dim), np_dtype)
    for p in np.unique(owner[lanes]):
      m = lanes[owner[lanes] == p]
      delta[m] = self._host_cold[int(p)][
          local_row[m] - self.hot_count].astype(np_dtype)
    delta_arr = jax.device_put(delta, out.sharding)
    return out + delta_arr

from .mesh import make_mesh, replicated, row_sharded
from .dist_feature import ShardedFeature
from .train import SPMDSageTrainStep

__all__ = ['make_mesh', 'replicated', 'row_sharded', 'ShardedFeature',
           'SPMDSageTrainStep']

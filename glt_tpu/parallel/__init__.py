from .mesh import make_mesh, replicated, row_sharded
from .dist_feature import ShardedFeature
from .train import SPMDSageTrainStep

__all__ = ['make_mesh', 'replicated', 'row_sharded', 'ShardedFeature',
           'SPMDSageTrainStep']
from . import multihost
from .collectives import (all_to_all, bucket_by_owner, bucket_payload,
                          sharded_segment_mean,
                          sharded_segment_mean_scattered, unbucket)

__all__ += ['multihost', 'all_to_all', 'bucket_by_owner',
            'bucket_payload', 'sharded_segment_mean',
            'sharded_segment_mean_scattered', 'unbucket']

"""Device-mesh helpers.

The reference's process-group topology (worker groups over
TensorPipe/NCCL, distributed/dist_context.py) maps on TPU to a
jax.sharding.Mesh. The default single-axis 'data' mesh carries both data
parallelism (gradient psum = the DDP allreduce) and graph/feature shard
parallelism (all_to_all = the reference's cross-partition rpc fabric,
SURVEY.md §2.3).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_devices: Optional[int] = None,
              axis_names: Sequence[str] = ('data',)) -> Mesh:
  devs = jax.devices()
  n = num_devices or len(devs)
  assert n <= len(devs), f'requested {n} devices, have {len(devs)}'
  shape = (n,) if len(axis_names) == 1 else None
  assert shape is not None, 'multi-axis meshes: pass explicit device grid'
  return Mesh(np.array(devs[:n]).reshape(shape), axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
  return NamedSharding(mesh, P())


def row_sharded(mesh: Mesh, axis: str = 'data') -> NamedSharding:
  return NamedSharding(mesh, P(axis))

"""Multi-host (DCN) helpers.

The reference scales out with one process per machine joined by
TensorPipe/NCCL rendezvous (SURVEY.md §2.3). The TPU equivalent is
jax.distributed: one process per host, devices fused into one global
mesh, ICI within a slice and DCN across slices handled by XLA. These
helpers cover the two framework needs:

  * initialize() — process-group bootstrap. With explicit args it calls
    jax.distributed.initialize directly; with no args it auto-initializes
    when a cluster environment is detectable and otherwise no-ops
    loudly-documented (single-process dev boxes).
  * global_from_local(mesh, local, axis) — assemble a mesh-sharded
    global array where THIS process contributes only its local block
    (jax.make_array_from_process_local_data), so a DistGraph/DistFeature
    can be built per-host from that host's partition only — no rank
    ever materializes the whole graph, exactly like the reference's
    per-rank partition loading.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.env import raw as raw_env

logger = logging.getLogger(__name__)

_CLUSTER_ENVS = (
    'JAX_COORDINATOR_ADDRESS', 'COORDINATOR_ADDRESS',
    'MEGASCALE_COORDINATOR_ADDRESS', 'TPU_WORKER_HOSTNAMES',
)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
  """Bootstrap jax.distributed.

  Explicit args are forwarded directly. With no args, the cluster is
  auto-detected: when a known coordinator env is present (or jax's own
  cluster detection succeeds) jax.distributed.initialize() runs with
  auto-detection; on a plain single-process machine this is a no-op and
  says so at debug level — it never silently skips a *detectable*
  cluster.
  """
  if (coordinator_address is not None or num_processes is not None
      or process_id is not None):
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)
    return
  if any(raw_env(k) for k in _CLUSTER_ENVS):
    jax.distributed.initialize()
    return
  logger.debug('multihost.initialize: no cluster environment detected; '
               'running single-process')


def global_from_local(mesh: Mesh, local: np.ndarray, axis: str = 'data',
                      memory_kind: str | None = None):
  """Build the [n_shards, ...] mesh-sharded stack where this process
  supplies blocks only for its own devices.

  ``local``: [n_local_shards, ...] — this process's blocks, ordered by
  its device order along the axis. Single-process: equals a plain
  device_put of the full stack. ``memory_kind='pinned_host'`` places
  the shards in host memory (the offloaded cold-block store).
  """
  sharding = NamedSharding(mesh, P(axis), memory_kind=memory_kind)
  if jax.process_count() == 1:
    return jax.device_put(local, sharding)
  n = mesh.shape[axis]
  global_shape = (n,) + tuple(local.shape[1:])
  return jax.make_array_from_process_local_data(
      sharding, local, global_shape=global_shape)

"""Multi-host (DCN) helpers.

The reference scales out with one process per machine joined by
TensorPipe/NCCL rendezvous (SURVEY.md §2.3). The TPU equivalent is
jax.distributed: one process per host, devices fused into one global
mesh, ICI within a slice and DCN across slices handled by XLA. These
helpers cover the two framework needs:

  * initialize() — process-group bootstrap (MASTER_ADDR-style envs or
    explicit coordinator), safe to call once per process.
  * global_from_local(mesh, local, axis) — assemble a mesh-sharded
    global array where THIS process contributes only its local block
    (jax.make_array_from_process_local_data), so a DistGraph/DistFeature
    can be built per-host from that host's partition only — no rank
    ever materializes the whole graph, exactly like the reference's
    per-rank partition loading.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
  """Bootstrap jax.distributed (no-op for a single process)."""
  if num_processes in (None, 1) and coordinator_address is None:
    return
  jax.distributed.initialize(
      coordinator_address=coordinator_address,
      num_processes=num_processes, process_id=process_id)


def process_mesh_info(mesh: Mesh, axis: str = 'data'):
  """(num_shards, shards_owned_by_this_process) along ``axis``."""
  n = mesh.shape[axis]
  devices = mesh.devices.reshape(-1)
  mine = [i for i, d in enumerate(devices)
          if d.process_index == jax.process_index()]
  return n, mine


def global_from_local(mesh: Mesh, local: np.ndarray, axis: str = 'data'):
  """Build the [n_shards, ...] mesh-sharded stack where this process
  supplies blocks only for its own devices.

  ``local``: [n_local_shards, ...] — this process's blocks, ordered by
  its device order along the axis. Single-process: equals a plain
  device_put of the full stack.
  """
  sharding = NamedSharding(mesh, P(axis))
  if jax.process_count() == 1:
    return jax.device_put(local, sharding)
  n = mesh.shape[axis]
  global_shape = (n,) + tuple(local.shape[1:])
  return jax.make_array_from_process_local_data(
      sharding, local, global_shape=global_shape)

"""Headline benchmark: neighbor-sampling + induction throughput per chip.

Protocol mirrors the reference's benchmarks/api/bench_sampler.py
("Sampled Edges per secs: {} M" over ogbn-products, batch 1024, fanout
[15,10,5]): here on a synthetic products-scale graph (2.45M nodes, ~62M
directed edges) generated in-process since datasets are not downloadable
in this environment. The measured quantity is identical: valid sampled
edges per second of wall-clock, steady state, one chip.

``vs_baseline`` compares against an A100 running the reference's CUDA
sampler on the same protocol. Upstream commits no number (BASELINE.md);
we use 2.0e8 edges/s as the assumed A100 figure (order-of-magnitude from
the reference's scale_up figure) until a measured value is available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

A100_ASSUMED_EDGES_PER_SEC = 2.0e8

NUM_NODES = 2_450_000
NUM_EDGES = 62_000_000
BATCH = 1024
FANOUT = (15, 10, 5)
WARMUP = 3
ITERS = 30


def main():
  import jax
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.ops.pipeline import multihop_sample
  from glt_tpu.ops.sample import sample_neighbors
  from glt_tpu.ops.unique import dense_make_tables

  rng = np.random.default_rng(0)
  # out-degrees ~Poisson(25) (products' mean); in-degrees skewed via a
  # squared-uniform draw so dedup and gathers see hub nodes
  src = rng.integers(0, NUM_NODES, NUM_EDGES, dtype=np.int64)
  dst = (rng.random(NUM_EDGES) ** 2 * NUM_NODES).astype(np.int64) \
      % NUM_NODES
  topo = Topology(indptr=None, edge_index=np.stack([src, dst]),
                  num_nodes=NUM_NODES)
  del src, dst
  indptr = jnp.asarray(topo.indptr.astype(np.int32))
  indices = jnp.asarray(topo.indices)

  one_hop = lambda ids, fanout, key, mask: sample_neighbors(
      indptr, indices, ids, fanout, key, seed_mask=mask)

  import functools
  scan = max(int(os.environ.get('GLT_BENCH_SCAN', '4')), 1)

  @functools.partial(jax.jit, donate_argnums=(2, 3))
  def sample_batch(seeds, key, table, scratch):
    if scan > 1:
      from glt_tpu.ops.pipeline import multihop_sample_many
      outs, table, scratch = multihop_sample_many(
          one_hop, seeds, jnp.full(scan, BATCH, jnp.int32), FANOUT, key,
          table, scratch)
      return outs['num_sampled_edges'].sum(), table, scratch
    out, table, scratch = multihop_sample(
        one_hop, seeds[0], jnp.asarray(BATCH), FANOUT, key, table,
        scratch)
    return out['num_sampled_edges'].sum(), table, scratch

  table, scratch = dense_make_tables(NUM_NODES)
  seed_pool = rng.integers(0, NUM_NODES, (ITERS + WARMUP, scan, BATCH))
  keys = jax.random.split(jax.random.key(0), ITERS + WARMUP)

  edges = None
  for i in range(WARMUP):
    edges, table, scratch = sample_batch(
        jnp.asarray(seed_pool[i], jnp.int32), keys[i], table, scratch)
  jax.block_until_ready(edges)

  edge_counts = []
  t0 = time.time()
  for i in range(WARMUP, WARMUP + ITERS):
    edges, table, scratch = sample_batch(
        jnp.asarray(seed_pool[i], jnp.int32), keys[i], table, scratch)
    edge_counts.append(edges)  # stay async: no host sync in the loop
  jax.block_until_ready(edge_counts[-1])
  dt = time.time() - t0
  total_edges = int(np.sum([int(e) for e in edge_counts]))

  eps = total_edges / dt
  print(json.dumps({
      'metric': 'sampled_edges_per_sec_per_chip',
      'value': round(eps, 1),
      'unit': 'edges/s',
      'vs_baseline': round(eps / A100_ASSUMED_EDGES_PER_SEC, 4),
  }))


if __name__ == '__main__':
  main()

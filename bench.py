"""Headline benchmark: neighbor-sampling + induction throughput per chip.

Protocol mirrors the reference's benchmarks/api/bench_sampler.py
("Sampled Edges per secs: {} M" over ogbn-products, batch 1024, fanout
[15,10,5]): here on a synthetic products-scale graph (2.45M nodes, ~62M
directed edges) generated in-process since datasets are not downloadable
in this environment. The measured quantity is identical: valid sampled
edges per second of wall-clock, steady state, one chip.

``vs_baseline`` compares against an A100 running the reference's CUDA
sampler on the same protocol. Upstream commits no number (BASELINE.md);
we use 2.0e8 edges/s as the assumed A100 figure (order-of-magnitude from
the reference's scale_up figure) until a measured value is available.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Run modes
---------
``python bench.py``         supervisor: probes the backend with a cheap
                            short-timeout child first (the known axon
                            failure mode is a silent hang in backend
                            init — pay 90 s to find out, not a full
                            attempt), then runs the measurement child
                            under a HARD TOTAL BUDGET. Always emits one
                            JSON line within the budget — on failure the
                            line carries ``value: 0.0`` and an ``error``
                            field instead of a stack trace.
``python bench.py --probe`` backend probe: init jax, list devices, exit.
``python bench.py --run``   worker: the actual measurement (may hang if
                            the tunnel is wedged; the supervisor guards).

Env knobs: GLT_BENCH_BUDGET total wall-clock seconds for the supervisor
(default 900 — sized well under the driver's observed ~1500 s kill
window so the structured line always lands), GLT_BENCH_PROBE_TIMEOUT
(default 90), GLT_BENCH_TIMEOUT seconds per measurement attempt
(default: fit budget), GLT_BENCH_SCAN (batches fused per device call,
default 4), GLT_BENCH_PLATFORM (force a jax platform, e.g. ``cpu``).
"""
import json
import os
import subprocess
import sys
import time

A100_ASSUMED_EDGES_PER_SEC = 2.0e8

# protocol shapes; the GLT_BENCH_* overrides exist for smoke-testing
# the bench itself at toy scale — headline runs use the defaults
NUM_NODES = int(os.environ.get('GLT_BENCH_NODES', 2_450_000))
NUM_EDGES = int(os.environ.get('GLT_BENCH_EDGES', 62_000_000))
BATCH = int(os.environ.get('GLT_BENCH_BATCH', 1024))
FANOUT = (15, 10, 5)
WARMUP = 3
ITERS = int(os.environ.get('GLT_BENCH_ITERS', 30))

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          '.jax_cache')


def _emit(value, vs_baseline, **extra):
  print(json.dumps({
      'metric': 'sampled_edges_per_sec_per_chip',
      'value': value,
      'unit': 'edges/s',
      'vs_baseline': vs_baseline,
      **extra,
  }))
  sys.stdout.flush()


def run_worker():
  import numpy as np
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()  # axon plugin ignores JAX_PLATFORMS; config API only
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.ops.pipeline import make_dedup_tables, multihop_sample
  from glt_tpu.ops.sample import sample_neighbors

  dev = jax.devices()[0]
  print(f'# backend: {dev.platform} ({dev.device_kind})', file=sys.stderr)

  rng = np.random.default_rng(0)
  # out-degrees ~Poisson(25) (products' mean); in-degrees skewed via a
  # squared-uniform draw so dedup and gathers see hub nodes
  src = rng.integers(0, NUM_NODES, NUM_EDGES, dtype=np.int64)
  dst = (rng.random(NUM_EDGES) ** 2 * NUM_NODES).astype(np.int64) \
      % NUM_NODES
  topo = Topology(indptr=None, edge_index=np.stack([src, dst]),
                  num_nodes=NUM_NODES)
  del src, dst
  indptr = jnp.asarray(topo.indptr.astype(np.int32))
  indices = jnp.asarray(topo.indices)

  win_state = {}

  def resolved_hop_engine():
    """The hop engine the current env ACTUALLY selects (post-fallback:
    GLT_HOP_ENGINE=pallas without an importable pallas resolves to
    'window'; pallas_fused whose dedup table would blow the VMEM knob
    resolves to 'pallas') — both the hop closure and the engines{}
    labels read this, so the recorded label never claims an engine that
    didn't run. Legacy GLT_WINDOW_HOP=1 maps to 'window'."""
    from glt_tpu.ops.pipeline import hop_engine, sample_budget
    if 'GLT_HOP_ENGINE' in os.environ:
      eng = hop_engine()
      if eng == 'pallas_fused':
        from glt_tpu.ops.pallas_kernels import (fused_table_max_slots,
                                                fused_table_slots)
        if fused_table_slots(sample_budget(BATCH, list(FANOUT))) \
            > fused_table_max_slots():
          from glt_tpu.ops.pipeline import count_engine_fallback
          count_engine_fallback('pallas_fused', 'pallas',
                                'table_overflow')
          return 'pallas'
      return eng
    if os.environ.get('GLT_WINDOW_HOP', '0') in ('1', 'true'):
      return 'window'
    return 'element'

  def make_one_hop():
    """Build (hop closure, fused plan) under the CURRENT env. The
    W-padded indices copy and the true hub count are built once and
    shared across engine passes; the fused plan routes multihop_sample
    through the pallas_fused kernel family (the hop closure is then
    unused but kept so every engine shares one call shape)."""
    eng = resolved_hop_engine()
    if eng == 'element':
      return (lambda ids, fanout, key, mask: sample_neighbors(
          indptr, indices, ids, fanout, key, seed_mask=mask)), None
    win_w = int(os.environ.get('GLT_WINDOW_W', '96'))
    if win_state.get('w') != win_w:
      # hub capacity from the graph's true hub count (host, once) so
      # results stay bit-identical to the element path (ops/sample.py)
      win_state['w'] = win_w
      win_state['n_hub'] = int((np.diff(topo.indptr) > win_w).sum())
      win_state['iw'] = jnp.concatenate(
          [indices, jnp.full((win_w,), -1, indices.dtype)])
    n_hub, iw = win_state['n_hub'], win_state['iw']
    print(f'# hop engine: {eng} W={win_w} n_hub={n_hub}',
          file=sys.stderr)
    interp = False
    if eng in ('pallas', 'pallas_fused'):
      from glt_tpu.ops.pallas_kernels import interpret_default
      interp = interpret_default()
    if eng == 'pallas_fused':
      from glt_tpu.ops.pallas_kernels import fused_table_slots
      from glt_tpu.ops.pipeline import sample_budget
      from glt_tpu.ops.sample import FusedHopPlan
      plan = FusedHopPlan(
          indptr, indices, iw, win_w, n_hub,
          fused_table_slots(sample_budget(BATCH, list(FANOUT))),
          interpret=interp)
      return (lambda ids, fanout, key, mask: sample_neighbors(
          indptr, indices, ids, fanout, key, seed_mask=mask,
          window=(win_w, min(n_hub, ids.shape[0])), indices_win=iw,
          engine='pallas', interpret=interp)), plan
    return (lambda ids, fanout, key, mask: sample_neighbors(
        indptr, indices, ids, fanout, key, seed_mask=mask,
        window=(win_w, min(n_hub, ids.shape[0])), indices_win=iw,
        engine=eng, interpret=interp)), None

  import functools
  scan = max(int(os.environ.get('GLT_BENCH_SCAN', '4')), 1)

  from glt_tpu.ops.pipeline import checksum_outputs as checksum
  from glt_tpu.utils.rng import make_key

  seed_pool = rng.integers(0, NUM_NODES, (ITERS + WARMUP, scan, BATCH))

  def measure():
    """Build + time the pipeline under the CURRENT env (GLT_DEDUP /
    GLT_FUSED_HOP / GLT_HOP_ENGINE are read at trace time, so each
    call re-jits). Returns per-engine stats: steady-state edges/s,
    compile/trace wall-time of the first dispatch, the number of
    re-traces observed during the timed loop (must be 0 — any recompile
    in steady state is a shape-stability bug), and — when the cost
    analysis is available — the program's HBM bytes + FLOPs per
    dispatch (the numerators of the per-engine roofline cell)."""
    one_hop, fused_plan = make_one_hop()
    traces = {'n': 0}

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def sample_batch(seeds, key, table, scratch):
      traces['n'] += 1  # trace-time side effect; executions never bump
      if scan > 1:
        from glt_tpu.ops.pipeline import multihop_sample_many
        outs, table, scratch = multihop_sample_many(
            one_hop, seeds, jnp.full(scan, BATCH, jnp.int32), FANOUT,
            key, table, scratch, fused_plan=fused_plan)
        return (outs['num_sampled_edges'].sum(), checksum(outs), table,
                scratch)
      out, table, scratch = multihop_sample(
          one_hop, seeds[0], jnp.asarray(BATCH), FANOUT, key, table,
          scratch, fused_plan=fused_plan)
      return (out['num_sampled_edges'].sum(), checksum(out), table,
              scratch)

    table, scratch = make_dedup_tables(NUM_NODES)
    # GLT_PRNG=rbg swaps threefry for the XLA RngBitGenerator-backed
    # implementation (same knob the samplers honor, utils/rng.py)
    keys = jax.random.split(make_key(0), ITERS + WARMUP)
    # arg avals captured BEFORE the loop: table/scratch are donated, so
    # the roofline's AOT re-lower below must run on ShapeDtypeStructs
    arg_sds = tuple(
        jax.ShapeDtypeStruct(a.shape, a.dtype)
        for a in (jnp.zeros((scan, BATCH), jnp.int32), keys[0], table,
                  scratch))
    t_c0 = time.time()
    edges, sig, table, scratch = sample_batch(
        jnp.asarray(seed_pool[0], jnp.int32), keys[0], table, scratch)
    jax.block_until_ready((edges, sig))
    compile_s = time.time() - t_c0   # trace + compile + first run
    for i in range(1, WARMUP):
      edges, sig, table, scratch = sample_batch(
          jnp.asarray(seed_pool[i], jnp.int32), keys[i], table, scratch)
    jax.block_until_ready((edges, sig))
    traces_warm = traces['n']
    edge_counts, sigs = [], []
    t0 = time.time()
    for i in range(WARMUP, WARMUP + ITERS):
      edges, sig, table, scratch = sample_batch(
          jnp.asarray(seed_pool[i], jnp.int32), keys[i], table, scratch)
      edge_counts.append(edges)  # stay async: no host sync in the loop
      sigs.append(sig)
    jax.block_until_ready((edge_counts[-1], sigs[-1]))
    dt = time.time() - t0
    total_edges = int(np.sum([int(e) for e in edge_counts]))
    out = {
        'edges_per_sec': total_edges / dt,
        'compile_s': compile_s,
        'steady_recompiles': traces['n'] - traces_warm,
        'edges_per_dispatch': total_edges / ITERS,
    }
    if os.environ.get('GLT_BENCH_ROOFLINE', '1') != '0':
      # XLA cost accounting for THIS engine's program (obs.perf): the
      # AOT lower re-traces (after steady_recompiles was read — it
      # never pollutes that stat); aot_compile so the roofline quotes
      # the OPTIMIZED executable's bytes/FLOPs, not pre-fusion HLO —
      # the persistent compilation cache (configured above) makes the
      # second compile of the just-jitted program cheap
      try:
        from glt_tpu.obs.perf import instrument_compiled
        cost = instrument_compiled('bench.sample_batch', sample_batch,
                                   *arg_sds, aot_compile=True)
        if 'bytes_accessed' in cost:
          out['hbm_bytes_per_dispatch'] = cost['bytes_accessed']
        if 'flops' in cost:
          out['flops_per_dispatch'] = cost['flops']
        if 'kernel_launches' in cost:
          # HLO custom-call count (TPU) / trace-time pallas_call count
          # (interpret): the O(hops)->O(1) launch collapse of the
          # cross-hop walk is a recorded number, not a claim
          out['kernel_launches_per_dispatch'] = cost['kernel_launches']
      except Exception as e:  # cost accounting is best-effort
        print(f'# cost analysis unavailable: {e}', file=sys.stderr)
    return out

  # Engine self-selection: race the dedup variants (sort vs sort+fused)
  # and the hop-read engines when the knobs were not forced and the
  # budget hint leaves room — the headline then reports the best
  # measured variant, and `engines{}` records every contender's
  # edges/s + compile wall-time + steady-state recompile count. The
  # pallas megakernel has never run on real hardware (tunnel wedged
  # since r2), so the driver's end-of-round bench doubles as the
  # deciding experiment; it only races where it can actually compile
  # (TPU backend, pallas importable) unless GLT_HOP_ENGINE forces it.
  from glt_tpu.ops.pipeline import dedup_engine, fused_hops
  t_start = time.time()
  worker_budget = float(os.environ.get('GLT_BENCH_WORKER_BUDGET', '0'))
  engines = {}

  def hop_suffix():
    eng = resolved_hop_engine()
    return '' if eng == 'element' else '+' + eng

  base_label = (dedup_engine() + ('+fused' if fused_hops() else '')
                + hop_suffix())
  res = engines[base_label] = measure()
  eps = res['edges_per_sec']
  first_cost = time.time() - t_start
  engine_envs = {base_label: {}}  # per-contender env, for the
                                  # per-engine stage-breakdown pass

  def room_for_another():
    return (not worker_budget
            or time.time() - t_start + first_cost * 1.5 + 30
            < worker_budget)

  def race(label, env):
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
      engines[label] = measure()
      engine_envs[label] = dict(env)
    except Exception as e:  # keep the measured headline on any failure
      engines[label + '_error'] = str(e)[:200]
    finally:
      for k, v in saved.items():
        if v is None:
          os.environ.pop(k, None)
        else:
          os.environ[k] = v

  if (dedup_engine() == 'sort' and not fused_hops()
      and 'GLT_FUSED_HOP' not in os.environ
      and resolved_hop_engine() != 'pallas_fused'  # knob is inert there
      and room_for_another()):
    # hop_suffix() rides along: under a forced hop engine the raced
    # pass still runs that engine, and the label must say so
    race('sort+fused' + hop_suffix(), {'GLT_FUSED_HOP': '1'})
  if ('GLT_HOP_ENGINE' not in os.environ
      and os.environ.get('GLT_WINDOW_HOP', '0') not in ('1', 'true')
      and dev.platform == 'tpu' and room_for_another()):
    from glt_tpu.ops.pallas_kernels import pallas_available
    if pallas_available():
      # ride the best dedup config measured so far, PINNING the fused
      # knob explicitly — auto-fusing would otherwise silently stay on
      # and the label would misattribute the fused delta to pallas
      if ('sort+fused' in engines and base_label != 'sort+fused'
          and isinstance(engines['sort+fused'], dict)):
        ride_fused = (engines['sort+fused']['edges_per_sec']
                      > engines[base_label]['edges_per_sec'])
      else:
        ride_fused = fused_hops()  # what the base run actually used
      label = (dedup_engine() + ('+fused' if ride_fused else '')
               + '+pallas')
      race(label, {'GLT_HOP_ENGINE': 'pallas',
                   'GLT_FUSED_HOP': '1' if ride_fused else '0'})
      from glt_tpu.ops.pallas_kernels import (fused_table_max_slots,
                                              fused_table_slots)
      from glt_tpu.ops.pipeline import sample_budget
      fused_fits = (fused_table_slots(sample_budget(BATCH, list(FANOUT)))
                    <= fused_table_max_slots())
      if fused_fits and room_for_another():
        # the fully-fused pipeline: sample + dedup in one kernel, the
        # sort+fused label contract implemented in VMEM. The walk knob
        # is PINNED per contender so each label names the form that
        # actually ran: per-hop kernels vs the cross-hop walk
        race('sort+pallas_fused', {'GLT_HOP_ENGINE': 'pallas_fused',
                                   'GLT_FUSED_HOP': '1',
                                   'GLT_FUSED_WALK': 'per_hop'})
        if room_for_another():
          # the cross-hop walk: ONE kernel for the whole multi-hop
          # walk, dedup table resident in VMEM across hop boundaries
          race('sort+pallas_walk', {'GLT_HOP_ENGINE': 'pallas_fused',
                                    'GLT_FUSED_HOP': '1',
                                    'GLT_FUSED_WALK': 'cross'})
      elif not fused_fits:
        # racing a demoted engine would just re-measure pallas under a
        # misleading label; record the reason instead
        engines['sort+pallas_fused_skipped'] = (
            'dedup table exceeds GLT_FUSED_TABLE_SLOTS at this batch')
  best = max((v['edges_per_sec'], k) for k, v in engines.items()
             if isinstance(v, dict))
  eps, chosen = best

  # Roofline cells (obs.perf): measure the device's HBM-stream + matmul
  # ceilings ONCE (disk-cached per device kind), then restate every
  # raced contender's edges/s as % of the MEASURED ceiling plus its
  # HBM bytes and FLOPs per edge — the self-grounding restatement every
  # perf claim in the trajectory rides on. Never fatal to the headline.
  if os.environ.get('GLT_BENCH_ROOFLINE', '1') != '0':
    try:
      from glt_tpu.obs.perf import device_ceilings, roofline_report
      ceilings = device_ceilings(dev)
      print(f"# roofline ceilings [{ceilings['device_kind']}]: "
            f"hbm={ceilings['hbm_bytes_per_sec']:.3e} B/s "
            f"matmul={ceilings['flops_per_sec']:.3e} FLOP/s",
            file=sys.stderr)
      for label, rec in engines.items():
        if not isinstance(rec, dict):
          continue
        epd = rec.get('edges_per_dispatch') or 0.0
        # the cell is emitted only when it can be WHOLE (CI asserts a
        # present cell carries all three fields): both cost numbers
        # and a nonzero edge count — a degraded cost pass or a
        # zero-edge run records no cell rather than absurd per-edge
        # numbers
        if (epd <= 0 or 'hbm_bytes_per_dispatch' not in rec
            or 'flops_per_dispatch' not in rec):
          continue
        rec['roofline'] = roofline_report(
            rec['edges_per_sec'],
            bytes_per_item=rec['hbm_bytes_per_dispatch'] / epd,
            flops_per_item=rec['flops_per_dispatch'] / epd,
            ceilings=ceilings, item='edge')
    except Exception as e:  # keep the measured headline regardless
      print(f'# roofline unavailable: {e}', file=sys.stderr)

  # End-to-end train-step throughput, per-batch vs superstep engines
  # side by side (PR: superstep training pipeline) — the growth bench
  # trajectory then tracks training-loop wins, not just sampler
  # throughput. Small fixed shapes independent of the headline knobs;
  # budget-guarded and never fatal to the headline line.
  train_ab = None
  if os.environ.get('GLT_BENCH_TRAIN_AB', '1') != '0':
    spent = time.time() - t_start
    # conservative margin: the A/B takes ~30s on an idle box but the
    # worker is HARD-KILLED at its budget (losing the already-measured
    # headline), so only run it with several-x headroom
    if not worker_budget or worker_budget - spent > 240:
      try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), 'benchmarks'))
        from bench_train import measure_engines
        d = measure_engines(supersteps=8)['detail']
        train_ab = {
            'per_batch': d['per_batch_steps_per_sec'],
            'superstep': d['superstep_steps_per_sec'],
            'speedup': d['speedup'],
            'superstep_k': d['superstep_k'],
            'batch': d['batch_size'],
        }
      except Exception as e:  # keep the measured headline regardless
        train_ab = {'error': str(e)[:200]}

  # Fused-walk smoke duel: per-hop vs cross-hop at a fixed toy
  # protocol, on every backend (interpret off-TPU) — the launch
  # collapse and byte delta land in the JSON even when the full-scale
  # contenders can only race on TPU. Runs BEFORE the stage-breakdown
  # passes so the walk's acceptance cells get budget priority on slow
  # runners. Budget-guarded; skip is recorded so CI can tell "didn't
  # fit" from "broke".
  fused_walk_duel = None
  if os.environ.get('GLT_BENCH_WALK_DUEL', '1') != '0':
    spent = time.time() - t_start
    # the duel's dominant cost is two whole-program compiles; in
    # interpret mode the cross-form compile alone was measured >100 s
    # on a slow core (BENCH_r06), so the guard must reflect the real
    # cost or it admits a duel it cannot finish inside the budget
    from glt_tpu.ops.pallas_kernels import interpret_default
    duel_cost = 420 if interpret_default() else 150
    if not worker_budget or worker_budget - spent > duel_cost:
      try:
        fused_walk_duel, duel_entries = measure_fused_walk_duel()
        # roofline cells for the duel entries ride the same ceilings
        if os.environ.get('GLT_BENCH_ROOFLINE', '1') != '0':
          try:
            from glt_tpu.obs.perf import device_ceilings, \
                roofline_report
            ceilings = device_ceilings(dev)
            for rec in duel_entries.values():
              epd = rec.get('edges_per_dispatch') or 0.0
              if (epd <= 0 or 'hbm_bytes_per_dispatch' not in rec
                  or 'flops_per_dispatch' not in rec):
                continue
              rec['roofline'] = roofline_report(
                  rec['edges_per_sec'],
                  bytes_per_item=rec['hbm_bytes_per_dispatch'] / epd,
                  flops_per_item=rec['flops_per_dispatch'] / epd,
                  ceilings=ceilings, item='edge')
          except Exception as e:
            print(f'# duel roofline unavailable: {e}', file=sys.stderr)
        engines.update(duel_entries)
      except Exception as e:  # never fatal to the headline
        fused_walk_duel = {'error': str(e)[:200]}
    else:
      fused_walk_duel = {'skipped': 'bench budget exhausted'}

  # Hetero multi-edge-type race (ISSUE 14 acceptance cells): sorted
  # per-edge-type reference vs the fused multi-edge-type engine,
  # per-batch vs superstep — seeds/s, the dispatches_per_step collapse
  # and per-dispatch cost cells, keyed under their own history bench so
  # hetero numbers never pollute homo baselines. Budget-guarded; a
  # skip is recorded so CI can tell "didn't fit" from "broke".
  hetero = None
  if os.environ.get('GLT_BENCH_HETERO', '1') != '0':
    spent = time.time() - t_start
    from glt_tpu.ops.pallas_kernels import interpret_default
    het_cost = 300 if interpret_default() else 120
    if not worker_budget or worker_budget - spent > het_cost:
      try:
        hetero = measure_hetero_race()
      except Exception as e:  # never fatal to the headline
        hetero = {'error': str(e)[:200]}
    else:
      hetero = {'skipped': 'bench budget exhausted'}

  # Per-stage time breakdown (the obs layer): run a short instrumented
  # sample->gather epoch with tracing + full device-sync sampling, then
  # report each stage's share next to the headline. Fixed smoke-scale
  # protocol independent of the headline knobs; budget-guarded, never
  # fatal. GLT_OBS_DUMP=<dir> additionally writes the registry snapshot
  # and a Perfetto-loadable trace JSON there (the CI smoke-bench
  # artifacts). Each raced contender additionally gets its OWN
  # breakdown (same protocol, smaller batch so the fused engine's
  # dedup table engages at smoke scale) so a fusion delta in the
  # headline is attributable stage-by-stage: the fused engine should
  # show gather.features self-time collapsing into sample.multihop.
  stage_breakdown = None
  if os.environ.get('GLT_BENCH_OBS', '1') != '0':
    spent = time.time() - t_start
    if not worker_budget or worker_budget - spent > 120:
      try:
        stage_breakdown = measure_stage_breakdown(
            dump_dir=os.environ.get('GLT_OBS_DUMP'))
      except Exception as e:  # keep the measured headline regardless
        stage_breakdown = {'error': str(e)[:200]}
    for label, env in engine_envs.items():
      if not isinstance(engines.get(label), dict):
        continue
      spent = time.time() - t_start
      if worker_budget and worker_budget - spent < 90:
        break
      saved = {k: os.environ.get(k) for k in env}
      os.environ.update(env)
      try:
        engines[label]['stage_breakdown'] = measure_stage_breakdown(
            batches=4, batch_size=256)
      except Exception as e:
        engines[label]['stage_breakdown'] = {'error': str(e)[:200]}
      finally:
        for k, v in saved.items():
          if v is None:
            os.environ.pop(k, None)
          else:
            os.environ[k] = v

  # what the backend-aware auto would run here (observability for the
  # default-flip evidence; never fatal — on TPU this may pay the
  # one-time kernel probe compile)
  auto_engine = None
  try:
    if 'GLT_HOP_ENGINE' not in os.environ:
      from glt_tpu.ops.pipeline import hop_engine
      auto_engine = hop_engine()
  except Exception as e:
    auto_engine = f'error: {str(e)[:120]}'

  def engine_record(v):
    if not isinstance(v, dict):
      return v
    rec = {'edges_per_sec': round(v['edges_per_sec'], 1),
           'compile_s': round(v['compile_s'], 2),
           'steady_recompiles': v['steady_recompiles']}
    for k in ('kernel_launches_per_dispatch', 'hbm_bytes_per_dispatch',
              'flops_per_dispatch', 'scale', 'roofline',
              'stage_breakdown'):
      if k in v:
        rec[k] = v[k]
    return rec

  winner = engines.get(chosen)
  _emit(round(eps, 1), round(eps / A100_ASSUMED_EDGES_PER_SEC, 4),
        backend=dev.platform, scan=scan, iters=ITERS, batch=BATCH,
        scale=f'N{NUM_NODES}_E{NUM_EDGES}_B{BATCH}_S{scan}',
        engine=chosen, auto_engine=auto_engine,
        engines={k: engine_record(v) for k, v in engines.items()},
        roofline=(winner.get('roofline')
                  if isinstance(winner, dict) else None),
        train_steps_per_sec=train_ab,
        stage_breakdown=stage_breakdown,
        fused_walk_duel=fused_walk_duel,
        hetero=hetero)


def measure_stage_breakdown(batches: int = 8, num_nodes: int = 100_000,
                            num_edges: int = 1_000_000,
                            feat_dim: int = 16,
                            batch_size: int = 1024,
                            dump_dir=None):
  """Instrumented sample->dedup->gather pass over a smoke-scale graph:
  glt_tpu.obs tracing on, device-sync sampling at 1.0 so every span
  covers real compute, per-stage times aggregated from the registry's
  ``stage_seconds`` histograms. Returns {stage: {total_ms, mean_ms,
  count}} plus the warmup compile wall time."""
  import numpy as np
  from glt_tpu.data import Dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.obs import MetricsRegistry, get_tracer, set_registry

  rng = np.random.default_rng(7)
  src = rng.integers(0, num_nodes, num_edges, dtype=np.int64)
  dst = rng.integers(0, num_nodes, num_edges, dtype=np.int64)
  ds = Dataset()
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=num_nodes)
  ds.init_node_features(
      rng.random((num_nodes, feat_dim)).astype(np.float32))
  seeds = rng.integers(0, num_nodes, (batches + 1) * batch_size)

  tracer = get_tracer()
  was_enabled, prev_sample = tracer.enabled, tracer._sample
  prev_registry = set_registry(MetricsRegistry())  # isolated aggregation
  tracer.enable(sample=1.0)
  try:
    loader = NeighborLoader(ds, list(FANOUT), seeds,
                            batch_size=batch_size, seed=0)
    it = iter(loader)
    t0 = time.time()
    next(it)  # first batch pays trace+compile; keep it out of the stats
    warm_s = time.time() - t0
    tracer.clear()
    set_registry(MetricsRegistry())  # drop warmup-batch observations
    for _ in range(batches):
      next(it)
    from glt_tpu.obs import get_registry
    snap = get_registry().snapshot()
    out = {'warmup_compile_s': round(warm_s, 2), 'batches': batches,
           'batch_size': batch_size}
    # spans NEST (loader.batch encloses sample.multihop and
    # gather.features), so raw per-stage totals double-count; report
    # self time (own duration minus direct children) so the stage
    # shares sum to ~wall — total_ms stays alongside for the
    # enclosing-span view
    events = tracer.events()
    child_dur = {}
    for e in events:
      p = e['args'].get('parent_id')
      if p is not None:
        child_dur[p] = child_dur.get(p, 0) + e['dur']
    stages = {}
    for e in events:
      s = stages.setdefault(e['name'],
                            {'total_ms': 0.0, 'self_ms': 0.0,
                             'count': 0})
      s['total_ms'] += e['dur'] / 1e3
      s['self_ms'] += (e['dur']
                       - child_dur.get(e['args']['span_id'], 0)) / 1e3
      s['count'] += 1
    out['stages'] = {
        name: {'total_ms': round(s['total_ms'], 2),
               'self_ms': round(s['self_ms'], 2),
               'mean_ms': round(s['total_ms'] / max(s['count'], 1), 3),
               'count': s['count']}
        for name, s in sorted(stages.items())
    }
    if dump_dir:
      with open(os.path.join(dump_dir, 'obs_registry.json'), 'w') as f:
        json.dump(snap, f, indent=2)
      tracer.save(os.path.join(dump_dir, 'obs_trace.json'))
    return out
  finally:
    set_registry(prev_registry)
    tracer.enabled = was_enabled
    tracer._sample = prev_sample
    tracer.clear()


def walk_hbm_model(batch, fanouts, slots, width, num_edges, planes=1):
  """Analytic HBM bytes per dispatch for the two fused-walk forms —
  the DELTA-relevant terms only (both forms share the XLA epilogue:
  relabel sorts, output concatenation). ``per_hop`` pays, per hop
  boundary, a full table-plane round trip, a fresh read of the padded
  edge-array operand, and the XLA-side table-label rewrite; ``cross``
  pays the edge operand once and stages only the [S_h, K_h] int32
  frontier per boundary. This model makes the expected ratio visible
  in the bench JSON on every backend — interpret-mode cost analysis
  measures the EMULATION of the kernels (dynamic-update-slice traffic
  of the discharged state machine), so the measured interpret ratio
  reflects the harness, not the Mosaic dataflow; the measured TPU
  cells are the decisive evidence."""
  table = 2 * slots * 4                     # both planes, bytes
  arr = (num_edges + width) * 4 * planes
  rows, s = [], batch
  for k in fanouts:
    rows.append(s)
    s *= k
  win = sum(r * width * 4 * planes for r in rows)
  m = sum(r * k * 4 for r, k in zip(rows, fanouts))
  hops = len(fanouts)
  per_hop = (2 * table                      # seed insert: planes in+out
             + hops * 2 * table             # per-hop planes in+out
             + hops * arr                   # edge operand per launch
             + win                          # window DMA reads
             + hops * (3 * table // 2))     # XLA relabel table rewrite
  cross = (arr                              # edge operand once
           + win                            # window DMA reads
           + 2 * m                          # frontier staging in+out
           + 2 * m)                         # per-hop indptr pair reads
  return dict(per_hop_bytes=per_hop, cross_bytes=cross,
              ratio=round(cross / max(per_hop, 1), 4))


def measure_fused_walk_duel(num_nodes: int = 20_000,
                            num_edges: int = 200_000,
                            iters: int = 3):
  """Per-hop vs cross-hop fused walk at a fixed smoke protocol (3-hop
  walk, its own toy graph), on WHATEVER backend the bench runs:
  interpret mode off-TPU, compiled Mosaic on TPU. Each form is traced
  once, AOT-compiled once, cost-analyzed (bytes/FLOPs/kernel launches
  per dispatch) and executed ``iters`` times for edges/s — so the
  O(hops)->O(1) launch collapse and the table-residency byte delta are
  recorded numbers in the BENCH JSON, next to the analytic
  ``hbm_model`` that states what the delta SHOULD be (see
  ``walk_hbm_model`` for why the interpret-mode measured ratio is the
  harness, not the kernel). Returns (duel_dict, engine_entries)."""
  import numpy as np
  import jax
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.obs.perf import instrument_compiled
  from glt_tpu.ops.pallas_kernels import (fused_table_slots,
                                          interpret_default,
                                          kernel_launch_count)
  from glt_tpu.ops.pipeline import (make_dedup_tables, multihop_sample,
                                    sample_budget)
  from glt_tpu.ops.sample import FusedHopPlan

  interp = interpret_default()
  # interpret-mode tracing cost scales with block*sum(fanouts) unrolled
  # probe-inserts, so the off-TPU smoke protocol uses smaller fanouts;
  # both forms always run the SAME protocol, which is what the ratio
  # needs
  batch = int(os.environ.get('GLT_BENCH_DUEL_BATCH',
                             '64' if interp else '256'))
  fan = tuple(int(x) for x in os.environ.get(
      'GLT_BENCH_DUEL_FANOUT',
      '5,4,3' if interp else '15,10,5').split(','))
  width = max(int(os.environ.get('GLT_WINDOW_W', '96')), 8)

  rng = np.random.default_rng(11)
  src = rng.integers(0, num_nodes, num_edges, dtype=np.int64)
  dst = (rng.random(num_edges) ** 2 * num_nodes).astype(np.int64) \
      % num_nodes
  topo = Topology(edge_index=np.stack([src, dst]),
                  num_nodes=num_nodes)
  indptr = jnp.asarray(topo.indptr.astype(np.int32))
  indices = jnp.asarray(topo.indices)
  iw = jnp.concatenate([indices, jnp.full((width,), -1,
                                          indices.dtype)])
  n_hub = int((np.diff(topo.indptr) > width).sum())
  slots = fused_table_slots(sample_budget(batch, list(fan)))
  plan = FusedHopPlan(indptr, indices, iw, width, n_hub, slots,
                      interpret=interp)
  table, scratch = make_dedup_tables(num_nodes)
  from glt_tpu.utils.rng import make_key
  seeds = jnp.asarray(
      rng.integers(0, num_nodes, batch).astype(np.int32))
  keys = jax.random.split(make_key(3), iters + 1)
  scale = f'N{num_nodes}_E{num_edges}_B{batch}_F{",".join(map(str, fan))}'

  entries = {}
  saved = {k: os.environ.get(k) for k in
           ('GLT_HOP_ENGINE', 'GLT_FUSED_HOP', 'GLT_FUSED_WALK')}
  try:
    for mode, label in (('per_hop', 'sort+pallas_fused_smoke'),
                        ('cross', 'sort+pallas_walk_smoke')):
      os.environ.update({'GLT_HOP_ENGINE': 'pallas_fused',
                         'GLT_FUSED_HOP': '1',
                         'GLT_FUSED_WALK': mode})

      def f(seeds, key, table, scratch):
        out, table, scratch = multihop_sample(
            None, seeds, jnp.asarray(batch), fan, key, table, scratch,
            fused_plan=plan)
        return (out['num_sampled_edges'].sum(),
                out['node_count'], table, scratch)

      t0 = time.time()
      launches0 = kernel_launch_count()
      lowered = jax.jit(f).lower(seeds, keys[0], table, scratch)
      launches = kernel_launch_count() - launches0
      compiled = lowered.compile()
      compile_s = time.time() - t0
      cost = instrument_compiled(f'bench.walk_duel.{mode}', compiled)
      if 'kernel_launches' not in cost and launches:
        cost['kernel_launches'] = launches
      try:  # TPU ground truth: Mosaic kernel entries in the lowered HLO
        hlo = lowered.as_text().count('tpu_custom_call')
        if hlo:
          cost['kernel_launches'] = hlo
      except Exception:
        pass
      edges, _, t2, s2 = compiled(seeds, keys[0], table, scratch)
      jax.block_until_ready(edges)   # warmup dispatch
      t1 = time.time()
      counts = []
      for it in range(iters):
        e_i, _, t2, s2 = compiled(seeds, keys[it + 1], t2, s2)
        counts.append(e_i)
      jax.block_until_ready(counts[-1])
      dt = time.time() - t1
      total = int(np.sum([int(c) for c in counts]))
      entries[label] = {
          'edges_per_sec': round(total / dt, 1),
          'compile_s': round(compile_s, 2),
          # one AOT executable served the whole timed loop: shape-
          # stable by construction, and no re-trace was observed
          'steady_recompiles': 0,
          'edges_per_dispatch': total / iters,
          'scale': scale,
      }
      if 'bytes_accessed' in cost:
        entries[label]['hbm_bytes_per_dispatch'] = cost[
            'bytes_accessed']
      if 'flops' in cost:
        entries[label]['flops_per_dispatch'] = cost['flops']
      if 'kernel_launches' in cost:
        entries[label]['kernel_launches_per_dispatch'] = cost[
            'kernel_launches']
  finally:
    for k, v in saved.items():
      if v is None:
        os.environ.pop(k, None)
      else:
        os.environ[k] = v

  duel = {'scale': scale, 'interpret': interp,
          'hbm_model': walk_hbm_model(batch, fan, slots, width,
                                      num_edges)}
  ph = entries.get('sort+pallas_fused_smoke', {})
  cr = entries.get('sort+pallas_walk_smoke', {})
  if 'hbm_bytes_per_dispatch' in ph and 'hbm_bytes_per_dispatch' in cr:
    duel['measured_bytes_ratio'] = round(
        cr['hbm_bytes_per_dispatch'] / max(ph['hbm_bytes_per_dispatch'],
                                           1.0), 4)
  if 'kernel_launches_per_dispatch' in ph \
      and 'kernel_launches_per_dispatch' in cr:
    duel['kernel_launches'] = {
        'per_hop': ph['kernel_launches_per_dispatch'],
        'cross': cr['kernel_launches_per_dispatch']}
  return duel, entries


def measure_hetero_race(iters: int = 3, supersteps: int = 4):
  """Hetero multi-edge-type sampling race (ISSUE 14 acceptance cells):
  the per-edge-type sorted reference vs the fused multi-edge-type
  kernel engine, per-batch vs superstep, at a fixed smoke protocol on
  WHATEVER backend the bench runs (interpret off-TPU, compiled Mosaic
  on TPU — the driver's TPU round produces the decisive seeds/s
  against the 174 seeds/s VERDICT baseline).

  Records per contender: seeds/s, compile_s, steady_recompiles,
  dispatches_per_step (1.0 per-batch; 1/K for the superstep — the
  recorded DISPATCH COLLAPSE), and — when cost analysis is available —
  bytes/FLOPs/kernel launches per dispatch plus a roofline cell
  (item='seed'). Keyed in benchmarks/history.py under its own
  ``hetero_sampler`` bench + its own scale string, so hetero numbers
  never enter a homo baseline window. Returns (hetero_dict)."""
  import functools
  import numpy as np
  import jax
  import jax.numpy as jnp
  from glt_tpu.data import Dataset
  from glt_tpu.obs.perf import instrument_compiled
  from glt_tpu.ops.pallas_kernels import interpret_default
  from glt_tpu.ops.pipeline import (multihop_sample_hetero,
                                    multihop_sample_hetero_many)
  from glt_tpu.sampler import NeighborSampler
  from glt_tpu.utils.rng import make_key

  interp = interpret_default()
  # interpret-mode fused tracing cost scales with the unrolled
  # probe-insert loops, so the off-TPU smoke protocol stays toy-sized;
  # every contender runs the SAME protocol, which is what the ratios
  # need
  nu = int(os.environ.get('GLT_BENCH_HET_USERS',
                          '2000' if interp else '200000'))
  ni = int(os.environ.get('GLT_BENCH_HET_ITEMS',
                          '4000' if interp else '400000'))
  batch = int(os.environ.get('GLT_BENCH_HET_BATCH',
                             '32' if interp else '512'))
  fan = [int(x) for x in os.environ.get(
      'GLT_BENCH_HET_FANOUT', '3,2' if interp else '10,5').split(',')]
  k_scan = max(int(os.environ.get('GLT_BENCH_HET_SCAN',
                                  str(supersteps))), 2)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  rng = np.random.default_rng(17)
  u2i_ei = np.stack([np.repeat(np.arange(nu, dtype=np.int64), 4),
                     rng.integers(0, ni, 4 * nu, dtype=np.int64)])
  # skewed in-degrees so the per-type dedup namespaces see real load
  i2i_src = np.repeat(np.arange(ni, dtype=np.int64), 4)
  i2i_dst = ((rng.random(4 * ni) ** 2) * ni).astype(np.int64) % ni
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index={u2i: u2i_ei,
                            i2i: np.stack([i2i_src, i2i_dst])},
                num_nodes={'user': nu, 'item': ni})
  nn = {u2i: list(fan), i2i: list(fan)}
  scale = (f'U{nu}_I{ni}_B{batch}_'
           f'F{",".join(map(str, fan))}_K{k_scan}')

  def _checksum(out):
    acc = jnp.zeros((), jnp.float32)
    for leaf in jax.tree_util.tree_leaves(out):
      acc = acc + leaf.sum(dtype=jnp.float32)
    return acc

  seed_pool = rng.integers(0, nu, (iters + 2, k_scan, batch))
  entries = {}
  saved = {k: os.environ.get(k) for k in
           ('GLT_HOP_ENGINE', 'GLT_FUSED_HOP', 'GLT_DEDUP',
            'GLT_FUSED_WALK')}
  try:
    for label, env, use_plan, scan in (
        ('hetero_sorted',
         {'GLT_DEDUP': 'sort', 'GLT_FUSED_HOP': '1'}, False, 1),
        ('hetero_pallas_fused',
         {'GLT_HOP_ENGINE': 'pallas_fused'}, True, 1),
        ('hetero_pallas_fused_superstep',
         {'GLT_HOP_ENGINE': 'pallas_fused'}, True, k_scan)):
      for k in saved:
        os.environ.pop(k, None)
      os.environ.update(env)
      samp = NeighborSampler(ds.graph, nn, seed=0)
      trav = samp._traversal_types()
      caps, budgets = samp._hetero_caps({'user': batch})
      plan = samp._hetero_fused_plan({'user': batch}) if use_plan \
          else None
      if use_plan and plan is None:
        entries[label + '_skipped'] = (
            'fused hetero plan unavailable (see '
            'hop_engine_fallbacks_total)')
        continue
      one_hops = {e: (lambda ids, f, k, m, _e=e: samp._one_hop(
          samp.graph[_e], ids, f, k, m)) for e in samp.edge_types}
      tables = {t: samp._get_tables(t, n)
                for t, n in samp._node_counts.items()}
      traces = {'n': 0}

      if scan > 1:
        @functools.partial(jax.jit, donate_argnums=(2,))
        def fn(seeds_stack, key, tables):
          traces['n'] += 1  # trace-time side effect only
          outs, tables = multihop_sample_hetero_many(
              one_hops, trav, samp.num_neighbors, samp.num_hops,
              caps, budgets, {'user': seeds_stack},
              {'user': jnp.full((seeds_stack.shape[0],), batch,
                                jnp.int32)},
              key, tables, fused_plan=plan)
          edges = sum(v.sum() for v in
                      outs['num_sampled_edges'].values())
          return edges, _checksum(outs), tables
      else:
        @functools.partial(jax.jit, donate_argnums=(2,))
        def fn(seeds_stack, key, tables):
          traces['n'] += 1  # trace-time side effect only
          out, tables = multihop_sample_hetero(
              one_hops, trav, samp.num_neighbors, samp.num_hops,
              caps, budgets, {'user': seeds_stack[0]},
              {'user': jnp.asarray(batch)}, key, tables,
              fused_plan=plan)
          edges = sum(v.sum() for v in
                      out['num_sampled_edges'].values())
          return edges, _checksum(out), tables

      keys = jax.random.split(make_key(5), iters + 2)
      arg_sds = (jax.ShapeDtypeStruct((k_scan, batch), jnp.int32),
                 jax.ShapeDtypeStruct(keys[0].shape, keys[0].dtype),
                 jax.tree_util.tree_map(
                     lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                     tables))
      t0 = time.time()
      edges, sig, tables = fn(
          jnp.asarray(seed_pool[0], jnp.int32), keys[0], tables)
      jax.block_until_ready((edges, sig))
      compile_s = time.time() - t0
      edges, sig, tables = fn(
          jnp.asarray(seed_pool[1], jnp.int32), keys[1], tables)
      jax.block_until_ready((edges, sig))
      traces_warm = traces['n']
      counts, sigs = [], []
      t1 = time.time()
      for it in range(iters):
        e_i, s_i, tables = fn(
            jnp.asarray(seed_pool[it + 2], jnp.int32), keys[it + 2],
            tables)
        counts.append(e_i)
        sigs.append(s_i)
      jax.block_until_ready((counts[-1], sigs[-1]))
      dt = time.time() - t1
      steps = iters * scan  # batches consumed during the timed loop
      total_edges = int(np.sum([int(c) for c in counts]))
      rec = {
          'seeds_per_sec': round(batch * steps / dt, 1),
          'edges_per_sec': round(total_edges / dt, 1),
          'compile_s': round(compile_s, 2),
          'steady_recompiles': traces['n'] - traces_warm,
          'dispatches_per_step': round(1.0 / scan, 4),
          'seeds_per_dispatch': batch * scan,
          'scale': scale,
      }
      if os.environ.get('GLT_BENCH_ROOFLINE', '1') != '0':
        try:
          cost = instrument_compiled(f'bench.hetero.{label}', fn,
                                     *arg_sds, aot_compile=True)
          if 'bytes_accessed' in cost:
            rec['hbm_bytes_per_dispatch'] = cost['bytes_accessed']
          if 'flops' in cost:
            rec['flops_per_dispatch'] = cost['flops']
          if 'kernel_launches' in cost:
            rec['kernel_launches_per_dispatch'] = cost[
                'kernel_launches']
        except Exception as e:
          print(f'# hetero cost analysis unavailable: {e}',
                file=sys.stderr)
      entries[label] = rec
  finally:
    for k, v in saved.items():
      if v is None:
        os.environ.pop(k, None)
      else:
        os.environ[k] = v

  het = {'scale': scale, 'interpret': interp,
         'baseline_seeds_per_sec_r5': 174.0,
         'engines': entries}
  pb = entries.get('hetero_pallas_fused', {})
  ss = entries.get('hetero_pallas_fused_superstep', {})
  if 'dispatches_per_step' in pb and 'dispatches_per_step' in ss:
    het['dispatches_per_step'] = {
        'per_batch': pb['dispatches_per_step'],
        'superstep': ss['dispatches_per_step']}
  if 'seeds_per_sec' in ss:
    het['vs_r5_baseline'] = round(ss['seeds_per_sec'] / 174.0, 2)
  # roofline cells: restate each contender's seeds/s against the
  # measured device ceilings (same whole-cell rule as the homo race)
  if os.environ.get('GLT_BENCH_ROOFLINE', '1') != '0':
    try:
      from glt_tpu.obs.perf import device_ceilings, roofline_report
      import jax as _jax
      ceilings = device_ceilings(_jax.devices()[0])
      for rec in entries.values():
        if not isinstance(rec, dict):
          continue
        spd = rec.get('seeds_per_dispatch') or 0
        if (spd <= 0 or 'hbm_bytes_per_dispatch' not in rec
            or 'flops_per_dispatch' not in rec):
          continue
        rec['roofline'] = roofline_report(
            rec['seeds_per_sec'],
            bytes_per_item=rec['hbm_bytes_per_dispatch'] / spd,
            flops_per_item=rec['flops_per_dispatch'] / spd,
            ceilings=ceilings, item='seed')
    except Exception as e:
      print(f'# hetero roofline unavailable: {e}', file=sys.stderr)
  return het


def _dump_obs_on_failure():
  """GLT_OBS_DUMP artifacts on the worker's FAILURE path: the success
  path writes them from measure_stage_breakdown, but a crashed run is
  exactly the one whose registry counters and last spans matter —
  without this the postmortem evidence dies with the process. Also
  leaves a flight-recorder postmortem when GLT_OBS_POSTMORTEM_DIR is
  configured."""
  dump_dir = os.environ.get('GLT_OBS_DUMP')
  try:
    from glt_tpu.obs import get_recorder, get_registry, get_tracer
    if dump_dir:
      with open(os.path.join(dump_dir, 'obs_registry.json'), 'w') as f:
        json.dump(get_registry().snapshot(), f, indent=2)
      get_tracer().save(os.path.join(dump_dir, 'obs_trace.json'))
      print(f'# worker failed; obs artifacts dumped to {dump_dir}',
            file=sys.stderr)
    get_recorder().trip('bench_worker_failure')
  except Exception as e:  # the dump must never mask the real error
    print(f'# obs failure dump failed: {e}', file=sys.stderr)


def _append_history(line: str) -> None:
  """GLT_BENCH_HISTORY=<path>: append the emitted headline JSON to the
  bench trajectory (benchmarks/history.py) — the series
  scripts/bench_compare.py gates against."""
  hist = os.environ.get('GLT_BENCH_HISTORY')
  if not hist:
    return
  try:
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'benchmarks'))
    from history import append_bench_json
    rows = append_bench_json(hist, json.loads(line))
    print(f'# appended {len(rows)} series to {hist}', file=sys.stderr)
  except Exception as e:  # trajectory bookkeeping is never fatal
    print(f'# bench history append failed: {e}', file=sys.stderr)


def run_probe():
  """Cheap backend liveness check: init jax + list devices, nothing else.
  A wedged axon tunnel hangs here — the supervisor's short timeout turns
  that hang into a fast, cheap verdict."""
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  dev = jax.devices()[0]
  print(f'probe-ok {dev.platform} {dev.device_kind}')


def _child(mode, timeout):
  """Run a child in its own process group; on timeout SIGKILL the whole
  group (subprocess.run's TimeoutExpired kills only the direct child —
  a surviving grandchild would both hold the TPU and keep the stdout
  pipe open, hanging the supervisor in communicate())."""
  import signal
  proc = subprocess.Popen(
      [sys.executable, os.path.abspath(__file__), mode],
      stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
      start_new_session=True)
  try:
    out, err = proc.communicate(timeout=timeout)
  except subprocess.TimeoutExpired:
    try:
      os.killpg(proc.pid, signal.SIGKILL)  # pgid == pid (new session)
    except (ProcessLookupError, PermissionError):
      proc.kill()
    try:
      proc.communicate(timeout=10)
    except Exception:
      pass
    return None, f'timeout after {timeout:.0f}s (wedged backend?)'
  proc.stdout, proc.stderr = out, err
  return proc, None


def run_supervisor():
  t0 = time.time()
  budget = float(os.environ.get('GLT_BENCH_BUDGET', '900'))
  probe_timeout = float(os.environ.get('GLT_BENCH_PROBE_TIMEOUT', '90'))
  deadline = t0 + budget
  last_err = 'unknown'

  def remaining():
    return deadline - time.time()

  # Phase 1: backend probe — up to 2 tries, small cost each.
  probe_ok = False
  for attempt in range(2):
    if remaining() < probe_timeout + 60:
      break  # keep enough budget for the failure line + one attempt
    proc, err = _child('--probe', probe_timeout)
    if proc is not None and proc.returncode == 0 \
        and 'probe-ok' in proc.stdout:
      print(f'# {proc.stdout.strip()} ({time.time() - t0:.0f}s)',
            file=sys.stderr)
      probe_ok = True
      break
    last_err = err or (f'probe rc={proc.returncode}: '
                       + (proc.stderr or proc.stdout).strip()[-300:])
    print(f'# probe attempt {attempt + 1}/2 failed: {last_err}',
          file=sys.stderr)
    if attempt == 0 and remaining() > probe_timeout + 120:
      time.sleep(20)
  if not probe_ok:
    _emit(0.0, 0.0, error=f'backend probe failed: {last_err}')
    return 0

  # Phase 2: measurement attempts within the remaining budget.
  env_timeout = os.environ.get('GLT_BENCH_TIMEOUT')
  while remaining() > 120:
    timeout = remaining() - 30
    if env_timeout:
      timeout = min(timeout, float(env_timeout))
    # budget hint: lets the worker decide whether the fused-engine
    # second pass fits before its own kill deadline
    os.environ['GLT_BENCH_WORKER_BUDGET'] = str(int(timeout))
    proc, err = _child('--run', timeout)
    if proc is None:
      last_err = err
      print(f'# measurement: {last_err}', file=sys.stderr)
      if not env_timeout:
        break  # the attempt consumed the whole remaining budget
      if remaining() > 180:
        time.sleep(20)   # short-capped attempt: budget remains, retry
      continue
    line = next((l for l in reversed(proc.stdout.splitlines())
                 if l.startswith('{')), None)
    if proc.returncode == 0 and line:
      print(line)
      _append_history(line)
      return 0
    tail = (proc.stderr or proc.stdout).strip().splitlines()[-8:]
    last_err = (f'rc={proc.returncode}: ' + ' | '.join(tail))[:800]
    print(f'# measurement failed: {last_err}', file=sys.stderr)
    # Only backend-init/tunnel failures are transient; a deterministic
    # error (ImportError, bad config, assertion) would fail identically
    # on retry — emit the failure line now instead of burning budget.
    transient = ('initialize backend' in last_err
                 or 'UNAVAILABLE' in last_err
                 or 'DEADLINE' in last_err
                 or 'RESOURCE_EXHAUSTED' in last_err
                 or 'axon' in last_err.lower())
    if not transient:
      break
    if remaining() > 180:
      time.sleep(20)
  # Unrecoverable: still emit the structured line so the driver records
  # a parseable failure instead of a stack trace. value 0.0 + error
  # field unambiguously marks "not measured", not "measured as 0".
  _emit(0.0, 0.0, error=f'not measured within {budget:.0f}s budget: '
        f'{last_err}')
  return 0


if __name__ == '__main__':
  if '--run' in sys.argv:
    try:
      run_worker()
    except BaseException:
      _dump_obs_on_failure()
      raise
  elif '--probe' in sys.argv:
    run_probe()
  else:
    sys.exit(run_supervisor())

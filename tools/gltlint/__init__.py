"""gltlint — stdlib-``ast`` static analysis for glt_tpu's own invariants.

Every rule encodes a bug class this repo has already paid for at least
once (see docs/static_analysis.md for the provenance of each):

  GLT001  raw os.environ parse outside glt_tpu.utils.env (import crash)
  GLT002  guarded-by violation: attr written under a lock, touched bare
  GLT003  trace-time staging: instance mutation inside a jitted callee
  GLT004  jit closure over instance/module arrays (recompile hazard)
  GLT005  Future.set_result/set_exception without a done-race guard
  GLT006  silent except swallow inside a thread/background target
  GLT007  docs drift: metric / GLT_* knob missing from the doc catalogs
  GLT008  int64/float64 planes in ops/ hot paths (narrowing audit)

Usage::

  python -m tools.gltlint glt_tpu/ [tools/ tests/] [--json out.json]

Findings not present in the checked-in baseline
(tools/gltlint/baseline.json) fail the run; inline
``# gltlint: disable=GLT00x`` comments suppress a single line.
"""
from .core import Finding, Rule, all_rules, lint_paths  # noqa: F401

__version__ = '0.1.0'

"""Command-line front end: ``python -m tools.gltlint [paths...]``.

Exit codes: 0 = clean (all findings baselined or none), 1 = new
findings or parse errors, 2 = usage error.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from .core import (
    all_rules, find_root, lint_paths, load_baseline, write_baseline,
)


def build_parser() -> argparse.ArgumentParser:
  p = argparse.ArgumentParser(
      prog='python -m tools.gltlint',
      description='glt_tpu invariant linter (see docs/static_analysis.md)')
  p.add_argument('paths', nargs='*', default=['glt_tpu/'],
                 help='files or directories to lint (default: glt_tpu/)')
  p.add_argument('--root', default=None,
                 help='project root (default: auto-detect via setup.py/.git)')
  p.add_argument('--baseline', default=None,
                 help='baseline JSON (default: tools/gltlint/baseline.json '
                      'under the root); findings listed there are reported '
                      'but do not fail the run')
  p.add_argument('--no-baseline', action='store_true',
                 help='ignore the baseline: every finding fails the run')
  p.add_argument('--write-baseline', action='store_true',
                 help='rewrite the baseline from the current findings '
                      '(keeps existing justifications)')
  p.add_argument('--select', default=None, metavar='GLT001,GLT002',
                 help='comma-separated rule codes to run (default: all)')
  p.add_argument('--json', dest='json_out', default=None, metavar='PATH',
                 help='also write findings as JSON (machine-readable, '
                      'uploaded as the CI artifact)')
  p.add_argument('--quiet', action='store_true',
                 help='print only the summary line')
  p.add_argument('--list-rules', action='store_true',
                 help='print the rule catalog and exit')
  return p


def main(argv: Optional[List[str]] = None) -> int:
  args = build_parser().parse_args(argv)
  if args.list_rules:
    for rule in all_rules():
      codes = '/'.join(getattr(rule, 'codes', None) or (rule.code,))
      scope = ','.join(rule.applies_to) or '<all>'
      print(f'{codes:16s} {rule.name:28s} scope={scope}')
    return 0

  t0 = time.perf_counter()
  first = args.paths[0]
  root = args.root or find_root(
      first if os.path.isdir(first) else os.path.dirname(first) or '.')
  baseline_path = args.baseline or os.path.join(
      root, 'tools', 'gltlint', 'baseline.json')
  baseline = {} if args.no_baseline else load_baseline(baseline_path)
  select = (set(c.strip() for c in args.select.split(','))
            if args.select else None)

  result = lint_paths(args.paths, root=root, select=select,
                      baseline=baseline)

  dt = time.perf_counter() - t0
  if args.json_out:
    payload = {
        'new': [f.as_dict() for f in result.findings],
        'baselined': [f.as_dict() for f in result.baselined],
        'errors': result.errors,
        'elapsed_s': round(dt, 3),
    }
    with open(args.json_out, 'w', encoding='utf-8') as fh:
      json.dump(payload, fh, indent=2)
      fh.write('\n')

  if args.write_baseline:
    if select is not None:
      # a partial rule set would rewrite the file WITHOUT the other
      # rules' entries, losing their hand-written justifications
      print('--write-baseline requires the full rule set: drop '
            '--select and rerun')
      return 2
    if result.errors:
      # an unparsable/missing input means the baseline would silently
      # omit its findings — refuse rather than write an incomplete one
      for err in result.errors:
        print(f'ERROR {err}')
      print('baseline NOT written: fix the errors above first')
      return 1
    # entries for files outside the linted paths were not re-checked:
    # carry them over verbatim instead of silently dropping them
    lint_dirs = [os.path.abspath(p) for p in args.paths]
    def outside_scope(key: str) -> bool:
      parts = key.split('::')
      target = os.path.join(root, parts[1]) if len(parts) > 1 else ''
      return not any(
          target == d or target.startswith(d.rstrip(os.sep) + os.sep)
          for d in lint_dirs)
    carry = {k: j for k, j in baseline.items() if outside_scope(k)}
    write_baseline(baseline_path,
                   result.findings + result.baselined,
                   old=baseline, carry=carry)
    print(f'wrote baseline to {os.path.relpath(baseline_path, root)} '
          f'({len(result.findings) + len(result.baselined)} observed, '
          f'{len(carry)} carried from outside the linted paths)')
    # every entry needs a REAL justification: exit nonzero while any
    # placeholder remains, so a rebaseline can't silently grandfather
    # a new violation behind a green exit code
    todos = [k for k, j in load_baseline(baseline_path).items()
             if j == 'TODO: justify or fix']
    if todos:
      for k in todos:
        print(f'NEEDS JUSTIFICATION {k}')
      print(f'{len(todos)} entr{"y" if len(todos) == 1 else "ies"} '
            'carry the TODO placeholder: justify each (or fix the '
            'code) before committing the baseline')
      return 1
    return 0

  if not args.quiet:
    for f in result.findings:
      print(f.render())
    for err in result.errors:
      print(f'ERROR {err}')
  print(f'gltlint: {len(result.findings)} new finding(s), '
        f'{len(result.baselined)} baselined, '
        f'{len(result.errors)} error(s) in {dt:.2f}s')

  return 0 if result.ok else 1


if __name__ == '__main__':
  sys.exit(main())

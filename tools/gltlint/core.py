"""Rule framework: file contexts, suppressions, baseline, runner.

Design constraints:
  * stdlib only (``ast`` + ``tokenize``) — the lint job must run before
    any dependency install and inside the sdist.
  * Baseline keys are line-number-free (rule + path + scope + token) so
    unrelated edits above a grandfathered finding don't churn the file.
  * Rules are registered by subclassing :class:`Rule`; each declares the
    path scope it applies to, so running the CLI over ``tests/`` doesn't
    drown the signal in fixture noise.
"""
from __future__ import annotations

import ast
import io
import json
import os
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

_SUPPRESS_TAG = 'gltlint:'

#: directories never collected when walking a tree (explicit file
#: arguments bypass this — the fixture tests lint them directly)
_SKIP_DIRS = {'__pycache__', '.git', 'gltlint_fixtures', 'build',
              '.pytest_cache', 'node_modules'}


@dataclass(frozen=True)
class Finding:
  rule: str          # 'GLT001'
  path: str          # root-relative posix path
  line: int
  col: int
  scope: str         # dotted context, e.g. 'Tracer.__init__'
  token: str         # stable discriminator (env var, attr name, ...)
  message: str

  @property
  def key(self) -> str:
    """Line-free identity used for baselining."""
    return f'{self.rule}::{self.path}::{self.scope}::{self.token}'

  def render(self) -> str:
    where = f' [{self.scope}]' if self.scope else ''
    return (f'{self.path}:{self.line}:{self.col}: {self.rule}'
            f'{where} {self.message}')

  def as_dict(self) -> dict:
    return {
        'rule': self.rule, 'path': self.path, 'line': self.line,
        'col': self.col, 'scope': self.scope, 'token': self.token,
        'message': self.message, 'key': self.key,
    }


class FileCtx:
  """Parsed source + per-line suppression table for one file."""

  def __init__(self, abspath: str, relpath: str, source: str):
    self.abspath = abspath
    self.relpath = relpath.replace(os.sep, '/')
    self.source = source
    self.tree = ast.parse(source, filename=abspath)
    self.file_disabled: Set[str] = set()
    # line -> set of rule codes disabled on that line
    self.line_disabled: Dict[int, Set[str]] = {}
    self._parse_suppressions()

  def _parse_suppressions(self) -> None:
    try:
      toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
      comments = [(t.start[0], t.string) for t in toks
                  if t.type == tokenize.COMMENT]
    except tokenize.TokenError:
      comments = []
    for line, text in comments:
      body = text.lstrip('#').strip()
      if not body.startswith(_SUPPRESS_TAG):
        continue
      directive = body[len(_SUPPRESS_TAG):].strip()
      for clause in directive.split(';'):
        clause = clause.strip()
        if clause.startswith('disable-file='):
          self.file_disabled |= _codes(clause[len('disable-file='):])
        elif clause.startswith('disable-next='):
          self.line_disabled.setdefault(line + 1, set()).update(
              _codes(clause[len('disable-next='):]))
        elif clause.startswith('disable='):
          self.line_disabled.setdefault(line, set()).update(
              _codes(clause[len('disable='):]))

  def suppressed(self, finding: Finding) -> bool:
    if finding.rule in self.file_disabled or 'all' in self.file_disabled:
      return True
    on_line = self.line_disabled.get(finding.line, ())
    return finding.rule in on_line or 'all' in on_line


def _codes(spec: str) -> Set[str]:
  return {c.strip() for c in spec.split(',') if c.strip()}


class ProjectCtx:
  """Cross-file context: project root + lazily-read doc catalogs."""

  DOC_CATALOGS = ('docs/observability.md', 'docs/performance.md')

  def __init__(self, root: str):
    self.root = os.path.abspath(root)
    self._docs: Optional[str] = None

  def doc_text(self) -> str:
    if self._docs is None:
      parts = []
      for rel in self.DOC_CATALOGS:
        p = os.path.join(self.root, rel)
        if os.path.exists(p):
          with open(p, encoding='utf-8') as f:
            parts.append(f.read())
      self._docs = '\n'.join(parts)
    return self._docs


class Rule:
  """Base class. Subclass, set ``code``/``name``/``applies_to``,
  implement :meth:`check`. Subclasses self-register."""

  code: str = ''
  name: str = ''
  #: root-relative posix path prefixes this rule runs on ((),) = all
  applies_to: Tuple[str, ...] = ()
  #: path prefixes this rule never runs on
  excludes: Tuple[str, ...] = ()

  _registry: List[type] = []

  def __init_subclass__(cls, **kw):
    super().__init_subclass__(**kw)
    if cls.code:
      Rule._registry.append(cls)

  def applies(self, relpath: str) -> bool:
    if any(relpath.startswith(p) for p in self.excludes):
      return False
    return (not self.applies_to
            or any(relpath.startswith(p) for p in self.applies_to))

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    raise NotImplementedError

  # -- helpers shared by rules -------------------------------------------

  @staticmethod
  def dotted(node: ast.AST) -> str:
    """'a.b.c' for nested Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
      parts.append(node.attr)
      node = node.value
    if isinstance(node, ast.Name):
      parts.append(node.id)
      return '.'.join(reversed(parts))
    return ''


def all_rules() -> List[Rule]:
  # importing the rules package populates the registry
  from . import rules  # noqa: F401
  return [cls() for cls in Rule._registry]


def find_root(start: str) -> str:
  """Walk up from ``start`` to the repo root (setup.py/.git marker)."""
  cur = os.path.abspath(start)
  while True:
    if (os.path.exists(os.path.join(cur, 'setup.py'))
        or os.path.exists(os.path.join(cur, '.git'))):
      return cur
    parent = os.path.dirname(cur)
    if parent == cur:
      return os.path.abspath(start)
    cur = parent


def collect_files(paths: Iterable[str], root: str) -> List[str]:
  out: List[str] = []
  for p in paths:
    p = os.path.abspath(p)
    if os.path.isfile(p):
      out.append(p)       # explicit files always lint (fixtures too)
      continue
    for dirpath, dirnames, filenames in os.walk(p):
      dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
      for fn in sorted(filenames):
        if fn.endswith('.py'):
          out.append(os.path.join(dirpath, fn))
  seen: Set[str] = set()
  uniq = []
  for p in out:
    if p not in seen:
      seen.add(p)
      uniq.append(p)
  return uniq


@dataclass
class LintResult:
  findings: List[Finding] = field(default_factory=list)     # new
  baselined: List[Finding] = field(default_factory=list)    # known
  errors: List[str] = field(default_factory=list)           # parse/etc.

  @property
  def ok(self) -> bool:
    return not self.findings and not self.errors


def load_baseline(path: str) -> Dict[str, str]:
  """baseline.json -> {finding key: justification}."""
  if not path or not os.path.exists(path):
    return {}
  with open(path, encoding='utf-8') as f:
    data = json.load(f)
  out: Dict[str, str] = {}
  for entry in data.get('findings', []):
    out[entry['key']] = entry.get('justification', '')
  return out


def write_baseline(path: str, findings: List[Finding],
                   old: Optional[Dict[str, str]] = None,
                   carry: Optional[Dict[str, str]] = None) -> None:
  """``carry`` = old entries for files OUTSIDE the run's scope: they
  were not re-checked, so they keep their grandfathering verbatim."""
  old = old or {}
  merged: Dict[str, str] = dict(carry or {})
  for f in findings:
    if f.key not in merged:     # several lines can share one key
      merged[f.key] = old.get(f.key, 'TODO: justify or fix')
  entries = [{'key': k, 'justification': merged[k]}
             for k in sorted(merged)]
  payload = {
      'comment': ('Grandfathered gltlint findings. Every entry needs a '
                  'one-line justification; remove entries as the code '
                  'is fixed. New findings are NOT auto-added here.'),
      'findings': entries,
  }
  with open(path, 'w', encoding='utf-8') as f:
    json.dump(payload, f, indent=2, sort_keys=False)
    f.write('\n')


def lint_paths(paths: Iterable[str], root: Optional[str] = None,
               select: Optional[Set[str]] = None,
               baseline: Optional[Dict[str, str]] = None) -> LintResult:
  """Run every (selected) rule over every file under ``paths``."""
  paths = list(paths)        # callers may pass a one-shot iterator
  first = paths[0] if paths else '.'
  root = root or find_root(first if os.path.isdir(first)
                           else os.path.dirname(first) or '.')
  project = ProjectCtx(root)
  rules = [r for r in all_rules()
           if select is None
           or select & set(getattr(r, 'codes', None) or (r.code,))]
  baseline = baseline or {}
  result = LintResult()
  for p in paths:
    if not os.path.exists(p):
      # a typo'd/renamed path must FAIL the gate, not go vacuously green
      result.errors.append(f'{p}: path does not exist')
  for abspath in collect_files(paths, root):
    relpath = os.path.relpath(abspath, root).replace(os.sep, '/')
    try:
      with open(abspath, encoding='utf-8') as f:
        source = f.read()
      ctx = FileCtx(abspath, relpath, source)
    except (OSError, SyntaxError, ValueError) as e:
      result.errors.append(f'{relpath}: {e!r}')
      continue
    for rule in rules:
      if not rule.applies(relpath):
        continue
      for finding in rule.check(ctx, project):
        if select is not None and finding.rule not in select:
          continue     # multi-code rules (GLT003/GLT004) half-selected
        if ctx.suppressed(finding):
          continue
        if finding.key in baseline:
          result.baselined.append(finding)
        else:
          result.findings.append(finding)
  result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
  return result

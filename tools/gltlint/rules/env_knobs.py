"""GLT001 — raw ``os.environ`` reads outside glt_tpu.utils.env.

Bug class: a malformed knob value (``GLT_OBS_BUFFER=zillion``) turning
``int(os.environ.get(...))`` into an exception at import time, killing
``import glt_tpu`` for the whole process (paid for in PR 6 and again in
PR 11). All reads must route through ``glt_tpu.utils.env.knob()`` (typed
parse, warn-and-default) or ``glt_tpu.utils.env.raw()`` (string
passthrough for infra vars). Writes (``setdefault``/item-assign) stay
legal: they configure child processes, they cannot crash a parse.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of


def _is_environ(node: ast.AST) -> bool:
  """os.environ / environ (imported from os)."""
  return (Rule.dotted(node) in ('os.environ', 'environ'))


class EnvKnobRule(Rule):
  code = 'GLT001'
  name = 'raw-environ-read'
  applies_to = ('glt_tpu/',)
  excludes = ('glt_tpu/utils/env.py',)

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
      hit = None     # (node-for-location, env var token)
      if isinstance(node, ast.Call):
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == 'get'
            and _is_environ(fn.value)):
          hit = (node, _literal_name(node.args))
        elif Rule.dotted(fn) in ('os.getenv', 'getenv'):
          hit = (node, _literal_name(node.args))
      elif (isinstance(node, ast.Subscript)
            and isinstance(node.ctx, ast.Load)
            and _is_environ(node.value)):
        hit = (node, _literal_name([node.slice]))
      if hit is None:
        continue
      loc, var = hit
      yield Finding(
          rule=self.code, path=ctx.relpath, line=loc.lineno,
          col=loc.col_offset, scope=scope_of(ctx.tree, loc),
          token=var,
          message=(f'raw os.environ read of {var!r}: route through '
                   'glt_tpu.utils.env.knob() (typed, warn-and-default) '
                   'or env.raw() so a malformed value cannot crash '
                   'import'))


def _literal_name(args) -> str:
  if args and isinstance(args[0], ast.Constant) \
      and isinstance(args[0].value, str):
    return args[0].value
  return '<dynamic>'

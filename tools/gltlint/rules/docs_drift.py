"""GLT007 — docs drift: every metric and ``GLT_*`` knob is cataloged.

Bug class: docs/observability.md and docs/performance.md carry the
knob + metric catalogs operators actually read; every PR that added a
counter or a knob without touching them made the catalogs a little
more wrong. This rule makes the contract mechanical: a ``GLT_*``
string literal or a literal metric name registered on the
MetricsRegistry anywhere under ``glt_tpu/`` must appear in at least
one of the two catalog documents.

Only literal names are checked (f-strings and variables pass — the
registry labels them at runtime); that keeps the rule exact on the
95% case instead of fuzzy on all of them.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of

_KNOB = re.compile(r'^GLT_[A-Z0-9_]+$')
_METRIC = re.compile(r'^[a-z][a-z0-9_]{3,}$')
_REGISTER = {'counter', 'gauge', 'histogram'}
_REGISTER_ON_REG = {'inc', 'set', 'observe', 'add'}


def _documented(name: str, docs: str) -> bool:
  """Boundary-aware containment: 'GLT_BENCH' must NOT count as
  documented just because 'GLT_BENCH_PLATFORM' has a catalog row, and
  'documented_metric' must not ride 'documented_metric_total'."""
  return re.search(
      r'(?<![A-Za-z0-9_])' + re.escape(name) + r'(?![A-Za-z0-9_])',
      docs) is not None


class DocsDriftRule(Rule):
  code = 'GLT007'
  name = 'docs-drift'
  applies_to = ('glt_tpu/',)

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    docs = project.doc_text()
    if not docs:
      return       # no catalogs in this tree (fixture corpus runs)
    for node in ast.walk(ctx.tree):
      if isinstance(node, ast.Constant) and isinstance(node.value, str) \
          and _KNOB.match(node.value) and not _documented(node.value,
                                                         docs):
        yield Finding(
            rule=self.code, path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=scope_of(ctx.tree, node),
            token=node.value,
            message=(f'knob {node.value!r} is not in the '
                     'docs/observability.md / docs/performance.md '
                     'catalogs — document it where operators look'))
      elif isinstance(node, ast.Call) and \
          isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        receiver = Rule.dotted(node.func.value).lower()
        registers = (attr in _REGISTER
                     or (attr in _REGISTER_ON_REG and 'reg' in receiver))
        if not registers or not node.args:
          continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)
                and _METRIC.match(first.value)):
          continue
        if _documented(first.value, docs):
          continue
        yield Finding(
            rule=self.code, path=ctx.relpath, line=first.lineno,
            col=first.col_offset, scope=scope_of(ctx.tree, node),
            token=first.value,
            message=(f'metric {first.value!r} is registered but absent '
                     'from the docs/observability.md / '
                     'docs/performance.md catalogs — add it to the '
                     'metrics table'))

"""GLT002 — guarded-by inference: torn reads of lock-owned attributes.

Bug class: an attribute consistently written under ``with self._lock:``
in one method and then read (or written) bare in another — the
EmbeddingCache.hit_rate (PR 3), ServingMetrics.qps (PR 6) and
HistogramMetric.count_and_above / SloBurnEvaluator._last (PR 11) torn
reads, each found in review after shipping.

Inference, per class:
  1. lock attrs  = names used as ``with self.X:`` or assigned a
     ``threading.Lock/RLock/Condition/Semaphore`` in the class.
  2. an attr is *lock-owned* if any method stores to it under a lock.
  3. every bare access (load or store) of a lock-owned attr is a
     finding — except in ``__init__``/``__new__``/``__del__``
     (happens-before construction/teardown), in methods that manually
     ``self.X.acquire()`` (assumed hand-rolled locking), and in private
     helpers whose every intra-class call site is itself under the lock
     (computed to fixpoint).

Benign bare accesses (GIL-atomic reference swaps, single-writer stats)
belong in the baseline with a justification, not silently unflagged.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Set, Tuple

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of

_LOCKISH_NAME = re.compile(r'(lock|mutex|cond)', re.IGNORECASE)
_LOCK_CTORS = {'Lock', 'RLock', 'Condition', 'Semaphore',
               'BoundedSemaphore'}
_EXEMPT_METHODS = {'__init__', '__new__', '__del__'}


@dataclass
class _Access:
  method: str
  attr: str
  guarded: bool
  is_store: bool
  line: int
  col: int
  node: ast.AST = None


@dataclass
class _ClassInfo:
  name: str
  lock_attrs: Set[str] = field(default_factory=set)
  accesses: List[_Access] = field(default_factory=list)
  #: method -> [(callee, guarded at call site)]
  calls: Dict[str, List[Tuple[str, bool]]] = field(default_factory=dict)
  #: methods that manually self.X.acquire() a known lock
  manual: Set[str] = field(default_factory=set)


def _lock_attr_in_with(item: ast.withitem,
                       lock_attrs: Set[str]) -> bool:
  """True for ``with self.X:`` / ``with self.a.b._lock:`` guard items."""
  dotted = Rule.dotted(item.context_expr)
  if not dotted.startswith('self.'):
    return False
  return (dotted[len('self.'):] in lock_attrs
          or bool(_LOCKISH_NAME.search(dotted.split('.')[-1])))


class GuardedByRule(Rule):
  code = 'GLT002'
  name = 'guarded-by-violation'

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
      if isinstance(node, ast.ClassDef):
        yield from self._check_class(ctx, node)

  # -- per-class analysis ------------------------------------------------

  def _check_class(self, ctx: FileCtx,
                   cls: ast.ClassDef) -> Iterator[Finding]:
    info = _ClassInfo(cls.name)
    methods = [n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    # pass 1: find lock attributes (ctor assignment or with-usage)
    for m in methods:
      for n in ast.walk(m):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
          fn = Rule.dotted(n.value.func)
          if fn.split('.')[-1] in _LOCK_CTORS:
            for t in n.targets:
              if isinstance(t, ast.Attribute) and \
                  isinstance(t.value, ast.Name) and t.value.id == 'self':
                info.lock_attrs.add(t.attr)
        elif isinstance(n, ast.With):
          for item in n.items:
            expr = item.context_expr
            if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == 'self' and \
                _LOCKISH_NAME.search(expr.attr):
              info.lock_attrs.add(expr.attr)
    if not info.lock_attrs:
      return
    # pass 2: record accesses with guarded state
    for m in methods:
      self._walk_method(info, m)
    # lock-owned attrs: stored under guard somewhere
    owned = {a.attr for a in info.accesses if a.guarded and a.is_store}
    if not owned:
      return
    # fixpoint: helpers whose every call site is guarded are exempt
    assumed = self._assumed_locked(info)
    for acc in info.accesses:
      if acc.attr not in owned or acc.guarded:
        continue
      if acc.method in _EXEMPT_METHODS or acc.method in assumed \
          or acc.method in info.manual:
        continue
      yield Finding(
          rule=self.code, path=ctx.relpath, line=acc.line, col=acc.col,
          scope=f'{info.name}.{acc.method}',
          token=acc.attr,
          message=(f'self.{acc.attr} is '
                   f'{"written" if acc.is_store else "read"} without '
                   f'the lock but stored under it elsewhere in '
                   f'{info.name} (torn-read class: '
                   'EmbeddingCache.hit_rate, ServingMetrics.qps); '
                   'take the lock or baseline with a justification'))

  def _walk_method(self, info: _ClassInfo, method: ast.AST) -> None:
    name = method.name
    info.calls.setdefault(name, [])

    def walk(node: ast.AST, guarded: bool) -> None:
      for child in ast.iter_child_nodes(node):
        child_guarded = guarded
        if isinstance(child, ast.With):
          if any(_lock_attr_in_with(i, info.lock_attrs)
                 for i in child.items):
            for i in child.items:
              walk(i, guarded)           # the lock expr itself
            for stmt in child.body:
              # walk() classifies CHILDREN of the node it is handed, so
              # a def directly in the guarded body must get the nested-
              # closure exemption here — its body runs later, lockless
              if isinstance(stmt, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                walk(stmt, False)
              else:
                walk(stmt, True)
            continue
        elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
          # a nested closure does NOT run under the enclosing lock
          child_guarded = False
        elif isinstance(child, ast.Attribute) and \
            isinstance(child.value, ast.Name) and \
            child.value.id == 'self':
          attr = child.attr
          if attr in info.lock_attrs:
            walk(child, guarded)
            continue
          is_store = isinstance(child.ctx, (ast.Store, ast.Del))
          info.accesses.append(_Access(
              name, attr, guarded, is_store,
              child.lineno, child.col_offset, child))
        if isinstance(child, ast.Call):
          fn = child.func
          if isinstance(fn, ast.Attribute):
            # manual lock protocol: self.X.acquire()
            if fn.attr == 'acquire' and \
                isinstance(fn.value, ast.Attribute) and \
                isinstance(fn.value.value, ast.Name) and \
                fn.value.value.id == 'self' and \
                fn.value.attr in info.lock_attrs:
              info.manual.add(name)
            # intra-class call: self.m(...)
            if isinstance(fn.value, ast.Name) and fn.value.id == 'self':
              info.calls[name].append((fn.attr, child_guarded))
        walk(child, child_guarded)

    walk(method, False)

  @staticmethod
  def _assumed_locked(info: _ClassInfo) -> Set[str]:
    """Methods every one of whose intra-class call sites holds the
    lock (directly or via an already-assumed-locked caller)."""
    sites: Dict[str, List[Tuple[str, bool]]] = {}
    for caller, callees in info.calls.items():
      for callee, guarded in callees:
        sites.setdefault(callee, []).append((caller, guarded))
    assumed: Set[str] = set()
    changed = True
    while changed:
      changed = False
      for callee, callers in sites.items():
        if callee in assumed or callee not in info.calls:
          continue
        if all(g or c in assumed for c, g in callers):
          assumed.add(callee)
          changed = True
    return assumed

"""GLT005 — ``Future.set_result``/``set_exception`` without a done guard.

Bug class: the watchdog-vs-dispatcher race in serving/batcher.py — two
threads resolving the same Future; ``done()`` + ``set_*`` is not
atomic, so the loser raises ``InvalidStateError``, and an exception
escaping a watchdog thread kills it silently, permanently disabling
stall protection. The sanctioned idiom (batcher._fail_future) is::

  try:
    if not fut.done():
      fut.set_exception(err)
  except InvalidStateError:
    pass   # the other thread resolved it first

A ``set_*`` call passes the lint when ANY enclosing ``if``/``while``
tests ``.done()`` / ``.cancelled()`` / ``set_running_or_notify_cancel``
or an enclosing ``try`` catches InvalidStateError; single-resolver
call sites that need neither belong in the baseline with the reason
the race cannot happen.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of

_GUARD_ATTRS = {'done', 'cancelled', 'set_running_or_notify_cancel'}


def _parents(tree: ast.AST) -> Dict[int, ast.AST]:
  table: Dict[int, ast.AST] = {}
  for node in ast.walk(tree):
    for child in ast.iter_child_nodes(node):
      table[id(child)] = node
  return table


def _test_has_guard(test: ast.AST) -> bool:
  for n in ast.walk(test):
    if isinstance(n, ast.Attribute) and n.attr in _GUARD_ATTRS:
      return True
  return False


def _catches_invalid_state(handlers: List[ast.ExceptHandler]) -> bool:
  for h in handlers:
    if h.type is None:
      return True          # bare except swallows the race too (GLT006's
    for n in ast.walk(h.type):       # problem, not this rule's)
      name = getattr(n, 'attr', getattr(n, 'id', ''))
      if name in ('InvalidStateError', 'Exception', 'BaseException'):
        return True
  return False


class FutureGuardRule(Rule):
  code = 'GLT005'
  name = 'unguarded-future-resolve'

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    parents = _parents(ctx.tree)
    for node in ast.walk(ctx.tree):
      if not (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr in ('set_result', 'set_exception')):
        continue
      receiver = Rule.dotted(node.func.value) or '<expr>'
      # asyncio loop.call_soon_threadsafe style wrappers pass the
      # bound method, not a call — only direct calls land here.
      if self._guarded(node, parents):
        continue
      yield Finding(
          rule=self.code, path=ctx.relpath, line=node.lineno,
          col=node.col_offset, scope=scope_of(ctx.tree, node),
          token=f'{receiver}.{node.func.attr}',
          message=(f'{receiver}.{node.func.attr}() without a done-race '
                   'guard: a second resolver raises InvalidStateError '
                   'and kills the losing thread (watchdog/dispatcher '
                   'race, serving/batcher._fail_future is the idiom)'))

  @staticmethod
  def _guarded(node: ast.AST, parents: Dict[int, ast.AST]) -> bool:
    cur = node
    while True:
      parent = parents.get(id(cur))
      if parent is None:
        return False
      if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
        return False
      if isinstance(parent, (ast.If, ast.While)) and \
          _test_has_guard(parent.test):
        # either branch counts: `if not f.done(): resolve` and
        # `if f.done(): return / else: resolve` are both the guard
        return True
      if isinstance(parent, ast.Try) and \
          any(cur is stmt for stmt in parent.body) and \
          _catches_invalid_state(parent.handlers):
        # only the try BODY is protected by its handlers: a resolve
        # INSIDE an except/else/finally (`except Exception:
        # fut.set_exception(e)`) is the unguarded watchdog race
        # itself, not a guarded call
        return True
      cur = parent

"""GLT003/GLT004 — trace-time staging and jit closure hazards.

GLT003 bug class: Graph.window_arrays (PR 4) rebound live instance
state inside a function being traced by ``jax.jit`` — the attribute
ended up holding a leaked tracer, poisoning every later untraced read.
Any ``self.X = ...`` (or ``self.X[...] = ...``) executed at trace time
is that bug unless wrapped in ``jax.ensure_compile_time_eval()``.

GLT004 bug class: a jitted callee that *closes over* instance or
module-level arrays instead of taking them as arguments bakes the
array values into the compiled program — every swap of the underlying
object recompiles, violating the zero-steady-state-recompile contract
every engine test asserts (StreamSampler passes graph arrays as jit
ARGUMENTS for exactly this reason, PR 3).

Jit discovery is per-module and syntactic: decorated defs
(``@jax.jit``, ``@partial(jax.jit, ...)``), and direct wrap sites
(``jit(f)`` / ``jax.jit(self._m)``). Helpers merely *called from* a
jitted function are not chased — keep jit entry points honest and the
callees inherit the discipline.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of

_ARRAY_CTORS = ('jnp.', 'np.', 'jax.numpy.', 'numpy.')
_ARRAY_FNS = {'device_put', 'array', 'asarray', 'zeros', 'ones',
              'arange', 'full', 'empty'}


def _is_jit_expr(node: ast.AST) -> bool:
  """jit / jax.jit / pjit / eqx.filter_jit — as a bare expression."""
  dotted = Rule.dotted(node)
  last = dotted.split('.')[-1] if dotted else ''
  return last in ('jit', 'pjit', 'filter_jit')


def _jit_decorated(fn: ast.AST) -> bool:
  for dec in getattr(fn, 'decorator_list', []):
    if _is_jit_expr(dec):
      return True
    if isinstance(dec, ast.Call):
      if _is_jit_expr(dec.func):         # @jax.jit(static_argnums=...)
        return True
      if Rule.dotted(dec.func).split('.')[-1] == 'partial' and \
          dec.args and _is_jit_expr(dec.args[0]):
        return True                      # @partial(jax.jit, ...)
  return False


class _JitIndex:
  """Names of functions wrapped by jit somewhere in the module, plus
  module-level names bound to array-constructor calls."""

  def __init__(self, tree: ast.Module):
    self.wrapped_names: Set[str] = set()
    self.module_arrays: Set[str] = set()
    for node in ast.walk(tree):
      if isinstance(node, ast.Call) and _is_jit_expr(node.func):
        target = node.args[0] if node.args else None
        if isinstance(target, ast.Name):
          self.wrapped_names.add(target.id)
        elif isinstance(target, ast.Attribute):
          self.wrapped_names.add(target.attr)
    for stmt in tree.body:
      if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
        fn = Rule.dotted(stmt.value.func)
        if fn.startswith(_ARRAY_CTORS) or \
            fn.split('.')[-1] in _ARRAY_FNS:
          for t in stmt.targets:
            if isinstance(t, ast.Name):
              self.module_arrays.add(t.id)


def _in_compile_time_eval(ancestors: List[ast.AST]) -> bool:
  for a in ancestors:
    if isinstance(a, ast.With):
      for item in a.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call) and \
            Rule.dotted(expr.func).endswith('ensure_compile_time_eval'):
          return True
  return False


class TraceStagingRule(Rule):
  """Both GLT003 and GLT004 ride one jit-discovery pass; the rule is
  registered once and emits findings under either code."""

  code = 'GLT003'
  codes = ('GLT003', 'GLT004')
  name = 'trace-time-staging'
  applies_to = ()

  CODE_CLOSURE = 'GLT004'

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    index = _JitIndex(ctx.tree)
    for node in ast.walk(ctx.tree):
      if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        continue
      if not (_jit_decorated(node) or node.name in index.wrapped_names):
        continue
      yield from self._check_jitted(ctx, index, node)

  def _check_jitted(self, ctx: FileCtx, index: _JitIndex,
                    fn: ast.AST) -> Iterator[Finding]:
    params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
              + fn.args.posonlyargs}
    if fn.args.vararg:
      params.add(fn.args.vararg.arg)
    if fn.args.kwarg:
      params.add(fn.args.kwarg.arg)
    self_free = 'self' not in params
    scope = scope_of(ctx.tree, fn) or fn.name

    stack: List[ast.AST] = []

    def visit(node: ast.AST) -> Iterator[Finding]:
      stack.append(node)
      # -- GLT003: instance mutation at trace time
      store_attr = None
      if isinstance(node, ast.Attribute) and \
          isinstance(node.ctx, (ast.Store, ast.Del)) and \
          isinstance(node.value, ast.Name) and node.value.id == 'self':
        store_attr = node.attr
      elif isinstance(node, ast.Subscript) and \
          isinstance(node.ctx, (ast.Store, ast.Del)) and \
          isinstance(node.value, ast.Attribute) and \
          isinstance(node.value.value, ast.Name) and \
          node.value.value.id == 'self':
        store_attr = node.value.attr
      if store_attr is not None and not _in_compile_time_eval(stack):
        yield Finding(
            rule='GLT003', path=ctx.relpath, line=node.lineno,
            col=node.col_offset, scope=scope, token=store_attr,
            message=(f'self.{store_attr} is rebound inside a jitted '
                     'callee: at trace time this stores a tracer into '
                     'live state (Graph.window_arrays leak, PR 4); '
                     'stage under jax.ensure_compile_time_eval() or '
                     'move the mutation out of the traced function'))
      # -- GLT004: closure over instance / module arrays
      if self_free and isinstance(node, ast.Attribute) and \
          isinstance(node.ctx, ast.Load) and \
          isinstance(node.value, ast.Name) and node.value.id == 'self':
        parent = stack[-2] if len(stack) >= 2 else None
        is_callee = isinstance(parent, ast.Call) and parent.func is node
        if not is_callee:
          yield Finding(
              rule=self.CODE_CLOSURE, path=ctx.relpath,
              line=node.lineno, col=node.col_offset, scope=scope,
              token=node.attr,
              message=(f'jitted function closes over self.{node.attr}: '
                       'closed-over arrays are baked into the compiled '
                       'program and every rebind recompiles — pass it '
                       'as an argument (StreamSampler contract)'))
      if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load) \
          and node.id in index.module_arrays and node.id not in params:
        yield Finding(
            rule=self.CODE_CLOSURE, path=ctx.relpath,
            line=node.lineno, col=node.col_offset, scope=scope,
            token=node.id,
            message=(f'jitted function closes over module-level array '
                     f'{node.id!r}: pass it as an argument so rebinding '
                     'the module global cannot silently recompile'))
      for child in ast.iter_child_nodes(node):
        yield from visit(child)
      stack.pop()

    for stmt in fn.body:
      yield from visit(stmt)

"""GLT008 — 64-bit index/pick planes in ``ops/`` hot paths.

Bug class: the PR 12 narrowing audit — int64 slot planes and float64
accumulators silently double HBM traffic and defeat the VMEM budget of
the fused kernels. TPU-native code keeps index/pick planes int32 and
feature math float32/bf16; any deliberate 64-bit use in ops/ carries a
``# gltlint: disable=GLT008`` with its reason.
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of

_WIDE = {'int64', 'float64', 'uint64'}


class DtypeWidthRule(Rule):
  code = 'GLT008'
  name = 'wide-dtype-in-ops'
  applies_to = ('glt_tpu/ops/',)

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
      token = None
      if isinstance(node, ast.Attribute) and node.attr in _WIDE:
        base = Rule.dotted(node.value)
        if base in ('jnp', 'np', 'jax.numpy', 'numpy', 'dtypes'):
          token = f'{base}.{node.attr}'
      elif isinstance(node, ast.Constant) \
          and isinstance(node.value, str) and node.value in _WIDE:
        token = repr(node.value)
      if token is None:
        continue
      yield Finding(
          rule=self.code, path=ctx.relpath, line=node.lineno,
          col=node.col_offset, scope=scope_of(ctx.tree, node),
          token=token,
          message=(f'{token} in an ops/ hot path: index/pick planes are '
                   'int32 and feature math float32/bf16 on TPU (PR 12 '
                   'narrowing audit); widen only with a justified '
                   'disable comment'))

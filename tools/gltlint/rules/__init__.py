"""Importing this package registers every rule with the framework."""
from . import (  # noqa: F401
    docs_drift,
    dtype_width,
    env_knobs,
    futures,
    guarded_by,
    thread_except,
    trace_staging,
)

"""Shared helper: map an AST node to its dotted scope ('Cls.method').

Builds (and caches per-tree) a node -> enclosing-scope table in one
walk, so rules can report *where* a finding lives and baseline keys
survive line-number drift.
"""
from __future__ import annotations

import ast
from typing import Dict

# one-entry cache: hold the tree OBJECT (not id(tree) — ids recycle
# after gc, which could serve a stale table to a new tree)
_cached_tree: ast.AST = None
_cached_table: Dict[int, str] = {}


def _build(tree: ast.AST) -> Dict[int, str]:
  table: Dict[int, str] = {}

  def visit(node: ast.AST, scope: str) -> None:
    for child in ast.iter_child_nodes(node):
      child_scope = scope
      if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
        child_scope = f'{scope}.{child.name}' if scope else child.name
      table[id(child)] = child_scope
      visit(child, child_scope)

  table[id(tree)] = ''
  visit(tree, '')
  return table


def scope_of(tree: ast.AST, node: ast.AST) -> str:
  global _cached_tree, _cached_table
  if tree is not _cached_tree:
    _cached_tree = tree
    _cached_table = _build(tree)
  return _cached_table.get(id(node), '')

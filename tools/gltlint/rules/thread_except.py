"""GLT006 — silent exception swallow inside a thread/background target.

Bug class: a background loop (batcher dispatcher, stream ingest
applier, health-check prober) wrapping its body in ``except Exception:
pass`` — the thread keeps running, the failure leaves no trace, and
the first evidence is a production stall with an empty flight
recorder. Every handler inside a function used as a ``Thread(target=)``
/ ``executor.submit`` callee must re-raise, log, or record something
(any call or state store in the handler counts — precision over
recall; intent is judged in review, absence of ANY action is judged
here).
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import FileCtx, Finding, ProjectCtx, Rule
from ._scopes import scope_of


def _thread_targets(tree: ast.AST) -> Set[str]:
  """Names of functions handed to Thread(target=) / .submit(f) /
  start_new_thread(f) anywhere in the module."""
  targets: Set[str] = set()

  def add(node: ast.AST) -> None:
    if isinstance(node, ast.Name):
      targets.add(node.id)
    elif isinstance(node, ast.Attribute):
      targets.add(node.attr)

  for node in ast.walk(tree):
    if not isinstance(node, ast.Call):
      continue
    fn = Rule.dotted(node.func)
    last = fn.split('.')[-1]
    if last == 'Thread':
      for kw in node.keywords:
        if kw.arg == 'target':
          add(kw.value)
    elif last in ('submit', 'start_new_thread', 'run_in_executor',
                  'call_soon_threadsafe', 'after_idle'):
      if node.args:
        add(node.args[0])
  return targets


def _is_silent(handler: ast.ExceptHandler) -> bool:
  """No raise, no call, no state store, and the caught exception value
  is never used anywhere in the handler body."""
  for n in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
    if isinstance(n, (ast.Raise, ast.Call)):
      return False
    if isinstance(n, (ast.Attribute, ast.Subscript)) and \
        isinstance(n.ctx, ast.Store):
      return False
    if handler.name and isinstance(n, ast.Name) and \
        isinstance(n.ctx, ast.Load) and n.id == handler.name:
      return False     # `except E as e: item = e` — the value is
                       # captured for later surfacing, not dropped
    if isinstance(n, (ast.Continue, ast.Break, ast.Return)) and \
        handler.type is not None and \
        _names_only_stop_kinds(handler.type):
      return False     # except StopIteration/queue.Empty: continue —
  return True          # control-flow on an expected sentinel, not a swallow


def _names_only_stop_kinds(type_expr: ast.AST) -> bool:
  names = set()
  for n in ast.walk(type_expr):
    name = getattr(n, 'attr', None) or getattr(n, 'id', None)
    if name:
      names.add(name)
  sentinels = {'Empty', 'Full', 'StopIteration', 'TimeoutError',
               'queue', 'asyncio', 'socket', 'timeout'}
  return bool(names) and names <= sentinels


class ThreadExceptRule(Rule):
  code = 'GLT006'
  name = 'silent-thread-except'

  def check(self, ctx: FileCtx, project: ProjectCtx) -> Iterator[Finding]:
    targets = _thread_targets(ctx.tree)
    if not targets:
      return
    for node in ast.walk(ctx.tree):
      if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
              and node.name in targets):
        continue
      # exclude ENTIRE nested-def subtrees: a closure defined inside
      # the target is analyzed on its own if it is itself a target,
      # and its handlers must not be attributed to the outer function
      nested = set()
      for inner in ast.walk(node):
        if isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and inner is not node:
          nested.update(id(sub) for sub in ast.walk(inner))
      for inner in ast.walk(node):
        if id(inner) in nested:
          continue
        if not isinstance(inner, ast.ExceptHandler):
          continue
        if _is_silent(inner):
          kind = ast.unparse(inner.type) if inner.type else 'BaseException'
          yield Finding(
              rule=self.code, path=ctx.relpath, line=inner.lineno,
              col=inner.col_offset, scope=scope_of(ctx.tree, inner),
              token=f'{node.name}:{kind}',
              message=(f'except {kind} in thread target '
                       f'{node.name}() neither re-raises, records to '
                       'the FlightRecorder, nor logs — a background '
                       'failure here is invisible until the stall'))

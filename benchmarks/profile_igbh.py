"""IGBH bottleneck profile: where do the seconds per step go?

VERDICT r3 next #5 asks for the 65 seeds/s (r3 54M-edge run) to be
EXPLAINED by a profile. The fused DistHeteroTrainStep is one SPMD
program, so this times its separable sub-programs at identical shapes:

  * sample   — DistHeteroNeighborSampler.sample_from_nodes alone
               (hetero hop loops + dedup + collective exchanges);
  * eval     — eval_step: sample + feature all_to_all + batch assembly
               + model FORWARD (no backward/optimizer);
  * train    — the full fused step (adds backward + grad pmean + adam).

Decomposition: assembly+forward = eval - sample;
backward+optimizer = train - eval. (A dummy-batch model-only timing
overestimates badly — the fused path trims per-hop — so the model cost
is bounded between the two differences, not measured standalone.)
Every stage is synced to the host each iteration — eval_step blocks on
a scalar transfer internally, so the other stages must block too or
the differences absorb the dispatch-pipelining gap and bwd_opt can go
negative.

Prints one JSON line; the seeds/s of the fused step should reproduce
the r3 number at --papers 4000000 and the stage shares say what to fix.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples', 'igbh'))

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def timed(fn, iters, warmup, sync):
  import jax
  for _ in range(warmup):
    jax.block_until_ready(sync(fn()))
  t0 = time.time()
  for _ in range(iters):
    out = fn()
    jax.block_until_ready(sync(out))
  return (time.time() - t0) / iters * 1e3, out


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-devices', type=int, default=8)
  ap.add_argument('--papers', type=int, default=1_000_000)
  ap.add_argument('--batch-size', type=int, default=64)
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--hidden', type=int, default=128)
  ap.add_argument('--conv', default='rsage')
  ap.add_argument('--iters', type=int, default=8)
  ap.add_argument('--warmup', type=int, default=2)
  ap.add_argument('--cpu-mesh', action=argparse.BooleanOptionalAction,
                  default=True)
  ap.add_argument('--trace', default=None)
  ap.add_argument('--data-root', default=None,
                  help='reuse an existing synthesized tree')
  ap.add_argument('--part-root', default=None,
                  help='reuse an existing partition dir')
  args = ap.parse_args()

  if args.cpu_mesh:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={args.num_devices}')
  import jax
  if args.cpu_mesh:
    from glt_tpu.utils.backend import force_backend
    force_backend('cpu')
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  import jax.numpy as jnp
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.parallel import make_mesh
  from glt_tpu.partition import RandomPartitioner
  from glt_tpu.typing import reverse_edge_type
  from compress_graph import synthesize, compress
  from split_seeds import split_seeds
  from dist_train_rgnn import load_igbh_root

  root = args.data_root
  if root is not None and not os.path.exists(
      os.path.join(root, 'processed', 'meta.txt')):
    ap.error(f'--data-root {root} has no processed/meta.txt — refusing '
             'to silently re-synthesize into it')
  if root is None:
    root = tempfile.mkdtemp(prefix='igbh_prof_')
    print(f'synthesizing at {args.papers} papers...', file=sys.stderr)
    synthesize(root, args.papers)
    compress(root, layout='CSC', bf16=True, topology=False)
    split_seeds(root)
  counts, edges, feats, labels, train_idx, _ = load_igbh_root(root)
  num_classes = int(labels.max()) + 1
  fanout = [int(x) for x in args.fanout.split(',')]
  rev = {}
  for (s, r, d), ei in list(edges.items()):
    if s != d:
      rev[(d, f'rev_{r}', s)] = ei[::-1].copy()
  edges.update(rev)
  total_edges = sum(e.shape[1] for e in edges.values())

  part_root = args.part_root
  if part_root is not None:
    if not os.path.exists(os.path.join(part_root, 'META.json')):
      ap.error(f'--part-root {part_root} has no META.json — refusing '
               'to silently re-partition into it')
    from glt_tpu.partition.base import load_meta
    meta_parts = load_meta(part_root)['num_parts']
    if meta_parts != args.num_devices:
      ap.error(f'--part-root was partitioned with num_parts='
               f'{meta_parts} but --num-devices={args.num_devices}')
  else:
    part_root = tempfile.mkdtemp(prefix='igbh_prof_parts_')
    part_feats = {t: np.asarray(f, dtype=np.float32)
                  for t, f in feats.items()}
    RandomPartitioner(part_root, num_parts=args.num_devices,
                      num_nodes=dict(counts), edge_index=edges,
                      node_feat=part_feats).partition()
    del part_feats

  mesh = make_mesh(args.num_devices)
  dg = DistHeteroGraph.from_dataset_partitions(mesh, part_root)
  dss = [DistDataset().load(part_root, p)
         for p in range(args.num_devices)]
  dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t,
                                              dtype=jnp.bfloat16)
            for t in counts}
  model = RGNN(edge_types=[reverse_edge_type(e) for e in edges],
               hidden_features=args.hidden, out_features=num_classes,
               num_layers=len(fanout), conv=args.conv)
  tx = optax.adam(2e-3)
  step = DistHeteroTrainStep(
      dg, dfeats, model, tx, {'paper': labels},
      {e: fanout for e in edges},
      batch_size_per_device=args.batch_size, seed_type='paper', seed=0)
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)

  n_dev, bs = args.num_devices, args.batch_size
  rng = np.random.default_rng(0)
  seeds = train_idx[rng.integers(0, train_idx.shape[0],
                                 n_dev * bs)].reshape(n_dev, bs)
  nv = np.full(n_dev, bs)

  # --- stage: sampling only -------------------------------------------
  ms_sample, _ = timed(
      lambda: step.sampler.sample_from_nodes('paper', seeds, nv),
      args.iters, args.warmup,
      lambda o: jax.tree.leaves(o)[:1])

  # --- stage: eval step = sample + gather + assemble + model FORWARD --
  def eval_only():
    return step.eval_step(params, seeds, nv, jax.random.key(2))
  ms_eval, _ = timed(eval_only, args.iters, args.warmup, lambda o: o[0])

  # --- full fused train step ------------------------------------------
  state = {'p': params, 'o': opt}

  def full():
    p, o, loss = step(state['p'], state['o'], seeds, nv,
                      jax.random.key(1))
    state['p'], state['o'] = p, o
    return loss
  ms_train, _ = timed(full, args.iters, args.warmup, lambda o: o)

  if args.trace:
    with jax.profiler.trace(args.trace):
      for _ in range(3):
        loss = full()
      jax.block_until_ready(loss)
    print(f'# trace written to {args.trace}', file=sys.stderr)

  seeds_per_s = n_dev * bs / (ms_train / 1e3)
  assembly_fwd = ms_eval - ms_sample
  bwd_opt = ms_train - ms_eval
  print(json.dumps({
      'metric': 'igbh_step_breakdown',
      'value': round(seeds_per_s, 1),
      'unit': 'seeds/s',
      'vs_baseline': None,
      'detail': {
          'papers': int(counts['paper']), 'total_edges': total_edges,
          'batch_global': n_dev * bs,
          'ms_train_step': round(ms_train, 1),
          'ms_eval_step': round(ms_eval, 1),
          'ms_sample_only': round(ms_sample, 1),
          'ms_assembly_plus_forward': round(assembly_fwd, 1),
          'ms_backward_plus_optimizer': round(bwd_opt, 1),
          'share_sample': round(ms_sample / ms_train, 3),
          'share_assembly_fwd': round(assembly_fwd / ms_train, 3),
          'share_bwd_opt': round(bwd_opt / ms_train, 3),
          'backend': jax.devices()[0].platform},
  }))


if __name__ == '__main__':
  main()

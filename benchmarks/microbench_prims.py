"""Primitive-op microbenchmarks at sampler shapes — decides the dedup
formulation (scatter-table vs sort-based) and quantifies the gather
floor on the actual backend.

Each row: steady-state ms for one op at the bench.py hot-loop shapes
(frontier 153.6k, slots 768k, table 2.45M, edges 62M). Emits one JSON
line; ``GLT_BENCH_PLATFORM=cpu`` forces the CPU backend.
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')

N = 2_450_000
E = 62_000_000
M = 768_000          # hop-2 slot count
F = 153_600          # hop-2 frontier width


def _fence(out):
  """Hard completion fence: HOST READBACK of one element. On the axon
  tunnel block_until_ready can return before device work completes
  (microbench_gather_chained.py's calibration cell measured a 256 MB
  copy at 23 TB/s under block_until_ready — 29x physical HBM — vs
  31-800 GB/s under a value readback), so every timing boundary here
  transfers a real value instead."""
  import numpy as np
  leaf = out[0] if isinstance(out, (tuple, list)) else out
  return np.asarray(leaf).reshape(-1)[:1]


def timed(fn, *args, iters=20, warmup=3, donate_idx=None):
  """NB: without donate_idx every iteration reuses identical inputs;
  results are only trustworthy when corroborated (the committed r5
  cells for gathers/sorts match the in-program device trace). Cells
  measured with identical args AND contradicting the trace
  (window_gather_xla, uniform_rbg) are marked invalid in results_r5.md."""
  import time as _t
  out = None
  state = list(args)
  for _ in range(warmup):
    out = fn(*state)
    if donate_idx is not None:
      state[donate_idx] = out[donate_idx] if isinstance(out, tuple) else out
  _fence(out)
  t0 = _t.time()
  for _ in range(iters):
    out = fn(*state)
    if donate_idx is not None:
      state[donate_idx] = out[donate_idx] if isinstance(out, tuple) else out
  _fence(out)
  return (_t.time() - t0) / iters * 1e3


def main():
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp

  rng = np.random.default_rng(0)
  res = {}

  def rec(name, ms):
    res[name] = round(ms, 3)
    print(f'# {name}: {ms:.3f} ms', file=sys.stderr, flush=True)

  big = jnp.asarray(rng.integers(0, N, E, dtype=np.int64).astype(np.int32))
  table = jnp.full((N + 1,), -1, jnp.int32)
  idx_m = jnp.asarray(rng.integers(0, N, M).astype(np.int32))
  idx_f = jnp.asarray(rng.integers(0, E, F).astype(np.int32))
  idx_me = jnp.asarray(rng.integers(0, E, M).astype(np.int32))
  vals_m = jnp.asarray(rng.integers(0, 1 << 30, M).astype(np.int32))

  # -- gathers ---------------------------------------------------------
  rec('gather_768k_from_62M',
      timed(jax.jit(lambda i: jnp.take(big, i, mode='clip')), idx_me))
  rec('gather_768k_from_2.45M',
      timed(jax.jit(lambda i: jnp.take(table, i, mode='clip')), idx_m))
  rec('gather_153k_from_62M',
      timed(jax.jit(lambda i: jnp.take(big, i, mode='clip')), idx_f))

  # -- scatters into the [N+1] table -----------------------------------
  @functools.partial(jax.jit, donate_argnums=(0,))
  def scat_set(t, i, v):
    return t.at[i].set(v)

  @functools.partial(jax.jit, donate_argnums=(0,))
  def scat_min(t, i, v):
    return t.at[i].min(v)

  rec('scatter_set_768k_into_2.45M',
      timed(scat_set, table, idx_m, vals_m, donate_idx=0))
  rec('scatter_min_768k_into_2.45M',
      timed(scat_min, jnp.full((N + 1,), 2**31 - 1, jnp.int32), idx_m,
            vals_m, donate_idx=0))

  # -- sorts at dedup shapes -------------------------------------------
  rec('sort_768k_i32', timed(jax.jit(jnp.sort), vals_m))
  rec('argsort_768k_i32', timed(jax.jit(jnp.argsort), vals_m))
  two = jax.jit(lambda k, v: jax.lax.sort([k, v], num_keys=1))
  rec('sortpair_768k_i32', timed(two, idx_m, vals_m))

  # -- misc hot-loop ops -----------------------------------------------
  rec('cumsum_768k', timed(jax.jit(lambda v: jnp.cumsum(v)), vals_m))
  rec('top_k_768k_k5',
      timed(jax.jit(lambda v: jax.lax.top_k(v, 5)[0]),
            vals_m.reshape(F, 5).astype(jnp.float32)))
  rec('uniform_15x153k',
      timed(jax.jit(lambda k: jax.random.uniform(k, (15, F))),
            jax.random.key(1)))

  # -- sort-engine internals at hop-2 widths ---------------------------
  from glt_tpu.ops.scan import cumsum_i32
  from glt_tpu.ops.unique import _fill_forward, sorted_hop_dedup
  ind_m = (vals_m & 1)
  rec('cumsum_i32_768k', timed(jax.jit(cumsum_i32), ind_m))
  cm = 186_000 + M     # seen-set + slots, the real dedup sort width
  hd = jnp.asarray((rng.random(cm) < 0.2))
  pay1 = jnp.asarray(rng.integers(0, 1 << 20, cm).astype(np.int32))
  pay2 = jnp.asarray(rng.integers(0, 1 << 20, cm).astype(np.int32))
  rec('fill_forward_954k_2pay',
      timed(jax.jit(lambda h, a, b: _fill_forward(h, a, b)), hd, pay1,
            pay2))
  u_ids = jnp.asarray(
      rng.choice(N, 186_000, replace=False).astype(np.int32))
  u_labs = jnp.arange(186_000, dtype=jnp.int32)
  ok_m = jnp.asarray(rng.random(M) < 0.9)
  rows_m = jnp.asarray(rng.integers(0, F, M).astype(np.int32))

  @jax.jit
  def dedup_full(uid, ula, ids, ok, rows):
    d = sorted_hop_dedup(uid, ula, jnp.asarray(186_000, jnp.int32), ids,
                         ok, rows)
    return (d['labels3'], d['rows3'], d['new_head3'], d['u_ids2'],
            d['count2'])

  rec('sorted_hop_dedup_h2',
      timed(dedup_full, u_ids, u_labs, idx_m, ok_m, rows_m))

  # -- windowed gather: XLA slice-gather vs Pallas per-row DMA ---------
  # the weighted / full-neighborhood samplers read a [S, W] neighbor
  # window per seed; feature lookup reads [S, D] rows. XLA charges per
  # output element; the Pallas kernel pays one DMA descriptor per row.
  W = 96
  starts_f = jnp.asarray(rng.integers(0, E - W, F).astype(np.int32))

  @jax.jit
  def xla_windows(a, st):
    win = jnp.arange(W, dtype=jnp.int32)[None, :]
    return jnp.take(a, st[:, None] + win, mode='clip')

  rec(f'window_gather_xla_{F//1000}kx{W}', timed(xla_windows, big,
                                                 starts_f))
  try:
    from glt_tpu.ops.pallas_kernels import gather_windows, \
        pallas_available
    if pallas_available() and jax.default_backend() == 'tpu':
      for blk in (8, 32):
        rec(f'window_gather_dma_{F//1000}kx{W}_blk{blk}',
            timed(jax.jit(lambda a, st, _b=blk: gather_windows(
                a, st, W, block=_b)), big, starts_f))
  except Exception as exc:
    print(f'# pallas window gather unavailable: {exc}', file=sys.stderr)

  # -- PRNG implementation A/B (threefry default vs rbg) ---------------
  try:
    rbg_key = jax.random.key(1, impl='rbg')
    rec('uniform_15x153k_rbg',
        timed(jax.jit(lambda k: jax.random.uniform(k, (15, F))),
              rbg_key))
  except Exception as e:
    print(f'# rbg unavailable: {e}', file=sys.stderr)

  dev = jax.devices()[0]
  print(json.dumps({'metric': 'prim_ms', 'backend': dev.platform,
                    'shapes': {'N': N, 'E': E, 'M': M, 'F': F},
                    'ops': res}))


if __name__ == '__main__':
  main()

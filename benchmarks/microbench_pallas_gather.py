"""Decide the next sampling/feature-gather design from hardware data.

The op trace (profile_ops_tpu.py) shows the composed sampling step is
bound by per-element random gathers from the [62M] edge array
(fusion.434: 11.0 ms/batch = 14.3 ns/elt). Candidate escapes, each
measured here in isolation:

  xla_elem  : baseline — jnp.take of M elements from [E] (the wall).
  xla_rows  : XLA row gather [B, 128] from [N, 128] — is the feature
              path per-row or per-element serialized?
  dma_rows  : per-row async-copy windows (gather_windows, compiled) —
              DMA-issue-bound cost.
  vmem_take : Mosaic dynamic gather from a VMEM-resident table (2-D
              row/col form — Mosaic supports only 2-D gathers) — does
              the hardware have a vectorized VMEM gather, or does
              Mosaic also emit a scalar loop?

MEASUREMENT RULE (learned the hard way, see results_r5.md): the axon
tunnel memoizes identical repeated executions, so every timed iteration
MUST use distinct inputs — rates from identical-args loops (earlier
microbench cells like window_gather_xla "0.016 ms") are cache reads,
not measurements.

Prints one JSON line of ns/element rates; run on TPU (CPU = interpret
mode, parity only — rates there are meaningless).
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

ITERS = 6


def timed_varying(fn, variants):
  """Time fn over DISTINCT argument tuples, fenced by a host READBACK
  of one element of the last output — on this tunnel neither identical
  -args loops nor block_until_ready are trustworthy (see
  microbench_gather_chained.py's calibration cell)."""
  import numpy as np

  def fence(o):
    leaf = o[0] if isinstance(o, (tuple, list)) else o
    return np.asarray(leaf).reshape(-1)[:1]

  out = fn(*variants[0])
  fence(out)
  t0 = time.time()
  outs = [fn(*v) for v in variants[1:]]
  fence(outs[-1])
  return (time.time() - t0) / (len(variants) - 1), outs[-1]


def main():
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  cache = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), '.jax_cache')
  jax.config.update('jax_compilation_cache_dir', cache)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from jax.experimental import pallas as pl

  interpret = jax.default_backend() != 'tpu'
  E = 62_000_000
  M = 768_000
  rng = np.random.default_rng(0)
  arr = jnp.asarray(rng.integers(0, 2_450_000, E, dtype=np.int32))
  idxs = [jnp.asarray(rng.integers(0, E, M, dtype=np.int32))
          for _ in range(ITERS)]
  res = {'backend': jax.default_backend(), 'interpret': interpret}

  # --- xla_elem: the wall -------------------------------------------------
  f = jax.jit(lambda a, i: jnp.take(a, i, mode='clip'))
  dt, _ = timed_varying(f, [(arr, i) for i in idxs])
  res['xla_elem_ns_per_elt'] = round(1e9 * dt / M, 2)

  # --- xla_rows: feature-path row gather ----------------------------------
  NR, D = 1_000_000, 128
  BR = 153_600
  tab_rows = jnp.asarray(rng.normal(size=(NR, D)).astype(np.float32))
  rowss = [jnp.asarray(rng.integers(0, NR, BR, dtype=np.int32))
           for _ in range(ITERS)]
  fr = jax.jit(lambda t, r: jnp.take(t, r, axis=0, mode='clip'))
  dtr, _ = timed_varying(fr, [(tab_rows, r) for r in rowss])
  res['xla_rows_ns_per_row'] = round(1e9 * dtr / BR, 1)
  res['xla_rows_ns_per_elt'] = round(1e9 * dtr / (BR * D), 3)
  res['xla_rows_ms'] = round(1e3 * dtr, 3)

  # --- dma_rows: compiled gather_windows (row-block DMA) ------------------
  from glt_tpu.ops.pallas_kernels import gather_windows
  R, W = 153_600, 128
  startss = [jnp.asarray(
      np.sort(rng.integers(0, E - W, R).astype(np.int32)))
      for _ in range(ITERS)]
  for blk in (8, 32):
    try:
      g = functools.partial(gather_windows, block=blk,
                            interpret=interpret)
      dtw, _ = timed_varying(g, [(arr, s, W) for s in startss])
      res[f'dma_rows_b{blk}_ns_per_row'] = round(1e9 * dtw / R, 1)
      res[f'dma_rows_b{blk}_ms'] = round(1e3 * dtw, 3)
    except Exception as e:
      res[f'dma_rows_b{blk}_error'] = str(e)[:300]

  # --- vmem_take: Mosaic dynamic gather from a VMEM table (2-D form) ------
  # table [64, 128] VMEM-resident; idx [200, 3840] per variant, block
  # (8, 3840) per grid step; in-kernel gather tab[idx>>7, idx&127].
  TN, TD = 64, 128
  table2d = jnp.asarray(
      rng.integers(0, 1 << 20, (TN, TD), dtype=np.int32))
  idx_smalls = [jnp.asarray(
      rng.integers(0, TN * TD, M, dtype=np.int32)).reshape(200, 3840)
      for _ in range(ITERS)]

  def vmem_take_kernel(tab_ref, idx_ref, out_ref):
    idx = idx_ref[:]
    tab = tab_ref[:]
    out_ref[:] = tab[idx >> 7, idx & 127]

  @jax.jit
  def vmem_take(tab, ib):
    return pl.pallas_call(
        vmem_take_kernel,
        grid=(ib.shape[0] // 8,),
        in_specs=[
            pl.BlockSpec((TN, TD), lambda i: (0, 0)),
            pl.BlockSpec((8, 3840), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, 3840), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(ib.shape, jnp.int32),
        interpret=interpret,
    )(tab, ib)

  try:
    dtv, outv = timed_varying(vmem_take,
                              [(table2d, ib) for ib in idx_smalls])
    ref = jnp.take(table2d.reshape(-1), idx_smalls[-1], mode='clip')
    assert bool(jnp.array_equal(outv, ref)), 'vmem_take mismatch'
    res['vmem_take_ns_per_elt'] = round(1e9 * dtv / M, 2)
    res['vmem_take_ms'] = round(1e3 * dtv, 3)
  except Exception as e:
    res['vmem_take_error'] = str(e)[:300]

  print(json.dumps(res))


if __name__ == '__main__':
  main()

"""Adjudicate the scan8 fused-engine anomaly (bench_sort_scan8.json).

The round-5 suite captured 3.5e9 edges/s for the fused engine at
GLT_BENCH_SCAN=8 — 117x the scan4 number, while the unfused sort engine
held ~28.6M at every scan width. Either lax.scan at T=8 unlocked a real
schedule win, or that capture is an artifact. This script decides from
first principles on hardware:

  1. identical seed stacks through BOTH engines at scan widths 4 and 8;
  2. cross-engine checksum + valid-edge-count equality (the engines are
     bit-compatible by contract, tests/test_fused_hop.py);
  3. honest timing: per-call block_until_ready (no async pipelining
     credit), plus the bench's async-loop timing for comparison.

Emits one JSON line per (engine, scan) cell plus a verdict line.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  cache = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), '.jax_cache')
  jax.config.update('jax_compilation_cache_dir', cache)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.ops.pipeline import (make_dedup_tables, multihop_sample_many,
                                    checksum_outputs)
  from glt_tpu.ops.sample import sample_neighbors
  from glt_tpu.utils.rng import make_key

  NUM_NODES = int(os.environ.get('GLT_BENCH_NODES', 2_450_000))
  NUM_EDGES = int(os.environ.get('GLT_BENCH_EDGES', 62_000_000))
  BATCH = 1024
  FANOUT = (15, 10, 5)
  ITERS = int(os.environ.get('GLT_ADJ_ITERS', 10))

  dev = jax.devices()[0]
  print(f'# backend: {dev.platform} ({dev.device_kind})', file=sys.stderr)

  rng = np.random.default_rng(0)
  src = rng.integers(0, NUM_NODES, NUM_EDGES, dtype=np.int64)
  dst = (rng.random(NUM_EDGES) ** 2 * NUM_NODES).astype(np.int64) % NUM_NODES
  topo = Topology(indptr=None, edge_index=np.stack([src, dst]),
                  num_nodes=NUM_NODES)
  del src, dst
  indptr = jnp.asarray(topo.indptr.astype(np.int32))
  indices = jnp.asarray(topo.indices)
  one_hop = lambda ids, fanout, key, mask: sample_neighbors(
      indptr, indices, ids, fanout, key, seed_mask=mask)

  results = {}
  for scan in (4, 8):
    seed_pool = np.random.default_rng(7).integers(
        0, NUM_NODES, (ITERS, scan, BATCH))
    for engine in ('sort', 'fused'):
      os.environ['GLT_FUSED_HOP'] = '1' if engine == 'fused' else '0'
      os.environ['GLT_DEDUP'] = 'sort'

      def sample_batch(seeds, key, table, scratch):
        outs, table, scratch = multihop_sample_many(
            one_hop, seeds, jnp.full(scan, BATCH, jnp.int32), FANOUT,
            key, table, scratch)
        return (outs['num_sampled_edges'].sum(),
                checksum_outputs(outs), table, scratch)

      fn = jax.jit(sample_batch, donate_argnums=(2, 3))
      table, scratch = make_dedup_tables(NUM_NODES)
      keys = jax.random.split(make_key(0), ITERS)
      # warmup (compile)
      e, s, table, scratch = fn(jnp.asarray(seed_pool[0], jnp.int32),
                                keys[0], table, scratch)
      jax.block_until_ready((e, s))
      # honest per-call timing: sync every call
      edge_sum, sig_sum, tsync = 0, 0, 0.0
      per_call = []
      for i in range(ITERS):
        t0 = time.time()
        e, s, table, scratch = fn(jnp.asarray(seed_pool[i], jnp.int32),
                                  keys[i], table, scratch)
        jax.block_until_ready((e, s))
        dt = time.time() - t0
        per_call.append(dt)
        tsync += dt
        edge_sum += int(e)
        sig_sum += int(np.asarray(s, np.uint64)) & 0xFFFFFFFFFFFFFFFF
      eps_sync = edge_sum / tsync
      cell = {
          'engine': engine, 'scan': scan, 'iters': ITERS,
          'edges_total': edge_sum,
          'checksum': f'{sig_sum & 0xFFFFFFFFFFFFFFFF:016x}',
          'eps_sync': round(eps_sync, 1),
          'ms_per_call_median': round(1e3 * float(np.median(per_call)), 2),
          'ms_per_call_min': round(1e3 * float(np.min(per_call)), 2),
      }
      results[(engine, scan)] = cell
      print(json.dumps(cell))
      sys.stdout.flush()

  verdict = {
      'checksum_match_scan4':
          results[('sort', 4)]['checksum'] == results[('fused', 4)]['checksum'],
      'checksum_match_scan8':
          results[('sort', 8)]['checksum'] == results[('fused', 8)]['checksum'],
      'edges_match_scan8':
          results[('sort', 8)]['edges_total']
          == results[('fused', 8)]['edges_total'],
      'fused8_vs_sort8_speedup':
          round(results[('fused', 8)]['eps_sync']
                / results[('sort', 8)]['eps_sync'], 2),
  }
  print(json.dumps({'verdict': verdict}))


if __name__ == '__main__':
  main()

"""Beyond-HBM training through the loader-driven host-spill path.

VERDICT r3 next #4: the host-spill stores exist and pass parity tests,
but nothing TRAINS against a feature table larger than one chip's HBM.
This benchmark does, and quantifies the spill tax:

  * builds a [N, D] float32 feature table whose full size exceeds one
    chip's HBM at the TPU-scale defaults (--num-nodes 40M --feat-dim 128
    = 20.5 GB > 16 GB v5e HBM; the hot split is what fits), degree-
    sorted so hot rows are the frequently sampled ones (reference
    reorder + UnifiedTensor cache semantics, unified_tensor.cu:202-231);
  * trains GraphSAGE through NeighborLoader (the loader-driven spill
    path, which resolves cold rows on host between device calls; the
    fused-step alternative is measured by bench_fused_spill.py) at
    prefetch_depth {0, 2} and, as the control, the SAME graph with a
    fully device-resident table;
  * reports seeds/s for each, the spill/resident throughput ratio, and
    the measured cold rate (fraction of gathered rows served from
    host) — the number that decides whether the default prefetch_depth
    should overlap host gathers with device compute.

CPU-mesh runs (GLT_BENCH_PLATFORM=cpu) measure the RATIO scaled down
(--num-nodes 300k); the absolute beyond-HBM claim needs the real chip.

Prints one JSON line.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def main():
  ap = argparse.ArgumentParser()
  cpu = os.environ.get('GLT_BENCH_PLATFORM') == 'cpu'
  ap.add_argument('--num-nodes', type=int,
                  default=300_000 if cpu else 40_000_000)
  ap.add_argument('--avg-degree', type=int, default=8)
  ap.add_argument('--feat-dim', type=int, default=128)
  ap.add_argument('--split-ratio', type=float,
                  default=0.2,
                  help='hot fraction; at TPU defaults hot = 8M rows '
                       '(4.1 GB HBM) of a 20.5 GB table')
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--hidden', type=int, default=128)
  ap.add_argument('--steps', type=int, default=30)
  ap.add_argument('--warmup', type=int, default=3)
  args = ap.parse_args()

  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  import optax
  from glt_tpu.data import Dataset
  from glt_tpu.data.reorder import sort_by_in_degree
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import GraphSAGE

  rng = np.random.default_rng(0)
  n, e = args.num_nodes, args.num_nodes * args.avg_degree
  src = rng.integers(0, n, e, dtype=np.int64)
  # skewed in-degrees so the degree-sorted hot split actually captures
  # the frequently-sampled rows, as on real graphs
  dst = (rng.random(e) ** 2 * n).astype(np.int64) % n
  feats = rng.normal(size=(n, args.feat_dim)).astype(np.float32)
  labels = rng.integers(0, 16, n).astype(np.int32)
  fanout = [int(x) for x in args.fanout.split(',')]
  train_idx = rng.choice(n, min(n, 200_000), replace=False)

  def build(split_ratio, host_offload=False):
    # host_offload=False by default: this bench quantifies the LEGACY
    # host-phase route and the prefetch overlap; the offloaded config
    # is measured separately below (and the fused-step variant by
    # bench_fused_spill.py)
    ds = Dataset(edge_dir='out')
    ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
    ds.init_node_features(feats, split_ratio=split_ratio,
                          sort_func=sort_by_in_degree,
                          host_offload=host_offload)
    ds.init_node_labels(labels)
    return ds

  def run(ds, prefetch_depth, count_cold=False):
    loader = NeighborLoader(ds, fanout, input_nodes=train_idx,
                            batch_size=args.batch_size, shuffle=True,
                            drop_last=True, seed=0,
                            prefetch_depth=prefetch_depth)
    model = GraphSAGE(hidden_features=args.hidden, out_features=16,
                      num_layers=len(fanout))
    tx = optax.adam(1e-3)
    feat = ds.get_node_feature()
    cold_rows = total_rows = 0
    if count_cold:
      orig = feat.gather_cold_host

      def counting(rows):
        nonlocal cold_rows
        cold_rows += int(rows.shape[0])
        return orig(rows)
      feat.gather_cold_host = counting

    it = iter(loader)
    b0 = next(it)
    params = model.init(jax.random.key(0), b0)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, batch):
      def loss_fn(p):
        logits = model.apply(p, batch)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch.y).mean()
      loss, g = jax.value_and_grad(loss_fn)(params)
      up, opt = tx.update(g, opt)
      return optax.apply_updates(params, up), opt, loss

    params, opt, loss = step(params, opt, b0)
    jax.block_until_ready(loss)
    steps = seeds = 0
    t0 = None
    for i, batch in enumerate(it):
      if i == args.warmup:
        jax.block_until_ready(loss)
        cold_rows = 0
        total_rows = 0
        t0 = time.time()
      params, opt, loss = step(params, opt, batch)
      if i >= args.warmup:
        steps += 1
        seeds += args.batch_size
        total_rows += int(np.asarray(batch.node_count))
      if steps >= args.steps:
        break
    jax.block_until_ready(loss)
    dt = time.time() - (t0 or time.time())
    return {'seeds_per_s': round(seeds / max(dt, 1e-9), 1),
            'steps': steps,
            'cold_rate': (round(cold_rows / max(total_rows, 1), 4)
                          if count_cold else None)}

  t_build = time.time()
  resident = run(build(1.0), 0)
  spill_ds = build(args.split_ratio)
  spill0 = run(spill_ds, 0, count_cold=True)
  spill2 = run(build(args.split_ratio), 2, count_cold=True)
  # offloaded route: pinned-host cold block served inside the jitted
  # collate (gather_mixed) — no host phase, prefetch irrelevant
  offload = run(build(args.split_ratio, host_offload=True), 0)

  ratio0 = spill0['seeds_per_s'] / max(resident['seeds_per_s'], 1e-9)
  ratio2 = spill2['seeds_per_s'] / max(resident['seeds_per_s'], 1e-9)
  ratio_off = offload['seeds_per_s'] / max(resident['seeds_per_s'],
                                           1e-9)
  table_gb = n * args.feat_dim * 4 / 2**30
  hot_gb = table_gb * args.split_ratio
  dev = jax.devices()[0]
  print(json.dumps({
      'metric': 'spill_train_seeds_per_sec',
      'value': max(spill0['seeds_per_s'], spill2['seeds_per_s'],
                   offload['seeds_per_s']),
      'unit': 'seeds/s',
      'vs_baseline': round(max(ratio0, ratio2, ratio_off), 4),
      'detail': {
          'table_gb': round(table_gb, 2), 'hot_gb': round(hot_gb, 2),
          'split_ratio': args.split_ratio,
          'resident': resident,
          'spill_prefetch0': spill0, 'spill_prefetch2': spill2,
          'spill_offload': offload,
          'ratio_prefetch0': round(ratio0, 4),
          'ratio_prefetch2': round(ratio2, 4),
          'ratio_offload': round(ratio_off, 4),
          'recommended_prefetch_depth': 2 if ratio2 > ratio0 else 0,
          'wall_s': round(time.time() - t_build, 1),
          'backend': dev.platform},
  }))


if __name__ == '__main__':
  main()

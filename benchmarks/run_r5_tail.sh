#!/bin/bash
# Round-5 tail: serial chip-exclusive captures, run unattended after
# the beyond-HBM spill bench frees the chip. Each step is independently
# timeout-guarded and commits its artifact on success.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/tpu_runs
RES=benchmarks/results
mkdir -p "$OUT"

step() {
  local name=$1; shift
  echo "== $(date -Is) $name" >> "$OUT/r5_tail.log"
  "$@" >> "$OUT/r5_tail.log" 2>&1
  local rc=$?
  echo "== $(date -Is) $name done rc=$rc" >> "$OUT/r5_tail.log"
  git add -A "$OUT" "$RES" 2>/dev/null
  git commit -qm "TPU evidence (r5 tail): $name rc=$rc" 2>/dev/null
  return $rc
}

# 1. IGBH RGAT on the chip — the MLPerf-model workload on hardware.
#    Same schedule as the r5 CPU certification (lr 1e-3, 100-step
#    warmup, cosine), global batch 512 to match its MLLOG.
step igbh_rgat_tpu timeout 7000 python examples/igbh/dist_train_rgnn.py \
    --papers 1000000 --num-devices 1 --batch-size 512 \
    --learning-rate 1e-3 --lr-schedule cosine --lr-warmup-steps 100 \
    --mlperf --seed 0 \
    --data-root /tmp/igbh_data_1m_tpu

# 2. capped-bucket drain grid on hardware
step bench_bucket_drain_tpu timeout 2400 \
    python benchmarks/bench_bucket_drain.py

# 3. accuracy certification under TPU numerics
step certify_accuracy_tpu timeout 3600 \
    python benchmarks/certify_accuracy.py \
    --out "$RES/certify_accuracy_tpu_clean.json"

# 4. primitive microbench re-capture with readback fencing
step microbench_prims_tpu2 timeout 2400 bash -c \
    'python benchmarks/microbench_prims.py > benchmarks/tpu_runs/microbench_prims_tpu2.json'

# 5. feature gather XLA baseline (the r5-morning casualty)
step bench_feature_xla timeout 1200 bash -c \
    'python benchmarks/bench_feature.py > benchmarks/tpu_runs/bench_feature_xla2.log 2>&1'

# 6. fresh headline for the round record
step bench_final timeout 1200 bash -c \
    'python bench.py > benchmarks/tpu_runs/bench_final_r5.json'

echo "== $(date -Is) r5 tail complete" >> "$OUT/r5_tail.log"

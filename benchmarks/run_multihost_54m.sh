#!/bin/bash
# VERDICT r4 next #5: the multihost builder past the single-process
# wall — 2 jax.distributed processes, 4M papers (~54M base directed
# edges), FULL epoch, per-rank-only partition loading, host-offloaded
# spill (--split-ratio), per-rank peak-RSS probes in the logs.
#
# Stage 1 (once, single process): synthesize + partition the tree.
# Stage 2: the 2-process epoch. Serial on this 1-core box.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results
mkdir -p "$OUT"
DATA=${IGBH_DATA:-/tmp/igbh_4m_data}
PARTS=${IGBH_PARTS:-/tmp/igbh_4m_parts}
BS=${IGBH_BS:-256}
PORT=${IGBH_PORT:-29811}

if [ ! -f "$PARTS/META.json" ]; then
  echo "== $(date -Is) multihost 54m: prep (synthesize+partition)" \
      >> "$OUT/evidence_chain.log"
  timeout 14400 python examples/igbh/dist_train_rgnn.py \
      --papers 4000000 --data-root "$DATA" --part-root "$PARTS" \
      --epochs 1 --steps-per-epoch 1 --batch-size 8 --val-batches 1 \
      > "$OUT/igbh_54m_prep.log" 2>&1
  echo "== $(date -Is) prep done rc=$?" >> "$OUT/evidence_chain.log"
fi

echo "== $(date -Is) multihost 54m: 2-proc epoch bs=$BS" \
    >> "$OUT/evidence_chain.log"
timeout 36000 python examples/igbh/dist_train_rgnn.py \
    --coordinator 127.0.0.1:$PORT --nprocs 2 --rank 1 \
    --data-root "$DATA" --part-root "$PARTS" \
    --epochs 1 --batch-size "$BS" --split-ratio 0.5 --val-batches 10 \
    > "$OUT/igbh_54m_mh_rank1.log" 2>&1 &
R1=$!
timeout 36000 python examples/igbh/dist_train_rgnn.py \
    --coordinator 127.0.0.1:$PORT --nprocs 2 --rank 0 \
    --data-root "$DATA" --part-root "$PARTS" \
    --epochs 1 --batch-size "$BS" --split-ratio 0.5 --val-batches 10 \
    > "$OUT/igbh_54m_mh_rank0.log" 2>&1
RC0=$?
wait $R1
RC1=$?
echo "== $(date -Is) 2-proc epoch done rc0=$RC0 rc1=$RC1" \
    >> "$OUT/evidence_chain.log"

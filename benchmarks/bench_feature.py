"""Feature-gather throughput benchmark.

Reference protocol: benchmarks/api/bench_feature.py (--split_ratio=0.2,
prints lookup throughput on random ids). Measures the two residency
paths: device-resident gather (HBM) and the hot/cold split with host
spill (the UVA analogue). Prints one JSON line per config.
"""
import argparse
import json
import time

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))  # repo root -> glt_tpu

import numpy as np


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-rows', type=int, default=2_000_000)
  ap.add_argument('--dim', type=int, default=128)
  ap.add_argument('--batch', type=int, default=200_000)
  ap.add_argument('--iters', type=int, default=30)
  ap.add_argument('--split-ratio', type=float, default=0.2)
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  from glt_tpu.data import Feature

  rng = np.random.default_rng(0)
  feats = rng.normal(size=(args.num_rows, args.dim)).astype(np.float32)

  # path 1: fully device resident
  f_dev = Feature(feats, split_ratio=1.0)
  f_dev.lazy_init()
  gather = jax.jit(lambda rows: f_dev.device_gather(rows))
  ids = jnp.asarray(rng.integers(0, args.num_rows, args.batch))
  gather(ids).block_until_ready()
  t0 = time.time()
  out = None
  for i in range(args.iters):
    out = gather(ids)
  out.block_until_ready()
  dt = time.time() - t0
  rate = args.batch * args.iters / dt
  print(json.dumps({
      'metric': 'feature_gather_rows_per_sec_device',
      'value': round(rate, 1), 'unit': 'rows/s',
      'vs_baseline': None}))

  # path 2: hot/cold split (degree-ordered hot prefix assumed)
  f_split = Feature(feats, split_ratio=args.split_ratio)
  f_split.lazy_init()
  # 80% of requests hit the hot prefix (cache-friendly skew)
  hot = rng.integers(0, int(args.num_rows * args.split_ratio),
                     int(args.batch * 0.8))
  cold = rng.integers(int(args.num_rows * args.split_ratio),
                      args.num_rows, args.batch - hot.shape[0])
  ids_np = np.concatenate([hot, cold])
  rng.shuffle(ids_np)
  t0 = time.time()
  for i in range(args.iters):
    out = f_split[ids_np]
  dt = time.time() - t0
  rate = args.batch * args.iters / dt
  print(json.dumps({
      'metric': 'feature_gather_rows_per_sec_split',
      'value': round(rate, 1), 'unit': 'rows/s',
      'vs_baseline': None}))


if __name__ == '__main__':
  main()

"""Distributed sampling scaling benchmark — the reference's scale_up
figure protocol (benchmarks/: sampled edges/s as workers are added).

Runs DistNeighborSampler over a partitioned synthetic products-slice
graph at 1..P devices and reports throughput per mesh size. On the
virtual CPU mesh this measures SCALING SHAPE (collective overhead vs
parallel speedup), not absolute TPU throughput — the same program runs
unmodified on a real slice.

Prints one JSON line: edges/s per mesh size + parallel efficiency.
``GLT_BENCH_PLATFORM=cpu`` + XLA_FLAGS=--xla_force_host_platform_device_count=8
run it hardware-free.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root -> glt_tpu

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def run_mesh(n_dev, root_by_p, num_nodes, fanout, batch, iters, warmup):
  import jax
  import jax.numpy as jnp
  from glt_tpu.distributed import DistGraph, DistNeighborSampler
  from glt_tpu.parallel import make_mesh
  mesh = make_mesh(n_dev)
  dg = DistGraph.from_dataset_partitions(mesh, root_by_p[n_dev])
  s = DistNeighborSampler(dg, fanout, seed=0)
  warmup = max(warmup, 1)  # first call compiles; never time it
  iters = max(iters, 1)
  rng = np.random.default_rng(0)
  outs = None
  t0 = None
  for it in range(warmup + iters):
    if it == warmup:
      jax.block_until_ready(outs['num_sampled_edges'])
      t0 = time.time()
    seeds = rng.integers(0, num_nodes, (n_dev, batch))
    outs = s.sample_from_nodes(seeds, np.full(n_dev, batch))
  total = np.asarray(
      jax.block_until_ready(outs['num_sampled_edges'])).sum()
  dt = time.time() - t0
  # num_sampled_edges is per-batch; edges/s = edges-per-iter * iters / dt
  edges_per_iter = float(total)
  return edges_per_iter * iters / dt


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=200_000)
  ap.add_argument('--avg-degree', type=int, default=15)
  ap.add_argument('--batch-size', type=int, default=512)
  ap.add_argument('--fanout', default='15,10')
  ap.add_argument('--iters', type=int, default=15)
  ap.add_argument('--warmup', type=int, default=3)
  ap.add_argument('--mesh-sizes', default='1,2,4,8')
  args = ap.parse_args()

  sizes = [int(x) for x in args.mesh_sizes.split(',')]
  os.environ.setdefault(
      'XLA_FLAGS',
      f'--xla_force_host_platform_device_count={max(sizes)}')
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  from glt_tpu.partition import RandomPartitioner

  n = args.num_nodes
  e = n * args.avg_degree
  rng = np.random.default_rng(0)
  src = rng.integers(0, n, e, dtype=np.int64)
  dst = (rng.random(e) ** 2 * n).astype(np.int64) % n
  fanout = [int(x) for x in args.fanout.split(',')]

  root_by_p = {}
  for p in sizes:
    root = tempfile.mkdtemp(prefix=f'bdist{p}_')
    RandomPartitioner(root, num_parts=p, num_nodes=n,
                      edge_index=np.stack([src, dst])).partition()
    root_by_p[p] = root

  results = {}
  for p in sizes:
    eps = run_mesh(p, root_by_p, n, fanout, args.batch_size,
                   args.iters, args.warmup)
    results[p] = round(eps, 1)

  base = results[sizes[0]] / sizes[0]
  eff = {p: round(results[p] / (p * base), 3) for p in sizes}
  backend = jax.devices()[0].platform
  out = {
      'metric': 'dist_sampled_edges_per_sec',
      'value': results[sizes[-1]],
      'unit': 'edges/s',
      'vs_baseline': None,
      'per_mesh_size': results,
      'parallel_efficiency': eff,
      'backend': backend,
  }
  if backend == 'cpu':
    # all virtual devices share the same physical cores: efficiency
    # here measures collective/program overhead (a regression canary),
    # NOT speedup — real speedup needs real chips per device
    out['note'] = ('cpu virtual mesh shares cores; efficiency is an '
                   'overhead canary, not a speedup measurement')
  print(json.dumps(out))


if __name__ == '__main__':
  main()

"""Beyond-HBM training through the FUSED SPMD step (host-offloaded
cold blocks) — the tax of serving cold feature rows from pinned host
memory inside the compiled program.

The round-4 host-offload work (parallel/dist_feature.py cold_array +
compute_on('device_host') gather) lets SPMDSageTrainStep consume
split_ratio<1 stores directly — the TPU-native analog of the
reference's UVA zero-copy path (unified_tensor.cu:202-231: device
kernels reading cudaHostRegisterMapped CPU rows across PCIe). This
benchmark quantifies it:

  * one graph, one model, three stores: fully device-resident,
    host-offloaded at --split-ratio (degree-ordered ids, so hot rows
    are the frequently-sampled prefix of each shard), and — as the
    upper bound of the tax — offloaded with split near 0 (everything
    cold);
  * N fused steps each (sample + all_to_all + host cold gather +
    fwd/bwd + pmean as ONE program); reports seeds/s and the
    offload/resident ratio.

At the TPU defaults the table (40M x 128 f32 = 20.5 GB) exceeds one
v5e chip's 16 GB HBM and the hot split (0.2 -> 4.1 GB) is what
fits — a genuine beyond-HBM fused-training run. CPU-mesh runs
(GLT_BENCH_PLATFORM=cpu) measure the ratio scaled down.

Prints one JSON line.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def _prefix_graph(src, dst, n_ctrl):
  """Degree-preserving control graph over the id prefix [0, n_ctrl):
  keeps every edge whose src is in range (out-degrees match the full
  graph exactly, so per-hop sampling work is comparable) and folds dst
  into range with a modulo (preserving the low-id skew shape; a
  both-endpoints filter would thin average degree by the dst-keep
  fraction and make the control's sampling easier than the real run)."""
  from glt_tpu.data import Dataset
  keep = src < n_ctrl
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src[keep], dst[keep] % n_ctrl]),
                num_nodes=n_ctrl)
  return ds.get_graph()


def main():
  ap = argparse.ArgumentParser()
  cpu = os.environ.get('GLT_BENCH_PLATFORM') == 'cpu'
  ap.add_argument('--num-nodes', type=int,
                  default=300_000 if cpu else 40_000_000)
  ap.add_argument('--avg-degree', type=int, default=8)
  ap.add_argument('--feat-dim', type=int, default=128)
  ap.add_argument('--split-ratio', type=float, default=0.2)
  ap.add_argument('--batch-size', type=int, default=256,
                  help='per device')
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--hidden', type=int, default=128)
  ap.add_argument('--steps', type=int, default=30)
  ap.add_argument('--warmup', type=int, default=3)
  ap.add_argument('--num-devices', type=int, default=0,
                  help='0 = all available (set 8 with the cpu mesh)')
  ap.add_argument('--cache-dir', default=None,
                  help='save/load the synthetic arrays here (the 40M '
                       'TPU build costs ~40 min on the 1-core host; '
                       'the cache turns reruns into a ~2 min load)')
  args = ap.parse_args()

  def phase(msg):
    print(f'# {time.strftime("%H:%M:%S")} {msg}', file=sys.stderr,
          flush=True)

  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  if cpu and args.num_devices:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={args.num_devices}')
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import optax
  from glt_tpu.data import Dataset
  from glt_tpu.models import GraphSAGE
  from glt_tpu.parallel import (
      ShardedFeature, SPMDSageTrainStep, make_mesh,
  )

  n_dev = args.num_devices or len(jax.devices())
  rng = np.random.default_rng(0)
  n, e = args.num_nodes, args.num_nodes * args.avg_degree
  cache = args.cache_dir
  meta_ok = False
  if cache and os.path.exists(os.path.join(cache, 'meta.json')):
    with open(os.path.join(cache, 'meta.json')) as f:
      meta_ok = json.load(f) == {'n': n, 'e': e, 'd': args.feat_dim}
  if meta_ok:
    phase(f'loading cached arrays from {cache}')
    src = np.load(os.path.join(cache, 'src.npy'))
    dst = np.load(os.path.join(cache, 'dst.npy'))
    feats = np.load(os.path.join(cache, 'feats.npy'), mmap_mode='r')
    labels = np.load(os.path.join(cache, 'labels.npy'))
  else:
    phase(f'building synthetic arrays: n={n} e={e}')
    src = rng.integers(0, n, e, dtype=np.int64)
    # skew toward LOW ids: under the range partition book the hot
    # prefix of each shard is the frequently-sampled set (the
    # degree-sort cache semantics without materializing a reorder of
    # this synthetic id space)
    dst = (rng.random(e) ** 2 * n).astype(np.int64) % n
    feats = rng.normal(size=(n, args.feat_dim)).astype(np.float32)
    labels = rng.integers(0, 16, n).astype(np.int32)
    if cache:
      phase(f'saving cache to {cache}')
      os.makedirs(cache, exist_ok=True)
      np.save(os.path.join(cache, 'src.npy'), src)
      np.save(os.path.join(cache, 'dst.npy'), dst)
      np.save(os.path.join(cache, 'feats.npy'), feats)
      np.save(os.path.join(cache, 'labels.npy'), labels)
      with open(os.path.join(cache, 'meta.json'), 'w') as f:
        json.dump({'n': n, 'e': e, 'd': args.feat_dim}, f)
  fanout = [int(x) for x in args.fanout.split(',')]
  phase('building CSR')
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
  graph = ds.get_graph()
  mesh = make_mesh(n_dev)
  model = GraphSAGE(hidden_features=args.hidden, out_features=16,
                    num_layers=len(fanout))
  tx = optax.adam(1e-3)
  train_idx = rng.choice(n, min(n, 200_000), replace=False)

  def run(split_ratio, control_nodes=None):
    if control_nodes is not None:
      # fit-scale resident control: same protocol on the id prefix
      pref = feats[:control_nodes]
      g_ctrl = _prefix_graph(src, dst, control_nodes)
      sf = ShardedFeature(pref, mesh, split_ratio=split_ratio)
      step = SPMDSageTrainStep(mesh, model, tx, g_ctrl, sf,
                               labels[:control_nodes], fanouts=fanout,
                               batch_size_per_device=args.batch_size)
      t_idx = train_idx[train_idx < control_nodes]
    else:
      sf = ShardedFeature(feats, mesh, split_ratio=split_ratio)
      step = SPMDSageTrainStep(mesh, model, tx, graph, sf, labels,
                               fanouts=fanout,
                               batch_size_per_device=args.batch_size)
      t_idx = train_idx
    offloaded = sf.cold_array is not None
    params = step.init_params(jax.random.key(0))
    opt = tx.init(params)
    gb = args.batch_size * n_dev
    order = rng.permutation(t_idx.shape[0])

    def seeds_at(i):
      lo = (i * gb) % t_idx.shape[0]
      sel = order[lo:lo + gb]
      if sel.shape[0] < gb:
        sel = np.concatenate([sel, np.resize(order, gb - sel.shape[0])])
      return t_idx[sel]

    phase(f'run split_ratio={split_ratio} control={control_nodes}: '
          'compiling + stepping')
    loss = None
    t0 = None
    for i in range(args.warmup + args.steps):
      if i == args.warmup:
        _ = np.asarray(loss)   # host readback: the only trustworthy
        t0 = time.time()       # completion fence on the axon tunnel
      keys = jax.random.split(jax.random.key(i), n_dev)
      params, opt, loss = step(params, opt, seeds_at(i),
                               np.full(n_dev, args.batch_size), keys)
    final_loss = float(np.asarray(loss)[0])   # readback fences the chain
    dt = time.time() - t0
    del step, sf, params, opt
    cell = {'seeds_per_s': round(args.steps * gb / max(dt, 1e-9), 1),
            'offloaded': offloaded,
            'loss': round(final_loss, 4)}
    phase(f'run done: {cell}')
    return cell

  t_all = time.time()
  table_gb = n * args.feat_dim * 4 / 2**30
  # A fully-resident store cannot exist above the HBM budget — that is
  # the point of the beyond-HBM run. There the resident baseline comes
  # from a FIT-SCALE control (same degree/fanout/batch, node count
  # scaled so the table fits), reported as resident['control_nodes'].
  hbm_budget_gb = float(os.environ.get('GLT_HBM_BUDGET_GB', '12'))
  offload = run(args.split_ratio)   # the essential number first: a
  # timeout after this point still leaves the beyond-HBM datum in the
  # stderr log
  if (jax.devices()[0].platform == 'tpu'
      and table_gb > hbm_budget_gb):
    ctrl_n = int(hbm_budget_gb * 0.6 * 2**30 / (args.feat_dim * 4))
    resident = dict(run(1.0, control_nodes=ctrl_n),
                    control_nodes=ctrl_n)
  else:
    resident = run(1.0)
  all_cold = run(0.0)  # 1-row hot floor: the tax's upper bound
  ratio = offload['seeds_per_s'] / max(resident['seeds_per_s'], 1e-9)
  ratio_ac = all_cold['seeds_per_s'] / max(resident['seeds_per_s'],
                                           1e-9)
  print(json.dumps({
      'metric': 'fused_spill_train_seeds_per_sec',
      'value': offload['seeds_per_s'],
      'unit': 'seeds/s',
      'vs_baseline': round(ratio, 4),
      'detail': {
          'table_gb': round(table_gb, 2),
          'hot_gb': round(table_gb * args.split_ratio, 2),
          'split_ratio': args.split_ratio,
          'num_devices': n_dev,
          'resident': resident, 'offloaded': offload,
          'all_cold': all_cold,
          'ratio_offloaded': round(ratio, 4),
          'ratio_all_cold': round(ratio_ac, 4),
          'wall_s': round(time.time() - t_all, 1),
          'backend': jax.devices()[0].platform},
  }))


if __name__ == '__main__':
  main()

"""Dump the optimized HLO of the composed sampling step and print the
bodies of the named fusions (default: the top ops from the device
trace, profile_ops_tpu.py) so the hot fusion can be attributed to
source ops. Host-side only — uses the persistent compile cache, cheap
once the profile run has compiled the program.

Usage: python benchmarks/dump_hlo.py fusion.434 fusion.440 [...]
Writes the full text to benchmarks/tpu_runs/sample_batch_opt.hlo.
"""
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
  names = [a for a in sys.argv[1:] if not a.startswith('-')] or \
      ['fusion.434', 'fusion.440', 'fusion.417']
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  cache = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), '.jax_cache')
  jax.config.update('jax_compilation_cache_dir', cache)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.ops.pipeline import (make_dedup_tables,
                                    multihop_sample_many,
                                    checksum_outputs)
  from glt_tpu.ops.sample import sample_neighbors
  from glt_tpu.utils.rng import make_key

  NUM_NODES = 2_450_000
  NUM_EDGES = 62_000_000
  BATCH, FANOUT, SCAN = 1024, (15, 10, 5), 4

  # tiny graph is fine for lowering; shapes of indptr/indices must match
  # the profiled program, so build the same-size arrays cheaply
  indptr = jnp.zeros((NUM_NODES + 1,), jnp.int32)
  indices = jnp.zeros((NUM_EDGES,), jnp.int32)
  one_hop = lambda ids, fanout, key, mask: sample_neighbors(
      indptr, indices, ids, fanout, key, seed_mask=mask)

  def sample_batch(seeds, key, table, scratch):
    outs, table, scratch = multihop_sample_many(
        one_hop, seeds, jnp.full(SCAN, BATCH, jnp.int32), FANOUT,
        key, table, scratch)
    return (outs['num_sampled_edges'].sum(), checksum_outputs(outs),
            table, scratch)

  table, scratch = make_dedup_tables(NUM_NODES)
  seeds = jnp.zeros((SCAN, BATCH), jnp.int32)
  lowered = jax.jit(sample_batch, donate_argnums=(2, 3)).lower(
      seeds, make_key(0), table, scratch)
  compiled = lowered.compile()
  txt = compiled.as_text()
  out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          'tpu_runs', 'sample_batch_opt.hlo')
  with open(out_path, 'w') as f:
    f.write(txt)
  print(f'# wrote {out_path} ({len(txt)} bytes)', file=sys.stderr)

  # print each requested fusion's computation body
  for name in names:
    # the fusion instruction line names its called computation
    m = re.search(rf'%?{re.escape(name)} = .*', txt)
    if not m:
      print(f'== {name}: NOT FOUND')
      continue
    line = m.group(0)
    print(f'== {name} instruction:\n{line[:2000]}\n')
    cm = re.search(r'calls=([%\w.\-]+)', line)
    if cm:
      comp = cm.group(1).lstrip('%')
      bm = re.search(
          rf'^(%?{re.escape(comp)}\b.*?^}})', txt,
          re.M | re.S)
      if bm:
        body = bm.group(1)
        print(f'-- body of {comp} ({len(body)} bytes):')
        print(body[:8000])
        print()


if __name__ == '__main__':
  main()

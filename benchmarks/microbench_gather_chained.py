"""Bulletproof gather timing on the axon tunnel + the fusion hypothesis.

Two prior harnesses produced impossible rates on this tunnel (identical
-args loops AND varying-args loops both showed cells exceeding HBM
bandwidth), so every cell here serializes iterations with a DEVICE-SIDE
dependency chain: each call's index input is tied (via
lax.optimization_barrier) to a scalar carried out of the previous call,
making overlap and result reuse impossible, and wall time covers the
whole chain with one final block_until_ready (amortizing tunnel RTT).

Cells:
  copy_bw       : y = x + 1 over 256 MB — calibration. If this reports
                  > ~900 GB/s the harness is lying; trust nothing.
  elem_alone    : gather M from [E], result returned whole (un-fused —
                  the gather's consumer is the output buffer itself).
  elem_fused    : same gather + a cheap fused consumer+reducer, forcing
                  XLA to fuse the gather into a loop (the composed
                  pipeline's situation per the op trace).
  elem_barrier  : gather wrapped in optimization_barrier on BOTH sides,
                  then the same consumer/reducer — does the barrier
                  recover the un-fused rate inside a larger program?
  rows_alone    : [B,128] row gather from [1M,128].

THE QUESTION: the op trace charges fusion.434 (the hop-2 gather, fused
with reshapes) 11.0 ms/batch = 14.3 ns/elt, while a standalone gather
benchmarked at ~bandwidth. If elem_fused >> elem_alone ~= elem_barrier,
the sampler fix is one optimization_barrier around each hop gather.

Prints one JSON line. TPU only (rates on CPU are meaningless).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

ITERS = 8


def main():
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  cache = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), '.jax_cache')
  jax.config.update('jax_compilation_cache_dir', cache)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from jax import lax

  E = 62_000_000
  M = 768_000
  rng = np.random.default_rng(0)
  arr = jnp.asarray(rng.integers(0, 2_450_000, E, dtype=np.int32))
  idxs = [jnp.asarray(rng.integers(0, E, M, dtype=np.int32))
          for _ in range(ITERS)]
  res = {'backend': jax.default_backend(), 'iters': ITERS}

  def chain_run(fn, inputs, *extra):
    """fn(dep, x, *extra) -> (out_scalar_dep, payload). Runs the chain;
    times the second pass (the first eats compile/RTT warmup).

    The pass is fenced by an actual HOST READBACK of the carried
    scalar, not block_until_ready: the calibration cell showed this
    tunnel's block_until_ready can return before the device work is
    done (copy_bw read 23 TB/s — 29x physical HBM), while a value
    readback cannot lie. The chain ties every call to the previous
    call's output, so the final readback transitively fences them all.
    """
    dep = jnp.zeros((), jnp.int32)
    for x in inputs:          # warm pass: compile + page in
      dep, _ = fn(dep, x, *extra)
    _ = int(dep)              # hard fence: host readback
    t0 = time.time()
    for x in inputs:
      dep, _ = fn(dep, x, *extra)
    _ = int(dep)              # hard fence: host readback
    return (time.time() - t0) / len(inputs)

  # --- calibration: big elementwise copy ---------------------------------
  big = jnp.asarray(rng.normal(size=(64_000_000,)).astype(np.float32))
  bigs = [big, big + 1, big + 2, big + 3]

  @jax.jit
  def copy_step(dep, x):
    x2, _ = lax.optimization_barrier((x, dep))
    y = x2 + 1.0
    return y[0].astype(jnp.int32) + dep, y

  dt = chain_run(copy_step, bigs)
  res['copy_bw_GBps'] = round(2 * big.nbytes / dt / 1e9, 1)
  res['copy_ms'] = round(1e3 * dt, 3)
  print(json.dumps(res), file=sys.stderr, flush=True)

  # --- elem_alone --------------------------------------------------------
  # NB: the source array rides as an ARGUMENT everywhere below — a
  # closed-over array becomes a jit constant, and axon ships constants
  # in the remote-compile request body (HTTP 413 at 248 MB).
  @jax.jit
  def elem_alone(dep, idx, a):
    idx2, _ = lax.optimization_barrier((idx, dep))
    out = jnp.take(a, idx2, mode='clip')
    return out[0] + dep, out

  dt = chain_run(elem_alone, idxs, arr)
  res['elem_alone_ns_per_elt'] = round(1e9 * dt / M, 3)
  res['elem_alone_ms'] = round(1e3 * dt, 3)
  print(json.dumps(res), file=sys.stderr, flush=True)

  # --- elem_fused: gather + fused consumer -------------------------------
  @jax.jit
  def elem_fused(dep, idx, a):
    idx2, _ = lax.optimization_barrier((idx, dep))
    out = jnp.take(a, idx2, mode='clip')
    s = (out ^ (out >> 7)).sum(dtype=jnp.int32)   # cheap fused consumer
    return s + dep, s

  dt = chain_run(elem_fused, idxs, arr)
  res['elem_fused_ns_per_elt'] = round(1e9 * dt / M, 3)
  res['elem_fused_ms'] = round(1e3 * dt, 3)
  print(json.dumps(res), file=sys.stderr, flush=True)

  # --- elem_barrier: barriered gather inside the same program ------------
  @jax.jit
  def elem_barrier(dep, idx, a):
    idx2, _ = lax.optimization_barrier((idx, dep))
    out = jnp.take(a, idx2, mode='clip')
    (out,) = lax.optimization_barrier((out,))
    s = (out ^ (out >> 7)).sum(dtype=jnp.int32)
    return s + dep, s

  dt = chain_run(elem_barrier, idxs, arr)
  res['elem_barrier_ns_per_elt'] = round(1e9 * dt / M, 3)
  res['elem_barrier_ms'] = round(1e3 * dt, 3)
  print(json.dumps(res), file=sys.stderr, flush=True)

  # --- rows_alone --------------------------------------------------------
  NR, D, BR = 1_000_000, 128, 153_600
  tab = jnp.asarray(rng.normal(size=(NR, D)).astype(np.float32))
  rowss = [jnp.asarray(rng.integers(0, NR, BR, dtype=np.int32))
           for _ in range(ITERS)]

  @jax.jit
  def rows_alone(dep, r, t):
    r2, _ = lax.optimization_barrier((r, dep))
    out = jnp.take(t, r2, axis=0, mode='clip')
    return out[0, 0].astype(jnp.int32) + dep, out

  dt = chain_run(rows_alone, rowss, tab)
  res['rows_alone_ns_per_row'] = round(1e9 * dt / BR, 2)
  res['rows_alone_ms'] = round(1e3 * dt, 3)
  res['rows_alone_GBps'] = round(BR * D * 4 / dt / 1e9, 1)

  print(json.dumps(res))


if __name__ == '__main__':
  main()

#!/bin/bash
# Poll the axon tunnel; run the full TPU suite as soon as it answers.
# The tunnel wedges for minutes-to-hours at a time, so perf evidence
# collection must be opportunistic: probe cheaply (90 s child) on an
# interval, fire run_tpu_suite.sh on the first success, and stop.
# Usage: nohup benchmarks/tpu_watch.sh [interval_s] & (default 600)
set -u
cd "$(dirname "$0")/.."
INTERVAL=${1:-600}
OUT=benchmarks/tpu_runs
mkdir -p "$OUT"
while true; do
  if GLT_BENCH_PROBE_TIMEOUT=90 timeout 120 \
      python bench.py --probe > "$OUT/probe.log" 2>&1; then
    echo "$(date -Is) tunnel alive; starting suite" >> "$OUT/watch.log"
    bash benchmarks/run_tpu_suite.sh >> "$OUT/watch.log" 2>&1
    echo "$(date -Is) suite finished" >> "$OUT/watch.log"
    exit 0
  fi
  echo "$(date -Is) tunnel wedged; retry in ${INTERVAL}s" \
      >> "$OUT/watch.log"
  sleep "$INTERVAL"
done

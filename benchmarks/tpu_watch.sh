#!/bin/bash
# Poll the axon tunnel; run the full TPU suite whenever it answers.
# The tunnel wedges for minutes-to-hours at a time, so perf evidence
# collection must be opportunistic: probe cheaply (90 s child) on an
# interval, fire run_tpu_suite.sh on success, git-commit any non-empty
# evidence immediately (the tunnel can drop mid-suite; whatever landed
# must survive), then RE-ARM — a flaky mid-suite drop must cost one
# suite pass, not the rest of the round.
# Usage: nohup benchmarks/tpu_watch.sh [interval_s] & (default 600)
set -u
cd "$(dirname "$0")/.."
INTERVAL=${1:-600}
OUT=benchmarks/tpu_runs
mkdir -p "$OUT"

commit_evidence() {
  # Commit ONLY the non-empty evidence files, by explicit pathspec: a
  # bare commit would sweep unrelated staged work, and a directory
  # pathspec would commit working-tree state of every tracked file
  # under $OUT — including a JSON a wedged suite step just truncated.
  local files=()
  for f in "$OUT"/*.json; do
    [ -s "$f" ] && files+=("$f")
  done
  for f in "$OUT"/*.log; do
    [ -s "$f" ] && files+=("$f")
  done
  [ "${#files[@]}" -eq 0 ] && return 0
  # stage first: suite outputs are usually UNTRACKED, and a commit
  # pathspec only matches files git already knows about
  git add -- "${files[@]}" 2>/dev/null || true
  if git commit -q \
      -m "TPU evidence: auto-commit from tpu_watch ($(date -Is))" \
      -- "${files[@]}" 2>/dev/null; then
    echo "$(date -Is) evidence committed" >> "$OUT/watch.log"
  fi
}

while true; do
  if GLT_BENCH_PROBE_TIMEOUT=90 timeout 120 \
      python bench.py --probe > "$OUT/probe.log" 2>&1; then
    echo "$(date -Is) tunnel alive; starting suite" >> "$OUT/watch.log"
    bash benchmarks/run_tpu_suite.sh >> "$OUT/watch.log" 2>&1
    echo "$(date -Is) suite finished" >> "$OUT/watch.log"
    commit_evidence
    # Re-arm: if the suite was cut short by a wedge, the next probe
    # success re-runs it (steps are cheap to redo; evidence accretes).
    sleep "$INTERVAL"
  else
    echo "$(date -Is) tunnel wedged; retry in ${INTERVAL}s" \
        >> "$OUT/watch.log"
    sleep "$INTERVAL"
  fi
done

"""Accuracy certification: reference-equivalent learning, skeptic-proof.

The reference certifies learning with ogbn-products test accuracy
(examples/train_sage_ogbn_products.py:16, ~0.787). Real datasets are not
downloadable in this environment, so this harness certifies the SAME
capability — multi-hop neighborhood aggregation through the sampled
pipeline — with a synthetic protocol designed to admit no shortcut:

  * labels are a fixed random linear readout of each node's MEAN 2-HOP
    NEIGHBOR FEATURES ONLY (label_i = argmax W . (A_mean^2 f)_i). Own
    features and 1-hop aggregates carry (asymptotically) no label
    signal, so
      - a feature-only linear probe must sit at ~chance,
      - a 1-layer GNN (sees f_i and (A f)_i) must sit at ~chance,
      - a 2-layer GNN can only climb by actually aggregating the
        sampled 2-hop frontier — the capability under test.
  * >= 3 seeds, mean +/- std reported per model family.
  * per-epoch accuracy curve committed for the 2-layer model.

Writes benchmarks/results/certify_accuracy.json (the committed
artifact) and prints one JSON summary line.

Run (CPU is fine; accuracy is backend-independent):
  GLT_BENCH_PLATFORM=cpu python benchmarks/certify_accuracy.py
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo root -> glt_tpu

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def mean_aggregate(src, dst, feats, num_nodes, chunk=2_000_000):
  """(A_mean f)_i = mean of feats[dst] over out-edges of i, chunked."""
  acc = np.zeros((num_nodes, feats.shape[1]), np.float32)
  deg = np.zeros(num_nodes, np.float32)
  for lo in range(0, src.shape[0], chunk):
    s, d = src[lo:lo + chunk], dst[lo:lo + chunk]
    np.add.at(acc, s, feats[d])
    np.add.at(deg, s, 1.0)
  return acc / np.maximum(deg, 1.0)[:, None]


def run_family(ds, train_idx, test_idx, fanout, hidden, n_classes,
               batch_size, epochs, seed, eval_batches, curve=False):
  """Train one GraphSAGE through the sampled pipeline; returns
  (final_test_acc, per_epoch_accs or None)."""
  import jax
  import jax.numpy as jnp
  import optax
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import GraphSAGE

  loader = NeighborLoader(ds, fanout, input_nodes=train_idx,
                          batch_size=batch_size, shuffle=True,
                          drop_last=True, seed=seed)
  model = GraphSAGE(hidden_features=hidden, out_features=n_classes,
                    num_layers=len(fanout))
  b0 = next(iter(loader))
  params = model.init(jax.random.key(seed), b0)
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      return optax.softmax_cross_entropy_with_integer_labels(
          logits, batch.y).mean()
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  @jax.jit
  def predict(params, batch):
    return jnp.argmax(model.apply(params, batch), -1)

  def evaluate():
    ev = NeighborLoader(ds, fanout, input_nodes=test_idx,
                        batch_size=batch_size, shuffle=False,
                        drop_last=False, seed=seed + 1)
    correct = total = 0
    for i, batch in enumerate(ev):
      if i >= eval_batches:
        break
      pred = np.asarray(predict(params, batch))
      yb = np.asarray(batch.y)
      nv = int((batch.metadata or {}).get('n_valid', yb.shape[0]))
      correct += int((pred[:nv] == yb[:nv]).sum())
      total += nv
    return correct / max(total, 1)

  accs = []
  for _ in range(epochs):
    for batch in loader:
      params, opt, _ = step(params, opt, batch)
    if curve:
      accs.append(round(evaluate(), 4))
  final = accs[-1] if curve else evaluate()
  return final, (accs if curve else None)


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=200_000)
  ap.add_argument('--avg-degree', type=int, default=10)
  ap.add_argument('--feat-dim', type=int, default=64)
  ap.add_argument('--classes', type=int, default=16)
  ap.add_argument('--hidden', type=int, default=128)
  ap.add_argument('--batch-size', type=int, default=512)
  ap.add_argument('--epochs', type=int, default=8)
  ap.add_argument('--seeds', type=int, default=3)
  ap.add_argument('--train-frac', type=float, default=0.1)
  ap.add_argument('--eval-batches', type=int, default=20)
  ap.add_argument('--out', default=os.path.join(
      os.path.dirname(os.path.abspath(__file__)), 'results',
      'certify_accuracy.json'))
  args = ap.parse_args()

  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  from glt_tpu.data import Dataset

  rng = np.random.default_rng(0)
  n, e = args.num_nodes, args.num_nodes * args.avg_degree
  src = rng.integers(0, n, e, dtype=np.int64)
  dst = (rng.random(e) ** 2 * n).astype(np.int64) % n
  feats = rng.normal(size=(n, args.feat_dim)).astype(np.float32)
  # 2-hop-only label signal: A_mean(A_mean f)
  hop1 = mean_aggregate(src, dst, feats, n)
  hop2 = mean_aggregate(src, dst, hop1, n)
  w = rng.normal(size=(args.feat_dim, args.classes)).astype(np.float32)
  labels = np.argmax(hop2 @ w, 1).astype(np.int32)
  del hop1, hop2

  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
  ds.init_node_features(feats)
  ds.init_node_labels(labels)
  perm = rng.permutation(n)
  train_idx = perm[: int(n * args.train_frac)]
  test_idx = perm[int(n * args.train_frac): int(n * args.train_frac)
                  + 20_000]

  # control 1: feature-only least-squares probe (fresh fit)
  sub = rng.choice(train_idx, min(20_000, train_idx.shape[0]),
                   replace=False)
  onehot = np.eye(args.classes, dtype=np.float32)[labels[sub]]
  w_fit, *_ = np.linalg.lstsq(feats[sub], onehot, rcond=None)
  probe_acc = float(
      (np.argmax(feats[test_idx] @ w_fit, 1) == labels[test_idx]).mean())

  chance = 1.0 / args.classes
  t0 = time.time()
  one_hop, two_hop, curves = [], [], []
  for s in range(args.seeds):
    # control 2: 1-layer GNN — sees f and (A f); must stay ~chance
    acc1, _ = run_family(ds, train_idx, test_idx, [args.avg_degree],
                         args.hidden, args.classes, args.batch_size,
                         args.epochs, 100 + s, args.eval_batches)
    # under test: 2-layer GNN through the sampled pipeline
    acc2, curve = run_family(
        ds, train_idx, test_idx, [args.avg_degree, args.avg_degree],
        args.hidden, args.classes, args.batch_size, args.epochs,
        200 + s, args.eval_batches, curve=True)
    one_hop.append(round(acc1, 4))
    two_hop.append(round(acc2, 4))
    curves.append(curve)
    print(f'# seed {s}: 1-hop {acc1:.4f}  2-hop {acc2:.4f}  '
          f'curve {curve}', file=sys.stderr)

  result = {
      'metric': 'certify_accuracy_2hop',
      'value': round(float(np.mean(two_hop)), 4),
      'unit': 'accuracy',
      'vs_baseline': None,
      'detail': {
          'protocol': '2-hop-only labels; controls must sit at chance',
          'chance': round(chance, 4),
          'linear_probe_acc': round(probe_acc, 4),
          'one_hop_acc_mean': round(float(np.mean(one_hop)), 4),
          'one_hop_acc_std': round(float(np.std(one_hop)), 4),
          'one_hop_accs': one_hop,
          'two_hop_acc_mean': round(float(np.mean(two_hop)), 4),
          'two_hop_acc_std': round(float(np.std(two_hop)), 4),
          'two_hop_accs': two_hop,
          'two_hop_curves_per_epoch': curves,
          'seeds': args.seeds, 'epochs': args.epochs,
          'num_nodes': n, 'num_edges': e,
          'seconds': round(time.time() - t0, 1),
          'backend': jax.devices()[0].platform,
      },
  }
  os.makedirs(os.path.dirname(args.out), exist_ok=True)
  with open(args.out, 'w') as f:
    json.dump(result, f, indent=1)
  print(json.dumps(result))


if __name__ == '__main__':
  main()

"""Escalating Pallas-compile probe for the axon remote compiler.

gather_windows (manual per-row DMA, PrefetchScalarGridSpec) dies with
"HTTP 500: tpu_compile_helper subprocess exit code 1" on the tunnel —
a server-side compiler crash with no visible diagnostics. This probe
compiles+runs a ladder of kernels from trivial to the failing shape so
the first failing rung names the construct:

  1 vmem_id        : identity through VMEM blocks
  2 smem_scalar    : scalar input in SMEM steering a @pl.when
  3 dma_fixed      : manual HBM->VMEM async_copy of a static slice
  4 dma_dynamic    : async_copy with pl.ds(dynamic scalar) source
  5 prefetch_grid  : PrefetchScalarGridSpec with index_map using the
                     prefetched scalars (the gather_rows pattern)
  6 gather_windows : the real kernel at toy size
  7 vmem_take2d    : in-kernel 2-D dynamic gather from a VMEM table

Prints one status line per rung. Run on TPU with the chip otherwise
idle.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  import jax.numpy as jnp
  from jax.experimental import pallas as pl
  from jax.experimental.pallas import tpu as pltpu

  interpret = jax.default_backend() != 'tpu'
  rng = np.random.default_rng(0)
  status = {}

  def rung(name, fn):
    try:
      out = fn()
      _ = np.asarray(out).reshape(-1)[:1]
      status[name] = 'ok'
    except Exception as e:
      status[name] = str(e)[:200]
    print(json.dumps({name: status[name]}), flush=True)

  # 1 vmem_id
  x = jnp.asarray(rng.normal(size=(128, 128)).astype(np.float32))

  def vmem_id():
    def k(i, o):
      o[:] = i[:]
    return pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret)(x)

  rung('1_vmem_id', vmem_id)

  # 2 smem_scalar
  def smem_scalar():
    s = jnp.asarray([[3]], jnp.int32)

    def k(s_ref, i_ref, o_ref):
      o_ref[:] = i_ref[:] * s_ref[0, 0].astype(jnp.float32)

    return pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret)(s, x)

  rung('2_smem_scalar', smem_scalar)

  # 3 dma_fixed
  big = jnp.asarray(rng.integers(0, 99, 4096, dtype=np.int32))

  def dma_fixed():
    def k(h_ref, o_ref):
      def body(scr, sem):
        dma = pltpu.make_async_copy(h_ref.at[pl.ds(256, 128)], scr, sem)
        dma.start()
        dma.wait()
        o_ref[:] = scr[:]
      pl.run_scoped(body, scr=pltpu.VMEM((128,), jnp.int32),
                    sem=pltpu.SemaphoreType.DMA(()))

    return pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct((128,), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret)(big)

  rung('3_dma_fixed', dma_fixed)

  # 4 dma_dynamic
  def dma_dynamic():
    st = jnp.asarray([[512]], jnp.int32)

    def k(s_ref, h_ref, o_ref):
      def body(scr, sem):
        dma = pltpu.make_async_copy(
            h_ref.at[pl.ds(s_ref[0, 0], 128)], scr, sem)
        dma.start()
        dma.wait()
        o_ref[:] = scr[:]
      pl.run_scoped(body, scr=pltpu.VMEM((128,), jnp.int32),
                    sem=pltpu.SemaphoreType.DMA(()))

    return pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct((128,), jnp.int32),
        in_specs=[pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret)(st, big)

  rung('4_dma_dynamic', dma_dynamic)

  # 5 prefetch_grid — gather_rows pattern on (n,1,d) singleton trick
  def prefetch_grid():
    tab = jnp.asarray(rng.normal(size=(64, 1, 128)).astype(np.float32))
    rows = jnp.asarray(rng.integers(0, 64, 16, dtype=np.int32))

    def k(idx_ref, row_ref, o_ref):
      o_ref[:] = row_ref[:]

    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(16,),
        in_specs=[pl.BlockSpec((1, 1, 128), lambda i, idx: (idx[i], 0, 0))],
        out_specs=pl.BlockSpec((1, 1, 128), lambda i, idx: (i, 0, 0)),
    )
    out = pl.pallas_call(
        k, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((16, 1, 128), jnp.float32),
        interpret=interpret)(rows, tab)
    ref = jnp.take(tab, rows, axis=0)
    assert bool(jnp.allclose(out, ref)), 'prefetch_grid mismatch'
    return out

  rung('5_prefetch_grid', prefetch_grid)

  # 6 gather_windows toy
  def gw():
    from glt_tpu.ops.pallas_kernels import gather_windows
    arr = jnp.asarray(rng.integers(0, 99, 8192, dtype=np.int32))
    starts = jnp.asarray(
        np.sort(rng.integers(0, 8192 - 128, 64).astype(np.int32)))
    out = gather_windows(arr, starts, 128, block=8, interpret=interpret)
    ref = jnp.stack([jax.lax.dynamic_slice(arr, (int(s),), (128,))
                     for s in np.asarray(starts)])
    assert bool(jnp.array_equal(out, ref)), 'gather_windows mismatch'
    return out

  rung('6_gather_windows', gw)

  # 7 vmem_take2d
  def vt():
    TN, TD = 64, 128
    tab = jnp.asarray(rng.integers(0, 1 << 20, (TN, TD), dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, TN * TD, (8, 3840), dtype=np.int32))

    def k(t_ref, i_ref, o_ref):
      ii = i_ref[:]
      o_ref[:] = t_ref[:][ii >> 7, ii & 127]

    out = pl.pallas_call(
        k, out_shape=jax.ShapeDtypeStruct(idx.shape, jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret)(tab, idx)
    ref = jnp.take(tab.reshape(-1), idx, mode='clip')
    assert bool(jnp.array_equal(out, ref)), 'vmem_take mismatch'
    return out

  rung('7_vmem_take2d', vt)

  print(json.dumps(status))


if __name__ == '__main__':
  main()

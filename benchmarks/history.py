"""Bench trajectory: an append-only JSONL of every bench run, keyed
(bench, engine, scale, device).

The BENCH_r0x.json snapshots record *rounds*; nothing compares run N
to run N-1, so a silent 30% throughput regression between rounds reads
as weather. This module is the memory: every bench emission appends
one row per measured series, and ``scripts/bench_compare.py`` gates a
fresh run against the recorded baseline (noise-aware: median of the
last N runs with a relative threshold).

Row shape (one JSON object per line)::

    {"ts": ..., "bench": "sampler_engine", "engine": "sort+fused",
     "scale": "N100000_E1000000_B1024_S4", "device": "cpu",
     "value": 1234567.8, "unit": "edges/s", ...extra}

Key contract: rows compare ONLY within an exact (bench, engine, scale,
device) match — a CPU smoke row never baselines a TPU headline, and a
batch-1024 row never baselines batch-256.

CLI (what CI's regression-gate step runs)::

    python benchmarks/history.py append --history bench_history.jsonl \
        --bench-json bench_smoke.json
    python benchmarks/history.py show --history bench_history.jsonl
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import List, Optional


def run_key(row: dict) -> tuple:
  return (str(row.get('bench', '')), str(row.get('engine', '')),
          str(row.get('scale', '')), str(row.get('device', '')))


def append_run(path: str, bench: str, value: float, unit: str = '',
               engine: str = '', scale: str = '', device: str = '',
               ts: Optional[float] = None, **extra) -> dict:
  """Append one run row; creates the file (and parents) on first use."""
  row = {
      'ts': float(ts if ts is not None else time.time()),
      'bench': str(bench),
      'engine': str(engine),
      'scale': str(scale),
      'device': str(device),
      'value': float(value),
      'unit': str(unit),
  }
  row.update(extra)
  parent = os.path.dirname(os.path.abspath(path))
  os.makedirs(parent, exist_ok=True)
  with open(path, 'a') as f:
    f.write(json.dumps(row, sort_keys=True) + '\n')
  return row


def load_runs(path: str, bench: Optional[str] = None,
              engine: Optional[str] = None,
              scale: Optional[str] = None,
              device: Optional[str] = None) -> List[dict]:
  """All rows (append order == time order), optionally filtered.
  Malformed lines are skipped — a truncated write from a killed run
  must not poison the whole trajectory."""
  if not os.path.exists(path):
    return []
  out = []
  with open(path) as f:
    for line in f:
      line = line.strip()
      if not line:
        continue
      try:
        row = json.loads(line)
      except ValueError:
        continue
      if bench is not None and row.get('bench') != bench:
        continue
      if engine is not None and row.get('engine') != engine:
        continue
      if scale is not None and row.get('scale') != scale:
        continue
      if device is not None and row.get('device') != device:
        continue
      out.append(row)
  return out


def baseline(runs: List[dict], median_of: int = 5) -> Optional[float]:
  """Noise-aware baseline: the median of the last ``median_of`` run
  values (None when there are none). Median, not mean/max: one noisy
  CI runner in the window must not move the bar."""
  vals = [float(r['value']) for r in runs[-max(int(median_of), 1):]
          if isinstance(r.get('value'), (int, float))]
  if not vals:
    return None
  return statistics.median(vals)


def rows_from_bench_json(doc: dict, device: Optional[str] = None,
                         scale: Optional[str] = None) -> List[dict]:
  """Explode one bench.py headline JSON into its trajectory rows: the
  headline, every raced engine contender, and the train A/B engines.
  Failed runs (``error`` present / no engines) yield no rows — "not
  measured" must never enter a baseline window as a zero."""
  if 'error' in doc:
    return []
  device = device or str(doc.get('backend', ''))
  scale = scale or str(doc.get('scale', ''))
  unit = str(doc.get('unit', ''))
  rows = []
  if isinstance(doc.get('value'), (int, float)) and doc['value'] > 0:
    rows.append({'bench': 'sampler_headline',
                 'engine': str(doc.get('engine', '')),
                 'scale': scale, 'device': device,
                 'value': float(doc['value']), 'unit': unit})
  for label, rec in (doc.get('engines') or {}).items():
    if not (isinstance(rec, dict) and 'edges_per_sec' in rec):
      continue
    if str(label).endswith('_smoke'):
      # fused-walk duel entries: 3-iteration toy-protocol timings whose
      # evidence is the launch/byte cells, not edges/s — a trajectory
      # series over them would only feed runner noise into the
      # regression gate (threshold-sized dips on shared runners)
      continue
    rows.append({'bench': 'sampler_engine', 'engine': str(label),
                 'scale': str(rec.get('scale', scale)),
                 'device': device,
                 'value': float(rec['edges_per_sec']),
                 'unit': 'edges/s'})
  tab = doc.get('train_steps_per_sec')
  if isinstance(tab, dict) and 'error' not in tab:
    for eng in ('per_batch', 'superstep'):
      if isinstance(tab.get(eng), (int, float)):
        rows.append({'bench': 'train_steps_per_sec', 'engine': eng,
                     'scale': scale, 'device': device,
                     'value': float(tab[eng]), 'unit': 'steps/s'})
  het = doc.get('hetero')
  if isinstance(het, dict) and 'error' not in het \
      and 'skipped' not in het:
    # hetero contenders live under their OWN bench name + their own
    # scale string: a hetero seeds/s row must never enter a homo
    # edges/s baseline window (run_key separates on both anyway; the
    # distinct bench makes the series self-describing)
    for label, rec in (het.get('engines') or {}).items():
      if isinstance(rec, dict) and 'seeds_per_sec' in rec:
        rows.append({'bench': 'hetero_sampler', 'engine': str(label),
                     'scale': str(rec.get('scale',
                                          het.get('scale', ''))),
                     'device': device,
                     'value': float(rec['seeds_per_sec']),
                     'unit': 'seeds/s'})
  return rows


def append_bench_json(history_path: str, doc: dict,
                      device: Optional[str] = None,
                      scale: Optional[str] = None,
                      ts: Optional[float] = None) -> List[dict]:
  out = []
  for row in rows_from_bench_json(doc, device=device, scale=scale):
    out.append(append_run(history_path, ts=ts, **row))
  return out


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__.split('\n')[0])
  sub = ap.add_subparsers(dest='cmd', required=True)
  a = sub.add_parser('append', help='append a bench.py JSON to the '
                                    'trajectory')
  a.add_argument('--history', required=True)
  a.add_argument('--bench-json', required=True)
  a.add_argument('--device', default=None)
  a.add_argument('--scale', default=None)
  s = sub.add_parser('show', help='print the trajectory (filtered)')
  s.add_argument('--history', required=True)
  s.add_argument('--bench', default=None)
  s.add_argument('--engine', default=None)
  args = ap.parse_args(argv)
  if args.cmd == 'append':
    with open(args.bench_json) as f:
      doc = json.load(f)
    rows = append_bench_json(args.history, doc, device=args.device,
                             scale=args.scale)
    print(json.dumps({'appended': len(rows),
                      'keys': ['|'.join(run_key(r)) for r in rows]}))
    if not rows and 'error' in doc:
      print(f"# bench run not measured ({doc['error'][:120]}); "
            'nothing appended', file=sys.stderr)
    return 0
  runs = load_runs(args.history, bench=args.bench, engine=args.engine)
  for r in runs:
    print(json.dumps(r, sort_keys=True))
  return 0


if __name__ == '__main__':
  sys.exit(main())

"""Fleet-router scaling benchmark: QPS vs shard count.

Measures the front-door cost of :class:`glt_tpu.serving.FleetRouter`
(admission, partition-book routing, snapshot-gate read, per-shard
failover walk, scatter-back) as the fleet widens: the same closed-loop
client load is replayed against 1..``--max-shards`` local shards.
Engines run an identity forward over a value-encoded graph, so the
curve isolates ROUTER overhead + dispatch parallelism from model
compute — and every response is self-checking (row k == ids[k]).

Prints one JSON line per shard count plus a ``curve`` summary line.
``GLT_BENCH_HISTORY=<path>`` appends each point to the bench
trajectory (benchmarks/history.py) under the ``fleet`` bench key,
``engine=shards<k>`` — rows only ever compare within an exact
(bench, engine, scale, device) key, so the per-width series gate
independently.

``GLT_BENCH_PLATFORM=cpu`` forces the CPU backend.
"""
import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def ring_dataset(num_nodes: int, feat_dim: int):
  """Value-encoded ring graph (row i == [i]*dim), the tests' fixture
  shape rebuilt here so the benchmark has no test-tree import."""
  from glt_tpu.data import Dataset
  v = np.arange(num_nodes, dtype=np.int64)
  rows = np.repeat(v, 2)
  cols = np.stack([(v + 1) % num_nodes, (v + 2) % num_nodes],
                  1).reshape(-1)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]),
                edge_ids=np.arange(2 * num_nodes, dtype=np.int64),
                num_nodes=num_nodes)
  ds.init_node_features(
      np.tile(np.arange(num_nodes, dtype=np.float32)[:, None],
              (1, feat_dim)))
  return ds


def build_router(num_shards, replicas, num_nodes, feat_dim, fanout,
                 buckets):
  from glt_tpu.partition.partition_book import RangePartitionBook
  from glt_tpu.serving import FleetRouter, FleetShard, InferenceEngine
  shards = []
  for s in range(num_shards):
    engines = [
        InferenceEngine(ring_dataset(num_nodes, feat_dim), None, None,
                        fanout, buckets=buckets,
                        apply_fn=lambda p, b: b.x)
        for _ in range(replicas)]
    shards.append(FleetShard.local(f'shard{s}', engines))
  bounds = [num_nodes * (s + 1) // num_shards
            for s in range(num_shards)]
  # replicated id space: every shard holds rows for its slice of one
  # global [0, num_nodes) space — the book is the load-spreading fn
  return FleetRouter(shards, RangePartitionBook(bounds))


def run_load(router, clients, requests, max_request, num_nodes):
  lat = []
  lat_lock = threading.Lock()
  errs = []

  def client(seed):
    rng = np.random.default_rng(seed)
    mine = []
    for _ in range(requests):
      ids = rng.integers(0, num_nodes, size=rng.integers(
          1, max_request + 1))
      t0 = time.perf_counter()
      try:
        out = router.infer(ids, timeout_ms=30_000)
      except Exception as e:
        errs.append(e)
        return
      mine.append(time.perf_counter() - t0)
      if not np.allclose(out[:, 0], ids):
        errs.append(AssertionError('routing returned wrong rows'))
        return
    with lat_lock:
      lat.extend(mine)

  threads = [threading.Thread(target=client, args=(s,))
             for s in range(clients)]
  t0 = time.perf_counter()
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  wall = time.perf_counter() - t0
  if errs:
    raise errs[0]
  lat_ms = np.sort(np.asarray(lat)) * 1e3
  return {
      'qps': len(lat) / wall,
      'latency_p50_ms': float(np.percentile(lat_ms, 50)),
      'latency_p99_ms': float(np.percentile(lat_ms, 99)),
      'wall_s': wall,
      'requests': len(lat),
  }


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--max-shards', type=int, default=3)
  ap.add_argument('--replicas', type=int, default=1,
                  help='replicas per shard (local engines)')
  ap.add_argument('--num-nodes', type=int, default=6_000)
  ap.add_argument('--feat-dim', type=int, default=32)
  ap.add_argument('--fanout', default='4,4')
  ap.add_argument('--buckets', default='16,64')
  ap.add_argument('--clients', type=int, default=4)
  ap.add_argument('--requests', type=int, default=100,
                  help='requests per client per shard count')
  ap.add_argument('--max-request', type=int, default=16)
  args = ap.parse_args()

  from glt_tpu.utils.backend import force_backend
  force_backend()
  import jax
  device = jax.devices()[0].platform

  fanout = [int(x) for x in args.fanout.split(',')]
  buckets = tuple(int(x) for x in args.buckets.split(','))
  hist = os.environ.get('GLT_BENCH_HISTORY')
  scale = f'n{args.num_nodes}xc{args.clients}'
  curve = {}
  for k in range(1, args.max_shards + 1):
    router = build_router(k, args.replicas, args.num_nodes,
                          args.feat_dim, fanout, buckets)
    try:
      # warm every shard's buckets out of the measured window (ids
      # spread over the full space so no shard cold-compiles mid-load)
      warm = np.linspace(0, args.num_nodes - 1, num=16 * k,
                         dtype=np.int64)
      for b in buckets:
        router.infer(warm[:min(b, warm.size)])
        router.infer(warm)
      res = run_load(router, args.clients, args.requests,
                     args.max_request, args.num_nodes)
    finally:
      router.close()
    row = {'bench': 'fleet', 'shards': k, 'replicas': args.replicas,
           'device': device, 'scale': scale, **res}
    print(json.dumps(row, sort_keys=True))
    curve[k] = round(res['qps'], 1)
    if hist:
      from benchmarks.history import append_run
      append_run(hist, bench='fleet', value=res['qps'], unit='qps',
                 engine=f'shards{k}', scale=scale, device=device,
                 latency_p50_ms=round(res['latency_p50_ms'], 3),
                 latency_p99_ms=round(res['latency_p99_ms'], 3))
  print(json.dumps({'bench': 'fleet', 'curve_qps_by_shards': curve,
                    'device': device, 'scale': scale},
                   sort_keys=True))


if __name__ == '__main__':
  main()

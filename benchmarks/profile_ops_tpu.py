"""Per-op hardware profile of the composed sampling step.

The stage-level timings (profile_sampler.py) bound which *stage* is
hot, but XLA fuses across our Python stage boundaries (composed 29 ms
vs op-sum 40 ms on the r5 capture), so stage timing cannot name the
*op* to attack next. This script runs the composed fused pipeline under
``jax.profiler.trace`` and reduces the device trace to a table of
HLO-op durations, so the next kernel decision (Pallas radix dedup?
wider scan? gather layout?) is made from op data, not inference.

If the axon tunnel cannot return device traces, falls back to printing
the compiled HLO's cost analysis and a note — still useful: the
optimized HLO op list names what XLA actually emitted.

Usage: python benchmarks/profile_ops_tpu.py [--scan N] [--iters N]
Writes benchmarks/tpu_runs/optrace/ (trace) and prints a JSON summary.
"""
import argparse
import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

OUT_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       'tpu_runs', 'optrace')


def summarize_trace(trace_dir):
  """Pull per-op durations out of the profiler's .trace.json.gz (the
  chrome-trace export the jax profiler always writes)."""
  pats = glob.glob(os.path.join(trace_dir, '**', '*.trace.json.gz'),
                   recursive=True)
  if not pats:
    return None
  with gzip.open(sorted(pats)[-1], 'rt') as f:
    tr = json.load(f)
  events = tr.get('traceEvents', [])
  # device lanes: pid names containing 'TPU'/'Device'; host lanes excluded
  dev_pids = set()
  for ev in events:
    if ev.get('ph') == 'M' and ev.get('name') == 'process_name':
      nm = ev.get('args', {}).get('name', '')
      if 'TPU' in nm or 'Device' in nm or 'XLA Ops' in nm:
        dev_pids.add(ev['pid'])
  per_op = {}
  for ev in events:
    if ev.get('ph') != 'X':
      continue
    if dev_pids and ev.get('pid') not in dev_pids:
      continue
    name = ev.get('name', '?')
    dur = ev.get('dur', 0) / 1e3  # us -> ms
    a = per_op.setdefault(name, [0.0, 0])
    a[0] += dur
    a[1] += 1
  rows = sorted(((t, n, c) for n, (t, c) in per_op.items()),
                reverse=True)
  return [{'op': n, 'total_ms': round(t, 3), 'count': c}
          for t, n, c in rows[:40]]


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--scan', type=int, default=4)
  ap.add_argument('--iters', type=int, default=8)
  ap.add_argument('--nodes', type=int, default=2_450_000)
  ap.add_argument('--edges', type=int, default=62_000_000)
  args = ap.parse_args()

  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  cache = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), '.jax_cache')
  jax.config.update('jax_compilation_cache_dir', cache)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.ops.pipeline import (make_dedup_tables,
                                    multihop_sample_many,
                                    checksum_outputs)
  from glt_tpu.ops.sample import sample_neighbors
  from glt_tpu.utils.rng import make_key

  BATCH, FANOUT = 1024, (15, 10, 5)
  dev = jax.devices()[0]
  print(f'# backend: {dev.platform} ({dev.device_kind})', file=sys.stderr)

  rng = np.random.default_rng(0)
  src = rng.integers(0, args.nodes, args.edges, dtype=np.int64)
  dst = (rng.random(args.edges) ** 2 * args.nodes).astype(np.int64) \
      % args.nodes
  topo = Topology(indptr=None, edge_index=np.stack([src, dst]),
                  num_nodes=args.nodes)
  del src, dst
  indptr = jnp.asarray(topo.indptr.astype(np.int32))
  indices = jnp.asarray(topo.indices)
  one_hop = lambda ids, fanout, key, mask: sample_neighbors(
      indptr, indices, ids, fanout, key, seed_mask=mask)

  scan = args.scan

  def sample_batch(seeds, key, table, scratch):
    outs, table, scratch = multihop_sample_many(
        one_hop, seeds, jnp.full(scan, BATCH, jnp.int32), FANOUT,
        key, table, scratch)
    return (outs['num_sampled_edges'].sum(), checksum_outputs(outs),
            table, scratch)

  fn = jax.jit(sample_batch, donate_argnums=(2, 3))
  seed_pool = rng.integers(0, args.nodes, (args.iters + 1, scan, BATCH))
  keys = jax.random.split(make_key(0), args.iters + 1)
  table, scratch = make_dedup_tables(args.nodes)
  e, s, table, scratch = fn(jnp.asarray(seed_pool[0], jnp.int32),
                            keys[0], table, scratch)
  jax.block_until_ready((e, s))

  os.makedirs(OUT_DIR, exist_ok=True)
  t0 = time.time()
  with jax.profiler.trace(OUT_DIR):
    for i in range(1, args.iters + 1):
      e, s, table, scratch = fn(jnp.asarray(seed_pool[i], jnp.int32),
                                keys[i], table, scratch)
    jax.block_until_ready((e, s))
  dt = time.time() - t0
  eps = None
  per_batch_ms = 1e3 * dt / (args.iters * scan)
  summary = summarize_trace(OUT_DIR)
  print(json.dumps({
      'metric': 'sampler_op_trace',
      'scan': scan, 'iters': args.iters,
      'wall_ms_per_batch': round(per_batch_ms, 2),
      'trace_ok': summary is not None,
      'top_ops': summary,
  }))


if __name__ == '__main__':
  main()

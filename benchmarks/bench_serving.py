"""Online-serving benchmark: QPS / latency / batch-fill / cache hit-rate
under synthetic multi-client load.

Load model: ``--clients`` threads each issue ``--requests`` node-ID
queries back-to-back (closed loop). Request sizes are uniform in
[1, --max-request]; ids follow a Zipf-ish skew (squared uniform, the
same concentration trick as examples.common.synthetic_products) so the
embedding cache sees realistic repeat traffic. ``--rpc`` routes clients
over the socket fabric instead of the in-process path, measuring the
full wire cost.

Prints one JSON line:
  qps, latency_p50_ms/p99_ms, batch_fill_ratio, cache_hit_rate,
  warmup_seconds, compile stats (to certify zero steady-state
  recompiles), and the config.

``GLT_BENCH_PLATFORM=cpu`` forces the CPU backend (the axon TPU plugin
ignores JAX_PLATFORMS).
"""
import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=24_000)
  ap.add_argument('--avg-degree', type=int, default=25)
  ap.add_argument('--feat-dim', type=int, default=100)
  ap.add_argument('--hidden', type=int, default=256)
  ap.add_argument('--classes', type=int, default=47)
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--buckets', default='8,32,128')
  ap.add_argument('--clients', type=int, default=4)
  ap.add_argument('--requests', type=int, default=50,
                  help='requests per client')
  ap.add_argument('--max-request', type=int, default=16,
                  help='max node ids per request')
  ap.add_argument('--max-wait-ms', type=float, default=2.0)
  ap.add_argument('--cache-capacity', type=int, default=50_000)
  ap.add_argument('--zipf-skew', type=float, default=2.0,
                  help='uniform^skew id concentration (higher = hotter)')
  ap.add_argument('--rpc', action='store_true',
                  help='clients go over the socket fabric')
  args = ap.parse_args()

  from glt_tpu.utils.backend import force_backend
  force_backend()
  import jax

  from examples.common import synthetic_products
  from glt_tpu.models import GraphSAGE
  from glt_tpu.serving import InferenceEngine, ServingClient, \
      ServingServer

  fanout = [int(x) for x in args.fanout.split(',')]
  buckets = [int(x) for x in args.buckets.split(',')]
  ds, num_classes = synthetic_products(
      num_nodes=args.num_nodes, avg_degree=args.avg_degree,
      feat_dim=args.feat_dim, num_classes=args.classes)
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=len(fanout))

  engine = InferenceEngine(ds, model, None, fanout, buckets=buckets,
                           cache_capacity=args.cache_capacity)
  # fresh weights: serving cost is invariant to the trained values
  engine.init_params(jax.random.key(0))

  t0 = time.perf_counter()
  srv = ServingServer(engine, max_wait_ms=args.max_wait_ms,
                      request_timeout_ms=120_000.0)
  warmup_s = time.perf_counter() - t0
  compile_after_warmup = engine.compile_stats()

  def client(rank: int, errors: list):
    rng = np.random.default_rng(rank)
    cli = ServingClient(*srv.address) if args.rpc else srv
    try:
      for _ in range(args.requests):
        n = int(rng.integers(1, args.max_request + 1))
        ids = ((rng.random(n) ** args.zipf_skew)
               * args.num_nodes).astype(np.int64)
        out = cli.infer(ids)
        assert out.shape[0] == n
    except BaseException as e:  # noqa: BLE001 — surfaced in the report
      errors.append(f'client {rank}: {e!r}')
    finally:
      if args.rpc:
        cli.close()

  errors: list = []
  t0 = time.perf_counter()
  threads = [threading.Thread(target=client, args=(r, errors))
             for r in range(args.clients)]
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  load_s = time.perf_counter() - t0

  snap = srv.metrics.snapshot(cache=engine.cache)
  compile_end = engine.compile_stats()
  srv.close()

  report = {
      'bench': 'serving',
      'transport': 'rpc' if args.rpc else 'inproc',
      'clients': args.clients,
      'requests': snap['requests'],
      'qps': round(snap['requests'] / load_s, 2),
      'ids_per_sec': round(snap['ids_served'] / load_s, 2),
      'latency_p50_ms': round(snap['latency_p50_ms'], 3),
      'latency_p99_ms': round(snap['latency_p99_ms'], 3),
      'batch_fill_ratio': round(snap['batch_fill_ratio'], 4),
      'cache_hit_rate': round(snap['cache_hit_rate'], 4),
      'timeouts': snap['timeouts'],
      'rejected': snap['rejected'],
      'warmup_seconds': round(warmup_s, 2),
      'steady_state_recompiles': sum(
          compile_end['forward_traces'].values()) - sum(
          compile_after_warmup['forward_traces'].values()),
      'forward_calls': compile_end['forward_calls'],
      'errors': errors,
      'config': {
          'num_nodes': args.num_nodes, 'fanout': fanout,
          'buckets': buckets, 'max_request': args.max_request,
          'max_wait_ms': args.max_wait_ms,
          'cache_capacity': args.cache_capacity,
          'hidden': args.hidden,
      },
  }
  print(json.dumps(report))
  if errors:
    sys.exit(1)


if __name__ == '__main__':
  main()

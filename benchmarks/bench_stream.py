"""Streaming-update benchmark: ingestion throughput, compaction
latency, and serving latency *during* compactions.

Three phases over one engine (StreamSampler + SnapshotManager):

  1. **ingest**: stage ``--updates`` edge ops through the
     StreamIngestor (overlay refresh on, compaction off) -> ops/s for
     the stage+refresh write path;
  2. **compact**: repeated delta fills + flushes -> compaction latency
     stats (mean/max ms) and the zero-recompile certificate across all
     swaps;
  3. **serve-under-churn**: client threads hammer ``infer`` while a
     writer thread streams updates and compactions fire by policy ->
     p50/p99 with the mutation engine live (the number a production
     deployment actually cares about).

Prints one JSON line (the CI smoke-bench job uploads it as an
artifact, same contract as bench_serving.py).
"""
import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int,
                  default=int(os.environ.get('GLT_BENCH_NODES', 24_000)))
  ap.add_argument('--avg-degree', type=int, default=25)
  ap.add_argument('--feat-dim', type=int, default=100)
  ap.add_argument('--hidden', type=int, default=128)
  ap.add_argument('--fanout', default='10,5')
  ap.add_argument('--buckets', default='8,32')
  ap.add_argument('--delta-window', type=int, default=8)
  ap.add_argument('--delta-capacity', type=int, default=8192)
  ap.add_argument('--updates', type=int, default=4096,
                  help='edge ops for the ingest phase')
  ap.add_argument('--ingest-batch', type=int, default=64,
                  help='edges per insert_edges call')
  ap.add_argument('--compactions', type=int, default=4)
  ap.add_argument('--clients', type=int, default=2)
  ap.add_argument('--serve-seconds', type=float, default=4.0)
  ap.add_argument('--max-request', type=int, default=16)
  args = ap.parse_args()

  from glt_tpu.utils.backend import force_backend
  force_backend()
  import jax

  from examples.common import synthetic_products
  from glt_tpu.models import GraphSAGE
  from glt_tpu.serving import InferenceEngine, ServingMetrics
  from glt_tpu.stream import (
      CompactionPolicy, SnapshotManager, StreamIngestor, StreamSampler,
  )

  fanout = [int(x) for x in args.fanout.split(',')]
  buckets = [int(x) for x in args.buckets.split(',')]
  ds, num_classes = synthetic_products(
      num_nodes=args.num_nodes, avg_degree=args.avg_degree,
      feat_dim=args.feat_dim)
  model = GraphSAGE(hidden_features=args.hidden,
                    out_features=num_classes, num_layers=len(fanout))

  manager = SnapshotManager(
      ds.get_graph().topo, ds.get_node_feature(),
      delta_capacity=args.delta_capacity)
  sampler = StreamSampler(manager, fanout,
                          delta_window=args.delta_window, seed=0)
  engine = InferenceEngine(ds, model, None, fanout, sampler=sampler,
                           buckets=buckets)
  engine.init_params(jax.random.key(0))
  t0 = time.perf_counter()
  engine.warmup()
  warmup_s = time.perf_counter() - t0
  warm = engine.compile_stats()
  warm_traces = sampler.trace_count
  rng = np.random.default_rng(0)

  # -- phase 1: ingest throughput (stage + overlay refresh) --------------
  ingestor = StreamIngestor(
      manager, sampler=sampler, engine=engine,
      policy=CompactionPolicy(occupancy_threshold=2.0,
                              max_staleness_s=0.0))
  n_batches = max(args.updates // args.ingest_batch, 1)
  srcs = rng.integers(0, args.num_nodes, (n_batches, args.ingest_batch))
  dsts = rng.integers(0, args.num_nodes, (n_batches, args.ingest_batch))
  t0 = time.perf_counter()
  for b in range(n_batches):
    ingestor.insert_edges(srcs[b], dsts[b])
  ingest_s = time.perf_counter() - t0
  ingest_ops = n_batches * args.ingest_batch
  ingestor.flush()

  # -- phase 2: compaction latency ---------------------------------------
  lat = []
  for _ in range(args.compactions):
    ingestor.insert_edges(
        rng.integers(0, args.num_nodes, args.ingest_batch),
        rng.integers(0, args.num_nodes, args.ingest_batch))
    ingestor.update_features(
        rng.integers(0, args.num_nodes, 8),
        rng.normal(size=(8, args.feat_dim)).astype(np.float32))
    info = ingestor.flush()
    lat.append(info['compaction_s'] * 1e3)

  # -- phase 3: serving latency during compactions -----------------------
  metrics = ServingMetrics()
  ingestor.metrics = metrics
  ingestor.policy = CompactionPolicy(
      occupancy_threshold=float(args.ingest_batch * 4)
      / args.delta_capacity,
      max_staleness_s=1e9)
  stop = threading.Event()
  errors: list = []
  compactions_before_serve = manager.compactions

  def writer():
    wrng = np.random.default_rng(99)
    while not stop.is_set():
      try:
        ingestor.insert_edges(
            wrng.integers(0, args.num_nodes, args.ingest_batch),
            wrng.integers(0, args.num_nodes, args.ingest_batch))
      except BaseException as e:  # noqa: BLE001 — surfaced in report
        errors.append(f'writer: {e!r}')
        return
      time.sleep(0.002)

  def client(rank):
    crng = np.random.default_rng(rank)
    deadline = time.monotonic() + args.serve_seconds
    while time.monotonic() < deadline:
      n = int(crng.integers(1, args.max_request + 1))
      ids = ((crng.random(n) ** 2) * args.num_nodes).astype(np.int64)
      t = time.perf_counter()
      try:
        out = engine.infer(ids)
        assert out.shape[0] == n
      except BaseException as e:  # noqa: BLE001
        errors.append(f'client {rank}: {e!r}')
        return
      metrics.record_request(time.perf_counter() - t, n)

  wt = threading.Thread(target=writer)
  cts = [threading.Thread(target=client, args=(r,))
         for r in range(args.clients)]
  wt.start()
  for t in cts:
    t.start()
  for t in cts:
    t.join()
  stop.set()
  wt.join()
  snap = metrics.snapshot(cache=engine.cache)
  end = engine.compile_stats()

  report = {
      'bench': 'stream',
      'ingest_ops_per_sec': round(ingest_ops / max(ingest_s, 1e-9), 1),
      'ingest_batch': args.ingest_batch,
      'compaction_ms_mean': round(float(np.mean(lat)), 2),
      'compaction_ms_max': round(float(np.max(lat)), 2),
      'compactions_total': manager.compactions,
      'snapshot_version': manager.current().version,
      'serve_requests': snap['requests'],
      'serve_qps': round(snap['qps'], 2),
      'serve_p50_ms': round(snap['latency_p50_ms'], 3),
      'serve_p99_ms': round(snap['latency_p99_ms'], 3),
      'cache_hit_rate': round(snap['cache_hit_rate'], 4),
      'compactions_during_serve':
          manager.compactions - compactions_before_serve,
      'steady_state_recompiles': (
          sum(end['forward_traces'].values())
          - sum(warm['forward_traces'].values())
          + sampler.trace_count - warm_traces),
      'capacity_growths': manager.capacity_growths,
      'warmup_seconds': round(warmup_s, 2),
      'errors': errors,
      'config': {
          'num_nodes': args.num_nodes, 'fanout': fanout,
          'buckets': buckets, 'delta_window': args.delta_window,
          'delta_capacity': args.delta_capacity,
          'updates': ingest_ops, 'clients': args.clients,
      },
  }
  print(json.dumps(report))
  if errors:
    sys.exit(1)


if __name__ == '__main__':
  main()

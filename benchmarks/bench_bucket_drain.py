"""Capped-bucket drain economics: rounds + wall time vs skew.

VERDICT r3 weak #3 / next #7: the bucket_cap overflow drain is
host-sequential — each extra round replays the full compiled collective
pass. This measures, on a P-device mesh (virtual CPU by default, the
same program on a real slice):

  * drain ROUNDS for bucket_cap = slack * ceil(B/P), slack in {1, 2, 4},
    under uniform and zipfian(a) request-id distributions — rounds are
    decided by the deterministic host replay, so they are exact, not
    sampled;
  * wall-clock per lookup for each (cap, distribution) vs the uncapped
    baseline, so the ICI-bytes saving can be weighed against the round
    cost on real hardware.

Output: one JSON line with the rounds/time grid + a recommended default.
Reference pattern being improved: graphlearn_torch dist_feature.py
270-366 (gloo all2all moves [P, B] unconditionally).
"""
import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def drain_rounds(ids, n_shards, b, rows_per_shard, cap):
  """Exact round count via the deterministic host replay."""
  from glt_tpu.parallel.dist_feature import overflow_lanes
  owner = np.clip(ids // rows_per_shard, 0, n_shards - 1)
  pending = np.ones(ids.shape[0], bool)
  rounds = 0
  while True:
    rounds += 1
    over = overflow_lanes(np.where(pending, owner, n_shards),
                          n_shards, b, cap)
    if not over.any():
      return rounds
    pending = over


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-devices', type=int, default=8)
  ap.add_argument('--rows', type=int, default=1_000_000)
  ap.add_argument('--dim', type=int, default=128)
  ap.add_argument('--batch', type=int, default=4096,
                  help='request ids per device')
  ap.add_argument('--iters', type=int, default=20)
  ap.add_argument('--warmup', type=int, default=3)
  ap.add_argument('--cpu-mesh', action='store_true',
                  default=os.environ.get('GLT_BENCH_PLATFORM') == 'cpu')
  args = ap.parse_args()

  if args.cpu_mesh:
    os.environ['XLA_FLAGS'] = (
        os.environ.get('XLA_FLAGS', '') +
        f' --xla_force_host_platform_device_count={args.num_devices}')
  import jax
  if args.cpu_mesh:
    from glt_tpu.utils.backend import force_backend
    force_backend('cpu')
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  import jax.numpy as jnp
  from glt_tpu.parallel import make_mesh
  from glt_tpu.parallel.dist_feature import ShardedFeature

  p = min(args.num_devices, len(jax.devices()))
  mesh = make_mesh(p)
  b = args.batch
  n = args.rows
  rps = math.ceil(n / p)
  feats = np.random.default_rng(0).normal(
      size=(n, args.dim)).astype(np.float32)

  rng = np.random.default_rng(1)
  dists = {
      'uniform': rng.integers(0, n, p * b),
      # zipf over rows: heavy head -> every device asks the head's
      # owner shard for most of its batch (the skew the cap fears)
      'zipf_1.2': (rng.zipf(1.2, p * b) - 1) % n,
      'zipf_2.0': (rng.zipf(2.0, p * b) - 1) % n,
      'hot_spot': np.zeros(p * b, np.int64),  # all-ask-one worst case
  }

  base_cap = math.ceil(b / p)
  grid = {}
  stores = {}

  def timed_lookup(store, ids):
    for _ in range(args.warmup):
      jax.block_until_ready(store.lookup(ids))
    t0 = time.time()
    for _ in range(args.iters):
      jax.block_until_ready(store.lookup(ids))
    return (time.time() - t0) / args.iters * 1e3  # ms

  uncapped = ShardedFeature(feats, mesh)
  for name, ids in dists.items():
    ids = ids.astype(np.int64)
    row = {'uncapped_ms': round(timed_lookup(uncapped, ids), 2)}
    for slack in (1, 2, 4):
      cap = slack * base_cap
      if cap not in stores:
        stores[cap] = ShardedFeature(feats, mesh, bucket_cap=cap)
      rounds = drain_rounds(ids, p, b, rps, cap)
      row[f'slack{slack}'] = {
          'cap': cap,
          'rounds': rounds,
          'ms': round(timed_lookup(stores[cap], ids), 2),
          # bytes each device puts on the wire per round vs uncapped:
          # request ids [P, C] + responses [P, C, D] vs [P, B](+[P,B,D])
          'ici_fraction': round(cap / b, 4),
      }
    grid[name] = row

  # recommendation: smallest slack whose rounds stay 1 on uniform AND
  # <= 3 under zipf_1.2 (real graph id streams are zipf-ish after
  # degree sort); hot_spot is the adversarial bound, not the default
  rec = None
  for slack in (1, 2, 4):
    if (grid['uniform'][f'slack{slack}']['rounds'] == 1
        and grid['zipf_1.2'][f'slack{slack}']['rounds'] <= 3):
      rec = slack
      break
  dev = jax.devices()[0]
  print(json.dumps({
      'metric': 'bucket_cap_drain_grid',
      'value': rec if rec is not None else 0,
      'unit': 'recommended_slack',
      'vs_baseline': None,
      'detail': {'devices': p, 'batch_per_device': b,
                 'base_cap': base_cap, 'grid': grid,
                 'backend': dev.platform},
  }))


if __name__ == '__main__':
  main()

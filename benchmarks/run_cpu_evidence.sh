#!/bin/bash
# Serial CPU evidence chain (1-core box: never run two heavy steps at
# once). Each step writes its JSON artifact under benchmarks/results/.
# TPU-independent counterpart of run_tpu_suite.sh — the epoch-protocol,
# convergence, spill, drain-grid, and IGBH-profile artifacts VERDICT r3
# asks for, runnable while the tunnel is wedged.
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results
mkdir -p "$OUT"
export GLT_BENCH_PLATFORM=cpu

run() {  # run NAME CMD...
  local name=$1; shift
  echo "== $(date -Is) $name: $*" >> "$OUT/evidence_chain.log"
  timeout 14400 "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  echo "== $(date -Is) $name done rc=$? $(tail -c 120 "$OUT/$name.json")" \
      >> "$OUT/evidence_chain.log"
}

# 1. north-star epoch protocol, products scale, ONE full epoch timed
run bench_train_products_cpu python benchmarks/bench_train.py --epochs 1

# 2. convergence curve to plateau, reduced scale, same protocol shapes
run bench_train_curve_cpu python benchmarks/bench_train.py \
    --num-nodes 200000 --avg-degree 15 --batch-size 512 \
    --plateau 3 --epochs 40

# 3. beyond-HBM spill training ratio (scaled-down on CPU)
run bench_spill_cpu python benchmarks/bench_spill_train.py

# 4. capped-bucket drain grid
run bench_bucket_drain_cpu python benchmarks/bench_bucket_drain.py

# 5. IGBH step breakdown at 1M papers
run profile_igbh_cpu python benchmarks/profile_igbh.py --papers 1000000

echo "== $(date -Is) chain complete" >> "$OUT/evidence_chain.log"

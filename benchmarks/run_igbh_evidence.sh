#!/bin/bash
# IGBH scale evidence (VERDICT r3 next #5), serial on the 1-core box:
#   1. full-epoch 54M-edge run (4M papers), eval every epoch, reusing
#      one synthesized data tree + partition dir across runs;
#   2. >=200M-edge single-step memory probe (per-host RSS wall).
# Batch size is taken from $IGBH_BS (default 256/device — set from the
# profile_igbh breakdown before launching).
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results
mkdir -p "$OUT"
BS=${IGBH_BS:-256}
DATA=${IGBH_DATA:-/tmp/igbh_data_4m}
PARTS=${IGBH_PARTS:-/tmp/igbh_parts_4m}

echo "== $(date -Is) igbh epoch: bs=$BS" >> "$OUT/evidence_chain.log"
timeout 36000 python examples/igbh/dist_train_rgnn.py \
    --papers 4000000 --data-root "$DATA" --part-root "$PARTS" \
    --epochs 1 --batch-size "$BS" --val-batches 20 \
    > "$OUT/igbh_epoch_54m.log" 2>&1
echo "== $(date -Is) igbh epoch done rc=$?" >> "$OUT/evidence_chain.log"

echo "== $(date -Is) igbh 200M probe" >> "$OUT/evidence_chain.log"
timeout 14400 python examples/igbh/dist_train_rgnn.py \
    --papers 15000000 --epochs 1 --steps-per-epoch 1 --batch-size 64 \
    --val-batches 1 \
    > "$OUT/igbh_probe_200m.log" 2>&1
echo "== $(date -Is) igbh 200M probe done rc=$?" >> "$OUT/evidence_chain.log"

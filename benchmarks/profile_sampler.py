"""Sampling-pipeline breakdown: where does a multihop batch spend time?

PERF_PLAN step 3: times the composed pipeline against its constituent
stages at bench.py shapes (2.45M nodes / 62M edges, batch 1024,
[15,10,5]) and, with ``--trace DIR``, also captures a ``jax.profiler``
trace of 10 steady-state iterations for op-level inspection.

Stages timed (each as its own jitted program, steady state):
  one_hop_h{i}    sample_neighbors at hop i's frontier width
  assign_h{i}     dense_assign (dedup/relabel) at hop i's output width
  composed        the full multihop_sample program
  composed_scan   multihop_sample_many with GLT_BENCH_SCAN batches fused

Prints one JSON line with per-stage ms and the top-3 costliest stages.
``GLT_BENCH_PLATFORM=cpu`` forces the CPU backend.
"""
import argparse
import functools
import json
import os
import time

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))  # repo root -> glt_tpu

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')

NUM_NODES = 2_450_000
NUM_EDGES = 62_000_000
BATCH = 1024
FANOUT = (15, 10, 5)


def _time_fn(fn, args, iters=20, warmup=3, donate_state=False):
  """Steady-state seconds/call for a jitted fn; fn returns arrays."""
  import jax
  out = None
  state = args
  for _ in range(warmup):
    out = fn(*state)
    if donate_state:
      state = (state[0], state[1], out[1], out[2])
  jax.block_until_ready(out)
  t0 = time.time()
  for _ in range(iters):
    out = fn(*state)
    if donate_state:
      state = (state[0], state[1], out[1], out[2])
  jax.block_until_ready(out)
  return (time.time() - t0) / iters


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--trace', default=None,
                  help='also dump a jax.profiler trace to this dir')
  ap.add_argument('--iters', type=int, default=20)
  args = ap.parse_args()

  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from glt_tpu.data import Topology
  from glt_tpu.ops.pipeline import multihop_sample, multihop_sample_many
  from glt_tpu.ops.sample import sample_neighbors
  from glt_tpu.ops.unique import dense_assign, dense_init, \
      dense_make_tables, dense_reset

  def record(stages, name, secs):
    # incremental output: the axon tunnel can drop mid-run, and stage
    # timings are too expensive to lose with a print-at-the-end design
    stages[name] = secs
    print(f'# {name}: {secs * 1e3:.3f} ms', file=_sys.stderr, flush=True)

  rng = np.random.default_rng(0)
  src = rng.integers(0, NUM_NODES, NUM_EDGES, dtype=np.int64)
  dst = (rng.random(NUM_EDGES) ** 2 * NUM_NODES).astype(np.int64) \
      % NUM_NODES
  topo = Topology(indptr=None, edge_index=np.stack([src, dst]),
                  num_nodes=NUM_NODES)
  del src, dst
  indptr = jnp.asarray(topo.indptr.astype(np.int32))
  indices = jnp.asarray(topo.indices)
  key = jax.random.key(0)

  stages = {}

  # per-hop one_hop and dense_assign at the real frontier widths
  width = BATCH
  for h, k in enumerate(FANOUT):
    frontier = jnp.asarray(
        rng.integers(0, NUM_NODES, width).astype(np.int32))
    mask = jnp.ones((width,), bool)

    @jax.jit
    def hop_only(fr, m, key, _k=k):
      out = sample_neighbors(indptr, indices, fr, _k, key, seed_mask=m)
      return out.nbrs, out.mask

    record(stages, f'one_hop_h{h}', _time_fn(
        lambda fr, m: hop_only(fr, m, key), (frontier, mask),
        iters=args.iters))

    nbrs = np.asarray(hop_only(frontier, mask, key)[0]).reshape(-1)
    nmask = np.asarray(hop_only(frontier, mask, key)[1]).reshape(-1)
    budget = width * k + 8

    @functools.partial(jax.jit, donate_argnums=(2, 3))
    def assign_only(ids, ok, table, scratch, _budget=budget):
      state = dense_init(table, scratch, _budget)
      state, labels = dense_assign(state, ids, ok)
      table, scratch = dense_reset(state)
      return labels, table, scratch

    table, scratch = dense_make_tables(NUM_NODES)
    record(stages, f'assign_h{h}', _time_fn(
        assign_only,
        (jnp.asarray(nbrs), jnp.asarray(nmask), table, scratch),
        iters=args.iters, donate_state=True))

    # the sort-merge inducer's equivalent stage at the same widths, with
    # a realistic seen-set size (everything deduped before this hop)
    from glt_tpu.ops.unique import sorted_hop_dedup
    seen_c = sum(BATCH * int(np.prod(FANOUT[:i])) for i in range(h + 1))
    u_ids = jnp.asarray(
        rng.choice(NUM_NODES, seen_c, replace=False).astype(np.int32))
    u_labs = jnp.arange(seen_c, dtype=jnp.int32)
    rows_flat = jnp.asarray(
        rng.integers(0, seen_c, width * k).astype(np.int32))

    @jax.jit
    def sorted_only(uid, ula, ids, ok, rows):
      d = sorted_hop_dedup(uid, ula, jnp.asarray(seen_c, jnp.int32),
                           ids, ok, rows)
      return (d['labels3'], d['rows3'], d['new_head3'], d['u_ids2'],
              d['count2'])

    record(stages, f'sorted_h{h}', _time_fn(
        sorted_only,
        (u_ids, u_labs, jnp.asarray(nbrs), jnp.asarray(nmask),
         rows_flat), iters=args.iters))
    width *= k

  # composed program (bench.py's work unit)
  one_hop = lambda ids, fanout, key, mask: sample_neighbors(
      indptr, indices, ids, fanout, key, seed_mask=mask)

  from glt_tpu.ops.pipeline import checksum_outputs as checksum
  from glt_tpu.ops.pipeline import make_dedup_tables

  @functools.partial(jax.jit, donate_argnums=(2, 3))
  def composed(seeds, key, table, scratch):
    out, table, scratch = multihop_sample(
        one_hop, seeds, jnp.asarray(BATCH), FANOUT, key, table, scratch)
    return (out['num_sampled_edges'].sum() + checksum(out), table,
            scratch)

  table, scratch = make_dedup_tables(NUM_NODES)
  seeds = jnp.asarray(rng.integers(0, NUM_NODES, BATCH).astype(np.int32))
  record(stages, 'composed', _time_fn(composed, (seeds, key, table, scratch),
                                      iters=args.iters, donate_state=True))

  scan = max(int(os.environ.get('GLT_BENCH_SCAN', '4')), 1)

  @functools.partial(jax.jit, donate_argnums=(2, 3))
  def composed_scan(seeds2, key, table, scratch):
    outs, table, scratch = multihop_sample_many(
        one_hop, seeds2, jnp.full(scan, BATCH, jnp.int32), FANOUT, key,
        table, scratch)
    return (outs['num_sampled_edges'].sum() + checksum(outs), table,
            scratch)

  seeds2 = jnp.asarray(
      rng.integers(0, NUM_NODES, (scan, BATCH)).astype(np.int32))
  table, scratch = make_dedup_tables(NUM_NODES)
  record(stages, 'composed_scan_per_batch', _time_fn(
      composed_scan, (seeds2, key, table, scratch),
      iters=args.iters, donate_state=True) / scan)

  if args.trace:
    table, scratch = make_dedup_tables(NUM_NODES)
    state = (seeds, key, table, scratch)
    out = composed(*state)  # ensure compiled before tracing
    jax.block_until_ready(out)
    with jax.profiler.trace(args.trace):
      for _ in range(10):
        out = composed(state[0], state[1], out[1], out[2])
      jax.block_until_ready(out)
    print(f'# trace written to {args.trace}')

  ms = {k: round(v * 1e3, 3) for k, v in stages.items()}
  # op_sum models the ACTIVE engine's composed program: both engines'
  # dedup stages are timed above, but only one runs inside `composed`
  from glt_tpu.ops.pipeline import dedup_engine
  skip = 'sorted_' if dedup_engine() == 'table' else 'assign_'
  in_sum = lambda k: not k.startswith('composed') and not k.startswith(skip)
  op_sum = sum(v for k, v in ms.items() if in_sum(k))
  top3 = sorted((k for k in ms if in_sum(k)), key=lambda k: -ms[k])[:3]
  dev = jax.devices()[0]
  out = {
      'metric': 'sampler_stage_ms',
      'stages': ms,
      'engine': dedup_engine(),
      'op_sum_ms': round(op_sum, 3),
      'composed_over_opsum': round(ms['composed'] / max(op_sum, 1e-9), 2),
      'top3': top3,
      'backend': dev.platform,
  }
  try:
    # XLA's own estimate of the composed program's work: bytes accessed
    # vs flops shows how bandwidth-bound the sampler is. lower() only
    # needs avals, so pass shape specs instead of fresh device buffers.
    spec = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
    t_spec = jax.ShapeDtypeStruct(table.shape, jnp.int32)
    ca = composed.lower(spec(seeds), spec(key), t_spec, t_spec) \
        .compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
      ca = ca[0] if ca else {}
    out['cost_analysis'] = {
        k: float(ca[k]) for k in ('flops', 'bytes accessed')
        if k in ca}
  except Exception as e:  # cost model availability varies by backend
    out['cost_analysis_error'] = str(e)[:120]
  print(json.dumps(out))


if __name__ == '__main__':
  main()

"""Prototype A/B: window-gather one-hop + fast hash RNG + matmul cumsum
vs the current element-gather formulation, at hop-2 shapes.

Hypotheses (from microbench_prims):
  H1  `lax.gather` with a contiguous slice (one [W]-window per row)
      costs ~per-ROW not per-element -> replaces the 12.7ms [S,K]
      element gather with a ~3ms [S,W] window gather + vector select.
  H2  a counter-hash RNG (vectorized mul/xor) replaces threefry
      uniforms (7.5ms/1M) at VPU speed.
  H3  cumsum via blocked triangular matmul beats reduce-window cumsum.

Emits one JSON line with per-variant ms.
"""
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')

N = 2_450_000
E = 62_000_000
F = 153_600
K = 5
W = 96        # window: covers Poisson(25) degrees to ~1e-12 tail


def timed(fn, *args, iters=20, warmup=3):
  import jax
  out = None
  for _ in range(warmup):
    out = fn(*args)
  jax.block_until_ready(out)
  t0 = time.time()
  for _ in range(iters):
    out = fn(*args)
  jax.block_until_ready(out)
  return (time.time() - t0) / iters * 1e3


def main():
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)
  import jax.numpy as jnp
  from jax import lax

  res = {}
  def rec(name, ms):
    res[name] = round(ms, 3)
    print(f'# {name}: {ms:.3f} ms', file=sys.stderr, flush=True)

  rng = np.random.default_rng(0)
  indices = jnp.asarray(rng.integers(0, N, E, dtype=np.int64)
                        .astype(np.int32))
  # synthetic indptr with Poisson(25)-ish rows
  deg_np = rng.poisson(25.0, N).astype(np.int64)
  indptr_np = np.zeros(N + 1, np.int64)
  np.cumsum(deg_np, out=indptr_np[1:])
  scale = E / indptr_np[-1]
  indptr_np = (indptr_np * scale).astype(np.int64)
  indptr = jnp.asarray(indptr_np.astype(np.int32))
  frontier = jnp.asarray(rng.integers(0, N, F).astype(np.int32))
  key = jax.random.key(0)

  # ---- baseline: current sample_neighbors (element gather + threefry)
  from glt_tpu.ops.sample import sample_neighbors

  @jax.jit
  def base(fr, key):
    out = sample_neighbors(indptr, indices, fr, K, key,
                           seed_mask=jnp.ones((F,), bool))
    return out.nbrs, out.mask

  rec('baseline_one_hop', timed(base, frontier, key))

  # ---- H2: counter-hash uniforms --------------------------------------
  def hash_u01(key32, shape, salt):
    # 2-round multiply-xorshift mix of (counter, key) — murmur3-style
    # finalizer; statistical (not cryptographic) quality, VPU-speed.
    n = int(np.prod(shape))
    x = lax.iota(jnp.uint32, n) + jnp.uint32((salt * 0x9E3779B9)
                                             & 0xFFFFFFFF)
    x = x ^ key32
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return (x.astype(jnp.float32) * (1.0 / 4294967296.0)).reshape(shape)

  @jax.jit
  def h2(k32):
    return hash_u01(k32, (K, F), 1)

  rec('hash_uniform_5x153k', timed(h2, jnp.uint32(1234)))
  rec('threefry_uniform_5x153k',
      timed(jax.jit(lambda k: jax.random.uniform(k, (K, F))), key))

  # ---- H1: window gather + select (one-hot vs take_along_axis) ------
  def _window_and_offsets(fr, k32):
    """Shared: [F,W] contiguous window per row + Floyd offsets in it."""
    start = jnp.take(indptr, fr, mode='clip')
    end = jnp.take(indptr, fr + 1, mode='clip')
    deg = (end - start).astype(jnp.int32)
    win = lax.gather(
        indices, start[:, None],
        lax.GatherDimensionNumbers(
            offset_dims=(1,), collapsed_slice_dims=(),
            start_index_map=(0,), operand_batching_dims=(),
            start_indices_batching_dims=()),
        slice_sizes=(W,), mode=lax.GatherScatterMode.CLIP)   # [F, W]
    u = hash_u01(k32, (K, F), 2)
    degc = jnp.minimum(deg, W)
    chosen = jnp.zeros((F, K), jnp.int32)
    for j in range(K):
      bound = jnp.maximum(degc - K + j, 0)
      t = jnp.minimum((u[j] * (bound + 1).astype(u.dtype)).astype(
          jnp.int32), bound)
      if j > 0:
        dup = jnp.any(chosen[:, :j] == t[:, None], axis=1)
      else:
        dup = jnp.zeros((F,), bool)
      chosen = chosen.at[:, j].set(jnp.where(dup, bound, t))
    iota_k = jnp.arange(K, dtype=jnp.int32)[None, :]
    offs = jnp.where((degc <= K)[:, None],
                     jnp.broadcast_to(iota_k, chosen.shape), chosen)
    mask = iota_k < jnp.minimum(degc, K)[:, None]
    return win, offs, mask

  @jax.jit
  def window_hop(fr, k32):
    win, offs, mask = _window_and_offsets(fr, k32)
    wio = lax.iota(jnp.int32, W)[None, None, :]
    sel = (offs[:, :, None] == wio)
    nbrs = jnp.sum(jnp.where(sel, win[:, None, :], 0), axis=-1)
    return nbrs, mask

  rec('window_hop_W96', timed(window_hop, frontier, jnp.uint32(7)))

  @jax.jit
  def window_hop_taa(fr, k32):
    win, offs, mask = _window_and_offsets(fr, k32)
    nbrs = jnp.take_along_axis(win, offs, axis=1)
    return nbrs, mask

  rec('window_hop_taa_W96', timed(window_hop_taa, frontier,
                                  jnp.uint32(7)))

  # ---- H3: cumsum via blocked triangular matmul -----------------------
  M = 768_000
  v = jnp.asarray(rng.integers(0, 3, M).astype(np.int32))

  def matmul_cumsum(x):
    b = 512
    m = x.shape[0]
    pad = (-m) % b
    x2 = jnp.pad(x, (0, pad)).reshape(-1, b).astype(jnp.float32)
    tri = jnp.tril(jnp.ones((b, b), jnp.float32))
    within = x2 @ tri.T                       # inclusive row cumsum
    block_tot = within[:, -1]
    # recurse one level on block totals (<=1501 blocks)
    nb = block_tot.shape[0]
    pad2 = (-nb) % b
    bt = jnp.pad(block_tot, (0, pad2)).reshape(-1, b)
    bt_within = bt @ tri.T
    bt_tot = bt_within[:, -1]
    lvl2 = jnp.cumsum(bt_tot)                 # tiny
    offs2 = jnp.concatenate([jnp.zeros((1,), jnp.float32), lvl2[:-1]])
    block_prefix = (bt_within + offs2[:, None] - bt).reshape(-1)[:nb]
    out = within + block_prefix[:, None] - 0.0
    return out.reshape(-1)[:m].astype(jnp.int32)

  rec('cumsum_matmul_768k', timed(jax.jit(matmul_cumsum), v))
  rec('cumsum_native_768k', timed(jax.jit(jnp.cumsum), v))

  # parity check (host)
  got = np.asarray(jax.jit(matmul_cumsum)(v))
  want = np.cumsum(np.asarray(v))
  assert (got == want).all(), 'matmul cumsum mismatch'

  dev = jax.devices()[0]
  print(json.dumps({'metric': 'proto_window_ms', 'backend': dev.platform,
                    'W': W, 'ops': res}))


if __name__ == '__main__':
  main()

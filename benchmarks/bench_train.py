"""End-to-end training benchmark: GraphSAGE epoch time (the reference's
train_sage_ogbn_products.py protocol — fanout [15,10,5], batch 1024,
3 layers, hidden 256 — on a synthetic products-scale graph).

Prints one JSON line: epoch seconds + sampled-edge throughput.
"""
import argparse
import json
import time

import numpy as np


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=2_450_000)
  ap.add_argument('--avg-degree', type=int, default=25)
  ap.add_argument('--feat-dim', type=int, default=100)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', default='15,10,5')
  ap.add_argument('--hidden', type=int, default=256)
  ap.add_argument('--max-steps', type=int, default=0,
                  help='cap steps per epoch (0 = full epoch)')
  args = ap.parse_args()

  import jax
  import jax.numpy as jnp
  import optax
  from glt_tpu.data import Dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import GraphSAGE

  rng = np.random.default_rng(0)
  n = args.num_nodes
  e = n * args.avg_degree
  src = rng.integers(0, n, e, dtype=np.int64)
  dst = (rng.random(e) ** 2 * n).astype(np.int64) % n
  feats = rng.normal(size=(n, args.feat_dim)).astype(np.float32)
  w = rng.normal(size=(args.feat_dim, 47)).astype(np.float32)
  labels = np.argmax(feats @ w, 1).astype(np.int32)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
  del src, dst
  ds.init_node_features(feats)
  ds.init_node_labels(labels)
  train_idx = rng.permutation(n)[: int(n * 0.1)]

  fanout = [int(x) for x in args.fanout.split(',')]
  loader = NeighborLoader(ds, fanout, input_nodes=train_idx,
                          batch_size=args.batch_size, shuffle=True,
                          drop_last=True, seed=0)
  model = GraphSAGE(hidden_features=args.hidden, out_features=47,
                    num_layers=len(fanout))
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      l = optax.softmax_cross_entropy_with_integer_labels(logits, batch.y)
      return l.mean()
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  # warmup/compile
  params, opt, loss = step(params, opt, b0)
  jax.block_until_ready(loss)

  t0 = time.time()
  steps = 0
  edges = 0
  for batch in loader:
    params, opt, loss = step(params, opt, batch)
    edges += int(np.asarray(jnp.sum(batch.num_sampled_edges)))
    steps += 1
    if args.max_steps and steps >= args.max_steps:
      break
  jax.block_until_ready(loss)
  dt = time.time() - t0
  full_epoch_est = dt * (len(loader) / max(steps, 1))
  print(json.dumps({
      'metric': 'sage_products_epoch_seconds',
      'value': round(full_epoch_est, 2),
      'unit': 's',
      'vs_baseline': None,
      'detail': {'steps_timed': steps, 'seconds': round(dt, 2),
                 'sampled_edges_per_sec': round(edges / dt, 1),
                 'final_loss': float(loss)},
  }))


if __name__ == '__main__':
  main()

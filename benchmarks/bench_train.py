"""End-to-end training benchmark: GraphSAGE epoch time + accuracy.

Protocol mirrors the reference's examples/train_sage_ogbn_products.py
(fanout [15,10,5], batch 1024, 3 layers, hidden 256; the reference
reports approx_acc ~= 0.787 on real ogbn-products after 20 epochs).

Synthetic <-> real mapping (datasets are not downloadable here): the
graph is products-scale (2.45M nodes / ~61M directed edges, skewed
in-degrees) and labels are the argmax of a fixed random linear map of
each node's features BLENDED WITH its mean out-neighbor features — the
label signal deliberately lives partly in the graph structure, as it
does in real products. The measured quantities decompose as:
  * epoch_seconds — directly comparable to the reference's wall-clock
    per epoch at identical shapes (same sampled work per step).
  * test_acc — NOT comparable to 0.787 in value (different label
    process); comparable in KIND: it must climb above the feature-only
    linear baseline printed alongside it (``linear_probe_acc``), which
    a model can only do by aggregating sampled neighborhoods — the
    capability the reference's accuracy number certifies.

Prints one JSON line: epoch seconds + accuracy evidence.
``GLT_BENCH_PLATFORM=cpu`` forces the CPU backend (the axon TPU plugin
ignores JAX_PLATFORMS).
"""
import argparse
import json
import os
import time

import os as _os
import sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(
    _os.path.abspath(__file__))))  # repo root -> glt_tpu

import numpy as np

_CACHE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_cache')


def measure_engines(num_nodes=5_000, avg_degree=8, feat_dim=16,
                    batch_size=256, fanout=(3, 2), hidden=16,
                    num_classes=8, k=8, supersteps=12, warmup=2,
                    seed=0):
  """Per-batch vs superstep engine A/B: end-to-end train_steps_per_sec.

  Both engines run the SAME compiled batch body (sample -> all_to_all
  feature gather -> forward/backward -> update) on a 1-device mesh with
  the same key stream; the superstep engine scans ``k`` batches per
  donated dispatch. Loss parity is ASSERTED (bit-exact), as is zero
  steady-state recompiles of the superstep program (trace counter).
  Returns the metrics dict (steps/sec per engine + speedup).
  """
  import jax
  import jax.numpy as jnp
  import numpy as np
  import optax
  from glt_tpu.data import Dataset
  from glt_tpu.models import GraphSAGE
  from glt_tpu.parallel import (ShardedFeature, SPMDSageTrainStep,
                                make_mesh)

  rng = np.random.default_rng(seed)
  e = num_nodes * avg_degree
  src = rng.integers(0, num_nodes, e, dtype=np.int64)
  dst = (rng.random(e) ** 2 * num_nodes).astype(np.int64) % num_nodes
  feats = rng.normal(size=(num_nodes, feat_dim)).astype(np.float32)
  labels = rng.integers(0, num_classes, num_nodes).astype(np.int32)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=num_nodes)
  del src, dst

  mesh = make_mesh(1)
  model = GraphSAGE(hidden_features=hidden, out_features=num_classes,
                    num_layers=len(fanout))
  tx = optax.adam(1e-3)
  sf = ShardedFeature(feats, mesh)
  step = SPMDSageTrainStep(mesh, model, tx, ds.get_graph(), sf, labels,
                           fanouts=list(fanout),
                           batch_size_per_device=batch_size)
  params0 = step.init_params(jax.random.key(0))
  opt0 = tx.init(params0)

  total = k * supersteps
  warm_total = k * warmup
  seed_pool = rng.integers(0, num_nodes, (warm_total + total,
                                          batch_size))
  keys = jax.random.split(jax.random.key(1), (warm_total + total, 1))
  nv = np.full((1,), batch_size)

  def fresh():
    return jax.tree.map(jnp.array, (params0, opt0))

  seeds_stacks = seed_pool.reshape(warmup + supersteps, k, batch_size)
  keys_stacks = keys.reshape(warmup + supersteps, k, 1)
  nv_stack = np.full((k, 1), batch_size)

  # warmup/compile both engines
  p_pb, o_pb = fresh()
  p_ss, o_ss = fresh()
  for w in range(warmup):
    for t in range(w * k, (w + 1) * k):
      p_pb, o_pb, loss_pb = step(p_pb, o_pb, seed_pool[t], nv, keys[t])
    p_ss, o_ss, loss_ss = step.superstep(
        p_ss, o_ss, seeds_stacks[w], nv_stack, keys_stacks[w])
  jax.block_until_ready((loss_pb, loss_ss))
  traces_before = step.superstep_traces

  # Interleaved measurement: each rep times one K-step block per engine
  # back to back (one device sync per block for BOTH), advancing the
  # SAME key stream on separate model states. CPU wall-clock on shared
  # boxes drifts on ~10 s scales; phase-separated timing aliases that
  # drift into the ratio, interleaving cancels it.
  losses_pb, losses_ss = [], []
  dt_pb = dt_ss = 0.0
  for w in range(warmup, warmup + supersteps):
    t0 = time.time()
    for t in range(w * k, (w + 1) * k):
      p_pb, o_pb, loss = step(p_pb, o_pb, seed_pool[t], nv, keys[t])
      losses_pb.append(loss)
    jax.block_until_ready(losses_pb[-1])
    dt_pb += time.time() - t0
    t0 = time.time()
    p_ss, o_ss, loss = step.superstep(
        p_ss, o_ss, seeds_stacks[w], nv_stack, keys_stacks[w])
    losses_ss.append(loss)
    jax.block_until_ready(loss)
    dt_ss += time.time() - t0

  recompiles = step.superstep_traces - traces_before
  assert recompiles == 0, (
      f'superstep steady state retraced {recompiles}x')
  pb = np.stack([np.asarray(l) for l in losses_pb]).reshape(-1)
  ss = np.concatenate([np.asarray(l) for l in losses_ss]).reshape(-1)
  assert np.array_equal(pb, ss), (
      'engine loss parity violated: max diff '
      f'{np.abs(pb - ss).max()}')

  per_batch = total / dt_pb
  superstep = total / dt_ss
  return {
      'metric': 'train_steps_per_sec',
      'value': round(superstep, 2),
      'unit': 'steps/s',
      'vs_baseline': None,
      'detail': {
          'per_batch_steps_per_sec': round(per_batch, 2),
          'superstep_steps_per_sec': round(superstep, 2),
          'speedup': round(superstep / per_batch, 3),
          'superstep_k': k,
          'batch_size': batch_size,
          'fanout': list(fanout),
          'steps_timed': total,
          'loss_parity': 'exact',
          'steady_state_recompiles': recompiles,
          'final_loss': float(ss[-1]),
          'backend': jax.devices()[0].platform,
      },
  }


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--num-nodes', type=int, default=2_450_000)
  ap.add_argument('--avg-degree', type=int, default=25)
  ap.add_argument('--feat-dim', type=int, default=100)
  ap.add_argument('--batch-size', type=int, default=1024)
  ap.add_argument('--fanout', default='15,10,5')
  ap.add_argument('--hidden', type=int, default=256)
  ap.add_argument('--max-steps', type=int, default=0,
                  help='cap steps per epoch (0 = full epoch)')
  ap.add_argument('--epochs', type=int, default=1,
                  help='training epochs before the accuracy eval')
  ap.add_argument('--eval-batches', type=int, default=20)
  ap.add_argument('--curve', action='store_true',
                  help='eval after EVERY epoch (accuracy curve); with '
                       '--plateau, stop once test_acc has not improved '
                       'by >0.002 for that many epochs (convergence '
                       'evidence, VERDICT r3 weak #5)')
  ap.add_argument('--plateau', type=int, default=0)
  ap.add_argument('--ckpt-dir', default=None,
                  help='save params+opt+epoch+curve after every epoch '
                       '(orbax); with --resume, continue from the '
                       'latest checkpoint — the north-star curve then '
                       'accumulates ACROSS benchmark invocations '
                       '(reference protocol: '
                       'train_sage_ogbn_products.py:111-120 trains 20 '
                       'epochs in one process; on this 1-core box the '
                       'same budget is paid across rounds instead)')
  ap.add_argument('--resume', action='store_true')
  ap.add_argument('--superstep-ab', action='store_true',
                  help='run the per-batch vs superstep engine A/B '
                       '(train_steps_per_sec, loss parity asserted, '
                       'zero steady-state recompiles asserted) instead '
                       'of the epoch protocol')
  ap.add_argument('--ab-k', type=int, default=8,
                  help='superstep length K for --superstep-ab')
  ap.add_argument('--ab-batch', type=int, default=256)
  ap.add_argument('--ab-supersteps', type=int, default=12)
  ap.add_argument('--min-speedup', type=float, default=0.0,
                  help='with --superstep-ab: exit nonzero when the '
                       'measured speedup falls below this')
  ap.add_argument('--time-budget', type=float, default=0,
                  help='stop starting new epochs after this many '
                       'seconds (0 = none); the last checkpoint makes '
                       'the partial run resumable')
  args = ap.parse_args()
  if args.plateau and not args.curve:
    args.curve = True  # plateau detection needs the per-epoch evals

  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend()
  jax.config.update('jax_compilation_cache_dir', _CACHE_DIR)
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 1.0)

  if args.superstep_ab:
    out = measure_engines(batch_size=args.ab_batch, k=args.ab_k,
                          supersteps=args.ab_supersteps)
    print(json.dumps(out))
    if args.min_speedup and out['detail']['speedup'] < args.min_speedup:
      _sys.exit(f"speedup {out['detail']['speedup']} < "
                f"{args.min_speedup}")
    return
  import jax.numpy as jnp
  import optax
  from glt_tpu.data import Dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import GraphSAGE

  rng = np.random.default_rng(0)
  n = args.num_nodes
  e = n * args.avg_degree
  src = rng.integers(0, n, e, dtype=np.int64)
  dst = (rng.random(e) ** 2 * n).astype(np.int64) % n
  feats = rng.normal(size=(n, args.feat_dim)).astype(np.float32)
  w = rng.normal(size=(args.feat_dim, 47)).astype(np.float32)
  # neighborhood-dependent labels: own features + mean out-neighbor
  # features, so beating the feature-only probe REQUIRES aggregation.
  # Chunked scatter: a whole-edge feats[dst] temporary would be
  # edges x feat_dim x 4B (~24 GB at default scale).
  nbr_sum = np.zeros_like(feats)
  deg = np.zeros(n, np.float32)
  chunk = 2_000_000
  for lo in range(0, e, chunk):
    s_c, d_c = src[lo:lo + chunk], dst[lo:lo + chunk]
    np.add.at(nbr_sum, s_c, feats[d_c])
    np.add.at(deg, s_c, 1.0)
  blended = feats + nbr_sum / np.maximum(deg, 1.0)[:, None]
  labels = np.argmax(blended @ w, 1).astype(np.int32)
  del nbr_sum, blended
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
  del src, dst
  ds.init_node_features(feats)
  ds.init_node_labels(labels)
  perm = rng.permutation(n)
  train_idx = perm[: int(n * 0.1)]
  test_idx = perm[int(n * 0.1): int(n * 0.11)]

  # feature-only linear probe: the baseline the GNN must beat (a fresh
  # least-squares fit, NOT the generating matrix)
  sub = rng.choice(train_idx, min(20_000, train_idx.shape[0]),
                   replace=False)
  onehot = np.eye(47, dtype=np.float32)[labels[sub]]
  w_fit, *_ = np.linalg.lstsq(feats[sub], onehot, rcond=None)
  probe_pred = np.argmax(feats[test_idx] @ w_fit, 1)
  linear_probe_acc = float((probe_pred == labels[test_idx]).mean())

  fanout = [int(x) for x in args.fanout.split(',')]
  loader = NeighborLoader(ds, fanout, input_nodes=train_idx,
                          batch_size=args.batch_size, shuffle=True,
                          drop_last=True, seed=0)
  model = GraphSAGE(hidden_features=args.hidden, out_features=47,
                    num_layers=len(fanout))
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(1e-3)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      l = optax.softmax_cross_entropy_with_integer_labels(logits, batch.y)
      return l.mean()
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  @jax.jit
  def predict(params, batch):
    return jnp.argmax(model.apply(params, batch), -1)

  # warmup/compile
  params, opt, loss = step(params, opt, b0)
  jax.block_until_ready(loss)

  # orbax carries the arrays; a json sidecar carries the curve (a
  # variable-length list cannot ride a StandardRestore template)
  start_epoch, prior_curve = 0, []
  meta_path = (os.path.join(args.ckpt_dir, 'curve.json')
               if args.ckpt_dir else None)
  if args.ckpt_dir and args.resume:
    from glt_tpu.utils.checkpoint import restore_checkpoint
    got, payload = restore_checkpoint(
        args.ckpt_dir, template={'params': params, 'opt_state': opt})
    if payload is not None:
      params = payload['params']
      opt = payload['opt_state']
      start_epoch = int(got)
      if os.path.exists(meta_path):
        with open(meta_path) as f:
          prior_curve = json.load(f)['curve']
      print(json.dumps({'resumed_epoch': start_epoch,
                        'prior_curve': prior_curve}),
            file=_sys.stderr, flush=True)

  # built ONCE: per-epoch curve evals reuse the compiled sampler fns
  eval_loader = NeighborLoader(ds, fanout, input_nodes=test_idx,
                               batch_size=args.batch_size,
                               shuffle=False, drop_last=False, seed=1)

  def eval_acc(params):
    correct = total = 0
    for i, batch in enumerate(eval_loader):
      if i >= args.eval_batches:
        break
      pred = np.asarray(predict(params, batch))
      yb = np.asarray(batch.y)
      nv = int((batch.metadata or {}).get('n_valid', yb.shape[0]))
      correct += int((pred[:nv] == yb[:nv]).sum())
      total += nv
    return correct / max(total, 1), total

  dt = steps = edges = 0
  curve = list(prior_curve)
  best = max(prior_curve) if prior_curve else -1.0
  since_best = 0
  n_epochs = max(args.epochs, 1)
  epoch = start_epoch
  t_run = time.time()
  while True:
    t0 = time.time()
    ep_steps = 0
    for batch in loader:
      params, opt, loss = step(params, opt, batch)
      edges += int(np.asarray(jnp.sum(batch.num_sampled_edges)))
      steps += 1
      ep_steps += 1
      if args.max_steps and ep_steps >= args.max_steps:
        break
    jax.block_until_ready(loss)
    ep_s = time.time() - t0   # training only; eval time excluded
    dt += ep_s
    epoch += 1
    if args.curve:
      acc, total = eval_acc(params)
      curve.append(round(acc, 4))
      print(json.dumps({'epoch': epoch, 'test_acc': round(acc, 4),
                        'loss': round(float(loss), 4),
                        'epoch_s': round(ep_s, 1)}),
            file=_sys.stderr, flush=True)
      if acc > best + 0.002:
        best, since_best = acc, 0
      else:
        since_best += 1
    if args.ckpt_dir:
      from glt_tpu.utils.checkpoint import save_checkpoint
      save_checkpoint(args.ckpt_dir, epoch, params, opt_state=opt)
      with open(meta_path, 'w') as f:
        json.dump({'curve': [round(float(a), 4) for a in curve],
                   'epoch': epoch}, f)
      print(json.dumps({'checkpoint_epoch': epoch}),
            file=_sys.stderr, flush=True)
    if args.curve and args.plateau and since_best >= args.plateau:
      break
    if epoch >= n_epochs and not (args.plateau and args.curve):
      break
    if args.plateau and args.curve and epoch >= max(n_epochs, 200):
      break  # hard stop safety
    if args.time_budget and time.time() - t_run > args.time_budget:
      print(json.dumps({'time_budget_stop': epoch}),
            file=_sys.stderr, flush=True)
      break
  ran_epochs = max(epoch - start_epoch, 1)
  per_epoch_steps = steps / ran_epochs
  full_epoch_est = (dt / ran_epochs) * (len(loader) /
                                        max(per_epoch_steps, 1))

  if args.curve and curve:
    test_acc = curve[-1]  # ``total`` keeps the last eval's seed count
  else:
    test_acc, total = eval_acc(params)

  dev = jax.devices()[0]
  print(json.dumps({
      'metric': 'sage_products_epoch_seconds',
      'value': round(full_epoch_est, 2),
      'unit': 's',
      'vs_baseline': None,
      'detail': {'steps_timed': steps, 'seconds': round(dt, 2),
                 'sampled_edges_per_sec': round(edges / max(dt, 1e-9), 1),
                 'final_loss': float(loss),
                 'epochs': epoch, 'epochs_this_run': ran_epochs,
                 'test_acc': round(test_acc, 4),
                 'acc_curve': curve if curve else None,
                 'best_test_acc': round(max(curve), 4) if curve
                 else round(test_acc, 4),
                 'linear_probe_acc': round(linear_probe_acc, 4),
                 'eval_seeds': total,
                 'num_nodes': n,
                 'backend': dev.platform},
  }))


if __name__ == '__main__':
  main()

#!/bin/bash
# Full TPU perf suite — run whenever hardware is reachable. Each step
# appends to benchmarks/tpu_runs/ so partial runs still leave evidence
# (the axon tunnel can drop at any time).
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/tpu_runs
mkdir -p "$OUT"

run() {  # run NAME CMD... — capture json + log, keep going on failure
  local name=$1; shift
  echo "== $name: $*" >&2
  # hard per-step timeout: a backend-init wedge must cost one step, not
  # hang the whole unattended suite. These steps are single python
  # processes, so timeout(1)'s TERM to the direct child suffices.
  timeout 2400 "$@" > "$OUT/$name.json" 2> "$OUT/$name.log"
  tail -c 200 "$OUT/$name.json" >&2; echo >&2
}

run_bench() {  # bench.py steps: self-supervising (probe child + budget),
  # so NO outer timeout — an outer TERM would orphan the --run
  # grandchild mid-attempt, which can keep the TPU held and wedge every
  # later step. The supervisor probes the backend with a 90 s child
  # before paying for a full attempt and bounds its own total wall
  # clock, so a wedged tunnel costs ~2 min per step, not 40.
  local name=$1; shift
  echo "== $name: $* (self-supervised)" >&2
  GLT_BENCH_BUDGET=700 \
      "$@" > "$OUT/$name.json" 2> "$OUT/$name.log"
  tail -c 200 "$OUT/$name.json" >&2; echo >&2
}

# 1. headline engine/scan/PRNG A/Bs (bench.py is supervised + retried).
# The first successful step doubles as the compile-cache prime: bench.py
# writes .jax_cache, which the driver's end-of-round run reuses.
run_bench bench_sort_scan4 python bench.py
# 1a. fused hop assign (GLT_FUSED_HOP): single-sort dedup targeting the
#     profiled 41 ms assign_h2 stage — ordered right after the headline
#     so any tunnel window captures the A/B (VERDICT r4 next #2)
run_bench bench_sort_fusedhop env GLT_FUSED_HOP=1 python bench.py
run_bench bench_table_scan4 env GLT_DEDUP=table python bench.py
run_bench bench_sort_scan1 env GLT_BENCH_SCAN=1 python bench.py
run_bench bench_sort_scan8 env GLT_BENCH_SCAN=8 python bench.py
run_bench bench_sort_rbg env GLT_PRNG=rbg python bench.py

# 2. primitive economics (incl. sort-engine internals + PRNG A/B)
run microbench_prims_tpu python benchmarks/microbench_prims.py

# 3. stage breakdown + profiler trace (top-op evidence)
run profile_sampler_tpu python benchmarks/profile_sampler.py \
    --trace /tmp/glt_trace
run profile_sampler_fused env GLT_FUSED_HOP=1 \
    python benchmarks/profile_sampler.py

# 4. feature gather: XLA vs Pallas row-DMA
run bench_feature_xla python benchmarks/bench_feature.py
run bench_feature_pallas env GLT_USE_PALLAS=1 \
    python benchmarks/bench_feature.py

# 5. epoch-time + accuracy protocol: full epochs with per-epoch curve
#    (the north-star artifact, BASELINE.md). 3 full epochs on TPU is
#    ~minutes at r2 trace speeds; fall back to a 50-step slice only if
#    this step times out.
run bench_train_tpu python benchmarks/bench_train.py --epochs 3 --curve

# 6. beyond-HBM spill training (20.5 GB table > 16 GB HBM; the real
#    beyond-HBM claim needs this chip run — CPU only measures the ratio)
run bench_spill_tpu python benchmarks/bench_spill_train.py

# 6b. beyond-HBM through the FUSED step: pinned-host cold blocks served
#     in-program (compute_on gather) vs device-resident — the offload
#     tax on real HBM/PCIe, same 20.5 GB table
run bench_fused_spill_tpu python benchmarks/bench_fused_spill.py

# 7. capped-bucket drain grid (mesh size 1 still lowers the collectives;
#    round counts come from the deterministic host replay)
run bench_bucket_drain_tpu python benchmarks/bench_bucket_drain.py

# 8. accuracy certification under TPU numerics (bf16/matmul precision).
#    --out stays under $OUT so the watcher's auto-commit catches the
#    CLEAN artifact (the stdout capture carries progress lines too).
run certify_accuracy_tpu python benchmarks/certify_accuracy.py \
    --out "$OUT/certify_accuracy_tpu_clean.json"

"""Any-to-any rpc fabric (reference rpc.py:240-529 surface): init_rpc
rendezvous, cross-rank requests, role-scoped collectives, partition
router. Pure sockets — no jax backend involved."""
import multiprocessing as mp
import socket

import numpy as np
import pytest


def _free_port() -> int:
  s = socket.socket()
  s.bind(('127.0.0.1', 0))
  port = s.getsockname()[1]
  s.close()
  return port


def _fabric_worker(rank: int, world: int, port: int, q) -> None:
  try:
    from glt_tpu.distributed import (
        RpcCalleeBase, RpcDataPartitionRouter, all_gather, barrier,
        global_all_gather, init_rpc, rpc_is_initialized, rpc_register,
        rpc_request, rpc_request_async, rpc_sync_data_partitions,
        shutdown_rpc,
    )
    assert not rpc_is_initialized()
    init_rpc('127.0.0.1', port, rank=rank, world_size=world)
    assert rpc_is_initialized()

    class Doubler(RpcCalleeBase):
      def call(self, x):
        return (rank, np.asarray(x) * 2)

    rpc_register('double', Doubler())
    barrier()  # all callees registered before anyone requests

    # every rank calls every OTHER rank (and itself through the socket)
    for dst in range(world):
      got_rank, doubled = rpc_request(dst, 'double', np.arange(3))
      assert got_rank == dst
      np.testing.assert_array_equal(doubled, np.arange(3) * 2)
    fut = rpc_request_async((rank + 1) % world, 'double', 7)
    assert fut.result(timeout=60)[1] == 14

    gathered = all_gather(f'v{rank}')
    assert gathered == {r: f'v{r}' for r in range(world)}
    gathered2 = global_all_gather(rank * 10)
    assert gathered2 == {r: r * 10 for r in range(world)}

    # partition->workers map + router: rank r serves partitions {r, r+1}
    p2w = rpc_sync_data_partitions([rank, (rank + 1) % world])
    assert sorted(p2w) == list(range(world))
    for p, ws in p2w.items():
      assert sorted(ws) == sorted({p, (p - 1) % world})
    router = RpcDataPartitionRouter(p2w)
    picks = {router.get_to_worker(0) for _ in range(4)}
    assert picks == set(p2w[0])  # round-robin covers every server

    shutdown_rpc()
    assert not rpc_is_initialized()
    q.put((rank, 'ok'))
  except BaseException as e:  # surface the failure to the parent
    q.put((rank, f'FAIL: {type(e).__name__}: {e}'))


def test_rpc_fabric_three_ranks():
  world = 3
  port = _free_port()
  ctx = mp.get_context('spawn')
  q = ctx.Queue()
  procs = [ctx.Process(target=_fabric_worker, args=(r, world, port, q))
           for r in range(world)]
  for p in procs:
    p.start()
  # generous: each spawned worker pays the full package import, and the
  # suite often runs alongside long background benchmarks on one core
  results = [q.get(timeout=600) for _ in range(world)]
  for p in procs:
    p.join(timeout=120)
  assert all(msg == 'ok' for _, msg in results), results


def test_rpc_fabric_requires_identity_without_context():
  from glt_tpu.distributed import init_rpc
  with pytest.raises(ValueError, match='rank/world_size'):
    init_rpc('127.0.0.1', _free_port())


def test_rpc_fabric_master_port_zero_rejected():
  from glt_tpu.distributed import init_rpc
  with pytest.raises(ValueError, match='concrete pre-agreed port'):
    init_rpc('127.0.0.1', 0, rank=0, world_size=1)


def test_rpc_server_waits_for_late_registration():
  # a peer can discover the server before user code registers; the
  # lookup waits instead of failing (the KeyError('push_edges') race)
  import threading
  import time
  from glt_tpu.distributed import RpcClient, RpcServer
  server = RpcServer()
  try:
    client = RpcClient(server.host, server.port)
    threading.Timer(0.5, lambda: server.register(
        'late', lambda x: x + 1)).start()
    t0 = time.monotonic()
    assert client.request('late', 41) == 42
    assert time.monotonic() - t0 < 30
    client.close()
  finally:
    server.stop()


def test_concurrent_event_loop():
  import threading
  import time
  from glt_tpu.distributed import ConcurrentEventLoop
  loop = ConcurrentEventLoop(concurrency=2)
  active = [0]
  peak = [0]
  lock = threading.Lock()

  def task(i):
    with lock:
      active[0] += 1
      peak[0] = max(peak[0], active[0])
    time.sleep(0.05)
    with lock:
      active[0] -= 1
    return i * 2

  got = []
  for i in range(6):
    loop.add_task(task, i, callback=got.append)
  loop.wait_all()
  assert sorted(got) == [0, 2, 4, 6, 8, 10]
  assert peak[0] <= 2  # bounded in-flight window
  assert loop.run_task(task, 21) == 42
  # failures surface at wait_all
  loop.add_task(lambda: (_ for _ in ()).throw(RuntimeError('boom')))
  with pytest.raises(RuntimeError, match='boom'):
    loop.wait_all()
  loop.shutdown()


def test_concurrent_event_loop_error_semantics():
  """ADVICE r4: nested submission fails loudly instead of deadlocking;
  callback exceptions land in the future (not the executor logger) and
  run only on success; run_task failures are consumed (wait_all must
  not re-raise them)."""
  from glt_tpu.distributed import ConcurrentEventLoop
  loop = ConcurrentEventLoop(concurrency=1)

  # nested add_task to the SAME loop -> loud error captured in future
  def nested():
    loop.add_task(lambda: None)
  with pytest.raises(RuntimeError, match='nested add_task'):
    loop.run_task(nested)

  # ...but a SIBLING loop is a legal nested stage
  sibling = ConcurrentEventLoop(concurrency=1)
  assert loop.run_task(lambda: sibling.run_task(lambda: 7)) == 7
  sibling.shutdown()

  # callback errors surface through the future
  def bad_cb(_):
    raise ValueError('callback blew up')
  fut = loop.add_task(lambda: 1, callback=bad_cb)
  with pytest.raises(ValueError, match='callback blew up'):
    fut.result()
  loop._pending.clear()  # consumed above

  # a failing task never invokes its callback
  ran = []
  fut = loop.add_task(
      lambda: (_ for _ in ()).throw(RuntimeError('task failed')),
      callback=ran.append)
  with pytest.raises(RuntimeError, match='task failed'):
    fut.result()
  assert ran == []
  loop._pending.clear()

  # run_task consumes its own failure: wait_all stays clean
  with pytest.raises(RuntimeError, match='once only'):
    loop.run_task(lambda: (_ for _ in ()).throw(
        RuntimeError('once only')))
  loop.wait_all()  # must NOT re-raise
  loop.shutdown()


def _role_worker(rank: int, world: int, port: int, q) -> None:
  try:
    from glt_tpu.distributed import (
        all_gather, barrier, init_rpc, init_worker_group, shutdown_rpc,
    )
    # role-scoped collectives resolve identity + world from the
    # DistContext (reference role-group all_gather, rpc.py:105-211)
    init_worker_group(world_size=world, rank=rank)
    init_rpc('127.0.0.1', port)  # rank/world from the context
    barrier()
    got = all_gather(rank + 100)
    assert got == {r: r + 100 for r in range(world)}, got
    shutdown_rpc()
    q.put((rank, 'ok'))
  except BaseException as e:
    q.put((rank, f'FAIL: {type(e).__name__}: {e}'))


def test_rpc_fabric_role_scoped_collectives():
  world = 2
  port = _free_port()
  ctx = mp.get_context('spawn')
  q = ctx.Queue()
  procs = [ctx.Process(target=_role_worker, args=(r, world, port, q))
           for r in range(world)]
  for p in procs:
    p.start()
  results = [q.get(timeout=600) for _ in range(world)]
  for p in procs:
    p.join(timeout=120)
  assert all(msg == 'ok' for _, msg in results), results

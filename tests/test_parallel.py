"""SPMD layer tests on the 8-device virtual CPU mesh: sharded feature
lookup (all_to_all) exactness and the full distributed train step."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from glt_tpu.parallel import ShardedFeature, SPMDSageTrainStep, make_mesh
from glt_tpu.models import GraphSAGE

from fixtures import ring_dataset, ring_edges


@pytest.fixture(scope='module')
def mesh():
  return make_mesh(8)


def test_sharded_feature_lookup_exact(mesh):
  n, d = 100, 8
  feats = np.arange(n * d, dtype=np.float32).reshape(n, d)
  sf = ShardedFeature(feats, mesh)
  assert sf.rows_per_shard == 13  # ceil(100/8)
  rng = np.random.default_rng(0)
  ids = rng.integers(0, n, size=8 * 16)  # 16 requests per device
  out = np.asarray(sf.lookup(ids))
  np.testing.assert_allclose(out, feats[ids])


def test_sharded_feature_lookup_with_invalid(mesh):
  n, d = 64, 4
  feats = np.random.default_rng(1).normal(size=(n, d)).astype(np.float32)
  sf = ShardedFeature(feats, mesh)
  ids = np.tile(np.arange(8), 8)          # 8 per device
  valid = np.tile(np.array([True] * 4 + [False] * 4), 8)
  out = np.asarray(sf.lookup(ids, jnp.asarray(valid)))
  np.testing.assert_allclose(out[valid], feats[ids[valid]])
  np.testing.assert_allclose(out[~valid], 0.0)


def test_sharded_feature_hot_spot(mesh):
  # every device asks for rows owned by shard 0 (worst-case skew)
  n, d = 80, 4
  feats = np.random.default_rng(2).normal(size=(n, d)).astype(np.float32)
  sf = ShardedFeature(feats, mesh)
  ids = np.zeros(8 * 8, dtype=np.int64)  # all ask row 0
  out = np.asarray(sf.lookup(ids))
  np.testing.assert_allclose(out, np.tile(feats[0], (64, 1)))


def test_spmd_train_step_runs_and_learns(mesh):
  n = 40
  rows, cols, _ = ring_edges(n)
  from glt_tpu.data import Dataset
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=n)
  feats = np.eye(n, dtype=np.float32)
  labels = (np.arange(n) % 4).astype(np.int32)

  model = GraphSAGE(hidden_features=16, out_features=4, num_layers=2)
  tx = optax.adam(1e-2)
  sf = ShardedFeature(feats, mesh)
  step = SPMDSageTrainStep(mesh, model, tx, ds.get_graph(), sf, labels,
                           fanouts=[2, 2], batch_size_per_device=4)
  params = step.init_params(jax.random.key(0))
  opt_state = jax.device_put(
      tx.init(params),
      jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))

  rng = np.random.default_rng(0)
  losses = []
  for it in range(60):
    seeds = rng.permutation(n)[:32]       # 8 devices x 4 seeds
    keys = jax.random.split(jax.random.key(it), 8)
    params, opt_state, loss = step(
        params, opt_state, seeds, np.full(8, 4), keys)
    losses.append(float(np.asarray(loss)[0]))
  assert losses[-1] < 0.25, f'did not learn: {losses[::10]}'


def test_spmd_losses_identical_across_devices(mesh):
  # pmean'd loss must be replicated: all 8 entries equal
  n = 40
  rows, cols, _ = ring_edges(n)
  from glt_tpu.data import Dataset
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=n)
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=1)
  tx = optax.sgd(1e-2)
  sf = ShardedFeature(np.eye(n, dtype=np.float32), mesh)
  step = SPMDSageTrainStep(mesh, model, tx, ds.get_graph(), sf,
                           (np.arange(n) % 4).astype(np.int32),
                           fanouts=[2], batch_size_per_device=4)
  params = step.init_params(jax.random.key(1))
  opt_state = tx.init(params)
  keys = jax.random.split(jax.random.key(9), 8)
  _, _, loss = step(params, opt_state, np.arange(32), np.full(8, 4), keys)
  loss = np.asarray(loss)
  np.testing.assert_allclose(loss, loss[0], rtol=1e-6)


def test_sharded_segment_mean_matches_global(mesh):
  """Context-parallel aggregation over a neighbor list sharded across
  the mesh equals the single-device segment mean."""
  from glt_tpu.parallel import sharded_segment_mean
  from jax.sharding import PartitionSpec as P
  rng = np.random.default_rng(0)
  m, d, segs = 8 * 64, 16, 10
  msgs = rng.normal(size=(m, d)).astype(np.float32)
  targets = rng.integers(0, segs, m).astype(np.int32)
  mask = rng.random(m) > 0.2

  fn = jax.shard_map(
      lambda ms, t, mk: sharded_segment_mean(ms, t, mk, segs, 'data'),
      mesh=mesh, in_specs=(P('data'), P('data'), P('data')),
      out_specs=P(), check_vma=False)
  got = np.asarray(fn(jnp.asarray(msgs), jnp.asarray(targets),
                      jnp.asarray(mask)))
  # reference: plain masked mean
  expect = np.zeros((segs, d), np.float32)
  for s in range(segs):
    sel = (targets == s) & mask
    if sel.any():
      expect[s] = msgs[sel].mean(0)
  np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_sharded_segment_mean_scattered_matches_global(mesh):
  """Ring (reduce-scatter) aggregation: each device's segment block
  equals the corresponding slice of the global segment mean."""
  from glt_tpu.parallel import sharded_segment_mean_scattered
  from jax.sharding import PartitionSpec as P
  rng = np.random.default_rng(1)
  m, d, segs = 8 * 64, 16, 16   # 16 segments / 8 devices = 2 per shard
  msgs = rng.normal(size=(m, d)).astype(np.float32)
  targets = rng.integers(0, segs, m).astype(np.int32)
  mask = rng.random(m) > 0.2

  fn = jax.shard_map(
      lambda ms, t, mk: sharded_segment_mean_scattered(
          ms, t, mk, segs, 'data'),
      mesh=mesh, in_specs=(P('data'), P('data'), P('data')),
      out_specs=P('data'), check_vma=False)
  got = np.asarray(fn(jnp.asarray(msgs), jnp.asarray(targets),
                      jnp.asarray(mask)))          # [segs, d] stacked
  expect = np.zeros((segs, d), np.float32)
  for s in range(segs):
    sel = (targets == s) & mask
    if sel.any():
      expect[s] = msgs[sel].mean(0)
  np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


@pytest.mark.pallas
def test_sharded_feature_pallas_row_gather_parity(mesh):
  # the injected interpret-mode Pallas row gather must serve identical
  # rows to the XLA take through the full all_to_all lookup
  import functools
  from glt_tpu.ops.pallas_kernels import gather_rows
  n, d = 64, 8
  feats = np.arange(n * d, dtype=np.float32).reshape(n, d)
  ids = np.random.default_rng(0).integers(0, n, 8 * 16)
  base = ShardedFeature(feats, mesh)
  fast = ShardedFeature(feats, mesh,
                        row_gather=functools.partial(gather_rows,
                                                     interpret=True))
  np.testing.assert_array_equal(np.asarray(base.lookup(ids)),
                                np.asarray(fast.lookup(ids)))


def test_sharded_feature_spill_parity(mesh):
  # host-spill store must be value-identical to the fully-resident one
  n, d = 100, 8
  feats = np.random.default_rng(7).normal(size=(n, d)).astype(np.float32)
  base = ShardedFeature(feats, mesh)
  spill = ShardedFeature(feats, mesh, split_ratio=0.3)
  assert spill._spill and spill.hot_count < spill.rows_per_shard
  rng = np.random.default_rng(8)
  ids = rng.integers(0, n, size=8 * 16)
  valid = rng.random(8 * 16) < 0.8
  a = np.asarray(base.lookup(ids, jnp.asarray(valid)))
  b = np.asarray(spill.lookup(ids, jnp.asarray(valid)))
  np.testing.assert_allclose(a, b)
  np.testing.assert_allclose(b[valid], feats[ids[valid]])
  np.testing.assert_allclose(b[~valid], 0.0)


def test_sharded_feature_spill_all_cold(mesh):
  # split_ratio ~ 0: everything except the forced 1-row hot floor is
  # host-resident; values must still be exact
  n, d = 64, 4
  feats = np.arange(n * d, dtype=np.float32).reshape(n, d)
  spill = ShardedFeature(feats, mesh, split_ratio=0.0)
  assert spill.hot_count == 1
  ids = np.arange(64)
  out = np.asarray(spill.lookup(ids))
  np.testing.assert_allclose(out, feats[ids])


def test_spill_store_without_offload_rejected_by_fused_train_step(mesh):
  # a spilled store WITHOUT the pinned-host cold block cannot resolve
  # cold rows in-jit; the fused step must fail loudly at construction,
  # not train on zero vectors
  n = 40
  rows, cols, _ = ring_edges(n)
  from glt_tpu.data import Dataset
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=n)
  sf = ShardedFeature(np.eye(n, dtype=np.float32), mesh,
                      split_ratio=0.5, host_offload=False)
  import optax
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=1)
  with pytest.raises(NotImplementedError, match='host-spilled'):
    SPMDSageTrainStep(mesh, model, optax.sgd(1e-2), ds.get_graph(), sf,
                      (np.arange(n) % 4).astype(np.int32), fanouts=[2],
                      batch_size_per_device=4)


def test_sharded_feature_spill_legacy_host_phase_parity(mesh):
  # host_offload=False keeps the lookup()-host-phase fallback exact
  # (the escape hatch for platforms without memory kinds)
  n, d = 100, 8
  feats = np.random.default_rng(21).normal(size=(n, d)) \
      .astype(np.float32)
  legacy = ShardedFeature(feats, mesh, split_ratio=0.3,
                          host_offload=False)
  assert legacy._spill and legacy.cold_array is None
  ids = np.random.default_rng(22).integers(0, n, size=8 * 16)
  np.testing.assert_allclose(np.asarray(legacy.lookup(ids)), feats[ids])


def test_fused_train_step_with_host_offloaded_spill(mesh):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  # the pinned-host cold block (reference unified_tensor.cu:202-231 UVA
  # analog) lets the fused SPMD step train a spilled store with results
  # IDENTICAL to the device-resident run
  import optax
  from glt_tpu.data import Dataset
  n = 64
  rng = np.random.default_rng(23)
  src = np.repeat(np.arange(n), 3)
  dst = (src + rng.integers(1, n, src.shape[0])) % n
  feats = rng.normal(size=(n, 8)).astype(np.float32)
  labels = rng.integers(0, 4, n).astype(np.int32)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
  tx = optax.adam(1e-2)

  def losses(sf):
    step = SPMDSageTrainStep(mesh, model, tx, ds.get_graph(), sf,
                             labels, fanouts=[3, 2],
                             batch_size_per_device=4)
    params = step.init_params(jax.random.key(0))
    opt = tx.init(params)
    seeds = np.arange(8 * 4) % n
    out = []
    for i in range(2):
      keys = jax.random.split(jax.random.key(1 + i), 8)
      params, opt, loss = step(params, opt, seeds, np.full(8, 4), keys)
      out.append(float(np.asarray(loss)[0]))
    return out

  spilled = ShardedFeature(feats, mesh, split_ratio=0.4)
  assert spilled._spill and spilled.cold_array is not None
  assert (spilled.cold_array.sharding.memory_kind == 'pinned_host')
  np.testing.assert_allclose(losses(spilled),
                             losses(ShardedFeature(feats, mesh)),
                             rtol=1e-6)


def test_sharded_feature_bucket_cap_parity(mesh):
  # capped per-peer buckets + overflow drain must be value-identical
  n, d = 100, 8
  feats = np.random.default_rng(11).normal(size=(n, d)) \
      .astype(np.float32)
  base = ShardedFeature(feats, mesh)
  capped = ShardedFeature(feats, mesh, bucket_cap=4)  # B=16 per device
  rng = np.random.default_rng(12)
  ids = rng.integers(0, n, size=8 * 16)
  valid = rng.random(8 * 16) < 0.8
  a = np.asarray(base.lookup(ids, jnp.asarray(valid)))
  b = np.asarray(capped.lookup(ids, jnp.asarray(valid)))
  np.testing.assert_allclose(a, b)


def test_sharded_feature_bucket_cap_mutation_after_trace_rejected(mesh):
  n, d = 64, 4
  feats = np.arange(n * d, dtype=np.float32).reshape(n, d)
  sf = ShardedFeature(feats, mesh, bucket_cap=4)
  ids = np.arange(8 * 16, dtype=np.int64) % n
  sf.lookup(ids)
  sf.bucket_cap = 2
  with pytest.raises(RuntimeError, match='bucket_cap changed'):
    sf.lookup(ids)


def test_sharded_feature_bucket_cap_hot_spot(mesh):
  # worst-case skew: every device asks shard 0 for its whole batch —
  # the drain must run ceil(B/C) rounds and still be exact
  n, d = 80, 4
  feats = np.random.default_rng(13).normal(size=(n, d)) \
      .astype(np.float32)
  capped = ShardedFeature(feats, mesh, bucket_cap=3)
  ids = np.tile(np.arange(8), 8)  # all rows live on shard 0 (rps=10)
  out = np.asarray(capped.lookup(ids))
  np.testing.assert_allclose(out, feats[ids])


def test_sharded_feature_bucket_cap_with_spill(mesh):
  # capped buckets compose with host spill: overflow drains first, the
  # arithmetic cold phase then fills every cold lane exactly once
  n, d = 96, 4
  feats = np.arange(n * d, dtype=np.float32).reshape(n, d)
  st = ShardedFeature(feats, mesh, split_ratio=0.5, bucket_cap=4)
  rng = np.random.default_rng(14)
  ids = rng.integers(0, n, size=8 * 16)
  out = np.asarray(st.lookup(ids))
  np.testing.assert_allclose(out, feats[ids])

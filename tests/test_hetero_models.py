"""Hetero model tests: RGNN (rsage/rgat) and HGT learn on the hetero
ring fixture through the full loader path."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from glt_tpu.data import Dataset
from glt_tpu.loader import NeighborLoader
from glt_tpu.models import HGT, RGNN

U2I = ('user', 'u2i', 'item')
I2I = ('item', 'i2i', 'item')
# message-passing keys produced by edge_dir='out' sampling
REV_U2I = ('item', 'rev_u2i', 'user')
REV_I2I = ('item', 'i2i', 'item')


def _hetero_onehot_dataset(num_users=12, num_items=24):
  u = np.arange(num_users, dtype=np.int64)
  u2i_rows = np.repeat(u, 2)
  u2i_cols = np.stack([2 * u, 2 * u + 1], 1).reshape(-1) % num_items
  i = np.arange(num_items, dtype=np.int64)
  i2i_rows = np.repeat(i, 2)
  i2i_cols = np.stack([(i + 1) % num_items, (i + 2) % num_items],
                      1).reshape(-1)
  ds = Dataset(edge_dir='out')
  ds.init_graph(
      edge_index={U2I: np.stack([u2i_rows, u2i_cols]),
                  I2I: np.stack([i2i_rows, i2i_cols])},
      num_nodes={'user': num_users, 'item': num_items})
  ds.init_node_features({
      'user': np.eye(num_users, dtype=np.float32),
      'item': np.eye(num_items, dtype=np.float32),
  })
  ds.init_node_labels({
      'user': (np.arange(num_users) % 3).astype(np.int32),
      'item': (np.arange(num_items) % 3).astype(np.int32),
  })
  return ds


def _pad_user_features(ds, dim):
  """user/item one-hots have different widths; RGNN aggregates them into
  one dst space per relation, so pad to a common width."""
  nu = ds.node_features['user'].num_rows
  ni = ds.node_features['item'].num_rows
  w = max(nu, ni)
  feats = {
      'user': np.pad(np.eye(nu, dtype=np.float32), ((0, 0), (0, w - nu))),
      'item': np.pad(np.eye(ni, dtype=np.float32), ((0, 0), (0, w - ni))),
  }
  ds.init_node_features(feats)
  return ds


def _train(model, loader, steps=80, lr=5e-3, seed=0):
  b0 = next(iter(loader))
  params = model.init(jax.random.key(seed), b0)
  tx = optax.adam(lr)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      losses = optax.softmax_cross_entropy_with_integer_labels(
          logits, batch.y_dict[batch.input_type])
      return jnp.where(mask, losses, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, g = jax.value_and_grad(loss_fn)(params)
    up, opt = tx.update(g, opt)
    return optax.apply_updates(params, up), opt, loss

  losses = []
  it = 0
  while it < steps:
    for batch in loader:
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt, loss = step(params, opt, batch.replace(metadata=meta))
      losses.append(float(loss))
      it += 1
      if it >= steps:
        break
  return losses


@pytest.mark.parametrize('conv', ['rsage', 'rgat'])
def test_rgnn_learns(conv):
  ds = _pad_user_features(_hetero_onehot_dataset(), 0)
  loader = NeighborLoader(ds, {U2I: [2, 2], I2I: [2, 2]},
                          input_nodes=('user', np.arange(12)),
                          batch_size=6, shuffle=True, seed=0,
                          rng=np.random.default_rng(1))
  model = RGNN(edge_types=[REV_U2I, REV_I2I], hidden_features=32,
               out_features=3, num_layers=2, conv=conv)
  steps = 150 if conv == 'rgat' else 60  # attention converges slower
  losses = _train(model, loader, steps=steps)
  assert losses[-1] < 0.35, f'{conv} did not learn: {losses[::12]}'


def test_hgt_learns():
  ds = _pad_user_features(_hetero_onehot_dataset(), 0)
  loader = NeighborLoader(ds, {U2I: [2, 2], I2I: [2, 2]},
                          input_nodes=('user', np.arange(12)),
                          batch_size=6, shuffle=True, seed=0,
                          rng=np.random.default_rng(2))
  model = HGT(node_types=['user', 'item'],
              edge_types=[REV_U2I, REV_I2I],
              hidden_features=32, out_features=3, num_layers=2, heads=2)
  losses = _train(model, loader, steps=60, lr=3e-3)
  assert losses[-1] < 0.5, f'HGT did not learn: {losses[::12]}'


def test_hetero_trim_equivalence():
  """RGNN hierarchical trimming must not change seed outputs: trimmed
  hops feed representations no later layer reads (reference
  trim_to_layer semantics)."""
  import jax
  import numpy as np
  from fixtures import hetero_ring_dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type

  ds = hetero_ring_dataset(num_users=12, num_items=24)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  loader = NeighborLoader(ds, [3, 2], ('user', np.arange(12)),
                          batch_size=4, shuffle=False, seed=0)
  batch = next(iter(loader))
  assert batch.edge_hop_offsets_dict
  kw = dict(edge_types=[reverse_edge_type(u2i), i2i],
            hidden_features=8, out_features=3, num_layers=2,
            conv='rsage')
  trimmed = RGNN(trim=True, **kw)
  full = RGNN(trim=False, **kw)
  params = trimmed.init(jax.random.key(0), batch)
  out_t = np.asarray(trimmed.apply(params, batch))
  out_f = np.asarray(full.apply(params, batch))
  np.testing.assert_allclose(out_t, out_f, rtol=1e-5, atol=1e-5)


def test_hetero_trim_equivalence_more_layers_than_hops():
  import jax
  import numpy as np
  from fixtures import hetero_ring_dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type

  ds = hetero_ring_dataset(num_users=12, num_items=24)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  loader = NeighborLoader(ds, [2, 2], ('user', np.arange(8)),
                          batch_size=4, shuffle=False, seed=0)
  batch = next(iter(loader))
  kw = dict(edge_types=[reverse_edge_type(u2i), i2i],
            hidden_features=8, out_features=3, num_layers=3,
            conv='rsage')
  params = RGNN(trim=True, **kw).init(jax.random.key(0), batch)
  out_t = np.asarray(RGNN(trim=True, **kw).apply(params, batch))
  out_f = np.asarray(RGNN(trim=False, **kw).apply(params, batch))
  np.testing.assert_allclose(out_t, out_f, rtol=1e-5, atol=1e-5)

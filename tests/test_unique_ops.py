import jax
import jax.numpy as jnp
import numpy as np

from glt_tpu.ops import ordered_unique, init_node, induce_next


def test_ordered_unique_first_occurrence():
  ids = jnp.array([7, 3, 7, 9, 3, 1])
  valid = jnp.ones(6, bool)
  uniq, count, inv = ordered_unique(ids, valid, capacity=8)
  assert int(count) == 4
  np.testing.assert_array_equal(np.asarray(uniq),
                                [7, 3, 9, 1, -1, -1, -1, -1])
  np.testing.assert_array_equal(np.asarray(inv), [0, 1, 0, 2, 1, 3])


def test_ordered_unique_with_invalid():
  ids = jnp.array([5, 5, 2, 8, 2])
  valid = jnp.array([True, False, True, False, True])
  uniq, count, inv = ordered_unique(ids, valid, capacity=4)
  assert int(count) == 2
  np.testing.assert_array_equal(np.asarray(uniq)[:2], [5, 2])
  np.testing.assert_array_equal(np.asarray(inv), [0, -1, 1, -1, 1])


def test_ordered_unique_all_invalid():
  ids = jnp.array([1, 2, 3])
  valid = jnp.zeros(3, bool)
  uniq, count, inv = ordered_unique(ids, valid, capacity=4)
  assert int(count) == 0
  assert np.all(np.asarray(uniq) == -1)
  assert np.all(np.asarray(inv) == -1)


def test_ordered_unique_jit_and_big_random():
  rng = np.random.default_rng(0)
  ids = rng.integers(0, 50, size=257)
  fn = jax.jit(lambda x: ordered_unique(x, jnp.ones(257, bool), 257))
  uniq, count, inv = fn(jnp.asarray(ids))
  # numpy reference: first-occurrence order
  _, first_idx = np.unique(ids, return_index=True)
  expect = ids[np.sort(first_idx)]
  assert int(count) == len(expect)
  np.testing.assert_array_equal(np.asarray(uniq)[:len(expect)], expect)
  # inverse maps back to original values
  np.testing.assert_array_equal(np.asarray(uniq)[np.asarray(inv)], ids)


def test_inducer_init_and_induce():
  # seeds [10, 20, 10] -> labels [0, 1, 0]
  seeds = jnp.array([10, 20, 10])
  state, labels = init_node(seeds, jnp.ones(3, bool), capacity=16)
  np.testing.assert_array_equal(np.asarray(labels), [0, 1, 0])
  assert int(state.count) == 2

  # frontier = [10, 20] (labels 0, 1); nbrs: 10->{20,30}, 20->{30,40}
  nbrs = jnp.array([[20, 30], [30, 40]])
  mask = jnp.ones((2, 2), bool)
  state2, rows, cols, emask = induce_next(
      state, jnp.array([0, 1]), nbrs, mask)
  assert int(state2.count) == 4
  np.testing.assert_array_equal(np.asarray(state2.nodes)[:4],
                                [10, 20, 30, 40])
  np.testing.assert_array_equal(np.asarray(rows), [0, 0, 1, 1])
  np.testing.assert_array_equal(np.asarray(cols), [1, 2, 2, 3])
  assert np.asarray(emask).all()


def test_inducer_label_stability_across_hops():
  # previously-seen nodes keep labels when re-encountered in later hops
  state, _ = init_node(jnp.array([5]), jnp.ones(1, bool), capacity=8)
  state, _, cols1, _ = induce_next(
      state, jnp.array([0]), jnp.array([[6, 7]]), jnp.ones((1, 2), bool))
  # hop 2 from node 6 (label 1) back to 5 and to new node 8
  state, rows2, cols2, _ = induce_next(
      state, jnp.array([1]), jnp.array([[5, 8]]), jnp.ones((1, 2), bool))
  np.testing.assert_array_equal(np.asarray(cols2), [0, 3])  # 5 kept label 0
  np.testing.assert_array_equal(np.asarray(state.nodes)[:4], [5, 6, 7, 8])


def test_inducer_masked_neighbors_ignored():
  state, _ = init_node(jnp.array([1, 2]), jnp.ones(2, bool), capacity=8)
  nbrs = jnp.array([[3, 99], [4, 98]])
  mask = jnp.array([[True, False], [True, False]])
  state2, rows, cols, emask = induce_next(
      state, jnp.array([0, 1]), nbrs, mask)
  assert int(state2.count) == 4
  np.testing.assert_array_equal(np.asarray(state2.nodes)[:4], [1, 2, 3, 4])
  np.testing.assert_array_equal(np.asarray(emask), [True, False, True, False])


def test_stitch_rows_pad_does_not_clobber_row_zero():
  from glt_tpu.ops import stitch_rows
  # partition A serves positions [0, -1(pad)]; B serves [1]
  out = stitch_rows(
      [jnp.array([0, -1]), jnp.array([1])],
      [jnp.array([[42.], [99.]]), jnp.array([[7.]])],
      total=2)
  np.testing.assert_allclose(np.asarray(out), [[42.], [7.]])


def test_dense_inducer_matches_sorted_inducer():
  from glt_tpu.ops.unique import (
      dense_make_tables, dense_init, dense_assign, dense_reset)
  n = 100
  table, scratch = dense_make_tables(n)
  state = dense_init(table, scratch, capacity=16)
  seeds = jnp.array([10, 20, 10, 30])
  state, labels = dense_assign(state, seeds, jnp.ones(4, bool))
  np.testing.assert_array_equal(np.asarray(labels), [0, 1, 0, 2])
  assert int(state.count) == 3
  # second wave: mixes existing (20) and new (40, 50), with invalid slots
  ids = jnp.array([40, 20, 40, 50, 99])
  valid = jnp.array([True, True, True, True, False])
  state, labels = dense_assign(state, ids, valid)
  np.testing.assert_array_equal(np.asarray(labels), [3, 1, 3, 4, -1])
  np.testing.assert_array_equal(np.asarray(state.nodes)[:5],
                                [10, 20, 30, 40, 50])
  # reset clears only touched entries
  table, scratch = dense_reset(state)
  assert int(np.asarray(table).max()) == -1 or np.all(np.asarray(table) == -1)
  assert np.all(np.asarray(scratch) == np.iinfo(np.int32).max)


def test_dense_inducer_reuse_after_reset():
  from glt_tpu.ops.unique import (
      dense_make_tables, dense_init, dense_assign, dense_reset)
  table, scratch = dense_make_tables(50)
  state = dense_init(table, scratch, capacity=8)
  state, _ = dense_assign(state, jnp.array([5, 6]), jnp.ones(2, bool))
  table, scratch = dense_reset(state)
  state2 = dense_init(table, scratch, capacity=8)
  state2, labels = dense_assign(state2, jnp.array([7, 5]), jnp.ones(2, bool))
  np.testing.assert_array_equal(np.asarray(labels), [0, 1])

"""Stream subsystem: delta buffers, snapshot compaction + RCU swap,
delta-aware sampling, cache-coherent serving, and the ingest policy.

The two load-bearing guarantees (ISSUE acceptance):

  * serving across snapshot swaps incurs ZERO steady-state recompiles —
    asserted via the engine forward trace counters, the sampler's
    compiled-program count, and StreamSampler.trace_count;
  * deterministic full-neighborhood sampling over base-CSR + delta
    windows is IDENTICAL to sampling the compacted CSR (insert and
    delete cases), and cache entries for updated nodes are provably
    never served post-update.
"""
import threading
import time

import numpy as np
import pytest

from fixtures import ring_dataset, ring_edges
from glt_tpu.serving import InferenceEngine, ServingMetrics
from glt_tpu.stream import (
    CompactionPolicy, DeltaOverflow, EdgeDeltaBuffer, FeatureDeltaBuffer,
    SnapshotManager, StreamIngestor, StreamSampler,
)

N = 24


def make_manager(num_nodes=N, delta_capacity=64, **kw):
  ds = ring_dataset(num_nodes=num_nodes)
  mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature(),
                        delta_capacity=delta_capacity, **kw)
  return ds, mgr


def canon(out):
  """SamplerOutput -> (node-id set, (parent, child) global-id pair set):
  order-insensitive comparison across engines/snapshot layouts."""
  node = np.asarray(out.node)
  row = np.asarray(out.row)
  col = np.asarray(out.col)
  mask = np.asarray(out.edge_mask)
  pairs = {(int(node[col[i]]), int(node[row[i]]))
           for i in range(mask.size) if mask[i]}
  return set(node[:int(out.node_count)].tolist()), pairs


# -- delta buffers -------------------------------------------------------

def test_edge_delta_staging_and_cancellation():
  buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
  assert buf.insert_edges([1, 2], [3, 4]) == 2
  assert buf.size == 2
  # delete cancels the matching pending insert in place
  buf.delete_edges([1], [3])
  cut = buf.view()
  assert cut.ins_src.tolist() == [2]
  assert cut.del_src.tolist() == [1]
  # a reinsert COEXISTS with the tombstone (tombstone clears the base
  # instances, the insert contributes exactly one fresh one)
  buf.insert_edges([1], [3])
  cut = buf.view()
  assert cut.del_src.tolist() == [1] and 1 in cut.ins_src.tolist()
  assert buf.stats()['total_inserts'] == 3


def test_insert_after_delete_of_nonexistent_edge_survives():
  """Regression: delete of an edge the base never held, then insert of
  the same pair — the insert must survive to the overlay AND the
  compacted CSR (the old staging-time cancellation silently lost it)."""
  ds, mgr = make_manager()
  samp = StreamSampler(mgr, [-1], delta_window=4, seed=0)
  ing = StreamIngestor(mgr, sampler=samp, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=1e9))
  ing.delete_edges([5], [17])   # (5, 17) is not a ring edge
  ing.insert_edges([5], [17])
  _, pairs = canon(samp.sample_from_nodes(np.array([5]), n_valid=1))
  assert (5, 17) in pairs       # visible pre-compaction
  ing.flush()
  t = mgr.current().topo
  seg = np.asarray(t.indices[t.indptr[5]:t.indptr[6]])
  assert (seg == 17).sum() == 1  # exactly one instance post-compaction


def test_delete_then_reinsert_of_base_edge_yields_one_instance():
  ds, mgr = make_manager()
  buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
  buf.delete_edges([3], [4])    # base ring edge
  buf.insert_edges([3], [4])
  snap, _ = mgr.compact(buf.drain())
  t = snap.topo
  seg = np.asarray(t.indices[t.indptr[3]:t.indptr[4]])
  assert (seg == 4).sum() == 1


def test_restage_respects_tombstones_staged_during_compaction():
  """Regression: an insert drained into a failed compaction must NOT
  resurrect past a delete that arrived while the cut was out."""
  buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
  buf.insert_edges([1], [2])
  cut = buf.drain()
  buf.delete_edges([1], [2])    # ordered AFTER the cut's insert
  buf.restage(cut)
  v = buf.view()
  assert 1 not in v.ins_src.tolist()      # insert cancelled
  assert v.del_src.tolist() == [1]        # tombstone preserved


def test_bipartite_bounds_checked_per_axis():
  """Regression: a row-axis-out-of-range endpoint on a non-square
  topology must be rejected at staging, not crash compaction later."""
  from glt_tpu.data import Topology
  # 5 src rows, 20 dst cols
  ei = np.stack([np.arange(5), np.arange(5) + 10])
  topo = Topology(edge_index=ei, layout='CSR', num_rows=5, num_cols=20)
  mgr = SnapshotManager(topo, delta_capacity=8)
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=1e9))
  with pytest.raises(ValueError, match='src endpoint out of range'):
    ing.insert_edges([10], [3])           # 10 >= num_rows=5
  ing.insert_edges([3], [15])             # valid bipartite edge
  info = ing.flush()
  assert info['num_edges'] == 6


def test_overlay_build_memoized_on_mutation_seq():
  ds, mgr = make_manager()
  buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
  buf.insert_edges([1], [5])
  o1 = mgr.build_overlay(buf)
  assert mgr.build_overlay(buf) is o1     # unchanged set: cached
  buf.insert_edges([2], [6])
  o2 = mgr.build_overlay(buf)
  assert o2 is not o1                     # mutation invalidates


def test_edge_delta_overflow_and_watermark():
  buf = EdgeDeltaBuffer(capacity=4, num_nodes=N)
  buf.insert_edges([0, 1, 2], [1, 2, 3])
  assert buf.occupancy == pytest.approx(0.75)
  assert buf.high_watermark == pytest.approx(0.75)
  with pytest.raises(DeltaOverflow):
    buf.insert_edges([4, 5], [6, 7])
  assert buf.size == 3  # rejected batch staged nothing
  with pytest.raises(ValueError, match='out of range'):
    buf.insert_edges([0], [N + 5])


def test_edge_delta_drain_and_restage():
  buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
  buf.insert_edges([1], [2])
  buf.delete_edges([3], [4])
  cut = buf.drain()
  assert buf.size == 0 and cut.num_ops == 2
  buf.insert_edges([5], [6])
  buf.restage(cut)  # failed compaction path: nothing lost
  v = buf.view()
  assert sorted(v.ins_src.tolist()) == [1, 5]
  assert v.del_src.tolist() == [3]


def test_feature_delta_last_write_wins():
  buf = FeatureDeltaBuffer(capacity=8, num_nodes=N)
  buf.update_rows([3], np.ones((1, 4), np.float32))
  buf.update_rows([3], np.full((1, 4), 2.0, np.float32))
  assert buf.size == 1
  cut = buf.drain()
  np.testing.assert_array_equal(cut.values[0], [2, 2, 2, 2])
  # staged rows own their memory
  src = np.zeros((1, 4), np.float32)
  buf.update_rows([5], src)
  src[:] = 9
  np.testing.assert_array_equal(buf.drain().values[0], [0, 0, 0, 0])


# -- compaction parity (acceptance) --------------------------------------

@pytest.mark.parametrize('case', ['insert', 'delete', 'mixed'])
def test_full_neighbor_delta_vs_compacted_parity(case):
  """Deterministic full-neighborhood sampling: base CSR + delta window
  == compacted CSR, for the same seeds."""
  ds, mgr = make_manager()
  samp = StreamSampler(mgr, [-1, -1], delta_window=4, seed=0)
  buf = EdgeDeltaBuffer(capacity=64, num_nodes=N)
  if case in ('insert', 'mixed'):
    buf.insert_edges([0, 0, 5], [7, 9, 17])
  if case in ('delete', 'mixed'):
    buf.delete_edges([0, 6], [1, 7])
  samp.refresh_overlay(buf)
  seeds = np.array([0, 5, 6, 11])
  live = canon(samp.sample_from_nodes(seeds, n_valid=4))

  snap, info = mgr.compact(buf.drain())
  samp.refresh_overlay(buf)  # residual = empty
  compacted = canon(samp.sample_from_nodes(seeds, n_valid=4))
  assert live == compacted

  # cross-check against a cold-built NeighborSampler on the new topo
  from glt_tpu.data import Graph
  from glt_tpu.sampler import NeighborSampler
  ref = NeighborSampler(Graph(snap.topo), [-1, -1], edge_dir='out',
                        full_neighbor_cap=-samp._base_fanouts[0])
  assert canon(ref.sample_from_nodes(seeds, n_valid=4)) == compacted


def test_uniform_hop_respects_tombstones_and_sees_inserts():
  ds, mgr = make_manager()
  samp = StreamSampler(mgr, [2], delta_window=4, seed=0)
  buf = EdgeDeltaBuffer(capacity=64, num_nodes=N)
  buf.delete_edges([0], [1])      # 0 keeps only 0->2 in the base
  buf.insert_edges([0, 0], [9, 11])
  samp.refresh_overlay(buf)
  for trial in range(5):
    _, pairs = canon(samp.sample_from_nodes(np.array([0]), n_valid=1))
    children = {c for p, c in pairs if p == 0}
    assert (0, 1) not in pairs            # tombstone never sampled
    assert {9, 11} <= children            # insert window is full
    assert children <= {2, 9, 11}


def test_multigraph_delete_removes_all_instances():
  ds, mgr = make_manager()
  buf = EdgeDeltaBuffer(capacity=64, num_nodes=N)
  buf.insert_edges([3], [4])  # duplicates the existing base edge 3->4
  snap, _ = mgr.compact(buf.drain())
  dup = snap.topo
  seg = dup.indices[dup.indptr[3]:dup.indptr[4]]
  assert (np.asarray(seg) == 4).sum() == 2
  buf.delete_edges([3], [4])
  snap2, _ = mgr.compact(buf.drain())
  seg = snap2.topo.indices[snap2.topo.indptr[3]:snap2.topo.indptr[4]]
  assert (np.asarray(seg) == 4).sum() == 0


def test_compaction_preserves_edge_ids_and_sort_invariant():
  ds, mgr = make_manager()
  base = mgr.current().topo
  buf = EdgeDeltaBuffer(capacity=64, num_nodes=N)
  buf.insert_edges([2, 8], [10, 1])
  buf.delete_edges([5], [6])
  snap, info = mgr.compact(buf.drain())
  t = snap.topo
  # columns stay ascending within each row (the locality invariant the
  # samplers rely on)
  for v in range(t.num_rows):
    seg = np.asarray(t.indices[t.indptr[v]:t.indptr[v + 1]])
    assert np.all(np.diff(seg) >= 0)
  # surviving base edges keep their original ids; new edges get fresh
  # ids past the old id space
  src, dst, eids = t.to_coo()
  old_src, old_dst, old_eids = base.to_coo()
  old_map = {(int(s), int(d)): int(e)
             for s, d, e in zip(old_src, old_dst, old_eids)}
  fresh = []
  for s, d, e in zip(src, dst, eids):
    key = (int(s), int(d))
    if key in old_map:
      assert int(e) == old_map[key]
    else:
      fresh.append(int(e))
  assert sorted(fresh) == [2 * N, 2 * N + 1]
  assert info['num_edges'] == 2 * N + 1  # +2 inserts, -1 delete


# -- snapshots: RCU + zero recompiles ------------------------------------

def test_rcu_inflight_reader_defers_free():
  ds, mgr = make_manager()
  old = mgr.acquire()
  snap, _ = mgr.compact()
  assert mgr.current() is snap and old is not snap
  assert mgr.num_retired == 1 and not old.freed
  # the in-flight reader still sees intact device arrays
  assert np.asarray(old.arrays['indptr']).shape[0] == N + 1
  mgr.release(old)
  assert mgr.num_retired == 0 and old.freed and old.arrays == {}


def test_sampler_zero_recompiles_across_swaps():
  ds, mgr = make_manager()
  samp = StreamSampler(mgr, [2, 2], delta_window=2, seed=0)
  buf = EdgeDeltaBuffer(capacity=64, num_nodes=N)
  seeds = np.arange(4)
  samp.sample_from_nodes(seeds, n_valid=4)
  traces, fns = samp.trace_count, samp.num_compiled_fns
  for round_ in range(3):
    buf.insert_edges([round_], [round_ + 10])
    samp.refresh_overlay(buf)
    samp.sample_from_nodes(seeds, n_valid=4)
    mgr.compact(buf.drain())
    samp.refresh_overlay(buf)
    samp.sample_from_nodes(seeds, n_valid=4)
  assert samp.trace_count == traces       # no retrace, ever
  assert samp.num_compiled_fns == fns
  assert mgr.current().version == 3


def test_capacity_growth_is_detected_and_counted():
  ds, mgr = make_manager(delta_capacity=8, edge_capacity=2 * N + 4)
  samp = StreamSampler(mgr, [2], delta_window=2, seed=0)
  samp.sample_from_nodes(np.arange(2), n_valid=2)
  t0 = samp.trace_count
  buf = EdgeDeltaBuffer(capacity=8, num_nodes=N)
  buf.insert_edges(np.arange(6), np.full(6, 20))
  snap, info = mgr.compact(buf.drain())
  assert info['capacity_grown'] and mgr.capacity_growths == 1
  samp.sample_from_nodes(np.arange(2), n_valid=2)
  # growth IS the one recompile event, and it is visible
  assert samp.trace_count == t0 + 1


# -- serving integration (acceptance) ------------------------------------

OUT_DIM = 3


@pytest.fixture(scope='module')
def stream_serving():
  import jax

  from glt_tpu.models import GraphSAGE
  ds, mgr = make_manager()
  sampler = StreamSampler(mgr, [-1, -1], delta_window=4, seed=0)
  model = GraphSAGE(hidden_features=8, out_features=OUT_DIM,
                    num_layers=2)
  eng = InferenceEngine(ds, model, None, [-1, -1], buckets=(4,),
                        sampler=sampler)
  eng.init_params(jax.random.key(0))
  eng.warmup()
  ing = StreamIngestor(
      mgr, sampler=sampler, engine=eng,
      policy=CompactionPolicy(occupancy_threshold=2.0,
                              max_staleness_s=0.0))
  return ds, mgr, sampler, eng, ing


def test_serving_zero_recompiles_across_snapshot_swap(stream_serving):
  ds, mgr, sampler, eng, ing = stream_serving
  eng.infer([1, 2, 3])
  warm = eng.compile_stats()
  traces = sampler.trace_count
  ing.insert_edges([1], [9])
  eng.infer([1, 2, 3])              # delta visible pre-compaction
  assert ing.flush() is not None    # >= 1 snapshot swap
  eng.infer([1, 2, 3, 7])
  now = eng.compile_stats()
  assert now['forward_traces'] == warm['forward_traces']
  assert now['sampler_compiled_fns'] == warm['sampler_compiled_fns']
  assert sampler.trace_count == traces
  assert mgr.current().version >= 1


def test_updated_nodes_never_served_stale(stream_serving):
  """THE cache-coherence guarantee: after update_snapshot, entries for
  touched nodes are gone (stale lookups miss) and fresh inference
  reflects the new features."""
  ds, mgr, sampler, eng, ing = stream_serving
  before = eng.infer([5, 6, 13])
  assert 5 in eng.cache.lookup([5], eng.model_version)
  new_row = np.full((1, ds.get_node_feature().feature_dim), 123.0,
                    np.float32)
  ing.update_features([5], new_row)
  info = ing.flush()
  assert 5 in info['touched'].tolist()
  # stale entry provably gone: the lookup misses across ALL versions
  assert eng.cache.lookup([5], eng.model_version) == {}
  after = eng.infer([5, 6, 13])
  assert not np.allclose(before[0], after[0])   # fresh features used
  # node 13's 2-hop neighborhood {13..17} excludes 5: cache-served
  np.testing.assert_allclose(before[2], after[2])


def test_invalidation_expands_to_in_neighbors(stream_serving):
  ds, mgr, sampler, eng, ing = stream_serving
  eng.infer([9, 10, 11])            # 9,10,11 cached; 11 samples 12,13
  snap = mgr.current()
  # feature of 11 changes: nodes 9,10 (in-neighbors via CSC) aggregate
  # it, node 4 does not
  expanded = snap.expand_affected(np.array([11]))
  assert {9, 10, 11} <= set(expanded.tolist())
  eng.infer([4])
  dropped = eng.update_snapshot(snap, touched_ids=[11],
                                expand_in_neighbors=True)
  assert dropped >= 3
  v = eng.model_version
  assert eng.cache.lookup([9], v) == {}
  assert eng.cache.lookup([10], v) == {}
  assert 4 in eng.cache.lookup([4], v)


@pytest.mark.pallas
def test_row_gather_override_survives_snapshot_swap():
  """resolve_row_gather seam, stream path: the engine-level gather
  override rides the gather CALL SITE, so it keeps serving after
  update_snapshot swaps in a freshly-built stream Feature (a
  store-level attribute would be lost with the old store)."""
  import jax

  from glt_tpu.models import GraphSAGE
  from glt_tpu.ops.pallas_kernels import gather_rows
  ds, mgr = make_manager()
  sampler = StreamSampler(mgr, [-1, -1], delta_window=4, seed=0)
  calls = {'n': 0}

  def counting_gather(table, rows):
    calls['n'] += 1
    return gather_rows(table, rows, interpret=True)

  model = GraphSAGE(hidden_features=8, out_features=OUT_DIM,
                    num_layers=2)
  eng = InferenceEngine(ds, model, None, [-1, -1], buckets=(4,),
                        sampler=sampler, cache_capacity=0,
                        row_gather=counting_gather)
  eng.init_params(jax.random.key(0))
  eng.warmup()
  assert calls['n'] > 0
  before = eng.infer([5, 6])
  # mutate node 5's feature and compact: update_snapshot installs the
  # NEW Feature; the override must still serve the gather against it
  buf = FeatureDeltaBuffer(
      capacity=8, num_nodes=N,
      feature_dim=ds.get_node_feature().feature_dim)
  new_row = np.full((1, ds.get_node_feature().feature_dim), 77.0,
                    np.float32)
  buf.update_rows([5], new_row)
  snap, info = mgr.compact(feat_cut=buf.drain())
  eng.update_snapshot(snap, touched_ids=info['touched'])
  n_before = calls['n']
  after = eng.infer([5, 6])
  assert calls['n'] == n_before + 1  # override still serves the gather
  assert not np.allclose(before[0], after[0])   # new feature visible
  assert eng.data.node_features is snap.feature


def test_ingest_gauges_surface_in_serving_metrics(stream_serving):
  ds, mgr, sampler, eng, ing = stream_serving
  metrics = ServingMetrics()
  ing.metrics = metrics
  ing.insert_edges([2], [15])
  ing.flush()
  g = metrics.snapshot()['gauges']
  assert g['snapshot_version'] == mgr.current().version
  assert g['compactions'] == mgr.compactions
  assert g['delta_occupancy'] == 0.0
  assert g['last_compaction_ms'] > 0


# -- ingest policy -------------------------------------------------------

def test_occupancy_policy_triggers_compaction():
  ds, mgr = make_manager(delta_capacity=16)
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=0.5, max_staleness_s=1e9))
  ing.insert_edges([0], [5])
  assert mgr.current().version == 0      # below watermark: staged only
  ing.insert_edges(np.arange(7), np.full(7, 11))
  assert mgr.current().version == 1      # 8/16 >= 0.5 -> compacted
  assert ing.edges.size == 0


def test_staleness_policy_and_background_thread():
  ds, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=0.05))
  ing.update_features([3], np.ones((1, 16), np.float32))
  assert mgr.current().version == 0
  ing.start(poll_interval_s=0.02)
  try:
    deadline = time.monotonic() + 5
    while mgr.current().version == 0 and time.monotonic() < deadline:
      time.sleep(0.02)
    assert mgr.current().version == 1
  finally:
    ing.stop()


def test_concurrent_writers_consistent_totals():
  ds, mgr = make_manager(delta_capacity=4096)
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=0.25, max_staleness_s=1e9))
  errors = []

  def writer(rank):
    rng = np.random.default_rng(rank)
    try:
      for _ in range(50):
        s, d = rng.integers(0, N, 2)
        ing.insert_edges([int(s)], [int(d)])
    except Exception as e:  # pragma: no cover
      errors.append(e)

  threads = [threading.Thread(target=writer, args=(r,))
             for r in range(4)]
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  assert not errors
  ing.flush()
  assert ing.edges.total_inserts == 200
  assert mgr.current().topo.num_edges == 2 * N + 200
  assert mgr.compactions >= 1


# -- distributed apply-delta RPC -----------------------------------------

def test_dist_server_apply_delta_roundtrip():
  from glt_tpu.channel import pack_message
  from glt_tpu.distributed.dist_server import DistServer
  ds = ring_dataset(num_nodes=12)
  srv = DistServer(ds)
  before = srv.get_edge_size()
  out = srv.apply_delta(pack_message({
      'ins': np.array([[0, 1], [6, 7]], np.int64)}))
  assert out['applied']['inserts'] == 2 and not out['compacted']
  assert out['pending'] == 2
  out = srv.apply_delta(pack_message({
      'dels': np.array([[0], [1]], np.int64),
      'feat_ids': np.array([2], np.int64),
      'feat_rows': np.full((1, 16), 42.0, np.float32),
      'compact': np.ones(1, np.int8)}))
  assert out['compacted'] and out['version'] == 1
  assert srv.get_edge_size() == before + 2 - 1
  # the data plane serves the fresh snapshot immediately
  from glt_tpu.channel import unpack_message
  feats = unpack_message(srv.get_node_feature(
      pack_message({'ids': np.array([2], np.int64)})))['feats']
  np.testing.assert_allclose(feats[0], 42.0)


def test_feature_staging_rejects_bad_rows_and_featureless_streams():
  """Wrong-width rows and updates on topology-only streams must fail
  at the STAGING call — deferred to compaction they would restage
  forever and wedge the stream."""
  ds, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=1e9))
  with pytest.raises(ValueError, match='row width'):
    ing.update_features([1, 2], np.ones((2, 7), np.float32))  # D=16
  mgr2 = SnapshotManager(ds.get_graph().topo, None, delta_capacity=8)
  ing2 = StreamIngestor(mgr2, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=1e9))
  with pytest.raises(ValueError, match='no Feature'):
    ing2.update_features([1], np.ones((1, 16), np.float32))
  ing2.insert_edges([1], [2])
  assert ing2.flush()['version'] == 1     # topology-only still works


def test_rejected_delete_leaves_pending_set_untouched():
  """Regression: a delete rejected with DeltaOverflow must not have
  already cancelled matching pending inserts (op not applied == no
  side effects), and the overlay memo must stay valid."""
  ds, mgr = make_manager()
  buf = EdgeDeltaBuffer(capacity=4, num_nodes=N)
  buf.insert_edges([1, 2, 3], [2, 3, 4])
  o1 = mgr.build_overlay(buf)
  with pytest.raises(DeltaOverflow):
    buf.delete_edges([1, 5, 6, 7], [2, 6, 7, 8])  # would overflow
  v = buf.view()
  assert sorted(v.ins_src.tolist()) == [1, 2, 3]  # (1,2) NOT cancelled
  assert v.del_src.size == 0
  assert mgr.build_overlay(buf) is o1             # memo still valid


def test_partitioned_feature_updates_validated_in_global_id_space():
  """Regression: a Feature with an id2index map (partitioned store)
  takes GLOBAL ids; staging must accept owned global ids >= the local
  row count and reject unowned ids that map to no local row."""
  from glt_tpu.data import Feature, Topology
  n_global, n_local = 40, 12
  owned = np.arange(0, n_global, 3)[:n_local]     # global ids owned
  id2index = np.full(n_global, -1, np.int64)
  id2index[owned] = np.arange(n_local)
  feat = Feature(np.zeros((n_local, 4), np.float32), id2index=id2index)
  ei = np.stack([np.arange(8), (np.arange(8) + 1) % 8])
  mgr = SnapshotManager(Topology(edge_index=ei, num_nodes=n_global),
                        feat, delta_capacity=8)
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=1e9))
  big_owned = int(owned[-1])
  assert big_owned >= n_local                     # the interesting case
  ing.update_features([big_owned], np.ones((1, 4), np.float32))
  with pytest.raises(ValueError, match='not owned'):
    ing.update_features([1], np.ones((1, 4), np.float32))  # unowned
  info = ing.flush()
  assert big_owned in info['touched'].tolist()
  got = mgr.current().feature[np.array([big_owned])]
  np.testing.assert_allclose(got[0], 1.0)


def test_flush_restages_edges_when_feature_drain_fails():
  ds, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=1e9))
  ing.insert_edges([1], [9])

  def boom():
    raise RuntimeError('kaput')
  ing.features.drain = boom
  with pytest.raises(RuntimeError, match='kaput'):
    ing.flush()
  assert ing.edges.size == 1              # drained edges restaged
  assert mgr.current().version == 0


def test_dist_server_rebinds_on_auto_compaction():
  """Regression: a compaction auto-triggered by the policy DURING
  staging (not by this call's explicit compact flag) must still rebind
  the served dataset and be reported."""
  from glt_tpu.channel import pack_message
  from glt_tpu.distributed.dist_server import DistServer
  ds = ring_dataset(num_nodes=12)
  srv = DistServer(ds)
  stream = srv._stream_ingestor()
  stream.policy = CompactionPolicy(occupancy_threshold=1e-9,
                                   max_staleness_s=1e9)
  before = srv.get_edge_size()
  out = srv.apply_delta(pack_message({
      'ins': np.array([[0], [6]], np.int64)}))   # no 'compact' flag
  assert out['compacted'] and out['version'] >= 1
  assert srv.get_edge_size() == before + 1       # dataset rebound


def test_dist_server_stream_init_is_single():
  from glt_tpu.distributed.dist_server import DistServer
  srv = DistServer(ring_dataset(num_nodes=12))
  got = []
  threads = [threading.Thread(
      target=lambda: got.append(srv._stream_ingestor()))
      for _ in range(4)]
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  assert all(g is got[0] for g in got)    # one chain, no racing init


def test_dist_apply_delta_over_rpc():
  from glt_tpu.distributed import rpc as rpc_mod
  from glt_tpu.distributed.dist_server import DistServer
  ds = ring_dataset(num_nodes=12)
  srv = DistServer(ds)
  server = rpc_mod.RpcServer(host='127.0.0.1', port=0, auto_start=False)
  server.register('apply_delta', srv.apply_delta)
  server.start()
  try:
    from glt_tpu.channel import pack_message
    cli = rpc_mod.RpcClient(server.host, server.port, timeout=30)
    out = cli.request('apply_delta', pack_message({
        'ins': np.array([[3], [9]], np.int64),
        'compact': np.ones(1, np.int8)}))
    assert out['compacted'] and out['version'] == 1
    cli.close()
  finally:
    server.stop()


# -- background-applier failure surfacing (resilience) -------------------

def test_ingestor_bg_crash_raises_on_next_stage_and_stop():
  """A background-tick crash must not be silent: with
  restart_policy='raise' the first failure kills the applier and the
  error re-raises from the next staging call AND from stop()."""
  _, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=0),
      restart_policy='raise')

  def boom():
    raise RuntimeError('injected tick failure')

  ing.maybe_compact = boom
  ing.start(poll_interval_s=0.02)
  deadline = time.monotonic() + 10
  while ing._bg_error is None and time.monotonic() < deadline:
    time.sleep(0.01)
  assert ing._bg_error is not None
  assert ing.tick_errors_total == 1
  with pytest.raises(RuntimeError, match='background applier died'):
    ing.insert_edges([1], [2])
  with pytest.raises(RuntimeError, match='background applier died'):
    ing.stop()
  ing.stop(raise_background_error=False)  # cleanup path stays usable


def test_ingestor_restart_policy_survives_transient_tick_failures():
  """restart_policy='restart' (default): transient tick failures are
  logged and the applier keeps running; only max_tick_failures
  CONSECUTIVE failures are fatal. A success resets the streak."""
  _, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=0),
      max_tick_failures=3)
  assert ing.restart_policy == 'restart'
  fails = {'left': 2}
  real = ing.maybe_compact

  def flaky_tick():
    if fails['left'] > 0:
      fails['left'] -= 1
      raise RuntimeError('transient')
    return real()

  ing.maybe_compact = flaky_tick
  ing.start(poll_interval_s=0.02)
  deadline = time.monotonic() + 10
  while ing.tick_errors_total < 2 and time.monotonic() < deadline:
    time.sleep(0.01)
  time.sleep(0.1)  # healthy ticks reset the consecutive streak
  assert ing._bg_error is None
  assert ing.insert_edges([1], [2]) == 1  # staging still works
  assert ing.tick_errors_total == 2
  ing.stop()


def test_ingestor_crash_loop_exceeding_budget_is_fatal():
  _, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=0),
      max_tick_failures=3)

  def always_boom():
    raise ValueError('poisoned cut')

  ing.maybe_compact = always_boom
  ing.start(poll_interval_s=0.02)
  deadline = time.monotonic() + 10
  while ing._bg_error is None and time.monotonic() < deadline:
    time.sleep(0.01)
  assert ing.tick_errors_total == 3      # stopped AT the budget
  with pytest.raises(RuntimeError) as ei:
    ing.update_features([0], np.zeros((1, 16), np.float32))
  assert isinstance(ei.value.__cause__, ValueError)
  ing.stop(raise_background_error=False)


def test_ingestor_log_policy_keeps_legacy_swallow_behavior():
  _, mgr = make_manager()
  ing = StreamIngestor(mgr, policy=CompactionPolicy(
      occupancy_threshold=2.0, max_staleness_s=0),
      restart_policy='log')
  def bg_boom():
    # staging calls maybe_compact too — inject only on the applier
    # thread so the stage path exercises the legacy swallow behavior
    if threading.current_thread().name == 'glt-stream-ingest':
      raise RuntimeError('x')

  ing.maybe_compact = bg_boom
  ing.start(poll_interval_s=0.01)
  deadline = time.monotonic() + 10
  while ing.tick_errors_total < 5 and time.monotonic() < deadline:
    time.sleep(0.01)
  assert ing._bg_error is None and ing._thread.is_alive()
  assert ing.insert_edges([1], [2]) == 1
  ing.stop()

"""True multi-process 'multi-host' test: 2 jax.distributed processes,
2 virtual devices each, one global 4-device mesh. Each process loads only
its own partitions from disk and runs the collective sampler — the
reference's multi-node deployment shape, on one machine (SURVEY.md §4's
multi-process simulation strategy applied to the SPMD design)."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from glt_tpu.partition import RandomPartitioner

from fixtures import ring_edges


def _free_port():
  s = socket.socket()
  s.bind(('127.0.0.1', 0))
  port = s.getsockname()[1]
  s.close()
  return port


# the workers hard-code force_backend('cpu') (multihost_worker.py), and
# their jax.distributed mesh ends in process_allgather, which jaxlib
# does not implement for multiprocess CPU: "Multiprocess computations
# aren't implemented on the CPU backend." The skip keys on the WORKERS'
# backend (always cpu as written), not the parent's — keying on the
# parent would both miss the failure on TPU/GPU hosts and initialize
# the parent's backend before the subprocesses spawn.
@pytest.mark.skip(reason='process_allgather is unimplemented on the '
                  'multiprocess CPU backend the workers force; '
                  're-enable when multihost_worker targets real chips')
def test_two_process_distributed_sampling(tmp_path):
  rows, cols, eids = ring_edges(40)
  feats = np.tile(np.arange(40, dtype=np.float32)[:, None], (1, 4))
  efeats = np.tile(np.arange(80, dtype=np.float32)[:, None], (1, 3))
  RandomPartitioner(str(tmp_path), num_parts=4, num_nodes=40,
                    edge_index=np.stack([rows, cols]),
                    node_feat=feats, edge_feat=efeats).partition()
  port = _free_port()
  rpc0, rpc1 = _free_port(), _free_port()
  worker = os.path.join(os.path.dirname(__file__), 'multihost_worker.py')
  env = dict(os.environ)
  env['PYTHONPATH'] = (os.path.dirname(os.path.dirname(worker))
                       + os.pathsep + env.get('PYTHONPATH', ''))
  procs = [subprocess.Popen(
      [sys.executable, worker, str(r), str(tmp_path), str(port),
       str(rpc0), str(rpc1)],
      stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
      text=True) for r in range(2)]
  outs = []
  for p in procs:
    try:
      out, _ = p.communicate(timeout=200)
    except subprocess.TimeoutExpired:
      p.kill()
      out, _ = p.communicate()
    outs.append(out)
  for r, (p, out) in enumerate(zip(procs, outs)):
    assert p.returncode == 0, f'rank {r} failed:\n{out[-3000:]}'
    assert f'RANK{r}_OK' in out

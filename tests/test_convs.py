"""Exact-math unit tests for the conv layers over padded edge lists."""
import jax
import jax.numpy as jnp
import numpy as np

from glt_tpu.models import GCNConv, SAGEConv
from glt_tpu.models.conv import segment_mean


def test_segment_mean_masked():
  msgs = jnp.array([[1.], [3.], [100.], [5.]])
  targets = jnp.array([0, 0, 1, 1])
  mask = jnp.array([True, True, False, True])
  out = np.asarray(segment_mean(msgs, targets, mask, 3))
  np.testing.assert_allclose(out, [[2.], [5.], [0.]])


def test_sage_conv_exact():
  # 3 nodes; edges child->parent: (1->0), (2->0); node features scalar
  x = jnp.array([[1.], [2.], [4.]])
  row = jnp.array([1, 2, 0])
  col = jnp.array([0, 0, 2])
  mask = jnp.array([True, True, False])     # last edge padded out
  conv = SAGEConv(1, use_bias=False)
  params = conv.init(jax.random.key(0), x, row, col, mask)
  w_root = np.asarray(params['params']['lin_root']['kernel'])[0, 0]
  w_nbr = np.asarray(params['params']['lin_nbr']['kernel'])[0, 0]
  out = np.asarray(conv.apply(params, x, row, col, mask))
  # node0: root*1 + nbr*mean(2,4); node1: root*2; node2: root*4
  np.testing.assert_allclose(out[0, 0], w_root * 1 + w_nbr * 3, rtol=1e-5)
  np.testing.assert_allclose(out[1, 0], w_root * 2, rtol=1e-5)
  np.testing.assert_allclose(out[2, 0], w_root * 4, rtol=1e-5)


def test_gcn_conv_shapes_and_mask():
  x = jnp.ones((4, 8))
  row = jnp.array([0, 1, 2, 3])
  col = jnp.array([1, 2, 3, 0])
  mask = jnp.array([True, True, False, False])
  conv = GCNConv(16)
  params = conv.init(jax.random.key(0), x, row, col, mask)
  out = conv.apply(params, x, row, col, mask)
  assert out.shape == (4, 16)
  # masked edges contribute nothing: recompute with only the valid edges
  out2 = conv.apply(params, x, row[:2], col[:2],
                    jnp.array([True, True]))
  np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                             rtol=1e-5)


def test_host_mode_graph_sampling():
  """GraphMode.HOST keeps topology in host memory; the sampler still
  works (arrays embed as constants — the beyond-HBM path uses the mp
  producer instead, this guards the API)."""
  from glt_tpu.data import Dataset
  from glt_tpu.sampler import NeighborSampler
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from fixtures import ring_edges
  rows, cols, _ = ring_edges(20)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=20,
                graph_mode='HOST')
  g = ds.get_graph()
  assert isinstance(g.indptr, np.ndarray)   # stayed on host
  s = NeighborSampler(g, [2], seed=0)
  out = s.sample_from_nodes(np.array([0, 5]))
  nodes = np.asarray(out.node)[:int(out.node_count)]
  assert set(nodes.tolist()) == {0, 5, 1, 2, 6, 7}


def test_gat_conv_multihead():
  x = jnp.asarray(np.random.default_rng(0).normal(size=(6, 8))
                  .astype(np.float32))
  row = jnp.array([1, 2, 3, 4, 5])
  col = jnp.array([0, 0, 0, 1, 1])
  mask = jnp.array([True, True, False, True, True])
  from glt_tpu.models import GATConv
  conv = GATConv(4, heads=3, concat=True)
  params = conv.init(jax.random.key(0), x, row, col, mask)
  out = conv.apply(params, x, row, col, mask)
  assert out.shape == (6, 12)                 # heads * features
  # attention weights per parent sum to 1 over valid incoming edges:
  # masked edge (3->0) contributes nothing — recompute without it
  keep = jnp.array([0, 1, 3, 4])
  out2 = conv.apply(params, x, row[keep], col[keep],
                    jnp.ones(4, bool))
  np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                             rtol=1e-5, atol=1e-6)
  conv_mean = GATConv(4, heads=3, concat=False)
  p2 = conv_mean.init(jax.random.key(1), x, row, col, mask)
  assert conv_mean.apply(p2, x, row, col, mask).shape == (6, 4)


def test_trim_does_not_change_seed_outputs():
  """Static hop-trimming drops only edges that cannot influence seed
  representations: trimmed and untrimmed GraphSAGE agree exactly on the
  seed rows."""
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from fixtures import ring_dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import GraphSAGE
  ds = ring_dataset(num_nodes=40, feat_dim=8)
  loader = NeighborLoader(ds, [2, 2, 2], input_nodes=np.arange(16),
                          batch_size=16, seed=0)
  b = next(iter(loader))
  trimmed = GraphSAGE(hidden_features=16, out_features=4, num_layers=3,
                      trim=True)
  full = GraphSAGE(hidden_features=16, out_features=4, num_layers=3,
                   trim=False)
  params = trimmed.init(jax.random.key(0), b)
  out_t = trimmed.apply(params, b)
  out_f = full.apply(params, b)
  np.testing.assert_allclose(np.asarray(out_t), np.asarray(out_f),
                             rtol=1e-5, atol=1e-6)


def test_trim_equivalence_more_layers_than_hops():
  """num_layers > num_hops: layers must keep every hop they can still
  propagate (regression for the over-trim at layer i > 0)."""
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from fixtures import ring_dataset
  from glt_tpu.loader import NeighborLoader
  from glt_tpu.models import GraphSAGE
  ds = ring_dataset(num_nodes=40, feat_dim=8)
  loader = NeighborLoader(ds, [2, 2], input_nodes=np.arange(8),
                          batch_size=8, seed=0)
  b = next(iter(loader))
  kw = dict(hidden_features=16, out_features=4, num_layers=3)
  params = GraphSAGE(trim=True, **kw).init(jax.random.key(0), b)
  out_t = GraphSAGE(trim=True, **kw).apply(params, b)
  out_f = GraphSAGE(trim=False, **kw).apply(params, b)
  np.testing.assert_allclose(np.asarray(out_t), np.asarray(out_f),
                             rtol=1e-5, atol=1e-6)

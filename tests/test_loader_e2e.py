"""End-to-end slice: NeighborLoader -> Batch -> flax GraphSAGE train step.
The v0 gate from SURVEY.md §7 step 3, on the deterministic fixture."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from glt_tpu.loader import NeighborLoader
from glt_tpu.models import GraphSAGE

from fixtures import ring_dataset, hetero_ring_dataset


@pytest.fixture(scope='module')
def ring():
  return ring_dataset(num_nodes=40, feat_dim=16)


def test_loader_yields_correct_batches(ring):
  loader = NeighborLoader(ring, [2, 2], input_nodes=np.arange(40),
                          batch_size=8, shuffle=False, seed=0)
  assert len(loader) == 5
  batches = list(loader)
  assert len(batches) == 5
  b = batches[0]
  # seeds 0..7 first, features value-encoded: x[i] == node_id
  np.testing.assert_array_equal(np.asarray(b.batch), np.arange(8))
  nc = int(b.node_count)
  nodes = np.asarray(b.node)[:nc]
  np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], nodes)
  np.testing.assert_array_equal(np.asarray(b.y), np.arange(8) % 4)
  assert b.batch_size == 8
  # ring relation on every valid edge
  em = np.asarray(b.edge_mask)
  child = nodes[np.asarray(b.row)[em]]
  parent = nodes[np.asarray(b.col)[em]]
  for p, c in zip(parent, child):
    assert c in ((p + 1) % 40, (p + 2) % 40)


def test_ragged_tail_batch_padded(ring):
  loader = NeighborLoader(ring, [2], input_nodes=np.arange(10),
                          batch_size=8, shuffle=False, seed=0)
  batches = list(loader)
  assert len(batches) == 2
  tail = batches[1]
  assert tail.metadata['n_valid'] == 2
  assert tail.batch_size == 8  # static shape retained


def test_drop_last(ring):
  loader = NeighborLoader(ring, [2], input_nodes=np.arange(10),
                          batch_size=8, drop_last=True, seed=0)
  assert len(list(loader)) == 1


def test_edge_features_collated(ring):
  loader = NeighborLoader(ring, [2], input_nodes=np.arange(8),
                          batch_size=8, with_edge=True, seed=0)
  b = next(iter(loader))
  em = np.asarray(b.edge_mask)
  eids = np.asarray(b.edge)[em]
  # edge features are value-encoded with the eid
  np.testing.assert_allclose(np.asarray(b.edge_attr)[em][:, 0], eids)


def test_split_feature_store_loader(ring=None):
  ds = ring_dataset(num_nodes=40, split_ratio=0.3)
  loader = NeighborLoader(ds, [2], input_nodes=np.arange(40),
                          batch_size=8, seed=0)
  for b in loader:
    nc = int(b.node_count)
    nodes = np.asarray(b.node)[:nc]
    np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], nodes)


def test_all_cold_feature_store_loader():
  # split_ratio=0.0: no device block at all — the whole batch must be
  # served host-side (ADVICE r3: the unconditional device_gather raised
  # on the empty hot block)
  ds = ring_dataset(num_nodes=40, split_ratio=0.0)
  feat = ds.get_node_feature()
  assert feat.hot_count == 0 and not feat.fully_device_resident
  loader = NeighborLoader(ds, [2], input_nodes=np.arange(40),
                          batch_size=8, seed=0)
  for b in loader:
    nc = int(b.node_count)
    nodes = np.asarray(b.node)[:nc]
    np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], nodes)


def test_prefetch_depth_auto_default():
  # LEGACY spilled stores (no offloaded cold block) have a host phase
  # per batch -> overlap by default; fully resident stores — and
  # offloaded spill, tested in test_feature.py — have nothing to hide
  spilled = ring_dataset(num_nodes=40, split_ratio=0.3,
                         host_offload=False)
  resident = ring_dataset(num_nodes=40)
  l_spill = NeighborLoader(spilled, [2], input_nodes=np.arange(8),
                           batch_size=8, seed=0)
  l_res = NeighborLoader(resident, [2], input_nodes=np.arange(8),
                         batch_size=8, seed=0)
  assert l_spill.prefetch_depth == 2
  assert l_res.prefetch_depth == 0
  # explicit value still wins
  l_off = NeighborLoader(spilled, [2], input_nodes=np.arange(8),
                         batch_size=8, seed=0, prefetch_depth=0)
  assert l_off.prefetch_depth == 0
  # spilled loader still yields exact features through the prefetcher
  for b in l_spill:
    nc = int(b.node_count)
    nodes = np.asarray(b.node)[:nc]
    np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], nodes)


def test_training_learns():
  """GraphSAGE learns y = node_id % 4 from one-hot features (solvable by
  memorization through the conv's root path; exercises the full
  loader->batch->model->grad loop)."""
  from glt_tpu.data import Dataset
  from fixtures import ring_edges
  n = 40
  rows, cols, eids = ring_edges(n)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=n)
  ds.init_node_features(np.eye(n, dtype=np.float32))
  ds.init_node_labels(np.arange(n, dtype=np.int32) % 4)
  model = GraphSAGE(hidden_features=32, out_features=4, num_layers=2)
  loader = NeighborLoader(ds, [2, 2], input_nodes=np.arange(40),
                          batch_size=8, shuffle=True, seed=0,
                          rng=np.random.default_rng(0))
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0)
  tx = optax.adam(1e-2)
  opt_state = tx.init(params)

  @jax.jit
  def step(params, opt_state, batch):
    def loss_fn(p):
      logits = model.apply(p, batch)
      mask = jnp.arange(logits.shape[0]) < batch.metadata['n_valid']
      losses = optax.softmax_cross_entropy_with_integer_labels(
          logits, batch.y)
      return jnp.where(mask, losses, 0).sum() / jnp.maximum(mask.sum(), 1)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(grads, opt_state)
    return optax.apply_updates(params, updates), opt_state, loss

  losses = []
  for epoch in range(60):
    for batch in loader:
      meta = dict(batch.metadata)
      meta['n_valid'] = jnp.asarray(meta['n_valid'])
      params, opt_state, loss = step(
          params, opt_state, batch.replace(metadata=meta))
    losses.append(float(loss))
  assert losses[-1] < 0.1, f'did not learn: {losses[::10]}'

  # eval accuracy on all nodes
  correct = total = 0
  for batch in loader:
    logits = model.apply(params, batch)
    nv = batch.metadata['n_valid']
    pred = np.asarray(jnp.argmax(logits, -1))[:nv]
    y = np.asarray(batch.y)[:nv]
    correct += (pred == y).sum()
    total += nv
  assert correct / total > 0.95


def test_hetero_loader(ring=None):
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  loader = NeighborLoader(ds, {u2i: [2, 2], i2i: [2, 2]},
                          input_nodes=('user', np.arange(10)),
                          batch_size=4, seed=0)
  batches = list(loader)
  assert len(batches) == 3
  b = batches[0]
  assert b.input_type == 'user'
  np.testing.assert_array_equal(np.asarray(b.batch), np.arange(4))
  # value-encoded features per type
  for t in ('user', 'item'):
    nc = int(b.node_count_dict[t])
    if nc:
      np.testing.assert_allclose(
          np.asarray(b.x_dict[t])[:nc, 0],
          np.asarray(b.node_dict[t])[:nc])
  assert ('item', 'rev_u2i', 'user') in b.row_dict
  np.testing.assert_array_equal(np.asarray(b.y_dict['user']),
                                np.arange(4) % 3)


def test_prefetching_loader_matches_sync(ring):
  sync = NeighborLoader(ring, [2], input_nodes=np.arange(40),
                        batch_size=8, shuffle=False, seed=0)
  pre = NeighborLoader(ring, [2], input_nodes=np.arange(40),
                       batch_size=8, shuffle=False, seed=0,
                       prefetch_depth=2)
  a = list(sync)
  b = list(pre)
  assert len(a) == len(b) == 5
  for x, y in zip(a, b):
    np.testing.assert_array_equal(np.asarray(x.batch), np.asarray(y.batch))
    assert int(x.node_count) == int(y.node_count)


def test_prefetch_iterator_propagates_errors():
  from glt_tpu.utils.prefetch import prefetch
  def gen():
    yield 1
    raise ValueError('boom')
  it = iter(prefetch(gen(), depth=2))
  assert next(it) == 1
  import pytest as _pytest
  with _pytest.raises(ValueError):
    next(it)


def test_to_pyg_v1_adapter(ring):
  from glt_tpu.loader import to_pyg_v1
  loader = NeighborLoader(ring, [2, 2], input_nodes=np.arange(8),
                          batch_size=8, seed=0)
  b = next(iter(loader))
  bs, n_id, adjs = to_pyg_v1(b)
  assert bs == 8
  assert len(adjs) == 2
  # innermost adj last: its dst count equals the seed count
  edge_index, e_id, (src_n, dst_n) = adjs[-1]
  assert dst_n == 8
  # all labels within n_id bounds; ring relation holds per hop
  for edge_index, e_id, (src_n, dst_n) in adjs:
    assert edge_index.max() < len(n_id)
    child = n_id[edge_index[0]]
    parent = n_id[edge_index[1]]
    for p, c in zip(parent, child):
      assert c in ((p + 1) % 40, (p + 2) % 40)


def test_neighbor_loader_as_pyg_v1_mode(ring):
  # the v1 training-loop idiom must work end to end without
  # torch_geometric: for bs, n_id, adjs in loader, with attribute
  # access on each adj (vendored EdgeIndex namedtuple)
  loader = NeighborLoader(ring, [2, 2], input_nodes=np.arange(8),
                          batch_size=8, as_pyg_v1=True, seed=0)
  bs, n_id, adjs = next(iter(loader))
  assert bs == 8
  assert len(adjs) == 2
  for adj in adjs:
    a = adj.to('anywhere')           # PyG-v1 loops call .to(device)
    assert a.edge_index.shape[0] == 2
    src_count, dst_count = a.size
    assert src_count >= dst_count
    # message-flow: cols index the smaller (dst) frontier
    if a.edge_index.shape[1]:
      assert a.edge_index[1].max() < dst_count
      assert a.edge_index[0].max() < src_count
  # outermost hop first: first adj has the largest src frontier
  assert adjs[0].size[0] >= adjs[1].size[0]

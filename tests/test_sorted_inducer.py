"""Parity: the sort-merge inducer (GLT_DEDUP=sort, TPU fast path) vs the
dense-table inducer. Labels/nodes/batch/counts must match EXACTLY (both
implement the reference inducer's first-occurrence semantics,
inducer.cu:33-133); edge tuples must match as per-hop multisets (the
sorted engine emits them permuted within a hop block)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from glt_tpu.data import Topology
from glt_tpu.ops.pipeline import (edge_hop_offsets, multihop_sample,
                                  sample_budget)
from glt_tpu.ops.sample import sample_neighbors
from glt_tpu.ops.unique import (dense_make_tables, sorted_hop_dedup,
                                sorted_nodes_by_label)


def _run(engine, seeds, n_valid, fanouts, num_nodes, indptr, indices,
         key, monkeypatch, with_edge=False):
  monkeypatch.setenv('GLT_DEDUP', engine)
  one_hop = lambda ids, f, k, m: sample_neighbors(
      indptr, indices, ids, f, k, seed_mask=m,
      edge_ids=jnp.arange(indices.shape[0], dtype=jnp.int32))
  table, scratch = dense_make_tables(num_nodes)
  out, _, _ = multihop_sample(one_hop, seeds, n_valid, fanouts, key,
                              table, scratch, with_edge=with_edge)
  return jax.tree.map(np.asarray, out)


def _edge_multiset(out, batch_size, fanouts, with_edge=False):
  offs = edge_hop_offsets(batch_size, fanouts)
  per_hop = []
  for h in range(len(fanouts)):
    s, e = offs[h], offs[h + 1]
    m = out['edge_mask'][s:e].astype(bool)
    tup = [out['row'][s:e][m], out['col'][s:e][m]]
    if with_edge:
      tup.append(out['edge'][s:e][m])
    per_hop.append(sorted(zip(*[t.tolist() for t in tup])))
  return per_hop


@pytest.mark.parametrize('fanouts', [(2,), (3, 2), (2, 2, 2)])
def test_sorted_engine_matches_table(monkeypatch, fanouts):
  # ring graph: deg 2 everywhere, heavy cross-hop overlap (the hard case
  # for seen-set exclusion)
  n = 24
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n],
                  1).reshape(-1)
  t = Topology(edge_index=np.stack([rows, cols]), num_nodes=n)
  indptr = jnp.asarray(t.indptr.astype(np.int32))
  indices = jnp.asarray(t.indices)
  seeds = jnp.array([5, 0, 5, 17], jnp.int32)       # dup seed included
  nv = jnp.asarray(3)                                # one masked slot
  key = jax.random.key(0)

  a = _run('table', seeds, nv, fanouts, n, indptr, indices, key,
           monkeypatch, with_edge=True)
  b = _run('sort', seeds, nv, fanouts, n, indptr, indices, key,
           monkeypatch, with_edge=True)

  # exact-equality surfaces (fanout >= degree makes sampling exhaustive,
  # so both engines see identical neighbor sets)
  assert int(a['node_count']) == int(b['node_count'])
  assert int(a['seed_count']) == int(b['seed_count'])
  np.testing.assert_array_equal(a['node'], b['node'])
  np.testing.assert_array_equal(a['batch'], b['batch'])
  np.testing.assert_array_equal(a['seed_labels'], b['seed_labels'])
  np.testing.assert_array_equal(a['num_sampled_nodes'],
                                b['num_sampled_nodes'])
  np.testing.assert_array_equal(a['num_sampled_edges'],
                                b['num_sampled_edges'])
  bs = seeds.shape[0]
  assert _edge_multiset(a, bs, fanouts, True) == \
      _edge_multiset(b, bs, fanouts, True)


def test_sorted_engine_random_graph_invariants(monkeypatch):
  rng = np.random.default_rng(3)
  n, e = 500, 4000
  src = rng.integers(0, n, e)
  dst = rng.integers(0, n, e)
  t = Topology(edge_index=np.stack([src, dst]), num_nodes=n)
  indptr = jnp.asarray(t.indptr.astype(np.int32))
  indices = jnp.asarray(t.indices)
  fanouts = (4, 3)
  seeds = jnp.asarray(rng.integers(0, n, 32).astype(np.int32))
  out = _run('sort', seeds, jnp.asarray(32), fanouts, n, indptr,
             indices, jax.random.key(1), monkeypatch)

  count = int(out['node_count'])
  nodes = out['node']
  # node list: unique ids, -1 padded exactly past count
  assert len(set(nodes[:count].tolist())) == count
  assert (nodes[count:] == -1).all()
  # every valid edge references in-range labels; child label's node id is
  # a real neighbor of the parent label's node id
  m = out['edge_mask'].astype(bool)
  row_l = out['row'][m]
  col_l = out['col'][m]
  assert (row_l >= 0).all() and (row_l < count).all()
  assert (col_l >= 0).all() and (col_l < count).all()
  ip = np.asarray(t.indptr)
  ix = np.asarray(t.indices)
  for child, parent in zip(row_l[:200], col_l[:200]):
    p, ch = nodes[parent], nodes[child]
    assert ch in ix[ip[p]:ip[p + 1]]
  # hop-blocked labels: hop h's new nodes occupy one contiguous range
  nsn = out['num_sampled_nodes']
  assert nsn.sum() == count
  # seeds keep the first labels
  sl = out['seed_labels']
  assert (sl >= 0).all() and (sl < int(out['seed_count'])).all()
  np.testing.assert_array_equal(nodes[sl], np.asarray(seeds))


@pytest.mark.parametrize('fanouts', [[2], [2, 2]])
def test_sorted_engine_matches_table_hetero(monkeypatch, fanouts):
  # exhaustive fanouts (deg 2 everywhere) make both engines see the same
  # neighbor sets; labels/nodes/counts must then match exactly, edge
  # tuples as per-hop multisets
  from fixtures import hetero_ring_dataset
  from glt_tpu.sampler import NeighborSampler, NodeSamplerInput
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  seeds = NodeSamplerInput(np.array([3, 7, 3, 9]), 'user')
  key = jax.random.key(5)

  outs = {}
  for engine in ('table', 'sort'):
    monkeypatch.setenv('GLT_DEDUP', engine)
    s = NeighborSampler(ds.graph, {u2i: fanouts, i2i: fanouts},
                        with_edge=True, seed=4)
    outs[engine] = s.sample_from_nodes(seeds, key=key)
  a, b = outs['table'], outs['sort']

  for t in ('user', 'item'):
    assert int(a.node_count[t]) == int(b.node_count[t])
    np.testing.assert_array_equal(np.asarray(a.node[t]),
                                  np.asarray(b.node[t]))
    np.testing.assert_array_equal(np.asarray(a.batch.get(t, [])),
                                  np.asarray(b.batch.get(t, [])))
    np.testing.assert_array_equal(np.asarray(a.num_sampled_nodes[t]),
                                  np.asarray(b.num_sampled_nodes[t]))
  for t in a.metadata['seed_labels']:
    np.testing.assert_array_equal(
        np.asarray(a.metadata['seed_labels'][t]),
        np.asarray(b.metadata['seed_labels'][t]))
  assert set(a.row) == set(b.row)
  for e in a.row:
    np.testing.assert_array_equal(np.asarray(a.num_sampled_edges[e]),
                                  np.asarray(b.num_sampled_edges[e]))
    offs = a.metadata['edge_hop_offsets'][e]
    assert offs == b.metadata['edge_hop_offsets'][e]
    for h in range(len(offs) - 1):
      lo, hi = offs[h], offs[h + 1]
      def hop_tuples(o):
        m = np.asarray(o.edge_mask[e])[lo:hi].astype(bool)
        return sorted(zip(np.asarray(o.row[e])[lo:hi][m].tolist(),
                          np.asarray(o.col[e])[lo:hi][m].tolist(),
                          np.asarray(o.edge[e])[lo:hi][m].tolist()))
      assert hop_tuples(a) == hop_tuples(b)


def test_sorted_hop_dedup_unit():
  # tiny hand-checked case incl. seen-set reuse and duplicates
  u_ids = jnp.array([40, 7], jnp.int32)       # labels 0, 1 already taken
  u_labs = jnp.array([0, 1], jnp.int32)
  ids = jnp.array([9, 7, 9, 3, 40, 9], jnp.int32)
  valid = jnp.array([True, True, True, True, True, False])
  rows = jnp.arange(6, dtype=jnp.int32) * 10
  d = sorted_hop_dedup(u_ids, u_labs, jnp.asarray(2, jnp.int32), ids,
                       valid, rows)
  lab_by_pos = {int(p): int(l) for p, l in zip(d['pos3'], d['labels3'])}
  # first occurrences: 9 -> 2 (slot 0), 3 -> 3 (slot 3); 7 -> 1, 40 -> 0
  assert lab_by_pos[0] == 2 and lab_by_pos[2] == 2 and lab_by_pos[5] == -1
  assert lab_by_pos[1] == 1
  assert lab_by_pos[3] == 3
  assert lab_by_pos[4] == 0
  assert int(d['new_count']) == 2 and int(d['count2']) == 4
  # rows stay aligned with their slots through the permutation
  row_by_pos = {int(p): int(r) for p, r in zip(d['pos3'], d['rows3'])}
  assert all(row_by_pos[p] == p * 10 for p in range(6))
  nodes = sorted_nodes_by_label(d['u_ids2'], d['u_labs2'], d['count2'],
                                6)
  np.testing.assert_array_equal(np.asarray(nodes),
                                [40, 7, 9, 3, -1, -1])


def test_cumsum_i32_exact():
  from glt_tpu.ops.scan import cumsum_i32
  rng = np.random.default_rng(0)
  for m in (7, 512, 513, 70_001):
    x = rng.integers(0, 3, m).astype(np.int32)
    got = np.asarray(jax.jit(cumsum_i32)(jnp.asarray(x)))
    np.testing.assert_array_equal(got, np.cumsum(x))


# -- sorted_hop_dedup_fused vs sorted_hop_dedup: adversarial inputs -----
#
# The fused variant relaxes ONE property (new labels in within-hop VALUE
# order instead of first-occurrence slot order); everything else —
# counts, seen-id labels, the label<->id bijection, exactly-one-head-
# per-new-id — must hold bit-for-bit on the inputs most likely to break
# a single-sort formulation: all-duplicate hops, empty frontiers,
# hub-only frontiers (few distinct ids, massive duplication), and a
# seen set landing EXACTLY on its capacity.

def _dedup_pair(u_ids, u_labs, count, ids, valid):
  from glt_tpu.ops.unique import sorted_hop_dedup_fused
  u_ids = jnp.asarray(u_ids, jnp.int32)
  u_labs = jnp.asarray(u_labs, jnp.int32)
  count = jnp.asarray(count, jnp.int32)
  ids = jnp.asarray(ids, jnp.int32)
  valid = jnp.asarray(valid, bool)
  exact = sorted_hop_dedup(u_ids, u_labs, count, ids, valid)
  fused = sorted_hop_dedup_fused(u_ids, u_labs, count, ids, valid)
  return (jax.tree.map(np.asarray, exact), jax.tree.map(np.asarray, fused))


def _assert_fused_parity(exact, fused, ids, valid, count, budget):
  ids = np.asarray(ids)
  valid = np.asarray(valid)
  m = ids.shape[0]
  assert int(exact['count2']) == int(fused['count2'])
  assert int(exact['new_count']) == int(fused['new_count'])
  # exact path returns per-element arrays permuted; map back via pos3
  exact_slot_labels = np.full((m,), -1, np.int64)
  exact_slot_labels[exact['pos3']] = exact['labels3']
  # seen ids (label < count) keep labels bit-identically; new ids may
  # permute within the hop but must stay a consistent bijection
  seen = valid & (exact_slot_labels >= 0) & (exact_slot_labels < count)
  np.testing.assert_array_equal(exact_slot_labels[seen],
                                fused['labels3'][seen])
  np.testing.assert_array_equal(fused['labels3'][~valid],
                                np.full((~valid).sum(), -1))
  # exactly one head per new id, placed on a slot holding that id
  nh = fused['new_head3']
  assert nh.sum() == int(fused['new_count'])
  head_ids = ids[nh]
  assert len(set(head_ids.tolist())) == len(head_ids)
  # bijection: every valid slot of one id maps to ONE label, ascending
  # label order == ascending id order for the new ids (value order)
  new_pairs = sorted(zip(fused['labels3'][nh].tolist(),
                         head_ids.tolist()))
  assert [p[1] for p in new_pairs] == sorted(head_ids.tolist())
  for lab, _id in new_pairs:
    sel = valid & (ids == _id)
    assert (fused['labels3'][sel] == lab).all()
  # both seen-set forms reconstruct the same dense node list
  na = sorted_nodes_by_label(jnp.asarray(exact['u_ids2']),
                             jnp.asarray(exact['u_labs2']),
                             jnp.asarray(exact['count2']), budget)
  nf = sorted_nodes_by_label(jnp.asarray(fused['u_ids2']),
                             jnp.asarray(fused['u_labs2']),
                             jnp.asarray(fused['count2']), budget)
  cnt = int(exact['count2'])
  assert set(np.asarray(na)[:cnt].tolist()) == \
      set(np.asarray(nf)[:cnt].tolist())
  assert (np.asarray(na)[cnt:] == -1).all()
  assert (np.asarray(nf)[cnt:] == -1).all()


def test_fused_dedup_all_duplicate_hop():
  # every element the SAME fresh id: one new label, one head, the rest
  # resolve to it; a masked copy must not create a second head
  u_ids = np.array([50, 60], np.int32)
  u_labs = np.array([0, 1], np.int32)
  ids = np.full((16,), 7, np.int32)
  valid = np.ones((16,), bool)
  valid[3] = False
  exact, fused = _dedup_pair(u_ids, u_labs, 2, ids, valid)
  _assert_fused_parity(exact, fused, ids, valid, 2, budget=8)
  assert int(fused['new_count']) == 1
  # the head sits on the FIRST valid slot (first-occurrence contract)
  assert fused['new_head3'].argmax() == 0


def test_fused_dedup_all_duplicate_of_seen_id():
  # all-duplicate hop of an id the seen set already holds: zero new
  # labels, zero heads, every valid slot returns the stored label
  u_ids = np.array([7, 9], np.int32)
  u_labs = np.array([0, 1], np.int32)
  ids = np.full((12,), 9, np.int32)
  valid = np.ones((12,), bool)
  exact, fused = _dedup_pair(u_ids, u_labs, 2, ids, valid)
  _assert_fused_parity(exact, fused, ids, valid, 2, budget=4)
  assert int(fused['new_count']) == 0
  assert (fused['labels3'] == 1).all()


def test_fused_dedup_empty_frontier():
  # fully-masked hop (the n_valid=0 batch): nothing changes
  u_ids = np.array([3], np.int32)
  u_labs = np.array([0], np.int32)
  ids = np.array([5, 6, 7, 5], np.int32)
  valid = np.zeros((4,), bool)
  exact, fused = _dedup_pair(u_ids, u_labs, 1, ids, valid)
  _assert_fused_parity(exact, fused, ids, valid, 1, budget=4)
  assert int(fused['new_count']) == 0
  assert (fused['labels3'] == -1).all()
  assert not fused['new_head3'].any()


def test_fused_dedup_hub_frontier():
  # a frontier made entirely of hub expansions: FEW distinct ids, each
  # repeated many times, half already seen — worst case for head
  # detection and run grouping
  rng = np.random.default_rng(0)
  hubs = np.array([100, 200, 300, 400], np.int32)
  u_ids = np.array([100, 200], np.int32)     # two hubs already seen
  u_labs = np.array([0, 1], np.int32)
  ids = rng.choice(hubs, size=64).astype(np.int32)
  valid = rng.random(64) < 0.8
  exact, fused = _dedup_pair(u_ids, u_labs, 2, ids, valid)
  _assert_fused_parity(exact, fused, ids, valid, 2, budget=8)


def test_fused_dedup_capacity_exactly_full():
  # the seen set lands EXACTLY on the node budget: every label in
  # [0, budget) assigned, reconstruction leaves no -1 padding, and the
  # next hop (all-seen) must still resolve every label correctly
  budget = 8
  u_ids = np.array([10, 11, 12], np.int32)
  u_labs = np.array([0, 1, 2], np.int32)
  ids = np.array([20, 21, 22, 23, 24, 20, 21, 24], np.int32)  # 5 new
  valid = np.ones((8,), bool)
  exact, fused = _dedup_pair(u_ids, u_labs, 3, ids, valid)
  _assert_fused_parity(exact, fused, ids, valid, 3, budget=budget)
  assert int(fused['count2']) == budget
  nodes = np.asarray(sorted_nodes_by_label(
      jnp.asarray(fused['u_ids2']), jnp.asarray(fused['u_labs2']),
      jnp.asarray(fused['count2']), budget))
  assert (nodes >= 0).all()
  # follow-up hop over the full table: all seen, labels exact
  exact2, fused2 = _dedup_pair(fused['u_ids2'], fused['u_labs2'],
                               budget, ids, valid)
  _assert_fused_parity(exact2, fused2, ids, valid, budget,
                       budget=budget)
  assert int(fused2['new_count']) == 0

"""Sharded serving fleet: routing, admission, per-shard resilience
ladders, the snapshot consistency token, burn-driven scaling, and the
kill-a-replica chaos acceptance (slow+chaos marked).

Determinism strategy: engines run an identity forward
(``apply_fn=lambda p, b: b.x``) over the value-encoded ring fixture
(feature row i == [i]*dim), so a served row PROVES which feature table
(and therefore which snapshot version) produced it — routing, failover
correctness, and version mixing are all directly assertable on values.
"""
import threading
import time

import numpy as np
import pytest

from fixtures import ring_dataset
from glt_tpu.obs.recorder import FlightRecorder, set_recorder
from glt_tpu.obs.registry import MetricsRegistry
from glt_tpu.obs.trace import get_tracer
from glt_tpu.partition.partition_book import RangePartitionBook
from glt_tpu.serving import (
    AdmissionClass, AdmissionController, FleetOverloaded, FleetRouter,
    FleetShard, FleetUnavailable, InferenceEngine, ScalePolicy,
    ServingServer,
)

FEAT_DIM = 8
FANOUT = [2]
BUCKETS = (8,)


def identity_engine(num_nodes=40, sampler=None, data=None):
  """Engine whose output rows ARE the seed feature rows."""
  ds = data if data is not None else ring_dataset(
      num_nodes=num_nodes, feat_dim=FEAT_DIM)
  return InferenceEngine(ds, None, None, FANOUT, buckets=BUCKETS,
                         apply_fn=lambda p, b: b.x, sampler=sampler)


def local_shard(name, num_nodes=40, replicas=1):
  return FleetShard.local(
      name, [identity_engine(num_nodes) for _ in range(replicas)])


def stream_shard(name, num_nodes=40):
  """2-replica local shard over one SnapshotManager (mutation path)."""
  from glt_tpu.stream import SnapshotManager, StreamSampler
  ds = ring_dataset(num_nodes=num_nodes, feat_dim=FEAT_DIM)
  mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature())
  engines = [
      identity_engine(data=ds, sampler=StreamSampler(mgr, FANOUT,
                                                     seed=0))
      for _ in range(2)]
  return FleetShard.local(name, engines, manager=mgr)


class _DeadEngine:
  """Stands in for a crashed local replica."""

  def infer(self, ids):
    raise ConnectionError('replica crashed')


# -- routing --------------------------------------------------------------

def test_routes_by_partition_book_and_preserves_order():
  r = FleetRouter([local_shard('s0'), local_shard('s1')],
                  RangePartitionBook([20, 40]))
  try:
    ids = np.array([1, 25, 5, 39, 25, 0])  # shard mix + duplicates
    out = r.infer(ids)
    # identity forward: row k must be the feature row of ids[k]
    np.testing.assert_allclose(out[:, 0], ids)
    st = r.stats()['shards']
    assert st['s0']['metrics']['requests'] == 1
    assert st['s1']['metrics']['requests'] == 1
  finally:
    r.close()


def test_rejects_out_of_range_and_negative_ids():
  r = FleetRouter([local_shard('s0')], RangePartitionBook([40]))
  try:
    with pytest.raises(ValueError, match='partition book'):
      r.infer(np.array([1, 40]))
    with pytest.raises(ValueError, match='negative'):
      r.infer(np.array([-1, 3]))
  finally:
    r.close()


def test_shard_count_must_match_partition_book():
  with pytest.raises(ValueError, match='partitions'):
    FleetRouter([local_shard('s0')], RangePartitionBook([20, 40]))


# -- admission ------------------------------------------------------------

def test_admission_rejects_when_class_queue_full():
  reg = MetricsRegistry()
  adm = AdmissionController(
      [AdmissionClass('tiny', max_inflight=1, max_queue=0)],
      registry=reg)
  adm.admit('tiny', time.monotonic() + 1.0)
  with pytest.raises(FleetOverloaded, match='queue full'):
    adm.admit('tiny', time.monotonic() + 1.0)
  assert reg.get('fleet_rejected_total', **{'class': 'tiny'}) == 1
  adm.release('tiny')
  # the slot is back: admission flows again
  adm.admit('tiny', time.monotonic() + 1.0)
  adm.release('tiny')


def test_admission_sheds_on_deadline_before_dispatch():
  reg = MetricsRegistry()
  adm = AdmissionController(
      [AdmissionClass('tiny', max_inflight=1, max_queue=4)],
      registry=reg)
  adm.admit('tiny', time.monotonic() + 5.0)  # occupy the only slot
  t0 = time.monotonic()
  with pytest.raises(FleetOverloaded, match='deadline'):
    adm.admit('tiny', time.monotonic() + 0.15)
  assert 0.1 < time.monotonic() - t0 < 2.0
  assert reg.get('fleet_shed_total', **{'class': 'tiny'}) == 1
  adm.release('tiny')


def test_admission_unknown_class_raises():
  adm = AdmissionController([AdmissionClass('a')])
  with pytest.raises(KeyError, match='unknown admission class'):
    adm.admit('nope', time.monotonic() + 1.0)


# -- per-shard resilience ladder ------------------------------------------

def test_failover_to_second_replica_is_counted():
  shard = local_shard('s0', replicas=2)
  r = FleetRouter([shard], RangePartitionBook([40]))
  try:
    shard.replicas[0].engine = _DeadEngine()
    ids = np.array([3, 9])
    out = r.infer(ids)
    np.testing.assert_allclose(out[:, 0], ids)
    m = r.stats()['shards']['s0']['metrics']
    assert m['failovers'] == 1
    assert shard.health.status('r0') != 'UP'
  finally:
    r.close()


def test_whole_shard_down_serves_stale_then_fails_fast():
  shard = local_shard('s0', replicas=2)
  r = FleetRouter([shard], RangePartitionBook([40]))
  try:
    ids = np.array([3, 9, 21])
    r.infer(ids)  # populates the fleet stale cache
    for rep in shard.replicas:
      rep.engine = _DeadEngine()
    out = r.infer(ids)  # whole chain fails -> stale tier
    np.testing.assert_allclose(out[:, 0], ids)
    st = r.stats()['shards']['s0']['metrics']
    assert st['stale_serves'] == 3
    assert r.registry.get('fleet_unavailable_total', shard='s0') >= 1
    # an id never served stale-misses: zero-filled, counted
    out = r.infer(np.array([15]))
    np.testing.assert_allclose(out, 0.0)
    # once health marks every replica DOWN the shard fails FAST:
    # requests cost a status lookup, not a dial/timeout
    t0 = time.monotonic()
    for _ in range(30):
      r.infer(ids)
    assert time.monotonic() - t0 < 2.0
  finally:
    r.close()


def test_whole_shard_down_without_stale_serve_fails_fast():
  shard = local_shard('s0')
  r = FleetRouter([shard], RangePartitionBook([40]), stale_serve=False)
  try:
    shard.replicas[0].engine = _DeadEngine()
    with pytest.raises(FleetUnavailable):
      r.infer(np.array([3]))
  finally:
    r.close()


def test_breaker_series_are_labeled_per_shard_and_replica():
  """Two shards on ONE registry: their breaker/health series must stay
  distinct (the metrics_name lesson applied to resilience)."""
  s0, s1 = local_shard('s0'), local_shard('s1')
  r = FleetRouter([s0, s1], RangePartitionBook([20, 40]))
  try:
    s0.replicas[0].engine = _DeadEngine()
    for _ in range(4):  # past the breaker threshold (3)
      with pytest.raises(ConnectionError):  # stale tier is empty
        r.infer(np.array([1]))
    reg = r.registry
    assert reg.get('breaker_opens_total', breaker='s0/r0',
                   shard='s0', replica='r0') >= 1
    assert reg.get('breaker_state', breaker='s0/r0', shard='s0',
                   replica='r0') == 2.0  # OPEN
    # shard1 untouched: its series never merged with shard0's
    assert reg.get('breaker_opens_total', breaker='s1/r0',
                   shard='s1', replica='r0') == 0
    assert reg.get('health_status', target='r0', shard='s0') == 2.0
  finally:
    r.close()


# -- consistency token ----------------------------------------------------

def test_apply_delta_advances_token_and_reaches_every_engine():
  s0, s1 = stream_shard('s0'), stream_shard('s1')
  r = FleetRouter([s0, s1], RangePartitionBook([20, 40]))
  try:
    ids = np.arange(0, 40, 5)
    np.testing.assert_allclose(r.infer(ids)[:, 0], ids)
    assert r.consistency_token() == 0
    rows = 1000.0 + np.arange(40, dtype=np.float32)[:, None] \
        * np.ones(FEAT_DIM, np.float32)
    res = r.apply_delta(feat_ids=np.arange(40), feat_rows=rows)
    assert res['fleet_version'] == 1
    assert res['shards']['s0']['version'] == 1
    assert res['shards']['s1']['version'] == 1
    assert r.consistency_token() == 1
    assert r.registry.get('fleet_version') == 1.0
    # EVERY engine of EVERY shard serves the new table (cache swept)
    np.testing.assert_allclose(r.infer(ids)[:, 0], 1000.0 + ids)
    for shard in (s0, s1):
      for rep in shard.replicas:
        assert rep.engine.snapshot_version == 1
  finally:
    r.close()


def test_no_request_spans_mixed_snapshot_versions():
  """The chaos-free half of the tentpole proof: while apply_delta
  propagates fleet-wide, every concurrent response is uniformly OLD or
  uniformly NEW — never shard0@v with shard1@v-1 (the write barrier)."""
  s0, s1 = stream_shard('s0'), stream_shard('s1')
  r = FleetRouter([s0, s1], RangePartitionBook([20, 40]))
  ids = np.array([2, 7, 13, 22, 29, 37])  # spans both shards
  seen, errs = set(), []
  stop = threading.Event()

  def hammer():
    try:
      while not stop.is_set():
        out = r.infer(ids, timeout_ms=5000)
        marks = np.unique(out[:, 0] - ids)  # 1000*v per row
        assert marks.size == 1, \
            f'mixed snapshot versions in one response: {marks}'
        seen.add(int(marks[0]))
    except Exception as e:  # surfaced below; a daemon assert is silent
      errs.append(e)

  threads = [threading.Thread(target=hammer) for _ in range(4)]
  try:
    for t in threads:
      t.start()
    for v in range(1, 4):
      rows = 1000.0 * v + np.arange(40, dtype=np.float32)[:, None] \
          * np.ones(FEAT_DIM, np.float32)
      r.apply_delta(feat_ids=np.arange(40), feat_rows=rows)
      time.sleep(0.05)
  finally:
    stop.set()
    for t in threads:
      t.join(timeout=10)
    r.close()
  assert not errs, errs
  assert r.consistency_token() == 3
  assert 3000 in seen, f'final version never observed: {sorted(seen)}'


# -- burn-driven scaling --------------------------------------------------

def test_fast_burn_emits_scale_up_signal_and_recorder_event():
  rec = FlightRecorder()
  prev = set_recorder(rec)
  # threshold no request can meet -> every request burns budget
  r = FleetRouter([local_shard('s0')], RangePartitionBook([40]),
                  scale_policy=ScalePolicy(threshold_s=1e-7,
                                           min_window=5))
  try:
    for _ in range(8):
      r.infer(np.array([1, 2]))
    out = r.evaluate_scaling()
    assert out['s0']['signal'] == 1
    assert out['s0']['burn'] > 1.0
    assert r.registry.get('fleet_scale_signal', shard='s0') == 1.0
    trips = [e for e in rec.events() if e['kind'] == 'fleet_scale_signal']
    assert trips and trips[0]['shard'] == 's0'
  finally:
    set_recorder(prev)
    r.close()


def test_low_burn_emits_scale_down_and_thin_windows_stay_quiet():
  r = FleetRouter([local_shard('s0')], RangePartitionBook([40]),
                  scale_policy=ScalePolicy(threshold_s=60.0,
                                           min_window=5))
  try:
    r.infer(np.array([1]))
    # window of 1 < min_window: no signal either way
    assert r.evaluate_scaling()['s0']['signal'] == 0
    for _ in range(8):
      r.infer(np.array([1, 2]))
    out = r.evaluate_scaling()  # everything under 60 s: zero burn
    assert out['s0']['signal'] == -1
    assert r.registry.get('fleet_scale_signal', shard='s0') == -1.0
  finally:
    r.close()


# -- tracing --------------------------------------------------------------

def test_one_trace_id_spans_router_and_every_shard():
  r = FleetRouter([local_shard('s0'), local_shard('s1')],
                  RangePartitionBook([20, 40]))
  tracer = get_tracer()
  tracer.enable(sample=1.0)
  try:
    tracer.clear()
    r.infer(np.array([1, 30]))
    evs = tracer.events()
    roots = [e for e in evs if e['name'] == 'fleet.infer']
    assert len(roots) == 1
    tid = roots[0]['args']['trace_id']
    shard_spans = [e for e in evs if e['name'] == 'fleet.shard']
    assert len(shard_spans) == 2
    assert {e['args']['trace_id'] for e in shard_spans} == {tid}
    # the engine-side spans of BOTH shards ride the same trace
    buckets = [e for e in evs if e['name'] == 'serve.bucket'
               and e['args'].get('trace_id') == tid]
    assert len(buckets) >= 2
  finally:
    tracer.disable()
    tracer.clear()
    r.close()


# -- chaos acceptance (CI `chaos` job) ------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_fleet_survives_killing_one_replica_under_load():
  """ISSUE 20 acceptance: a 3-shard x 2-replica fleet under sustained
  load survives killing one replica mid-run with ZERO client-visible
  failures — failovers/stale-serves counted, one trace id spanning
  router -> surviving shard, and a burn-triggered fleet_scale_signal
  in the FlightRecorder."""
  from glt_tpu.resilience.chaos import chaos_seed
  rec = FlightRecorder()
  prev = set_recorder(rec)
  servers = []

  def remote_pair():
    pair = []
    for _ in range(2):
      ds = ring_dataset(num_nodes=60, feat_dim=FEAT_DIM)
      eng = InferenceEngine(ds, None, None, FANOUT, buckets=BUCKETS,
                            apply_fn=lambda p, b: b.x)
      pair.append(ServingServer(eng, max_wait_ms=1.0,
                                request_timeout_ms=5000.0))
    servers.extend(pair)
    return [s.address for s in pair]

  shard0 = FleetShard.remote('shard0', remote_pair())
  shard1 = FleetShard.local(
      'shard1', [identity_engine(60) for _ in range(2)])
  shard2 = FleetShard.local(
      'shard2', [identity_engine(60) for _ in range(2)])
  r = FleetRouter([shard0, shard1, shard2],
                  RangePartitionBook([20, 40, 60]),
                  scale_policy=ScalePolicy(threshold_s=1e-7,
                                           min_window=10))
  rng = np.random.default_rng(chaos_seed(1234))
  worker_seeds = rng.integers(0, 2**31, size=4)
  failures, responses = [], [0]
  count_lock = threading.Lock()
  stop = threading.Event()

  def load(seed):
    wrng = np.random.default_rng(seed)
    while not stop.is_set():
      ids = wrng.integers(0, 60, size=6)
      try:
        out = r.infer(ids, timeout_ms=8000)
        np.testing.assert_allclose(out[:, 0], ids)
      except Exception as e:
        failures.append(e)
        return
      with count_lock:
        responses[0] += 1

  threads = [threading.Thread(target=load, args=(s,))
             for s in worker_seeds]
  tracer = get_tracer()
  try:
    for t in threads:
      t.start()
    time.sleep(1.0)
    servers[0].close()  # kill shard0's primary replica mid-run
    time.sleep(1.5)
    # one traced request after the kill: its single trace id must span
    # the router span AND the surviving remote replica's handler span
    tracer.enable(sample=1.0)
    tracer.clear()
    ids = np.array([3, 9, 15])  # shard0 ids -> surviving replica
    np.testing.assert_allclose(r.infer(ids, timeout_ms=8000)[:, 0], ids)
    evs = tracer.events()
    tracer.disable()
    # the load threads trace roots too (6-id requests): pick OUR root
    # by its distinctive 3-id batch
    roots = [e for e in evs if e['name'] == 'fleet.infer'
             and e['args'].get('ids') == 3]
    assert roots, 'traced request opened no fleet.infer root'
    tid = roots[0]['args']['trace_id']
    server_side = [e for e in evs if e['name'] == 'rpc.server:infer'
                   and e['args'].get('trace_id') == tid]
    assert server_side, 'no surviving-shard handler span on the trace'
    time.sleep(0.5)
  finally:
    stop.set()
    for t in threads:
      t.join(timeout=30)
    scaling = r.evaluate_scaling()
    stats = r.stats()
    r.close()
    for s in servers[1:]:
      s.close()
    set_recorder(prev)
    tracer.clear()

  assert not failures, f'client-visible failures: {failures[:3]}'
  assert responses[0] > 50, f'load too thin: {responses[0]} responses'
  m0 = stats['shards']['shard0']['metrics']
  assert m0['failovers'] > 0, 'the kill never exercised failover?'
  # stale-serves are COUNTED (the surviving replica answered, so the
  # tier may legitimately be 0 — the counter must exist and be sane)
  assert m0['stale_serves'] >= 0
  assert stats['shards']['shard0']['health']['r0'] == 'DOWN'
  # sustained load at a 100 ns threshold: fast burn tripped the
  # recorder with the fleet_scale_signal event
  assert any(s['signal'] == 1 for s in scaling.values())
  trips = [e for e in rec.events() if e['kind'] == 'fleet_scale_signal']
  assert trips, 'fast burn never landed on the flight recorder'


@pytest.mark.chaos
def test_fleet_remote_apply_delta_propagates_to_remote_replicas():
  """Remote mutation path: the router's apply_delta reaches every
  remote replica's stream ingestor (ServingServer stream=) and the
  returned consistency token matches what both replicas now serve."""
  from glt_tpu.stream import SnapshotManager, StreamIngestor, StreamSampler
  servers = []
  for _ in range(2):
    ds = ring_dataset(num_nodes=40, feat_dim=FEAT_DIM)
    mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature())
    eng = identity_engine(data=ds,
                          sampler=StreamSampler(mgr, FANOUT, seed=0))
    ing = StreamIngestor(mgr, sampler=eng.sampler, engine=eng)
    servers.append(ServingServer(eng, max_wait_ms=1.0, stream=ing))
  shard = FleetShard.remote('s0', [s.address for s in servers])
  r = FleetRouter([shard], RangePartitionBook([40]))
  try:
    ids = np.array([4, 11, 30])
    np.testing.assert_allclose(r.infer(ids)[:, 0], ids)
    rows = 500.0 + np.arange(40, dtype=np.float32)[:, None] \
        * np.ones(FEAT_DIM, np.float32)
    res = r.apply_delta(feat_ids=np.arange(40), feat_rows=rows)
    assert res['shards']['s0']['version'] == 1
    assert res['fleet_version'] == 1
    np.testing.assert_allclose(r.infer(ids)[:, 0], 500.0 + ids)
    for s in servers:
      assert s.engine.snapshot_version == 1
  finally:
    r.close()
    for s in servers:
      s.close()

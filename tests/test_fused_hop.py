"""GLT_FUSED_HOP: the single-sort fused sample+assign stage.

The committed TPU trace (benchmarks/tpu_runs/profile_sampler_tpu.json)
showed the per-hop dedup/assign at 41 ms against 15 ms of sampling — the
stage the reference fuses into one CUDA kernel
(csrc/cuda/random_sampler.cu:59-109). The fused engine replaces the two
wide multi-operand sorts of sorted_hop_dedup with one narrow sort plus a
packed scatter; the one observable change is that NEW nodes within a hop
get labels in value order rather than first-occurrence slot order (the
seed hop keeps the exact path). These tests pin:
  * exact parity of every scalar/count surface and of batch/seed_labels
    against BOTH existing engines under exhaustive fanouts,
  * per-hop edge multisets in GLOBAL-ID space (labels map through the
    node list, so value-order labels must describe the same subgraph),
  * random-graph invariants (valid sample, bijection, -1 padding),
  * the hetero loop and the SPMD train step on the virtual mesh.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from glt_tpu.data import Topology
from glt_tpu.ops.pipeline import edge_hop_offsets, multihop_sample
from glt_tpu.ops.sample import sample_neighbors
from glt_tpu.ops.unique import sorted_hop_dedup_fused

from fixtures import ring_edges


@pytest.fixture(scope='module')
def mesh():
  from glt_tpu.parallel import make_mesh
  return make_mesh(8)


def _run(engine, fused, seeds, n_valid, fanouts, num_nodes, indptr,
         indices, key, monkeypatch, with_edge=False):
  from glt_tpu.ops.unique import dense_make_tables
  monkeypatch.setenv('GLT_DEDUP', engine)
  monkeypatch.setenv('GLT_FUSED_HOP', '1' if fused else '0')
  one_hop = lambda ids, f, k, m: sample_neighbors(
      indptr, indices, ids, f, k, seed_mask=m,
      edge_ids=jnp.arange(indices.shape[0], dtype=jnp.int32))
  table, scratch = dense_make_tables(num_nodes)
  out, _, _ = multihop_sample(one_hop, seeds, n_valid, fanouts, key,
                              table, scratch, with_edge=with_edge)
  return jax.tree.map(np.asarray, out)


def _edge_multiset_gid(out, batch_size, fanouts):
  """Per-hop (parent_gid, child_gid, eid) multisets: label-order
  independent."""
  offs = edge_hop_offsets(batch_size, fanouts)
  nodes = out['node']
  per_hop = []
  for h in range(len(fanouts)):
    s, e = offs[h], offs[h + 1]
    m = out['edge_mask'][s:e].astype(bool)
    child = nodes[out['row'][s:e][m]]
    parent = nodes[out['col'][s:e][m]]
    eid = out['edge'][s:e][m]
    per_hop.append(sorted(zip(parent.tolist(), child.tolist(),
                              eid.tolist())))
  return per_hop


@pytest.mark.parametrize('fanouts', [(2,), (3, 2), (2, 2, 2)])
def test_fused_matches_both_engines(monkeypatch, fanouts):
  # ring graph, deg 2: fanouts are exhaustive, so all engines see the
  # same neighbor sets and every count surface must match exactly
  n = 24
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n],
                  1).reshape(-1)
  t = Topology(edge_index=np.stack([rows, cols]), num_nodes=n)
  indptr = jnp.asarray(t.indptr.astype(np.int32))
  indices = jnp.asarray(t.indices)
  seeds = jnp.array([5, 0, 5, 17], jnp.int32)       # dup seed included
  nv = jnp.asarray(3)                                # one masked slot
  key = jax.random.key(0)
  bs = seeds.shape[0]

  f = _run('sort', True, seeds, nv, fanouts, n, indptr, indices, key,
           monkeypatch, with_edge=True)
  for engine in ('table', 'sort'):
    a = _run(engine, False, seeds, nv, fanouts, n, indptr, indices,
             key, monkeypatch, with_edge=True)
    assert int(a['node_count']) == int(f['node_count'])
    assert int(a['seed_count']) == int(f['seed_count'])
    sc = int(f['seed_count'])
    # seed hop stays on the exact path: labels and batch prefix are
    # bit-identical; past seed_count the node list is value-ordered
    # within each hop, so compare as sets there
    np.testing.assert_array_equal(a['batch'][:sc], f['batch'][:sc])
    np.testing.assert_array_equal(a['seed_labels'], f['seed_labels'])
    np.testing.assert_array_equal(a['num_sampled_nodes'],
                                  f['num_sampled_nodes'])
    np.testing.assert_array_equal(a['num_sampled_edges'],
                                  f['num_sampled_edges'])
    cnt = int(f['node_count'])
    assert set(a['node'][:cnt].tolist()) == set(f['node'][:cnt].tolist())
    assert (f['node'][cnt:] == -1).all()
    assert _edge_multiset_gid(a, bs, fanouts) == \
        _edge_multiset_gid(f, bs, fanouts)


def test_fused_random_graph_invariants(monkeypatch):
  # non-exhaustive fanouts: the fused draw differs from the unfused one
  # (frontier lane order feeds the RNG) but must still be a VALID sample
  rng = np.random.default_rng(3)
  n, e = 500, 4000
  src = rng.integers(0, n, e)
  dst = rng.integers(0, n, e)
  t = Topology(edge_index=np.stack([src, dst]), num_nodes=n)
  indptr = jnp.asarray(t.indptr.astype(np.int32))
  indices = jnp.asarray(t.indices)
  fanouts = (4, 3)
  seeds = jnp.asarray(rng.integers(0, n, 32).astype(np.int32))
  out = _run('sort', True, seeds, jnp.asarray(32), fanouts, n, indptr,
             indices, jax.random.key(1), monkeypatch)

  count = int(out['node_count'])
  nodes = out['node']
  assert len(set(nodes[:count].tolist())) == count
  assert (nodes[count:] == -1).all()
  m = out['edge_mask'].astype(bool)
  row_l = out['row'][m]
  col_l = out['col'][m]
  assert (row_l >= 0).all() and (row_l < count).all()
  assert (col_l >= 0).all() and (col_l < count).all()
  ip = np.asarray(t.indptr)
  ix = np.asarray(t.indices)
  for child, parent in zip(row_l[:200], col_l[:200]):
    p, ch = nodes[parent], nodes[child]
    assert ch in ix[ip[p]:ip[p + 1]]
  assert out['num_sampled_nodes'].sum() == count
  sl = out['seed_labels']
  assert (sl >= 0).all() and (sl < int(out['seed_count'])).all()
  np.testing.assert_array_equal(nodes[sl], np.asarray(seeds))


def test_fused_hop_dedup_unit():
  # hand-checked: seen ids keep labels; NEW ids rank in VALUE order
  # (3 < 9 -> 3 gets label 2, 9 gets label 3); invalid slots -> -1
  u_ids = jnp.array([40, 7], jnp.int32)
  u_labs = jnp.array([0, 1], jnp.int32)
  ids = jnp.array([9, 7, 9, 3, 40, 9], jnp.int32)
  valid = jnp.array([True, True, True, True, True, False])
  d = sorted_hop_dedup_fused(u_ids, u_labs, jnp.asarray(2, jnp.int32),
                             ids, valid)
  labels = np.asarray(d['labels3'])
  np.testing.assert_array_equal(labels, [3, 1, 3, 2, 0, -1])
  assert int(d['new_count']) == 2 and int(d['count2']) == 4
  # exactly one new-head per new id, at a slot holding that id
  nh = np.asarray(d['new_head3'])
  assert nh.sum() == 2
  assert sorted(np.asarray(ids)[nh].tolist()) == [3, 9]
  # append-form seen-set reconstructs the dense node list
  from glt_tpu.ops.unique import sorted_nodes_by_label
  nodes = sorted_nodes_by_label(d['u_ids2'], d['u_labs2'], d['count2'],
                                6)
  np.testing.assert_array_equal(np.asarray(nodes),
                                [40, 7, 3, 9, -1, -1])


@pytest.mark.parametrize('fanouts', [[2], [2, 2]])
def test_fused_hetero_matches_table(monkeypatch, fanouts):
  from fixtures import hetero_ring_dataset
  from glt_tpu.sampler import NeighborSampler, NodeSamplerInput
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  seeds = NodeSamplerInput(np.array([3, 7, 3, 9]), 'user')
  key = jax.random.key(5)

  outs = {}
  for engine, fused in (('table', False), ('sort', True)):
    monkeypatch.setenv('GLT_DEDUP', engine)
    monkeypatch.setenv('GLT_FUSED_HOP', '1' if fused else '0')
    s = NeighborSampler(ds.graph, {u2i: fanouts, i2i: fanouts},
                        with_edge=True, seed=4)
    outs[engine] = s.sample_from_nodes(seeds, key=key)
  a, f = outs['table'], outs['sort']

  for t in ('user', 'item'):
    cnt = int(a.node_count[t])
    assert cnt == int(f.node_count[t])
    na, nf = np.asarray(a.node[t]), np.asarray(f.node[t])
    assert set(na[:cnt].tolist()) == set(nf[:cnt].tolist())
    assert (nf[cnt:] == -1).all()
    np.testing.assert_array_equal(np.asarray(a.num_sampled_nodes[t]),
                                  np.asarray(f.num_sampled_nodes[t]))
  for t in a.metadata['seed_labels']:
    np.testing.assert_array_equal(
        np.asarray(a.metadata['seed_labels'][t]),
        np.asarray(f.metadata['seed_labels'][t]))
  assert set(a.row) == set(f.row)
  for e in a.row:
    np.testing.assert_array_equal(np.asarray(a.num_sampled_edges[e]),
                                  np.asarray(f.num_sampled_edges[e]))
    offs = a.metadata['edge_hop_offsets'][e]
    assert offs == f.metadata['edge_hop_offsets'][e]
    col_t = e[2]
    for h in range(len(offs) - 1):
      lo, hi = offs[h], offs[h + 1]
      def hop_gid_tuples(o, row_t_nodes, col_t_nodes):
        m = np.asarray(o.edge_mask[e])[lo:hi].astype(bool)
        parent = row_t_nodes[np.asarray(o.row[e])[lo:hi][m]]
        child = col_t_nodes[np.asarray(o.col[e])[lo:hi][m]]
        eid = np.asarray(o.edge[e])[lo:hi][m]
        return sorted(zip(parent.tolist(), child.tolist(),
                          eid.tolist()))
      # row buffer holds PARENT labels (expand-from type), col holds
      # CHILD labels (neighbor type) in traversal orientation
      row_t = e[0]
      assert hop_gid_tuples(a, np.asarray(a.node[row_t]),
                            np.asarray(a.node[col_t])) == \
          hop_gid_tuples(f, np.asarray(f.node[row_t]),
                         np.asarray(f.node[col_t]))


def test_fused_spmd_train_step_learns(monkeypatch, mesh):
  # the fused assign inside the full SPMD training step on the 8-device
  # virtual mesh: compiles, runs, learns (VERDICT r4 next #2's
  # virtual-mesh validation)
  import optax
  from glt_tpu.data import Dataset
  from glt_tpu.models import GraphSAGE
  from glt_tpu.parallel import ShardedFeature, SPMDSageTrainStep
  monkeypatch.setenv('GLT_DEDUP', 'sort')
  monkeypatch.setenv('GLT_FUSED_HOP', '1')
  n = 40
  rows, cols, _ = ring_edges(n)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=n)
  model = GraphSAGE(hidden_features=16, out_features=4, num_layers=2)
  tx = optax.adam(1e-2)
  sf = ShardedFeature(np.eye(n, dtype=np.float32), mesh)
  step = SPMDSageTrainStep(mesh, model, tx, ds.get_graph(), sf,
                           (np.arange(n) % 4).astype(np.int32),
                           fanouts=[2, 2], batch_size_per_device=4)
  params = step.init_params(jax.random.key(0))
  opt_state = jax.device_put(
      tx.init(params),
      jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()))
  rng = np.random.default_rng(0)
  losses = []
  for it in range(60):
    seeds = rng.permutation(n)[:32]
    keys = jax.random.split(jax.random.key(it), 8)
    params, opt_state, loss = step(
        params, opt_state, seeds, np.full(8, 4), keys)
    losses.append(float(np.asarray(loss)[0]))
  assert losses[-1] < 0.25, f'did not learn: {losses[::10]}'

"""GLT002 true negatives: every access path is actually safe."""
import threading


class LockedCounter:
  def __init__(self):
    self._lock = threading.Lock()
    self.hits = 0     # __init__ happens-before any thread

  def record(self, n):
    with self._lock:
      self.hits += n

  def hit_rate(self):
    with self._lock:                  # guarded read
      return self.hits

  def bulk(self, items):
    with self._lock:
      for n in items:
        self._record_locked(n)        # helper only ever called under
                                      # the lock -> assumed-locked

  def _record_locked(self, n):
    self.hits += n

  def manual(self):
    self._lock.acquire()              # hand-rolled protocol: exempt
    try:
      self.hits += 1
    finally:
      self._lock.release()


class ClosureUnderLock:
  """A def INSIDE the guarded block runs later, without the lock: its
  store must not count as guarded (no false lock-ownership of _count,
  so the bare read() stays clean)."""

  def __init__(self):
    self._lock = threading.Lock()
    self._count = 0
    self._cb = None

  def start(self):
    with self._lock:
      def cb():
        self._count += 1    # deferred, lockless — NOT a guarded store
      self._cb = cb

  def read(self):
    return self._count


class NoLockNoFindings:
  """No lock in the class at all: attribute access is out of scope."""

  def __init__(self):
    self.count = 0

  def bump(self):
    self.count += 1

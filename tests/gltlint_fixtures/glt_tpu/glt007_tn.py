"""GLT007 true negatives: cataloged names and non-literal names."""
from glt_tpu.utils.env import knob


def read_knob():
  return knob('GLT_DOCUMENTED_KNOB', 1)


def register(registry, dynamic_name):
  registry.counter('documented_metric_total').inc()
  registry.counter(dynamic_name).inc()    # runtime name: out of scope
  options = {'not_a_metric': 1}           # plain dict key, no registry
  return options

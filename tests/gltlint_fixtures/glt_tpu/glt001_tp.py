"""GLT001 true positives: every flavor of raw environ read."""
import os
from os import environ, getenv


def numeric_parse():
  return int(os.environ.get('GLT_FIXTURE_KNOB', '8'))


def subscript_read():
  return os.environ['GLT_FIXTURE_KNOB']


def via_getenv():
  return getenv('GLT_FIXTURE_KNOB')


def via_imported_environ():
  return environ.get('GLT_FIXTURE_KNOB')

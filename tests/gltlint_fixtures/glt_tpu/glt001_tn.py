"""GLT001 true negatives: knob()/raw() reads and environ WRITES."""
import os

from glt_tpu.utils.env import knob, raw


def through_knob():
  return knob('GLT_FIXTURE_KNOB', 8)


def through_raw():
  return raw('XLA_FLAGS', '')


def writes_are_legal():
  os.environ.setdefault('XLA_FLAGS', '')
  os.environ['GLT_FIXTURE_CHILD'] = '1'


def suppressed_read():
  return os.environ.get('GLT_FIXTURE_KNOB')  # gltlint: disable=GLT001

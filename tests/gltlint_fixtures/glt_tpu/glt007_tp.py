"""GLT007 true positives: undocumented knob + metric."""
from glt_tpu.utils.env import knob


def read_knob():
  return knob('GLT_UNDOCUMENTED_KNOB', 1)


def read_substring_knob():
  # a PREFIX of the documented GLT_DOCUMENTED_KNOB: substring luck
  # must not count as documentation
  return knob('GLT_DOCUMENTED', 1)


def register(registry):
  registry.counter('metric_missing_from_docs_total').inc()
  registry.gauge('gauge_missing_from_docs').set(1.0)
  registry.counter('documented_metric').inc()   # prefix of _total row

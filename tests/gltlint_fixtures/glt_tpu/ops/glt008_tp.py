"""GLT008 true positives: 64-bit planes in an ops/ hot path."""
import jax.numpy as jnp
import numpy as np


def widen_indices(idx):
  wide = idx.astype(jnp.int64)          # attribute form
  host = np.zeros(8, dtype=np.float64)  # np attribute form
  named = idx.astype('int64')           # string dtype form
  return wide, host, named

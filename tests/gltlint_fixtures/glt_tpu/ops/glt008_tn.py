"""GLT008 true negatives: narrow planes, and a justified widening."""
import jax.numpy as jnp
import numpy as np


def narrow_indices(idx):
  slots = idx.astype(jnp.int32)
  feats = np.zeros(8, dtype=np.float32)
  picks = idx.astype('int32')
  return slots, feats, picks


def justified_widening(idx):
  # host-side accumulation across the whole epoch genuinely needs i64
  return idx.astype(np.int64)  # gltlint: disable=GLT008

"""GLT006 true positives: silent swallows inside thread targets."""
import threading


class Worker:
  def start(self):
    self._t = threading.Thread(target=self._loop, daemon=True)
    self._t.start()

  def _loop(self):
    while True:
      try:
        self._tick()
      except Exception:
        pass                          # invisible until the stall

  def _tick(self):
    raise NotImplementedError


def submitted(pool):
  def job():
    try:
      risky()
    except ValueError:
      pass                            # swallowed in an executor job
  pool.submit(job)


def risky():
  raise ValueError

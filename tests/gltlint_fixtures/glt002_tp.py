"""GLT002 true positives: lock-owned attrs touched bare."""
import threading


class TornCounter:
  """hits is written under the lock, then read AND written bare."""

  def __init__(self):
    self._lock = threading.Lock()
    self.hits = 0
    self.total = 0

  def record(self, n):
    with self._lock:
      self.hits += n
      self.total += n

  def hit_rate(self):
    return self.hits / max(self.total, 1)    # bare read: finding x2

  def reset(self):
    self.hits = 0                            # bare write: finding

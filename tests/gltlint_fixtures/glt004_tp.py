"""GLT004 true positives: jitted closures over instance/module arrays."""
import functools

import jax
import jax.numpy as jnp

TABLE = jnp.arange(1024)              # module-level array


class Sampler:
  def build(self):
    @jax.jit
    def fn(seeds):
      rows = TABLE[seeds]             # closure over the module array
      return rows * self.weights      # closure over instance state
    return fn

  def build_partial(self):
    @functools.partial(jax.jit, static_argnums=0)
    def fn(k, seeds):
      return seeds + self.offsets     # @partial(jax.jit, ...) form
    return fn

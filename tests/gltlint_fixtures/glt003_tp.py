"""GLT003 true positives: instance mutation inside jitted callees."""
import jax
import jax.numpy as jnp


class Staging:
  def build(self):
    @jax.jit
    def fwd(x):
      self.window = jnp.cumsum(x)     # rebinds live state to a tracer
      self.cache[0] = x               # subscript store on self state
      return x * 2
    return fwd

  def wrap_site(self):
    def inner(x):
      self.latest = x                 # found via jit(inner) below
      return x + 1
    return jax.jit(inner)

"""GLT005 true positives: unguarded Future resolution."""


def resolve(fut, value):
  fut.set_result(value)               # no done() guard, no try


def fail(req, err):
  req.future.set_exception(err)       # dotted receiver, same class


def conditional_but_wrong(fut, value, ready):
  if ready:                           # an if, but not a done-race test
    fut.set_result(value)


def resolve_from_handler(fut, work):
  try:
    work()
  except Exception as e:
    fut.set_exception(e)              # the handler is NOT guarded by
                                      # its own try: the watchdog race

"""GLT006 true negatives: handlers that surface, plus non-thread code."""
import logging
import queue
import threading

logger = logging.getLogger(__name__)


class Worker:
  def start(self):
    self._t = threading.Thread(target=self._loop, daemon=True)
    self._t.start()

  def _loop(self):
    while True:
      try:
        self._tick()
      except queue.Empty:
        continue                      # expected sentinel: control flow
      except Exception as e:
        self._last_error = e          # recorded to state
        logger.exception('tick failed')

  def _tick(self):
    raise NotImplementedError


def not_a_thread_target():
  try:
    risky()
  except Exception:
    pass                              # sync caller sees the fallout


def risky():
  raise ValueError

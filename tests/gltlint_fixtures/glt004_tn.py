"""GLT004 true negatives: arrays ride jit ARGUMENTS (the StreamSampler
contract), and jitted METHODS (self is a parameter) are out of scope."""
import jax
import jax.numpy as jnp

TABLE = jnp.arange(1024)


class Sampler:
  def build(self):
    @jax.jit
    def fn(seeds, table, weights):
      return table[seeds] * weights   # everything is an argument
    return fn

  def run(self, seeds):
    return self.build()(seeds, TABLE, jnp.ones(1024))

  def method_form(self, seeds):
    # jitting a bound method: self is a (pytree) parameter, not a free
    # closure — the recompile story is the instance hash, not a leak
    return jax.jit(self._fwd)(seeds)

  def _fwd(self, seeds):
    return seeds * 2

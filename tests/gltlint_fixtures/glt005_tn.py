"""GLT005 true negatives: every sanctioned done-race guard."""
from concurrent.futures import InvalidStateError


def guard_by_done(fut, value):
  if not fut.done():
    fut.set_result(value)


def guard_by_try(fut, err):
  try:
    if not fut.done():
      fut.set_exception(err)
  except InvalidStateError:
    pass  # the other thread resolved it first: that outcome stands


def guard_by_handshake(fut, value):
  if fut.set_running_or_notify_cancel():
    fut.set_result(value)


def guard_by_cancelled(fut, value):
  if not fut.cancelled():
    fut.set_result(value)

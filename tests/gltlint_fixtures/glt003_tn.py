"""GLT003 true negatives: staged correctly or not traced at all."""
import jax
import jax.numpy as jnp


class Staging:
  def build(self):
    @jax.jit
    def fwd(x):
      return x * 2                    # pure: nothing to flag
    return fwd

  def staged(self):
    @jax.jit
    def fwd(x):
      with jax.ensure_compile_time_eval():
        self.window = jnp.arange(4)   # sanctioned compile-time staging
      return x
    return fwd

  def untraced_mutation(self, x):
    self.window = jnp.cumsum(x)       # plain method, never jitted
    return self.window

"""Partitioning round-trip tests (reference test_partition.py pattern:
save/load + PB correctness for random & frequency partitioners)."""
import numpy as np
import pytest

from glt_tpu.partition import (
    FrequencyPartitioner, RandomPartitioner, RangePartitionBook,
    TablePartitionBook, cat_feature_cache, load_meta, load_partition,
)

from fixtures import ring_edges


def test_range_partition_book():
  pb = RangePartitionBook([10, 20, 40])
  np.testing.assert_array_equal(pb[np.array([0, 9, 10, 19, 20, 39])],
                                [0, 0, 1, 1, 2, 2])
  np.testing.assert_array_equal(pb.id2index(np.array([0, 9, 10, 25])),
                                [0, 9, 0, 5])
  assert pb.num_partitions == 3


def test_table_partition_book():
  pb = TablePartitionBook(np.array([0, 1, 1, 0]))
  np.testing.assert_array_equal(pb[np.array([1, 3])], [1, 0])
  assert pb.num_partitions == 2


def _make_inputs(n=40, feat_dim=4):
  rows, cols, eids = ring_edges(n)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, feat_dim))
  efeats = np.tile(np.arange(2 * n, dtype=np.float32)[:, None], (1, 2))
  return np.stack([rows, cols]), feats, efeats


def test_random_partitioner_roundtrip(tmp_path):
  ei, feats, efeats = _make_inputs()
  p = RandomPartitioner(str(tmp_path), num_parts=2, num_nodes=40,
                        edge_index=ei, node_feat=feats, edge_feat=efeats,
                        edge_assign_strategy='by_src')
  p.partition()
  meta = load_meta(str(tmp_path))
  assert meta['num_parts'] == 2 and meta['data_cls'] == 'homo'

  seen_nodes, seen_edges = [], []
  for part in range(2):
    _, graph, nfeat, efeat, node_pb, edge_pb = load_partition(
        str(tmp_path), part)
    # every edge's src is owned by this partition (by_src)
    np.testing.assert_array_equal(node_pb[graph.edge_index[0]], part)
    np.testing.assert_array_equal(edge_pb[graph.eids], part)
    # features are value-encoded: row for id i has value i
    np.testing.assert_allclose(nfeat.feats[:, 0], nfeat.ids)
    np.testing.assert_allclose(efeat.feats[:, 0], efeat.ids)
    seen_nodes.append(nfeat.ids)
    seen_edges.append(graph.eids)
  np.testing.assert_array_equal(np.sort(np.concatenate(seen_nodes)),
                                np.arange(40))
  np.testing.assert_array_equal(np.sort(np.concatenate(seen_edges)),
                                np.arange(80))


def test_frequency_partitioner_with_cache(tmp_path):
  ei, feats, _ = _make_inputs()
  # partition 0 is hot on nodes 0..19, partition 1 on 20..39
  probs = np.zeros((2, 40), np.float32)
  probs[0, :20] = 1.0
  probs[1, 20:] = 1.0
  # both partitions also want node 0 and 20 a bit (cache candidates)
  probs[1, 0] = 0.5
  probs[0, 20] = 0.5
  p = FrequencyPartitioner(str(tmp_path), num_parts=2, num_nodes=40,
                           edge_index=ei, node_feat=feats,
                           probs=probs, cache_ratio=0.1)
  p.partition()
  _, graph0, nfeat0, _, node_pb, _ = load_partition(str(tmp_path), 0)
  # hot nodes landed where they're hottest
  assert set(np.nonzero(node_pb.table == 0)[0]) == set(range(20))
  # partition 0 cached node 20 (hot remote row)
  _, _, nf0, _, _, _ = load_partition(str(tmp_path), 0)
  assert nf0.cache_ids is not None and 20 in nf0.cache_ids
  _, _, nf1, _, _, _ = load_partition(str(tmp_path), 1)
  assert 0 in nf1.cache_ids


def test_cat_feature_cache_rewrites_pb(tmp_path):
  ei, feats, _ = _make_inputs()
  probs = np.zeros((2, 40), np.float32)
  probs[0, :20] = 1.0
  probs[1, 20:] = 1.0
  probs[0, 20] = 0.5
  p = FrequencyPartitioner(str(tmp_path), num_parts=2, num_nodes=40,
                           edge_index=ei, node_feat=feats,
                           probs=probs, cache_ratio=0.05)
  p.partition()
  _, _, nfeat, _, node_pb, _ = load_partition(str(tmp_path), 0)
  feats_cat, ids, id2index, new_pb = cat_feature_cache(0, nfeat, node_pb)
  # cached remote id 20 now resolves to partition 0
  assert new_pb[np.array([20])][0] == 0
  # id2index maps every held id to its row
  for gid in ids:
    np.testing.assert_allclose(feats_cat[id2index[gid]][0], gid)


def test_hetero_partition_roundtrip(tmp_path):
  u2i = ('user', 'u2i', 'item')
  ei = {u2i: np.array([[0, 1, 2, 3], [9, 5, 7, 1]])}
  nfeat = {'user': np.arange(4, dtype=np.float32)[:, None],
           'item': np.arange(10, dtype=np.float32)[:, None]}
  p = RandomPartitioner(str(tmp_path), num_parts=2,
                        num_nodes={'user': 4, 'item': 10},
                        edge_index=ei, node_feat=nfeat)
  p.partition()
  meta, graph, nf, ef, node_pb, edge_pb = load_partition(str(tmp_path), 0)
  assert meta['data_cls'] == 'hetero'
  assert u2i in graph
  assert set(nf) <= {'user', 'item'}
  assert node_pb['user'].table.shape[0] == 4
  assert node_pb['item'].table.shape[0] == 10


def test_frequency_partitioner_hetero(tmp_path):
  """Hetero FrequencyPartitioner: per-ntype prob dicts drive assignment
  and hot-row caching per node type (reference
  frequency_partitioner.py hetero loops)."""
  u2i = ('user', 'u2i', 'item')
  nu, ni = 20, 30
  u = np.arange(nu)
  # user u -> items (u, u+1) % ni
  ei = {u2i: np.stack([np.repeat(u, 2),
                       (np.repeat(u, 2)
                        + np.tile(np.arange(2), nu)) % ni])}
  feats = {'user': np.tile(np.arange(nu, dtype=np.float32)[:, None],
                           (1, 4)),
           'item': np.tile(np.arange(ni, dtype=np.float32)[:, None],
                           (1, 4))}
  probs = {
      'user': np.stack([(np.arange(nu) < 10).astype(np.float32),
                        (np.arange(nu) >= 10).astype(np.float32)]),
      'item': np.stack([(np.arange(ni) < 15).astype(np.float32),
                        (np.arange(ni) >= 15).astype(np.float32)]),
  }
  probs['item'][1, 0] = 0.5   # partition 1 also wants item 0 (cache)
  p = FrequencyPartitioner(str(tmp_path), num_parts=2,
                           num_nodes={'user': nu, 'item': ni},
                           edge_index=ei, node_feat=feats,
                           probs=probs, cache_ratio=0.1)
  p.partition()
  _, _, _, _, node_pb, _ = load_partition(str(tmp_path), 0)
  assert set(np.nonzero(node_pb['user'].table == 0)[0]) == \
      set(range(10))
  assert set(np.nonzero(node_pb['item'].table == 0)[0]) == \
      set(range(15))
  _, _, nfeat1, _, _, _ = load_partition(str(tmp_path), 1)
  assert 0 in nfeat1['item'].cache_ids  # hot remote item row cached

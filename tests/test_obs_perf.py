"""Performance observability: XLA cost accounting (compiles_total +
instrument_compiled gauges), measured rooflines, the bench trajectory +
regression gate, the postmortem flight recorder, SLO burn, and the
exposition/harvest satellites.

Acceptance pins (ISSUE 11): bench_compare exits nonzero on a synthetic
30% throughput regression; an injected engine stall produces a
postmortem dump carrying the stall event, the last spans, and a
registry snapshot."""
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from glt_tpu.obs import (
    FlightRecorder, MetricsRegistry, SloBurnEvaluator, Tracer,
    compile_counts, count_compile, device_ceilings, get_registry,
    get_tracer, instrument_compiled, parse_slo_env, roofline_report,
    set_recorder, set_registry,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, 'benchmarks'))


@pytest.fixture
def registry():
  """Fresh process-global registry, restored afterwards — compile
  counters and roofline gauges land on the global surface."""
  prev = set_registry(MetricsRegistry())
  yield get_registry()
  set_registry(prev)


@pytest.fixture
def recorder(tmp_path):
  """Fresh process-global flight recorder dumping into tmp_path with
  no rate limit, restored afterwards."""
  rec = FlightRecorder(dump_dir=str(tmp_path), min_dump_interval_s=0)
  prev = set_recorder(rec)
  yield rec
  set_recorder(prev)


# -- satellites: exposition escaping + dropped-span counter --------------

#: one exposition line: name{labels} value  (labels optional). The
#: label-value body may contain anything except a raw unescaped quote,
#: backslash, or newline.
_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*\})?'
    r' -?[0-9.eE+-]+$')


def test_prometheus_label_escaping_conformance():
  r = MetricsRegistry()
  nasty = 'a\\b"c\nd'
  r.inc('requests_total', path=nasty, code='200')
  r.set('depth', 2.0, q='say "hi"')
  r.observe('lat_seconds', 0.01, stage='x\\y')
  text = r.to_prometheus()
  for line in text.strip().split('\n'):
    if line.startswith('#'):
      continue
    assert _PROM_LINE.match(line), f'malformed exposition line: {line!r}'
  # the escapes are reversible — the scraper recovers the raw value
  m = re.search(r'path="((?:[^"\\]|\\.)*)"', text)
  unescaped = (m.group(1).replace(r'\n', '\n').replace(r'\"', '"')
               .replace('\\\\', '\\'))
  assert unescaped == nasty


def test_histogram_fraction_above():
  r = MetricsRegistry()
  h = r.histogram('lat')
  for v in (0.01, 0.01, 0.01, 1.0):
    h.observe(v)
  assert h.count_above(0.1) == 1
  assert abs(h.fraction_above(0.1) - 0.25) < 1e-9
  assert h.fraction_above(10.0) == 0.0
  assert r.histogram('empty').fraction_above(0.1) == 0.0


def test_spans_dropped_surfaces_as_counter():
  r = MetricsRegistry()
  t = Tracer(enabled=True, buffer=16, registry=r)
  for i in range(20):
    with t.span(f's{i}'):
      pass
  assert t.dropped == 4
  assert r.snapshot()['counters']['obs_spans_dropped_total'] == 4


# -- XLA cost accounting -------------------------------------------------

def test_compiles_total_counts_traces_not_executions(registry):
  import jax
  import jax.numpy as jnp

  @jax.jit
  def f(x):
    count_compile('test.fn')
    return x * 2

  for _ in range(3):
    f(jnp.ones((4,)))           # one trace, three executions
  assert compile_counts()['test.fn'] == 1
  f(jnp.ones((8,)))             # new shape: one more trace
  assert compile_counts()['test.fn'] == 2


def test_instrument_compiled_publishes_cost_gauges(registry):
  import jax
  import jax.numpy as jnp

  f = jax.jit(lambda x: (x @ x).sum())
  sds = jax.ShapeDtypeStruct((16, 16), jnp.float32)
  out = instrument_compiled('test.mm', f, sds)
  assert out.get('flops', 0) > 0
  gauges = registry.snapshot()['gauges']
  assert gauges['xla_flops{fn="test.mm"}'] > 0
  assert gauges['xla_bytes_accessed{fn="test.mm"}'] > 0
  # a pre-compiled stage also carries memory_analysis -> peak bytes
  out2 = instrument_compiled('test.mm2', f.lower(sds).compile())
  assert out2.get('peak_bytes', 0) > 0
  assert registry.snapshot()['gauges']['xla_peak_bytes{fn="test.mm2"}'] \
      > 0
  # garbage input degrades to {} (best-effort contract), never raises
  assert instrument_compiled('test.bad', object()) == {}


def test_serving_warmup_publishes_costs_opt_in(registry):
  import jax
  from fixtures import ring_dataset
  from glt_tpu.models import GraphSAGE
  from glt_tpu.serving import InferenceEngine
  ds = ring_dataset(num_nodes=24)
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
  eng = InferenceEngine(ds, model, None, [2, 2], buckets=(4,))
  eng.init_params(jax.random.key(0))
  eng.warmup(publish_costs=True)
  gauges = registry.snapshot()['gauges']
  assert gauges['xla_flops{fn="serve.forward[b4]"}'] > 0
  # the AOT lower is one extra trace per bucket — and only one: the
  # steady state afterwards must stay flat (zero-recompile invariant)
  warm = eng.compile_stats()
  eng.infer(np.arange(3) % 24)
  assert eng.compile_stats()['forward_traces'] == \
      warm['forward_traces']


# -- measured rooflines --------------------------------------------------

def test_device_ceilings_measured_then_cached(tmp_path, registry,
                                              monkeypatch):
  from glt_tpu.obs import perf
  cache = str(tmp_path / 'roofline.json')
  perf._CEILINGS.clear()
  c1 = device_ceilings(cache_path=cache, mib=2, dim=64)
  assert c1['hbm_bytes_per_sec'] > 0 and c1['flops_per_sec'] > 0
  assert os.path.exists(cache)
  # second resolution must come from the cache, never re-measure
  perf._CEILINGS.clear()

  def boom(*a, **k):
    raise AssertionError('re-measured despite a valid cache')

  monkeypatch.setattr(perf, 'measure_hbm_bandwidth', boom)
  monkeypatch.setattr(perf, 'measure_matmul_flops', boom)
  c2 = device_ceilings(cache_path=cache)
  assert c2['hbm_bytes_per_sec'] == c1['hbm_bytes_per_sec']
  # ...and every resolution republishes the ceiling gauges
  gauges = registry.snapshot()['gauges']
  assert any(k.startswith('roofline_hbm_bytes_per_sec') for k in gauges)
  assert any(k.startswith('roofline_flops_per_sec') for k in gauges)


def test_roofline_report_math_and_cell_keys():
  ceilings = {'device_kind': 'fake', 'hbm_bytes_per_sec': 1e9,
              'flops_per_sec': 1e12}
  cell = roofline_report(1e6, bytes_per_item=100.0, flops_per_item=50.0,
                         ceilings=ceilings, item='edge')
  # the acceptance cell contract: these keys ride every raced engine
  assert {'pct_of_measured_hbm_ceiling', 'hbm_bytes_per_edge',
          'flops_per_edge'} <= set(cell)
  # 1e6 edges/s * 100 B/edge = 1e8 B/s of a 1e9 B/s ceiling = 10%
  assert abs(cell['pct_of_measured_hbm_ceiling'] - 10.0) < 1e-6
  # 1e6 * 50 = 5e7 FLOP/s of 1e12 = 0.005%
  assert abs(cell['pct_of_measured_flop_ceiling'] - 0.005) < 1e-6
  assert cell['bound'] == 'hbm'
  assert roofline_report(1e6, ceilings=ceilings) == \
      {'device_kind': 'fake'}  # nothing measurable -> no percentages


# -- bench history + regression gate -------------------------------------

def _history_rows(path, values, engine='sort', bench='sampler_headline'):
  from history import append_run
  for v in values:
    append_run(path, bench, v, unit='edges/s', engine=engine,
               scale='s1', device='cpu')


def test_history_append_load_baseline(tmp_path):
  from history import baseline, load_runs
  h = str(tmp_path / 'h.jsonl')
  _history_rows(h, [100.0, 90.0, 110.0, 105.0])
  runs = load_runs(h, bench='sampler_headline', engine='sort',
                   scale='s1', device='cpu')
  assert [r['value'] for r in runs] == [100.0, 90.0, 110.0, 105.0]
  assert baseline(runs, median_of=3) == 105.0   # median of last 3
  assert load_runs(h, engine='other') == []
  assert baseline([], median_of=3) is None
  with open(h, 'a') as f:                       # torn final line
    f.write('{"truncated\n')
  assert len(load_runs(h)) == 4                 # skipped, not fatal


def test_history_rows_from_bench_json_skips_failures():
  from history import rows_from_bench_json
  doc = {'metric': 'x', 'value': 9.0, 'unit': 'edges/s',
         'engine': 'sort', 'backend': 'cpu', 'scale': 's1',
         'engines': {'sort+fused': {'edges_per_sec': 8.0},
                     'pallas_error': 'boom'},
         'train_steps_per_sec': {'per_batch': 3.0, 'superstep': 4.0}}
  rows = rows_from_bench_json(doc)
  assert {(r['bench'], r['engine']) for r in rows} == {
      ('sampler_headline', 'sort'), ('sampler_engine', 'sort+fused'),
      ('train_steps_per_sec', 'per_batch'),
      ('train_steps_per_sec', 'superstep')}
  assert rows_from_bench_json({'error': 'probe failed',
                               'value': 0.0}) == []


def test_bench_compare_fails_on_30_percent_regression(tmp_path):
  """The acceptance pin: a synthetically injected 30% throughput
  regression must exit nonzero; the healthy run must exit zero."""
  h = str(tmp_path / 'h.jsonl')
  _history_rows(h, [100.0, 102.0, 98.0])
  base_doc = {'metric': 'x', 'unit': 'edges/s', 'engine': 'sort',
              'backend': 'cpu', 'scale': 's1', 'engines': {}}
  ok = str(tmp_path / 'ok.json')
  bad = str(tmp_path / 'bad.json')
  json.dump(dict(base_doc, value=99.0), open(ok, 'w'))
  json.dump(dict(base_doc, value=70.0), open(bad, 'w'))  # -30% vs 100

  def gate(current):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts',
                                      'bench_compare.py'),
         '--history', h, '--current', current, '--threshold', '0.30'],
        capture_output=True, text=True)

  assert gate(ok).returncode == 0
  p = gate(bad)
  assert p.returncode != 0
  assert 'REGRESSION' in p.stderr
  report = json.loads(p.stdout)
  assert report['regressions'][0]['drop_pct'] == 30.0


def test_bench_compare_skips_unbaselined_and_failed_runs(tmp_path):
  sys.path.insert(0, os.path.join(REPO, 'scripts'))
  from bench_compare import compare
  h = str(tmp_path / 'h.jsonl')
  doc = {'metric': 'x', 'value': 50.0, 'unit': 'edges/s',
         'engine': 'sort', 'backend': 'cpu', 'scale': 's1',
         'engines': {}}
  # one recorded run < min_runs: nothing gates yet
  _history_rows(h, [100.0])
  r = compare(h, doc, threshold=0.3, min_runs=2)
  assert not r['regressions'] and r['skipped']
  # a run that failed to measure gates nothing (value 0 is "not
  # measured", per bench.py's own error contract)
  _history_rows(h, [100.0])
  r = compare(h, {'error': 'backend probe failed', 'value': 0.0},
              threshold=0.3)
  assert not r['regressions']
  # ...but with a baseline in place, the same doc WITHOUT an error
  # field gates loudly
  r = compare(h, doc, threshold=0.3)
  assert r['regressions'] and r['regressions'][0]['drop_pct'] == 50.0


# -- flight recorder -----------------------------------------------------

def test_flight_recorder_dump_contents(tmp_path, registry):
  rec = FlightRecorder(dump_dir=str(tmp_path), min_dump_interval_s=0,
                       registry=registry)
  registry.inc('requests_total', 5)
  rec.record('breaker_open', breaker='server:0')
  path = rec.trip('engine_stall', stall_timeout_s=0.15)
  assert path is not None and os.path.exists(path)
  doc = json.load(open(path))
  assert doc['reason'] == 'engine_stall'
  kinds = [e['kind'] for e in doc['events']]
  assert kinds == ['breaker_open', 'engine_stall']
  assert doc['registry']['counters']['requests_total'] == 5
  assert doc['counters_delta']['requests_total'] == 5
  # second dump reports only the movement since the first
  registry.inc('requests_total', 2)
  doc2 = json.load(open(rec.dump('again')))
  assert doc2['counters_delta']['requests_total'] == 2
  assert 'flight_trips_total{reason="engine_stall"}' \
      not in doc2['counters_delta']  # old movement aged out
  snap = registry.snapshot()['counters']
  assert snap['flight_trips_total{reason="engine_stall"}'] == 1
  assert snap['flight_events_total{kind="breaker_open"}'] == 1


def test_flight_recorder_rate_limit_and_ring_bound(tmp_path, registry):
  rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                       min_dump_interval_s=3600, registry=registry)
  assert rec.trip('breaker_open') is not None   # first dump lands
  assert rec.trip('breaker_open') is None       # rate-limited
  assert rec.dumps == 1
  # ...but every trip is still recorded and counted
  assert registry.snapshot()['counters'][
      'flight_trips_total{reason="breaker_open"}'] == 2
  for i in range(40):
    rec.record('evt', i=i)
  assert len(rec.events()) == 16                # bounded ring
  # no dump dir: trips record but never touch the filesystem
  rec2 = FlightRecorder(dump_dir='', registry=registry)
  assert rec2.trip('breaker_open') is None


def test_breaker_open_lands_on_recorder(recorder, registry):
  from glt_tpu.resilience import CircuitBreaker
  b = CircuitBreaker(failure_threshold=2, name='peer:7')
  b.record_failure()
  b.record_failure()
  assert b.state == 'OPEN'
  evts = [e for e in recorder.events() if e['kind'] == 'breaker_open']
  assert evts and evts[-1]['breaker'] == 'peer:7'
  # ...and the trip left a postmortem behind (recorder fixture dir)
  assert recorder.dumps == 1


def test_ingestor_crash_lands_on_recorder(recorder, registry):
  from glt_tpu.stream import (
      CompactionPolicy, SnapshotManager, StreamIngestor,
  )
  from glt_tpu.data import Topology
  topo = Topology(indptr=None,
                  edge_index=np.array([[0, 1], [1, 2]]), num_nodes=4)
  mgr = SnapshotManager(topo, delta_capacity=16)
  ing = StreamIngestor(mgr, policy=CompactionPolicy(max_staleness_s=0),
                       restart_policy='raise')
  ing.start(poll_interval_s=0.01)
  # poison the BACKGROUND tick only (the caller-thread staging path
  # raises synchronously and never reaches the bg-death trip)
  ing.maybe_compact = lambda: (_ for _ in ()).throw(
      RuntimeError('poisoned cut'))
  deadline = time.monotonic() + 10
  while ing._bg_error is None and time.monotonic() < deadline:
    time.sleep(0.01)
  ing.stop(raise_background_error=False)
  evts = [e for e in recorder.events() if e['kind'] == 'ingestor_crash']
  assert evts and 'poisoned cut' in evts[-1]['error']
  assert recorder.dumps >= 1


@pytest.mark.chaos
def test_engine_stall_writes_postmortem(tmp_path, registry):
  """Acceptance: an injected engine stall produces a flight-recorder
  postmortem containing the stall event, the last spans, and a
  registry snapshot."""
  from glt_tpu.serving import EngineStalledError, MicroBatcher
  rec = FlightRecorder(dump_dir=str(tmp_path), min_dump_interval_s=0,
                       registry=registry)
  prev_rec = set_recorder(rec)
  tracer = get_tracer()
  was_enabled = tracer.enabled
  tracer.clear()
  tracer.enable()
  gate = threading.Event()
  entered = threading.Event()

  def handler(ids):
    entered.set()
    gate.wait(timeout=30)
    return np.stack([ids.astype(np.float32)] * 2, axis=1)

  b = MicroBatcher(handler, max_batch_size=8, max_wait_ms=1.0,
                   request_timeout_ms=5000.0, stall_timeout_ms=100.0)
  try:
    with tracer.span('serve.infer'):   # pipeline activity pre-stall
      f = b.submit([1, 2])
    assert entered.wait(timeout=10)
    with pytest.raises(EngineStalledError):
      f.result(timeout=10)
    deadline = time.monotonic() + 10
    while rec.dumps == 0 and time.monotonic() < deadline:
      time.sleep(0.01)
    dumps = sorted(os.listdir(tmp_path))
    assert dumps, 'stall produced no postmortem dump'
    doc = json.load(open(tmp_path / dumps[0]))
    assert doc['reason'] == 'engine_stall'
    stall = [e for e in doc['events'] if e['kind'] == 'engine_stall']
    assert stall and stall[0]['stall_timeout_s'] == 0.1
    assert any(s['name'] == 'serve.infer' for s in doc['spans'])
    assert 'counters' in doc['registry']
    assert registry.snapshot()['counters'][
        'flight_trips_total{reason="engine_stall"}'] >= 1
  finally:
    gate.set()
    b.stop()
    set_recorder(prev_rec)
    tracer.enabled = was_enabled
    tracer.clear()


# -- SLO burn ------------------------------------------------------------

def test_slo_burn_windowed_evaluation(registry):
  ev = SloBurnEvaluator([], registry=registry)
  ev.add('serve_fast', 'serving_latency_seconds', 0.1, objective=0.9)
  for v in (0.01, 0.01, 1.0, 1.0):   # 50% above threshold
    registry.observe('serving_latency_seconds', v)
  burns = ev.evaluate()
  # bad fraction 0.5 against a 10% error budget = burn 5x
  assert abs(burns['serve_fast'] - 5.0) < 1e-6
  assert abs(registry.snapshot()['gauges']
             ['slo_burn{slo="serve_fast"}'] - 5.0) < 1e-6
  # next window: only good traffic -> burn 0 (windowed, not lifetime)
  for _ in range(10):
    registry.observe('serving_latency_seconds', 0.01)
  assert ev.evaluate()['serve_fast'] == 0.0
  # an empty window burns nothing
  assert ev.evaluate()['serve_fast'] == 0.0


def test_slo_burn_trips_recorder_on_fast_burn(tmp_path, registry):
  rec = FlightRecorder(dump_dir=str(tmp_path), min_dump_interval_s=0,
                       registry=registry)
  ev = SloBurnEvaluator([], registry=registry, recorder=rec,
                        trip_above=2.0)
  ev.add('p99', 'lat', 0.1, objective=0.99)
  for _ in range(10):
    registry.observe('lat', 1.0)     # 100% bad: burn 100x
  burns = ev.evaluate()
  assert burns['p99'] > 2.0
  evts = [e for e in rec.events() if e['kind'] == 'slo_burn']
  assert evts and evts[0]['slo'] == 'p99'
  assert rec.dumps == 1


def test_serving_server_publishes_slo_burn():
  """The per-shard wiring: a ServingServer with SLO policies evaluates
  burn on every stats() pull and publishes slo_burn gauges on its own
  registry (shared-registry fleets get per-shard series via
  metrics_name)."""
  import jax
  from fixtures import ring_dataset
  from glt_tpu.models import GraphSAGE
  from glt_tpu.obs import SloPolicy
  from glt_tpu.serving import ServingServer
  ds = ring_dataset(num_nodes=24)
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
  from glt_tpu.serving import InferenceEngine
  eng = InferenceEngine(ds, model, None, [2, 2], buckets=(4,))
  eng.init_params(jax.random.key(0))
  # threshold below any real latency: every request burns budget
  with ServingServer(eng, slos=[SloPolicy(
      'p99_fast', 'serving_latency_seconds', 1e-6,
      objective=0.99)]) as srv:
    srv.infer(np.arange(3))
    stats = srv.stats()
    assert stats['slo_burn']['p99_fast'] > 1.0
    gauges = srv.metrics.registry.snapshot()['gauges']
    assert gauges['slo_burn{slo="p99_fast"}'] > 1.0
    # quiet window: the burn gauge decays to 0, not to its lifetime avg
    assert srv.stats()['slo_burn']['p99_fast'] == 0.0


def test_parse_slo_env():
  pols = parse_slo_env(
      'serve:serving_latency_seconds:0.25:0.999;'
      'gather:stage_seconds{stage=gather.features}:0.05')
  assert len(pols) == 2
  assert pols[0].name == 'serve' and pols[0].objective == 0.999
  assert pols[1].labels == {'stage': 'gather.features'}
  assert pols[1].objective == 0.99          # default
  assert abs(pols[0].error_budget - 0.001) < 1e-12
  assert parse_slo_env('') == []
  with pytest.raises(ValueError):
    parse_slo_env('just_a_name')


# -- fabric harvest: dead endpoint is a counted miss ---------------------

def test_fabric_harvest_partial_on_dead_endpoint(tmp_path, registry):
  """collect_endpoint_obs/collect_obs raise for the dead peer, but
  export_fabric_trace still merges every reachable peer's spans and
  counts the miss instead of aborting."""
  from glt_tpu.distributed import dist_client
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  from glt_tpu.obs import collect_endpoint_obs
  from glt_tpu.resilience import RetryPolicy
  srv = RpcServer()
  dead = RpcServer()
  cli_live = RpcClient(srv.host, srv.port, timeout=5,
                       retry=RetryPolicy(max_attempts=1))
  cli_dead = RpcClient(dead.host, dead.port, timeout=5,
                       retry=RetryPolicy(max_attempts=1))
  dead_port = dead.port
  dead.stop()
  # a direct harvest of the dead endpoint raises (callers that want
  # one peer get the real error)...
  with pytest.raises(OSError):
    collect_endpoint_obs('127.0.0.1', dead_port, timeout=2.0)
  saved = (dict(dist_client._clients), dist_client._num_servers,
           dist_client._health, dist_client._metrics)
  try:
    dist_client._clients.clear()
    dist_client._clients.update({0: cli_live, 1: cli_dead})
    dist_client._num_servers = 2
    dist_client._health = None
    dist_client._metrics = None
    assert 'counters' in dist_client.collect_obs(0)['metrics']
    with pytest.raises((ConnectionError, OSError)):
      dist_client.collect_obs(1)
    # ...but the merged export partial-harvests with a counted miss
    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.clear()
    tracer.enable()
    try:
      with tracer.span('client.work'):
        pass
      out = str(tmp_path / 'fabric.json')
      assert dist_client.export_fabric_trace(out) == out
    finally:
      tracer.enabled = was_enabled
      tracer.clear()
    doc = json.load(open(out))
    assert any(e.get('name') == 'client.work'
               for e in doc['traceEvents'])
    misses = registry.snapshot()['counters']
    assert misses['obs_harvest_misses_total{server="1"}'] == 1
    assert 'obs_harvest_misses_total{server="0"}' not in misses
  finally:
    dist_client._clients.clear()
    dist_client._clients.update(saved[0])
    dist_client._num_servers = saved[1]
    dist_client._health = saved[2]
    dist_client._metrics = saved[3]
    cli_live.close()
    cli_dead.close()
    srv.stop()


# -- bench worker failure path -------------------------------------------

def test_bench_worker_failure_dumps_obs_artifacts(tmp_path,
                                                  monkeypatch):
  """The GLT_OBS_DUMP artifacts must land on the worker's FAILURE path
  too — the crashed run is the one whose registry/trace state matters."""
  import importlib.util
  spec = importlib.util.spec_from_file_location(
      'bench_mod', os.path.join(REPO, 'bench.py'))
  bench = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(bench)
  monkeypatch.setenv('GLT_OBS_DUMP', str(tmp_path))
  get_registry().inc('loader_batches_total')  # some state to dump
  bench._dump_obs_on_failure()
  reg = json.load(open(tmp_path / 'obs_registry.json'))
  assert 'counters' in reg
  tr = json.load(open(tmp_path / 'obs_trace.json'))
  assert 'traceEvents' in tr

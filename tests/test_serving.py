"""Serving subsystem: bucketed engine compile discipline, embedding
cache semantics, micro-batcher edge cases, metrics, and the RPC
front-end.

Determinism strategy: engine tests sample with full-neighborhood fanout
(``[-1, -1]``) on the bounded-degree ring fixture, so the sampled
subgraph — and therefore the forward — is exact and padding-invariant
up to float summation order (asserted with allclose)."""
import threading
import time

import numpy as np
import pytest

from fixtures import ring_dataset
from glt_tpu.models import GraphSAGE
from glt_tpu.serving import (
    EmbeddingCache, InferenceEngine, LatencyHistogram, MicroBatcher,
    ServingClient, ServingMetrics, ServingOverloaded, ServingServer,
)

N_NODES = 40
OUT_DIM = 4


@pytest.fixture(scope='module')
def model_and_params():
  import jax
  ds = ring_dataset(num_nodes=N_NODES)
  model = GraphSAGE(hidden_features=16, out_features=OUT_DIM,
                    num_layers=2)
  eng = InferenceEngine(ds, model, None, [-1, -1], buckets=(4,))
  return model, eng.init_params(jax.random.key(0))


def make_engine(model_and_params, buckets=(4, 8), **kw):
  model, params = model_and_params
  return InferenceEngine(ring_dataset(num_nodes=N_NODES), model, params,
                         [-1, -1], buckets=buckets, **kw)


# -- engine: bucketed compilation ----------------------------------------

def test_warmup_compiles_each_bucket_exactly_once(model_and_params):
  eng = make_engine(model_and_params, buckets=(4, 8))
  stats = eng.warmup()
  assert stats['forward_traces'] == {4: 1, 8: 1}
  assert stats['sampler_compiled_fns'] == 2


def test_steady_state_zero_recompiles(model_and_params):
  eng = make_engine(model_and_params, buckets=(4, 8))
  eng.warmup()
  warm = eng.compile_stats()
  # every request size in [1, 8] plus an oversized one (chunked through
  # the largest bucket) must reuse the warmed programs
  for n in list(range(1, 9)) + [13]:
    out = eng.infer(np.arange(n) % N_NODES)
    assert out.shape == (n, OUT_DIM)
  now = eng.compile_stats()
  assert now['forward_traces'] == warm['forward_traces']
  assert now['sampler_compiled_fns'] == warm['sampler_compiled_fns']
  assert now['forward_calls'] > 0


def test_bucket_boundary_padding_correctness(model_and_params):
  """Padded execution equals the unpadded reference at and around the
  bucket boundary (n = B-1, B, 1)."""
  eng = make_engine(model_and_params, buckets=(8,), cache_capacity=0)
  eng.warmup()
  for n in (1, 7, 8):
    ids = (np.arange(n) * 3) % N_NODES
    ref_eng = make_engine(model_and_params, buckets=(n,),
                          cache_capacity=0)
    np.testing.assert_allclose(eng.infer(ids), ref_eng.infer(ids),
                               atol=1e-4)


def test_duplicate_and_empty_requests(model_and_params):
  eng = make_engine(model_and_params)
  eng.warmup()
  ids = np.array([5, 7, 5, 5, 7])
  out = eng.infer(ids)
  np.testing.assert_allclose(out[0], out[2])
  np.testing.assert_allclose(out[0], out[3])
  np.testing.assert_allclose(out[1], out[4])
  single = eng.infer([5])
  np.testing.assert_allclose(out[0], single[0], atol=1e-4)
  empty = eng.infer([])
  assert empty.shape == (0, OUT_DIM)


# -- engine: cache integration -------------------------------------------

def test_cached_lookup_bypasses_forward(model_and_params):
  eng = make_engine(model_and_params)
  eng.warmup()
  first = eng.infer([1, 2, 3])
  calls = eng.forward_calls
  again = eng.infer([1, 2, 3])   # full hit: no sampling, no forward
  assert eng.forward_calls == calls
  np.testing.assert_allclose(first, again)
  assert eng.cache.hit_rate > 0
  # partial hit computes only the missing ids (one more bucket run)
  eng.infer([2, 3, 4])
  assert eng.forward_calls == calls + 1


def test_version_bump_invalidates_cache(model_and_params):
  import jax
  eng = make_engine(model_and_params)
  eng.warmup()
  before = eng.infer([1, 2])
  calls = eng.forward_calls
  # scale params: embeddings must change once the version bumps
  new_params = jax.tree.map(lambda a: a * 2.0, eng.params)
  assert eng.set_params(new_params) == 1
  after = eng.infer([1, 2])
  assert eng.forward_calls == calls + 1  # recomputed, not cache-served
  assert not np.allclose(before, after)


@pytest.mark.pallas
def test_row_gather_override_threads_through_serving(model_and_params):
  """resolve_row_gather seam, serving path: an injected gather kernel
  (here the interpret-mode Pallas row gather) serves EVERY feature-row
  gather the engine performs, and results match the XLA gather path."""
  import functools
  from glt_tpu.ops.pallas_kernels import gather_rows
  calls = {'n': 0}

  def counting_gather(table, rows):
    calls['n'] += 1    # trace-time count: proves the override is used
    return gather_rows(table, rows, interpret=True)

  ref = make_engine(model_and_params, cache_capacity=0)
  eng = make_engine(model_and_params, cache_capacity=0,
                    row_gather=counting_gather)
  eng.warmup()
  assert calls['n'] > 0
  ids = np.array([3, 7, 11])
  np.testing.assert_allclose(eng.infer(ids), ref.infer(ids), atol=1e-5)


@pytest.mark.pallas
def test_row_gather_override_reaches_offloaded_store():
  """The injection seam also covers host-offloaded stores: the hot-row
  gather inside the fused mixed gather runs the injected kernel."""
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  import jax.numpy as jnp

  from glt_tpu.data.feature import Feature, gather_features
  from glt_tpu.ops.pallas_kernels import gather_rows
  calls = {'n': 0}

  def counting_gather(table, rows):
    calls['n'] += 1
    return gather_rows(table, rows, interpret=True)

  rows = np.arange(20, dtype=np.float32)[:, None] * np.ones((1, 4),
                                                            np.float32)
  feat = Feature(rows, split_ratio=0.5, host_offload=True)
  feat.lazy_init()
  assert feat.cold_array is not None
  ids = np.array([0, 3, 12, 19])
  want = gather_features(feat, jnp.asarray(ids))
  got = gather_features(feat, jnp.asarray(ids),
                        row_gather=counting_gather)
  assert calls['n'] > 0
  np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_invalidate_nodes_hook(model_and_params):
  eng = make_engine(model_and_params)
  eng.warmup()
  eng.infer([1, 2, 3])
  calls = eng.forward_calls
  assert eng.invalidate_nodes([2]) == 1
  eng.infer([1, 2, 3])
  assert eng.forward_calls == calls + 1  # only node 2 recomputed
  seen = []
  eng.cache.add_invalidation_listener(
      lambda ids, version: seen.append((ids, version)))
  eng.cache.invalidate()
  assert seen == [(None, None)]


# -- embedding cache -----------------------------------------------------

def test_lru_eviction_and_stats():
  c = EmbeddingCache(capacity=2)
  c.insert([1, 2], np.eye(2, dtype=np.float32), version=0)
  assert c.lookup([1], 0)  # touch 1 -> 2 is now LRU
  c.insert([3], np.ones((1, 2), np.float32), version=0)
  assert len(c) == 2
  got = c.lookup([1, 2, 3], 0)
  assert set(got) == {1, 3}  # 2 evicted
  s = c.stats()
  assert s['evictions'] == 1 and s['hits'] == 3 and s['misses'] == 1
  # capacity 0 disables caching
  c0 = EmbeddingCache(capacity=0)
  c0.insert([1], np.ones((1, 2), np.float32), version=0)
  assert len(c0) == 0 and c0.lookup([1], 0) == {}


def test_cache_version_keying():
  c = EmbeddingCache(capacity=8)
  c.insert([1], np.zeros((1, 2), np.float32), version=0)
  assert c.lookup([1], 1) == {}          # other version never hits
  assert 1 in c.lookup([1], 0)
  assert c.invalidate(version=0) == 1
  assert c.lookup([1], 0) == {}
  # id-probe invalidation spans all LIVE versions, and the live-version
  # set shrinks as entries die (no growth across version bumps)
  c.insert([2], np.ones((1, 2), np.float32), version=3)
  c.insert([2], np.ones((1, 2), np.float32), version=4)
  assert c.invalidate(ids=[2]) == 2
  assert len(c._version_counts) == 0
  # cached rows own their memory (no view into the bucket output)
  c.insert([5], np.ones((2, 2), np.float32)[:1], version=0)
  assert c.lookup([5], 0)[5].base is None


# -- micro-batcher edge cases (satellite) --------------------------------

def _echo_handler(calls):
  def handler(ids):
    calls.append(np.asarray(ids).copy())
    return np.asarray(ids, np.float32)[:, None] * 2
  return handler


def test_batcher_merges_concurrent_requests():
  calls = []
  b = MicroBatcher(_echo_handler(calls), max_batch_size=8,
                   max_wait_ms=60.0)
  try:
    f1 = b.submit([1, 2])
    f2 = b.submit([3])
    f3 = b.submit([4, 5, 6, 7, 8])   # fills the batch -> flush now
    np.testing.assert_array_equal(f1.result(timeout=5).ravel(), [2, 4])
    np.testing.assert_array_equal(f2.result(timeout=5).ravel(), [6])
    np.testing.assert_array_equal(
        f3.result(timeout=5).ravel(), [8, 10, 12, 14, 16])
    assert len(calls) == 1 and calls[0].size == 8
  finally:
    b.stop()


def test_batcher_deadline_flush_partial_batch():
  calls = []
  b = MicroBatcher(_echo_handler(calls), max_batch_size=64,
                   max_wait_ms=20.0)
  try:
    t0 = time.monotonic()
    f = b.submit([9])
    np.testing.assert_array_equal(f.result(timeout=5).ravel(), [18])
    waited = time.monotonic() - t0
    assert waited >= 0.015  # the deadline, not an instant flush
    assert len(calls) == 1 and calls[0].size == 1
  finally:
    b.stop()


def test_batcher_empty_flush_on_deadline():
  """All queued requests expire before the flush deadline: the flush
  finds nothing and the handler must NOT be called."""
  calls = []
  b = MicroBatcher(_echo_handler(calls), max_batch_size=64,
                   max_wait_ms=200.0)
  try:
    f = b.submit([1], timeout_ms=10.0)
    with pytest.raises(TimeoutError):
      f.result(timeout=5)
    time.sleep(0.05)
    assert calls == [] and b.depth == 0
  finally:
    b.stop()


def test_batcher_request_timeout_under_slow_handler():
  release = threading.Event()
  def slow(ids):
    release.wait(5)
    return np.asarray(ids, np.float32)[:, None]
  m = ServingMetrics()
  b = MicroBatcher(slow, max_batch_size=1, max_wait_ms=0.0,
                   max_queue=8, metrics=m)
  try:
    b.submit([1])                         # occupies the dispatcher
    f2 = b.submit([2], timeout_ms=30.0)   # expires while queued
    time.sleep(0.06)                      # let the deadline pass...
    release.set()                         # ...then free the dispatcher
    with pytest.raises(TimeoutError):
      f2.result(timeout=5)
    assert m.timeouts == 1
  finally:
    release.set()
    b.stop()


def test_batcher_backpressure():
  release = threading.Event()
  def slow(ids):
    release.wait(5)
    return np.asarray(ids, np.float32)[:, None]
  m = ServingMetrics()
  b = MicroBatcher(slow, max_batch_size=1, max_wait_ms=0.0,
                   max_queue=2, metrics=m)
  try:
    b.submit([1])            # dispatched (stuck in the slow handler)
    time.sleep(0.05)         # let the dispatcher drain the queue
    b.submit([2])
    b.submit([3])            # queue now at capacity (2)
    with pytest.raises(ServingOverloaded):
      b.submit([4])
    assert m.rejected == 1
  finally:
    release.set()
    b.stop()


def test_batcher_oversized_head_request_ships_alone():
  calls = []
  b = MicroBatcher(_echo_handler(calls), max_batch_size=4,
                   max_wait_ms=60.0)
  try:
    f = b.submit(np.arange(10))  # bigger than max_batch: ships whole
    assert f.result(timeout=5).shape == (10, 1)
    assert len(calls) == 1 and calls[0].size == 10
  finally:
    b.stop()


def test_batcher_handler_errors_propagate_and_stop_fails_pending():
  def boom(ids):
    raise ValueError('kaput')
  b = MicroBatcher(boom, max_batch_size=4, max_wait_ms=1.0)
  f = b.submit([1])
  with pytest.raises(ValueError, match='kaput'):
    f.result(timeout=5)
  b.stop()
  with pytest.raises(RuntimeError, match='stopped'):
    b.submit([2])


# -- metrics -------------------------------------------------------------

def test_latency_histogram_percentiles():
  h = LatencyHistogram()
  for ms in range(1, 101):            # 1..100ms uniform
    h.observe(ms / 1e3)
  assert h.count == 100
  assert abs(h.percentile(50) - 0.050) < 0.01
  assert abs(h.percentile(99) - 0.100) < 0.012
  assert h.percentile(100) == h.max
  assert LatencyHistogram().percentile(99) == 0.0


def test_serving_metrics_snapshot():
  m = ServingMetrics()
  m.record_request(0.002, num_ids=3)
  m.record_request(0.004, num_ids=1)
  m.record_batch(4, 8)
  snap = m.snapshot()
  assert snap['requests'] == 2 and snap['ids_served'] == 4
  assert snap['batch_fill_ratio'] == 0.5
  assert 0 < snap['latency_p50_ms'] <= snap['latency_p99_ms']
  assert 'req/s' in m.report()


# -- RPC front-end -------------------------------------------------------

def test_server_client_roundtrip(model_and_params):
  eng = make_engine(model_and_params, buckets=(4, 8))
  with ServingServer(eng, max_wait_ms=1.0,
                     request_timeout_ms=30_000.0) as srv:
    cli = ServingClient(*srv.address)
    try:
      info = cli.ping()
      assert info['ok'] and info['buckets'] == [4, 8]
      ids = np.array([3, 1, 4, 1, 5])
      out = cli.infer(ids)
      assert out.shape == (5, OUT_DIM)
      np.testing.assert_allclose(out, eng.infer(ids))  # cache-served
      # concurrent clients interleave through the batcher
      cli2 = ServingClient(*srv.address)
      futs = [cli.infer_async([7, 8]), cli2.infer_async([9])]
      assert futs[0].result(timeout=30).shape == (2, OUT_DIM)
      assert futs[1].result(timeout=30).shape == (1, OUT_DIM)
      cli2.close()
      # out-of-range ids rejected per-request (never co-batched, never
      # clamped into a wrong-but-cacheable embedding)
      with pytest.raises(ValueError, match='out of range'):
        cli.infer([N_NODES + 7])
      assert cli.invalidate(ids=[3]) == 1
      stats = cli.stats()
      assert stats['requests'] >= 3
      assert stats['engine']['forward_traces'] == {4: 1, 8: 1}
      assert stats['cache']['size'] > 0
      assert stats['latency_p99_ms'] >= stats['latency_p50_ms'] > 0
      # resilience counters surface through ServingClient.stats()
      for key in ('retries', 'reconnects', 'breaker_opens', 'shed',
                  'stale_serves', 'failovers'):
        assert stats[key] == 0, (key, stats[key])
      assert stats['stalled'] is False
    finally:
      cli.close()


# -- degradation tiers (resilience) --------------------------------------

def test_stale_serve_answers_from_cache_while_engine_stalled(
    model_and_params):
  """Engine watchdog + opt-in stale-serve: a wedged forward opens the
  engine circuit; requests are answered from the versioned
  EmbeddingCache (zero-fill for misses) with bounded latency, every
  stale answer counted; the wedged call returning closes the circuit
  and serving resumes through the engine."""
  from glt_tpu.serving import EngineStalledError

  eng = make_engine(model_and_params, buckets=(4,))
  srv = ServingServer(eng, max_wait_ms=1.0, request_timeout_ms=5000.0,
                      stall_timeout_ms=150.0, stale_serve=True)
  try:
    primed_ids = np.array([1, 2, 3])
    primed = srv.infer(primed_ids)          # fills the cache
    # wedge the engine behind the batcher
    gate = threading.Event()
    wedge = threading.Event()
    real = srv.batcher.handler

    def wedging(ids):
      if wedge.is_set():
        gate.wait(timeout=30)
      return real(ids)

    srv.batcher.handler = wedging
    wedge.set()
    t0 = time.monotonic()
    out = srv.infer([1, 2], timeout_ms=3000.0)  # rides the stall
    dt = time.monotonic() - t0
    np.testing.assert_allclose(out, primed[:2], rtol=1e-5)
    assert dt < 2.0, f'stale serve not bounded by the watchdog ({dt}s)'
    assert srv.batcher.stalled
    # while OPEN: immediate stale answers, hits and misses both counted
    out2 = srv.infer([3, 17])
    np.testing.assert_allclose(out2[0], primed[2], rtol=1e-5)
    np.testing.assert_allclose(out2[1], 0)   # true miss: zero-fill
    stats = srv.stats()
    assert stats['stalled'] is True
    assert stats['stale_serves'] >= 3
    assert stats['breaker_opens'] == 1
    assert stats['gauges']['stale_zero_fills'] == 1
    # p99 stays bounded by the deadline: every recorded request was
    # either served fresh (fast) or stale (immediate)
    assert stats['latency_p99_ms'] <= 3000.0
    # release the wedge: circuit closes, engine serves again
    wedge.clear()
    gate.set()
    deadline = time.monotonic() + 10
    while srv.batcher.stalled and time.monotonic() < deadline:
      time.sleep(0.01)
    assert not srv.batcher.stalled
    calls0 = eng.forward_calls
    fresh = srv.infer([11, 12])
    assert fresh.shape == (2, OUT_DIM)
    assert eng.forward_calls > calls0        # really went through
    assert srv.stats()['stalled'] is False
  finally:
    srv.close()


def test_stale_serve_disabled_fails_fast(model_and_params):
  """Without stale_serve the stall surfaces as EngineStalledError —
  fail fast, never a silent zero answer."""
  from glt_tpu.serving import EngineStalledError

  eng = make_engine(model_and_params, buckets=(4,))
  srv = ServingServer(eng, max_wait_ms=1.0, request_timeout_ms=5000.0,
                      stall_timeout_ms=150.0, stale_serve=False)
  try:
    srv.infer([1])
    gate = threading.Event()
    real = srv.batcher.handler
    srv.batcher.handler = lambda ids: (gate.wait(timeout=30), real(ids))[1]
    with pytest.raises(EngineStalledError):
      srv.infer([2], timeout_ms=3000.0)
    gate.set()
  finally:
    srv.close()


def test_update_snapshot_never_serves_mixed_versions():
  """Versioned-consistency regression: while ``update_snapshot`` swaps
  the feature table under the engine lock, a concurrent ``infer`` must
  observe EITHER the old table end-to-end OR the new one — never
  snapshot-v rows for some ids and v-1 rows for others in one response.
  Rows value-encode their version (1000*v + id) so a torn response is
  directly visible in the output."""
  from glt_tpu.stream import SnapshotManager, StreamIngestor, StreamSampler

  dim, n = 8, 40
  ds = ring_dataset(num_nodes=n, feat_dim=dim)
  mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature())
  eng = InferenceEngine(ds, None, None, [2], buckets=(8,),
                        apply_fn=lambda p, b: b.x,
                        sampler=StreamSampler(mgr, [2], seed=0))
  ing = StreamIngestor(mgr, sampler=eng.sampler, engine=eng)
  ids = np.array([2, 7, 13, 22, 29, 37])
  errs, seen = [], set()
  stop = threading.Event()

  def hammer():
    try:
      while not stop.is_set():
        before = eng.snapshot_version
        out = eng.infer(ids)
        marks = np.unique(out[:, 0] - ids)  # 1000*v per row
        assert marks.size == 1, f'mixed versions in one infer: {marks}'
        v = int(marks[0]) // 1000
        # monotone: an infer that started at snapshot ``before`` may
        # observe a newer table, never an older one
        assert v >= before, (v, before)
        seen.add(v)
    except Exception as e:
      errs.append(e)

  threads = [threading.Thread(target=hammer) for _ in range(3)]
  try:
    for t in threads:
      t.start()
    for v in range(1, 4):
      rows = 1000.0 * v + np.arange(n, dtype=np.float32)[:, None] \
          * np.ones(dim, np.float32)
      ing.update_features(np.arange(n), rows)
      info = ing.flush()
      assert info['version'] == v
      assert eng.snapshot_version == v
      time.sleep(0.05)
  finally:
    stop.set()
    for t in threads:
      t.join(timeout=10)
  assert not errs, errs
  assert 3 in seen, f'final snapshot never observed: {sorted(seen)}'

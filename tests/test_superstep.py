"""Superstep training pipeline: K batches per dispatch.

Parity contract under test: a K-step superstep (one donated lax.scan
dispatch) is BIT-IDENTICAL to K sequential per-batch SPMDSageTrainStep
calls — same RNG stream, same losses, same params — for fully-resident,
host-offloaded-spill and cold-streaming feature stores, with_edge on and
off. Plus the DeviceEpochLoader staging layer and the shared staged-pad
helper.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from glt_tpu.data import Dataset, Feature
from glt_tpu.loader import (DeviceEpochLoader, NodeLoader, pad_seed_batch,
                            shard_n_valid, stack_epoch_batches)
from glt_tpu.models import GraphSAGE
from glt_tpu.ops.superstep import superstep
from glt_tpu.parallel import ShardedFeature, SPMDSageTrainStep, make_mesh

from fixtures import ring_edges

N = 64
K = 3
BS = 4  # per device; 8-device mesh -> global batch 32


@pytest.fixture(scope='module')
def mesh():
  return make_mesh(8)


@pytest.fixture(scope='module')
def setting(mesh):
  rng = np.random.default_rng(23)
  src = np.repeat(np.arange(N), 3)
  dst = (src + rng.integers(1, N, src.shape[0])) % N
  feats = rng.normal(size=(N, 8)).astype(np.float32)
  labels = rng.integers(0, 4, N).astype(np.int32)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=N)
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
  tx = optax.adam(1e-2)
  return ds, model, tx, feats, labels


def _trainer(mesh, setting, sf, **kw):
  ds, model, tx, _, labels = setting
  return SPMDSageTrainStep(mesh, model, tx, ds.get_graph(), sf, labels,
                           fanouts=[3, 2], batch_size_per_device=BS,
                           **kw)


@pytest.fixture(scope='module')
def resident(mesh, setting):
  """Fully-resident trainer + init params/opt (shared: compiles once)."""
  sf = ShardedFeature(setting[3], mesh)
  step = _trainer(mesh, setting, sf)
  params = step.init_params(jax.random.key(0))
  opt = step.tx.init(params)
  return step, params, opt


def _inputs(t=K):
  seeds = np.arange(8 * BS) % N
  seeds_stack = np.broadcast_to(seeds, (t, seeds.shape[0])).copy()
  n_valid = np.full((t, 8), BS)
  keys = jax.random.split(jax.random.key(7), (t, 8))
  return seeds, seeds_stack, n_valid, keys


def _copy(tree):
  return jax.tree.map(jnp.array, tree)


def _run_sequential(step, params, opt, seeds, keys):
  losses = []
  for t in range(keys.shape[0]):
    params, opt, loss = step(params, opt, seeds, np.full(8, BS), keys[t])
    losses.append(np.asarray(loss))
  return params, opt, np.stack(losses)


# -- parity ---------------------------------------------------------------

def test_superstep_matches_sequential_per_batch(resident):
  step, params, opt = resident
  seeds, seeds_stack, n_valid, keys = _inputs()
  p1, o1, ref = _run_sequential(step, *_copy((params, opt)), seeds, keys)
  p2, o2 = _copy((params, opt))
  p2, o2, got = step.superstep(p2, o2, seeds_stack, n_valid, keys)
  np.testing.assert_array_equal(ref, np.asarray(got))
  for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
  for a, b in zip(jax.tree.leaves(o1), jax.tree.leaves(o2)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope='module')
def resident_edge(mesh, setting):
  """with_edge=True trainer (sampled edge ids threaded into Batch)."""
  sf = ShardedFeature(setting[3], mesh)
  step = _trainer(mesh, setting, sf, with_edge=True)
  params = step.init_params(jax.random.key(0))
  opt = step.tx.init(params)
  return step, params, opt


def test_superstep_parity_with_edge(resident_edge):
  step, params, opt = resident_edge
  seeds, seeds_stack, n_valid, keys = _inputs()
  _, _, ref = _run_sequential(step, *_copy((params, opt)), seeds, keys)
  _, _, got = step.superstep(*_copy((params, opt)), seeds_stack,
                             n_valid, keys)
  np.testing.assert_array_equal(ref, np.asarray(got))


def test_superstep_cold_streaming_parity_with_edge(mesh, setting,
                                                  resident_edge):
  res_step, params, opt = resident_edge
  sf = ShardedFeature(setting[3], mesh, split_ratio=0.4,
                      host_offload=False)
  step = _trainer(mesh, setting, sf, with_edge=True,
                  cold_streaming=True)
  _, seeds_stack, n_valid, keys = _inputs()
  _, _, ref = res_step.superstep(*_copy((params, opt)), seeds_stack,
                                 n_valid, keys)
  _, _, got = step.superstep(*_copy((params, opt)), seeds_stack,
                             n_valid, keys)
  np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_superstep_parity_offloaded_spill(mesh, setting):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  sf = ShardedFeature(setting[3], mesh, split_ratio=0.4)
  assert sf.cold_array is not None
  step = _trainer(mesh, setting, sf)
  params = step.init_params(jax.random.key(0))
  opt = step.tx.init(params)
  seeds, seeds_stack, n_valid, keys = _inputs()
  _, _, ref = _run_sequential(step, *_copy((params, opt)), seeds, keys)
  _, _, got = step.superstep(*_copy((params, opt)), seeds_stack,
                             n_valid, keys)
  np.testing.assert_array_equal(ref, np.asarray(got))


def test_superstep_cold_streaming_parity(mesh, setting, resident):
  """A host-spilled store with NO in-program cold path trains through
  sample+stage+consume supersteps with results identical to the
  fully-resident fused superstep (same values, same RNG stream)."""
  res_step, params, opt = resident
  sf = ShardedFeature(setting[3], mesh, split_ratio=0.4,
                      host_offload=False)
  assert sf._spill and sf.cold_array is None
  step = _trainer(mesh, setting, sf, cold_streaming=True)
  _, seeds_stack, n_valid, keys = _inputs()
  p1, o1, ref = res_step.superstep(*_copy((params, opt)), seeds_stack,
                                   n_valid, keys)
  p2, o2, got = step.superstep(*_copy((params, opt)), seeds_stack,
                               n_valid, keys)
  np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
  for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
  # per-batch path cannot resolve cold rows in-program
  with pytest.raises(NotImplementedError):
    step(_copy(params), _copy(opt), np.arange(8 * BS) % N,
         np.full(8, BS), jax.random.split(jax.random.key(0), 8))


def test_cold_streaming_requires_spilled_store(mesh, setting):
  with pytest.raises(ValueError, match='cold_streaming'):
    _trainer(mesh, setting, ShardedFeature(setting[3], mesh),
             cold_streaming=True)


def test_superstep_zero_steady_state_recompiles(resident):
  step, params, opt = resident
  _, seeds_stack, n_valid, keys = _inputs()
  p, o = _copy((params, opt))
  p, o, _ = step.superstep(p, o, seeds_stack, n_valid, keys)
  traces = step.superstep_traces
  for _ in range(2):
    p, o, _ = step.superstep(p, o, seeds_stack, n_valid, keys)
  assert step.superstep_traces == traces  # zero steady-state recompiles
  # a ragged tail length compiles exactly once more
  _, tail_stack, tail_nv, tail_keys = _inputs(t=2)
  p, o, _ = step.superstep(p, o, tail_stack, tail_nv, tail_keys)
  assert step.superstep_traces == traces + 1


def test_run_epoch_engines_agree(mesh, setting, resident):
  """run_epoch over a DeviceEpochLoader: streaming (double-buffered
  stage thread) and fused engines produce identical losses for
  identical stores/keys, including the ragged tail superstep."""
  res_step, params, opt = resident
  sf = ShardedFeature(setting[3], mesh, split_ratio=0.4,
                      host_offload=False)
  stream_step = _trainer(mesh, setting, sf, cold_streaming=True)
  out = {}
  for name, step in [('fused', res_step), ('stream', stream_step)]:
    loader = step.make_epoch_loader(
        np.arange(N), superstep_len=K, shuffle=True,
        rng=np.random.default_rng(5))
    p, o = _copy((params, opt))
    p, o, losses = step.run_epoch(p, o, loader, jax.random.key(11))
    out[name] = np.asarray(losses)
  assert out['fused'].shape == (2, 8)  # 2 batches of 32 seeds over 64
  np.testing.assert_array_equal(out['fused'], out['stream'])
  assert np.isfinite(out['fused']).all()


# -- ops-level builder contract ------------------------------------------

def test_superstep_builder_threads_carry_and_stacks_aux():
  def body(params, opt, table, scratch, seeds, n_valid, key):
    params = params + seeds.sum() * n_valid
    table = table + 1
    return params, opt, table, scratch, params * 2

  run = superstep(body)
  p, o, t, s, aux = run(jnp.zeros(()), None, jnp.zeros((), jnp.int32),
                        jnp.zeros(()),
                        jnp.arange(6).reshape(3, 2).astype(jnp.float32),
                        jnp.ones((3,)), jnp.zeros((3,)))
  assert int(t) == 3                       # carry threaded through
  np.testing.assert_allclose(np.asarray(aux), [2., 12., 30.])
  assert float(p) == 15.                   # 1 + 5 + 9


# -- DeviceEpochLoader / staged padding ----------------------------------

def test_pad_seed_batch_is_the_node_loader_tail_rule():
  seeds = np.array([7, 3, 9], np.int64)
  padded, n_valid = pad_seed_batch(seeds, 8)
  assert n_valid == 3
  np.testing.assert_array_equal(padded, [7, 3, 9, 9, 9, 9, 9, 9])
  full, nv = pad_seed_batch(np.arange(8), 8)
  assert nv == 8 and np.array_equal(full, np.arange(8))
  with pytest.raises(ValueError):
    pad_seed_batch(np.array([], np.int64), 4)


def test_node_loader_tail_uses_shared_pad(mesh):
  from fixtures import ring_dataset
  ds = ring_dataset(num_nodes=20)
  from glt_tpu.loader import NeighborLoader
  loader = NeighborLoader(ds, [2], input_nodes=np.arange(10),
                          batch_size=8, shuffle=False)
  batches = list(loader)
  assert len(batches) == 2
  tail = batches[1]
  assert tail.metadata['n_valid'] == 2
  # the two valid seeds come through; fill slots (repeats of seed 9)
  # dedup away inside the sampler, which is why n_valid masks them
  np.testing.assert_array_equal(np.asarray(tail.batch)[:2], [8, 9])


def test_stack_epoch_batches_and_shard_n_valid():
  seeds = np.arange(10, dtype=np.int64)
  stack, nv = stack_epoch_batches(seeds, np.arange(10), 4,
                                  drop_last=False)
  assert stack.shape == (3, 4)
  np.testing.assert_array_equal(nv, [4, 4, 2])
  np.testing.assert_array_equal(stack[2], [8, 9, 9, 9])
  stack_d, nv_d = stack_epoch_batches(seeds, np.arange(10), 4,
                                      drop_last=True)
  assert stack_d.shape == (2, 4) and nv_d.tolist() == [4, 4]
  # global count 6 over 2 shards of 4: first shard full, second gets 2
  np.testing.assert_array_equal(
      shard_n_valid(np.array([6, 4]), 2, 4), [[4, 2], [4, 0]])


def test_device_epoch_loader_stages_and_windows():
  rng = np.random.default_rng(3)
  loader = DeviceEpochLoader(np.arange(37), batch_size=8,
                             superstep_len=2, num_shards=2,
                             shuffle=True, rng=rng)
  assert loader.batches_per_epoch == 5 and len(loader) == 3
  windows = list(loader)
  assert [w.length for w in windows] == [2, 2, 1]
  seen = []
  for w in windows:
    assert isinstance(w.seeds, jax.Array)
    assert w.seeds.shape == (w.length, 8)
    assert w.n_valid.shape == (w.length, 2)
    nv = np.asarray(w.n_valid)
    for t in range(w.length):
      valid = np.asarray(w.seeds[t])[:nv[t].sum()]
      seen.extend(valid.tolist())
  # one epoch = every seed exactly once (padding masked by n_valid)
  assert sorted(seen) == list(range(37))
  # tail window: 5 valid in the last batch -> shards get [4, 1]
  np.testing.assert_array_equal(np.asarray(windows[-1].n_valid), [[4, 1]])


def test_device_epoch_loader_shuffle_reproducible():
  a = DeviceEpochLoader(np.arange(16), 4, superstep_len=2, shuffle=True,
                        rng=np.random.default_rng(9))
  b = DeviceEpochLoader(np.arange(16), 4, superstep_len=2, shuffle=True,
                        rng=np.random.default_rng(9))
  for wa, wb in zip(a, b):
    np.testing.assert_array_equal(np.asarray(wa.seeds),
                                  np.asarray(wb.seeds))
  # successive epochs reshuffle
  first = np.asarray(next(iter(a)).seeds)
  second = np.asarray(next(iter(a)).seeds)
  assert not np.array_equal(first, second)


def test_device_epoch_loader_drop_last_superstep():
  loader = DeviceEpochLoader(np.arange(40), 8, superstep_len=3,
                             drop_last_superstep=True)
  windows = list(loader)
  assert [w.length for w in windows] == [3] and len(loader) == 1


# -- cold-row staging -----------------------------------------------------

def test_feature_stage_cold_rows():
  feats = np.arange(40, dtype=np.float32).reshape(10, 4)
  f = Feature(feats, split_ratio=0.5, host_offload=False)
  nodes = np.array([[1, 7, 9, 3], [8, 0, 2, 6]])
  counts = np.array([3, 2])  # trailing slots invalid
  out = f.stage_cold_rows(nodes, counts)
  assert out.shape == (2, 4, 4)
  np.testing.assert_array_equal(out[0, 1], feats[7])  # cold, valid
  np.testing.assert_array_equal(out[0, 2], feats[9])
  np.testing.assert_array_equal(out[0, 0], 0)         # hot lane
  np.testing.assert_array_equal(out[0, 3], 0)         # invalid lane
  np.testing.assert_array_equal(out[1, 0], feats[8])
  np.testing.assert_array_equal(out[1, 2], 0)         # invalid (count 2)


def test_sharded_stage_cold_rows(mesh):
  n, d = 32, 4
  feats = np.arange(n * d, dtype=np.float32).reshape(n, d)
  sf = ShardedFeature(feats, mesh, split_ratio=0.5, host_offload=False)
  assert sf._spill and sf.cold_array is None
  rps, hot = sf.rows_per_shard, sf.hot_count
  # [T=2, 8 shards * B=2 lanes]
  rng = np.random.default_rng(0)
  nodes = rng.integers(0, n, (2, 16))
  counts = np.tile(np.array([2, 2, 1, 2, 2, 0, 2, 2]), (2, 1))
  out = sf.stage_cold_rows(nodes, counts)
  assert out.shape == (2, 16, d)
  for t in range(2):
    for lane in range(16):
      dev, pos = lane // 2, lane % 2
      nid = nodes[t, lane]
      cold = (pos < counts[t, dev]) and (nid % rps >= hot)
      expect = feats[nid] if cold else np.zeros(d)
      np.testing.assert_array_equal(out[t, lane], expect)


def test_sharded_stage_cold_rows_rejects_resident(mesh):
  sf = ShardedFeature(np.eye(8, dtype=np.float32), mesh)
  with pytest.raises(ValueError, match='stage_cold_rows'):
    sf.stage_cold_rows(np.zeros((1, 8), np.int64), np.ones((1, 8)))

"""Graph storage behaviors: pickling and window-copy superseding.

Round-4 guarantees: Graph objects pickle across process boundaries
(mp channel payloads / checkpoints) despite the window lock, and the
window-DMA padded copy REPLACES the original edge array in HBM instead
of duplicating it (VERDICT r3 weak #4 — at papers100M scale a duplicate
edge array costs ~GBs).
"""
import pickle

import jax
import numpy as np
import pytest

from fixtures import ring_dataset

from glt_tpu.ops.sample import neighbor_probs


def test_graph_pickle_roundtrip():
  ds = ring_dataset(num_nodes=24)
  g = ds.get_graph()
  g.lazy_init()
  g.window_arrays(4, ('indices',))      # populate cache + lock usage
  g2 = pickle.loads(pickle.dumps(g))
  # lock recreated, caches cleared, arrays lazily rebuilt
  assert g2._window_lock is not None and g2._window_lock is not g._window_lock
  assert g2._window_cache == {}
  np.testing.assert_array_equal(np.asarray(g2.indptr),
                                np.asarray(g.topo.indptr))
  # device arrays were dropped from the pickle (re-placed on this
  # process's devices on first touch)
  assert g2.num_edges == g.num_edges
  w = g2.window_arrays(4, ('indices',))
  assert w['indices'].shape[0] == g2.num_edges + 4


def test_window_copy_supersedes_original():
  ds = ring_dataset(num_nodes=20)
  g = ds.get_graph()
  e = g.num_edges
  w = g.window_arrays(4, ('indices', 'edge_ids'))
  # ONE resident copy: the property now returns the padded array itself
  assert g.indices is w['indices']
  assert g.edge_ids is w['edge_ids']
  assert g.indices.shape[0] == e + 4
  np.testing.assert_array_equal(np.asarray(g.indices)[e:], -1)
  # growing the width rebuilds from the logical prefix, not the old pad
  w2 = g.window_arrays(7, ('indices',))
  assert g.indices is w2['indices']
  assert g.indices.shape[0] == e + 7
  np.testing.assert_array_equal(np.asarray(w2['indices'])[:e],
                                np.asarray(w['indices'])[:e])
  # a smaller later width reuses the grown copy
  w3 = g.window_arrays(3, ('indices',))
  assert w3['indices'] is w2['indices']


def test_sampling_parity_after_window_supersede():
  from glt_tpu.sampler import NeighborSampler
  ds = ring_dataset(num_nodes=30)
  g = ds.get_graph()
  s = NeighborSampler(g, [2, 2], with_edge=True, seed=5)
  key = jax.random.key(7)
  seeds = np.arange(0, 30, 3)
  before = s.sample_from_nodes(seeds, key=key)
  g.window_arrays(5, ('indices', 'edge_ids'))  # padded copies take over
  s2 = NeighborSampler(g, [2, 2], with_edge=True, seed=5)
  after = s2.sample_from_nodes(seeds, key=key)
  for k in ('node', 'row', 'col', 'edge'):
    np.testing.assert_array_equal(np.asarray(getattr(before, k)),
                                  np.asarray(getattr(after, k)), k)


def test_neighbor_probs_pad_safe():
  ds = ring_dataset(num_nodes=16)
  g = ds.get_graph()
  probs = np.zeros(16, np.float32)
  probs[:4] = 1.0
  want = np.asarray(neighbor_probs(np.asarray(g.topo.indptr),
                                   np.asarray(g.topo.indices),
                                   probs, 2, 16))
  g.window_arrays(6, ('indices',))     # sentinel tail now on g.indices
  got = np.asarray(neighbor_probs(g.indptr, g.indices, probs, 2, 16))
  np.testing.assert_allclose(got, want, rtol=1e-6)

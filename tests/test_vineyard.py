"""Vineyard connector contract tests — the full loader surface driven
through InMemoryFragmentStore (the FragmentClient reference
implementation), so a real vineyard adapter only has to satisfy the
same five-method contract (reference v6d/vineyard_utils.cc:318)."""
import numpy as np
import pytest

from glt_tpu.data.vineyard_utils import (
    InMemoryFragmentStore, get_frag_vertex_num, get_frag_vertex_offset,
    load_edge_feature_from_vineyard, load_vertex_feature_from_vineyard,
    load_vineyard_dataset, vineyard_to_csr,
)

from fixtures import ring_edges


@pytest.fixture()
def store():
  """Ring graph over 20 nodes split into 2 fragments of 10 by source."""
  rows, cols, eids = ring_edges(20)
  s = InMemoryFragmentStore()
  for fid, off in ((0, 0), (1, 10)):
    m = (rows >= off) & (rows < off + 10)
    s.add_fragment(
        fid, 'person', 'knows', offset=off, num_vertices=10,
        edge_index=np.stack([rows[m], cols[m]]), edge_ids=eids[m],
        vertex_feats={'age': np.arange(off, off + 10, dtype=np.float32),
                      'w': np.full(10, float(fid), np.float32)},
        edge_feats={'since': eids[m].astype(np.float32)})
  return s


def test_vineyard_to_csr_window_local(store):
  indptr, indices, eids = vineyard_to_csr(store, 0, 'person', 'knows')
  indptr = np.asarray(indptr)
  assert indptr.shape[0] == 11 and indptr[-1] == 20  # 10 nodes x deg 2
  # node v's neighbors are (v+1, v+2) mod 20, in adjacency order
  for v in range(10):
    nb = np.asarray(indices)[indptr[v]:indptr[v + 1]]
    assert set(nb.tolist()) == {(v + 1) % 20, (v + 2) % 20}
  # edge ids preserved: node v's out-edges are 2v, 2v+1
  got = np.asarray(eids)[indptr[3]:indptr[4]]
  assert set(got.tolist()) == {6, 7}


def test_vineyard_feature_columns(store):
  f = load_vertex_feature_from_vineyard(store, 1, ['age', 'w'],
                                        'person')
  np.testing.assert_allclose(f[:, 0], np.arange(10, 20))
  np.testing.assert_allclose(f[:, 1], 1.0)
  ef = load_edge_feature_from_vineyard(store, 0, ['since'], 'knows')
  assert ef.shape == (20, 1)


def test_vineyard_offsets(store):
  assert get_frag_vertex_offset(store, 1, 'person') == 10
  assert get_frag_vertex_num(store, 1, 'person') == 10


def test_vineyard_dataset_roundtrip_and_sampling(store):
  """Fragments -> Dataset -> NeighborSampler: the end-to-end path the
  reference's vineyard deployment uses."""
  from glt_tpu.sampler import NeighborSampler
  ds = load_vineyard_dataset(store, [0, 1], 'person', 'knows',
                             vcols=['age'])
  g = ds.get_graph()
  assert g.num_edges == 40 and g.num_nodes == 20
  feat = ds.get_node_feature()
  np.testing.assert_allclose(feat[np.arange(20)][:, 0], np.arange(20))
  s = NeighborSampler(g, [2], seed=0)
  out = s.sample_from_nodes(np.array([0, 15]))
  nodes = np.asarray(out.node)[:int(out.node_count)]
  assert set(nodes.tolist()) == {0, 15, 1, 2, 16, 17}


def test_socket_path_requires_client():
  # ImportError without the vineyard package; NotImplementedError where
  # it is installed (the socket adapter is the remaining seam)
  with pytest.raises((ImportError, NotImplementedError)):
    vineyard_to_csr('/tmp/vineyard.sock', 0, 'person', 'knows')

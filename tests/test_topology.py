import numpy as np
import pytest

from glt_tpu.data import Topology
from glt_tpu.typing import GraphMode
from glt_tpu.data import Graph


def test_coo_to_csr_basic():
  # 4 nodes: 0->1, 0->2, 1->2, 3->0  (given shuffled)
  ei = np.array([[1, 3, 0, 0], [2, 0, 2, 1]])
  topo = Topology(edge_index=ei, layout='CSR', num_nodes=4)
  assert topo.layout == 'CSR'
  np.testing.assert_array_equal(topo.indptr, [0, 2, 3, 3, 4])
  np.testing.assert_array_equal(topo.indices, [1, 2, 2, 0])
  # edge_ids map compressed slots back to original COO positions
  np.testing.assert_array_equal(topo.edge_ids, [3, 2, 0, 1])
  np.testing.assert_array_equal(topo.degrees, [2, 1, 0, 1])


def test_coo_to_csc():
  ei = np.array([[1, 3, 0, 0], [2, 0, 2, 1]])
  topo = Topology(edge_index=ei, layout='CSC', num_nodes=4)
  # in-edges: node0 <- 3; node1 <- 0; node2 <- 0, 1
  np.testing.assert_array_equal(topo.indptr, [0, 1, 2, 4, 4])
  np.testing.assert_array_equal(topo.indices, [3, 0, 0, 1])


def test_columns_sorted_within_rows():
  rng = np.random.default_rng(0)
  n, e = 50, 400
  ei = rng.integers(0, n, size=(2, e))
  topo = Topology(edge_index=ei, num_nodes=n)
  for v in range(n):
    seg = topo.indices[topo.indptr[v]:topo.indptr[v + 1]]
    assert np.all(np.diff(seg) >= 0)


def test_edge_ids_and_weights_follow_permutation():
  ei = np.array([[2, 0, 1], [0, 1, 0]])
  eids = np.array([10, 11, 12])
  w = np.array([0.5, 0.25, 0.125], dtype=np.float32)
  topo = Topology(edge_index=ei, edge_ids=eids, edge_weights=w, num_nodes=3)
  # CSR order: (0->1, id 11, w .25), (1->0, id 12, w .125), (2->0, id 10, w .5)
  np.testing.assert_array_equal(topo.edge_ids, [11, 12, 10])
  np.testing.assert_allclose(topo.edge_weights, [0.25, 0.125, 0.5])


def test_flip_layout_roundtrip():
  rng = np.random.default_rng(1)
  ei = rng.integers(0, 30, size=(2, 200))
  csr = Topology(edge_index=ei, layout='CSR', num_nodes=30)
  csc = csr.flip_layout()
  assert csc.layout == 'CSC'
  back = csc.flip_layout()
  np.testing.assert_array_equal(back.indptr, csr.indptr)
  np.testing.assert_array_equal(back.indices, csr.indices)
  np.testing.assert_array_equal(back.edge_ids, csr.edge_ids)
  # edge set is identical: (src,dst,eid) triples match
  src_a, dst_a, id_a = csr.to_coo()
  dst_b, src_b, id_b = csc.to_coo()
  tri_a = sorted(zip(src_a.tolist(), dst_a.tolist(), id_a.tolist()))
  tri_b = sorted(zip(src_b.tolist(), dst_b.tolist(), id_b.tolist()))
  assert tri_a == tri_b


def test_csr_input_passthrough():
  indptr = np.array([0, 2, 3, 3])
  indices = np.array([2, 1, 0])
  topo = Topology(indptr=indptr, indices=indices, layout='CSR')
  np.testing.assert_array_equal(topo.indptr, indptr)
  # columns get sorted within rows
  np.testing.assert_array_equal(topo.indices, [1, 2, 0])
  np.testing.assert_array_equal(topo.edge_ids, [1, 0, 2])


def test_graph_device_arrays():
  ei = np.array([[0, 1], [1, 0]])
  topo = Topology(edge_index=ei, num_nodes=2)
  g = Graph(topo, mode=GraphMode.HBM)
  assert g.num_nodes == 2 and g.num_edges == 2
  np.testing.assert_array_equal(np.asarray(g.indptr), [0, 1, 2])
  np.testing.assert_array_equal(g.degree(np.array([0, 1])), [1, 1])


def test_isolated_node_padding():
  ei = np.array([[0], [1]])
  topo = Topology(edge_index=ei, num_nodes=5)
  assert topo.indptr.shape[0] == 6
  np.testing.assert_array_equal(topo.degrees, [1, 0, 0, 0, 0])


# -- property tests: the compaction foundation ---------------------------
# The stream subsystem's compactor rebuilds CSRs through to_coo() /
# flip_layout() / the constructor's _sort_within_rows; these randomized
# invariants are what make that merge safe on real (duplicate- and
# self-edge-bearing) graphs.

def _random_multigraph(rng, n, e, self_loop_frac=0.1, dup_frac=0.3):
  """COO with intentional self loops and exact duplicate edges."""
  src = rng.integers(0, n, size=e)
  dst = rng.integers(0, n, size=e)
  loops = rng.random(e) < self_loop_frac
  dst[loops] = src[loops]
  n_dup = int(e * dup_frac)
  if n_dup:
    pick = rng.integers(0, e, size=n_dup)
    src = np.concatenate([src, src[pick]])
    dst = np.concatenate([dst, dst[pick]])
  return np.stack([src, dst])


def _triples(topo):
  """Canonical (src, dst, eid) multiset regardless of layout."""
  ptr, other, eids = topo.to_coo()
  if topo.layout == 'CSR':
    src, dst = ptr, other
  else:
    src, dst = other, ptr
  return sorted(zip(src.tolist(), dst.tolist(), eids.tolist()))


@pytest.mark.parametrize('trial', range(5))
def test_property_to_coo_roundtrip_multigraph(trial):
  """to_coo -> constructor reproduces the identical compressed form,
  and the (src, dst, eid) multiset is preserved exactly — duplicate
  and self edges included."""
  rng = np.random.default_rng(100 + trial)
  n = int(rng.integers(3, 60))
  e = int(rng.integers(1, 6 * n))
  ei = _random_multigraph(rng, n, e)
  layout = 'CSR' if trial % 2 == 0 else 'CSC'
  topo = Topology(edge_index=ei, layout=layout, num_nodes=n)
  ptr, other, eids = topo.to_coo()
  rebuilt = Topology(
      edge_index=np.stack([ptr, other] if layout == 'CSR'
                          else [other, ptr]),
      edge_ids=eids, layout=layout, num_nodes=n)
  np.testing.assert_array_equal(rebuilt.indptr, topo.indptr)
  np.testing.assert_array_equal(rebuilt.indices, topo.indices)
  np.testing.assert_array_equal(rebuilt.edge_ids, topo.edge_ids)
  # the original COO multiset survives (eids map back to input slots)
  orig = sorted(zip(ei[0].tolist(), ei[1].tolist(),
                    range(ei.shape[1])))
  assert _triples(topo) == orig


@pytest.mark.parametrize('trial', range(5))
def test_property_flip_layout_involution_multigraph(trial):
  """flip twice == identity, and one flip preserves the edge multiset,
  on graphs with duplicates and self loops."""
  rng = np.random.default_rng(200 + trial)
  n = int(rng.integers(3, 50))
  ei = _random_multigraph(rng, n, int(rng.integers(1, 5 * n)))
  csr = Topology(edge_index=ei, layout='CSR', num_nodes=n)
  csc = csr.flip_layout()
  assert csc.layout == 'CSC'
  assert _triples(csc) == _triples(csr)
  back = csc.flip_layout()
  np.testing.assert_array_equal(back.indptr, csr.indptr)
  np.testing.assert_array_equal(back.indices, csr.indices)
  np.testing.assert_array_equal(back.edge_ids, csr.edge_ids)
  if csr.edge_weights is not None:
    np.testing.assert_array_equal(back.edge_weights, csr.edge_weights)


@pytest.mark.parametrize('trial', range(5))
def test_property_sort_within_rows_stable_on_duplicates(trial):
  """_sort_within_rows: ascending columns per row, slot permutation is
  a bijection, and equal columns keep their input order (lexsort is
  stable) — the invariant that keeps duplicate edges' ids/weights
  aligned through compaction."""
  from glt_tpu.data.topology import _sort_within_rows
  rng = np.random.default_rng(300 + trial)
  n = int(rng.integers(2, 30))
  deg = rng.integers(0, 8, size=n)
  indptr = np.zeros(n + 1, np.int64)
  np.cumsum(deg, out=indptr[1:])
  e = int(indptr[-1])
  # few distinct columns -> many duplicates within a row
  indices = rng.integers(0, max(n // 2, 1), size=e)
  out_ptr, out_idx, perm = _sort_within_rows(indptr, indices.copy())
  np.testing.assert_array_equal(out_ptr, indptr)
  assert sorted(perm.tolist()) == list(range(e))  # bijection
  np.testing.assert_array_equal(out_idx, indices[perm])
  for v in range(n):
    lo, hi = indptr[v], indptr[v + 1]
    seg = out_idx[lo:hi]
    assert np.all(np.diff(seg) >= 0)
    seg_perm = perm[lo:hi]
    assert np.all((seg_perm >= lo) & (seg_perm < hi))  # row-local
    # stability: among equal column values, original slot order holds
    for c in np.unique(seg):
      slots = seg_perm[seg == c]
      assert np.all(np.diff(slots) > 0)

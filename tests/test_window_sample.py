"""Window read path of sample_neighbors (window=(W, H)).

Contract under test: offsets are drawn identically in both read paths,
so for ANY graph the window path's outputs are BIT-IDENTICAL to the
element-gather path's, provided H >= the frontier's hub-row count and
the window source carries >= W padding slots (sample.py docstring).
Covers: hub fix-up rows (deg > W), tail rows whose window crosses the
end of the real edge array (the CLIP start-shift hazard the padding
exists for), seed_mask, edge_ids, and replace=True.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from glt_tpu.ops.sample import sample_neighbors


def _csr(degrees):
  rng = np.random.default_rng(7)
  indptr = np.zeros(len(degrees) + 1, np.int32)
  np.cumsum(degrees, out=indptr[1:])
  num_edges = int(indptr[-1])
  indices = rng.integers(0, len(degrees), num_edges).astype(np.int32)
  return jnp.asarray(indptr), jnp.asarray(indices)


def _padded(indices, w):
  return jnp.concatenate(
      [indices, jnp.full((w,), -1, indices.dtype)])


W = 8
K = 4


@pytest.fixture(scope='module')
def graph():
  # degrees: zeros, sub-fanout, mid, exactly W, hubs (> W); the LAST
  # node has deg < W so its window crosses the array end (tail hazard)
  degrees = np.array([0, 2, 5, W, 20, 3, 17, 1, W - 1, 6], np.int64)
  return _csr(degrees)


def _run(graph, key, *, window, seed_mask=None, edge_ids=None,
         replace=False):
  indptr, indices = graph
  seeds = jnp.arange(indptr.shape[0] - 1, dtype=jnp.int32)
  kw = {}
  if window is not None:
    kw = dict(window=window, indices_win=_padded(indices, W),
              edge_ids_win=(_padded(edge_ids, W)
                            if edge_ids is not None else None))
  return sample_neighbors(indptr, indices, seeds, K, key,
                          seed_mask=seed_mask, edge_ids=edge_ids,
                          replace=replace, **kw)


def _assert_identical(a, b):
  np.testing.assert_array_equal(np.asarray(a.mask), np.asarray(b.mask))
  m = np.asarray(a.mask)
  np.testing.assert_array_equal(np.asarray(a.nbrs)[m],
                                np.asarray(b.nbrs)[m])
  np.testing.assert_array_equal(np.asarray(a.eids)[m],
                                np.asarray(b.eids)[m])


def test_bit_identical_to_element_path(graph):
  key = jax.random.key(0)
  base = _run(graph, key, window=None)
  winp = _run(graph, key, window=(W, 2))  # 2 hubs: deg 20 and 17
  _assert_identical(base, winp)


def test_hub_capacity_from_graph_is_sufficient(graph):
  indptr, _ = graph
  deg = np.asarray(indptr[1:] - indptr[:-1])
  n_hub = int((deg > W).sum())
  assert n_hub == 2
  key = jax.random.key(3)
  base = _run(graph, key, window=None)
  winp = _run(graph, key, window=(W, n_hub))
  _assert_identical(base, winp)


def test_no_hubs_pure_window():
  degrees = np.array([3, 1, 0, W, 5, 2], np.int64)
  g = _csr(degrees)
  key = jax.random.key(1)
  _assert_identical(_run(g, key, window=None),
                    _run(g, key, window=(W, 0)))


def test_seed_mask_and_edge_ids(graph):
  indptr, indices = graph
  key = jax.random.key(2)
  mask = jnp.asarray(np.arange(indptr.shape[0] - 1) % 2 == 0)
  eids = jnp.arange(indices.shape[0], dtype=jnp.int32) * 10
  base = _run(graph, key, window=None, seed_mask=mask, edge_ids=eids)
  winp = _run(graph, key, window=(W, 2), seed_mask=mask, edge_ids=eids)
  _assert_identical(base, winp)


def test_replace_path(graph):
  key = jax.random.key(4)
  base = _run(graph, key, window=None, replace=True)
  winp = _run(graph, key, window=(W, 2), replace=True)
  _assert_identical(base, winp)


def test_all_hub_frontier():
  # every row's degree exceeds W: the whole batch rides the fix-up
  degrees = np.full(6, 3 * W, np.int64)
  g = _csr(degrees)
  key = jax.random.key(6)
  _assert_identical(_run(g, key, window=None),
                    _run(g, key, window=(W, 6)))


def test_window_at_least_max_degree_has_zero_hubs(graph):
  # W >= max degree: H=0 is sufficient, no fix-up rows at all
  indptr, indices = graph
  max_deg = int(np.max(np.asarray(indptr[1:] - indptr[:-1])))
  seeds = jnp.arange(indptr.shape[0] - 1, dtype=jnp.int32)
  key = jax.random.key(7)
  base = sample_neighbors(indptr, indices, seeds, K, key)
  winp = sample_neighbors(
      indptr, indices, seeds, K, key, window=(max_deg, 0),
      indices_win=_padded(indices, max_deg))
  _assert_identical(base, winp)


def test_empty_frontier(graph):
  indptr, indices = graph
  out = sample_neighbors(indptr, indices, jnp.zeros((0,), jnp.int32),
                         K, jax.random.key(8), window=(W, 2),
                         indices_win=_padded(indices, W))
  assert out.nbrs.shape == (0, K)
  assert out.mask.shape == (0, K)
  assert int(out.nbrs_num.sum()) == 0


def test_undersized_hub_capacity_raises_eagerly(graph):
  # the docstring guarantee (H >= true hub count) is now CHECKED on
  # eager calls: 2 hubs in this frontier, H=1 must fail loudly
  with pytest.raises(ValueError, match='underestimates'):
    _run(graph, jax.random.key(9), window=(W, 1))


def test_jit_and_undersized_hub_capacity_only_affects_hubs(graph):
  # H smaller than the hub count: non-hub rows must still be exact
  # (the documented failure mode is confined to unfixed hub rows)
  indptr, _ = graph
  key = jax.random.key(5)
  base = _run(graph, key, window=None)
  winp = jax.jit(
      lambda: _run(graph, key, window=(W, 1)))()
  deg = np.asarray(indptr[1:] - indptr[:-1])
  nonhub = deg <= W
  m = np.asarray(base.mask)[nonhub]
  np.testing.assert_array_equal(
      np.asarray(winp.nbrs)[nonhub][m], np.asarray(base.nbrs)[nonhub][m])

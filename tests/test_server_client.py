"""Mp-mode and server-client-mode tests with real subprocesses + sockets
(the reference's multi-process-on-one-host strategy,
test_dist_neighbor_loader.py / server-client tests)."""
import multiprocessing as mp
import time

import numpy as np
import pytest

from glt_tpu.sampler.base import SamplingConfig


def build_ring_dataset():
  """Module-level picklable dataset builder for spawned workers."""
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from fixtures import ring_dataset
  ds = ring_dataset(num_nodes=40, feat_dim=4)
  ds.random_node_split(num_val=0.25, num_test=0.25, seed=3)
  return ds




def _free_port_base(n=2):
  """Reserve n consecutive-ish free ports via OS assignment; returns a
  base such that base..base+n-1 are (momentarily) free."""
  import socket
  socks, ports = [], []
  for _ in range(n):
    s = socket.socket()
    s.bind(('127.0.0.1', 0))
    socks.append(s)
    ports.append(s.getsockname()[1])
  for s in socks:
    s.close()
  return ports


def _free_consecutive_base(span=2, tries=50):
  import socket
  for _ in range(tries):
    s = socket.socket(); s.bind(('127.0.0.1', 0))
    base = s.getsockname()[1]; s.close()
    ok = True
    for k in range(span):
      t = socket.socket()
      try:
        t.bind(('127.0.0.1', base + k))
      except OSError:
        ok = False
      finally:
        t.close()
      if not ok:
        break
    if ok:
      return base
  raise RuntimeError('no consecutive free ports found')

def test_rpc_roundtrip():
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  srv = RpcServer()
  srv.register('add', lambda a, b: a + b)
  srv.register('boom', lambda: (_ for _ in ()).throw(ValueError('x')))
  cli = RpcClient(srv.host, srv.port)
  assert cli.request('add', 2, 3) == 5
  fut = cli.async_request('add', 10, 20)
  assert fut.result(timeout=10) == 30
  with pytest.raises(ValueError):
    cli.request('boom')
  cli.close()
  srv.stop()


def test_mp_neighbor_loader_epoch():
  from glt_tpu.distributed import MpDistSamplingWorkerOptions, \
      MpNeighborLoader
  loader = MpNeighborLoader(
      build_ring_dataset, [2], input_nodes=np.arange(40),
      batch_size=8, collect_features=True,
      worker_options=MpDistSamplingWorkerOptions(num_workers=2),
      seed=0)
  try:
    batches = list(loader)
    # 2 workers x 20 seeds each -> 3 batches per worker (8,8,4-padded)
    assert len(batches) == 6
    seen = set()
    for b in batches:
      nv = b.metadata['n_valid']
      batch_nodes = np.asarray(b.batch)[:nv]
      seen.update(batch_nodes.tolist())
      nc = int(b.node_count)
      nodes = np.asarray(b.node)[:nc]
      # features value-encoded
      np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], nodes)
      np.testing.assert_array_equal(np.asarray(b.y)[:nv],
                                    batch_nodes % 4)
    assert seen == set(range(40))
    # second epoch works too
    assert len(list(loader)) == 6
  finally:
    loader.shutdown()


def test_mp_loader_edge_features_value_encoded():
  """Edge features ride the channel path: the ring fixture value-encodes
  edge feature row e as [e]*4, so batch.edge_attr must equal the eids."""
  from glt_tpu.distributed import MpDistSamplingWorkerOptions, \
      MpNeighborLoader
  loader = MpNeighborLoader(
      build_ring_dataset, [2], input_nodes=np.arange(40),
      batch_size=8, collect_features=True, with_edge=True,
      worker_options=MpDistSamplingWorkerOptions(num_workers=2),
      seed=0)
  try:
    saw_edges = 0
    for b in loader:
      assert b.edge is not None and b.edge_attr is not None
      em = np.asarray(b.edge_mask)
      eids = np.asarray(b.edge)[em]
      ea = np.asarray(b.edge_attr)[em]
      np.testing.assert_allclose(ea[:, 0], eids)
      saw_edges += em.sum()
    assert saw_edges > 0
  finally:
    loader.shutdown()


def test_mp_loader_abandoned_epoch_no_leak():
  """Leftover messages from a partially-consumed epoch must be filtered
  out of the next epoch (epoch tags, channel_loader epoch filter)."""
  from glt_tpu.distributed import MpDistSamplingWorkerOptions, \
      MpNeighborLoader
  loader = MpNeighborLoader(
      build_ring_dataset, [2], input_nodes=np.arange(40),
      batch_size=8, collect_features=True,
      worker_options=MpDistSamplingWorkerOptions(num_workers=2),
      seed=0)
  try:
    it = iter(loader)
    next(it)
    next(it)  # consume 2 of 6 batches, then abandon the epoch
    time.sleep(1.0)  # let workers finish buffering epoch-0 leftovers
    batches = list(loader)  # epoch 1 must see exactly its own 6 batches
    assert len(batches) == 6
    seen = set()
    for b in batches:
      seen.update(np.asarray(b.batch)[:b.metadata['n_valid']].tolist())
    assert seen == set(range(40))
  finally:
    loader.shutdown()


def _server_proc(rank, port, ready, done):
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from glt_tpu.distributed import init_server, wait_and_shutdown_server
  ds = build_ring_dataset()
  init_server(num_servers=2, num_clients=1, server_rank=rank,
              dataset=ds, master_port=port,
              dataset_builder=build_ring_dataset)
  ready.set()
  wait_and_shutdown_server(poll_s=0.1)
  done.set()


def test_server_client_mode():
  from glt_tpu.channel import pack_message, unpack_message
  ctx = mp.get_context('spawn')
  port = 47123
  readies = [ctx.Event() for _ in range(2)]
  dones = [ctx.Event() for _ in range(2)]
  # NOT daemonic: servers must spawn sampling worker children
  servers = [ctx.Process(target=_server_proc,
                         args=(r, port, readies[r], dones[r]))
             for r in range(2)]
  for s in servers:
    s.start()
  for e in readies:
    assert e.wait(timeout=60), 'server did not come up'

  from glt_tpu.distributed import (
      RemoteDistSamplingWorkerOptions, RemoteNeighborLoader, init_client,
      request_server, shutdown_client,
  )
  init_client(num_servers=2, num_clients=1, client_rank=0,
              master_port=port)
  try:
    meta = request_server(0, 'get_dataset_meta')
    assert meta['num_nodes'] == 40 and not meta['is_hetero']
    # data plane
    out = unpack_message(request_server(
        0, 'get_node_feature', pack_message({'ids': np.array([3, 7])})))
    np.testing.assert_allclose(out['feats'][:, 0], [3, 7])
    assert request_server(0, 'get_tensor_size') == (40, 4)

    # remote sampling: server 0 serves seeds 0..19, server 1 20..39;
    # with_edge also rides the remote path (efeats collated server-side)
    loader = RemoteNeighborLoader(
        [2], [np.arange(20), np.arange(20, 40)], batch_size=5,
        with_edge=True,
        worker_options=RemoteDistSamplingWorkerOptions(
            server_rank=[0, 1], prefetch_size=2),
        seed=1)
    seen = set()
    count = 0
    for b in loader:
      count += 1
      nv = b.metadata['n_valid']
      seen.update(np.asarray(b.batch)[:nv].tolist())
      # ring fixture value-encodes edge features: row e == [e]*4
      em = np.asarray(b.edge_mask)
      assert b.edge is not None and b.edge_attr is not None
      np.testing.assert_allclose(np.asarray(b.edge_attr)[em][:, 0],
                                 np.asarray(b.edge)[em])
    assert count == 8  # 4 batches per server
    assert seen == set(range(40))
    # second epoch
    assert sum(1 for _ in loader) == 8

    # split-name seeding: each server materializes its OWN train split
    # (RemoteNodeSplitSamplerInput parity)
    split_loader = RemoteNeighborLoader(
        [2], 'train', batch_size=5,
        worker_options=RemoteDistSamplingWorkerOptions(
            server_rank=[0, 1], prefetch_size=2, worker_key='bysplit'),
        seed=2)
    seen2 = []
    for b in split_loader:
      nv = b.metadata['n_valid']
      seen2.extend(np.asarray(b.batch)[:nv].tolist())
    # both servers share the same dataset here, so each contributes the
    # same 20-node train split
    import collections
    counts = collections.Counter(seen2)
    assert len(counts) == 20 and set(counts.values()) == {2}
  finally:
    shutdown_client()
  for i, s in enumerate(servers):
    assert dones[i].wait(timeout=30), 'server did not exit cleanly'
    s.join(timeout=10)


def test_dist_random_partitioner_two_ranks(tmp_path):
  """Two ranks partition their slices online, pushing rows to owners
  over rpc; the merged result covers every edge exactly once."""
  import threading
  from glt_tpu.distributed import DistRandomPartitioner
  from fixtures import ring_edges
  rows, cols, eids = ring_edges(40)
  feats = np.tile(np.arange(40, dtype=np.float32)[:, None], (1, 4))
  # rank slices: first/second half of edges; node features split evenly
  halves = [slice(0, 40), slice(40, 80)]
  nodes_halves = [np.arange(0, 20), np.arange(20, 40)]
  parts = []
  errs = []

  base_port = _free_consecutive_base(2)

  def run_rank(r):
    try:
      p = DistRandomPartitioner(
          str(tmp_path), rank=r, world_size=2, num_nodes=40,
          edge_slice=np.stack([rows[halves[r]], cols[halves[r]]]),
          eid_slice=eids[halves[r]],
          node_ids=nodes_halves[r], node_feat=feats[nodes_halves[r]],
          master_port=base_port)
      parts.append(p)
      p.partition()
    except Exception as e:
      errs.append(e)

  threads = [threading.Thread(target=run_rank, args=(r,))
             for r in range(2)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(timeout=180)
  alive = [t for t in threads if t.is_alive()]
  for p in parts:
    p.shutdown()
  assert not errs, errs
  assert not alive, 'partitioner ranks did not finish'

  node_pb = np.load(str(tmp_path / 'node_pb.npy'))
  seen_eids, seen_nodes = [], []
  for r in range(2):
    z = np.load(str(tmp_path / f'part{r}' / 'graph' / 'data.npz'))
    # ownership: every stored edge's src belongs to this rank
    np.testing.assert_array_equal(node_pb[z['rows']], r)
    seen_eids.append(z['eids'])
    nf = np.load(str(tmp_path / f'part{r}' / 'node_feat' / 'data.npz'))
    np.testing.assert_array_equal(node_pb[nf['ids']], r)
    np.testing.assert_allclose(nf['feats'][:, 0], nf['ids'])
    seen_nodes.append(nf['ids'])
  np.testing.assert_array_equal(np.sort(np.concatenate(seen_eids)),
                                np.arange(80))
  np.testing.assert_array_equal(np.sort(np.concatenate(seen_nodes)),
                                np.arange(40))


def test_dist_partitioner_output_loads(tmp_path):
  """The online partitioner's output must round-trip through
  load_partition / DistDataset.load (review regression)."""
  import threading
  from glt_tpu.distributed import DistDataset, DistRandomPartitioner
  from fixtures import ring_edges
  import os
  rows, cols, eids = ring_edges(40)
  feats = np.tile(np.arange(40, dtype=np.float32)[:, None], (1, 4))
  base_port = _free_consecutive_base(2)
  parts, errs = [], []

  def run_rank(r):
    try:
      sl = slice(r * 40, (r + 1) * 40)
      p = DistRandomPartitioner(
          str(tmp_path), rank=r, world_size=2, num_nodes=40,
          edge_slice=np.stack([rows[sl], cols[sl]]), eid_slice=eids[sl],
          node_ids=np.arange(r * 20, (r + 1) * 20),
          node_feat=feats[r * 20:(r + 1) * 20], master_port=base_port)
      parts.append(p)
      p.partition()
    except Exception as e:
      errs.append(e)

  threads = [threading.Thread(target=run_rank, args=(r,))
             for r in range(2)]
  for t in threads: t.start()
  for t in threads: t.join(timeout=180)
  alive = [t for t in threads if t.is_alive()]
  for p in parts: p.shutdown()
  assert not errs, errs
  assert not alive, 'partitioner ranks did not finish'

  ds = DistDataset().load(str(tmp_path), 0)
  assert ds.num_partitions == 2
  owned = np.nonzero(ds.node_pb.table == 0)[0]
  np.testing.assert_allclose(ds.get_node_feature()[owned][:, 0], owned)


def test_dist_table_dataset(tmp_path):
  """DistTableDataset: two ranks stream disjoint table slices, partition
  online, and load their partitions (review regression: no duplicate
  zero rows, disjoint global eids)."""
  import threading
  from glt_tpu.distributed import DistTableDataset
  from fixtures import ring_edges
  import os
  rows, cols, eids = ring_edges(40)
  feats = np.tile(np.arange(40, dtype=np.float32)[:, None], (1, 4))
  base_port = _free_consecutive_base(2)
  out, errs = {}, []

  def run_rank(r):
    try:
      sl = slice(r * 40, (r + 1) * 40)
      ids = np.arange(r * 20, (r + 1) * 20)
      ds = DistTableDataset().load_tables(
          edge_reader=[(rows[sl], cols[sl])],
          node_reader=[(ids, feats[ids])],
          rank=r, world_size=2, num_nodes=40,
          output_dir=str(tmp_path), edge_id_offset=r * 40,
          master_port=base_port)
      out[r] = ds
    except Exception as e:
      errs.append(e)

  threads = [threading.Thread(target=run_rank, args=(r,))
             for r in range(2)]
  for t in threads: t.start()
  for t in threads: t.join(timeout=180)
  alive = [t for t in threads if t.is_alive()]
  assert not errs, errs
  assert not alive, 'partitioner ranks did not finish'
  node_pb = np.load(str(tmp_path / 'node_pb.npy'))
  for r in range(2):
    ds = out[r]
    owned = np.nonzero(node_pb == r)[0]
    got = ds.get_node_feature()[owned]
    np.testing.assert_allclose(got[:, 0], owned)   # no zero clobbering
  # eids globally disjoint and complete
  all_eids = np.concatenate([
      np.load(str(tmp_path / f'part{r}' / 'graph' / 'data.npz'))['eids']
      for r in range(2)])
  assert np.unique(all_eids).shape[0] == 80


def test_mp_loader_dead_worker_times_out_cleanly():
  """Failure detection: if sampling workers die mid-epoch, the consumer
  gets a clean QueueTimeoutError instead of hanging (the reference's
  MP_STATUS_CHECK watchdog behavior)."""
  from glt_tpu.channel import QueueTimeoutError
  from glt_tpu.distributed import MpDistSamplingWorkerOptions, \
      MpNeighborLoader
  loader = MpNeighborLoader(
      build_ring_dataset, [2], input_nodes=np.arange(40),
      batch_size=8, collect_features=False,
      worker_options=MpDistSamplingWorkerOptions(
          num_workers=1, rpc_timeout=25.0),
      seed=0)
  try:
    it = iter(loader)
    first = next(it)                   # epoch running
    # kill the worker hard mid-epoch
    for w in loader.producer._workers:
      w.terminate()
      w.join(timeout=10)
    with pytest.raises((QueueTimeoutError, StopIteration)):
      # drain: either the remaining buffered batches end cleanly via
      # StopIteration (epoch end marker was already queued) or the
      # consumer times out — never a hang
      for _ in range(100):
        next(it)
  finally:
    loader.shutdown()


def test_mp_loader_worker_respawn_heals_next_epoch():
  """Self-healing across epochs: a worker killed between epochs is
  respawned by produce_all, so the next epoch is complete again
  (exceeds the reference, which only times out)."""
  from glt_tpu.distributed import MpDistSamplingWorkerOptions, \
      MpNeighborLoader
  loader = MpNeighborLoader(
      build_ring_dataset, [2], input_nodes=np.arange(40),
      batch_size=8, collect_features=False,
      worker_options=MpDistSamplingWorkerOptions(num_workers=2),
      seed=0)
  try:
    assert len(list(loader)) == 6        # healthy epoch: 3 per worker
    for w in loader.producer._workers:   # kill everything between epochs
      w.terminate()
      w.join(timeout=10)
    batches = list(loader)               # produce_all respawns first
    assert len(batches) == 6, len(batches)
    seen = set()
    for b in batches:
      seen.update(np.asarray(b.batch)[:b.metadata['n_valid']].tolist())
    assert seen == set(range(40))
  finally:
    loader.shutdown()

"""Sampling-op tests following the reference strategy (SURVEY.md §4):
tiny graphs where req_num >= degree makes sampling exhaustive and exact,
plus statistical checks for the sub-degree regime."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glt_tpu.data import Topology
from glt_tpu.ops import (
    sample_neighbors, sample_neighbors_weighted, neighbor_probs,
)


@pytest.fixture
def small_csr():
  # 0 -> {1,2,3}; 1 -> {0}; 2 -> {}; 3 -> {1,2}
  ei = np.array([[0, 0, 0, 1, 3, 3], [1, 2, 3, 0, 1, 2]])
  topo = Topology(edge_index=ei, num_nodes=4)
  return topo


def test_exhaustive_when_fanout_geq_degree(small_csr):
  t = small_csr
  out = sample_neighbors(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                         jnp.array([0, 1, 2, 3]), fanout=3,
                         key=jax.random.key(0))
  nbrs = np.asarray(out.nbrs)
  mask = np.asarray(out.mask)
  assert set(nbrs[0][mask[0]]) == {1, 2, 3}
  assert set(nbrs[1][mask[1]]) == {0}
  assert mask[2].sum() == 0
  assert set(nbrs[3][mask[3]]) == {1, 2}
  np.testing.assert_array_equal(np.asarray(out.nbrs_num), [3, 1, 0, 2])


def test_eids_match_adjacency_slots(small_csr):
  t = small_csr
  out = sample_neighbors(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                         jnp.array([3]), fanout=2, key=jax.random.key(1),
                         edge_ids=jnp.asarray(t.edge_ids))
  eids = np.asarray(out.eids)[0]
  mask = np.asarray(out.mask)[0]
  # node 3's edges are original COO positions 4,5 (3->1, 3->2)
  assert set(eids[mask]) == {4, 5}


def test_seed_mask_suppresses(small_csr):
  t = small_csr
  out = sample_neighbors(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                         jnp.array([0, 0]), fanout=3,
                         key=jax.random.key(0),
                         seed_mask=jnp.array([True, False]))
  mask = np.asarray(out.mask)
  assert mask[0].sum() == 3 and mask[1].sum() == 0


def test_without_replacement_distinct():
  # star: node 0 -> 1..20
  n = 21
  ei = np.stack([np.zeros(20, np.int64), np.arange(1, 21)])
  t = Topology(edge_index=ei, num_nodes=n)
  for s in range(20):
    out = sample_neighbors(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                           jnp.array([0]), fanout=5,
                           key=jax.random.key(s))
    nbrs = np.asarray(out.nbrs)[0]
    mask = np.asarray(out.mask)[0]
    assert mask.all()
    assert len(set(nbrs.tolist())) == 5, 'duplicates in WOR sample'
    assert all(1 <= v <= 20 for v in nbrs)


def test_uniformity_of_floyd():
  # node 0 with degree 12, fanout 4; each neighbor should appear with
  # p = 4/12 over many trials
  deg, fan, trials = 12, 4, 3000
  ei = np.stack([np.zeros(deg, np.int64), np.arange(1, deg + 1)])
  t = Topology(edge_index=ei, num_nodes=deg + 1)
  indptr, indices = jnp.asarray(t.indptr), jnp.asarray(t.indices)

  @jax.jit
  def draw(key):
    return sample_neighbors(indptr, indices, jnp.array([0]), fan, key).nbrs

  counts = np.zeros(deg + 1)
  for s in range(trials):
    nbrs = np.asarray(draw(jax.random.key(s)))[0]
    counts[nbrs] += 1
  p = counts[1:] / trials
  np.testing.assert_allclose(p, fan / deg, atol=0.04)


def test_with_replacement():
  ei = np.stack([np.zeros(3, np.int64), np.arange(1, 4)])
  t = Topology(edge_index=ei, num_nodes=4)
  out = sample_neighbors(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                         jnp.array([0]), fanout=8,
                         key=jax.random.key(0), replace=True)
  assert np.asarray(out.mask).all()
  assert set(np.asarray(out.nbrs)[0]) <= {1, 2, 3}


def test_weighted_prefers_heavy_edges():
  deg = 10
  ei = np.stack([np.zeros(deg, np.int64), np.arange(1, deg + 1)])
  w = np.ones(deg, np.float32)
  w[0] = 1000.0  # edge to node 1 dominates
  t = Topology(edge_index=ei, edge_weights=w, num_nodes=deg + 1)
  hits = 0
  for s in range(50):
    out = sample_neighbors_weighted(
        jnp.asarray(t.indptr), jnp.asarray(t.indices),
        jnp.asarray(t.edge_weights), jnp.array([0]), fanout=3,
        key=jax.random.key(s), max_degree=16)
    nbrs = np.asarray(out.nbrs)[0][np.asarray(out.mask)[0]]
    assert len(set(nbrs.tolist())) == len(nbrs)  # WOR
    hits += int(1 in nbrs)
  assert hits >= 49  # dominant edge nearly always present


def test_weighted_exhaustive_small_degree():
  ei = np.array([[0, 0], [1, 2]])
  w = np.array([0.5, 2.0], np.float32)
  t = Topology(edge_index=ei, edge_weights=w, num_nodes=3)
  out = sample_neighbors_weighted(
      jnp.asarray(t.indptr), jnp.asarray(t.indices),
      jnp.asarray(t.edge_weights), jnp.array([0, 1]), fanout=4,
      key=jax.random.key(0), max_degree=4)
  mask = np.asarray(out.mask)
  assert set(np.asarray(out.nbrs)[0][mask[0]]) == {1, 2}
  assert mask[1].sum() == 0


def test_neighbor_probs_hotness():
  # 0 -> {1,2}; seed prob 1.0 at node 0, fanout 1 => each nbr gets 0.5
  ei = np.array([[0, 0], [1, 2]])
  t = Topology(edge_index=ei, num_nodes=3)
  probs = neighbor_probs(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                         jnp.array([1.0, 0.0, 0.0]), fanout=1, num_nodes=3)
  np.testing.assert_allclose(np.asarray(probs), [0.0, 0.5, 0.5])


@pytest.mark.pallas
def test_pallas_gather_rows_parity():
  """Interpret-mode parity of the Pallas feature gather vs jnp.take."""
  from glt_tpu.ops.pallas_kernels import gather_rows
  rng = np.random.default_rng(0)
  table = jnp.asarray(rng.normal(size=(64, 128)).astype(np.float32))
  rows = jnp.asarray(rng.integers(0, 64, 16, dtype=np.int32))
  got = gather_rows(table, rows, interpret=True)
  np.testing.assert_allclose(np.asarray(got),
                             np.asarray(table)[np.asarray(rows)])


@pytest.mark.pallas
def test_pallas_gather_rows_clamps():
  from glt_tpu.ops.pallas_kernels import gather_rows
  table = jnp.arange(12.0).reshape(3, 4)
  # pad rows to a multiple-of-8-friendly length; out-of-range clamps
  rows = jnp.array([0, 2, 99, -5, 1, 1, 0, 2], jnp.int32)
  got = np.asarray(gather_rows(table, rows, interpret=True))
  np.testing.assert_allclose(got[2], np.asarray(table)[2])
  np.testing.assert_allclose(got[3], np.asarray(table)[0])


def test_multihop_sample_many_matches_single():
  from glt_tpu.ops.pipeline import multihop_sample, multihop_sample_many
  from glt_tpu.ops.unique import dense_make_tables
  ei = np.stack([np.repeat(np.arange(30), 2),
                 np.concatenate([(np.arange(30) + 1) % 30,
                                 (np.arange(30) + 2) % 30])])
  # interleave (v+1, v+2) per v
  rows = np.repeat(np.arange(30), 2)
  cols = np.stack([(np.arange(30) + 1) % 30,
                   (np.arange(30) + 2) % 30], 1).reshape(-1)
  t = Topology(edge_index=np.stack([rows, cols]), num_nodes=30)
  indptr, indices = jnp.asarray(t.indptr.astype(np.int32)), \
      jnp.asarray(t.indices)
  one_hop = lambda ids, f, k, m: sample_neighbors(
      indptr, indices, ids, f, k, seed_mask=m)
  table, scratch = dense_make_tables(30)
  seeds_stack = jnp.asarray([[0, 5], [10, 15], [20, 25]], jnp.int32)
  nv = jnp.full(3, 2, jnp.int32)
  outs, table, scratch = multihop_sample_many(
      one_hop, seeds_stack, nv, (2,), jax.random.key(0), table, scratch)
  nodes = np.asarray(outs['node'])          # [3, budget]
  counts = np.asarray(outs['node_count'])
  for i, (a, b) in enumerate([(0, 5), (10, 15), (20, 25)]):
    got = set(nodes[i][:counts[i]].tolist())
    expect = {a, b, (a+1) % 30, (a+2) % 30, (b+1) % 30, (b+2) % 30}
    assert got == expect
  # tables came back clean: a fresh single batch behaves identically
  out2, _, _ = multihop_sample(one_hop, jnp.array([7, 8], jnp.int32),
                               jnp.asarray(2), (2,), jax.random.key(1),
                               table, scratch)
  got = set(np.asarray(out2['node'])[:int(out2['node_count'])].tolist())
  assert got == {7, 8, 9, 10}


@pytest.mark.pallas
def test_pallas_gather_windows_parity():
  from glt_tpu.ops.pallas_kernels import gather_windows
  rng = np.random.default_rng(3)
  arr = jnp.asarray(rng.integers(0, 999, 5000).astype(np.int32))
  starts = jnp.asarray(rng.integers(0, 5000, 37).astype(np.int32))
  w = 16
  got = np.asarray(gather_windows(arr, starts, w, interpret=True))
  st = np.clip(np.asarray(starts), 0, 5000 - w)
  want = np.stack([np.asarray(arr)[x:x + w] for x in st])
  np.testing.assert_array_equal(got, want)


@pytest.mark.pallas
def test_pallas_gather_windows_block_padding():
  # row count not a multiple of the block: the pad rows must not leak
  from glt_tpu.ops.pallas_kernels import gather_windows
  arr = jnp.arange(100, dtype=jnp.int32)
  starts = jnp.array([0, 50, 84], jnp.int32)   # 3 rows, block 8
  got = np.asarray(gather_windows(arr, starts, 16, block=8,
                                  interpret=True))
  assert got.shape == (3, 16)
  np.testing.assert_array_equal(got[0], np.arange(16))
  np.testing.assert_array_equal(got[2], np.arange(84, 100))


@pytest.mark.pallas
@pytest.mark.parametrize('engine', ['table', 'sort'])
def test_window_dma_path_matches_xla_weighted_and_full(monkeypatch,
                                                       engine):
  """The Pallas window-gather fast path (injected in interpret mode on
  CPU) must reproduce the XLA slice-gather path bit-for-bit: same key
  -> same Gumbel draws -> same picks, and the weight windows are equal
  because the padded source satisfies the kernel's containment
  contract. Both dedup engines are covered — on TPU the sort engine is
  the one that will carry the window path's sentinel lanes."""
  import functools
  from fixtures import ring_dataset
  from glt_tpu.ops.pallas_kernels import gather_windows
  from glt_tpu.sampler import NeighborSampler

  monkeypatch.setenv('GLT_DEDUP', engine)
  ds = ring_dataset(num_nodes=30, weighted=True)
  seeds = np.arange(0, 30, 3)

  def run(inject):
    s = NeighborSampler(ds.get_graph(), [2, 2], with_edge=True,
                        with_weight=True, seed=9)
    if inject:
      s._window_gather_fn = functools.partial(gather_windows,
                                              interpret=True)
    out = s.sample_from_nodes(seeds, key=jax.random.key(3))
    return jax.tree.map(np.asarray, dict(
        node=out.node, count=out.node_count, row=out.row, col=out.col,
        mask=out.edge_mask, edge=out.edge))

  a, b = run(False), run(True)
  for k in a:
    np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.pallas
@pytest.mark.parametrize('engine', ['table', 'sort'])
def test_window_dma_path_matches_xla_full_neighborhood(monkeypatch,
                                                       engine):
  import functools
  from fixtures import ring_dataset
  from glt_tpu.ops.pallas_kernels import gather_windows
  from glt_tpu.sampler import NeighborSampler

  monkeypatch.setenv('GLT_DEDUP', engine)
  ds = ring_dataset(num_nodes=24)
  seeds = np.array([0, 7, 13])

  def run(inject):
    s = NeighborSampler(ds.get_graph(), [-1, -1], with_edge=True,
                        seed=2)
    if inject:
      s._window_gather_fn = functools.partial(gather_windows,
                                              interpret=True)
    out = s.sample_from_nodes(seeds, key=jax.random.key(1))
    return jax.tree.map(np.asarray, dict(
        node=out.node, count=out.node_count, row=out.row, col=out.col,
        mask=out.edge_mask, edge=out.edge))

  a, b = run(False), run(True)
  for k in a:
    np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.parametrize('fanouts', [[-1, -1], [3]])
def test_window_dma_variable_degree_mask_sanitized(monkeypatch, fanouts):
  """Variable-degree graph: short windows DO read sentinel lanes in the
  DMA path (unlike the uniform ring). Valid lanes must match the XLA
  path exactly; masked lanes are contractually unspecified, so the
  comparison sanitizes them with the mask first."""
  import functools
  from glt_tpu.data import Dataset
  from glt_tpu.ops.pallas_kernels import gather_windows
  from glt_tpu.sampler import NeighborSampler

  rng = np.random.default_rng(11)
  n = 30
  edges = set()
  for v in range(n):                     # degrees 0..6
    for w in rng.choice(n, int(rng.integers(0, 7)), replace=False):
      if int(w) != v:
        edges.add((v, int(w)))
  ei = np.array(sorted(edges)).T
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=ei, num_nodes=n,
                edge_weights=(np.arange(ei.shape[1]) % 5 + 1
                              ).astype(np.float32))
  seeds = np.arange(0, n, 4)
  weighted = fanouts == [3]

  def run(inject):
    s = NeighborSampler(ds.get_graph(), fanouts, with_edge=True,
                        with_weight=weighted, seed=5)
    if inject:
      s._window_gather_fn = functools.partial(gather_windows,
                                              interpret=True)
    out = s.sample_from_nodes(seeds, key=jax.random.key(7))
    m = np.asarray(out.edge_mask)
    return dict(
        node=np.asarray(out.node), count=int(out.node_count), mask=m,
        row=np.where(m, np.asarray(out.row), -1),
        col=np.where(m, np.asarray(out.col), -1),
        edge=np.where(m, np.asarray(out.edge), -1))

  a, b = run(False), run(True)
  for k in a:
    np.testing.assert_array_equal(a[k], b[k], err_msg=k)

"""Unit tests for utility surfaces: typing conventions, rng manager,
profiling meters, size parsing, mesh helpers."""
import time

import jax
import numpy as np

from glt_tpu.typing import as_str, reverse_edge_type
from glt_tpu.utils import (
    RandomSeedManager, id2idx, merge_dict, parse_size, seed_everything,
)
from glt_tpu.utils.common import CastMixin
from glt_tpu.utils.profile import ThroughputMeter, Timer


def test_reverse_edge_type_conventions():
  assert reverse_edge_type(('u', 'rel', 'i')) == ('i', 'rev_rel', 'u')
  assert reverse_edge_type(('i', 'rev_rel', 'u')) == ('u', 'rel', 'i')
  # same-type relations keep their name
  assert reverse_edge_type(('i', 'link', 'i')) == ('i', 'link', 'i')
  assert as_str(('a', 'b', 'c')) == 'a__b__c'
  assert as_str('node') == 'node'


def test_seed_manager_reproducible_streams():
  m = RandomSeedManager.getInstance()
  m.setSeed(123)
  k1, k2 = m.nextKey(), m.nextKey()
  m.setSeed(123)
  k1b, k2b = m.nextKey(), m.nextKey()
  assert jax.random.key_data(k1).tolist() == \
      jax.random.key_data(k1b).tolist()
  assert jax.random.key_data(k1).tolist() != \
      jax.random.key_data(k2).tolist()
  assert jax.random.key_data(k2).tolist() == \
      jax.random.key_data(k2b).tolist()


def test_id2idx_and_merge_dict():
  out = id2idx(np.array([5, 2, 9]))
  assert out[5] == 0 and out[2] == 1 and out[9] == 2
  d = {}
  merge_dict({'a': 1}, d)
  merge_dict({'a': 2, 'b': 3}, d)
  assert d == {'a': [1, 2], 'b': [3]}


def test_parse_size():
  assert parse_size(1024) == 1024
  assert parse_size('2KB') == 2048
  assert parse_size('1.5MB') == int(1.5 * 1024 ** 2)
  assert parse_size('3g') == 3 * 1024 ** 3
  import pytest
  with pytest.raises(ValueError):
    parse_size('10parsecs')


def test_cast_mixin():
  import dataclasses

  @dataclasses.dataclass
  class Cfg(CastMixin):
    a: int
    b: int = 2

  assert Cfg.cast(None) is None
  c = Cfg.cast({'a': 1, 'b': 5})
  assert (c.a, c.b) == (1, 5)
  assert Cfg.cast((7,)).a == 7
  same = Cfg(3)
  assert Cfg.cast(same) is same


def test_timer_and_meter():
  t = Timer()
  with t:
    time.sleep(0.01)
  assert t.elapsed >= 0.01
  m = ThroughputMeter('edges')
  m.update(1000, 0.5)
  m.update(1000, 0.5)
  assert abs(m.rate - 2000) < 1e-6
  assert 'edges/s' in m.report()


def test_timer_stop_without_start_raises():
  """Historically crashed with `TypeError: unsupported operand` on the
  None start stamp; now a clear RuntimeError."""
  import pytest
  t = Timer()
  with pytest.raises(RuntimeError, match='without a running interval'):
    t.stop()
  # stop() consumes its start(): a second stop is the same clear error
  t.start()
  t.stop()
  with pytest.raises(RuntimeError, match='without a running interval'):
    t.stop()


def test_timer_reentrant_enter_resets_cleanly():
  t = Timer()
  with t:
    time.sleep(0.002)
  first = t.elapsed
  assert not t.running
  with t:  # reuse: restarts the interval, keeps accumulating
    time.sleep(0.002)
  assert t.elapsed >= first + 0.002
  # an explicit stop() inside the body is tolerated by __exit__
  with t:
    t.stop()
  assert not t.running
  # back-to-back start() calls restart the stamp instead of corrupting
  t.reset()
  t.start()
  t.start()
  assert t.stop() < 10.0  # one interval's worth, not garbage


def test_meter_report_auto_scales_unit():
  """Sub-million rates used to print '0.00M edges/s' (hard-coded /1e6);
  the unit now auto-scales across raw / K / M."""
  def at_rate(rate):
    m = ThroughputMeter('req')
    m.update(rate, 1.0)
    return m.report()
  assert at_rate(42) == '42.00 req/s'
  assert at_rate(2_000) == '2.00K req/s'
  assert at_rate(3_500_000) == '3.50M req/s'
  assert at_rate(999) == '999.00 req/s'
  assert ThroughputMeter('req').report() == '0.00 req/s'


def test_prefetch_joins_worker_on_abandon():
  """An abandoned/closed consumer must stop AND JOIN the prefetch
  worker; before the fix the daemon thread (and the batch references it
  held) leaked until process exit."""
  from glt_tpu.utils.prefetch import PrefetchIterator

  def endless():
    i = 0
    while True:
      yield i
      i += 1

  p = PrefetchIterator(endless(), depth=2)
  it = iter(p)
  assert next(it) == 0
  assert p.worker_thread is not None and p.worker_thread.is_alive()
  it.close()  # abandon mid-stream -> generator finally -> stop + join
  assert not p.worker_thread.is_alive()


def test_prefetch_joins_worker_on_exhaustion():
  from glt_tpu.utils.prefetch import prefetch
  p = prefetch(iter(range(5)), depth=2)
  assert list(p) == [0, 1, 2, 3, 4]
  assert not p.worker_thread.is_alive()


def test_mesh_helpers():
  from glt_tpu.parallel import make_mesh, replicated, row_sharded
  mesh = make_mesh(8)
  assert mesh.shape['data'] == 8
  assert replicated(mesh).spec == jax.sharding.PartitionSpec()
  assert row_sharded(mesh).spec == jax.sharding.PartitionSpec('data')


def test_force_backend_guard():
  """The central axon-footgun guard: idempotent when the requested
  platform is already active; a too-late DIFFERENT platform raises."""
  import pytest
  from glt_tpu.utils.backend import force_backend
  import jax
  jax.devices()  # ensure the (cpu) backend is initialized
  assert force_backend('cpu') == 'cpu'  # idempotent, no error
  with pytest.raises(RuntimeError, match='after backend'):
    force_backend('tpu')
  # env-driven resolution: nothing set -> untouched
  import os
  for v in ('GLT_BENCH_PLATFORM', 'GLT_PLATFORM'):
    assert v not in os.environ or os.environ.pop(v)
  assert force_backend() is None

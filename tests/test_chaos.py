"""Chaos scenarios: deterministic fault injection against the real
rpc/serving/loader stack (marker ``chaos``; CI runs ``-m chaos`` with a
pinned GLT_CHAOS_SEED so every fault path executes on every PR).

Everything here drives REAL sockets/processes through the seeded
:mod:`glt_tpu.resilience.chaos` harness — no mocks — asserting the
degradation contracts of docs/fault_tolerance.md: bounded latency,
counted (never silent) data loss, and no hangs."""
import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.chaos


def build_long_ring_dataset():
  """Module-level picklable builder (spawned sampling workers)."""
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from fixtures import ring_dataset
  return ring_dataset(num_nodes=200, feat_dim=4)


def build_ring_dataset_40():
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from fixtures import ring_dataset
  return ring_dataset(num_nodes=40, feat_dim=4)


# -- rpc hardening -------------------------------------------------------

def test_rpc_client_survives_server_bounce():
  """Satellite: a peer close must not kill the client — the socket is
  re-established transparently on the next request."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  srv = RpcServer()
  srv.register('add', lambda a, b: a + b)
  cli = RpcClient(srv.host, srv.port, timeout=10)
  assert cli.request('add', 2, 3) == 5
  host, port = srv.host, srv.port
  srv.stop()
  time.sleep(0.1)
  srv2 = RpcServer(host=host, port=port)  # bounced: same address
  srv2.register('add', lambda a, b: a + b)
  try:
    assert cli.request('_ping')['ok']      # reconnects transparently
    assert cli.request('add', 4, 5) == 9   # and serves non-idempotent
    assert cli.reconnects >= 1
  finally:
    cli.close()
    srv2.stop()


def test_rpc_probe_token_released_on_caller_bug():
  """An exception that aborts a request before it reaches the wire (an
  unpicklable argument) must return the HALF_OPEN probe token — else
  the breaker wedges OPEN forever against a healthy peer."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  from glt_tpu.resilience import CircuitBreaker
  srv = RpcServer()
  srv.register('echo', lambda x: x)
  cli = RpcClient(srv.host, srv.port, timeout=10,
                  breaker=CircuitBreaker(failure_threshold=1,
                                         reset_timeout_s=0.0))
  try:
    cli.breaker.record_failure()       # tripped; timeout 0 => HALF_OPEN
    with pytest.raises((TypeError, AttributeError)):  # pickle's error
      cli.request('echo', lambda: 1)   # dies in pickle, pre-wire
    # token returned: the next well-formed probe is admitted + closes
    assert cli.request('echo', 7) == 7
    assert cli.breaker.state == 'CLOSED'
  finally:
    cli.close()
    srv.stop()


def test_rpc_dedup_entry_released_after_next_request():
  """Steady-state memory: a NEW request arriving on a connection proves
  the client consumed the previous reply, so its cached dedup payload
  is dropped immediately instead of pinning until the LRU cap."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  srv = RpcServer()
  srv.register('echo', lambda x: x)
  cli = RpcClient(srv.host, srv.port, timeout=10,
                  idempotent=frozenset({'echo'}))
  try:
    for k in range(5):
      assert cli.request('echo', k) == k
    # receiving reply k+1 proves the server evicted entry k first:
    # only the LAST request's reply may remain cached
    with srv._lock:
      assert len(srv._dedup) == 1
  finally:
    cli.close()
    srv.stop()


def test_rpc_retry_through_flaky_link_exactly_once():
  """Drops/disconnects/delays on a seeded schedule: every request
  eventually succeeds, and the server-side request-id dedup cache
  guarantees each request EXECUTED exactly once even when only the
  reply was lost."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  from glt_tpu.resilience import (
      ChaosTcpProxy, CircuitBreaker, FaultPlan, RetryPolicy,
  )
  srv = RpcServer()
  calls = {}
  lock = threading.Lock()

  def echo(x):
    with lock:
      calls[x] = calls.get(x, 0) + 1
    return x * 2

  srv.register('echo', echo)
  plan = FaultPlan(seed=1234, drop=0.15, disconnect=0.1, delay=0.1,
                   delay_s=0.01)
  proxy = ChaosTcpProxy(srv.host, srv.port, plan)
  cli = RpcClient(
      *proxy.address, timeout=10,
      retry=RetryPolicy(max_attempts=8, base_delay_s=0.01,
                        max_delay_s=0.05, jitter=0),
      breaker=CircuitBreaker(failure_threshold=1000),
      idempotent=frozenset({'echo'}))
  try:
    # the budget exercises the deadline-slicing path but stays WIDE:
    # this test asserts exactly-once execution, not tight latency, and
    # how many faults one request eats depends on how its frames align
    # with the proxy's per-connection schedules (timing-dependent) — a
    # 0.5 s budget was observed to exhaust on a request that drew ~6
    # consecutive faults when neighboring suites shifted the alignment
    for i in range(60):
      assert cli.request('echo', i, _rpc_timeout=5.0) == 2 * i
    assert cli.retries > 0, 'chaos schedule injected no faults?'
    faults = proxy.faults_injected
    assert sum(faults.values()) > 0
    multi = {k: v for k, v in calls.items() if v != 1}
    assert not multi, f'dedup failed — double-executed: {multi}'
    assert len(calls) == 60
  finally:
    cli.close()
    proxy.close()
    srv.stop()


def test_circuit_breaker_fails_fast_on_dead_peer():
  """A dead server costs the retry budget ONCE; every call after the
  breaker opens fails in microseconds, not a 180 s timeout."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  from glt_tpu.resilience import (
      CircuitBreaker, CircuitOpenError, RetryPolicy,
  )
  srv = RpcServer()
  cli = RpcClient(
      srv.host, srv.port, timeout=5,
      retry=RetryPolicy(max_attempts=2, base_delay_s=0.01, jitter=0),
      breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=60))
  assert cli.request('_ping')['ok']
  srv.stop()
  time.sleep(0.1)
  with pytest.raises(ConnectionError):
    cli.request('_ping', _rpc_timeout=1.0)
  t0 = time.monotonic()
  with pytest.raises(CircuitOpenError):
    cli.request('_ping')
  assert time.monotonic() - t0 < 0.1, 'breaker did not fail fast'
  assert cli.breaker.opens == 1
  cli.close()


def test_truncated_frame_recovers():
  """A torn write (half a frame, then close) must surface as a clean
  retryable failure, not corrupt the next request's framing."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  from glt_tpu.resilience import (
      ChaosTcpProxy, CircuitBreaker, FaultPlan, RetryPolicy,
  )
  srv = RpcServer()
  srv.register('big', lambda: bytes(100_000))
  plan = FaultPlan(seed=7, truncate=0.25)
  proxy = ChaosTcpProxy(srv.host, srv.port, plan)
  cli = RpcClient(
      *proxy.address, timeout=10,
      retry=RetryPolicy(max_attempts=8, base_delay_s=0.01, jitter=0),
      breaker=CircuitBreaker(failure_threshold=1000),
      idempotent=frozenset({'big'}))
  try:
    for _ in range(20):
      assert cli.request('big', _rpc_timeout=1.0) == bytes(100_000)
    assert proxy.faults_injected['truncate'] > 0
  finally:
    cli.close()
    proxy.close()
    srv.stop()


# -- serving degradation -------------------------------------------------

def test_engine_stall_sheds_queued_and_bounds_latency():
  """Injected engine stall: the watchdog fails the wedged batch AND the
  queue within the stall budget (bounded p99 with a dead engine), the
  circuit fails fast while open, and the engine's eventual return
  closes it."""
  from glt_tpu.serving import (
      EngineStalledError, MicroBatcher, ServingMetrics,
  )
  gate = threading.Event()
  entered = threading.Event()
  wedge = threading.Event()

  def handler(ids):
    if wedge.is_set():
      entered.set()
      gate.wait(timeout=30)
    return np.stack([ids.astype(np.float32)] * 2, axis=1)

  m = ServingMetrics()
  b = MicroBatcher(handler, max_batch_size=8, max_wait_ms=1.0,
                   request_timeout_ms=5000.0, stall_timeout_ms=150.0,
                   metrics=m)
  try:
    assert b.submit([1, 2]).result(timeout=10).shape == (2, 2)
    wedge.set()
    t0 = time.monotonic()
    f1 = b.submit([3, 4])
    assert entered.wait(timeout=10)   # the dispatch is provably wedged
    f2 = b.submit([5])                # queued behind the corpse
    for f in (f1, f2):
      with pytest.raises(EngineStalledError):
        f.result(timeout=10)
    dt = time.monotonic() - t0
    assert dt < 2.0, f'pending futures not failed promptly ({dt:.2f}s)'
    assert b.stalled
    with pytest.raises(EngineStalledError):
      b.submit([6])                   # fail fast while OPEN
    snap = m.snapshot()
    assert snap['breaker_opens'] == 1
    assert snap['shed'] >= 2          # queued victim + fast-failed
    assert snap['gauges']['engine_stalled'] == 1.0
    # the wedged call returning closes the circuit
    wedge.clear()
    gate.set()
    deadline = time.monotonic() + 10
    while b.stalled and time.monotonic() < deadline:
      time.sleep(0.01)
    assert not b.stalled
    assert b.submit([7]).result(timeout=10).shape == (1, 2)
    assert m.snapshot()['gauges']['engine_stalled'] == 0.0
  finally:
    gate.set()
    b.stop()


def test_dispatcher_survives_handler_death():
  """Satellite: an exception escaping the dispatch fn fails the batch
  with the ORIGINAL error and the dispatcher thread survives to serve
  later submits (no stranding until request_timeout_ms)."""
  from glt_tpu.serving import MicroBatcher

  boom = {'on': False}

  def handler(ids):
    if boom['on']:
      raise ZeroDivisionError('injected handler death')
    return np.stack([ids.astype(np.float32)] * 2, axis=1)

  b = MicroBatcher(handler, max_batch_size=8, max_wait_ms=1.0,
                   request_timeout_ms=60_000.0)
  try:
    assert b.submit([1]).result(timeout=10).shape == (1, 2)
    boom['on'] = True
    t0 = time.monotonic()
    with pytest.raises(ZeroDivisionError, match='injected'):
      b.submit([2]).result(timeout=10)
    assert time.monotonic() - t0 < 5, 'stranded until timeout'
    boom['on'] = False
    assert b.submit([3]).result(timeout=10).shape == (1, 2)
  finally:
    b.stop()


# -- dist_server fetch deadline path (satellite) -------------------------

def test_fetch_one_sampled_message_deadline_and_producer_death():
  """The fetch deadline path: an empty channel times out CLEANLY (a
  typed error, not a hang), a retry after the timeout succeeds, and a
  producer death mid-epoch surfaces as the documented per-epoch
  timeout."""
  from glt_tpu.channel import QueueTimeoutError, pack_message
  from glt_tpu.distributed.dist_server import DistServer, _END

  ds = build_long_ring_dataset()
  server = DistServer(ds, dataset_builder=build_long_ring_dataset)
  cfg = dict(num_neighbors=[2], batch_size=4, shuffle=False,
             drop_last=False, with_edge=False, collect_features=True,
             seed=0)
  # tiny buffer: the producer CANNOT finish the epoch ahead of the
  # consumer, so a mid-epoch kill deterministically leaves the epoch
  # unfinished (50 batches never fit in 64 KiB)
  server.create_sampling_producer(
      'k', pack_message({'seeds': np.arange(200)}), cfg,
      num_workers=1, buffer_capacity=64 * 1024)
  producer = server._producers['k']
  try:
    # 1) timeout before any epoch: clean typed error, bounded wall time
    t0 = time.monotonic()
    with pytest.raises(QueueTimeoutError):
      server.fetch_one_sampled_message('k', 0, timeout_ms=300)
    assert 0.2 < time.monotonic() - t0 < 5.0
    # 2) retry after timeout succeeds once the epoch starts
    server.start_new_epoch_sampling('k', 0)
    out = server.fetch_one_sampled_message('k', 0, timeout_ms=30_000)
    assert out != _END and len(out) > 0
    # 3) producer death mid-epoch -> per-epoch timeout, not a hang
    assert all(w.is_alive() for w in producer._workers)
    for w in producer._workers:
      w.terminate()
      w.join(timeout=10)
    t0 = time.monotonic()
    with pytest.raises(QueueTimeoutError):
      for _ in range(200):  # drain buffered, then time out
        out = server.fetch_one_sampled_message('k', 0, timeout_ms=1500)
        assert out != _END, 'epoch cannot end: its producer died'
    assert time.monotonic() - t0 < 30
    # 4) the healing boundary: the next epoch respawns the worker
    server.start_new_epoch_sampling('k', 1)
    out = server.fetch_one_sampled_message('k', 1, timeout_ms=30_000)
    assert out != _END and len(out) > 0
  finally:
    server.exit()


# -- kill 1-of-N servers mid-epoch (acceptance scenario) -----------------

def _chaos_server_proc(rank, port, ready, done):
  import sys, os
  sys.path.insert(0, os.path.dirname(__file__))
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from glt_tpu.distributed import init_server, wait_and_shutdown_server
  ds = build_ring_dataset_40()
  init_server(num_servers=3, num_clients=1, server_rank=rank,
              dataset=ds, master_port=port,
              dataset_builder=build_ring_dataset_40)
  ready.set()
  wait_and_shutdown_server(poll_s=0.1)
  done.set()


@pytest.mark.slow
def test_kill_one_of_three_servers_mid_epoch_completes():
  """Acceptance: with 3 partition servers, killing one mid-epoch lets
  the epoch COMPLETE from the survivors via retry + degradation — no
  hang, no per-call 180 s stall — and the dropout is accounted in the
  fabric health/metrics."""
  import socket
  from glt_tpu.distributed import (
      RemoteDistSamplingWorkerOptions, RemoteNeighborLoader,
      fabric_stats, init_client, shutdown_client,
  )
  from glt_tpu.resilience import RetryPolicy

  # three consecutive free ports (server_port = master_port + rank)
  base = None
  for _ in range(50):
    s = socket.socket(); s.bind(('127.0.0.1', 0))
    cand = s.getsockname()[1]; s.close()
    ok = True
    for k in range(3):
      t = socket.socket()
      try:
        t.bind(('127.0.0.1', cand + k))
      except OSError:
        ok = False
      finally:
        t.close()
      if not ok:
        break
    if ok:
      base = cand
      break
  assert base is not None

  ctx = mp.get_context('spawn')
  readies = [ctx.Event() for _ in range(3)]
  dones = [ctx.Event() for _ in range(3)]
  servers = [ctx.Process(target=_chaos_server_proc,
                         args=(r, base, readies[r], dones[r]))
             for r in range(3)]
  for s in servers:
    s.start()
  for e in readies:
    assert e.wait(timeout=120), 'server did not come up'

  init_client(num_servers=3, num_clients=1, client_rank=0,
              master_port=base, rpc_timeout=30.0,
              retry=RetryPolicy(max_attempts=3, base_delay_s=0.05,
                                max_delay_s=0.5, jitter=0),
              breaker_threshold=3, health_interval_s=None)
  try:
    seeds = [np.arange(0, 13), np.arange(13, 26), np.arange(26, 40)]
    loader = RemoteNeighborLoader(
        [2], seeds, batch_size=5,
        worker_options=RemoteDistSamplingWorkerOptions(
            server_rank=[0, 1, 2], prefetch_size=1, rpc_timeout=30.0),
        seed=0)
    # healthy epoch: 3 + 3 + 3 batches
    assert sum(1 for _ in loader) == 9
    # epoch 2: kill server 1 after the first batches arrive
    it = iter(loader)
    got = [next(it), next(it)]
    servers[1].kill()
    servers[1].join(timeout=30)
    t0 = time.monotonic()
    got += list(it)                       # must TERMINATE, not hang
    wall = time.monotonic() - t0
    assert wall < 120, f'epoch drain took {wall:.0f}s'
    assert 6 <= len(got) <= 9
    assert loader.degraded_servers == {1}
    stats = fabric_stats()
    assert 1 in stats['dropouts'] or stats['health'].get(1) != 'UP'
    # epoch 3: survivors keep serving full epochs minus the dead server
    n3 = sum(1 for _ in loader)
    assert n3 == 6, n3
    seen = set()
    # (re-run one more epoch collecting coverage of the survivors)
    for b in loader:
      nv = b.metadata['n_valid']
      seen.update(np.asarray(b.batch)[:nv].tolist())
    assert set(range(0, 13)) <= seen and set(range(26, 40)) <= seen
    assert not (set(range(13, 26)) & seen)
  finally:
    shutdown_client()
  for r in (0, 2):
    assert dones[r].wait(timeout=60), f'server {r} did not exit cleanly'
    servers[r].join(timeout=10)


# -- chaos determinism (CI seed contract) --------------------------------

def test_chaos_schedule_is_deterministic_across_runs():
  """The CI contract: with GLT_CHAOS_SEED pinned, the exact same fault
  schedule replays — including per-connection forks."""
  from glt_tpu.resilience import FaultPlan
  a = FaultPlan(seed=1234, drop=0.15, disconnect=0.1, delay=0.1)
  b = FaultPlan(seed=1234, drop=0.15, disconnect=0.1, delay=0.1)
  assert a.schedule(500) == b.schedule(500)
  fa, fb = a.fork(9), b.fork(9)  # ONE fork each: compare whole streams
  assert [fa.next_fault() for _ in range(100)] \
      == [fb.next_fault() for _ in range(100)]


def test_apply_delta_retry_never_double_stages():
  """Satellite of the fleet PR: ``apply_delta`` is mutating-but-
  dedupable. A client that marks it idempotent attaches a request id,
  so when chaos eats only the REPLY the retry replays the server's
  recorded answer instead of staging (and compacting) the delta cut a
  second time — staging twice would double-insert the same edges."""
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  from glt_tpu.resilience import (
      ChaosTcpProxy, CircuitBreaker, FaultPlan, RetryPolicy,
  )
  srv = RpcServer()
  stages = {}
  lock = threading.Lock()

  def apply_delta(cut):
    with lock:
      stages[cut] = stages.get(cut, 0) + 1
      version = len(stages)
    return {'version': version, 'staged': 1}

  srv.register('apply_delta', apply_delta)
  plan = FaultPlan(seed=1234, drop=0.2, disconnect=0.1, delay=0.1,
                   delay_s=0.01)
  proxy = ChaosTcpProxy(srv.host, srv.port, plan)
  # the same client shape the fleet's remote replicas and the
  # dist_client build: apply_delta opted into the req-id dedup
  cli = RpcClient(
      *proxy.address, timeout=10,
      retry=RetryPolicy(max_attempts=8, base_delay_s=0.01,
                        max_delay_s=0.05, jitter=0),
      breaker=CircuitBreaker(failure_threshold=1000),
      idempotent=frozenset({'apply_delta'}))
  try:
    versions = []
    for cut in range(40):
      out = cli.request('apply_delta', cut, _rpc_timeout=5.0)
      versions.append(out['version'])
    assert cli.retries > 0, 'chaos schedule injected no faults?'
    assert sum(proxy.faults_injected.values()) > 0
    multi = {k: v for k, v in stages.items() if v != 1}
    assert not multi, f'delta cut staged more than once: {multi}'
    assert len(stages) == 40
    # replayed replies are the RECORDED ones: the version sequence the
    # client observed is exactly the server's staging order
    assert versions == list(range(1, 41))
  finally:
    cli.close()
    proxy.close()
    srv.stop()

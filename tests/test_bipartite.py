"""Regression tests for bipartite (rectangular) topologies — the hetero
('user','u2i','item')-style edge types that the RGCN/RGAT configs rely on."""
import numpy as np

from glt_tpu.data import Dataset, Topology
from glt_tpu.typing import Split


def test_bipartite_flip_layout():
  # 3 users -> 10 items
  ei = np.array([[0, 1, 2], [9, 5, 7]])
  csr = Topology(edge_index=ei, layout='CSR', num_rows=3, num_cols=10)
  assert csr.num_rows == 3 and csr.num_cols == 10
  csc = csr.flip_layout()
  assert csc.layout == 'CSC'
  assert csc.num_rows == 10 and csc.num_cols == 3
  np.testing.assert_array_equal(csc.degrees,
                                [0, 0, 0, 0, 0, 1, 0, 1, 0, 1])
  back = csc.flip_layout()
  np.testing.assert_array_equal(back.indptr, csr.indptr)
  np.testing.assert_array_equal(back.indices, csr.indices)


def test_bipartite_csc_build():
  ei = np.array([[0, 1, 2], [9, 5, 7]])
  csc = Topology(edge_index=ei, layout='CSC', num_rows=10, num_cols=3)
  assert csc.indptr.shape[0] == 11
  np.testing.assert_array_equal(csc.indices[csc.indptr[9]:csc.indptr[10]], [0])


def test_row_out_of_range_raises():
  ei = np.array([[0, 5], [1, 1]])
  try:
    Topology(edge_index=ei, layout='CSR', num_rows=3, num_cols=2)
    raise AssertionError('expected ValueError')
  except ValueError as ex:
    assert 'out of range' in str(ex)


def test_bipartite_dataset_split_covers_dst_type():
  u2i = ('user', 'u2i', 'item')
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index={u2i: np.array([[0, 1, 2], [9, 5, 7]])},
                num_nodes={'user': 3, 'item': 10})
  assert ds.node_count('item') == 10
  assert ds.node_count('user') == 3
  ds.random_node_split(num_val=0.2, num_test=0.2)
  tr, va, te = ds.node_split['item']
  all_ids = np.sort(np.concatenate([tr, va, te]))
  np.testing.assert_array_equal(all_ids, np.arange(10))


def test_indptr_is_int64_on_host():
  ei = np.array([[0, 1], [1, 0]])
  topo = Topology(edge_index=ei, num_nodes=2)
  assert topo.indptr.dtype == np.int64


def test_dataset_edge_dir_in_bipartite():
  u2i = ('user', 'u2i', 'item')
  ds = Dataset(edge_dir='in')
  ds.init_graph(edge_index={u2i: np.array([[0, 1, 2], [9, 5, 7]])},
                num_nodes={'user': 3, 'item': 10})
  g = ds.get_graph(u2i)
  assert g.layout == 'CSC'
  assert g.topo.num_rows == 10 and g.topo.num_cols == 3

"""Property-based tests (hypothesis): the dense-table inducer must be
EXACTLY equivalent to the sort-based ordered_unique path on arbitrary
inputs, and sampling invariants must hold for any degree distribution —
the randomized counterpart of the fixture-exact tests (reference test
strategy, SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

hypothesis = pytest.importorskip(
    'hypothesis', reason='property tests need hypothesis (optional '
    'test dependency; the fixture-exact tests cover the same paths)')
from hypothesis import given, settings, strategies as st  # noqa: E402

from glt_tpu.ops.sample import sample_full_neighbors, sample_neighbors
from glt_tpu.ops.unique import (
    dense_assign, dense_init, dense_make_tables, dense_reset,
    ordered_unique,
)

ids_strategy = st.lists(
    st.tuples(st.integers(0, 19), st.booleans()), min_size=1,
    max_size=40)


def _py_ordered_unique(ids, valid):
  seen, uniq, inv = {}, [], []
  for x, ok in zip(ids, valid):
    if not ok:
      inv.append(-1)
      continue
    if x not in seen:
      seen[x] = len(uniq)
      uniq.append(x)
    inv.append(seen[x])
  return uniq, inv


@settings(max_examples=60, deadline=None)
@given(ids_strategy)
def test_ordered_unique_matches_python(pairs):
  ids = np.array([p[0] for p in pairs], np.int32)
  valid = np.array([p[1] for p in pairs])
  cap = ids.shape[0]
  uniq, count, inv = ordered_unique(jnp.asarray(ids), jnp.asarray(valid),
                                    cap)
  want_uniq, want_inv = _py_ordered_unique(ids.tolist(), valid.tolist())
  assert int(count) == len(want_uniq)
  np.testing.assert_array_equal(np.asarray(uniq)[:len(want_uniq)],
                                want_uniq)
  np.testing.assert_array_equal(np.asarray(inv), want_inv)


@settings(max_examples=40, deadline=None)
@given(ids_strategy, ids_strategy)
def test_dense_assign_matches_ordered_unique_two_rounds(pairs_a, pairs_b):
  """Two consecutive dense_assign rounds = ordered_unique over the
  concatenation: same first-occurrence labels, same node list."""
  a_ids = np.array([p[0] for p in pairs_a], np.int32)
  a_ok = np.array([p[1] for p in pairs_a])
  b_ids = np.array([p[0] for p in pairs_b], np.int32)
  b_ok = np.array([p[1] for p in pairs_b])
  cap = a_ids.shape[0] + b_ids.shape[0]

  table, scratch = dense_make_tables(20)
  state = dense_init(table, scratch, cap)
  state, lab_a = dense_assign(state, jnp.asarray(a_ids),
                              jnp.asarray(a_ok))
  state, lab_b = dense_assign(state, jnp.asarray(b_ids),
                              jnp.asarray(b_ok))

  cat_ids = np.concatenate([a_ids, b_ids]).tolist()
  cat_ok = np.concatenate([a_ok, b_ok]).tolist()
  want_uniq, want_inv = _py_ordered_unique(cat_ids, cat_ok)
  got_inv = np.concatenate([np.asarray(lab_a), np.asarray(lab_b)])
  np.testing.assert_array_equal(got_inv, want_inv)
  assert int(state.count) == len(want_uniq)
  np.testing.assert_array_equal(np.asarray(state.nodes)[:len(want_uniq)],
                                want_uniq)
  # reset leaves the tables clean for the next batch
  table, scratch = dense_reset(state)
  assert int(np.asarray(table).max()) == -1


@settings(max_examples=25, deadline=None)
@given(st.lists(st.integers(0, 6), min_size=1, max_size=12),
       st.integers(1, 8), st.integers(0, 2**31 - 1))
def test_sample_neighbors_invariants(degrees, fanout, seed):
  """For ANY degree multiset: samples are real neighbors, distinct, and
  exhaustive-in-order when degree <= fanout."""
  indptr = np.concatenate([[0], np.cumsum(degrees)]).astype(np.int32)
  e = int(indptr[-1])
  indices = np.arange(e, dtype=np.int32) * 7 % 100  # arbitrary ids
  seeds = np.arange(len(degrees), dtype=np.int32)
  out = sample_neighbors(jnp.asarray(indptr), jnp.asarray(indices),
                         jnp.asarray(seeds), fanout,
                         jax.random.key(seed))
  nbrs = np.asarray(out.nbrs)
  mask = np.asarray(out.mask)
  for v, deg in enumerate(degrees):
    got = nbrs[v][mask[v]]
    adj = indices[indptr[v]:indptr[v + 1]]
    assert got.shape[0] == min(deg, fanout)
    if deg <= fanout:
      np.testing.assert_array_equal(got, adj)   # exhaustive, in order
    else:
      # all sampled slots hold real neighbors at distinct offsets
      eids = np.asarray(out.eids)[v][mask[v]]
      assert len(set(eids.tolist())) == fanout  # WOR: distinct slots
      assert all(x in adj for x in got)


@settings(max_examples=25, deadline=None)
@given(st.lists(st.integers(0, 5), min_size=1, max_size=10),
       st.integers(1, 6))
def test_full_neighbors_is_exact(degrees, window_extra):
  indptr = np.concatenate([[0], np.cumsum(degrees)]).astype(np.int32)
  e = int(indptr[-1])
  indices = (np.arange(e, dtype=np.int32) * 3 + 1) % 50
  seeds = np.arange(len(degrees), dtype=np.int32)
  window = max(degrees) + window_extra if degrees else window_extra
  window = max(window, 1)
  out = sample_full_neighbors(jnp.asarray(indptr), jnp.asarray(indices),
                              jnp.asarray(seeds), window)
  nbrs = np.asarray(out.nbrs)
  mask = np.asarray(out.mask)
  for v, deg in enumerate(degrees):
    np.testing.assert_array_equal(nbrs[v][mask[v]],
                                  indices[indptr[v]:indptr[v + 1]])

"""Test harness config: run every test on an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing the real distributed stack on a
single host (SURVEY.md §4): instead of torch.multiprocessing.spawn over
localhost rpc, we ask XLA for 8 host devices so sharding/collective code
paths execute exactly as they would on a TPU slice.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the suite's offload assertions assume the documented default (auto-on
# when spilled); an ambient GLT_HOST_OFFLOAD=0 opt-out must not leak in
os.environ.pop('GLT_HOST_OFFLOAD', None)

# Must run before jax initializes its backend (the axon TPU plugin
# overrides JAX_PLATFORMS; the config API is authoritative) — the
# shared guard owns that rule: glt_tpu/utils/backend.py
from glt_tpu.utils.backend import force_backend

force_backend('cpu', host_devices=8)

import jax

import numpy as np
import pytest


def pytest_configure(config):
  assert jax.devices()[0].platform == 'cpu', (
      'tests must run on the virtual CPU mesh, not the real TPU')
  assert jax.device_count() == 8


@pytest.fixture
def rng():
  return np.random.default_rng(0)

"""Test harness config: run every test on an 8-device virtual CPU mesh.

Mirrors the reference's strategy of testing the real distributed stack on a
single host (SURVEY.md §4): instead of torch.multiprocessing.spawn over
localhost rpc, we ask XLA for 8 host devices so sharding/collective code
paths execute exactly as they would on a TPU slice.
"""
import os

# Must run before jax initializes its backend. NOTE: the JAX_PLATFORMS env
# var is overridden by the axon TPU plugin in this image — the config API
# is authoritative, so force CPU through it.
_flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in _flags:
  os.environ['XLA_FLAGS'] = (
      _flags + ' --xla_force_host_platform_device_count=8').strip()

# the suite's offload assertions assume the documented default (auto-on
# when spilled); an ambient GLT_HOST_OFFLOAD=0 opt-out must not leak in
os.environ.pop('GLT_HOST_OFFLOAD', None)

import jax

jax.config.update('jax_platforms', 'cpu')

import numpy as np
import pytest


def pytest_configure(config):
  assert jax.devices()[0].platform == 'cpu', (
      'tests must run on the virtual CPU mesh, not the real TPU')
  assert jax.device_count() == 8


@pytest.fixture
def rng():
  return np.random.default_rng(0)

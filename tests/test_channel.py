"""Channel layer tests: native shm queue (C++ ring buffer), TensorMap
wire format, cross-process transfer (the reference's test_shm_channel /
test_tensor_map_serializer coverage)."""
import multiprocessing as mp
import numpy as np
import pytest

from glt_tpu.channel import (
    QueueTimeoutError, ShmChannel, ShmQueue, pack_message, unpack_message,
)


def test_pack_unpack_roundtrip():
  msg = {
      'ids': np.arange(10, dtype=np.int64),
      'feats': np.random.default_rng(0).normal(size=(4, 3)).astype(
          np.float32),
      'mask': np.array([True, False, True]),
      'scalar': np.float32(3.5).reshape(()),
  }
  out = unpack_message(pack_message(msg))
  assert set(out) == set(msg)
  for k in msg:
    np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(msg[k]))
    assert out[k].dtype == np.asarray(msg[k]).dtype


def test_pack_unpack_bf16():
  import ml_dtypes
  msg = {'x': np.arange(6, dtype=np.float32).astype(
      ml_dtypes.bfloat16).reshape(2, 3)}
  out = unpack_message(pack_message(msg))
  assert out['x'].dtype.name == 'bfloat16'
  np.testing.assert_array_equal(
      out['x'].astype(np.float32), np.arange(6, np.float32).reshape(2, 3)
      if False else np.arange(6, dtype=np.float32).reshape(2, 3))


def test_shm_queue_fifo_and_wraparound():
  q = ShmQueue(capacity_bytes=1 << 12)  # tiny: forces wraparound
  try:
    rng = np.random.default_rng(0)
    payloads = [rng.bytes(rng.integers(1, 800)) for _ in range(64)]
    # interleave: stay under capacity while forcing the ring to wrap
    for i in range(0, 64, 4):
      for p in payloads[i:i + 4]:
        q.enqueue(p)
      for p in payloads[i:i + 4]:
        assert q.dequeue() == p
    assert q.empty()
  finally:
    q.close()


def test_shm_queue_timeout():
  q = ShmQueue(capacity_bytes=1 << 12)
  try:
    with pytest.raises(QueueTimeoutError):
      q.dequeue(timeout_ms=50)
  finally:
    q.close()


def test_shm_queue_oversized_message():
  q = ShmQueue(capacity_bytes=1 << 10)
  try:
    with pytest.raises(OSError):
      q.enqueue(b'x' * 5000)
  finally:
    q.close()


def _producer_proc(chan, n):
  for i in range(n):
    chan.send({'i': np.array([i]), 'payload': np.full((8,), i,
                                                      np.float32)})


def test_shm_channel_cross_process():
  chan = ShmChannel(capacity_bytes=1 << 20)
  try:
    ctx = mp.get_context('spawn')
    p = ctx.Process(target=_producer_proc, args=(chan, 20))
    p.start()
    got = [chan.recv(timeout_ms=30_000) for _ in range(20)]
    p.join(timeout=30)
    assert p.exitcode == 0
    for i, msg in enumerate(got):
      assert int(msg['i'][0]) == i
      np.testing.assert_allclose(msg['payload'], i)
  finally:
    chan.close()


def test_shm_channel_blocking_backpressure():
  # producer blocks when the ring is full, resumes as consumer drains
  chan = ShmChannel(capacity_bytes=1 << 12)
  try:
    ctx = mp.get_context('spawn')
    p = ctx.Process(target=_producer_proc, args=(chan, 200))
    p.start()
    seen = 0
    for _ in range(200):
      msg = chan.recv(timeout_ms=30_000)
      seen += 1
    p.join(timeout=30)
    assert seen == 200 and p.exitcode == 0
  finally:
    chan.close()


def test_remote_receiving_channel():
  from glt_tpu.channel import RemoteReceivingChannel
  def make_fetcher(server_id, n=5):
    state = {'i': 0}
    def fetch():
      if state['i'] >= n:
        raise StopIteration
      i = state['i']; state['i'] += 1
      return {'sid': np.array([server_id]), 'i': np.array([i])}
    return fetch
  ch = RemoteReceivingChannel([make_fetcher(0), make_fetcher(1)],
                              prefetch_size=2)
  got = []
  while True:
    try:
      got.append(ch.recv(timeout_ms=10_000))
    except StopIteration:
      break
  assert len(got) == 10
  per = {0: [], 1: []}
  for m in got:
    per[int(m['sid'][0])].append(int(m['i'][0]))
  assert per[0] == list(range(5)) and per[1] == list(range(5))


def test_remote_channel_reset_discards_stale_epoch():
  """A partially-consumed epoch must not leak messages (or pullers) into
  the next epoch after reset()."""
  from glt_tpu.channel import RemoteReceivingChannel
  epoch = {'n': 0}
  def make_fetcher(server_id, n=50):
    state = {'i': 0, 'epoch': None}
    def fetch():
      if state['epoch'] != epoch['n']:
        state['epoch'] = epoch['n']
        state['i'] = 0
      if state['i'] >= n:
        raise StopIteration
      i = state['i']; state['i'] += 1
      return {'epoch': np.array([epoch['n']]), 'i': np.array([i])}
    return fetch
  ch = RemoteReceivingChannel([make_fetcher(0), make_fetcher(1)],
                              prefetch_size=2)
  # consume only 3 of 100 messages, then abandon the epoch. stop()
  # before flipping the epoch so no stale in-flight fetch can consume an
  # epoch-1 item through the shared fetcher closures.
  for _ in range(3):
    ch.recv(timeout_ms=10_000)
  ch.stop()
  epoch['n'] = 1
  ch.reset()
  got = []
  while True:
    try:
      got.append(ch.recv(timeout_ms=10_000))
    except StopIteration:
      break
  assert len(got) == 100
  assert all(int(m['epoch'][0]) == 1 for m in got)
  # a second clean epoch still terminates correctly
  ch.stop()
  epoch['n'] = 2
  ch.reset()
  n2 = 0
  while True:
    try:
      ch.recv(timeout_ms=10_000); n2 += 1
    except StopIteration:
      break
  assert n2 == 100


def test_remote_channel_per_server_readahead_bound():
  """One fast server must not fill the whole window: each server's
  readahead is individually bounded by prefetch_size."""
  import time as _time
  from glt_tpu.channel import RemoteReceivingChannel
  pulled = {0: 0, 1: 0}
  def make_fetcher(server_id, delay):
    def fetch():
      _time.sleep(delay)
      pulled[server_id] += 1
      return {'sid': np.array([server_id])}
    return fetch
  ch = RemoteReceivingChannel([make_fetcher(0, 0.0),
                               make_fetcher(1, 0.05)], prefetch_size=3)
  ch.reset()
  _time.sleep(0.5)  # let pullers run without any consumption
  # fast server holds at most prefetch_size buffered + 1 in-flight
  assert pulled[0] <= 4, pulled
  ch.stop()


def test_table_dataset_from_csv(tmp_path):
  from glt_tpu.data import TableDataset, csv_edge_reader
  p = tmp_path / 'edges.csv'
  p.write_text('0,1\n1,2\n2,0\n0,2\n')
  ds = TableDataset(edge_dir='out')
  ds.load(edge_reader=csv_edge_reader(str(p)), num_nodes=3)
  g = ds.get_graph()
  assert g.num_nodes == 3 and g.num_edges == 4
  np.testing.assert_array_equal(g.degree(np.array([0, 1, 2])), [2, 1, 1])


def test_table_dataset_node_reader():
  from glt_tpu.data import TableDataset
  def node_reader():
    yield (np.array([0, 2]), np.array([[1.], [3.]], np.float32),
           np.array([7, 9]))
    yield (np.array([1]), np.array([[2.]], np.float32), np.array([8]))
  ds = TableDataset()
  ds.load(edge_reader=[(np.array([0, 1]), np.array([1, 2]))],
          node_reader=node_reader(), num_nodes=3)
  np.testing.assert_allclose(ds.get_node_feature()[np.arange(3)][:, 0],
                             [1., 2., 3.])
  np.testing.assert_array_equal(ds.get_node_label(), [7, 8, 9])


def test_checkpoint_roundtrip(tmp_path):
  import jax.numpy as jnp
  from glt_tpu.utils.checkpoint import restore_checkpoint, save_checkpoint
  params = {'w': jnp.arange(6.0).reshape(2, 3), 'b': jnp.zeros(3)}
  save_checkpoint(str(tmp_path / 'ckpt'), step=5, params=params,
                  extra={'epoch': 2})
  step, payload = restore_checkpoint(str(tmp_path / 'ckpt'))
  assert step == 5
  np.testing.assert_allclose(np.asarray(payload['params']['w']),
                             np.arange(6.0).reshape(2, 3))
  assert payload['extra']['epoch'] == 2


def test_mllog_format(capsys):
  from glt_tpu.utils.mlperf_logging import MLLogger
  lines = []
  log = MLLogger(emit=lines.append)
  log.run_start()
  log.eval_accuracy(0.78, epoch=1)
  log.run_stop()
  assert len(lines) == 3
  import json as _json
  for l in lines:
    assert l.startswith(':::MLLOG ')
    rec = _json.loads(l[len(':::MLLOG '):])
    assert 'key' in rec and 'time_ms' in rec


def _stress_producer(chan, pid, n):
  for i in range(n):
    chan.send({'pid': np.array([pid]), 'i': np.array([i]),
               'data': np.full((pid + 1) * 7, i, np.int32)})


def test_shm_channel_multi_producer_multi_consumer():
  """3 producer processes, 2 consumer threads, one 64KB ring: every
  message arrives exactly once, per-producer order preserved."""
  import threading
  chan = ShmChannel(capacity_bytes=1 << 16)
  try:
    ctx = mp.get_context('spawn')
    n = 60
    procs = [ctx.Process(target=_stress_producer, args=(chan, p, n))
             for p in range(3)]
    for p in procs:
      p.start()
    got = []
    lock = threading.Lock()
    def consume(k):
      while True:
        with lock:
          if len(got) >= 3 * n:
            return
        try:
          msg = chan.recv(timeout_ms=5_000)
        except Exception:
          # spawn startup re-imports the package in each child (slow
          # under load); keep polling while any producer might still
          # send rather than treating one timeout as end-of-stream.
          # After the last producer exits, one final drain pass covers
          # messages sent between the timeout and the liveness check.
          if any(p.is_alive() for p in procs):
            continue
          try:
            while True:
              msg = chan.recv(timeout_ms=200)
              with lock:
                got.append((int(msg['pid'][0]), int(msg['i'][0]),
                            msg['data'].copy()))
          except Exception:  # gltlint: disable=GLT006
            pass  # drain runs until recv times out: that IS the exit
          return
        with lock:
          got.append((int(msg['pid'][0]), int(msg['i'][0]),
                      msg['data'].copy()))
    threads = [threading.Thread(target=consume, args=(k,))
               for k in range(2)]
    for t in threads:
      t.start()
    for p in procs:
      p.join(timeout=60)
    for t in threads:
      t.join(timeout=60)
    assert len(got) == 3 * n
    per = {0: [], 1: [], 2: []}
    for pid, i, data in got:
      per[pid].append(i)
      assert data.shape[0] == (pid + 1) * 7
      assert (data == i).all()
    for pid in per:
      assert sorted(per[pid]) == list(range(n))
  finally:
    chan.close()


def test_table_dataset_hetero_tables(tmp_path):
  """Hetero table loading (reference TableDataset.load edge_tables/
  node_tables dicts) via the reader protocol + CSV stand-ins."""
  from glt_tpu.data import TableDataset, csv_edge_reader, csv_node_reader
  u2i = ('user', 'buys', 'item')
  i2i = ('item', 'sim', 'item')
  (tmp_path / 'u2i.csv').write_text('0,0\n1,1\n2,0\n')
  (tmp_path / 'i2i.csv').write_text('0,1\n1,0\n')
  (tmp_path / 'users.csv').write_text(
      '0,1:0,0\n1,0:1,1\n2,1:1,0\n')
  (tmp_path / 'items.csv').write_text('0,5:5\n1,6:6\n')
  ds = TableDataset(edge_dir='out').load_tables(
      edge_tables={u2i: csv_edge_reader(str(tmp_path / 'u2i.csv')),
                   i2i: csv_edge_reader(str(tmp_path / 'i2i.csv'))},
      node_tables={'user': csv_node_reader(str(tmp_path / 'users.csv'),
                                           label_col=2),
                   'item': csv_node_reader(str(tmp_path / 'items.csv'))})
  assert ds.is_hetero
  assert ds.graph[u2i].num_edges == 3
  assert ds.graph[i2i].num_edges == 2
  np.testing.assert_allclose(
      ds.node_features['item'][np.array([1])][0], [6, 6])
  np.testing.assert_array_equal(np.asarray(ds.node_labels['user']),
                                [0, 1, 0])


def test_odps_reader_gated():
  import pytest
  from glt_tpu.data import odps_table_reader
  with pytest.raises(ImportError):
    next(iter(odps_table_reader('odps://proj/tables/edges')))


def test_native_shm_queue_binary():
  """Build and run the native C++ test binary (the reference keeps
  googletest binaries for its native layer; csrc/shm_queue_test.cc is
  the plain-assert equivalent)."""
  import os
  import subprocess
  csrc = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), 'glt_tpu', 'csrc')
  out = subprocess.run(['make', '-C', csrc, 'test'],
                       capture_output=True, text=True, timeout=300)
  assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
  assert 'ALL NATIVE TESTS PASSED' in out.stdout

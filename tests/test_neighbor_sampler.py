"""NeighborSampler tests on the deterministic ring fixture (reference
strategy: req_num >= degree makes sampling exhaustive; ring adjacency is
formulaic, test_neighbor_sampler.py:25-80 upstream)."""
import jax.numpy as jnp
import numpy as np
import pytest

from glt_tpu.sampler import NeighborSampler, NodeSamplerInput

from fixtures import ring_dataset, hetero_ring_dataset


@pytest.fixture(scope='module')
def ring():
  return ring_dataset(num_nodes=40)


def _valid_nodes(out):
  n = np.asarray(out.node)
  return n[:int(out.node_count)]


def test_one_hop_exhaustive(ring):
  s = NeighborSampler(ring.get_graph(), [2], seed=7)
  out = s.sample_from_nodes(np.array([0, 10]))
  nodes = _valid_nodes(out)
  # seeds first, then neighbors (v+1, v+2) % 40 in first-occurrence order
  np.testing.assert_array_equal(nodes, [0, 10, 1, 2, 11, 12])
  em = np.asarray(out.edge_mask)
  rows = np.asarray(out.row)[em]   # children
  cols = np.asarray(out.col)[em]   # parents
  got = sorted(zip(cols.tolist(), rows.tolist()))
  # parent label -> child label: 0->{2(=node1),3(=node2)}, 1->{4,5}
  assert got == [(0, 2), (0, 3), (1, 4), (1, 5)]
  np.testing.assert_array_equal(np.asarray(out.num_sampled_nodes), [2, 4])
  np.testing.assert_array_equal(np.asarray(out.num_sampled_edges), [4])


def test_two_hop_ring_closure(ring):
  # nodes 0's 2-hop neighborhood in the ring: {0,1,2,3,4}
  s = NeighborSampler(ring.get_graph(), [2, 2], seed=3)
  out = s.sample_from_nodes(np.array([0]))
  nodes = set(_valid_nodes(out).tolist())
  assert nodes == {0, 1, 2, 3, 4}
  # every valid edge satisfies the ring relation child = (parent+1|2) % 40
  em = np.asarray(out.edge_mask)
  node_arr = np.asarray(out.node)
  child = node_arr[np.asarray(out.row)[em]]
  parent = node_arr[np.asarray(out.col)[em]]
  for p, c in zip(parent, child):
    assert c % 40 in ((p + 1) % 40, (p + 2) % 40)


def test_edge_ids_recoverable(ring):
  s = NeighborSampler(ring.get_graph(), [2], with_edge=True, seed=1)
  out = s.sample_from_nodes(np.array([5]))
  em = np.asarray(out.edge_mask)
  eids = np.asarray(out.edge)[em]
  # node 5's out-edges have eids 10, 11
  assert set(eids.tolist()) == {10, 11}


def test_padded_seed_batch(ring):
  s = NeighborSampler(ring.get_graph(), [2], seed=0)
  seeds = np.array([7, 8, 0, 0])  # last two are padding
  out = s.sample_from_nodes(seeds, n_valid=2)
  nodes = _valid_nodes(out)
  assert set(nodes.tolist()) == {7, 8, 9, 10}
  assert int(np.asarray(out.num_sampled_nodes)[0]) == 2


def test_fanout_smaller_than_degree_distinct(ring):
  s = NeighborSampler(ring.get_graph(), [1], seed=11)
  seen = set()
  for trial in range(30):
    out = s.sample_from_nodes(np.array([0]))
    em = np.asarray(out.edge_mask)
    assert em.sum() == 1
    child = np.asarray(out.node)[np.asarray(out.row)[em][0]]
    assert child in (1, 2)
    seen.add(int(child))
  assert seen == {1, 2}  # both neighbors eventually sampled


def test_weighted_sampler_runs(ring=None):
  ds = ring_dataset(num_nodes=20, weighted=True)
  s = NeighborSampler(ds.get_graph(), [2], with_weight=True, seed=5)
  out = s.sample_from_nodes(np.array([0, 5]))
  nodes = _valid_nodes(out)
  assert set(nodes.tolist()) == {0, 5, 1, 2, 6, 7}


def test_sampler_batches_are_independent(ring):
  # table reset between batches: second batch labels start from scratch
  s = NeighborSampler(ring.get_graph(), [2], seed=2)
  out1 = s.sample_from_nodes(np.array([0]))
  out2 = s.sample_from_nodes(np.array([20]))
  np.testing.assert_array_equal(_valid_nodes(out2), [20, 21, 22])


def test_sample_prob(ring):
  s = NeighborSampler(ring.get_graph(), [2, 2], seed=2)
  probs = np.asarray(s.sample_prob(np.array([0]), 40))
  assert probs[0] == 1.0
  assert probs[1] == 1.0 and probs[2] == 1.0   # deg=2 <= fanout
  assert probs[3] > 0 and probs[4] > 0          # second hop reached
  assert probs[10] == 0.0


def test_subgraph_via_sampler(ring):
  s = NeighborSampler(ring.get_graph(), [2, 2], with_edge=True, seed=0)
  sub = s.subgraph(np.array([0]))
  # nodes {0..4}; induced edges are all (v -> v+1|v+2) pairs within the set
  nodes = np.asarray(sub.nodes)[:int(sub.node_count)]
  assert set(nodes.tolist()) == {0, 1, 2, 3, 4}
  em = np.asarray(sub.edge_mask)
  pairs = {(int(nodes[r]), int(nodes[c]))
           for r, c in zip(np.asarray(sub.rows)[em], np.asarray(sub.cols)[em])}
  assert pairs == {(0, 1), (0, 2), (1, 2), (1, 3), (2, 3), (2, 4), (3, 4)}


# -- hetero -------------------------------------------------------------

@pytest.fixture(scope='module')
def hetero():
  return hetero_ring_dataset(num_users=10, num_items=20)


def test_hetero_sample_out_direction(hetero):
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  s = NeighborSampler(hetero.graph, {u2i: [2, 2], i2i: [2, 2]}, seed=4)
  out = s.sample_from_nodes(NodeSamplerInput(np.array([3]), 'user'))
  # user 3 -> items {6,7}; hop2: i2i from {6,7} -> {7,8,9} (+u2i has no
  # user frontier at hop 2)
  items = np.asarray(out.node['item'])[:int(out.node_count['item'])]
  assert set(items.tolist()) == {6, 7, 8, 9}
  users = np.asarray(out.node['user'])[:int(out.node_count['user'])]
  np.testing.assert_array_equal(users, [3])
  # 'out' direction: keys are reversed types
  rev_u2i = ('item', 'rev_u2i', 'user')
  rev_i2i = ('item', 'i2i', 'item')  # same src/dst type keeps its name
  assert rev_u2i in out.row
  em = np.asarray(out.edge_mask[rev_u2i])
  child_items = np.asarray(out.node['item'])[np.asarray(out.row[rev_u2i])[em]]
  parent_users = np.asarray(out.node['user'])[np.asarray(out.col[rev_u2i])[em]]
  assert set(child_items.tolist()) == {6, 7}
  assert set(parent_users.tolist()) == {3}
  # i2i edges follow the ring relation
  em2 = np.asarray(out.edge_mask[rev_i2i])
  child = np.asarray(out.node['item'])[np.asarray(out.row[rev_i2i])[em2]]
  parent = np.asarray(out.node['item'])[np.asarray(out.col[rev_i2i])[em2]]
  for p, c in zip(parent, child):
    assert c in ((p + 1) % 20, (p + 2) % 20)


def test_hetero_num_sampled_counts(hetero):
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  s = NeighborSampler(hetero.graph, {u2i: [2], i2i: [2]}, seed=4)
  out = s.sample_from_nodes(NodeSamplerInput(np.array([0, 1]), 'user'))
  np.testing.assert_array_equal(
      np.asarray(out.num_sampled_nodes['user']), [2, 0])
  np.testing.assert_array_equal(
      np.asarray(out.num_sampled_nodes['item']), [0, 4])


def test_hetero_sample_prob(hetero):
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  s = NeighborSampler(hetero.graph, {u2i: [2], i2i: [2]}, seed=0)
  probs = s.sample_prob(('user', np.array([3])))
  u = np.asarray(probs['user'])
  it = np.asarray(probs['item'])
  assert u[3] == 1.0 and u.sum() == 1.0      # only the seed user
  # user 3 -> items {6, 7} (deg 2 <= fanout 2 -> prob 1)
  assert it[6] == 1.0 and it[7] == 1.0
  assert it[[0, 1, 2, 3]].sum() == 0.0


# -- fanout = -1 (full neighborhood) ------------------------------------

def _random_var_degree_dataset(n=25, seed=42):
  from glt_tpu.data import Dataset
  rng = np.random.default_rng(seed)
  edges = set()
  for v in range(n):
    for w in rng.choice(n, int(rng.integers(0, 7)), replace=False):
      if int(w) != v:
        edges.add((v, int(w)))
  edges = sorted(edges)
  rows = np.array([e[0] for e in edges], np.int64)
  cols = np.array([e[1] for e in edges], np.int64)
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index=np.stack([rows, cols]), num_nodes=n)
  adj = {v: sorted(w for (x, w) in edges if x == v) for v in range(n)}
  return ds, adj


def test_full_neighborhood_two_hop_exact():
  """NeighborSampler([-1, -1]) must reproduce the dense 2-hop expansion
  exactly (reference fanout=-1 semantics, seal_link_pred.py:45-59)."""
  ds, adj = _random_var_degree_dataset()
  s = NeighborSampler(ds.get_graph(), [-1, -1], seed=0)
  seeds = [3, 17]
  out = s.sample_from_nodes(np.array(seeds))

  node = np.asarray(out.node)
  em = np.asarray(out.edge_mask)
  child = node[np.asarray(out.row)]
  parent = node[np.asarray(out.col)]
  offs = out.edge_hop_offsets

  # hop 1: exactly every out-edge of every seed
  got1 = sorted((int(parent[i]), int(child[i]))
                for i in range(offs[0], offs[1]) if em[i])
  want1 = sorted((v, w) for v in seeds for w in adj[v])
  assert got1 == want1

  # hop 2: every out-edge of every node first seen in hop 1
  seen = list(seeds)
  lvl1_new = []
  for i in range(offs[0], offs[1]):
    if em[i] and int(child[i]) not in seen:
      seen.append(int(child[i]))
      lvl1_new.append(int(child[i]))
  got2 = sorted((int(parent[i]), int(child[i]))
                for i in range(offs[1], offs[2]) if em[i])
  want2 = sorted((v, w) for v in lvl1_new for w in adj[v])
  assert got2 == want2

  # node set is the exact 2-hop closure
  closure = set(seeds)
  closure |= {w for v in seeds for w in adj[v]}
  closure |= {w for v in list(closure) for w in adj[v]}
  assert set(node[:int(out.node_count)].tolist()) == closure


def test_full_neighborhood_cap_truncates():
  ds, adj = _random_var_degree_dataset()
  s = NeighborSampler(ds.get_graph(), [-1], seed=0, full_neighbor_cap=2)
  out = s.sample_from_nodes(np.array([3]))
  em = np.asarray(out.edge_mask)
  # window of 2: at most 2 neighbors survive, in adjacency order
  got = sorted(np.asarray(out.node)[np.asarray(out.row)[em]].tolist())
  assert got == sorted(adj[3][:2])


def test_full_neighborhood_mixed_with_sampled_hop():
  """[-1, K] mixes a full hop with a sampled hop."""
  ds, adj = _random_var_degree_dataset()
  s = NeighborSampler(ds.get_graph(), [-1, 1], seed=5)
  out = s.sample_from_nodes(np.array([3]))
  offs = out.edge_hop_offsets
  em = np.asarray(out.edge_mask)
  node = np.asarray(out.node)
  got1 = sorted(node[np.asarray(out.row)[offs[0]:offs[1]]]
                [em[offs[0]:offs[1]]].tolist())
  assert got1 == adj[3]
  # hop 2: each new frontier node contributes at most 1 sampled edge
  parents2 = node[np.asarray(out.col)[offs[1]:offs[2]]][em[offs[1]:offs[2]]]
  cnt = {}
  for p in parents2.tolist():
    cnt[p] = cnt.get(p, 0) + 1
  assert all(c == 1 for c in cnt.values())


def test_rbg_prng_sampler(monkeypatch, ring):
  # GLT_PRNG=rbg swaps the PRNG implementation inside the typed key;
  # sampling semantics (exhaustive when deg <= fanout) are unchanged
  monkeypatch.setenv('GLT_PRNG', 'rbg')
  s = NeighborSampler(ring.get_graph(), [2], seed=7)
  out = s.sample_from_nodes(np.array([0, 5]))
  nodes = np.asarray(out.node)[:int(out.node_count)]
  assert set(nodes.tolist()) == {0, 1, 2, 5, 6, 7}

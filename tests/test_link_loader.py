"""Link sampling / LinkNeighborLoader / SubGraphLoader tests."""
import jax.numpy as jnp
import numpy as np
import pytest

from glt_tpu.loader import LinkNeighborLoader, SubGraphLoader
from glt_tpu.sampler import (
    EdgeSamplerInput, NegativeSampling, NeighborSampler,
)

from fixtures import ring_dataset


@pytest.fixture(scope='module')
def ring():
  return ring_dataset(num_nodes=40)


def test_sample_from_edges_binary(ring):
  s = NeighborSampler(ring.get_graph(), [2], seed=0)
  rows = np.array([0, 1, 2, 3])
  cols = (rows + 1) % 40
  out = s.sample_from_edges(EdgeSamplerInput(
      rows, cols, neg_sampling=NegativeSampling('binary', amount=1)))
  meta = out.metadata
  eli = np.asarray(meta['edge_label_index'])
  assert eli.shape == (2, 8)   # 4 pos + 4 neg
  lab = np.asarray(meta['edge_label'])
  np.testing.assert_array_equal(lab, [1, 1, 1, 1, 0, 0, 0, 0])
  # labels resolve back to the original endpoints
  node = np.asarray(out.node)
  np.testing.assert_array_equal(node[eli[0, :4]], rows)
  np.testing.assert_array_equal(node[eli[1, :4]], cols)


def test_sample_from_edges_triplet(ring):
  s = NeighborSampler(ring.get_graph(), [2], seed=1)
  rows = np.array([5, 6])
  cols = (rows + 2) % 40
  out = s.sample_from_edges(EdgeSamplerInput(
      rows, cols, neg_sampling=NegativeSampling('triplet', amount=2)))
  meta = out.metadata
  node = np.asarray(out.node)
  np.testing.assert_array_equal(node[np.asarray(meta['src_index'])], rows)
  np.testing.assert_array_equal(node[np.asarray(meta['dst_pos_index'])],
                                cols)
  assert np.asarray(meta['dst_neg_index']).shape == (2, 2)


def test_link_neighbor_loader_epoch(ring):
  loader = LinkNeighborLoader(
      ring, [2], batch_size=16, shuffle=True, seed=0,
      neg_sampling=NegativeSampling('binary', amount=1),
      rng=np.random.default_rng(3))
  batches = list(loader)
  assert len(batches) == 5  # 80 edges / 16
  b = batches[0]
  eli = np.asarray(b.metadata['edge_label_index'])
  assert eli.shape == (2, 32)
  node = np.asarray(b.node)
  # positive pairs obey the ring relation
  src = node[eli[0, :16]]
  dst = node[eli[1, :16]]
  for u, v in zip(src, dst):
    assert v in ((u + 1) % 40, (u + 2) % 40)
  # features present for all valid nodes
  nc = int(b.node_count)
  np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], node[:nc])


def test_subgraph_loader(ring):
  loader = SubGraphLoader(ring, [2, 2], input_nodes=np.arange(8),
                          batch_size=8, seed=0)
  b = next(iter(loader))
  nc = int(b.node_count)
  nodes = np.asarray(b.node)[:nc]
  # 2-hop from seeds 0..7 covers 0..11
  assert set(nodes.tolist()) == set(range(12))
  em = np.asarray(b.edge_mask)
  child = nodes[np.asarray(b.row)[em]]
  parent = nodes[np.asarray(b.col)[em]]
  for p, c in zip(parent, child):
    assert c in ((p + 1) % 40, (p + 2) % 40)
  np.testing.assert_allclose(np.asarray(b.x)[:nc, 0], nodes)


def test_bipartite_link_sampling():
  """Two-type (user->item) link sampling: the bipartite_sage_unsup
  workload shape. Seeds both type spaces in one call."""
  from fixtures import hetero_ring_dataset
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  s = NeighborSampler(ds.graph, {u2i: [2], i2i: [2]}, seed=0)
  rows = np.array([0, 1, 2, 3])          # users
  cols = (2 * rows) % 20                 # their items
  out = s.sample_from_edges(EdgeSamplerInput(
      rows, cols, input_type=u2i,
      neg_sampling=NegativeSampling('binary', amount=1)))
  meta = out.metadata
  eli = np.asarray(meta['edge_label_index'])
  assert eli.shape == (2, 8)
  users = np.asarray(out.node['user'])
  items = np.asarray(out.node['item'])
  np.testing.assert_array_equal(users[eli[0, :4]], rows)
  np.testing.assert_array_equal(items[eli[1, :4]], cols)
  np.testing.assert_array_equal(np.asarray(meta['edge_label']),
                                [1, 1, 1, 1, 0, 0, 0, 0])
  # negatives live in valid id spaces
  assert users[eli[0, 4:]].max() < 10
  assert items[eli[1, 4:]].max() < 20


def test_bipartite_triplet_sampling():
  from fixtures import hetero_ring_dataset
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  s = NeighborSampler(ds.graph, {u2i: [2], i2i: [2]}, seed=1)
  rows = np.array([4, 5])
  cols = (2 * rows) % 20
  out = s.sample_from_edges(EdgeSamplerInput(
      rows, cols, input_type=u2i,
      neg_sampling=NegativeSampling('triplet', amount=3)))
  meta = out.metadata
  users = np.asarray(out.node['user'])
  items = np.asarray(out.node['item'])
  np.testing.assert_array_equal(users[np.asarray(meta['src_index'])],
                                rows)
  np.testing.assert_array_equal(items[np.asarray(meta['dst_pos_index'])],
                                cols)
  assert np.asarray(meta['dst_neg_index']).shape == (2, 3)

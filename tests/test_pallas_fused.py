"""The `pallas_fused` hop engine (ops/pallas_kernels.py::
sample_hop_dedup + dedup_table_insert, routed via
ops/sample.py::FusedHopPlan).

Acceptance contract (ISSUE 10): the fused sample+dedup(+gather)
pipeline is BIT-IDENTICAL to the `sort+fused` engine (GLT_DEDUP=sort
GLT_FUSED_HOP=1) in interpret mode — same labels (new ids in within-hop
value order, seed hop exact), same node list, same counts — with the
documented exception that `edge`/`nbrs` values on MASKED-OUT lanes are
undefined per engine (same contract as tests/test_pallas_hop.py; full
equality holds against a window-read reference, which reads the same
physical slots). Zero steady-state recompiles must hold with the
engine forced, for the plain sampler, the serving engine, and the
stream sampler (which falls back to `pallas` for its overlay hops,
counted in hop_engine_fallbacks_total).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from glt_tpu.data import Topology
from glt_tpu.ops.pipeline import (make_dedup_tables, multihop_sample,
                                  multihop_sample_many, sample_budget)
from glt_tpu.ops.sample import FusedHopPlan, sample_neighbors
from glt_tpu.ops.pallas_kernels import fused_table_slots

from fixtures import ring_dataset

pytestmark = pytest.mark.pallas

W = 8

EXACT_KEYS = ('node', 'node_count', 'row', 'col', 'edge_mask', 'batch',
              'seed_labels', 'seed_count', 'num_sampled_nodes',
              'num_sampled_edges')


def _graph(n=64, e=600, seed=0):
  rng = np.random.default_rng(seed)
  src = rng.integers(0, n, e)
  dst = rng.integers(0, n, e)
  t = Topology(edge_index=np.stack([src, dst]), num_nodes=n)
  indptr = jnp.asarray(t.indptr.astype(np.int32))
  indices = jnp.asarray(t.indices)
  iw = jnp.concatenate([indices, jnp.full((W,), -1, indices.dtype)])
  eids = jnp.arange(indices.shape[0], dtype=jnp.int32) * 3
  ew = jnp.concatenate([eids, jnp.full((W,), -1, eids.dtype)])
  n_hub = int((np.diff(t.indptr) > W).sum())
  return dict(n=n, topo=t, indptr=indptr, indices=indices, iw=iw,
              eids=eids, ew=ew, n_hub=n_hub)


def _plan(g, fanouts, batch, with_edge=False, replace=False,
          **gather_kw):
  return FusedHopPlan(
      g['indptr'], g['indices'], g['iw'], W, g['n_hub'],
      fused_table_slots(sample_budget(batch, list(fanouts))),
      edge_ids=g['eids'] if with_edge else None,
      edge_ids_win=g['ew'] if with_edge else None,
      replace=replace, interpret=True, **gather_kw)


def _ref_sort_fused(g, seeds, nv, fanouts, key, monkeypatch,
                    with_edge=False, window_read=False, replace=False):
  """The reference engine: GLT_DEDUP=sort + GLT_FUSED_HOP=1.
  window_read=True reads neighbor values through the same padded
  windows as the kernel, making even masked-lane junk identical."""
  monkeypatch.setenv('GLT_DEDUP', 'sort')
  monkeypatch.setenv('GLT_FUSED_HOP', '1')
  kw = {}
  if window_read:
    kw = dict(window=(W, None), indices_win=g['iw'],
              edge_ids_win=g['ew'] if with_edge else None,
              engine='window')
  def one_hop(ids, f, k, m):
    w = dict(kw)
    if window_read:
      w['window'] = (W, min(g['n_hub'], ids.shape[0]))
    return sample_neighbors(
        g['indptr'], g['indices'], ids, f, k, seed_mask=m,
        edge_ids=g['eids'] if with_edge else None, replace=replace, **w)
  table, scratch = make_dedup_tables(g['n'])
  out, _, _ = multihop_sample(one_hop, seeds, nv, fanouts, key, table,
                              scratch, with_edge=with_edge)
  monkeypatch.delenv('GLT_DEDUP')
  monkeypatch.delenv('GLT_FUSED_HOP')
  return jax.tree.map(np.asarray, out)


@pytest.mark.parametrize('with_edge', [False, True])
@pytest.mark.parametrize('fanouts', [(3,), (3, 2)])
def test_multihop_bit_identical_to_sort_fused(monkeypatch, fanouts,
                                              with_edge):
  g = _graph()
  seeds = jnp.asarray(np.array([5, 0, 5, 17, 63, 2, 2, 9], np.int32))
  nv = jnp.asarray(7)
  key = jax.random.key(0)
  ref = _ref_sort_fused(g, seeds, nv, fanouts, key, monkeypatch,
                        with_edge=with_edge)
  table, scratch = make_dedup_tables(g['n'])
  got, _, _ = multihop_sample(
      None, seeds, nv, fanouts, key, table, scratch,
      with_edge=with_edge,
      fused_plan=_plan(g, fanouts, seeds.shape[0], with_edge=with_edge))
  for k in EXACT_KEYS:
    np.testing.assert_array_equal(ref[k], np.asarray(got[k]),
                                  err_msg=k)
  if with_edge:
    m = ref['edge_mask'].astype(bool)
    np.testing.assert_array_equal(ref['edge'][m],
                                  np.asarray(got['edge'])[m])


def test_edge_full_parity_vs_window_reference(monkeypatch):
  # against a window-read reference even the masked-lane junk matches:
  # both engines read the same physical window slots
  g = _graph(seed=3)
  seeds = jnp.asarray(np.arange(10, dtype=np.int32))
  nv = jnp.asarray(10)
  key = jax.random.key(1)
  fanouts = (3, 2)
  ref = _ref_sort_fused(g, seeds, nv, fanouts, key, monkeypatch,
                        with_edge=True, window_read=True)
  table, scratch = make_dedup_tables(g['n'])
  got, _, _ = multihop_sample(
      None, seeds, nv, fanouts, key, table, scratch, with_edge=True,
      fused_plan=_plan(g, fanouts, seeds.shape[0], with_edge=True))
  np.testing.assert_array_equal(ref['edge'], np.asarray(got['edge']))


def test_replace_and_empty_frontier(monkeypatch):
  g = _graph(seed=5)
  fanouts = (4,)
  seeds = jnp.asarray(np.array([1, 2, 3, 4], np.int32))
  key = jax.random.key(2)
  # sampling WITH replacement
  ref = _ref_sort_fused(g, seeds, jnp.asarray(4), fanouts, key,
                        monkeypatch, replace=True)
  table, scratch = make_dedup_tables(g['n'])
  got, _, _ = multihop_sample(
      None, seeds, jnp.asarray(4), fanouts, key, table, scratch,
      fused_plan=_plan(g, fanouts, 4, replace=True))
  for k in EXACT_KEYS:
    np.testing.assert_array_equal(ref[k], np.asarray(got[k]), err_msg=k)
  # fully-masked batch (n_valid = 0): every surface empty/-1, both
  ref0 = _ref_sort_fused(g, seeds, jnp.asarray(0), fanouts, key,
                         monkeypatch)
  got0, _, _ = multihop_sample(
      None, seeds, jnp.asarray(0), fanouts, key, table, scratch,
      fused_plan=_plan(g, fanouts, 4))
  for k in EXACT_KEYS:
    np.testing.assert_array_equal(ref0[k], np.asarray(got0[k]),
                                  err_msg=k)
  assert int(got0['node_count']) == 0


def test_multihop_many_scan_parity(monkeypatch):
  # the lax.scan entry point (bench scan>1): fresh VMEM table per scan
  # step, results identical to per-batch fused calls
  g = _graph(seed=7)
  fanouts = (3, 2)
  seeds = jnp.asarray(
      np.random.default_rng(0).integers(0, g['n'], (3, 6)).astype(
          np.int32))
  nv = jnp.full((3,), 6, jnp.int32)
  key = jax.random.key(4)
  plan = _plan(g, fanouts, 6)
  table, scratch = make_dedup_tables(g['n'])
  outs, _, _ = multihop_sample_many(None, seeds, nv, fanouts, key,
                                    table, scratch, fused_plan=plan)
  k = key
  for t in range(3):
    k, sub = jax.random.split(k)
    one, _, _ = multihop_sample(None, seeds[t], nv[t], fanouts, sub,
                                table, scratch, fused_plan=plan)
    np.testing.assert_array_equal(np.asarray(outs['node'])[t],
                                  np.asarray(one['node']))
    np.testing.assert_array_equal(np.asarray(outs['row'])[t],
                                  np.asarray(one['row']))


# -- sampler / serving / stream wiring ----------------------------------

def test_sampler_forced_engine_parity_and_zero_recompiles(monkeypatch):
  from glt_tpu.sampler import NeighborSampler
  ds = ring_dataset(num_nodes=40)
  monkeypatch.setenv('GLT_DEDUP', 'sort')
  monkeypatch.setenv('GLT_FUSED_HOP', '1')
  seeds = np.arange(8)
  base = NeighborSampler(ds.get_graph(), [3, 2], seed=0,
                         with_edge=True).sample_from_nodes(seeds)
  monkeypatch.delenv('GLT_DEDUP')
  monkeypatch.delenv('GLT_FUSED_HOP')
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0, with_edge=True)
  out = samp.sample_from_nodes(seeds)
  for f in ('node', 'row', 'col', 'edge_mask', 'batch'):
    np.testing.assert_array_equal(np.asarray(getattr(base, f)),
                                  np.asarray(getattr(out, f)),
                                  err_msg=f)
  m = np.asarray(base.edge_mask).astype(bool)
  np.testing.assert_array_equal(np.asarray(base.edge)[m],
                                np.asarray(out.edge)[m])
  assert samp.num_compiled_fns == 1
  for _ in range(3):   # steady state: the one program serves every call
    samp.sample_from_nodes(seeds)
  assert samp.num_compiled_fns == 1


def test_two_batch_shapes_share_the_padded_arrays(monkeypatch):
  # regression mirror of test_pallas_hop: window_arrays must stay
  # concrete across two trace-time plan builds over the same graph
  from glt_tpu.sampler import NeighborSampler
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  ds = ring_dataset(num_nodes=40)
  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0)
  out4 = samp.sample_from_nodes(np.arange(4))    # trace 1
  out8 = samp.sample_from_nodes(np.arange(8))    # trace 2: same graph
  assert samp.num_compiled_fns == 2
  assert int(out4.node_count) > 0 and int(out8.node_count) > 0


def test_fused_gather_matches_gather_features(monkeypatch):
  # in-walk gather == post-hoc gather_features, EVERY lane including
  # the -1 padding, and the row_gather override rides the fused path
  from glt_tpu.data.feature import gather_features
  from glt_tpu.sampler import NeighborSampler
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  ds = ring_dataset(num_nodes=40)
  feat = ds.get_node_feature()
  calls = {'n': 0}

  def counting_row_gather(table, rows):
    calls['n'] += 1  # trace-time counter: the override must be USED
    return jnp.take(table, jnp.clip(rows, 0, table.shape[0] - 1),
                    axis=0)

  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0,
                         fused_feature=feat,
                         row_gather=counting_row_gather)
  out = samp.sample_from_nodes(np.arange(8))
  assert calls['n'] > 0, 'row_gather override never reached'
  fused_x = out.metadata['node_feats']
  ref_x = gather_features(feat, out.node)
  np.testing.assert_array_equal(np.asarray(ref_x), np.asarray(fused_x))


def test_serving_engine_fused_parity_and_zero_recompiles(monkeypatch):
  # the serving call site composes: a fused sampler's node_feats ride
  # gather_features(fused=) into the bucket pipeline; embeddings match
  # the sort+fused engine and warmup compiles stay flat
  from glt_tpu.serving import InferenceEngine
  from glt_tpu.sampler import NeighborSampler
  ds = ring_dataset(num_nodes=40)
  apply_fn = lambda params, batch: batch.x[:, :4] * 2.0

  monkeypatch.setenv('GLT_DEDUP', 'sort')
  monkeypatch.setenv('GLT_FUSED_HOP', '1')
  base = InferenceEngine(ds, model=None, params={}, num_neighbors=[3, 2],
                         buckets=(8,), apply_fn=apply_fn, seed=0,
                         cache_capacity=0)
  base.warmup()
  want = base.infer(np.arange(6))
  monkeypatch.delenv('GLT_DEDUP')
  monkeypatch.delenv('GLT_FUSED_HOP')

  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0,
                         fused_feature=ds.get_node_feature())
  eng = InferenceEngine(ds, model=None, params={}, num_neighbors=[3, 2],
                        buckets=(8,), apply_fn=apply_fn,
                        sampler=samp, cache_capacity=0)
  eng.warmup()
  got = eng.infer(np.arange(6))
  np.testing.assert_array_equal(want, got)
  stats = eng.compile_stats()
  for _ in range(4):
    eng.infer(np.arange(6))
  assert eng.compile_stats()['forward_traces'] == \
      stats['forward_traces']
  assert eng.compile_stats()['sampler_compiled_fns'] == \
      stats['sampler_compiled_fns']


def test_stream_forced_engine_fallback_parity_and_counter(monkeypatch):
  # forcing pallas_fused on the stream path must keep working (counted
  # demotion to pallas for the overlay hops) with zero steady-state
  # recompiles across overlay refreshes and snapshot swaps
  from glt_tpu.obs import MetricsRegistry, get_registry, set_registry
  from glt_tpu.stream import (EdgeDeltaBuffer, SnapshotManager,
                              StreamSampler)
  prev = set_registry(MetricsRegistry())
  try:
    N = 24
    ds = ring_dataset(num_nodes=N)
    mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature(),
                          delta_capacity=64)
    seeds = np.arange(6)
    # pin the base to the sorted inducer: forcing pallas_fused implies
    # the sort dedup contract, and the sorted EXACT path permutes edge
    # tuples within a hop block vs the table engine (documented) — the
    # comparison must be like-for-like
    monkeypatch.setenv('GLT_DEDUP', 'sort')
    base = StreamSampler(mgr, [3, 2], seed=0).sample_from_nodes(seeds)
    monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
    monkeypatch.setenv('GLT_WINDOW_W', '8')
    samp = StreamSampler(mgr, [3, 2], seed=0)
    out = samp.sample_from_nodes(seeds)
    for f in ('node', 'row', 'col', 'edge_mask', 'batch'):
      np.testing.assert_array_equal(np.asarray(getattr(base, f)),
                                    np.asarray(getattr(out, f)),
                                    err_msg=f)
    fb = get_registry().get('hop_engine_fallbacks_total',
                            requested='pallas_fused',
                            resolved='pallas', reason='stream_overlay')
    assert fb == 1.0
    buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
    buf.insert_edges([1, 2], [5, 6])
    samp.refresh_overlay(buf)
    traces, fns = samp.trace_count, samp.num_compiled_fns
    for _ in range(3):
      samp.sample_from_nodes(seeds)
    mgr.compact(buf.drain())        # swap: same static shapes
    samp.clear_overlay()
    samp.sample_from_nodes(seeds)
    assert samp.trace_count == traces
    assert samp.num_compiled_fns == fns
    # the demotion is counted once per sampler, not per call
    assert get_registry().get('hop_engine_fallbacks_total',
                              requested='pallas_fused',
                              resolved='pallas',
                              reason='stream_overlay') == 1.0
  finally:
    set_registry(prev)


def test_fallback_counters_for_unservable_shapes(monkeypatch):
  from glt_tpu.obs import MetricsRegistry, get_registry, set_registry
  from glt_tpu.sampler import NeighborSampler
  prev = set_registry(MetricsRegistry())
  try:
    monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
    monkeypatch.setenv('GLT_WINDOW_W', '8')
    ds = ring_dataset(num_nodes=40)
    # weighted sampling cannot fuse
    NeighborSampler(ds.get_graph(), [3], seed=0,
                    with_weight=True).sample_from_nodes(np.arange(4))
    assert get_registry().get('hop_engine_fallbacks_total',
                              requested='pallas_fused',
                              resolved='pallas', reason='weighted') == 1
    # a dedup table past the VMEM sizing knob cannot fuse — but the
    # demoted engine still samples correctly
    monkeypatch.setenv('GLT_FUSED_TABLE_SLOTS', '512')
    samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0)
    out = samp.sample_from_nodes(np.arange(8))
    assert int(out.node_count) > 0
    assert get_registry().get('hop_engine_fallbacks_total',
                              requested='pallas_fused',
                              resolved='pallas',
                              reason='table_overflow') == 1
  finally:
    set_registry(prev)


# -- cross-hop fused walk (ISSUE 13) ------------------------------------
#
# GLT_FUSED_WALK=cross runs the WHOLE walk as one sample_walk_dedup
# kernel (table resident in VMEM across hops); auto resolves to per_hop
# under interpret mode, so every cross-walk test forces the knob.


def test_walk_bit_identical_to_sort_fused(monkeypatch):
  monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
  g = _graph(seed=2)
  seeds = jnp.asarray(np.array([5, 0, 5, 17, 63, 2, 2, 9], np.int32))
  nv = jnp.asarray(7)
  key = jax.random.key(9)
  fanouts = (3, 2)
  ref = _ref_sort_fused(g, seeds, nv, fanouts, key, monkeypatch,
                        with_edge=True)
  table, scratch = make_dedup_tables(g['n'])
  got, _, _ = multihop_sample(
      None, seeds, nv, fanouts, key, table, scratch, with_edge=True,
      fused_plan=_plan(g, fanouts, seeds.shape[0], with_edge=True))
  for k in EXACT_KEYS:
    np.testing.assert_array_equal(ref[k], np.asarray(got[k]),
                                  err_msg=k)
  m = ref['edge_mask'].astype(bool)
  np.testing.assert_array_equal(ref['edge'][m],
                                np.asarray(got['edge'])[m])


@pytest.mark.slow  # interpret-mode walk traces are minutes on 1 CPU;
                   # the pallas-interpret CI job (-m pallas) runs this
def test_walk_full_window_parity_and_replace(monkeypatch):
  # against a window-read reference even masked-lane junk matches (the
  # walk reads the same physical window slots, incl. duplicate-seed
  # rows which keep their REAL windows on hop 1); replace rides the
  # in-kernel replace offset formula
  monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
  g = _graph(seed=3)
  seeds = jnp.asarray(np.arange(10, dtype=np.int32))
  nv = jnp.asarray(10)
  key = jax.random.key(1)
  fanouts = (3, 2)
  ref = _ref_sort_fused(g, seeds, nv, fanouts, key, monkeypatch,
                        with_edge=True, window_read=True)
  table, scratch = make_dedup_tables(g['n'])
  got, _, _ = multihop_sample(
      None, seeds, nv, fanouts, key, table, scratch, with_edge=True,
      fused_plan=_plan(g, fanouts, seeds.shape[0], with_edge=True))
  np.testing.assert_array_equal(ref['edge'], np.asarray(got['edge']))
  # replace draw, plus a fully-masked batch through the walk
  refr = _ref_sort_fused(g, seeds, jnp.asarray(4), (4,), key,
                         monkeypatch, replace=True)
  gotr, _, _ = multihop_sample(
      None, seeds, jnp.asarray(4), (4,), key, table, scratch,
      fused_plan=_plan(g, (4,), seeds.shape[0], replace=True))
  for k in EXACT_KEYS:
    np.testing.assert_array_equal(refr[k], np.asarray(gotr[k]),
                                  err_msg=k)
  got0, _, _ = multihop_sample(
      None, seeds, jnp.asarray(0), fanouts, key, table, scratch,
      fused_plan=_plan(g, fanouts, seeds.shape[0]))
  assert int(got0['node_count']) == 0


@pytest.mark.slow  # see test_walk_full_window_parity_and_replace
def test_walk_scan_entry_parity(monkeypatch):
  # the lax.scan entry point: the walk kernel sits inside the batch
  # scan body; each step's table is kernel-local scratch, so
  # iterations are independent by construction
  monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
  g = _graph(seed=7)
  fanouts = (3, 2)
  seeds = jnp.asarray(
      np.random.default_rng(0).integers(0, g['n'], (3, 6)).astype(
          np.int32))
  nv = jnp.full((3,), 6, jnp.int32)
  key = jax.random.key(4)
  plan = _plan(g, fanouts, 6)
  table, scratch = make_dedup_tables(g['n'])
  outs, _, _ = multihop_sample_many(None, seeds, nv, fanouts, key,
                                    table, scratch, fused_plan=plan)
  k = key
  for t in range(3):
    k, sub = jax.random.split(k)
    one, _, _ = multihop_sample(None, seeds[t], nv[t], fanouts, sub,
                                table, scratch, fused_plan=plan)
    np.testing.assert_array_equal(np.asarray(outs['node'])[t],
                                  np.asarray(one['node']))
    np.testing.assert_array_equal(np.asarray(outs['row'])[t],
                                  np.asarray(one['row']))


def test_walk_fused_gather_and_bf16_plane(monkeypatch):
  # in-walk gather through the cross-hop walk == post-hoc
  # gather_features on every lane; the opt-in bf16 plane narrows the
  # emitted block (values == reference cast) without touching the
  # default path
  from glt_tpu.data.feature import gather_features
  from glt_tpu.sampler import NeighborSampler
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  ds = ring_dataset(num_nodes=40)
  feat = ds.get_node_feature()
  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0,
                         fused_feature=feat)
  out = samp.sample_from_nodes(np.arange(8))
  fused_x = out.metadata['node_feats']
  ref_x = gather_features(feat, out.node)
  np.testing.assert_array_equal(np.asarray(ref_x), np.asarray(fused_x))

  monkeypatch.setenv('GLT_FUSED_FEAT_DTYPE', 'bfloat16')
  samp16 = NeighborSampler(ds.get_graph(), [3, 2], seed=0,
                           fused_feature=feat)
  out16 = samp16.sample_from_nodes(np.arange(8))
  x16 = out16.metadata['node_feats']
  assert x16.dtype == jnp.bfloat16
  np.testing.assert_array_equal(
      np.asarray(ref_x.astype(jnp.bfloat16), dtype=np.float32),
      np.asarray(x16, dtype=np.float32))


def test_walk_stream_zero_recompile_across_refresh_and_swap(
    monkeypatch):
  # the scan-carried walk forced on the stream path: overlay hops
  # demote to pallas (counted once) and the zero-steady-state-
  # recompile contract holds across overlay refreshes AND snapshot
  # swaps, mirroring tests/test_stream.py
  from glt_tpu.obs import MetricsRegistry, get_registry, set_registry
  from glt_tpu.stream import (EdgeDeltaBuffer, SnapshotManager,
                              StreamSampler)
  prev = set_registry(MetricsRegistry())
  try:
    N = 24
    ds = ring_dataset(num_nodes=N)
    mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature(),
                          delta_capacity=64)
    seeds = np.arange(6)
    monkeypatch.setenv('GLT_DEDUP', 'sort')
    monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
    monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
    monkeypatch.setenv('GLT_WINDOW_W', '8')
    samp = StreamSampler(mgr, [3, 2], seed=0)
    samp.sample_from_nodes(seeds)
    buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
    buf.insert_edges([1, 2], [5, 6])
    samp.refresh_overlay(buf)
    traces, fns = samp.trace_count, samp.num_compiled_fns
    for _ in range(3):
      samp.sample_from_nodes(seeds)
    mgr.compact(buf.drain())        # swap: same static shapes
    samp.clear_overlay()
    samp.sample_from_nodes(seeds)
    assert samp.trace_count == traces
    assert samp.num_compiled_fns == fns
    assert get_registry().get('hop_engine_fallbacks_total',
                              requested='pallas_fused',
                              resolved='pallas',
                              reason='stream_overlay') == 1.0
  finally:
    set_registry(prev)


def test_walk_launch_collapse_and_table_gauges(monkeypatch):
  # the O(hops)->O(1) launch collapse is an assertable number: the
  # per-hop program traces hops+1 kernel entries (seed insert + one
  # per hop), the walk exactly one; the fused-table geometry gauges
  # land in the registry at plan build and occupancy under the opt-in
  from glt_tpu.obs import MetricsRegistry, get_registry, set_registry
  from glt_tpu.ops.pallas_kernels import kernel_launch_count
  from glt_tpu.sampler import NeighborSampler
  prev = set_registry(MetricsRegistry())
  try:
    g = _graph(seed=4)
    seeds = jnp.asarray(np.arange(8, dtype=np.int32))
    nv = jnp.asarray(8)
    fanouts = (3, 2)
    table, scratch = make_dedup_tables(g['n'])

    def count_traced_launches(walk_mode):
      monkeypatch.setenv('GLT_FUSED_WALK', walk_mode)
      plan = _plan(g, fanouts, 8)

      def f(s, k):
        out, _, _ = multihop_sample(None, s, nv, fanouts, k, table,
                                    scratch, fused_plan=plan)
        return out['node_count']

      # the counter bumps per NEW trace of a kernel wrapper — an inner
      # jit-cache hit (same kernel, same shapes, earlier test) would
      # silently undercount, so count against a cold cache
      jax.clear_caches()
      before = kernel_launch_count()
      jax.jit(f).lower(seeds, jax.random.key(0))
      return kernel_launch_count() - before

    assert count_traced_launches('per_hop') == len(fanouts) + 1
    assert count_traced_launches('cross') == 1

    monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
    monkeypatch.setenv('GLT_WINDOW_W', '8')
    monkeypatch.setenv('GLT_OBS_TABLE_OCCUPANCY', '1')
    ds = ring_dataset(num_nodes=40)
    samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0)
    out = samp.sample_from_nodes(np.arange(8))
    reg = get_registry()
    slots = reg.get('fused_table_slots')
    assert slots > 0
    assert reg.get('fused_table_vmem_bytes') == 2 * slots * 4
    assert reg.get('fused_table_occupancy_hwm') == float(
        int(out.node_count))
    assert 0 < reg.get('fused_table_occupancy_ratio_hwm') <= 1.0
  finally:
    set_registry(prev)


def test_walk_demotes_to_per_hop_for_slot_eids(monkeypatch):
  # with_edge over a graph WITHOUT an edge-id plane: the eids contract
  # is raw CSR slots, which the walk never materializes — the fused
  # path must quietly stay per-hop and keep the slot contract
  monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
  g = _graph(seed=6)
  seeds = jnp.asarray(np.arange(6, dtype=np.int32))
  nv = jnp.asarray(6)
  key = jax.random.key(3)
  fanouts = (3,)
  plan = FusedHopPlan(
      g['indptr'], g['indices'], g['iw'], W, g['n_hub'],
      fused_table_slots(sample_budget(6, list(fanouts))),
      interpret=True)  # no edge_ids plane
  table, scratch = make_dedup_tables(g['n'])
  got, _, _ = multihop_sample(None, seeds, nv, fanouts, key, table,
                              scratch, with_edge=True,
                              fused_plan=plan)
  ref = _ref_sort_fused(g, seeds, nv, fanouts, key, monkeypatch,
                        with_edge=False)
  for k in EXACT_KEYS:
    np.testing.assert_array_equal(ref[k], np.asarray(got[k]),
                                  err_msg=k)
  assert 'edge' in got  # slot-contract eids still emitted


# -- hetero: one multi-edge-type kernel invocation per hop (ISSUE 14) --
#
# The pallas_fused engine serves HETERO walks: each hop's per-edge-type
# sampling is batched into ONE padded sample_hop_dedup invocation over
# the flat edge-type plane (type-tagged global ids = per-type dedup
# namespaces in one VMEM table). Parity target: the per-edge-type
# sorted reference, GLT_DEDUP=sort GLT_FUSED_HOP=1.

U2I = ('user', 'u2i', 'item')
I2I = ('item', 'i2i', 'item')

HETERO_NODE_KEYS = ('node', 'node_count', 'num_sampled_nodes')
HETERO_EDGE_KEYS = ('row', 'col', 'edge_mask', 'num_sampled_edges')


def _hetero_ref_vs_fused(ds, nn, inputs, nv, monkeypatch, seed=4,
                         with_edge=False, **sampler_kw):
  from glt_tpu.sampler import NeighborSampler
  monkeypatch.delenv('GLT_HOP_ENGINE', raising=False)
  monkeypatch.setenv('GLT_DEDUP', 'sort')
  monkeypatch.setenv('GLT_FUSED_HOP', '1')
  base = NeighborSampler(
      ds.graph, nn, seed=seed, with_edge=with_edge,
      **sampler_kw)._hetero_sample_from_nodes(inputs, n_valid=nv)
  monkeypatch.delenv('GLT_DEDUP')
  monkeypatch.delenv('GLT_FUSED_HOP')
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  samp = NeighborSampler(ds.graph, nn, seed=seed, with_edge=with_edge,
                         **sampler_kw)
  out = samp._hetero_sample_from_nodes(inputs, n_valid=nv)
  return base, out, samp


def _assert_hetero_identical(base, out, with_edge=False):
  for t in base.node:
    for k in HETERO_NODE_KEYS:
      np.testing.assert_array_equal(
          np.asarray(getattr(base, k)[t]),
          np.asarray(getattr(out, k)[t]), err_msg=f'{k}[{t}]')
  for e in base.row:
    for k in HETERO_EDGE_KEYS:
      np.testing.assert_array_equal(
          np.asarray(getattr(base, k)[e]),
          np.asarray(getattr(out, k)[e]), err_msg=f'{k}[{e}]')
    if with_edge:
      m = np.asarray(base.edge_mask[e]).astype(bool)
      np.testing.assert_array_equal(np.asarray(base.edge[e])[m],
                                    np.asarray(out.edge[e])[m],
                                    err_msg=f'edge[{e}]')
  for t in base.batch:
    np.testing.assert_array_equal(np.asarray(base.batch[t]),
                                  np.asarray(out.batch[t]),
                                  err_msg=f'batch[{t}]')
    np.testing.assert_array_equal(
        np.asarray(base.metadata['seed_labels'][t]),
        np.asarray(out.metadata['seed_labels'][t]),
        err_msg=f'seed_labels[{t}]')


def _hub_hetero_dataset(nu=8, ni=24, hub_deg=14):
  """item 0 is a HUB in i2i (degree > the forced W=8); every other row
  in both types stays far below the window — the hub fix-up must fire
  for exactly one type's segment of the concatenated frontier."""
  from glt_tpu.data import Dataset
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2 * u, 2 * u + 1], 1).reshape(-1) % ni])
  hub_dst = (np.arange(hub_deg) + 1) % ni
  i = np.arange(1, ni)
  i2i_ei = np.stack([
      np.concatenate([np.zeros(hub_deg, np.int64), i]),
      np.concatenate([hub_dst, (i + 1) % ni])])
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index={U2I: u2i_ei, I2I: i2i_ei},
                num_nodes={'user': nu, 'item': ni})
  return ds


@pytest.mark.slow  # two full hetero program traces per param on 1 CPU;
                   # the pallas-interpret CI job (-m pallas) runs it
@pytest.mark.parametrize('with_edge', [False, True])
def test_hetero_bit_identical_to_per_etype_sorted_ref(monkeypatch,
                                                      with_edge):
  from fixtures import hetero_ring_dataset
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  seeds = np.array([3, 0, 3, 7, 9, 1], np.int64)  # duplicate seeds
  base, out, _ = _hetero_ref_vs_fused(
      ds, {U2I: [2, 2], I2I: [2, 2]}, ('user', seeds), 5, monkeypatch,
      with_edge=with_edge)
  _assert_hetero_identical(base, out, with_edge=with_edge)


def test_hetero_hub_rows_in_one_type_only(monkeypatch):
  ds = _hub_hetero_dataset()
  seeds = np.array([3, 0, 3, 7], np.int64)
  base, out, _ = _hetero_ref_vs_fused(
      ds, {U2I: [2, 2], I2I: [3, 2]}, ('user', seeds), 4, monkeypatch)
  _assert_hetero_identical(base, out)


def test_hetero_empty_frontier_and_n_valid_zero(monkeypatch):
  ds = _hub_hetero_dataset()
  seeds = np.array([3, 0, 3, 7], np.int64)
  base, out, _ = _hetero_ref_vs_fused(
      ds, {U2I: [2, 2], I2I: [3, 2]}, ('user', seeds), 0, monkeypatch)
  _assert_hetero_identical(base, out)
  assert all(int(c) == 0 for c in
             jax.tree_util.tree_leaves(out.node_count))


def test_hetero_zero_budget_type_and_empty_etype(monkeypatch):
  from glt_tpu.data import Dataset
  nu, ni = 6, 12
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2 * u, 2 * u + 1], 1).reshape(-1) % ni])
  # zero-budget type: nothing ever expands INTO 'user', so its caps
  # are 0 past hop 0 and the u2i frontier dies after hop 1
  ds = Dataset(edge_dir='out')
  ds.init_graph(edge_index={U2I: u2i_ei},
                num_nodes={'user': nu, 'item': ni})
  base, out, _ = _hetero_ref_vs_fused(
      ds, {U2I: [2, 2]}, ('user', np.array([1, 2, 5], np.int64)), 3,
      monkeypatch)
  _assert_hetero_identical(base, out)
  # empty per-type frontier via a zero-EDGE etype: i2i exists in the
  # schema but holds no edges — its segments ride the invocation as
  # all-invalid lanes, exactly the reference's _empty_output chunks
  ds2 = Dataset(edge_dir='out')
  ds2.init_graph(edge_index={U2I: u2i_ei, I2I: np.zeros((2, 0),
                                                        np.int64)},
                 num_nodes={'user': nu, 'item': ni})
  base2, out2, _ = _hetero_ref_vs_fused(
      ds2, {U2I: [2, 2], I2I: [2, 2]},
      ('user', np.array([1, 2, 5], np.int64)), 3, monkeypatch)
  _assert_hetero_identical(base2, out2)


@pytest.mark.slow  # 4 hetero program traces; runs in the -m pallas job
def test_hetero_two_type_seeding_and_mixed_fanouts(monkeypatch):
  from fixtures import hetero_ring_dataset
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  base, out, _ = _hetero_ref_vs_fused(
      ds, {U2I: [2, 2], I2I: [2, 2]},
      {'user': np.array([1, 2, 5], np.int64),
       'item': np.array([0, 7, 7, 3], np.int64)}, 3, monkeypatch)
  _assert_hetero_identical(base, out)
  # per-etype fanouts differ: the K_max offset/validity padding path
  base2, out2, _ = _hetero_ref_vs_fused(
      ds, {U2I: [3, 1], I2I: [1, 2]},
      ('user', np.array([4, 4, 0, 9], np.int64)), 4, monkeypatch)
  _assert_hetero_identical(base2, out2)


def test_hetero_sampler_zero_recompiles_and_honest_fallbacks(
    monkeypatch):
  # hetero is SERVED by the fused family: no `hetero` fallback reason
  # fires for a plain hetero sampler, the one compiled program serves
  # every steady-state call, and the specific reasons (weighted,
  # table_overflow) keep firing with the requested label honest
  from fixtures import hetero_ring_dataset
  from glt_tpu.obs import MetricsRegistry, get_registry, set_registry
  from glt_tpu.sampler import NeighborSampler
  prev = set_registry(MetricsRegistry())
  try:
    monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
    monkeypatch.setenv('GLT_WINDOW_W', '8')
    ds = hetero_ring_dataset(num_users=10, num_items=20)
    samp = NeighborSampler(ds.graph, {U2I: [2, 2], I2I: [2, 2]},
                           seed=0)
    seeds = np.arange(6)
    samp._hetero_sample_from_nodes(('user', seeds))
    assert samp.num_compiled_fns == 1
    for _ in range(3):
      samp._hetero_sample_from_nodes(('user', seeds))
    assert samp.num_compiled_fns == 1
    snap = get_registry().snapshot()
    hetero_fb = [k for k in snap['counters']
                 if 'hop_engine_fallbacks_total' in k
                 and 'hetero' in k]
    assert not hetero_fb, hetero_fb
    # a table past the VMEM sizing knob is a SPECIFIC reason (never
    # the blanket `hetero`), requested label honest
    monkeypatch.setenv('GLT_FUSED_TABLE_SLOTS', '512')
    osamp = NeighborSampler(ds.graph, {U2I: [4, 4], I2I: [4, 4]},
                            seed=0)
    out = osamp._hetero_sample_from_nodes(('user', np.arange(8)))
    assert int(out.node_count['item']) > 0  # demoted engine still works
    assert get_registry().get('hop_engine_fallbacks_total',
                              requested='pallas_fused',
                              resolved='pallas',
                              reason='table_overflow') == 1
  finally:
    set_registry(prev)


@pytest.mark.slow  # two serving warmups (4 program traces); -m pallas job
def test_hetero_serving_parity_and_zero_recompiles(monkeypatch):
  # hetero bucket serving (input_type seeding, HeteroBatch forward):
  # embeddings match the per-etype sorted reference and warmup
  # compiles stay flat with the fused hetero engine forced
  from fixtures import hetero_ring_dataset
  from glt_tpu.serving import InferenceEngine
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  nn = {U2I: [2, 2], I2I: [2, 2]}
  apply_fn = lambda params, batch: \
      batch.x_dict['user'][:batch.batch_size, :4] * 2.0

  monkeypatch.setenv('GLT_DEDUP', 'sort')
  monkeypatch.setenv('GLT_FUSED_HOP', '1')
  base = InferenceEngine(ds, model=None, params={}, num_neighbors=nn,
                         buckets=(8,), apply_fn=apply_fn, seed=0,
                         cache_capacity=0, input_type='user')
  base.warmup()
  want = base.infer(np.arange(6))
  monkeypatch.delenv('GLT_DEDUP')
  monkeypatch.delenv('GLT_FUSED_HOP')

  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  eng = InferenceEngine(ds, model=None, params={}, num_neighbors=nn,
                        buckets=(8,), apply_fn=apply_fn, seed=0,
                        cache_capacity=0, input_type='user')
  eng.warmup()
  got = eng.infer(np.arange(6))
  np.testing.assert_array_equal(want, got)
  stats = eng.compile_stats()
  for _ in range(4):
    eng.infer(np.arange(6))
  assert eng.compile_stats()['forward_traces'] == \
      stats['forward_traces']
  assert eng.compile_stats()['sampler_compiled_fns'] == \
      stats['sampler_compiled_fns']


@pytest.mark.slow  # whole-superstep scan trace in interpret; -m pallas job
def test_hetero_superstep_scan_parity_and_one_trace(monkeypatch):
  # K hetero batches in ONE dispatch (multihop_sample_hetero_many):
  # results identical to K per-batch calls on the same key stream,
  # one trace serves every superstep call — the dispatch collapse the
  # bench records as dispatches_per_step 1 -> 1/K
  from fixtures import hetero_ring_dataset
  from glt_tpu.ops.pipeline import (multihop_sample_hetero,
                                    multihop_sample_hetero_many)
  from glt_tpu.sampler import NeighborSampler
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  ds = hetero_ring_dataset(num_users=10, num_items=20)
  nn = {U2I: [2, 2], I2I: [2, 2]}
  samp = NeighborSampler(ds.graph, nn, seed=0)
  batch_sizes = {'user': 6}
  trav = samp._traversal_types()
  caps, budgets = samp._hetero_caps(batch_sizes)
  plan = samp._hetero_fused_plan(batch_sizes)
  assert plan is not None
  one_hops = {e: (lambda ids, f, k, m, _e=e: samp._one_hop(
      samp.graph[_e], ids, f, k, m)) for e in samp.edge_types}
  tables = {t: samp._get_tables(t, n)
            for t, n in samp._node_counts.items()}
  T = 3
  seeds = jnp.asarray(np.random.default_rng(0).integers(
      0, 10, (T, 6)).astype(np.int32))
  nv = jnp.full((T,), 6, jnp.int32)
  key = jax.random.key(7)
  traces = {'n': 0}

  @jax.jit
  def run_super(seeds_stack, nv_stack, key, tables):
    traces['n'] += 1  # trace-time side effect only
    return multihop_sample_hetero_many(
        one_hops, trav, samp.num_neighbors, samp.num_hops, caps,
        budgets, {'user': seeds_stack}, {'user': nv_stack}, key,
        tables, fused_plan=plan)

  outs, tables = run_super(seeds, nv, key, tables)
  outs2, tables = run_super(seeds, nv, key, tables)
  assert traces['n'] == 1  # one dispatch per K batches, zero recompile
  k = key
  for t in range(T):
    k, sub = jax.random.split(k)
    one, tables = multihop_sample_hetero(
        one_hops, trav, samp.num_neighbors, samp.num_hops, caps,
        budgets, {'user': seeds[t]}, {'user': nv[t]}, sub, tables,
        fused_plan=plan)
    for ty in one['node']:
      np.testing.assert_array_equal(np.asarray(outs['node'][ty])[t],
                                    np.asarray(one['node'][ty]),
                                    err_msg=f'node[{ty}] step {t}')
    for e in one['row']:
      np.testing.assert_array_equal(np.asarray(outs['row'][e])[t],
                                    np.asarray(one['row'][e]),
                                    err_msg=f'row[{e}] step {t}')


def test_fused_walk_mode_knob(monkeypatch):
  from glt_tpu.ops.pipeline import fused_walk_mode
  monkeypatch.delenv('GLT_FUSED_WALK', raising=False)
  # auto resolves per interpret-default: per_hop on the CPU suite
  assert fused_walk_mode() == 'per_hop'
  monkeypatch.setenv('GLT_FUSED_WALK', 'cross')
  assert fused_walk_mode() == 'cross'
  monkeypatch.setenv('GLT_FUSED_WALK', 'sideways')
  with pytest.raises(ValueError):
    fused_walk_mode()


def test_hop_engine_knob_accepts_pallas_fused(monkeypatch):
  from glt_tpu.ops.pipeline import dedup_engine, hop_engine
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas_fused')
  assert hop_engine() in ('pallas_fused', 'window')
  # the fused engine implies the sort dedup contract under auto
  monkeypatch.delenv('GLT_DEDUP', raising=False)
  assert dedup_engine() == 'sort'
  monkeypatch.setenv('GLT_HOP_ENGINE', 'warp')
  with pytest.raises(ValueError):
    hop_engine()

"""gltlint suite tests: every rule against its fixture corpus (one
true-positive and one true-negative file per rule), the suppression /
baseline machinery, the typed env-knob helper the rules enforce, and
the CI gate itself (nonzero on a seeded violation, zero on the tree)."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
  sys.path.insert(0, REPO)

from tools.gltlint.core import (  # noqa: E402
    all_rules, lint_paths, load_baseline, write_baseline,
)

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'gltlint_fixtures')

#: rule -> (true-positive file, true-negative file), fixture-relative.
#: GLT001/GLT007/GLT008 are path-scoped, so their fixtures sit under a
#: miniature glt_tpu/ tree and lint with root=FIXTURES.
CASES = {
    'GLT001': ('glt_tpu/glt001_tp.py', 'glt_tpu/glt001_tn.py'),
    'GLT002': ('glt002_tp.py', 'glt002_tn.py'),
    'GLT003': ('glt003_tp.py', 'glt003_tn.py'),
    'GLT004': ('glt004_tp.py', 'glt004_tn.py'),
    'GLT005': ('glt005_tp.py', 'glt005_tn.py'),
    'GLT006': ('glt006_tp.py', 'glt006_tn.py'),
    'GLT007': ('glt_tpu/glt007_tp.py', 'glt_tpu/glt007_tn.py'),
    'GLT008': ('glt_tpu/ops/glt008_tp.py', 'glt_tpu/ops/glt008_tn.py'),
}

#: minimum finding count the true-positive file must produce (each
#: fixture seeds several distinct violation flavors)
MIN_TP = {
    'GLT001': 4, 'GLT002': 3, 'GLT003': 3, 'GLT004': 3,
    'GLT005': 4, 'GLT006': 2, 'GLT007': 5, 'GLT008': 3,
}


def _lint(relpath, code):
  result = lint_paths([os.path.join(FIXTURES, relpath)],
                      root=FIXTURES, select={code})
  assert not result.errors, result.errors
  return result.findings


@pytest.mark.parametrize('code', sorted(CASES))
def test_rule_true_positives(code):
  tp, _ = CASES[code]
  findings = _lint(tp, code)
  assert len(findings) >= MIN_TP[code], (
      f'{code} missed seeded violations in {tp}: '
      f'{[f.render() for f in findings]}')
  assert all(f.rule == code for f in findings)
  for f in findings:
    assert f.line > 0 and f.message and f.key.startswith(f'{code}::')


@pytest.mark.parametrize('code', sorted(CASES))
def test_rule_true_negatives(code):
  _, tn = CASES[code]
  findings = _lint(tn, code)
  assert findings == [], (
      f'{code} false positives in {tn}: '
      f'{[f.render() for f in findings]}')


def test_all_eight_rules_registered():
  codes = set()
  for rule in all_rules():
    codes.update(getattr(rule, 'codes', None) or (rule.code,))
  assert codes == {f'GLT00{i}' for i in range(1, 9)}


def test_inline_suppression_and_file_disable(tmp_path):
  src = tmp_path / 'mod.py'
  src.write_text(
      'def resolve(fut, v):\n'
      '  fut.set_result(v)  # gltlint: disable=GLT005\n'
      'def resolve2(fut, v):\n'
      '  # gltlint: disable-next=GLT005\n'
      '  fut.set_result(v)\n'
      'def resolve3(fut, v):\n'
      '  fut.set_result(v)\n')
  findings = lint_paths([str(src)], root=str(tmp_path),
                        select={'GLT005'}).findings
  assert len(findings) == 1 and findings[0].scope == 'resolve3'
  src.write_text('# gltlint: disable-file=GLT005\n' + src.read_text())
  assert lint_paths([str(src)], root=str(tmp_path),
                    select={'GLT005'}).findings == []


def test_baseline_roundtrip(tmp_path):
  src = tmp_path / 'mod.py'
  src.write_text('def f(fut):\n  fut.set_result(1)\n')
  result = lint_paths([str(src)], root=str(tmp_path), select={'GLT005'})
  assert len(result.findings) == 1
  bl = tmp_path / 'baseline.json'
  write_baseline(str(bl), result.findings)
  result2 = lint_paths([str(src)], root=str(tmp_path),
                       select={'GLT005'},
                       baseline=load_baseline(str(bl)))
  assert result2.findings == [] and len(result2.baselined) == 1
  assert result2.ok
  # baseline keys are line-free: shifting the code down two lines
  # must not invalidate the entry
  src.write_text('\n\n' + src.read_text())
  result3 = lint_paths([str(src)], root=str(tmp_path),
                       select={'GLT005'},
                       baseline=load_baseline(str(bl)))
  assert result3.findings == [] and len(result3.baselined) == 1


# -- the CI gate itself ---------------------------------------------------

def _run_cli(args, cwd=REPO):
  return subprocess.run(
      [sys.executable, '-m', 'tools.gltlint', *args],
      cwd=cwd, capture_output=True, text=True, timeout=120)


@pytest.mark.parametrize('code', sorted(CASES))
def test_cli_gate_fails_on_seeded_violation(code):
  """Acceptance contract: the gate exits nonzero on EVERY rule's
  seeded fixture violation (root= the fixture mini-repo so the
  path-scoped rules resolve)."""
  tp, _ = CASES[code]
  proc = _run_cli([os.path.join(FIXTURES, tp), '--no-baseline',
                   '--root', FIXTURES, '--select', code])
  assert proc.returncode == 1, proc.stdout + proc.stderr
  assert code in proc.stdout


def test_cli_gate_green_on_tree_and_writes_json(tmp_path):
  """The exact contract the ci.yml lint job enforces: zero unsuppressed
  findings over glt_tpu/ tools/ tests/ with the checked-in baseline,
  machine-readable findings JSON on the side."""
  out = tmp_path / 'findings.json'
  proc = _run_cli(['glt_tpu/', 'tools/', 'tests/',
                   '--json', str(out)])
  assert proc.returncode == 0, proc.stdout + proc.stderr
  payload = json.loads(out.read_text())
  assert payload['new'] == []
  assert isinstance(payload['baselined'], list)


def test_missing_path_fails_not_vacuously_green(tmp_path):
  result = lint_paths([str(tmp_path / 'no_such_dir')],
                      root=str(tmp_path))
  assert result.errors and not result.ok
  proc = _run_cli(['glt_tpuu/'])     # the typo'd-gate scenario
  assert proc.returncode == 1 and 'does not exist' in proc.stdout


def test_lint_paths_accepts_one_shot_iterator(tmp_path):
  src = tmp_path / 'mod.py'
  src.write_text('def f(fut):\n  fut.set_result(1)\n')
  result = lint_paths((p for p in [str(src)]), root=str(tmp_path),
                      select={'GLT005'})
  assert len(result.findings) == 1


def test_glt006_nested_closure_not_attributed_to_outer(tmp_path):
  src = tmp_path / 'mod.py'
  src.write_text(
      'import threading\n'
      'def target():\n'
      '  def callback():\n'
      '    try:\n'
      '      pass\n'
      '    except Exception:\n'
      '      pass\n'                  # in the closure, not the target
      '  register(callback)\n'
      'threading.Thread(target=target).start()\n')
  assert lint_paths([str(src)], root=str(tmp_path),
                    select={'GLT006'}).findings == []


def test_write_baseline_refuses_on_errors(tmp_path):
  bad = tmp_path / 'broken.py'
  bad.write_text('def f(:\n')
  proc = _run_cli([str(bad), '--write-baseline',
                   '--baseline', str(tmp_path / 'bl.json')])
  assert proc.returncode == 1
  assert not (tmp_path / 'bl.json').exists()


def test_write_baseline_refuses_partial_rule_set(tmp_path):
  src = tmp_path / 'mod.py'
  src.write_text('def f(fut):\n  fut.set_result(1)\n')
  proc = _run_cli([str(src), '--select', 'GLT005', '--write-baseline',
                   '--baseline', str(tmp_path / 'bl.json')])
  assert proc.returncode == 2
  assert not (tmp_path / 'bl.json').exists()


def test_write_baseline_carries_out_of_scope_entries(tmp_path):
  """Rebaselining one subdirectory must not drop (or lose the
  justifications of) entries for files the run never looked at; TODO
  placeholders keep the exit nonzero until every entry is justified."""
  (tmp_path / 'a').mkdir()
  (tmp_path / 'b').mkdir()
  (tmp_path / 'a' / 'mod.py').write_text(
      'def f(fut):\n  fut.set_result(1)\n')
  (tmp_path / 'b' / 'mod.py').write_text(
      'def g(fut):\n  fut.set_result(2)\n')
  bl = tmp_path / 'bl.json'
  proc = _run_cli([str(tmp_path / 'a'), str(tmp_path / 'b'),
                   '--root', str(tmp_path), '--baseline', str(bl),
                   '--write-baseline'])
  # written, but nonzero: both fresh entries carry the TODO placeholder
  assert proc.returncode == 1, proc.stdout
  assert 'NEEDS JUSTIFICATION' in proc.stdout
  full = load_baseline(str(bl))
  assert len(full) == 2
  # hand-justify everything, then rebaseline only a/: b/'s entry (and
  # its justification) must survive untouched, and the exit goes green
  write_baseline(str(bl), [], carry={
      k: f'verified benign: single resolver ({k.split("::")[1]})'
      for k in full})
  proc = _run_cli([str(tmp_path / 'a'), '--root', str(tmp_path),
                   '--baseline', str(bl), '--write-baseline'])
  assert proc.returncode == 0, proc.stdout
  after = load_baseline(str(bl))
  assert len(after) == 2
  b_key = next(k for k in after if '::b/' in k)
  assert after[b_key] == 'verified benign: single resolver (b/mod.py)'


def test_write_baseline_still_writes_json(tmp_path):
  src = tmp_path / 'mod.py'
  src.write_text('def f(fut):\n  fut.set_result(1)\n')
  out = tmp_path / 'findings.json'
  proc = _run_cli([str(src), '--baseline', str(tmp_path / 'bl.json'),
                   '--write-baseline', '--json', str(out)])
  # exit 1 (fresh TODO entry), but the JSON artifact is still written
  assert proc.returncode == 1, proc.stdout
  assert json.loads(out.read_text())['new']


def test_cli_list_rules():
  proc = _run_cli(['--list-rules'])
  assert proc.returncode == 0
  for code in CASES:
    assert code in proc.stdout


# -- the env-knob helper GLT001 enforces ----------------------------------

def test_knob_types_and_malformed_defaults(monkeypatch):
  from glt_tpu.utils import env

  monkeypatch.setenv('GLT_T_INT', '12')
  assert env.knob('GLT_T_INT', 7) == 12
  monkeypatch.setenv('GLT_T_INT', 'zillion')
  with pytest.warns(RuntimeWarning, match='GLT_T_INT'):
    assert env.knob('GLT_T_INT', 7) == 7       # the import-crash class

  monkeypatch.setenv('GLT_T_FLOAT', '0.5')
  assert env.knob('GLT_T_FLOAT', 0.0) == 0.5

  for raw_val, want in (('1', True), ('true', True), ('on', True),
                        ('0', False), ('false', False), ('off', False)):
    monkeypatch.setenv('GLT_T_BOOL', raw_val)
    assert env.knob('GLT_T_BOOL', not want) is want
  monkeypatch.setenv('GLT_T_BOOL', 'maybe')
  with pytest.warns(RuntimeWarning):
    assert env.knob('GLT_T_BOOL', True) is True

  monkeypatch.setenv('GLT_T_STR', 'pallas_fused')
  assert env.knob('GLT_T_STR', 'auto') == 'pallas_fused'
  monkeypatch.delenv('GLT_T_STR')
  assert env.knob('GLT_T_STR', 'auto') == 'auto'
  monkeypatch.setenv('GLT_T_STR', '')
  assert env.knob('GLT_T_STR', 'auto') == 'auto'   # empty = unset
  assert env.knob('GLT_T_UNSET', None) is None

  monkeypatch.setenv('GLT_T_RAW', 'cpu')
  assert env.raw('GLT_T_RAW') == 'cpu'
  assert env.raw('GLT_T_RAW_UNSET', 'dflt') == 'dflt'


def test_knob_custom_parse_and_warn_once(monkeypatch):
  from glt_tpu.utils import env

  monkeypatch.setenv('GLT_T_LIST', '1,2,3')
  parse = lambda s: [int(x) for x in s.split(',')]  # noqa: E731
  assert env.knob('GLT_T_LIST', [], parse) == [1, 2, 3]
  monkeypatch.setenv('GLT_T_LIST', '1,x')
  with pytest.warns(RuntimeWarning):
    assert env.knob('GLT_T_LIST', [7], parse) == [7]
  # second read of the SAME bad value stays silent (hot loops)
  import warnings as _w
  with _w.catch_warnings():
    _w.simplefilter('error')
    assert env.knob('GLT_T_LIST', [7], parse) == [7]

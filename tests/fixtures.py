"""Deterministic graph fixtures, modeled on the reference's test strategy
(test/python/dist_test_utils.py:44-125): a ring-structured graph with a
formulaic adjacency and value-encoded features, so any test can assert
exactness without golden files.

Homogeneous fixture: ``num_nodes`` nodes; node v has out-edges to
(v+1) % n and (v+2) % n. Edge id of (v -> (v+k) % n) is 2*v + (k-1).
Feature row i == [i] * dim; edge feature row e == [e] * edge_dim.
"""
from __future__ import annotations

import numpy as np

from glt_tpu.data import Dataset, Topology


def ring_edges(num_nodes: int):
  v = np.arange(num_nodes, dtype=np.int64)
  rows = np.repeat(v, 2)
  cols = np.stack([(v + 1) % num_nodes, (v + 2) % num_nodes], 1).reshape(-1)
  eids = np.stack([2 * v, 2 * v + 1], 1).reshape(-1)
  return rows, cols, eids


def ring_dataset(num_nodes: int = 40, feat_dim: int = 16,
                 edge_feat_dim: int = 4, edge_dir: str = 'out',
                 split_ratio: float = 1.0, weighted: bool = False,
                 host_offload=None) -> Dataset:
  rows, cols, eids = ring_edges(num_nodes)
  weights = (eids % 7 + 1).astype(np.float32) if weighted else None
  ds = Dataset(edge_dir=edge_dir)
  ds.init_graph(edge_index=np.stack([rows, cols]), edge_ids=eids,
                edge_weights=weights, num_nodes=num_nodes)
  nfeat = np.tile(np.arange(num_nodes, dtype=np.float32)[:, None],
                  (1, feat_dim))
  efeat = np.tile(np.arange(2 * num_nodes, dtype=np.float32)[:, None],
                  (1, edge_feat_dim))
  ds.init_node_features(nfeat, split_ratio=split_ratio,
                        host_offload=host_offload)
  ds.init_edge_features(efeat)
  ds.init_node_labels(np.arange(num_nodes, dtype=np.int32) % 4)
  return ds


def hetero_ring_dataset(num_users: int = 20, num_items: int = 40,
                        feat_dim: int = 8) -> Dataset:
  """user/item graph as in the reference hetero fixture
  (dist_test_utils.py:143-284): u2i edges user u -> items (2u, 2u+1),
  i2i edges item i -> items ((i+1)%n, (i+2)%n)."""
  u = np.arange(num_users, dtype=np.int64)
  u2i_rows = np.repeat(u, 2)
  u2i_cols = np.stack([2 * u, 2 * u + 1], 1).reshape(-1) % num_items
  u2i_eids = np.arange(2 * num_users, dtype=np.int64)
  i2i_rows, i2i_cols, i2i_eids = ring_edges(num_items)
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  ds = Dataset(edge_dir='out')
  ds.init_graph(
      edge_index={u2i: np.stack([u2i_rows, u2i_cols]),
                  i2i: np.stack([i2i_rows, i2i_cols])},
      edge_ids={u2i: u2i_eids, i2i: i2i_eids},
      num_nodes={'user': num_users, 'item': num_items})
  ds.init_node_features({
      'user': np.tile(np.arange(num_users, dtype=np.float32)[:, None],
                      (1, feat_dim)),
      'item': np.tile(np.arange(num_items, dtype=np.float32)[:, None],
                      (1, feat_dim)),
  })
  ds.init_node_labels({
      'user': np.arange(num_users, dtype=np.int32) % 3,
      'item': np.arange(num_items, dtype=np.int32) % 5,
  })
  return ds


def skip_unless_pinned_host():
  """Offload-engagement tests assert a pinned-host cold block exists;
  on backends WITHOUT a pinned_host memory kind (e.g. CPU on some jax
  versions) that can never hold — skip rather than fail. On a capable
  backend the asserts still run, so offload regressions stay loud."""
  import pytest
  from glt_tpu.utils.offload import pinned_host_supported
  if not pinned_host_supported():
    pytest.skip('platform lacks pinned_host memory kind')

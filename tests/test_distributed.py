"""Distributed (sharded-topology) tests on the 8-device CPU mesh:
partition on disk -> DistDataset load -> DistGraph/DistFeature ->
DistNeighborSampler, asserting exactness against the ring fixture —
the reference's dist test strategy (SURVEY.md §4) without processes.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glt_tpu.data import Dataset
from glt_tpu.distributed import (
    DistDataset, DistFeature, DistGraph, DistNeighborSampler,
)
from glt_tpu.parallel import make_mesh
from glt_tpu.partition import RandomPartitioner

from fixtures import ring_edges

N_NODES = 40
N_PARTS = 8


@pytest.fixture(scope='module')
def part_dir(tmp_path_factory):
  root = tmp_path_factory.mktemp('parts')
  rows, cols, eids = ring_edges(N_NODES)
  feats = np.tile(np.arange(N_NODES, dtype=np.float32)[:, None], (1, 8))
  p = RandomPartitioner(str(root), num_parts=N_PARTS, num_nodes=N_NODES,
                        edge_index=np.stack([rows, cols]),
                        node_feat=feats, edge_assign_strategy='by_src')
  p.partition()
  return str(root)


@pytest.fixture(scope='module')
def mesh():
  return make_mesh(N_PARTS)


@pytest.fixture(scope='module')
def dist_datasets(part_dir):
  return [DistDataset().load(part_dir, p) for p in range(N_PARTS)]


def test_dist_dataset_load(dist_datasets):
  ds = dist_datasets[0]
  assert ds.num_partitions == N_PARTS
  g = ds.get_graph()
  # every edge's src is owned by partition 0
  src, _, _ = g.topo.to_coo()
  # local graph stores global ids on the pointer axis? (it stores the
  # partition's edges with original ids)
  feat = ds.get_node_feature()
  owned = np.nonzero(ds.node_pb.table == 0)[0]
  looked = feat[owned]
  np.testing.assert_allclose(looked[:, 0], owned)


def test_dist_graph_shapes(mesh, part_dir):
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  assert dg.num_partitions == N_PARTS
  assert dg.indptr.shape[0] == N_PARTS
  # node_pb covers every node
  pb = np.asarray(dg.node_pb)
  assert pb.shape == (N_NODES,)
  assert set(pb.tolist()) <= set(range(N_PARTS))


def test_dist_feature_lookup(mesh, dist_datasets):
  df = DistFeature.from_dist_datasets(mesh, dist_datasets)
  rng = np.random.default_rng(0)
  ids = rng.integers(0, N_NODES, N_PARTS * 16)
  out = np.asarray(df.lookup(ids))
  np.testing.assert_allclose(out[:, 0], ids)


def test_dist_sampler_one_hop_exact(mesh, part_dir):
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  s = DistNeighborSampler(dg, [2], seed=0)
  # each device seeds two nodes: device p seeds {p, p+8}
  seeds = np.stack([np.arange(N_PARTS), np.arange(N_PARTS) + 8], 1)
  out = s.sample_from_nodes(seeds)
  nodes = np.asarray(out['node'])        # [P, budget]
  counts = np.asarray(out['node_count'])
  for p in range(N_PARTS):
    got = set(nodes[p][:counts[p]].tolist())
    expect = {p, p + 8}
    for v in (p, p + 8):
      expect |= {(v + 1) % N_NODES, (v + 2) % N_NODES}
    assert got == expect, f'device {p}: {got} != {expect}'
    # edges obey ring relation
    em = np.asarray(out['edge_mask'])[p]
    child = nodes[p][np.asarray(out['row'])[p][em]]
    parent = nodes[p][np.asarray(out['col'])[p][em]]
    for pp, cc in zip(parent, child):
      assert cc in ((pp + 1) % N_NODES, (pp + 2) % N_NODES)


def test_dist_sampler_two_hops(mesh, part_dir):
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  s = DistNeighborSampler(dg, [2, 2], seed=1)
  seeds = np.arange(N_PARTS)[:, None]    # one seed per device
  out = s.sample_from_nodes(seeds)
  nodes = np.asarray(out['node'])
  counts = np.asarray(out['node_count'])
  for p in range(N_PARTS):
    got = set(nodes[p][:counts[p]].tolist())
    expect = {p, (p+1) % N_NODES, (p+2) % N_NODES, (p+3) % N_NODES,
              (p+4) % N_NODES}
    assert got == expect


def test_dist_sampler_edge_ids(mesh, part_dir):
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  s = DistNeighborSampler(dg, [2], with_edge=True, seed=2)
  seeds = np.arange(N_PARTS)[:, None]
  out = s.sample_from_nodes(seeds)
  for p in range(N_PARTS):
    em = np.asarray(out['edge_mask'])[p]
    eids = np.asarray(out['edge'])[p][em]
    # node p's out-edges have eids {2p, 2p+1}
    assert set(eids.tolist()) == {2 * p, 2 * p + 1}


def test_dist_loader_and_train_step(mesh, part_dir, dist_datasets):
  import optax
  from glt_tpu.distributed import DistNeighborLoader, DistTrainStep
  from glt_tpu.models import GraphSAGE
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  df = DistFeature.from_dist_datasets(mesh, dist_datasets)
  labels = (np.arange(N_NODES) % 4).astype(np.int32)

  # loader round: each device iterates its own partition's nodes
  per_dev = [np.nonzero(np.asarray(dg.node_pb) == p)[0]
             for p in range(N_PARTS)]
  loader = DistNeighborLoader(dg, [2], input_nodes=per_dev,
                              dist_feature=df, labels=labels,
                              batch_size=2, seed=0)
  b = next(iter(loader))
  nodes = np.asarray(b['node'])
  x = np.asarray(b['x'])
  counts = np.asarray(b['node_count'])
  for p in range(N_PARTS):
    nc = counts[p]
    np.testing.assert_allclose(x[p][:nc, 0], nodes[p][:nc])

  # one-program train step learns on the ring task
  model = GraphSAGE(hidden_features=16, out_features=4, num_layers=1)
  tx = optax.adam(1e-2)
  step = DistTrainStep(dg, df, model, tx, labels, fanouts=[2],
                       batch_size_per_device=4)
  params = step.init_params(jax.random.key(0))
  opt_state = tx.init(params)
  rng = np.random.default_rng(0)
  losses = []
  for it in range(40):
    seeds = np.stack([rng.choice(per_dev[p] if len(per_dev[p]) >= 4
                                 else np.arange(N_NODES), 4)
                      for p in range(N_PARTS)])
    params, opt_state, loss = step(params, opt_state, seeds,
                                   np.full(N_PARTS, 4),
                                   jax.random.key(it))
    losses.append(float(np.asarray(loss)[0]))
  assert losses[-1] < losses[0], f'no learning: {losses[::8]}'


def test_dist_hetero_sampler(tmp_path_factory, mesh):
  from glt_tpu.distributed import DistHeteroGraph, DistHeteroNeighborSampler
  # partition the hetero user/item fixture to disk
  root = str(tmp_path_factory.mktemp('hetero_parts'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei}).partition()

  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  s = DistHeteroNeighborSampler(dg, {u2i: [2, 2], i2i: [2, 2]}, seed=0)
  seeds = (np.arange(N_PARTS) % nu)[:, None]   # one user per device
  out = s.sample_from_nodes('user', seeds)
  items = np.asarray(out['node']['item'])
  users = np.asarray(out['node']['user'])
  icount = np.asarray(out['node_count']['item'])
  for p in range(N_PARTS):
    uu = p % nu
    np.testing.assert_array_equal(
        users[p][:int(np.asarray(out['node_count']['user'])[p])], [uu])
    # hop1 items {2u, 2u+1}; hop2 via i2i: +1, +2 of those
    expect = {2*uu % ni, (2*uu+1) % ni}
    for v in list(expect):
      expect |= {(v+1) % ni, (v+2) % ni}
    got = set(items[p][:icount[p]].tolist())
    assert got == expect, f'dev {p}: {got} != {expect}'
  # reversed etype keys present
  assert ('item', 'rev_u2i', 'user') in out['row']


def test_dist_hetero_multihost_builder_parity(tmp_path_factory, mesh):
  # single-process path of the multihost hetero builder must produce a
  # store whose sampling matches from_dataset_partitions exactly
  from glt_tpu.distributed import (
      DistHeteroGraph, DistHeteroNeighborSampler,
      dist_hetero_graph_from_partitions_multihost,
  )
  root = str(tmp_path_factory.mktemp('hetero_mh_parts'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei}).partition()
  ref = DistHeteroGraph.from_dataset_partitions(mesh, root)
  got = dist_hetero_graph_from_partitions_multihost(mesh, root)
  assert got.node_counts == ref.node_counts
  for e in ref.graphs:
    a, b = ref.graphs[e], got.graphs[e]
    assert (a.max_rows, a.max_edges, a.max_degree) == \
        (b.max_rows, b.max_edges, b.max_degree), e
    np.testing.assert_array_equal(np.asarray(a.indptr),
                                  np.asarray(b.indptr), str(e))
    np.testing.assert_array_equal(np.asarray(a.indices),
                                  np.asarray(b.indices), str(e))
    np.testing.assert_array_equal(np.asarray(a.local_row),
                                  np.asarray(b.local_row), str(e))
  seeds = (np.arange(N_PARTS) % nu)[:, None]
  out_a = DistHeteroNeighborSampler(
      ref, {u2i: [2, 2], i2i: [2, 2]}, seed=0).sample_from_nodes(
          'user', seeds, key=jax.random.key(3))
  out_b = DistHeteroNeighborSampler(
      got, {u2i: [2, 2], i2i: [2, 2]}, seed=0).sample_from_nodes(
          'user', seeds, key=jax.random.key(3))
  for t in out_a['node']:
    np.testing.assert_array_equal(np.asarray(out_a['node'][t]),
                                  np.asarray(out_b['node'][t]), t)


def test_dist_hetero_train_step(tmp_path_factory, mesh):
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type
  root = str(tmp_path_factory.mktemp('hetero_train'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  w = max(nu, ni)
  feats = {'user': np.pad(np.eye(nu, dtype=np.float32),
                          ((0, 0), (0, w - nu))),
           'item': np.pad(np.eye(ni, dtype=np.float32),
                          ((0, 0), (0, w - ni)))}
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei},
                    node_feat=feats).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(N_PARTS)]
  dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t)
            for t in ('user', 'item')}
  labels = {'user': (np.arange(nu) % 3).astype(np.int32)}
  model = RGNN(edge_types=[reverse_edge_type(u2i), i2i],
               hidden_features=16, out_features=3, num_layers=2,
               conv='rsage')
  tx = optax.adam(1e-2)
  step = DistHeteroTrainStep(dg, dfeats, model, tx, labels,
                             {u2i: [2, 2], i2i: [2, 2]},
                             batch_size_per_device=2, seed_type='user',
                             seed=0)
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)
  rng = np.random.default_rng(0)
  losses = []
  for it in range(30):
    seeds = rng.integers(0, nu, (N_PARTS, 2))
    params, opt, loss = step(params, opt, seeds, np.full(N_PARTS, 2),
                             jax.random.key(it))
    losses.append(float(np.asarray(loss)[0]))
  assert losses[-1] < losses[0], f'no learning: {losses[::6]}'


def test_dist_hetero_train_superstep(tmp_path_factory, mesh):
  """K hetero train batches in ONE donated dispatch (ISSUE 14
  tentpole, program half): superstep loss trajectory bit-identical to
  K sequential per-batch calls on the same key stream, with zero
  steady-state recompiles across repeated supersteps of the same T."""
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type
  root = str(tmp_path_factory.mktemp('hetero_superstep'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  w = max(nu, ni)
  feats = {'user': np.pad(np.eye(nu, dtype=np.float32),
                          ((0, 0), (0, w - nu))),
           'item': np.pad(np.eye(ni, dtype=np.float32),
                          ((0, 0), (0, w - ni)))}
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei},
                    node_feat=feats).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(N_PARTS)]
  dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t)
            for t in ('user', 'item')}
  labels = {'user': (np.arange(nu) % 3).astype(np.int32)}
  # 1 hop / 1 layer: the parity + zero-recompile claims are about the
  # scan lift, not model depth — tier-1 time budget matters here
  model = RGNN(edge_types=[reverse_edge_type(u2i), i2i],
               hidden_features=8, out_features=3, num_layers=1,
               conv='rsage')
  tx = optax.adam(1e-2)

  def build():
    return DistHeteroTrainStep(dg, dfeats, model, tx, labels,
                               {u2i: [2], i2i: [2]},
                               batch_size_per_device=2,
                               seed_type='user', seed=0)

  T = 2
  rng = np.random.default_rng(0)
  seeds = rng.integers(0, nu, (T, N_PARTS, 2))
  keys = jnp.stack([jax.random.split(jax.random.key(t), N_PARTS)
                    for t in range(T)])

  step = build()
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)
  seq = []
  for t in range(T):
    params, opt, loss = step(params, opt, seeds[t],
                             np.full(N_PARTS, 2), jax.random.key(t))
    seq.append(np.asarray(loss))

  step2 = build()
  params2 = step2.init_params(jax.random.key(0))
  opt2 = tx.init(params2)
  from glt_tpu.obs import get_registry
  compiles0 = get_registry().get('compiles_total',
                                 fn='train.hetero_superstep')
  params2, opt2, loss_ss = step2.superstep(
      params2, opt2, seeds.reshape(T, -1), np.full((T, N_PARTS), 2),
      keys)
  np.testing.assert_array_equal(np.asarray(loss_ss), np.stack(seq))
  assert step2.superstep_traces == 1
  compiles1 = get_registry().get('compiles_total',
                                 fn='train.hetero_superstep')
  assert compiles1 == compiles0 + 1
  params2, opt2, _ = step2.superstep(
      params2, opt2, seeds.reshape(T, -1), np.full((T, N_PARTS), 2),
      keys)
  assert step2.superstep_traces == 1  # steady state: zero recompiles
  # the process-wide counter agrees: one trace served both supersteps
  assert get_registry().get('compiles_total',
                            fn='train.hetero_superstep') == compiles1


def test_dist_weighted_sampling(tmp_path_factory, mesh):
  """Distributed weighted sampling: the dominant-weight edge is sampled
  nearly always (reference parity: weighted sampling works through the
  partitioned path)."""
  root = str(tmp_path_factory.mktemp('wparts'))
  rows, cols, eids = ring_edges(N_NODES)
  w = np.ones(2 * N_NODES, np.float32)
  w[eids % 2 == 0] = 1000.0   # the (v -> v+1) edge dominates
  RandomPartitioner(root, num_parts=N_PARTS, num_nodes=N_NODES,
                    edge_index=np.stack([rows, cols]),
                    edge_weights=w).partition()
  dg = DistGraph.from_dataset_partitions(mesh, root)
  assert dg.edge_weights is not None
  s = DistNeighborSampler(dg, [1], with_weight=True, seed=0)
  hits = total = 0
  for trial in range(12):
    seeds = ((np.arange(N_PARTS) + trial * N_PARTS) % N_NODES)[:, None]
    out = s.sample_from_nodes(seeds)
    nodes = np.asarray(out['node'])
    counts = np.asarray(out['node_count'])
    for p in range(N_PARTS):
      v = int(seeds[p, 0])
      got = set(nodes[p][:counts[p]].tolist()) - {v}
      if got:
        total += 1
        hits += int((v + 1) % N_NODES in got)
  assert total > 50
  assert hits / total > 0.95, f'{hits}/{total}'


def test_dist_hetero_weighted(tmp_path_factory, mesh):
  from glt_tpu.distributed import DistHeteroGraph, DistHeteroNeighborSampler
  root = str(tmp_path_factory.mktemp('hw'))
  i2i = ('item', 'i2i', 'item')
  ni = 32
  i = np.arange(ni)
  ei = np.stack([np.repeat(i, 2),
                 np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  w = np.ones(2 * ni, np.float32)
  w[::2] = 500.0    # (v -> v+1) dominates
  RandomPartitioner(root, num_parts=N_PARTS, num_nodes={'item': ni},
                    edge_index={i2i: ei},
                    edge_weights={i2i: w}).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  assert dg.graphs[i2i].edge_weights is not None
  s = DistHeteroNeighborSampler(dg, {i2i: [1]}, with_weight=True, seed=0)
  hits = total = 0
  for trial in range(10):
    seeds = ((np.arange(N_PARTS) + trial * N_PARTS) % ni)[:, None]
    out = s.sample_from_nodes('item', seeds)
    nodes = np.asarray(out['node']['item'])
    counts = np.asarray(out['node_count']['item'])
    for p in range(N_PARTS):
      v = int(seeds[p, 0])
      got = set(nodes[p][:counts[p]].tolist()) - {v}
      if got:
        total += 1
        hits += int((v + 1) % ni in got)
  assert total > 40 and hits / total > 0.9, f'{hits}/{total}'


def test_dist_link_neighbor_loader(mesh, part_dir, dist_datasets):
  from glt_tpu.distributed import DistLinkNeighborLoader
  from glt_tpu.sampler import NegativeSampling
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  df = DistFeature.from_dist_datasets(mesh, dist_datasets)
  # per-device edge pools: device p holds the ring edges of its nodes
  pools = []
  for p in range(N_PARTS):
    owned = np.nonzero(np.asarray(dg.node_pb) == p)[0]
    src = np.repeat(owned, 2)
    dst = np.concatenate([(owned + 1) % N_NODES,
                          (owned + 2) % N_NODES]).reshape(2, -1).T.reshape(-1)
    # interleave properly: for each v: (v+1), (v+2)
    dst = np.stack([(owned + 1) % N_NODES, (owned + 2) % N_NODES],
                   1).reshape(-1)
    pools.append(np.stack([src, dst]))
  loader = DistLinkNeighborLoader(
      dg, [2], pools, dist_feature=df,
      neg_sampling=NegativeSampling('binary', amount=1),
      batch_size=4, seed=0)
  batches = list(loader)
  assert len(batches) >= 2
  b = batches[0]
  eli = np.asarray(b['edge_label_index'])      # [P, 2, 8]
  nodes = np.asarray(b['node'])
  for p in range(N_PARTS):
    n_pos = int(np.asarray(b['n_pos'])[p])
    src = nodes[p][eli[p, 0, :n_pos]]
    dst = nodes[p][eli[p, 1, :n_pos]]
    for u, v in zip(src, dst):
      assert v in ((u + 1) % N_NODES, (u + 2) % N_NODES)
    # labels: first batch_size are positives
    lab = np.asarray(b['edge_label'])[p]
    np.testing.assert_array_equal(lab[:4], 1.0)
    np.testing.assert_array_equal(lab[4:], 0.0)
  # features resolve for all sampled nodes
  x = np.asarray(b['x'])
  counts = np.asarray(b['node_count'])
  for p in range(N_PARTS):
    np.testing.assert_allclose(x[p][:counts[p], 0],
                               nodes[p][:counts[p]])


def test_dist_subgraph_loader(mesh, part_dir):
  from glt_tpu.distributed import DistSubGraphLoader
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  loader = DistSubGraphLoader(
      dg, num_hops=2, input_nodes_per_device=[
          np.array([p]) for p in range(N_PARTS)],
      max_degree=2, batch_size=1, seed=0)
  b = next(iter(loader))
  nodes = np.asarray(b['node'])
  counts = np.asarray(b['node_count'])
  for p in range(N_PARTS):
    got = set(nodes[p][:counts[p]].tolist())
    expect = {p, (p+1) % N_NODES, (p+2) % N_NODES, (p+3) % N_NODES,
              (p+4) % N_NODES}
    assert got == expect
    ind = b['induced'][p]
    # induced edges: every ring edge within the 2-hop set, each once
    pairs = {(int(nodes[p][r]), int(nodes[p][c]))
             for r, c in zip(ind['cols'], ind['rows'])}
    # (cols=parent? note: out row=child col=parent in dist outputs too)
    expect_edges = set()
    for v in expect:
      for d in (1, 2):
        if (v + d) % N_NODES in expect:
          expect_edges.add((v, (v + d) % N_NODES))
    assert pairs == expect_edges, (pairs, expect_edges)
    assert len(ind['eids']) == len(set(ind['eids'].tolist()))


def test_dist_strict_negative_sampling(mesh, part_dir):
  from glt_tpu.distributed import DistRandomNegativeSampler
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  s = DistRandomNegativeSampler(dg, trials_num=6, padding=False)
  rows, cols, mask = s.sample(32, key=jax.random.key(0))
  rows, cols, mask = map(np.asarray, (rows, cols, mask))
  assert mask.sum() > 100  # plenty of valid negatives on a sparse ring
  ring = {(v, (v + 1) % N_NODES) for v in range(N_NODES)} | \
         {(v, (v + 2) % N_NODES) for v in range(N_NODES)}
  for p in range(N_PARTS):
    for r, c in zip(rows[p][mask[p]], cols[p][mask[p]]):
      assert (int(r), int(c)) not in ring, (r, c)


def test_dist_strict_negative_rejects_on_dense_graph(tmp_path_factory,
                                                     mesh):
  # complete digraph: strict mode finds nothing without padding
  root = str(tmp_path_factory.mktemp('dense'))
  n = 8
  r, c = np.meshgrid(np.arange(n), np.arange(n), indexing='ij')
  RandomPartitioner(root, num_parts=N_PARTS, num_nodes=n,
                    edge_index=np.stack([r.reshape(-1), c.reshape(-1)])
                    ).partition()
  from glt_tpu.distributed import DistRandomNegativeSampler
  dg = DistGraph.from_dataset_partitions(mesh, root)
  s = DistRandomNegativeSampler(dg, trials_num=4, padding=False)
  _, _, mask = s.sample(16, key=jax.random.key(1))
  assert not np.asarray(mask).any()


def test_dist_link_loader_strict_negatives(mesh, part_dir, dist_datasets):
  from glt_tpu.distributed import DistLinkNeighborLoader
  from glt_tpu.sampler import NegativeSampling
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  pools = []
  for p in range(N_PARTS):
    owned = np.nonzero(np.asarray(dg.node_pb) == p)[0]
    src = np.repeat(owned, 2)
    dst = np.stack([(owned + 1) % N_NODES, (owned + 2) % N_NODES],
                   1).reshape(-1)
    pools.append(np.stack([src, dst]))
  loader = DistLinkNeighborLoader(
      dg, [2], pools,
      neg_sampling=NegativeSampling('binary', amount=1, strict=True),
      batch_size=4, seed=0)
  b = next(iter(loader))
  eli = np.asarray(b['edge_label_index'])
  nodes = np.asarray(b['node'])
  ring = {(v, (v + 1) % N_NODES) for v in range(N_NODES)} | \
         {(v, (v + 2) % N_NODES) for v in range(N_NODES)}
  for p in range(N_PARTS):
    neg_src = nodes[p][eli[p, 0, 4:]]
    neg_dst = nodes[p][eli[p, 1, 4:]]
    for u, v in zip(neg_src, neg_dst):
      assert (int(u), int(v)) not in ring


def test_dist_strict_triplet_negatives(mesh, part_dir):
  from glt_tpu.distributed import DistLinkNeighborLoader
  from glt_tpu.sampler import NegativeSampling
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  pools = []
  for p in range(N_PARTS):
    owned = np.nonzero(np.asarray(dg.node_pb) == p)[0]
    src = np.repeat(owned, 2)
    dst = np.stack([(owned + 1) % N_NODES, (owned + 2) % N_NODES],
                   1).reshape(-1)
    pools.append(np.stack([src, dst]))
  loader = DistLinkNeighborLoader(
      dg, [2], pools,
      neg_sampling=NegativeSampling('triplet', amount=2, strict=True),
      batch_size=4, seed=0)
  b = next(iter(loader))
  nodes = np.asarray(b['node'])
  si = np.asarray(b['src_index'])
  dni = np.asarray(b['dst_neg_index'])
  ring = {(v, (v + 1) % N_NODES) for v in range(N_NODES)} | \
         {(v, (v + 2) % N_NODES) for v in range(N_NODES)}
  for p in range(N_PARTS):
    srcs = nodes[p][si[p]]
    # the emitted (src, dst_neg) pairs themselves must be non-edges
    negs = nodes[p][dni[p].reshape(-1)].reshape(dni[p].shape)
    for i, s in enumerate(srcs):
      ds_ = negs[i] if negs.ndim == 2 else [negs[i]]
      for d in np.atleast_1d(ds_):
        assert (int(s), int(d)) not in ring, (s, d)


def test_dist_strict_negatives_reproducible(mesh, part_dir):
  from glt_tpu.distributed import DistLinkNeighborLoader
  from glt_tpu.sampler import NegativeSampling
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  pools = []
  for p in range(N_PARTS):
    owned = np.nonzero(np.asarray(dg.node_pb) == p)[0]
    src = np.repeat(owned, 2)
    dst = np.stack([(owned + 1) % N_NODES, (owned + 2) % N_NODES],
                   1).reshape(-1)
    pools.append(np.stack([src, dst]))
  def first_batch():
    loader = DistLinkNeighborLoader(
        dg, [2], pools,
        neg_sampling=NegativeSampling('binary', amount=1, strict=True),
        batch_size=4, seed=7)
    b = next(iter(loader))
    return np.asarray(b['node'])[np.arange(N_PARTS)[:, None],
                                 np.asarray(b['edge_label_index'])[:, 0]]
  np.testing.assert_array_equal(first_batch(), first_batch())


# -- distributed edge features (reference dist_neighbor_sampler.py:689-807,
# dist_feature.py:69-452 edge group) --------------------------------------

N_EDGES = 2 * N_NODES


@pytest.fixture(scope='module')
def part_dir_ef(tmp_path_factory):
  """Partitions with value-encoded edge features (row e == [e] * 4)."""
  root = tmp_path_factory.mktemp('parts_ef')
  rows, cols, eids = ring_edges(N_NODES)
  feats = np.tile(np.arange(N_NODES, dtype=np.float32)[:, None], (1, 8))
  efeats = np.tile(np.arange(N_EDGES, dtype=np.float32)[:, None], (1, 4))
  p = RandomPartitioner(str(root), num_parts=N_PARTS, num_nodes=N_NODES,
                        edge_index=np.stack([rows, cols]),
                        node_feat=feats, edge_feat=efeats,
                        edge_assign_strategy='by_src')
  p.partition()
  return str(root)


@pytest.fixture(scope='module')
def dist_datasets_ef(part_dir_ef):
  return [DistDataset().load(part_dir_ef, p) for p in range(N_PARTS)]


def test_dist_edge_feature_lookup(mesh, dist_datasets_ef):
  edf = DistFeature.from_dist_datasets(mesh, dist_datasets_ef,
                                       kind='edge')
  rng = np.random.default_rng(1)
  eids = rng.integers(0, N_EDGES, N_PARTS * 12)
  out = np.asarray(edf.lookup(eids))
  np.testing.assert_allclose(out[:, 0], eids)


def test_dist_loader_edge_attr_value_encoded(mesh, part_dir_ef,
                                             dist_datasets_ef):
  """Sampled eids come back with their value-encoded edge features
  through the SPMD all_to_all path."""
  from glt_tpu.distributed import DistNeighborLoader
  dg = DistGraph.from_dataset_partitions(mesh, part_dir_ef)
  edf = DistFeature.from_dist_datasets(mesh, dist_datasets_ef,
                                       kind='edge')
  loader = DistNeighborLoader(
      dg, [2, 2], input_nodes=[np.arange(p * 5, p * 5 + 5)
                               for p in range(N_PARTS)],
      batch_size=5, edge_feature=edf)
  out = next(iter(loader))
  em = np.asarray(out['edge_mask'])
  ea = np.asarray(out['edge_attr'])
  eids = np.asarray(out['edge'])
  assert em.sum() > 0
  np.testing.assert_allclose(ea[em][:, 0], eids[em])
  # every sampled edge id is a real ring edge id
  assert eids[em].min() >= 0 and eids[em].max() < N_EDGES


class _EdgeSumModel(__import__('flax').linen.Module):
  """Logits from node features + aggregated edge features — nonzero
  grads only possible if edge_attr actually arrives."""
  num_classes: int = 4

  @__import__('flax').linen.compact
  def __call__(self, batch):
    import flax.linen as nn
    n = batch.node.shape[0]
    seg = jnp.where(batch.edge_mask, jnp.clip(batch.col, 0, n - 1), n)
    agg = jax.ops.segment_sum(
        jnp.where(batch.edge_mask[:, None], batch.edge_attr, 0.0),
        seg, n + 1)[:n]
    h = jnp.concatenate([batch.x, agg], axis=-1)
    return nn.Dense(self.num_classes)(h)[:batch.batch_size]


def test_dist_train_step_consumes_edge_features(mesh, part_dir_ef,
                                                dist_datasets_ef):
  import optax
  from glt_tpu.distributed import DistTrainStep
  dg = DistGraph.from_dataset_partitions(mesh, part_dir_ef)
  ndf = DistFeature.from_dist_datasets(mesh, dist_datasets_ef)
  edf = DistFeature.from_dist_datasets(mesh, dist_datasets_ef,
                                       kind='edge')
  labels = np.arange(N_NODES, dtype=np.int32) % 4
  model = _EdgeSumModel()
  tx = optax.sgd(1e-2)
  step = DistTrainStep(dg, ndf, model, tx, labels, fanouts=[2, 2],
                       batch_size_per_device=4, edge_feature=edf)
  params = step.init_params(jax.random.key(0))
  opt_state = tx.init(params)
  seeds = np.arange(N_PARTS * 4) % N_NODES
  p0 = jax.tree.map(np.asarray, params)
  params, opt_state, loss = step(params, opt_state, seeds,
                                 np.full(N_PARTS, 4),
                                 jax.random.key(1))
  loss = np.asarray(jax.block_until_ready(loss))
  assert np.isfinite(loss).all()
  # edge-feature-dependent weights moved -> edge_attr flowed end-to-end
  changed = jax.tree.map(
      lambda a, b: float(np.abs(np.asarray(a) - b).sum()), params, p0)
  assert sum(jax.tree.leaves(changed)) > 0


class _HeteroEdgeProbe(__import__('flax').linen.Module):
  """Seed-user logits from user features + aggregated rev_u2i edge
  features — grads require edge_attr_dict to arrive."""
  num_classes: int = 3

  @__import__('flax').linen.compact
  def __call__(self, batch):
    import flax.linen as nn
    rev = ('item', 'rev_u2i', 'user')
    n = batch.node_dict['user'].shape[0]
    em = batch.edge_mask_dict[rev]
    seg = jnp.where(em, jnp.clip(batch.col_dict[rev], 0, n - 1), n)
    agg = jax.ops.segment_sum(
        jnp.where(em[:, None], batch.edge_attr_dict[rev], 0.0),
        seg, n + 1)[:n]
    h = jnp.concatenate([batch.x_dict['user'], agg], axis=-1)
    return nn.Dense(self.num_classes)(h)[:batch.batch_size]


def test_dist_hetero_edge_features(tmp_path_factory, mesh):
  """Hetero distributed edge features: value-encoded per-etype efeats
  arrive through the SPMD path and feed the train step."""
  import optax
  from glt_tpu.distributed import (
      DistHeteroGraph, DistHeteroNeighborSampler, DistHeteroTrainStep,
  )
  root = str(tmp_path_factory.mktemp('hetero_ef'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  feats = {'user': np.tile(np.arange(nu, dtype=np.float32)[:, None],
                           (1, 4)),
           'item': np.tile(np.arange(ni, dtype=np.float32)[:, None],
                           (1, 4))}
  efeats = {u2i: np.tile(np.arange(2*nu, dtype=np.float32)[:, None],
                         (1, 4)),
            i2i: np.tile(np.arange(2*ni, dtype=np.float32)[:, None],
                         (1, 4))}
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei},
                    node_feat=feats, edge_feat=efeats).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(N_PARTS)]
  dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t)
            for t in ('user', 'item')}
  edfs = {e: DistFeature.from_dist_datasets(mesh, dss, ntype=e,
                                            kind='edge')
          for e in (u2i, i2i)}

  # value assertion through the SPMD sampler path
  s = DistHeteroNeighborSampler(dg, {u2i: [2], i2i: [2]},
                                with_edge=True, seed=0)
  seeds = (np.arange(N_PARTS) % nu)[:, None]
  out = s.sample_from_nodes('user', seeds)
  rev = ('item', 'rev_u2i', 'user')
  eids = np.asarray(out['edge'][rev])
  em = np.asarray(out['edge_mask'][rev])
  looked = np.asarray(edfs[u2i].lookup(
      jnp.maximum(jnp.asarray(eids.reshape(-1)), 0),
      jnp.asarray(em.reshape(-1))))
  np.testing.assert_allclose(looked[em.reshape(-1)][:, 0],
                             eids[em])

  # and end-to-end through the hetero train step
  labels = {'user': (np.arange(nu) % 3).astype(np.int32)}
  model = _HeteroEdgeProbe()
  tx = optax.sgd(1e-2)
  step = DistHeteroTrainStep(dg, dfeats, model, tx, labels,
                             {u2i: [2], i2i: [2]},
                             batch_size_per_device=2, seed_type='user',
                             seed=0, edge_features=edfs)
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)
  p0 = jax.tree.map(np.asarray, params)
  params, opt, loss = step(params, opt,
                           np.arange(N_PARTS * 2).reshape(N_PARTS, 2)
                           % nu,
                           np.full(N_PARTS, 2), jax.random.key(1))
  loss = np.asarray(jax.block_until_ready(loss))
  assert np.isfinite(loss).all()
  changed = jax.tree.map(
      lambda a, b: float(np.abs(np.asarray(a) - b).sum()), params, p0)
  assert sum(jax.tree.leaves(changed)) > 0


def test_dist_hetero_train_step_weighted(tmp_path_factory, mesh):
  """with_weight reaches the per-etype collective one-hop through the
  hetero train step (passthrough smoke)."""
  import optax
  from glt_tpu.distributed import (
      DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type
  root = str(tmp_path_factory.mktemp('hw_train'))
  i2i = ('item', 'i2i', 'item')
  ni = 32
  i = np.arange(ni)
  ei = np.stack([np.repeat(i, 2),
                 np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  w = np.ones(2 * ni, np.float32)
  w[::2] = 500.0
  feats = {'item': np.tile(np.arange(ni, dtype=np.float32)[:, None],
                           (1, 4))}
  RandomPartitioner(root, num_parts=N_PARTS, num_nodes={'item': ni},
                    edge_index={i2i: ei}, edge_weights={i2i: w},
                    node_feat=feats).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(N_PARTS)]
  dfeats = {'item': DistFeature.from_dist_datasets(mesh, dss,
                                                   ntype='item')}
  labels = {'item': (np.arange(ni) % 3).astype(np.int32)}
  model = RGNN(edge_types=[i2i], hidden_features=8, out_features=3,
               num_layers=1, conv='rsage')
  tx = optax.sgd(1e-2)
  step = DistHeteroTrainStep(dg, dfeats, model, tx, labels, {i2i: [2]},
                             batch_size_per_device=2, seed_type='item',
                             seed=0, with_weight=True)
  assert step.sampler.with_weight
  params = step.init_params(jax.random.key(0))
  opt = tx.init(params)
  _, _, loss = step(params, opt,
                    np.arange(N_PARTS * 2).reshape(N_PARTS, 2) % ni,
                    np.full(N_PARTS, 2), jax.random.key(1))
  assert np.isfinite(np.asarray(jax.block_until_ready(loss))).all()


def test_dist_link_loader_edge_features(mesh, part_dir_ef,
                                        dist_datasets_ef):
  from glt_tpu.distributed import DistLinkNeighborLoader
  from glt_tpu.sampler import NegativeSampling
  dg = DistGraph.from_dataset_partitions(mesh, part_dir_ef)
  edf = DistFeature.from_dist_datasets(mesh, dist_datasets_ef,
                                       kind='edge')
  pools = []
  for p in range(N_PARTS):
    owned = np.nonzero(np.asarray(dg.node_pb) == p)[0]
    src = np.repeat(owned, 2)
    dst = np.stack([(owned + 1) % N_NODES, (owned + 2) % N_NODES],
                   1).reshape(-1)
    pools.append(np.stack([src, dst]))
  loader = DistLinkNeighborLoader(
      dg, [2], pools, neg_sampling=NegativeSampling('binary', amount=1),
      batch_size=4, seed=0, edge_feature=edf)
  b = next(iter(loader))
  em = np.asarray(b['edge_mask'])
  np.testing.assert_allclose(np.asarray(b['edge_attr'])[em][:, 0],
                             np.asarray(b['edge'])[em])


def test_dist_subgraph_loader_edge_features(mesh, part_dir_ef,
                                            dist_datasets_ef):
  from glt_tpu.distributed import DistSubGraphLoader
  dg = DistGraph.from_dataset_partitions(mesh, part_dir_ef)
  edf = DistFeature.from_dist_datasets(mesh, dist_datasets_ef,
                                       kind='edge')
  loader = DistSubGraphLoader(
      dg, num_hops=1,
      input_nodes_per_device=[np.arange(p * 5, p * 5 + 4)
                              for p in range(N_PARTS)],
      batch_size=4, seed=0, edge_feature=edf)
  b = next(iter(loader))
  saw = 0
  for item in b['induced']:
    if item['eids'].shape[0]:
      np.testing.assert_allclose(item['edge_attr'][:, 0], item['eids'])
      saw += item['eids'].shape[0]
  assert saw > 0


# -- sort-merge inducer inside the SPMD program --------------------------
# On real TPU hardware GLT_DEDUP=auto resolves to 'sort', so the
# collective one-hop is fed the sorted engine's permuted, _BIG-padded
# frontier. These force that engine on the CPU mesh and re-assert the
# exactness the table-engine tests above establish.

def test_dist_sampler_sort_engine_exact(mesh, part_dir, monkeypatch):
  monkeypatch.setenv('GLT_DEDUP', 'sort')
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  s = DistNeighborSampler(dg, [2, 2], with_edge=True, seed=1)
  seeds = np.arange(N_PARTS)[:, None]
  out = s.sample_from_nodes(seeds)
  nodes = np.asarray(out['node'])
  counts = np.asarray(out['node_count'])
  for p in range(N_PARTS):
    got = set(nodes[p][:counts[p]].tolist())
    expect = {p, (p + 1) % N_NODES, (p + 2) % N_NODES,
              (p + 3) % N_NODES, (p + 4) % N_NODES}
    assert got == expect
    em = np.asarray(out['edge_mask'])[p]
    child = nodes[p][np.asarray(out['row'])[p][em]]
    parent = nodes[p][np.asarray(out['col'])[p][em]]
    for pp, cc in zip(parent, child):
      assert cc in ((pp + 1) % N_NODES, (pp + 2) % N_NODES)
    # hop-0 edge ids are the seed's out-edges {2p, 2p+1}
    offs = out['edge_hop_offsets']
    em0 = em[offs[0]:offs[1]]
    eids0 = np.asarray(out['edge'])[p][offs[0]:offs[1]][em0]
    assert set(eids0.tolist()) == {2 * p, 2 * p + 1}


def test_dist_hetero_sampler_sort_engine(tmp_path_factory, mesh,
                                         monkeypatch):
  monkeypatch.setenv('GLT_DEDUP', 'sort')
  from glt_tpu.distributed import DistHeteroGraph, DistHeteroNeighborSampler
  root = str(tmp_path_factory.mktemp('hetero_parts_sort'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei}).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  s = DistHeteroNeighborSampler(dg, {u2i: [2, 2], i2i: [2, 2]}, seed=0)
  seeds = (np.arange(N_PARTS) % nu)[:, None]
  out = s.sample_from_nodes('user', seeds)
  items = np.asarray(out['node']['item'])
  icount = np.asarray(out['node_count']['item'])
  for p in range(N_PARTS):
    uu = p % nu
    expect = {2*uu % ni, (2*uu+1) % ni}
    for v in list(expect):
      expect |= {(v+1) % ni, (v+2) % ni}
    got = set(items[p][:icount[p]].tolist())
    assert got == expect, f'dev {p}: {got} != {expect}'
  assert ('item', 'rev_u2i', 'user') in out['row']


@pytest.mark.pallas
def test_dist_feature_pallas_row_gather_parity(mesh, dist_datasets):
  # injected interpret-mode Pallas serving gather == XLA take through
  # the PB-routed all_to_all lookup
  import functools
  from glt_tpu.ops.pallas_kernels import gather_rows
  base = DistFeature.from_dist_datasets(mesh, dist_datasets)
  fast = DistFeature.from_dist_datasets(
      mesh, dist_datasets,
      row_gather=functools.partial(gather_rows, interpret=True))
  ids = np.random.default_rng(1).integers(0, N_NODES, N_PARTS * 16)
  np.testing.assert_array_equal(np.asarray(base.lookup(ids)),
                                np.asarray(fast.lookup(ids)))


def test_dist_feature_spill_parity(mesh, dist_datasets):
  # beyond-HBM store: cold rows served from host shards must be
  # value-identical to the fully-resident store
  df = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                      split_ratio=0.4)
  assert df._spill
  rng = np.random.default_rng(3)
  ids = rng.integers(0, N_NODES, N_PARTS * 16)
  valid = rng.random(N_PARTS * 16) < 0.75
  out = np.asarray(df.lookup(ids, jnp.asarray(valid)))
  np.testing.assert_allclose(out[valid][:, 0], ids[valid])
  np.testing.assert_allclose(out[~valid], 0.0)


def test_dist_feature_spill_cold_get_roundtrip(mesh, dist_datasets):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  # the rpc-callee surface (legacy host-phase path): cold_get(partition,
  # ids) must serve exactly the rows lookup() would have resolved for
  # that partition. Offloaded stores free this state and refuse.
  df = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                      split_ratio=0.25,
                                      host_offload=False)
  offloaded = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                             split_ratio=0.25)
  with pytest.raises(RuntimeError, match='legacy host-phase'):
    offloaded.cold_get(0, np.arange(2))
  served = 0
  for p, pb in df._host_pb.items():
    if p not in df._host_cold:
      continue
    owned = np.nonzero(pb == p)[0]
    rows = df._host_id2index[p][owned]
    cold_ids = owned[rows >= int(df.hot_counts[p])]
    if cold_ids.size == 0:
      continue
    vals = df.cold_get(p, cold_ids)
    np.testing.assert_allclose(vals[:, 0], cold_ids)
    served += cold_ids.size
  assert served > 0


def test_dist_feature_bucket_cap_parity(mesh, dist_datasets):
  # capped request buckets with drain rounds: value parity vs uncapped,
  # including composition with host spill
  rng = np.random.default_rng(9)
  ids = rng.integers(0, N_NODES, N_PARTS * 16)
  valid = rng.random(N_PARTS * 16) < 0.8
  base = DistFeature.from_dist_datasets(mesh, dist_datasets)
  want = np.asarray(base.lookup(ids, jnp.asarray(valid)))
  capped = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                          bucket_cap=4)  # B=16/device
  got = np.asarray(capped.lookup(ids, jnp.asarray(valid)))
  np.testing.assert_allclose(got, want)
  spilled = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                           split_ratio=0.4,
                                           bucket_cap=4)
  got2 = np.asarray(spilled.lookup(ids, jnp.asarray(valid)))
  np.testing.assert_allclose(got2, want)


def test_dist_hetero_train_step_capped_offloaded_spill(
    tmp_path_factory, mesh):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  """VERDICT r4 next #7: bucket_cap + host-offloaded spill COMBINED in
  the fused hetero train step (IGBH shape: typed stores, rgnn, fused
  sampling+gather+update). The in-program drain makes the combination
  legal; losses must match a fully-resident uncapped run bit-for-bit
  (zeros from undrained or unserved-cold lanes would shift them)."""
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistFeature, DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type
  root = str(tmp_path_factory.mktemp('hetero_cap_spill'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  # value-encoded features: any lane served as zero changes the loss
  feats = {'user': np.tile(np.arange(nu, dtype=np.float32)[:, None],
                           (1, 8)) + 1.0,
           'item': np.tile(np.arange(ni, dtype=np.float32)[:, None],
                           (1, 8)) + 1.0}
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei},
                    node_feat=feats).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(N_PARTS)]
  labels = {'user': (np.arange(nu) % 3).astype(np.int32)}
  model = RGNN(edge_types=[reverse_edge_type(u2i), i2i],
               hidden_features=8, out_features=3, num_layers=2,
               conv='rsage')
  tx = optax.sgd(1e-2)

  def run(**store_kw):
    dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t,
                                                **store_kw)
              for t in ('user', 'item')}
    if store_kw:
      assert all(st.cold_array is not None for st in dfeats.values())
      assert all(st.bucket_cap == 4 for st in dfeats.values())
    step = DistHeteroTrainStep(dg, dfeats, model, tx, labels,
                               {u2i: [2, 2], i2i: [2, 2]},
                               batch_size_per_device=2,
                               seed_type='user', seed=0)
    params = step.init_params(jax.random.key(0))
    opt = tx.init(params)
    rng = np.random.default_rng(0)
    losses = []
    for it in range(3):
      seeds = rng.integers(0, nu, (N_PARTS, 2))
      params, opt, loss = step(params, opt, seeds, np.full(N_PARTS, 2),
                               jax.random.key(it))
      losses.append(float(np.asarray(loss)[0]))
    return losses

  base = run()
  combined = run(split_ratio=0.5, bucket_cap=4)
  np.testing.assert_allclose(combined, base, rtol=1e-6)


def test_dist_feature_bucket_cap_post_hoc_before_trace_ok(
    mesh, dist_datasets):
  # the in-program drain needs no retained host books, so a cap set
  # any time BEFORE the first lookup (which bakes it into the trace)
  # is honored exactly — even under worst-case hot-spot overflow
  # (this replaced the old 'routing books' rejection, which guarded
  # the host drain replay that no longer exists)
  df = DistFeature.from_dist_datasets(mesh, dist_datasets)
  df.bucket_cap = 4
  ids = np.zeros(N_PARTS * 16, np.int64)  # hot-spot: forces overflow
  out = np.asarray(df.lookup(ids))
  base = DistFeature.from_dist_datasets(mesh, dist_datasets)
  want = np.asarray(base.lookup(ids))
  np.testing.assert_allclose(out, want)


def test_dist_feature_bucket_cap_mutation_after_trace_rejected(
    mesh, dist_datasets):
  # the first lookup bakes the cap into the shard_map trace; mutating
  # it afterwards would silently keep routing with the old cap — must
  # raise, not silently diverge
  df = DistFeature.from_dist_datasets(mesh, dist_datasets, bucket_cap=4)
  ids = np.arange(N_PARTS * 16, dtype=np.int64) % N_NODES
  df.lookup(ids)
  df.bucket_cap = 8
  with pytest.raises(RuntimeError, match='bucket_cap changed'):
    df.lookup(ids)


def test_dist_feature_host_offload_active_and_parity(mesh, dist_datasets):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  # spilled store auto-builds the pinned-host cold block; lookup parity
  # vs the resident store with NO host phase (cold served in-program)
  df = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                      split_ratio=0.4)
  assert df._spill and df.cold_array is not None
  assert df.cold_array.sharding.memory_kind == 'pinned_host'
  rng = np.random.default_rng(31)
  ids = rng.integers(0, N_NODES, N_PARTS * 16)
  out = np.asarray(df.lookup(ids))
  np.testing.assert_allclose(out[:, 0], ids)
  # explicit opt-out keeps the legacy host-phase path
  legacy = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                          split_ratio=0.4,
                                          host_offload=False)
  assert legacy._spill and legacy.cold_array is None
  np.testing.assert_allclose(np.asarray(legacy.lookup(ids)), out)


def test_dist_train_step_with_host_offloaded_spill(mesh, part_dir,
                                                   dist_datasets):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  # the fused one-program step accepts a spilled store once the cold
  # block is host-offloaded, and trains IDENTICALLY to resident
  import optax
  from glt_tpu.distributed import DistTrainStep
  from glt_tpu.models import GraphSAGE
  dg = DistGraph.from_dataset_partitions(mesh, part_dir)
  labels = (np.arange(N_NODES) % 4).astype(np.int32)
  model = GraphSAGE(hidden_features=16, out_features=4, num_layers=1)
  tx = optax.adam(1e-2)

  def losses(df):
    step = DistTrainStep(dg, df, model, tx, labels, fanouts=[2],
                         batch_size_per_device=4)
    params = step.init_params(jax.random.key(0))
    opt = tx.init(params)
    out = []
    for it in range(3):
      seeds = (np.arange(N_PARTS * 4) * 3) % N_NODES
      params, opt, loss = step(params, opt, seeds, np.full(N_PARTS, 4),
                               jax.random.key(it))
      out.append(float(np.asarray(loss)[0]))
    return out

  spilled = DistFeature.from_dist_datasets(mesh, dist_datasets,
                                           split_ratio=0.4)
  assert spilled.cold_array is not None
  resident = DistFeature.from_dist_datasets(mesh, dist_datasets)
  np.testing.assert_allclose(losses(spilled), losses(resident),
                             rtol=1e-6)


def test_dist_hetero_train_step_with_host_offloaded_spill(
    tmp_path_factory, mesh):
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  # the fused hetero (IGBH-path) step trains spilled per-type stores
  # via the pinned-host cold blocks, identically to resident stores
  import optax
  from glt_tpu.distributed import (
      DistDataset, DistHeteroGraph, DistHeteroTrainStep,
  )
  from glt_tpu.models import RGNN
  from glt_tpu.typing import reverse_edge_type
  root = str(tmp_path_factory.mktemp('hetero_spill_train'))
  u2i = ('user', 'u2i', 'item')
  i2i = ('item', 'i2i', 'item')
  nu, ni = 16, 32
  u = np.arange(nu)
  u2i_ei = np.stack([np.repeat(u, 2),
                     np.stack([2*u, 2*u+1], 1).reshape(-1) % ni])
  i = np.arange(ni)
  i2i_ei = np.stack([np.repeat(i, 2),
                     np.stack([(i+1) % ni, (i+2) % ni], 1).reshape(-1)])
  w = max(nu, ni)
  feats = {'user': np.pad(np.eye(nu, dtype=np.float32),
                          ((0, 0), (0, w - nu))),
           'item': np.pad(np.eye(ni, dtype=np.float32),
                          ((0, 0), (0, w - ni)))}
  RandomPartitioner(root, num_parts=N_PARTS,
                    num_nodes={'user': nu, 'item': ni},
                    edge_index={u2i: u2i_ei, i2i: i2i_ei},
                    node_feat=feats).partition()
  dg = DistHeteroGraph.from_dataset_partitions(mesh, root)
  dss = [DistDataset().load(root, p) for p in range(N_PARTS)]
  labels = {'user': (np.arange(nu) % 3).astype(np.int32)}
  model = RGNN(edge_types=[reverse_edge_type(u2i), i2i],
               hidden_features=16, out_features=3, num_layers=2,
               conv='rsage')
  tx = optax.adam(1e-2)

  def losses(split):
    dfeats = {t: DistFeature.from_dist_datasets(mesh, dss, ntype=t,
                                                split_ratio=split)
              for t in ('user', 'item')}
    if split is not None and split < 1:
      assert any(st.cold_array is not None for st in dfeats.values())
    step = DistHeteroTrainStep(dg, dfeats, model, tx, labels,
                               {u2i: [2, 2], i2i: [2, 2]},
                               batch_size_per_device=2,
                               seed_type='user', seed=0)
    params = step.init_params(jax.random.key(0))
    opt = tx.init(params)
    out = []
    for it in range(3):
      seeds = (np.arange(N_PARTS * 2).reshape(N_PARTS, 2) * 5) % nu
      params, opt, loss = step(params, opt, seeds, np.full(N_PARTS, 2),
                               jax.random.key(it))
      out.append(float(np.asarray(loss)[0]))
    return out

  np.testing.assert_allclose(losses(0.3), losses(None), rtol=1e-6)

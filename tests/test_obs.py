"""Unified observability layer: metrics registry, tracer, RPC trace
propagation, back-compat of the ServingMetrics view, and the
obs-disabled overhead bound.

The cross-process acceptance test (client + 2 partition servers
assembling ONE Chrome trace) lives at the bottom — it reuses the
test_server_client spawn harness."""
import json
import multiprocessing as mp
import threading
import time

import numpy as np
import pytest

from glt_tpu.obs import (
    LatencyHistogram, MetricsRegistry, Tracer, collect_endpoint_obs,
    get_tracer, merge_chrome_traces,
)
from glt_tpu.serving import ServingMetrics


@pytest.fixture
def tracer():
  """The process tracer, force-restored to disabled+empty."""
  t = get_tracer()
  was, sample = t.enabled, t._sample
  t.clear()
  yield t
  t.enabled, t._sample = was, sample
  t.clear()


# -- registry ------------------------------------------------------------

def test_registry_counter_gauge_histogram():
  r = MetricsRegistry()
  c = r.counter('requests_total')
  assert c.inc() == 1 and c.inc(4) == 5
  assert r.counter('requests_total') is c  # get-or-create
  r.set('depth', 3.0)
  assert r.add('depth', -1.0) == 2.0
  assert r.get('depth') == 2.0
  assert r.get('missing', default=7.0) == 7.0
  r.observe('lat_seconds', 0.01)
  r.observe('lat_seconds', 0.02)
  snap = r.snapshot()
  assert snap['counters']['requests_total'] == 5
  assert snap['gauges']['depth'] == 2.0
  h = snap['histograms']['lat_seconds']
  assert h['count'] == 2 and abs(h['sum'] - 0.03) < 1e-9
  assert 0 < h['p50'] <= h['p99'] <= h['max'] + 1e-9
  json.loads(r.to_json())  # exposition is valid JSON


def test_registry_labels_distinct_series():
  r = MetricsRegistry()
  r.inc('hits', stage='sample')
  r.inc('hits', 2, stage='gather')
  snap = r.snapshot()['counters']
  assert snap['hits{stage="sample"}'] == 1
  assert snap['hits{stage="gather"}'] == 2
  assert r.get('hits', stage='sample') == 1


def test_registry_prometheus_exposition():
  r = MetricsRegistry(namespace='glt')
  r.inc('serving_requests_total', 3)
  r.set('queue_depth', 4.0, shard='0')
  r.observe('stage_seconds', 0.05, stage='gather')
  text = r.to_prometheus()
  assert '# TYPE glt_serving_requests_total counter' in text
  assert 'glt_serving_requests_total 3' in text
  assert 'glt_queue_depth{shard="0"} 4' in text
  assert '# TYPE glt_stage_seconds summary' in text
  assert 'glt_stage_seconds_count{stage="gather"} 1' in text
  assert 'quantile="0.99"' in text


def test_registry_snapshot_is_atomic_under_writers():
  """Paired counters incremented under one registry-lock hold must
  never tear in a concurrent snapshot (the hit_rate bug class)."""
  r = MetricsRegistry()
  a, b = r.counter('a_total'), r.counter('b_total')
  stop = threading.Event()
  bad = []

  def writer():
    for _ in range(2000):
      with r._lock:  # one atomic group, as ServingMetrics writes them
        a.inc()
        b.inc()

  def reader():
    while not stop.is_set():
      s = r.snapshot()['counters']
      if s['a_total'] != s['b_total']:
        bad.append(s)

  a.inc(0); b.inc(0)  # materialize before readers start
  ts = [threading.Thread(target=writer) for _ in range(4)]
  rd = threading.Thread(target=reader)
  rd.start()
  for t in ts:
    t.start()
  for t in ts:
    t.join()
  stop.set()
  rd.join()
  assert not bad, bad[:3]
  assert a.value == b.value == 8000


# -- LatencyHistogram edge cases (satellite) -----------------------------

def test_histogram_percentile_edges():
  h = LatencyHistogram()
  assert h.percentile(0) == 0.0 and h.percentile(100) == 0.0  # empty
  assert h.mean == 0.0
  for ms in (1, 2, 5, 10):
    h.observe(ms / 1e3)
  # q=0 answers the underflow edge (a lower bound), q=100 the true max
  assert h.percentile(0) == h._MIN
  assert h.percentile(100) == h.max == 0.010


def test_histogram_underflow_overflow_buckets():
  h = LatencyHistogram(num_bins=10)
  h.observe(1e-7)   # under the 10 µs floor -> underflow bucket
  assert h._counts[0] == 1
  assert h.percentile(50) == h._MIN
  h2 = LatencyHistogram(num_bins=10)
  h2.observe(1e9)   # absurdly past the top bucket -> overflow bucket
  assert h2._counts[-1] == 1
  # the overflow bucket's answer is clamped to the tracked true max
  assert h2.percentile(99) == 1e9 == h2.max
  assert h2.count == 1 and h2.sum == 1e9


def test_add_gauge_concurrent_writers():
  """add_gauge is one lock hold — N threads accumulating must land on
  the exact total (a get/set pair would tear)."""
  m = ServingMetrics()
  N, W = 1000, 8

  def worker():
    for _ in range(N):
      m.add_gauge('acc', 1.0)

  ts = [threading.Thread(target=worker) for _ in range(W)]
  for t in ts:
    t.start()
  for t in ts:
    t.join()
  assert m.get_gauge('acc') == float(N * W)


# -- ServingMetrics as a registry view (back-compat) ---------------------

#: the frozen pre-obs snapshot() key set — the back-compat contract
_LEGACY_SNAPSHOT_KEYS = {
    'requests', 'ids_served', 'qps', 'latency_p50_ms', 'latency_p99_ms',
    'latency_mean_ms', 'latency_max_ms', 'batches', 'batch_fill_ratio',
    'timeouts', 'rejected', 'retries', 'reconnects', 'breaker_opens',
    'shed', 'stale_serves', 'failovers', 'gauges',
}


def test_serving_metrics_snapshot_keys_unchanged():
  m = ServingMetrics()
  assert set(m.snapshot().keys()) == _LEGACY_SNAPSHOT_KEYS
  # every legacy counter attribute still reads as an int
  for attr in ('requests', 'ids_served', 'timeouts', 'rejected',
               'batches', 'batched_ids', 'batch_capacity', 'retries',
               'reconnects', 'breaker_opens', 'shed', 'stale_serves',
               'failovers'):
    assert getattr(m, attr) == 0


def test_serving_metrics_exposed_in_registry():
  """The view publishes into ONE registry: every legacy counter appears
  in the registry's Prometheus exposition."""
  m = ServingMetrics()
  m.record_request(0.003, num_ids=2)
  m.record_retry(3)
  m.set_gauge('snapshot_version', 5)
  text = m.registry.to_prometheus()
  assert 'glt_serving_requests_total 1' in text
  assert 'glt_serving_ids_served_total 2' in text
  assert 'glt_rpc_retries_total 3' in text
  assert 'glt_snapshot_version 5' in text
  assert 'glt_serving_latency_seconds_count 1' in text


def test_serving_metrics_shared_registry_with_labels():
  r = MetricsRegistry()
  m1 = ServingMetrics(registry=r, name='a')
  m2 = ServingMetrics(registry=r, name='b')
  m1.record_request(0.001)
  m1.record_request(0.001)
  m2.record_request(0.001)
  assert m1.requests == 2 and m2.requests == 1  # no collision
  counters = r.snapshot()['counters']
  assert counters['serving_requests_total{view="a"}'] == 2
  assert counters['serving_requests_total{view="b"}'] == 1


def test_qps_and_fill_ratio_derive_from_locked_snapshot():
  """The satellite fix: qps / batch_fill_ratio / report() route through
  one locked snapshot instead of raw unlocked field reads."""
  m = ServingMetrics()
  for _ in range(10):
    m.record_request(0.001)
  m.record_batch(6, 8)
  assert m.qps > 0
  assert m.batch_fill_ratio == 0.75
  rep = m.report()
  assert 'p50=' in rep and 'fill=0.75' in rep
  # hammer writers while reading the derived properties: no exceptions,
  # values always internally consistent
  done = threading.Event()

  def writer():
    while not done.is_set():
      m.record_batch(1, 2)

  t = threading.Thread(target=writer)
  t.start()
  try:
    for _ in range(200):
      assert 0.0 <= m.batch_fill_ratio <= 1.0
      assert m.qps >= 0.0
  finally:
    done.set()
    t.join()


# -- tracer --------------------------------------------------------------

def test_tracer_disabled_is_noop(tracer):
  assert not tracer.enabled
  cm = tracer.span('x')
  assert tracer.span('y') is cm  # the cached null manager
  with cm as ctx:
    assert ctx is None
  assert tracer.events() == []


def test_tracer_nesting_and_chrome_export(tracer):
  tracer.enable()
  with tracer.span('root', cat='test') as root:
    with tracer.span('child') as child:
      assert child.trace_id == root.trace_id
      assert tracer.current_context() == child
    with tracer.span('child2'):
      pass
  evs = tracer.events(trace_id=root.trace_id)
  assert [e['name'] for e in evs] == ['child', 'child2', 'root']
  by_name = {e['name']: e for e in evs}
  assert by_name['child']['args']['parent_id'] == root.span_id
  assert by_name['child2']['args']['parent_id'] == root.span_id
  assert 'parent_id' not in by_name['root']['args']
  doc = merge_chrome_traces(evs)
  json.dumps(doc)  # Chrome/Perfetto-loadable
  assert any(e.get('ph') == 'M' for e in doc['traceEvents'])
  assert all(e['ph'] == 'X' and e['dur'] >= 0
             for e in doc['traceEvents'] if e.get('ph') != 'M')


def test_tracer_remote_span_reopens_context(tracer):
  """The server side of RPC propagation: an incoming (trace_id,
  span_id) pair becomes the parent, even with the local tracer
  disabled (the CALLER opted into tracing)."""
  assert not tracer.enabled
  with tracer.remote_span('rpc.server:f', ('t1234', 'c9')):
    pass
  (ev,) = tracer.events()
  assert ev['args']['trace_id'] == 't1234'
  assert ev['args']['parent_id'] == 'c9'


def test_tracer_sync_callable_and_sampling(tracer):
  import jax.numpy as jnp
  tracer.enable(sample=1.0)
  holder = {}
  with tracer.span('dispatch', sync=lambda: holder.get('x')):
    holder['x'] = jnp.arange(8) * 2
  (ev,) = tracer.events()
  assert ev['args'].get('synced') is True
  tracer.clear()
  tracer.enable(sample=0.0)  # sampling off: no sync marker
  with tracer.span('dispatch', sync=lambda: holder['x']):
    pass
  (ev,) = tracer.events()
  assert 'synced' not in ev['args']


def test_tracer_ring_buffer_bounds(tracer):
  t = Tracer(enabled=True, buffer=16, registry=MetricsRegistry())
  for i in range(40):
    with t.span(f's{i}'):
      pass
  assert len(t.events()) == 16
  assert t.dropped == 24


def test_tracer_publishes_stage_histograms(tracer):
  reg = MetricsRegistry()
  t = Tracer(enabled=True, registry=reg)
  for _ in range(3):
    with t.span('gather.features'):
      pass
  snap = reg.snapshot()['histograms']
  assert snap['stage_seconds{stage="gather.features"}']['count'] == 3


# -- RPC propagation (single process, real sockets) ----------------------

def test_rpc_trace_propagation_and_obs_harvest(tracer):
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  srv = RpcServer()
  srv.register('mul', lambda a, b: a * b)
  cli = RpcClient(srv.host, srv.port)
  try:
    # untraced request: no spans recorded anywhere
    assert cli.request('mul', 3, 4) == 12
    assert tracer.events() == []
    tracer.enable()
    with tracer.span('root') as root:
      assert cli.request('mul', 5, 6) == 30
    tracer.disable()
    evs = tracer.events(trace_id=root.trace_id)
    names = sorted(e['name'] for e in evs)
    assert names == ['root', 'rpc.client:mul', 'rpc.server:mul']
    by = {e['name']: e for e in evs}
    assert by['rpc.client:mul']['args']['parent_id'] == root.span_id
    assert by['rpc.server:mul']['args']['parent_id'] == \
        by['rpc.client:mul']['args']['span_id']
    # the built-in _obs callee harvests the same events + registry
    out = collect_endpoint_obs(srv.host, srv.port)
    assert {e['name'] for e in out['events']} >= {'rpc.server:mul'}
    assert 'counters' in out['metrics']
  finally:
    cli.close()
    srv.stop()


# -- zero-recompile invariants hold with obs enabled ---------------------

def test_engine_zero_recompiles_with_obs_enabled(tracer):
  """Tracing (incl. 100% device-sync sampling) is host-side only: the
  serving engine's steady state must stay at zero re-traces."""
  import jax
  from fixtures import ring_dataset
  from glt_tpu.models import GraphSAGE
  from glt_tpu.serving import InferenceEngine
  ds = ring_dataset(num_nodes=40)
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
  eng = InferenceEngine(ds, model, None, [-1, -1], buckets=(4, 8))
  eng.init_params(jax.random.key(0))
  eng.warmup()
  warm = eng.compile_stats()
  tracer.enable(sample=1.0)
  for n in (1, 3, 4, 7, 8):
    eng.infer(np.arange(n) % 40)
  now = eng.compile_stats()
  assert now['forward_traces'] == warm['forward_traces']
  assert now['sampler_compiled_fns'] == warm['sampler_compiled_fns']
  # and the stages actually traced
  names = {e['name'] for e in tracer.events()}
  assert {'serve.bucket', 'serve.forward', 'sample.multihop',
          'gather.features'} <= names


# -- obs-disabled overhead bound (satellite: tier-1 guard) ---------------

def test_obs_disabled_overhead_under_2_percent(tracer):
  """The no-op path (disabled tracer span + enabled-check) must cost
  under 2% of a sampled-epoch microbenchmark. Measured structurally:
  time one real sampled epoch, then time the no-op obs calls that
  epoch would issue, scaled up 4x for margin."""
  from fixtures import ring_dataset
  from glt_tpu.loader import NeighborLoader
  assert not tracer.enabled
  ds = ring_dataset(num_nodes=200)
  loader = NeighborLoader(ds, [4, 4], np.arange(200), batch_size=32,
                          seed=0)
  list(loader)  # compile outside the timed window
  epoch_s = min(_timed(lambda: list(loader)) for _ in range(3))
  n_batches = len(loader)
  # spans issued per batch on this path: loader.batch enabled-check,
  # sample.multihop, gather.features (+ slack for future stages)
  spans_per_batch = 8

  def noop_spans():
    for _ in range(n_batches * spans_per_batch * 4):
      with tracer.span('loader.batch', batch=32):
        pass

  noop_s = min(_timed(noop_spans) for _ in range(3)) / 4
  assert noop_s < 0.02 * epoch_s, (
      f'no-op obs path costs {noop_s * 1e3:.3f}ms against a '
      f'{epoch_s * 1e3:.1f}ms epoch (>{noop_s / epoch_s:.1%})')


def _timed(fn):
  t0 = time.perf_counter()
  fn()
  return time.perf_counter() - t0


# -- cross-process acceptance: one trace across client + 2 servers -------

def _obs_server_proc(rank, port, ready, done):
  import os
  import sys
  sys.path.insert(0, os.path.dirname(__file__))
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from glt_tpu.obs import get_tracer
  get_tracer().enable()
  from fixtures import ring_dataset
  from glt_tpu.distributed import init_server, wait_and_shutdown_server
  ds = ring_dataset(num_nodes=40, feat_dim=4)
  init_server(num_servers=2, num_clients=1, server_rank=rank,
              dataset=ds, master_port=port)
  ready.set()
  wait_and_shutdown_server(poll_s=0.1)
  done.set()


def test_distributed_trace_single_trace_id(tmp_path, tracer):
  """Client + 2 partition servers: a sample-and-serve run must emit ONE
  Chrome-trace JSON where the client-side spans and the server-side
  handler spans share one trace id and nest correctly (deterministic
  span tree)."""
  from fixtures import ring_dataset
  from glt_tpu.channel import pack_message
  from glt_tpu.distributed import (
      export_fabric_trace, init_client, request_server, shutdown_client,
  )
  from glt_tpu.sampler import NeighborSampler
  ctx = mp.get_context('spawn')
  port = 47321
  readies = [ctx.Event() for _ in range(2)]
  dones = [ctx.Event() for _ in range(2)]
  servers = [ctx.Process(target=_obs_server_proc,
                         args=(r, port, readies[r], dones[r]))
             for r in range(2)]
  for s in servers:
    s.start()
  for e in readies:
    assert e.wait(timeout=60), 'server did not come up'

  init_client(num_servers=2, num_clients=1, client_rank=0,
              master_port=port, health_interval_s=None)
  try:
    tracer.enable()
    local_sampler = NeighborSampler(
        ring_dataset(num_nodes=40).graph, [2, 2], seed=0)
    with tracer.span('pipeline.request') as root:
      # sample arm: local multihop (the client-side sampling stage)
      local_sampler.sample_from_nodes(np.arange(8))
      # serve arm: remote feature lookups on BOTH partition servers
      for s in (0, 1):
        request_server(s, 'get_node_feature',
                       pack_message({'ids': np.array([1, 2, 3])}))
    tracer.disable()

    # -- deterministic span-tree assertions ------------------------------
    client_evs = tracer.events(trace_id=root.trace_id)
    names = sorted(e['name'] for e in client_evs)
    assert names == ['pipeline.request', 'rpc.client:get_node_feature',
                     'rpc.client:get_node_feature', 'sample.multihop']
    by_id = {e['args']['span_id']: e for e in client_evs}
    rpc_spans = [e for e in client_evs
                 if e['name'] == 'rpc.client:get_node_feature']
    for e in client_evs:
      if e['name'] != 'pipeline.request':
        assert e['args']['parent_id'] == root.span_id

    from glt_tpu.distributed import collect_obs
    server_parents = []
    for s in (0, 1):
      sev = [e for e in collect_obs(s)['events']
             if e['args'].get('trace_id') == root.trace_id]
      assert [e['name'] for e in sev] == \
          ['rpc.server:get_node_feature'], sev
      assert sev[0]['pid'] != client_evs[0]['pid']  # truly cross-process
      server_parents.append(sev[0]['args']['parent_id'])
    # each handler span nests under exactly one distinct client rpc span
    assert sorted(server_parents) == \
        sorted(e['args']['span_id'] for e in rpc_spans)
    assert set(server_parents) <= set(by_id)

    # -- single merged Perfetto/Chrome JSON ------------------------------
    path = str(tmp_path / 'fabric_trace.json')
    export_fabric_trace(path, trace_id=root.trace_id)
    doc = json.load(open(path))
    spans = [e for e in doc['traceEvents'] if e.get('ph') == 'X']
    assert len(spans) == 6  # 4 client + 2 server handler spans
    assert {e['args']['trace_id'] for e in spans} == {root.trace_id}
    assert len({e['pid'] for e in spans}) == 3  # client + 2 servers
  finally:
    shutdown_client()
    # drop the client DistContext so later tests (e.g. test_rpc_fabric's
    # no-context identity check) see a clean slate
    from glt_tpu.distributed.dist_context import shutdown
    shutdown()
  for i, s in enumerate(servers):
    assert dones[i].wait(timeout=30), 'server did not exit cleanly'
    s.join(timeout=10)

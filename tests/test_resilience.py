"""Unit tests for the resilience primitives: retry policy, circuit
breaker, health monitor, degraded feature cache, fault-plan
determinism, and the ServingMetrics failure counters."""
import threading
import time

import numpy as np
import pytest

from glt_tpu.resilience import (
    CLOSED, DEGRADED, DOWN, HALF_OPEN, OPEN, UP, ChaosChannel,
    CircuitBreaker, CircuitOpenError, DegradedFeatureCache, FaultPlan,
    HealthMonitor, RetryPolicy, chaos_seed, flaky,
)
from glt_tpu.serving import ServingMetrics


# -- retry policy --------------------------------------------------------

def test_retry_backoff_caps_and_grows():
  p = RetryPolicy(max_attempts=5, base_delay_s=0.1, max_delay_s=0.5,
                  jitter=0)
  delays = [p.delay(a) for a in range(5)]
  assert delays == [0.1, 0.2, 0.4, 0.5, 0.5]  # doubles, then capped


def test_retry_jitter_bounds():
  p = RetryPolicy(base_delay_s=0.1, max_delay_s=10.0, jitter=0.5)
  for a in range(4):
    base = min(0.1 * 2 ** a, 10.0)
    for _ in range(50):
      d = p.delay(a)
      assert base * 0.5 <= d <= base + 1e-12


# -- circuit breaker -----------------------------------------------------

def test_breaker_trips_after_consecutive_failures_only():
  b = CircuitBreaker(failure_threshold=3, reset_timeout_s=60)
  for _ in range(2):
    assert b.allow()
    b.record_failure()
  b.record_success()          # streak broken: an occasional flake
  for _ in range(2):
    assert b.allow()
    b.record_failure()
  assert b.state == CLOSED    # still under threshold
  b.record_failure()
  assert b.state == OPEN
  assert not b.allow()        # fail fast
  assert b.opens == 1


def test_breaker_half_open_probe_closes_or_reopens():
  b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
  b.record_failure()
  assert b.state == OPEN and not b.allow()
  time.sleep(0.06)
  assert b.state == HALF_OPEN
  assert b.allow()            # the single probe is admitted
  assert not b.allow()        # second concurrent probe is NOT
  b.record_failure()          # probe failed: re-open + re-arm
  assert b.state == OPEN and b.opens == 2
  time.sleep(0.06)
  assert b.allow()
  b.record_success()
  assert b.state == CLOSED and b.allow()


def test_breaker_release_probe_returns_token():
  """A probe that aborts before exercising the peer (caller bug, e.g.
  an unpicklable argument) must hand its HALF_OPEN token back — it is
  neither a success nor a peer failure — or no probe is ever admitted
  again and the breaker wedges OPEN against a healthy peer."""
  b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05)
  b.record_failure()
  time.sleep(0.06)
  assert b.allow()            # probe token taken
  assert not b.allow()
  b.release_probe()           # aborted attempt: token returned
  assert b.state == HALF_OPEN
  assert b.allow()            # the NEXT probe is admitted
  b.record_success()
  assert b.state == CLOSED


def test_breaker_on_open_fires_once_per_transition():
  opens = []
  b = CircuitBreaker(failure_threshold=2, reset_timeout_s=60,
                     on_open=lambda: opens.append(1))
  b.record_failure()
  b.record_failure()
  b.record_failure()          # already OPEN: no second event
  assert len(opens) == 1


# -- health monitor ------------------------------------------------------

def test_health_monitor_thresholds_and_recovery():
  ok = {'a': True, 'b': True}

  def probe(name):
    def run():
      if not ok[name]:
        raise ConnectionError('down')
    return run

  m = HealthMonitor({'a': probe('a'), 'b': probe('b')},
                    degraded_after=1, down_after=3)
  assert m.check_now() == {'a': UP, 'b': UP}
  ok['b'] = False
  assert m.check_now()['b'] == DEGRADED
  m.check_now(); m.check_now()
  assert m.status('b') == DOWN
  assert m.healthy() == ['a']
  ok['b'] = True
  assert m.check_now()['b'] == UP   # one good probe fully recovers


def test_health_monitor_passive_observations_and_background():
  m = HealthMonitor({'s': lambda: None}, degraded_after=1, down_after=2)
  m.record_failure('s')
  assert m.status('s') == DEGRADED
  m.record_failure('s')
  assert m.is_down('s')
  # the background prober (healthy probe fn) recovers it
  m.start(interval_s=0.02)
  try:
    assert m.wait_for('s', UP, timeout_s=5)
  finally:
    m.stop()


def test_health_monitor_allow_probe_rate_limited():
  """Passive-only deployments (no background prober) rejoin a DOWN
  peer via rate-limited probe-throughs: the first admission is
  immediate, repeats wait out the interval."""
  m = HealthMonitor({'s': lambda: None}, interval_s=0.1,
                    degraded_after=1, down_after=1)
  m.record_failure('s')
  assert m.is_down('s')
  assert m.allow_probe('s')          # first probe-through admitted
  assert not m.allow_probe('s')      # rate-limited inside interval
  time.sleep(0.11)
  assert m.allow_probe('s')          # next window: admitted again
  m.record_success('s')              # the probe-through succeeded
  assert m.status('s') == UP


# -- degraded feature cache ----------------------------------------------

def test_degraded_cache_serves_stale_rows_and_zero_fills():
  c = DegradedFeatureCache(capacity=100)
  c.update([1, 2], np.array([[1., 1.], [2., 2.]], np.float32))
  rows, mask = c.serve([2, 7, 1])
  np.testing.assert_allclose(rows, [[2, 2], [0, 0], [1, 1]])
  assert mask.tolist() == [True, False, True]


def test_degraded_cache_unknown_width_raises():
  with pytest.raises(RuntimeError):
    DegradedFeatureCache().serve([1, 2])


# -- chaos determinism ---------------------------------------------------

def test_fault_plan_same_seed_same_schedule():
  mk = lambda: FaultPlan(seed=77, drop=0.2, disconnect=0.1, delay=0.15)
  a = [mk().next_fault() for _ in range(1)]  # noqa: F841 (api sanity)
  p1, p2 = mk(), mk()
  s1 = [p1.next_fault() for _ in range(200)]
  s2 = [p2.next_fault() for _ in range(200)]
  assert s1 == s2
  assert any(f is not None for f in s1)
  # forks are deterministic AND independent per salt — ONE fork each,
  # whole streams compared (a fresh fork per draw only ever checks the
  # first decision)
  f1, f2 = p1.fork(3), p2.fork(3)
  assert [f1.next_fault() for _ in range(50)] \
      == [f2.next_fault() for _ in range(50)]
  g1, g2 = p1.fork(4), p2.fork(4)
  assert [g1.next_fault() for _ in range(50)] \
      == [g2.next_fault() for _ in range(50)]
  assert mk().schedule(200) == s1


def test_fault_plan_start_after_and_max_faults():
  p = FaultPlan(seed=1, drop=1.0, start_after=3, max_faults=2)
  sched = [p.next_fault() for _ in range(10)]
  assert sched[:3] == [None, None, None]
  assert sched[3:5] == ['drop', 'drop']
  assert sched[5:] == [None] * 5


def test_chaos_seed_env_knob(monkeypatch):
  monkeypatch.setenv('GLT_CHAOS_SEED', '4242')
  assert chaos_seed() == 4242
  assert FaultPlan(drop=0.5).seed == 4242
  monkeypatch.delenv('GLT_CHAOS_SEED')
  assert chaos_seed() == 0


def test_flaky_wrapper_injects_connection_errors():
  plan = FaultPlan(seed=5, disconnect=0.5)
  fn = flaky(lambda x: x + 1, plan)
  outcomes = []
  for i in range(50):
    try:
      outcomes.append(fn(i))
    except ConnectionError:
      outcomes.append('boom')
  assert 'boom' in outcomes and any(isinstance(o, int) for o in outcomes)


def test_chaos_channel_drop_and_disconnect():
  from glt_tpu.channel.mp_channel import MpChannel

  class ListChannel:
    def __init__(self):
      self.items = []
    def send(self, m):
      self.items.append(m)
    def recv(self, timeout_ms=1000):
      if not self.items:
        raise TimeoutError('empty')
      return self.items.pop(0)
    def empty(self):
      return not self.items

  plan = FaultPlan(seed=0, drop=1.0, max_faults=1)
  ch = ChaosChannel(ListChannel(), plan)
  ch.send({'a': 1}); ch.send({'a': 2})
  # first message dropped, second delivered within the same budget
  assert ch.recv(timeout_ms=1000) == {'a': 2}
  plan2 = FaultPlan(seed=0, disconnect=1.0)
  ch2 = ChaosChannel(ListChannel(), plan2)
  ch2.send({'x': 1})
  with pytest.raises(ConnectionError):
    ch2.recv(timeout_ms=200)


# -- metrics failure counters --------------------------------------------

def test_metrics_failure_counters_in_snapshot():
  m = ServingMetrics()
  m.record_retry(); m.record_retry(2)
  m.record_reconnect()
  m.record_breaker_open()
  m.record_shed(3)
  m.record_stale_serve(4)
  m.record_failover()
  snap = m.snapshot()
  assert snap['retries'] == 3
  assert snap['reconnects'] == 1
  assert snap['breaker_opens'] == 1
  assert snap['shed'] == 3
  assert snap['stale_serves'] == 4
  assert snap['failovers'] == 1


def test_metrics_counters_torn_read_safe():
  """Mirror of the PR-3 hit_rate torn-read fix: hammer the failure
  counters from writer threads while snapshotting concurrently; every
  snapshot must show internally-consistent (never negative, never
  beyond-final) values and the final totals must be exact."""
  m = ServingMetrics()
  N, W = 500, 4
  stop = threading.Event()
  bad = []

  def writer():
    for _ in range(N):
      m.record_retry()
      m.record_shed()
      m.record_stale_serve()

  def reader():
    while not stop.is_set():
      s = m.snapshot()
      for k in ('retries', 'shed', 'stale_serves'):
        if not (0 <= s[k] <= N * W):
          bad.append(s)

  threads = [threading.Thread(target=writer) for _ in range(W)]
  r = threading.Thread(target=reader)
  r.start()
  for t in threads:
    t.start()
  for t in threads:
    t.join()
  stop.set()
  r.join()
  assert not bad
  s = m.snapshot()
  assert s['retries'] == s['shed'] == s['stale_serves'] == N * W


# -- shard/replica-labeled resilience series (fleet contract) ------------

def test_breaker_series_and_trip_payload_carry_labels():
  """Two shards' breakers on ONE shared registry: the ``labels=`` keys
  must ride both the published series (``breaker_state`` /
  ``breaker_opens_total``) and the FlightRecorder trip payload, so a
  fleet postmortem can tell WHICH replica opened — unlabeled series
  from shard0/r0 and shard1/r0 would silently merge."""
  from glt_tpu.obs.recorder import FlightRecorder, set_recorder
  from glt_tpu.obs.registry import MetricsRegistry

  reg = MetricsRegistry()
  rec = FlightRecorder()
  prev = set_recorder(rec)
  try:
    breakers = {
        s: CircuitBreaker(failure_threshold=2, reset_timeout_s=60,
                          name=f'{s}/r0',
                          labels={'shard': s, 'replica': 'r0'},
                          registry=reg)
        for s in ('s0', 's1')}
    breakers['s0'].record_failure()
    breakers['s0'].record_failure()   # s0/r0 opens
    assert breakers['s0'].state == OPEN
    assert reg.get('breaker_state', breaker='s0/r0', shard='s0',
                   replica='r0') == 2.0
    assert reg.get('breaker_opens_total', breaker='s0/r0', shard='s0',
                   replica='r0') == 1
    # s1/r0 shares the registry but NOT the series
    assert reg.get('breaker_opens_total', breaker='s1/r0', shard='s1',
                   replica='r0') == 0
    breakers['s1'].record_failure()
    assert reg.get('breaker_state', breaker='s1/r0', shard='s1',
                   replica='r0') == 0.0  # still CLOSED
    trips = [e for e in rec.events() if e['kind'] == 'breaker_open']
    assert len(trips) == 1
    assert trips[0]['shard'] == 's0'
    assert trips[0]['replica'] == 'r0'
    assert trips[0]['breaker'] == 's0/r0'
  finally:
    set_recorder(prev)


def test_breaker_close_publishes_closed_state():
  from glt_tpu.obs.registry import MetricsRegistry
  reg = MetricsRegistry()
  b = CircuitBreaker(failure_threshold=1, reset_timeout_s=0.05,
                     name='s0/r0', labels={'shard': 's0'}, registry=reg)
  b.record_failure()
  assert reg.get('breaker_state', breaker='s0/r0', shard='s0') == 2.0
  time.sleep(0.06)
  assert b.allow()
  b.record_success()
  assert reg.get('breaker_state', breaker='s0/r0', shard='s0') == 0.0


def test_health_monitor_publishes_labeled_status_series():
  """Two monitors with colliding target names (every shard calls its
  replicas r0/r1) stay distinct series via ``labels=``."""
  from glt_tpu.obs.registry import MetricsRegistry
  reg = MetricsRegistry()
  mons = {
      s: HealthMonitor({'r0': lambda: True}, degraded_after=1,
                       down_after=2, labels={'shard': s}, registry=reg)
      for s in ('s0', 's1')}
  mons['s0'].record_failure('r0')
  mons['s0'].record_failure('r0')
  assert mons['s0'].status('r0') == DOWN
  assert reg.get('health_status', target='r0', shard='s0') == 2.0
  # shard1's r0 is untouched: no publication, default reads 0
  assert mons['s1'].status('r0') == UP
  assert reg.get('health_status', target='r0', shard='s1') == 0.0
  mons['s0'].record_success('r0')
  assert reg.get('health_status', target='r0', shard='s0') == 0.0

import numpy as np
import pytest

from glt_tpu.data import Feature, Topology, sort_by_in_degree


def test_fully_device_resident_lookup():
  feats = np.arange(20, dtype=np.float32).reshape(10, 2)
  f = Feature(feats, split_ratio=1.0)
  out = f[np.array([3, 0, 9])]
  np.testing.assert_allclose(out, feats[[3, 0, 9]])
  assert f.fully_device_resident


def test_split_lookup_crosses_hot_cold_boundary():
  feats = np.arange(40, dtype=np.float32).reshape(10, 4)
  f = Feature(feats, split_ratio=0.3)  # rows 0-2 hot, 3-9 cold
  assert f.hot_count == 3
  ids = np.array([0, 5, 2, 9, 3])
  np.testing.assert_allclose(f[ids], feats[ids])
  assert not f.fully_device_resident


def test_id2index_mapping():
  feats = np.array([[10.], [20.], [30.]], dtype=np.float32)
  id2index = np.array([2, 0, 1])  # global id 0 -> row 2, etc.
  f = Feature(feats, id2index=id2index)
  out = f[np.array([0, 1, 2])]
  np.testing.assert_allclose(out, [[30.], [10.], [20.]])


def test_sort_by_in_degree_reorder():
  # node 2 has in-degree 3, node 0 has 1, node 1 has 0
  ei = np.array([[0, 1, 0, 2], [2, 2, 2, 0]])
  topo = Topology(edge_index=ei, num_nodes=3)
  feats = np.array([[0.], [1.], [2.]], dtype=np.float32)
  sorted_feats, old2new = sort_by_in_degree(feats, 0.5, topo)
  # hottest first: node2, then node0, then node1
  np.testing.assert_allclose(sorted_feats, [[2.], [0.], [1.]])
  np.testing.assert_array_equal(old2new, [1, 2, 0])
  # lookup through the map returns original values
  f = Feature(sorted_feats, split_ratio=1.0, id2index=old2new)
  np.testing.assert_allclose(f[np.array([0, 1, 2])], feats)


def test_dtype_cast_bf16():
  import jax.numpy as jnp
  feats = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
  f = Feature(feats, split_ratio=0.5, dtype=jnp.bfloat16)
  out = f[np.arange(8)]
  np.testing.assert_allclose(
      out.astype(np.float32), feats, rtol=2e-2, atol=2e-2)


def test_gather_mixed_host_offload_parity():
  from fixtures import skip_unless_pinned_host
  skip_unless_pinned_host()
  # pinned-host cold block served in-jit == plain values, across the
  # hot/cold boundary and for the all-cold (hot_count=0) table
  import jax.numpy as jnp
  feats = (np.arange(20, dtype=np.float32)[:, None]
           * np.ones(4, np.float32))
  for ratio in (0.3, 0.0):
    f = Feature(feats, split_ratio=ratio)
    f.lazy_init()
    assert f.cold_array is not None
    rows = jnp.asarray(np.array([0, 5, 19, 7, 3, 19]))
    out = np.asarray(f.gather_mixed(rows))
    np.testing.assert_allclose(out, feats[np.asarray(rows)])


def test_host_offload_opt_out_keeps_host_phase():
  feats = np.arange(12, dtype=np.float32)[:, None]
  f = Feature(feats, split_ratio=0.5, host_offload=False)
  f.lazy_init()
  assert f.cold_array is None
  np.testing.assert_allclose(
      f.gather_cold_host(np.array([8, 11])), feats[[8, 11]])


def test_loader_prefetch_auto_keys_on_offload():
  # offloaded spill has no host phase -> auto prefetch 0; legacy spill
  # keeps the depth-2 overlap default
  from glt_tpu.data import Dataset
  from glt_tpu.loader import NeighborLoader
  rng = np.random.default_rng(0)
  n = 60
  src = np.repeat(np.arange(n), 2)
  dst = (src + rng.integers(1, n, src.shape[0])) % n
  feats = np.arange(n, dtype=np.float32)[:, None]
  def build(**kw):
    ds = Dataset(edge_dir='out')
    ds.init_graph(edge_index=np.stack([src, dst]), num_nodes=n)
    ds.init_node_features(feats, **kw)
    return ds
  mk = lambda ds: NeighborLoader(ds, [2], input_nodes=np.arange(16),
                                 batch_size=8, seed=0)
  assert mk(build(split_ratio=0.3)).prefetch_depth == 0
  assert mk(build(split_ratio=0.3,
                  host_offload=False)).prefetch_depth == 2
  # and the offloaded loader still collates exact values
  loader = mk(build(split_ratio=0.3))
  b = next(iter(loader))
  nc = int(np.asarray(b.node_count))
  np.testing.assert_allclose(np.asarray(b.x)[:nc, 0],
                             np.asarray(b.node)[:nc])

import numpy as np
import pytest

from glt_tpu.data import Feature, Topology, sort_by_in_degree


def test_fully_device_resident_lookup():
  feats = np.arange(20, dtype=np.float32).reshape(10, 2)
  f = Feature(feats, split_ratio=1.0)
  out = f[np.array([3, 0, 9])]
  np.testing.assert_allclose(out, feats[[3, 0, 9]])
  assert f.fully_device_resident


def test_split_lookup_crosses_hot_cold_boundary():
  feats = np.arange(40, dtype=np.float32).reshape(10, 4)
  f = Feature(feats, split_ratio=0.3)  # rows 0-2 hot, 3-9 cold
  assert f.hot_count == 3
  ids = np.array([0, 5, 2, 9, 3])
  np.testing.assert_allclose(f[ids], feats[ids])
  assert not f.fully_device_resident


def test_id2index_mapping():
  feats = np.array([[10.], [20.], [30.]], dtype=np.float32)
  id2index = np.array([2, 0, 1])  # global id 0 -> row 2, etc.
  f = Feature(feats, id2index=id2index)
  out = f[np.array([0, 1, 2])]
  np.testing.assert_allclose(out, [[30.], [10.], [20.]])


def test_sort_by_in_degree_reorder():
  # node 2 has in-degree 3, node 0 has 1, node 1 has 0
  ei = np.array([[0, 1, 0, 2], [2, 2, 2, 0]])
  topo = Topology(edge_index=ei, num_nodes=3)
  feats = np.array([[0.], [1.], [2.]], dtype=np.float32)
  sorted_feats, old2new = sort_by_in_degree(feats, 0.5, topo)
  # hottest first: node2, then node0, then node1
  np.testing.assert_allclose(sorted_feats, [[2.], [0.], [1.]])
  np.testing.assert_array_equal(old2new, [1, 2, 0])
  # lookup through the map returns original values
  f = Feature(sorted_feats, split_ratio=1.0, id2index=old2new)
  np.testing.assert_allclose(f[np.array([0, 1, 2])], feats)


def test_dtype_cast_bf16():
  import jax.numpy as jnp
  feats = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
  f = Feature(feats, split_ratio=0.5, dtype=jnp.bfloat16)
  out = f[np.arange(8)]
  np.testing.assert_allclose(
      out.astype(np.float32), feats, rtol=2e-2, atol=2e-2)

"""Worker script for the two-process multi-host test: each process owns
half the mesh devices, loads only its partitions, and runs the collective
distributed sampler. Invoked by test_multihost.py."""
import os
import sys


def main():
  rank = int(sys.argv[1])
  root = sys.argv[2]
  port = sys.argv[3]
  os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
  import jax
  from glt_tpu.utils.backend import force_backend
  force_backend('cpu')
  from glt_tpu.parallel.multihost import initialize
  initialize(coordinator_address=f'127.0.0.1:{port}', num_processes=2,
             process_id=rank)
  assert jax.process_count() == 2 and jax.device_count() == 4

  import numpy as np
  from jax.sharding import Mesh
  from glt_tpu.distributed import (
      DistNeighborSampler, dist_feature_from_partitions_multihost,
      dist_graph_from_partitions_multihost,
  )
  mesh = Mesh(np.array(jax.devices()), ('data',))
  dg = dist_graph_from_partitions_multihost(mesh, root)
  df = dist_feature_from_partitions_multihost(mesh, root)
  s = DistNeighborSampler(dg, [2], seed=0)
  n_nodes = 40
  seeds = np.arange(4)[:, None] * 10       # devices seed 0,10,20,30
  out = s.sample_from_nodes(seeds)
  # every process verifies ITS addressable shards
  nodes = out['node']
  counts = out['node_count']
  ok = 0
  for shard in nodes.addressable_shards:
    p = shard.index[0].start
    local_nodes = np.asarray(shard.data)[0]
    cnt = int(np.asarray(
        [sh.data for sh in counts.addressable_shards
         if sh.index[0].start == p][0])[0])
    v = p * 10
    got = set(local_nodes[:cnt].tolist())
    expect = {v, (v + 1) % n_nodes, (v + 2) % n_nodes}
    assert got == expect, f'rank {rank} shard {p}: {got} != {expect}'
    ok += 1
  assert ok == 2, f'rank {rank}: expected 2 local shards, saw {ok}'
  # collective feature lookup: value-encoded rows resolve exactly
  import jax.numpy as jnp
  ids = np.arange(4 * 8) % n_nodes
  x = df.lookup(jnp.asarray(ids))
  for shard in x.addressable_shards:
    p = shard.index[0].start // 8
    local = np.asarray(shard.data)
    expect = ids[shard.index[0]]
    np.testing.assert_allclose(local[:, 0], expect)
  # edge-feature store over the same multihost tree (value-encoded)
  edf = dist_feature_from_partitions_multihost(mesh, root, kind='edge')
  eids = np.arange(4 * 8) % 80
  ex = edf.lookup(jnp.asarray(eids))
  for shard in ex.addressable_shards:
    local = np.asarray(shard.data)
    np.testing.assert_allclose(local[:, 0], eids[shard.index[0]])

  # beyond-HBM spill across PROCESSES, default path: each process's
  # cold tails become its pinned-host shard of the offloaded cold
  # array, served in-program — no cross-process fetch at all
  dfo = dist_feature_from_partitions_multihost(mesh, root,
                                               split_ratio=0.5)
  assert dfo.cold_array is not None, 'multihost host-offload inactive'
  xo = dfo.lookup(jnp.asarray(ids))
  for shard in xo.addressable_shards:
    local = np.asarray(shard.data)
    np.testing.assert_allclose(local[:, 0], ids[shard.index[0]])

  # legacy fetcher path (host_offload=False): each process keeps its
  # own partitions' cold rows in host RAM and serves the peer's cold
  # lookups over the rpc fabric (reference RpcFeatureLookupCallee,
  # dist_feature.py:57-66)
  from glt_tpu.distributed.rpc import RpcClient, RpcServer
  dfs = dist_feature_from_partitions_multihost(mesh, root,
                                               split_ratio=0.5,
                                               host_offload=False)
  my_port, peer_port = int(sys.argv[4 + rank]), int(sys.argv[5 - rank])
  server = RpcServer(port=my_port)
  server.register('cold_get',
                  lambda p, i: dfs.cold_get(int(p), np.asarray(i)))
  server.start()
  peer = RpcClient('127.0.0.1', peer_port, connect_retries=120,
                   retry_interval=0.25)
  dfs.set_cold_fetcher(
      lambda p, i: np.asarray(peer.request('cold_get', int(p),
                                           np.asarray(i))))
  from jax.experimental import multihost_utils
  multihost_utils.sync_global_devices('cold_rpc_up')
  xs = dfs.lookup(jnp.asarray(ids))
  for shard in xs.addressable_shards:
    local = np.asarray(shard.data)
    np.testing.assert_allclose(local[:, 0], ids[shard.index[0]])
  multihost_utils.sync_global_devices('cold_rpc_done')
  peer.close()
  server.stop()
  print(f'RANK{rank}_OK', flush=True)


if __name__ == '__main__':
  main()

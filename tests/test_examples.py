"""Smoke tests for the examples (subprocess, CPU backend): examples are
the workload catalog's executable documentation — they must not rot."""
import os
import subprocess
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples')


def _run(script, *args, timeout=150):
  env = dict(os.environ)
  env['GLT_PLATFORM'] = 'cpu'
  env['PYTHONPATH'] = (os.path.dirname(_EXAMPLES) + os.pathsep
                       + env.get('PYTHONPATH', ''))
  out = subprocess.run(
      [sys.executable, os.path.join(_EXAMPLES, script), *args],
      capture_output=True, text=True, timeout=timeout, env=env,
      cwd=_EXAMPLES)
  assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
  return out.stdout


def test_train_sage_example():
  out = _run('train_sage_products.py', '--epochs', '1',
             '--batch-size', '512', '--fanout', '5,5')
  assert 'test acc:' in out


@pytest.mark.slow
def test_serve_sage_example():
  """Train -> checkpoint -> restore -> serve over the rpc fabric.
  (slow: two jax subprocess cold-starts; the in-process serving path is
  covered by tests/test_serving.py in tier-1)"""
  out = _run('serve_sage_products.py', '--nodes', '4000',
             '--max-steps', '3', '--hidden', '32', '--queries', '8',
             timeout=300)
  assert 'checkpoint saved' in out
  assert 'steady-state recompiles: 0' in out
  assert 'cache_hit=' in out


@pytest.mark.slow
def test_stream_updates_example():
  """Train -> serve -> live edge+feature updates -> cache-coherent
  fresh predictions (slow: a jax subprocess cold-start; the in-process
  stream path is covered by tests/test_stream.py in tier-1)."""
  out = _run('stream_updates.py', '--nodes', '2000',
             '--max-steps', '3', timeout=300)
  assert 'steady-state recompiles across swap: 0' in out
  assert 'fresh predictions for updated nodes:' in out


def test_unsup_example():
  out = _run('graph_sage_unsup.py', '--epochs', '1', timeout=300)
  assert 'loss=' in out


def test_seal_example():
  out = _run('seal_link_pred.py', '--epochs', '1', '--nodes', '120')
  assert 'Loss:' in out and 'Test:' in out


def test_hetero_rgnn_example():
  out = _run(os.path.join('hetero', 'train_rgnn.py'), '--epochs', '1',
             '--conv', 'rsage')
  assert 'loss=' in out


def test_igbh_pipeline_tools(tmp_path):
  """compress_graph --synthesize -> split_seeds: the preprocessing
  chain produces loadable compressed topology + seed splits."""
  import numpy as np
  root = str(tmp_path / 'igbh')
  out = _run(os.path.join('igbh', 'compress_graph.py'),
             '--path', root, '--synthesize', '500', '--bf16')
  assert 'edges -> CSC' in out and 'bf16' in out
  out = _run(os.path.join('igbh', 'split_seeds.py'), '--path', root)
  assert 'train' in out
  ti = np.load(os.path.join(root, 'processed', 'train_idx.npy'))
  vi = np.load(os.path.join(root, 'processed', 'val_idx.npy'))
  assert ti.shape[0] == 300 and vi.shape[0] == 5
  assert len(set(ti.tolist()) & set(vi.tolist())) == 0
  comp = np.load(os.path.join(
      root, 'csc', 'paper__cites__paper', 'compressed.npz'))
  assert comp['indptr'].shape[0] == 501
  assert comp['indices'].shape[0] == 5000


def test_igbh_dist_train_example():
  out = _run(os.path.join('igbh', 'dist_train_rgnn.py'),
             '--papers', '1500', '--epochs', '1',
             '--steps-per-epoch', '2', '--batch-size', '8',
             '--val-batches', '1', '--hidden', '16', '--conv', 'rsage',
             timeout=400)
  assert 'val_acc=' in out and ':::MLLOG' in out and 'done' in out


def test_dist_sage_unsup_example():
  out = _run(os.path.join('distributed', 'dist_sage_unsup.py'),
             '--nodes', '600', '--epochs', '1', '--batch-size', '8',
             timeout=400)
  assert 'loss=' in out


def test_hierarchical_sage_example():
  out = _run(os.path.join('hetero', 'hierarchical_sage.py'),
             '--epochs', '1', '--papers', '1000', '--batch-size', '64',
             timeout=300)
  assert 'loss=' in out


def test_bipartite_sage_unsup_example():
  out = _run(os.path.join('hetero', 'bipartite_sage_unsup.py'),
             '--epochs', '2', '--users', '300', timeout=400)
  assert 'test_auc=' in out


def test_hgt_mag_example():
  out = _run(os.path.join('hetero', 'train_hgt_mag.py'), '--epochs', '1',
             timeout=300)
  assert 'loss=' in out


def test_pai_table_train_example():
  out = _run('pai_table_train.py', '--epochs', '1', timeout=300)
  assert 'loss=' in out


def test_gpt_on_graphs_example():
  """Ego-subgraph -> LLM prompt demo (reference examples/gpt/arxiv.py):
  prompts carry the sampled structure and the seed-pair question."""
  out = _run('gpt_on_graphs.py', '--papers', '300',
             '--num-batches', '1', timeout=300)
  assert 'Papers:' in out and 'Known citations' in out
  assert 'Question: based only on the structure above' in out


def test_trim_example():
  out = _run('train_sage_with_trim.py', '--nodes', '600',
             '--fanout', '5,3', timeout=420)
  assert 'trim=True' in out and 'done' in out

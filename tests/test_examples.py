"""Smoke tests for the examples (subprocess, CPU backend): examples are
the workload catalog's executable documentation — they must not rot."""
import os
import subprocess
import sys

import pytest

_EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'examples')


def _run(script, *args, timeout=150):
  env = dict(os.environ)
  env['GLT_PLATFORM'] = 'cpu'
  env['PYTHONPATH'] = (os.path.dirname(_EXAMPLES) + os.pathsep
                       + env.get('PYTHONPATH', ''))
  out = subprocess.run(
      [sys.executable, os.path.join(_EXAMPLES, script), *args],
      capture_output=True, text=True, timeout=timeout, env=env,
      cwd=_EXAMPLES)
  assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
  return out.stdout


def test_train_sage_example():
  out = _run('train_sage_products.py', '--epochs', '1',
             '--batch-size', '512', '--fanout', '5,5')
  assert 'test acc:' in out


def test_unsup_example():
  out = _run('graph_sage_unsup.py', '--epochs', '1')
  assert 'loss=' in out


def test_seal_example():
  out = _run('seal_link_pred.py', '--epochs', '1', '--nodes', '120')
  assert 'Loss:' in out and 'Test:' in out


def test_hetero_rgnn_example():
  out = _run(os.path.join('hetero', 'train_rgnn.py'), '--epochs', '1',
             '--conv', 'rsage')
  assert 'loss=' in out

import jax
import jax.numpy as jnp
import numpy as np

from glt_tpu.data import Topology
from glt_tpu.ops import (
    edge_in_csr, random_negative_sample, induced_subgraph,
)


def _dense_edges(topo):
  s = set()
  for v in range(topo.num_rows):
    for c in topo.indices[topo.indptr[v]:topo.indptr[v + 1]]:
      s.add((v, int(c)))
  return s


def test_edge_in_csr_exact():
  rng = np.random.default_rng(0)
  n = 30
  ei = rng.integers(0, n, size=(2, 150))
  t = Topology(edge_index=ei, num_nodes=n)
  edges = _dense_edges(t)
  qr = rng.integers(0, n, size=400)
  qc = rng.integers(0, n, size=400)
  got = np.asarray(edge_in_csr(jnp.asarray(t.indptr), jnp.asarray(t.indices),
                               jnp.asarray(qr), jnp.asarray(qc)))
  expect = np.array([(int(r), int(c)) in edges for r, c in zip(qr, qc)])
  np.testing.assert_array_equal(got, expect)


def test_negative_sampling_strict_excludes_edges():
  rng = np.random.default_rng(1)
  n = 20
  ei = rng.integers(0, n, size=(2, 120))
  t = Topology(edge_index=ei, num_nodes=n)
  edges = _dense_edges(t)
  out = random_negative_sample(
      jnp.asarray(t.indptr), jnp.asarray(t.indices),
      req_num=64, trials_num=5, key=jax.random.key(0),
      num_rows=n, num_cols=n, strict=True, padding=False)
  rows, cols, mask = (np.asarray(out.rows), np.asarray(out.cols),
                      np.asarray(out.mask))
  assert mask.sum() > 0
  for r, c in zip(rows[mask], cols[mask]):
    assert (int(r), int(c)) not in edges


def test_negative_sampling_padding_fills_all():
  # complete digraph on 3 nodes -> no strict negatives exist (incl self?)
  n = 3
  rows, cols = np.meshgrid(np.arange(n), np.arange(n), indexing='ij')
  ei = np.stack([rows.reshape(-1), cols.reshape(-1)])
  t = Topology(edge_index=ei, num_nodes=n)
  out = random_negative_sample(
      jnp.asarray(t.indptr), jnp.asarray(t.indices),
      req_num=16, trials_num=3, key=jax.random.key(0),
      num_rows=n, num_cols=n, strict=True, padding=True)
  assert np.asarray(out.mask).all()
  strict_out = random_negative_sample(
      jnp.asarray(t.indptr), jnp.asarray(t.indices),
      req_num=16, trials_num=3, key=jax.random.key(0),
      num_rows=n, num_cols=n, strict=True, padding=False)
  assert not np.asarray(strict_out.mask).any()


def test_induced_subgraph_exact():
  # 0->1,0->2,1->2,2->3,3->0 ; induce on {0,1,2}
  ei = np.array([[0, 0, 1, 2, 3], [1, 2, 2, 3, 0]])
  t = Topology(edge_index=ei, num_nodes=4)
  sub = induced_subgraph(
      jnp.asarray(t.indptr), jnp.asarray(t.indices),
      jnp.array([0, 1, 2, 0]), jnp.ones(4, bool),
      node_capacity=6, max_degree=4, edge_ids=jnp.asarray(t.edge_ids))
  assert int(sub.node_count) == 3
  np.testing.assert_array_equal(np.asarray(sub.nodes)[:3], [0, 1, 2])
  em = np.asarray(sub.edge_mask)
  rows = np.asarray(sub.rows)[em]
  cols = np.asarray(sub.cols)[em]
  eids = np.asarray(sub.eids)[em]
  got = sorted(zip(rows.tolist(), cols.tolist(), eids.tolist()))
  # edges inside {0,1,2}: 0->1 (eid0), 0->2 (eid1), 1->2 (eid2)
  assert got == [(0, 1, 0), (0, 2, 1), (1, 2, 2)]


def test_induced_subgraph_label_order_follows_first_occurrence():
  ei = np.array([[5, 9], [9, 5]])
  t = Topology(edge_index=ei, num_nodes=10)
  sub = induced_subgraph(
      jnp.asarray(t.indptr), jnp.asarray(t.indices),
      jnp.array([9, 5, 9]), jnp.ones(3, bool),
      node_capacity=4, max_degree=2)
  np.testing.assert_array_equal(np.asarray(sub.nodes)[:2], [9, 5])
  em = np.asarray(sub.edge_mask)
  pairs = sorted(zip(np.asarray(sub.rows)[em].tolist(),
                     np.asarray(sub.cols)[em].tolist()))
  # 9->5 is (label0 -> label1), 5->9 is (label1 -> label0)
  assert pairs == [(0, 1), (1, 0)]

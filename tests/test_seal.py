"""SEAL components: DRNL labeling (vs a pure-python BFS reference) and
the DGCNN model (reference examples/seal_link_pred.py:107-193)."""
import jax
import jax.numpy as jnp
import numpy as np

from glt_tpu.ops.drnl import bfs_distances, drnl_node_labeling

INF = 1 << 29


def _py_bfs(n, edges, source, removed=None):
  adj = {v: [] for v in range(n)}
  for a, b in edges:
    if removed is None or (a != removed and b != removed):
      adj[a].append(b)
  dist = {source: 0}
  frontier = [source]
  while frontier:
    nxt = []
    for v in frontier:
      for w in adj[v]:
        if w not in dist:
          dist[w] = dist[v] + 1
          nxt.append(w)
    frontier = nxt
  return [dist.get(v, INF) for v in range(n)]


def _rand_graph(n, m, seed):
  rng = np.random.default_rng(seed)
  edges = set()
  while len(edges) < m:
    a, b = int(rng.integers(0, n)), int(rng.integers(0, n))
    if a != b:
      edges.add((a, b))
      edges.add((b, a))  # undirected: both directions
  return sorted(edges)


def test_bfs_distances_matches_python_bfs():
  n = 18
  edges = _rand_graph(n, 30, seed=1)
  row = np.array([e[0] for e in edges], np.int32)
  col = np.array([e[1] for e in edges], np.int32)
  mask = np.ones(len(edges), bool)
  for src in (0, 5, 11):
    got = np.asarray(bfs_distances(jnp.asarray(row), jnp.asarray(col),
                                   jnp.asarray(mask), n,
                                   jnp.int32(src)))
    want = _py_bfs(n, edges, src)
    for v in range(n):
      if want[v] >= INF:
        assert got[v] >= INF
      else:
        assert got[v] == want[v], (src, v)


def test_drnl_matches_reference_formula():
  n = 16
  edges = _rand_graph(n, 24, seed=7)
  row = np.array([e[0] for e in edges], np.int32)
  col = np.array([e[1] for e in edges], np.int32)
  mask = np.ones(len(edges), bool)
  src, dst, max_z = 2, 9, 50
  got = np.asarray(drnl_node_labeling(
      jnp.asarray(row), jnp.asarray(col), jnp.asarray(mask), n,
      jnp.int32(src), jnp.int32(dst), max_z))

  d_src = _py_bfs(n, edges, src, removed=dst)
  d_dst = _py_bfs(n, edges, dst, removed=src)
  for v in range(n):
    if v in (src, dst):
      want = 1
    elif d_src[v] >= INF or d_dst[v] >= INF:
      want = 0
    else:
      d = d_src[v] + d_dst[v]
      want = 1 + min(d_src[v], d_dst[v]) + (d // 2) * (d // 2 + d % 2 - 1)
      want = min(max(want, 0), max_z)
    assert got[v] == want, (v, got[v], want)


def test_drnl_masks_removed_target_link():
  # path graph 0-1-2; removing link (0,1) disconnects 0 from 1 via BFS
  row = np.array([0, 1, 1, 2], np.int32)
  col = np.array([1, 0, 2, 1], np.int32)
  keep = np.array([False, False, True, True])  # target link removed
  z = np.asarray(drnl_node_labeling(
      jnp.asarray(row), jnp.asarray(col), jnp.asarray(keep), 3,
      jnp.int32(0), jnp.int32(1), 20))
  assert z[0] == 1 and z[1] == 1
  assert z[2] == 0  # unreachable from src once dst is removed


def test_dgcnn_forward_and_grad():
  from glt_tpu.models.dgcnn import DGCNN
  n, e, f = 12, 30, 6
  rng = np.random.default_rng(0)
  x = rng.normal(size=(n, f)).astype(np.float32)
  row = rng.integers(0, n, e).astype(np.int32)
  col = rng.integers(0, n, e).astype(np.int32)
  emask = rng.random(e) < 0.8
  nmask = np.ones(n, bool)
  model = DGCNN(hidden=8, num_layers=2, k=10)
  params = model.init(jax.random.key(0), jnp.asarray(x), jnp.asarray(row),
                      jnp.asarray(col), jnp.asarray(emask),
                      jnp.asarray(nmask))
  logit = model.apply(params, jnp.asarray(x), jnp.asarray(row),
                      jnp.asarray(col), jnp.asarray(emask),
                      jnp.asarray(nmask))
  assert logit.shape == ()
  # batched via vmap + gradable
  xs = jnp.stack([jnp.asarray(x)] * 3)
  rs = jnp.stack([jnp.asarray(row)] * 3)
  cs = jnp.stack([jnp.asarray(col)] * 3)
  ems = jnp.stack([jnp.asarray(emask)] * 3)
  nms = jnp.stack([jnp.asarray(nmask)] * 3)
  fwd = jax.vmap(model.apply, in_axes=(None, 0, 0, 0, 0, 0))

  def loss(p):
    return fwd(p, xs, rs, cs, ems, nms).sum()

  g = jax.grad(loss)(params)
  flat = jax.tree.leaves(g)
  assert any(float(jnp.abs(a).sum()) > 0 for a in flat)


def test_seal_example_learns():
  """End-to-end smoke: the SEAL pipeline beats chance AUC quickly."""
  import os
  import subprocess
  import sys
  env = dict(os.environ, GLT_PLATFORM='cpu')
  root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  out = subprocess.run(
      [sys.executable, os.path.join(root, 'examples', 'seal_link_pred.py'),
       '--epochs', '4', '--nodes', '200'],
      capture_output=True, text=True, timeout=600, env=env, cwd=root)
  assert out.returncode == 0, out.stderr[-2000:]
  aucs = [float(l.split('Test: ')[1]) for l in out.stdout.splitlines()
          if 'Test: ' in l]
  assert aucs and max(aucs) > 0.6, out.stdout

"""The `pallas` hop engine (ops/pallas_kernels.py::sample_hop).

Acceptance contract (ISSUE 4): the megakernel produces BIT-IDENTICAL
NeighborOutput to the element path in interpret mode — offsets are
drawn from the same jax.random stream outside the kernel, the window
read only changes WHERE values are read from — and the multi-hop
pipeline shows zero steady-state recompiles under the engine. Parity is
asserted on the mask everywhere and on nbrs/eids over masked lanes
(invalid lanes are undefined in every engine, same contract as
tests/test_window_sample.py).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from glt_tpu.ops.sample import sample_neighbors

pytestmark = pytest.mark.pallas

W = 8
K = 4


def _csr(degrees, seed=7):
  rng = np.random.default_rng(seed)
  indptr = np.zeros(len(degrees) + 1, np.int32)
  np.cumsum(degrees, out=indptr[1:])
  num_edges = int(indptr[-1])
  indices = rng.integers(0, len(degrees), num_edges).astype(np.int32)
  return jnp.asarray(indptr), jnp.asarray(indices)


def _padded(arr, w=W):
  return jnp.concatenate([arr, jnp.full((w,), -1, arr.dtype)])


def _assert_identical(a, b):
  np.testing.assert_array_equal(np.asarray(a.mask), np.asarray(b.mask))
  m = np.asarray(a.mask)
  np.testing.assert_array_equal(np.asarray(a.nbrs)[m],
                                np.asarray(b.nbrs)[m])
  np.testing.assert_array_equal(np.asarray(a.eids)[m],
                                np.asarray(b.eids)[m])


@pytest.fixture(scope='module')
def graph():
  # zeros, sub-fanout, mid, exactly W, hubs (> W), tail row whose
  # window crosses the real edge-array end
  degrees = np.array([0, 2, 5, W, 20, 3, 17, 1, W - 1, 6], np.int64)
  return _csr(degrees)


def _run(graph, key, *, engine=None, seed_mask=None, edge_ids=None,
         replace=False, n_hub=2, width=W):
  indptr, indices = graph
  seeds = jnp.arange(indptr.shape[0] - 1, dtype=jnp.int32)
  kw = {}
  if engine is not None:
    kw = dict(window=(width, n_hub), indices_win=_padded(indices, width),
              edge_ids_win=(_padded(edge_ids, width)
                            if edge_ids is not None else None),
              engine=engine, interpret=True)
  return sample_neighbors(indptr, indices, seeds, K, key,
                          seed_mask=seed_mask, edge_ids=edge_ids,
                          replace=replace, **kw)


def test_bit_identical_to_element_path(graph):
  key = jax.random.key(0)
  _assert_identical(_run(graph, key),
                    _run(graph, key, engine='pallas'))


def test_matches_window_engine_exactly(graph):
  key = jax.random.key(1)
  _assert_identical(_run(graph, key, engine='window'),
                    _run(graph, key, engine='pallas'))


def test_edge_ids_and_seed_mask(graph):
  indptr, indices = graph
  key = jax.random.key(2)
  mask = jnp.asarray(np.arange(indptr.shape[0] - 1) % 2 == 0)
  eids = jnp.arange(indices.shape[0], dtype=jnp.int32) * 10
  _assert_identical(
      _run(graph, key, seed_mask=mask, edge_ids=eids),
      _run(graph, key, engine='pallas', seed_mask=mask, edge_ids=eids))


def test_replace_path(graph):
  key = jax.random.key(3)
  _assert_identical(_run(graph, key, replace=True),
                    _run(graph, key, engine='pallas', replace=True))


def test_all_hub_frontier():
  g = _csr(np.full(6, 3 * W, np.int64))
  key = jax.random.key(4)
  _assert_identical(_run(g, key),
                    _run(g, key, engine='pallas', n_hub=6))


def test_zero_hubs_wide_window(graph):
  key = jax.random.key(5)
  _assert_identical(
      _run(graph, key),
      _run(graph, key, engine='pallas', width=32, n_hub=0))


def test_empty_frontier(graph):
  indptr, indices = graph
  out = sample_neighbors(indptr, indices,
                         jnp.zeros((0,), jnp.int32), K,
                         jax.random.key(6), window=(W, 2),
                         indices_win=_padded(indices), engine='pallas',
                         interpret=True)
  assert out.nbrs.shape == (0, K) and out.mask.shape == (0, K)


def test_under_jit(graph):
  key = jax.random.key(7)
  base = _run(graph, key)
  winp = jax.jit(lambda: _run(graph, key, engine='pallas'))()
  _assert_identical(base, winp)


# -- multi-hop pipeline: engine selection + compile discipline ----------

def test_sampler_engine_bit_parity_and_zero_recompiles(monkeypatch):
  from fixtures import ring_dataset
  from glt_tpu.sampler import NeighborSampler
  ds = ring_dataset(num_nodes=40)
  seeds = np.arange(8)
  base = NeighborSampler(ds.get_graph(), [3, 2], seed=0,
                         with_edge=True).sample_from_nodes(seeds)
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0, with_edge=True)
  out = samp.sample_from_nodes(seeds)
  for f in ('node', 'row', 'col', 'edge_mask', 'batch', 'edge'):
    np.testing.assert_array_equal(np.asarray(getattr(base, f)),
                                  np.asarray(getattr(out, f)),
                                  err_msg=f)
  assert samp.num_compiled_fns == 1
  for _ in range(3):   # steady state: the one program serves every call
    samp.sample_from_nodes(seeds)
  assert samp.num_compiled_fns == 1


def test_stream_engine_parity_and_zero_recompiles(monkeypatch):
  """The stream pipeline under GLT_HOP_ENGINE=pallas: base-hop reads go
  through the megakernel, delta overlays keep their fixed windows, and
  overlay refreshes + snapshot swaps stay at zero recompiles
  (StreamSampler.trace_count — same discipline as tests/test_stream.py).
  """
  from fixtures import ring_dataset
  from glt_tpu.stream import (EdgeDeltaBuffer, SnapshotManager,
                              StreamSampler)
  N = 24
  ds = ring_dataset(num_nodes=N)
  mgr = SnapshotManager(ds.get_graph().topo, ds.get_node_feature(),
                        delta_capacity=64)
  seeds = np.arange(6)
  base = StreamSampler(mgr, [3, 2], seed=0).sample_from_nodes(seeds)
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  samp = StreamSampler(mgr, [3, 2], seed=0)
  out = samp.sample_from_nodes(seeds)
  for f in ('node', 'row', 'col', 'edge_mask', 'batch'):
    np.testing.assert_array_equal(np.asarray(getattr(base, f)),
                                  np.asarray(getattr(out, f)),
                                  err_msg=f)
  buf = EdgeDeltaBuffer(capacity=16, num_nodes=N)
  buf.insert_edges([1, 2], [5, 6])
  samp.refresh_overlay(buf)
  traces, fns = samp.trace_count, samp.num_compiled_fns
  for _ in range(3):
    samp.sample_from_nodes(seeds)
  mgr.compact(buf.drain())        # swap: same static shapes
  samp.clear_overlay()
  samp.sample_from_nodes(seeds)
  assert samp.trace_count == traces
  assert samp.num_compiled_fns == fns


def test_two_batch_shapes_share_the_padded_arrays(monkeypatch):
  """Two compiled programs over the same graph (serving buckets trace
  the sampler once per batch size): the window-padded edge arrays must
  come out of window_arrays as CONCRETE arrays even though the one_hop
  closures run at trace time — a staged pad would rebind the graph's
  indices to a tracer that leaks into the second trace (regression for
  the multi-bucket UnexpectedTracerError)."""
  from fixtures import ring_dataset
  from glt_tpu.sampler import NeighborSampler
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  ds = ring_dataset(num_nodes=40)
  samp = NeighborSampler(ds.get_graph(), [3, 2], seed=0)
  out4 = samp.sample_from_nodes(np.arange(4))    # trace 1
  out8 = samp.sample_from_nodes(np.arange(8))    # trace 2: same graph
  assert samp.num_compiled_fns == 2
  assert int(out4.node_count) > 0 and int(out8.node_count) > 0


def test_hetero_engine_bit_parity(monkeypatch):
  from fixtures import hetero_ring_dataset
  from glt_tpu.sampler import NeighborSampler
  from glt_tpu.sampler.base import NodeSamplerInput
  ds = hetero_ring_dataset()
  seeds = NodeSamplerInput(np.arange(6), 'user')
  base = NeighborSampler(ds.graph, [2, 2], seed=0,
                         with_edge=True).sample_from_nodes(seeds)
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas')
  monkeypatch.setenv('GLT_WINDOW_W', '8')
  out = NeighborSampler(ds.graph, [2, 2], seed=0,
                        with_edge=True).sample_from_nodes(seeds)
  for t in base.node:
    np.testing.assert_array_equal(np.asarray(base.node[t]),
                                  np.asarray(out.node[t]), err_msg=t)
  for e in base.row:
    for field in ('row', 'col', 'edge_mask', 'edge'):
      np.testing.assert_array_equal(
          np.asarray(getattr(base, field)[e]),
          np.asarray(getattr(out, field)[e]), err_msg=f'{field} {e}')


def test_hop_engine_knob_validation(monkeypatch):
  from glt_tpu.ops.pipeline import hop_engine
  monkeypatch.setenv('GLT_HOP_ENGINE', 'warp')
  with pytest.raises(ValueError):
    hop_engine()
  monkeypatch.setenv('GLT_HOP_ENGINE', 'pallas')
  assert hop_engine() in ('pallas', 'window')  # window iff no pallas
  monkeypatch.delenv('GLT_HOP_ENGINE')
  assert hop_engine() == 'element'

#!/usr/bin/env bash
# Build the native shared-memory queue library (plain g++).
set -e
make -C "$(dirname "$0")/../glt_tpu/csrc"

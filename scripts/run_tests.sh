#!/usr/bin/env bash
# Run the test suite on the 8-device virtual CPU mesh.
set -e
cd "$(dirname "$0")/.."
python -m pytest tests/ -q "$@"

#!/usr/bin/env python
"""Noise-aware bench regression gate.

Compares a fresh ``bench.py`` headline JSON against the recorded
trajectory (``benchmarks/history.py`` JSONL) and **exits nonzero on
regression** — the CI gate that turns the bench from a one-off
snapshot into a ratchet.

Per series (exact (bench, engine, scale, device) key match):

  * baseline = median of the last ``--median-of`` recorded runs
    (median: one noisy runner in the window must not move the bar);
  * a series needs ``--min-runs`` history rows before it gates at all
    (a single prior run is itself noise);
  * regression = relative drop vs baseline >= ``--threshold`` — all
    series here are throughput (higher is better);
  * a bench run that failed to measure (``error`` field) gates
    nothing: "not measured" is not "measured as 0" (bench.py's own
    contract), and the append step skips it too.

Usage (CI order: gate against the PAST, then append the present)::

    python scripts/bench_compare.py \
        --history bench_history.jsonl --current bench_smoke.json \
        [--threshold 0.30] [--median-of 5] [--min-runs 2]
    python benchmarks/history.py append \
        --history bench_history.jsonl --bench-json bench_smoke.json

Prints one JSON report to stdout; exit 0 = no regression (or nothing
gateable yet), 1 = regression, 2 = bad invocation.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, '..', 'benchmarks'))

from history import baseline, load_runs, rows_from_bench_json  # noqa: E402


def compare(history_path: str, current: dict, threshold: float = 0.30,
            median_of: int = 5, min_runs: int = 2) -> dict:
  """Pure comparison (no exit): returns the report dict with
  ``regressions`` / ``ok`` / ``skipped`` series lists."""
  report = {'threshold': threshold, 'median_of': median_of,
            'min_runs': min_runs, 'regressions': [], 'ok': [],
            'skipped': []}
  if 'error' in current:
    report['skipped'].append(
        {'reason': 'current run not measured',
         'error': str(current['error'])[:200]})
    return report
  rows = rows_from_bench_json(current)
  if not rows:
    report['skipped'].append({'reason': 'no series in current run'})
    return report
  for row in rows:
    runs = load_runs(history_path, bench=row['bench'],
                     engine=row['engine'], scale=row['scale'],
                     device=row['device'])
    key = '|'.join((row['bench'], row['engine'], row['scale'],
                    row['device']))
    if len(runs) < min_runs:
      report['skipped'].append(
          {'series': key, 'reason': f'only {len(runs)} recorded '
                                    f'run(s) (< {min_runs})'})
      continue
    base = baseline(runs, median_of=median_of)
    entry = {
        'series': key,
        'value': row['value'],
        'baseline': round(base, 3),
        'ratio': round(row['value'] / base, 4) if base else None,
        'window': min(len(runs), median_of),
    }
    drop = (1.0 - row['value'] / base) if base else 0.0
    if base and drop >= threshold:
      entry['drop_pct'] = round(100.0 * drop, 1)
      report['regressions'].append(entry)
    else:
      report['ok'].append(entry)
  return report


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__.split('\n')[1])
  ap.add_argument('--history', required=True,
                  help='trajectory JSONL (benchmarks/history.py)')
  ap.add_argument('--current', required=True,
                  help='fresh bench.py headline JSON')
  ap.add_argument('--threshold', type=float, default=0.30,
                  help='relative drop that fails the gate '
                       '(default 0.30 = 30%%)')
  ap.add_argument('--median-of', type=int, default=5,
                  help='baseline = median of the last N runs')
  ap.add_argument('--min-runs', type=int, default=2,
                  help='history rows a series needs before gating')
  args = ap.parse_args(argv)
  try:
    with open(args.current) as f:
      current = json.load(f)
  except (OSError, ValueError) as e:
    print(f'bench_compare: cannot read {args.current}: {e}',
          file=sys.stderr)
    return 2
  report = compare(args.history, current, threshold=args.threshold,
                   median_of=args.median_of, min_runs=args.min_runs)
  print(json.dumps(report, indent=2))
  if report['regressions']:
    for r in report['regressions']:
      print(f"bench_compare: REGRESSION {r['series']}: "
            f"{r['value']:.1f} vs baseline {r['baseline']:.1f} "
            f"(-{r['drop_pct']}%)", file=sys.stderr)
    return 1
  return 0


if __name__ == '__main__':
  sys.exit(main())

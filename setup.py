"""Install glt_tpu (pure Python; the native shm library builds on demand
via make -C glt_tpu/csrc)."""
from setuptools import find_packages, setup

setup(
    name='glt_tpu',
    version='0.1.0',
    description=('TPU-native graph learning framework: sampling, unified '
                 'feature store, distributed GNN training on JAX/XLA'),
    packages=find_packages(include=['glt_tpu', 'glt_tpu.*']),
    package_data={'glt_tpu': ['csrc/*.cc', 'csrc/Makefile']},
    python_requires='>=3.10',
    install_requires=[
        'jax', 'flax', 'optax', 'numpy',
    ],
    extras_require={
        'ckpt': ['orbax-checkpoint'],
        'test': ['pytest'],
    },
)
